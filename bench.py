"""Headline benchmark: CT entries/sec/chip through the fused device step.

Measures the device pipeline that replaces the reference's per-entry
hot loop (x509 parse + filter + Redis SADD dedup + issuer accumulate,
/root/reference/cmd/ct-fetch/ct-fetch.go:180-246 →
/root/reference/storage/knowncertificates.go:38-55): DER field
extraction, SHA-256 fingerprinting, HBM hash-table insert-if-absent,
and per-issuer counts, all in one jitted call.

Methodology: G structurally-valid certificate batches live resident in
HBM; every epoch a jitted prologue restamps each lane's serial INTEGER
with (epoch, lane) counter bytes, so every processed entry is a unique
certificate — the all-fresh-insert worst case for the dedup table (the
reference pays one Redis round trip per entry in exactly this case).
Input H2D streaming is the host pipeline's job and is overlapped with
device compute in production (double-buffered device_put); it is not
part of this kernel-throughput metric.

Parity gate: the run aborts (exit 1) unless the final table count
equals the number of entries processed — i.e. the dedup path really
inserted every unique serial exactly once.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is against BASELINE.json's 10M entries/sec/chip north star
(the reference publishes no numbers of its own — BASELINE.md).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class BenchError(RuntimeError):
    """Raised for any bench failure; __main__ turns it into the
    structured one-line JSON the driver can parse."""


_emit_lock = threading.Lock()
_emitted = False


def emit(payload: dict) -> bool:
    """Print the single stdout JSON line, exactly once per process."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return False
        _emitted = True
        print(json.dumps(payload), flush=True)
        return True


def emit_error(msg: str) -> bool:
    return emit({
        "metric": "ct_entries_per_sec_per_chip",
        "value": 0,
        "unit": "entries/s/chip",
        "vs_baseline": 0,
        "error": msg[:500],
    })


def start_watchdog(budget_s: float) -> None:
    """Force-exit with a parseable error JSON if the whole bench
    doesn't finish inside ``budget_s`` — a hung backend init or compile
    on the tunneled TPU must yield rc=1 + JSON, never the driver's
    rc=124 with nothing on stdout (round 1/2 failure mode)."""
    def fire() -> None:
        time.sleep(budget_s)
        if emit_error(f"bench watchdog: exceeded {budget_s:.0f}s budget"):
            log(f"watchdog fired after {budget_s:.0f}s; force-exiting")
            sys.stderr.flush()
            os._exit(1)

    threading.Thread(target=fire, daemon=True, name="bench-watchdog").start()


def acquire_device(max_attempts: int = 4, attempt_timeout_s: float = 90.0):
    """First device, surviving backend-init failure AND hang.

    The tunneled TPU backend has shown two failure modes at init:
    ``UNAVAILABLE: TPU backend setup/compile error`` (round 1, rc=1)
    and an outright hang (round 2 testing, rc=124). Each attempt runs
    in a watchdog thread with a timeout; failures get bounded
    retry-with-backoff — mirroring the reference's transient-failure
    tolerance on its hot path (/root/reference/cmd/ct-fetch/
    ct-fetch.go:409-437: jittered backoff + retry on 429).
    """
    delay = 2.0
    last_err: Exception | None = None
    for attempt in range(1, max_attempts + 1):
        result: dict = {}

        def target() -> None:
            try:
                import jax

                result["dev"] = jax.devices()[0]
            except Exception as err:  # RuntimeError / JaxRuntimeError
                result["err"] = err

        t = threading.Thread(target=target, daemon=True, name="backend-init")
        t.start()
        t.join(attempt_timeout_s)
        if "dev" in result:
            return result["dev"]
        if t.is_alive():
            last_err = TimeoutError(
                f"backend init hung > {attempt_timeout_s:.0f}s"
            )
        else:
            last_err = result.get("err") or RuntimeError("no device")
        log(f"backend init attempt {attempt}/{max_attempts} failed: "
            f"{type(last_err).__name__}: {last_err}")
        try:
            import jax._src.xla_bridge as xb

            xb._clear_backends()
        except Exception:
            pass
        if attempt < max_attempts:
            time.sleep(delay)
            delay = min(delay * 2, 30.0)
    raise BenchError(f"backend unavailable after {max_attempts} attempts: "
                     f"{type(last_err).__name__}: {last_err}")


def main() -> int:
    import jax
    import jax.numpy as jnp

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    batch = int(os.environ.get("CT_BENCH_BATCH", "16384"))
    n_batches = int(os.environ.get("CT_BENCH_RESIDENT", "8"))
    pad_len = int(os.environ.get("CT_BENCH_PADLEN", "1024"))
    capacity = 1 << int(os.environ.get("CT_BENCH_LOG2_CAPACITY", "26"))
    target_secs = float(os.environ.get("CT_BENCH_SECS", "2.0"))
    max_sweeps = int(os.environ.get("CT_BENCH_MAX_SWEEPS", "240"))

    # All-fresh inserts fill the table; keep the worst-case load factor
    # bounded so probe behavior stays representative.
    max_entries = (max_sweeps + 1) * n_batches * batch
    if max_entries > capacity * 0.6:
        raise BenchError(
            f"capacity {capacity} too small for {max_entries} unique "
            f"entries; raise CT_BENCH_LOG2_CAPACITY or lower sweeps"
        )

    start_watchdog(float(os.environ.get("CT_BENCH_WATCHDOG_SECS", "540")))
    dev = acquire_device()
    log(f"device: {dev.platform} ({dev.device_kind}); batch={batch} "
        f"resident={n_batches} pad={pad_len} capacity={capacity}")

    tpl = syncerts.make_template()
    now_hour = 500_000  # well before the template's 2031 expiry

    # Resident batches: lane bytes unique per (batch, lane); epoch bytes
    # stamped on device each sweep.
    dev_batches = []
    for i in range(n_batches):
        data, lengths = syncerts.stamp_batch_array(
            tpl, start=i * batch, batch=batch, pad_len=pad_len
        )
        dev_batches.append(
            (jax.device_put(data), jax.device_put(lengths))
        )
    issuer_idx = jax.device_put(np.zeros((batch,), np.int32))
    valid = jax.device_put(np.ones((batch,), bool))
    epoch_cols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)

    # CRITICAL (axon/PJRT): every device array must be an ARGUMENT.
    # A jitted program that closes over a committed device buffer — even
    # a scalar — permanently degrades all subsequent dispatches on this
    # stack to a ~70 ms synchronous path (measured; see PROGRESS notes).
    # numpy closures (epoch_cols) lower to HLO literals and are fine.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def bench_step(table, data, length, issuer_idx, valid, epoch):
        # Unique serials per epoch: write the epoch uint32 into serial
        # bytes 4..8 (lane counter already occupies bytes 8..16).
        e = epoch.astype(jnp.uint32)
        eb = jnp.stack(
            [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF, e & 0xFF]
        ).astype(jnp.uint8)
        data = data.at[:, epoch_cols].set(eb[None, :])
        table, out = pipeline.ingest_core(
            table, data, length, issuer_idx, valid,
            jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
            jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0,), jnp.int32),
        )
        # Only the table and cheap scalars leave the step: keep the
        # benchmark output-bound on compute, not D2H.
        return table, out.was_unknown.sum(), out.host_lane.sum()

    table = hashtable.make_table(capacity)

    # Warmup sweep: compiles and inserts epoch-0 serials.
    t0 = time.perf_counter()
    for data, lengths in dev_batches:
        table, f, h = bench_step(table, data, lengths, issuer_idx, valid,
                                 jnp.uint32(0))
    f.block_until_ready()
    log(f"warmup (compile + first sweep): {time.perf_counter() - t0:.1f}s")
    warm_entries = n_batches * batch

    # Timed sweeps.
    t0 = time.perf_counter()
    processed = 0
    fresh_totals = []
    sweep = 0
    while sweep < max_sweeps:
        sweep += 1
        for data, lengths in dev_batches:
            table, f, h = bench_step(table, data, lengths, issuer_idx,
                                     valid, jnp.uint32(sweep))
            fresh_totals.append((f, h))
        processed += n_batches * batch
        if sweep >= 3 and time.perf_counter() - t0 >= target_secs:
            break
    table.count.block_until_ready()
    elapsed = time.perf_counter() - t0

    # Parity gate: every processed entry was unique ⇒ every one must
    # have been inserted exactly once (no silent drops, no collisions).
    total_fresh = int(np.sum([int(f) for f, _ in fresh_totals]))
    total_host = int(np.sum([int(h) for _, h in fresh_totals]))
    final_count = int(table.count)
    expected = warm_entries + processed
    log(f"processed={processed} in {elapsed:.3f}s; fresh={total_fresh} "
        f"host_lane={total_host} table_count={final_count} expected={expected}")
    if final_count != expected or total_fresh != processed or total_host != 0:
        raise BenchError(
            "PARITY FAILURE: dedup table does not match unique-entry count: "
            f"table_count={final_count} expected={expected} "
            f"fresh={total_fresh} host_lane={total_host}"
        )

    rate = processed / elapsed
    emit({
        "metric": "ct_entries_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "entries/s/chip",
        "vs_baseline": round(rate / 10_000_000, 4),
    })
    return 0


if __name__ == "__main__":
    # Whatever happens, stdout carries exactly one JSON line: a real
    # metric on success, a structured {"error": ...} on failure — never
    # a bare traceback (round 1's rc=1 left the driver nothing to parse).
    try:
        rc = main()
    except SystemExit:
        raise
    except Exception as err:
        msg = f"{type(err).__name__}: {err}"
        emit_error(msg)
        log(msg)
        # A hung backend-init thread must not block interpreter exit.
        sys.stderr.flush()
        os._exit(1)
    sys.exit(rc)
