"""Headline benchmark: CT entries/sec/chip through the fused device step.

Measures the device pipeline that replaces the reference's per-entry
hot loop (x509 parse + filter + Redis SADD dedup + issuer accumulate,
/root/reference/cmd/ct-fetch/ct-fetch.go:180-246 →
/root/reference/storage/knowncertificates.go:38-55): DER field
extraction, SHA-256 fingerprinting, HBM hash-table insert-if-absent,
and per-issuer counts, all in one jitted call.

Methodology: G structurally-valid certificate batches live resident in
HBM; every epoch a jitted prologue restamps each lane's serial INTEGER
with (epoch, lane) counter bytes, so every processed entry is a unique
certificate — the all-fresh-insert worst case for the dedup table (the
reference pays one Redis round trip per entry in exactly this case).
The epoch counter itself lives ON DEVICE (donated through the step), so
a timed dispatch transfers nothing host→device. Input H2D streaming is
the host pipeline's job and is overlapped with device compute in
production (double-buffered device_put); it is not part of this
kernel-throughput metric (the e2e ingest-path benchmark is a separate
metric — see tests/test_ingest.py's engine drives).

Robustness contract (round-2/3 postmortems: r02 recorded value 0 after
its 540s watchdog; r03 diagnosis found BOTH failure modes — per-
execution readback cost on the axon stack is ~0.2s regardless of
compute, and a single device execution longer than ~20s gets the TPU
worker killed): the timed phase is chunked into device executions
sized ADAPTIVELY from a measured calibration sweep so each execution
stays near CT_BENCH_EXEC_SECS (default 6s), every chunk ends with a
synchronous value read (honest timing: dispatch → compute → readback,
nothing in flight), a stderr heartbeat prints the cumulative rate per
chunk, and the watchdog emits the partial measured rate — never 0 —
once at least one chunk has completed. The watchdog deadline is
extended by device-acquisition time so backend retries can't squeeze
the measurement window.

Parity gate: the run aborts (exit 1) unless the final table count
equals the number of entries processed — i.e. the dedup path really
inserted every unique serial exactly once.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is against BASELINE.json's 10M entries/sec/chip north star
(the reference publishes no numbers of its own — BASELINE.md).
"""

from __future__ import annotations

import contextlib
import faulthandler
import functools
import json
import os
import signal
import sys
import threading
import time

import numpy as np

# SIGUSR1 → all-thread Python stacks on stderr: a wedged run can be
# diagnosed in place (kill -USR1 <pid>) without killing it.
try:
    faulthandler.register(signal.SIGUSR1)
except (AttributeError, ValueError):
    pass


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class BenchError(RuntimeError):
    """Raised for any bench failure; __main__ turns it into the
    structured one-line JSON the driver can parse."""


_emit_lock = threading.Lock()
_emitted = False


def emit(payload: dict) -> bool:
    """Print the single stdout JSON line, exactly once per process."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return False
        _emitted = True
        print(json.dumps(payload), flush=True)
        return True


def emit_error(msg: str) -> bool:
    return emit({
        "metric": "ct_entries_per_sec_per_chip",
        "value": 0,
        "unit": "entries/s/chip",
        "vs_baseline": 0,
        "error": msg[:500],
    })


# Shared progress state the watchdog reads so a timeout yields the
# PARTIAL measured rate, never a bare 0 (round-2 failure mode).
_progress = {
    "deadline": None,  # absolute monotonic deadline; main may extend it
    "processed": 0,    # entries completed (post-block) in the timed phase
    "t0": None,        # timed-phase start (monotonic)
    "last_sync": None, # monotonic time of the last completed sweep
}


def maybe_enable_compile_cache():
    """Opt-in persistent JAX compilation cache (CT_COMPILE_CACHE=<dir>).

    Opt-in because it is measurably HARMFUL on the tunneled TPU stack
    (306.8s vs 198.8s cold, 2026-07-31 — compiles are remote, the AOT
    path can't reuse entries and pays serialization on top). On hosts
    where XLA compiles locally (CPU smoke runs, CI, real on-host TPU
    VMs) it removes the repeated-compile tax across the bench's legs
    and across processes; tests/test_compile_cache.py gates the hit
    path. Returns the cache dir when enabled, else None.
    """
    path = os.environ.get("CT_COMPILE_CACHE", "")
    if not path:
        return None
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache every program: the bench's small helper jits compile in
        # milliseconds but recompile per process without this.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as err:  # pragma: no cover - jax-version dependent
        log(f"CT_COMPILE_CACHE disabled ({type(err).__name__}: {err})")
        return None
    log(f"persistent compilation cache: {path}")
    return path


def start_watchdog(budget_s: float) -> None:
    """Force-exit with a parseable JSON line if the bench doesn't finish
    inside its budget — a hung backend init or compile on the tunneled
    TPU must yield rc=1 + JSON, never the driver's rc=124 with nothing
    on stdout (round 1/2 failure mode). If the timed phase has completed
    at least one sweep, the emitted line carries the partial measured
    rate (flagged ``"error": "partial: watchdog"``) instead of 0."""
    _progress["deadline"] = time.monotonic() + budget_s

    def fire() -> None:
        while True:
            remaining = _progress["deadline"] - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 5.0))
        processed = _progress["processed"]
        t0 = _progress["t0"]
        last = _progress["last_sync"]
        if processed > 0 and t0 is not None and last is not None and last > t0:
            rate = processed / (last - t0)
            done = emit({
                "metric": "ct_entries_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "entries/s/chip",
                "vs_baseline": round(rate / 10_000_000, 4),
                "error": f"partial: watchdog after {budget_s:.0f}s budget "
                         f"({processed} entries in {last - t0:.1f}s)",
            })
        else:
            done = emit_error(
                f"bench watchdog: exceeded {budget_s:.0f}s budget "
                f"before any timed sweep completed"
            )
        if done:
            log(f"watchdog fired; processed={processed}; force-exiting")
            sys.stderr.flush()
            os._exit(1)

    threading.Thread(target=fire, daemon=True, name="bench-watchdog").start()


def extend_watchdog(extra_s: float, cap_s: float = 240.0) -> None:
    """Push the deadline out by time spent acquiring the device, so
    backend-init retries don't eat the measurement window (round-2
    weak spot: 4 retries could consume ~370s of a 540s budget)."""
    if _progress["deadline"] is not None:
        _progress["deadline"] += min(extra_s, cap_s)


def acquire_device(attempt_timeout_s: float = 90.0,
                   reserve_s: float = 45.0):
    """First device, surviving backend-init failure AND hang.

    The tunneled TPU backend has shown three failure modes at init:
    ``UNAVAILABLE: TPU backend setup/compile error`` (round 1, rc=1),
    an outright hang (round 2 testing, rc=124), and a pool outage
    where every claim waits ~25 min before erring Unavailable (round 3,
    ~2.5 h long). Each attempt runs in a watchdog thread with a
    timeout; attempts repeat with backoff for as long as the bench
    watchdog budget allows (minus ``reserve_s`` to emit clean JSON) —
    a recovering pool in the final minute still yields a measurement,
    mirroring the reference's transient-failure tolerance on its hot
    path (/root/reference/cmd/ct-fetch/ct-fetch.go:409-437).
    """
    delay = 2.0
    last_err: Exception | None = None
    attempt = 0
    while True:
        attempt += 1
        result: dict = {}

        def target() -> None:
            try:
                import jax

                result["dev"] = jax.devices()[0]
            except Exception as err:  # RuntimeError / JaxRuntimeError
                result["err"] = err

        t = threading.Thread(target=target, daemon=True, name="backend-init")
        t.start()
        t.join(attempt_timeout_s)
        if "dev" in result:
            return result["dev"]
        if t.is_alive():
            last_err = TimeoutError(
                f"backend init hung > {attempt_timeout_s:.0f}s"
            )
        else:
            last_err = result.get("err") or RuntimeError("no device")
        deadline = _progress["deadline"]
        if deadline is None:
            log(f"backend init attempt {attempt}/4 failed (no watchdog): "
                f"{type(last_err).__name__}: {last_err}")
        else:
            remaining = deadline - time.monotonic()
            log(f"backend init attempt {attempt} failed "
                f"({remaining:.0f}s of watchdog budget left): "
                f"{type(last_err).__name__}: {last_err}")
        try:
            import jax._src.xla_bridge as xb

            xb._clear_backends()
        except Exception:
            pass
        if deadline is None:
            # No watchdog (direct reuse from a script): the bounded
            # 4-attempt retry contract, independent of timeout values.
            if attempt >= 4:
                break
        elif deadline - time.monotonic() < reserve_s + delay + attempt_timeout_s:
            break
        time.sleep(delay)
        delay = min(delay * 2, 30.0)
    raise BenchError(f"backend unavailable after {attempt} attempts: "
                     f"{type(last_err).__name__}: {last_err}")


def main() -> int:
    import jax
    import jax.numpy as jnp

    # Honor JAX_PLATFORMS=cpu for local smoke runs: the ambient axon
    # sitecustomize imports jax early, so the env var alone is too late
    # (same workaround as tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    # NOTE on compiles: the bucket-table step costs ~180-200s to
    # compile cold on the tunneled remote compiler, every process
    # (nothing caches across processes). BOTH standard escapes were
    # tried and measured useless here: jax's persistent compilation
    # cache was SLOWER (306.8s vs 198.8s cold, 2026-07-31 — the
    # chipless AOT path can't reuse the entries and pays serialization
    # on top), and jax.export round-tripping is a wash
    # (tools/aotprobe.py, docs/ladder_r05_run.log: export+serialize
    # 0.5s/24KB — tracing is NOT the cost — deserialize 0.0s, but the
    # first call still pays the REMOTE backend compile: 159.0s vs
    # 169.2s cold). The compile lives server-side on this stack; the
    # budget protection is extend_watchdog(compile_s), not a cache.
    # On stacks that compile LOCALLY the cache does help (the three
    # bench legs repaid ~580s of compile in BENCH_r05.json), so it is
    # wired opt-in behind CT_COMPILE_CACHE:
    maybe_enable_compile_cache()

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.agg.aggregator import _table_layout
    from ct_mapreduce_tpu.ops import buckettable, hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    # Batch width amortizes the per-execution fixed costs; table
    # CAPACITY has its own price — random access over a 4 GB table
    # measures ~30% slower per entry than over 2 GB (stagecost at
    # cap 2^27 vs 2^26: 256 vs 197 ns/entry, 2026-07-31), so the
    # bench uses the smallest capacity that still bounds the timed
    # phase's worst-case load under 40%.
    batch = int(os.environ.get("CT_BENCH_BATCH", "1048576"))
    n_batches = int(os.environ.get("CT_BENCH_RESIDENT", "1"))
    # Batch realism (VERDICT r04 #4: the default headline is a friendly
    # ~1KB ECDSA single-issuer batch; real logs are RSA-dominated and
    # multi-issuer):
    #   CT_BENCH_MIX=      (default) one minimal ECDSA template
    #   CT_BENCH_MIX=rsa   one rich-extension RSA-2048 template (~1.4KB)
    #   CT_BENCH_MIX=mixed 16 issuers, Zipf split, EC+RSA, serial lens
    #                      8..20, rich extensions — the realistic mix
    mix = os.environ.get("CT_BENCH_MIX", "").strip().lower()
    default_pad = "1024" if mix == "" else "2048"
    pad_len = int(os.environ.get("CT_BENCH_PADLEN", default_pad))
    capacity = 1 << int(os.environ.get("CT_BENCH_LOG2_CAPACITY", "26"))
    # Timed phase: device executions (jitted lax.fori_loop over sweeps ×
    # resident batches), each synced by a value read. Execution length
    # is calibrated so one execution ≈ exec_target_s (a >~20s execution
    # gets the worker killed on the tunneled stack), and chunks run
    # until ~target_total_s of measurement or the table-load cap.
    exec_target_s = float(os.environ.get("CT_BENCH_EXEC_SECS", "6.0"))
    target_total_s = float(os.environ.get("CT_BENCH_SECS", "15.0"))
    # All-fresh inserts fill the table; bound the worst-case load factor
    # so probe behavior stays representative (and nothing overflows).
    max_total_sweeps = int(capacity * 0.6) // (n_batches * batch) - 2
    if max_total_sweeps < 1:
        raise BenchError(
            f"capacity {capacity} too small for even one timed sweep of "
            f"{n_batches * batch} entries; raise CT_BENCH_LOG2_CAPACITY"
        )

    start_watchdog(float(os.environ.get("CT_BENCH_WATCHDOG_SECS", "540")))
    t_acq = time.perf_counter()
    dev = acquire_device()
    acq_s = time.perf_counter() - t_acq
    extend_watchdog(acq_s)
    log(f"device: {dev.platform} ({dev.device_kind}) acquired in {acq_s:.1f}s; "
        f"batch={batch} resident={n_batches} pad={pad_len} capacity={capacity}")

    now_hour = 500_000  # well before the templates' 2031 expiry

    # Resident batches, stacked [G, B, L], built ON DEVICE from signed
    # templates (syncerts builders: lane counter in the serial's last
    # 4 bytes; an epoch window is restamped per sweep inside mega_step).
    try:
        if mix == "mixed":
            t0 = time.perf_counter()
            tpls = [
                syncerts.make_template(
                    issuer_cn=f"Mix Issuer {k}",
                    key_type=("rsa2048" if k % 2 else "ec"),
                    serial_len=(8, 12, 16, 20)[k % 4],
                    rich_extensions=True,
                )
                for k in range(16)
            ]
            ms = syncerts.build_mixed_device_batches(
                tpls, syncerts.zipf_weights(16), n_batches, batch,
                pad_len)
            datas, lens = ms.datas, ms.lens
            issuer_idx = jax.device_put(ms.issuer_idx)
            # Per-lane FIRST epoch column (serial_off + 1); mega_step
            # derives the 3-byte window from it in one fused where.
            epoch_cols = ms.epoch_cols[:, 0].astype(np.int32)
            log(f"mixed batch: 16 issuers (8 rsa2048 + 8 ec, rich "
                f"extensions, serial lens 8..20, Zipf split) built in "
                f"{time.perf_counter() - t0:.1f}s")
        else:
            if mix == "rsa":
                tpl = syncerts.make_template(
                    key_type="rsa2048", serial_len=20,
                    rich_extensions=True)
                log(f"rsa template: {len(tpl.leaf_der)}B leaf DER")
            elif mix == "":
                tpl = syncerts.make_template()
            else:
                raise BenchError(f"unknown CT_BENCH_MIX={mix!r}")
            datas, lens = syncerts.build_device_batches(
                tpl, n_batches, batch, pad_len)
            issuer_idx = jax.device_put(np.zeros((batch,), np.int32))
            epoch_cols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)
    except ValueError as err:
        raise BenchError(str(err))
    valid = jax.device_put(np.ones((batch,), bool))
    mixed = mix == "mixed"
    epoch_cols_dev = jax.device_put(epoch_cols)

    # CRITICAL (axon/PJRT): every device array must be an ARGUMENT.
    # A jitted program that closes over a committed device buffer — even
    # a scalar — permanently degrades all subsequent dispatches on this
    # stack to a ~70 ms synchronous path (measured; see PROGRESS notes).
    # numpy closures (epoch_cols) lower to HLO literals and are fine.
    #
    # DESIGN (round-3 postmortem of the r02 value-0 record): the sweep
    # loop lives INSIDE jit (lax.fori_loop), so the whole timed phase is
    # a couple of device executions rather than hundreds of dispatches.
    # On this axon stack every EXECUTION charges a hidden ~0.2 s toll on
    # the first later D2H read (measured: linear in executions-since-
    # last-read; 1,920 queued dispatches × ~0.27 s ≈ the 520 s r02
    # "hang" — block_until_ready alone never pays it, so r02's loop
    # looked healthy until its final read wedged the watchdog). One
    # fori_loop execution per chunk pays the toll once per CHUNK, and
    # the end-of-chunk value read makes the timing fully synchronous —
    # dispatch → compute → readback, nothing left in flight.
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def mega_step(table, fresh_acc, host_acc, epoch_base, n_sweeps,
                  datas, lens, issuer_idx, valid, ecols):
        g_count = datas.shape[0]

        def batch_body(g, carry):
            table, fresh_acc, host_acc, sweep = carry
            # Unique serials per (sweep, batch): write the epoch into
            # each lane's serial epoch window (single-template: uint32
            # at serial bytes 4..8; mixed: 24 bits at per-lane bytes
            # 1..4 — the lane counter occupies the serial's last 4
            # bytes in both schemas).
            e = (epoch_base + sweep * g_count + g).astype(jnp.uint32)
            if mixed:
                # Per-lane epoch window via ONE fused full-width where
                # (a [B, 3] advanced-index scatter would violate the
                # measured [B, small] layout rule — minor dims pad to
                # 128 lanes — and pay the ~7x misaligned-scatter toll).
                # The [B] offset vector broadcasts inside the fusion.
                colr = jnp.arange(datas.shape[2], dtype=jnp.int32)[None, :]
                k = colr - ecols[:, None]  # [B, pad]
                byte = jnp.where(
                    k == 0, (e >> 16) & 0xFF,
                    jnp.where(k == 1, (e >> 8) & 0xFF, e & 0xFF)
                ).astype(jnp.uint8)
                data = jnp.where((k >= 0) & (k < 3), byte, datas[g])
            else:
                # epoch_cols stays a host np constant closed over by
                # the jit (4 contiguous static columns lower to cheap
                # constant-index updates; only committed DEVICE buffer
                # closures are forbidden on this stack).
                eb = jnp.stack(
                    [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF,
                     e & 0xFF]
                ).astype(jnp.uint8)
                data = datas[g].at[:, epoch_cols].set(eb[None, :])
            table, out = pipeline.ingest_core(
                table, data, lens[g], issuer_idx, valid,
                jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
                jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0, 2), jnp.int32),
            )
            return (table,
                    fresh_acc + out.was_unknown.sum().astype(jnp.int32),
                    host_acc + out.host_lane.sum().astype(jnp.int32),
                    sweep)

        def sweep_body(s, carry):
            table, fresh_acc, host_acc, _ = carry
            return jax.lax.fori_loop(
                0, g_count, batch_body, (table, fresh_acc, host_acc, s)
            )

        table, fresh_acc, host_acc, _ = jax.lax.fori_loop(
            0, n_sweeps, sweep_body,
            (table, fresh_acc, host_acc, jnp.int32(0)),
        )
        return table, fresh_acc, host_acc

    # `_fetch` reads device scalars through a fresh (non-donated) output
    # and forces full synchronization including the per-execution toll.
    _fetch = jax.jit(lambda a: a + a.dtype.type(0))

    # Same layout selection as the aggregator (CTMR_TABLE, default
    # bucket): the timed step must measure the shipping table.
    if _table_layout() == "bucket":
        table = buckettable.make_table(capacity)
    else:
        table = hashtable.make_table(capacity)
    fresh_acc = jax.device_put(np.int32(0))
    host_acc = jax.device_put(np.int32(0))

    # Warmup: one single-sweep execution — compiles the program (the
    # sweep count is a dynamic while_loop bound, so chunks reuse it).
    t0 = time.perf_counter()
    table, fresh_acc, host_acc = mega_step(
        table, fresh_acc, host_acc, np.int32(0), np.int32(1),
        datas, lens, issuer_idx, valid, epoch_cols_dev)
    warm_fresh = int(_fetch(fresh_acc))
    compile_s = time.perf_counter() - t0
    log(f"compile + warmup sweep + synced read: {compile_s:.1f}s "
        f"(fresh={warm_fresh})")
    # A compile is not a hang: push the deadline out by what the
    # (uncached) headline compile consumed, so the watchdog guards the
    # measurement, not the compiler (the bucket-table step compiles in
    # ~200s cold, ~35s cached on this stack).
    extend_watchdog(compile_s)
    # Calibration: a second single-sweep execution, now compiled, gives
    # the honest per-sweep cost (incl. the per-execution overhead).
    t0 = time.perf_counter()
    table, fresh_acc, host_acc = mega_step(
        table, fresh_acc, host_acc, np.int32(n_batches), np.int32(1),
        datas, lens, issuer_idx, valid, epoch_cols_dev)
    int(_fetch(fresh_acc))
    per_sweep_s = max(time.perf_counter() - t0, 1e-4)
    warm_entries = 2 * n_batches * batch
    chunk_sweeps = max(1, min(int(exec_target_s / per_sweep_s),
                              max_total_sweeps))
    log(f"calibration: {per_sweep_s * 1e3:.1f} ms/sweep → "
        f"chunk_sweeps={chunk_sweeps} (cap {max_total_sweeps})")

    # Optional profiler capture of the timed phase (CT_BENCH_PROFILE=
    # <dir> → a jax.profiler trace viewable in TensorBoard/Perfetto),
    # the same machinery ct-fetch exposes via the profileDir directive.
    profile_dir = os.environ.get("CT_BENCH_PROFILE", "")
    profile_cm = (jax.profiler.trace(profile_dir) if profile_dir
                  else contextlib.nullcontext())

    # Timed chunks: each is one execution; _progress updates between
    # chunks so a watchdog fire still reports the partial measured rate.
    t0 = time.perf_counter()
    _progress["t0"] = t0
    processed = 0
    sweeps_done = 0
    chunk = 0
    with profile_cm:
        while (sweeps_done < max_total_sweeps
               and (chunk == 0 or time.perf_counter() - t0 < target_total_s)):
            chunk += 1
            n_sweeps = min(chunk_sweeps, max_total_sweeps - sweeps_done)
            epoch_base = (2 + sweeps_done) * n_batches
            table, fresh_acc, host_acc = mega_step(
                table, fresh_acc, host_acc,
                np.int32(epoch_base), np.int32(n_sweeps),
                datas, lens, issuer_idx, valid, epoch_cols_dev)
            chunk_fresh = int(_fetch(fresh_acc))  # full sync incl. toll
            now = time.perf_counter()
            sweeps_done += n_sweeps
            processed += n_sweeps * n_batches * batch
            _progress["processed"] = processed
            _progress["last_sync"] = now
            log(f"chunk {chunk}: {processed} entries in "
                f"{now - t0:.3f}s cumulative {processed / (now - t0):,.0f} "
                f"entries/s (fresh={chunk_fresh})")
        # Inside the with-block: profiler teardown (trace serialization)
        # must not count against the measured rate.
        elapsed = time.perf_counter() - t0
    if profile_dir:
        log(f"profiler trace written to {profile_dir}")

    # Parity gate: every processed entry was unique ⇒ every one must
    # have been inserted exactly once (no silent drops, no collisions).
    total_fresh = int(_fetch(fresh_acc))
    total_host = int(_fetch(host_acc))
    final_count = int(_fetch(table.count))
    expected = warm_entries + processed
    log(f"processed={processed} in {elapsed:.3f}s over {sweeps_done} sweeps; "
        f"fresh={total_fresh} host_lane={total_host} "
        f"table_count={final_count} expected={expected}")
    if final_count != expected or total_fresh != expected or total_host != 0:
        raise BenchError(
            "PARITY FAILURE: dedup table does not match unique-entry count: "
            f"table_count={final_count} expected={expected} "
            f"fresh={total_fresh} host_lane={total_host}"
        )

    rate = processed / elapsed

    # Test hook for the launcher's silent-death insurance: die the way
    # the 2026-07-31 run did — measured, logged, never emitted.
    if os.environ.get("CT_BENCH_TEST_DIE") == "post-measure":
        os.kill(os.getpid(), signal.SIGKILL)

    # -- end-to-end replay benchmark (BASELINE configs' ingest path) --
    # Wire-format entries → native C++ leaf decode → pack → H2D →
    # fused device step → readback, through the production
    # AggregatorSink with deviceQueueDepth pipelining — the e2e analog
    # of the reference's download→store loop
    # (/root/reference/cmd/ct-fetch/ct-fetch.go:180-246,398-488),
    # including issuer-count parity vs the per-entry host path
    # (DatabaseSink semantics) on the same stream.
    e2e = {}
    if os.environ.get("CT_BENCH_E2E", "1") == "1":
        try:
            e2e = run_e2e()
        except Exception as err:  # the headline number must survive
            e2e = {"e2e_error": f"{type(err).__name__}: {err}"[:300]}
            log(f"e2e bench failed: {e2e['e2e_error']}")

    emit({
        "metric": "ct_entries_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "entries/s/chip",
        "vs_baseline": round(rate / 10_000_000, 4),
        "compile_s": round(compile_s, 1),
        "sweeps": sweeps_done,
        **({"mix": mix, "pad_len": pad_len} if mix else {}),
        **e2e,
    })
    return 0


def run_e2e() -> dict:
    """The ingest-path benchmark: decode + pack + H2D + device + drain.

    Builds a wire-format entry stream (RFC 6962 leaf_input/extra_data,
    unique serial per entry) from a signed template, replays it through
    ``AggregatorSink.store_raw_batch`` (native batch decoder → packed
    fast path → pipelined device steps), and checks issuer-count parity
    against the exact host-lane implementation on a prefix of the same
    stream. Returns extra fields for the single bench JSON line.
    """
    import base64

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
    from ct_mapreduce_tpu.utils import syncerts

    # 2^20-lane dispatches: the tunneled stack charges ~0.2s of
    # readback toll per device execution regardless of size, so the
    # e2e leg uses the same execution width the headline proves works
    # (r04 ran 64K-lane dispatches here and paid the toll 16x more
    # often — device_wait was ~50x the step's compute cost).
    batch = int(os.environ.get("CT_BENCH_E2E_BATCH", "1048576"))
    n_batches = int(os.environ.get("CT_BENCH_E2E_BATCHES", "2"))
    # Pipelining depth: 2 (overlap) measured FASTER than 0 even on the
    # one-core host (32.5k vs 20.2k entries/s, docs/quiet_r05_run.log
    # + the depth experiment) — the single-core decode-contention
    # theory predicted the opposite and lost; synchronous ordering
    # serializes the tunnel waits without freeing the decoder.
    depth = int(os.environ.get("CT_BENCH_E2E_DEPTH", "2"))
    # Overlapped ingest (ingest/overlap.py): decode pool ‖ ordered
    # device submit ‖ drain consumer. The value is the decode pool
    # size; 0 reverts to the serial caller-thread dispatch.
    overlap = int(os.environ.get("CT_BENCH_E2E_OVERLAP", "2"))
    # Staged device queue (round 11): K chunks fused per resident
    # device envelope, fed by the double-buffered staging ring. The
    # default keeps K=1 (per-chunk dispatch) because the default e2e
    # shape already uses 2^20-lane executions — staging pays off when
    # the execution width is SMALLER than that (e.g.
    # CT_BENCH_E2E_BATCH=65536 CT_BENCH_E2E_STAGED_K=16 runs the same
    # lanes/execution while the ring ships H2D ahead of compute and
    # the per-execution readback toll is paid once per 16 chunks).
    staged_k = int(os.environ.get("CT_BENCH_E2E_STAGED_K", "1"))
    staging_depth = int(os.environ.get("CT_BENCH_E2E_STAGING_DEPTH", "2"))
    cn_batches = 1  # raw batches replayed through the CN-filter leg
    # The per-entry parity legs (host-exact + DatabaseSink→redis) cost
    # ~0.5 ms/entry in Python; cap their prefix so bigger device
    # batches don't balloon the non-measured legs.
    parity_n = min(batch, 16384)

    # Two issuers (BASELINE config #3's multi-issuer shape): entries
    # alternate, so the parity check covers per-issuer attribution too.
    # CT_BENCH_E2E_MIX=1 replays a realistic wire stream instead of the
    # minimal-ECDSA one: alternating rich-extension RSA-2048 and EC
    # leaves (the li length bound exceeds the narrow row width, so the
    # full 2048-wide decode+H2D path is the one measured — the same
    # regime CT_BENCH_MIX=rsa measures device-side).
    e2e_mix = os.environ.get("CT_BENCH_E2E_MIX", "0") == "1"
    if e2e_mix:
        tpls = [
            syncerts.make_template(issuer_cn="Bench Issuer 0",
                                   key_type="rsa2048", serial_len=20,
                                   rich_extensions=True),
            syncerts.make_template(issuer_cn="Bench Issuer 1",
                                   serial_len=16, rich_extensions=True),
        ]
        log(f"e2e mix: rsa {len(tpls[0].leaf_der)}B / "
            f"ec {len(tpls[1].leaf_der)}B leaves")
    else:
        tpls = [syncerts.make_template(issuer_cn=f"Bench Issuer {k}")
                for k in range(2)]
    t0 = time.perf_counter()
    raw_batches = []
    for i in range(n_batches):
        lis, eds = syncerts.make_wire_batch(tpls, i * batch, batch)
        raw_batches.append(RawBatch(lis, eds, i * batch, "bench-log"))
    log(f"e2e setup: {n_batches}x{batch} wire entries in "
        f"{time.perf_counter() - t0:.1f}s")

    # Warmup run on a throwaway aggregator: compiles the batch-shaped
    # ingest step once so the timed replay measures steady state. The
    # table capacity is part of the compiled shape — warm with the SAME
    # capacity as the timed aggregator, or the first timed dispatch
    # recompiles (~26s observed on the tunneled stack, r03 postmortem).
    capacity = 1 << max(17, (n_batches * batch).bit_length() + 1)
    t0 = time.perf_counter()
    warm_agg = TpuAggregator(capacity=capacity, batch_size=batch)
    warm_sink = AggregatorSink(warm_agg, flush_size=batch,
                               device_queue_depth=depth,
                               chunks_per_dispatch=staged_k,
                               staging_depth=staging_depth)
    warm_sink.store_raw_batch(raw_batches[0])
    warm_sink.flush()
    e2e_compile_s = time.perf_counter() - t0
    log(f"e2e warmup (compile): {e2e_compile_s:.1f}s")
    extend_watchdog(e2e_compile_s)  # same reasoning as the headline
    # Free the warmup table before the timed run — the jit cache is
    # keyed by shapes, not object lifetime, so the compiled step
    # survives while the duplicate full-capacity buffers do not.
    del warm_sink, warm_agg

    agg = TpuAggregator(capacity=capacity, batch_size=batch)
    sink = AggregatorSink(agg, flush_size=batch, device_queue_depth=depth,
                          overlap_workers=overlap,
                          chunks_per_dispatch=staged_k,
                          staging_depth=staging_depth)
    # Phase-budget capture: a private metrics sink records the sink's
    # decode/h2dSubmit/storeCertificate/completeBatch timers for JUST
    # the timed replay, so the JSON carries a breakdown proving where
    # the e2e wall time goes (decode vs submit vs device wait).
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    budget_sink = tmetrics.InMemSink()
    prev_sink = tmetrics.get_sink()
    tmetrics.set_sink(budget_sink)
    try:
        t0 = time.perf_counter()
        t_prev = t0
        for i, rb in enumerate(raw_batches):
            sink.store_raw_batch(rb)
            t_now = time.perf_counter()
            log(f"e2e batch {i + 1}/{n_batches}: +{t_now - t_prev:.2f}s")
            t_prev = t_now
        sink.flush()
        t_drain = time.perf_counter()
        snap = agg.drain()
        elapsed = time.perf_counter() - t0
        drain_s = elapsed - (t_drain - t0)
    finally:
        tmetrics.set_sink(prev_sink)
    total = n_batches * batch
    rate = total / elapsed
    samples = budget_sink.snapshot()["samples"]

    def _sum(key: str) -> float:
        return samples.get(f"ct-fetch.{key}", {}).get("sum", 0.0)

    complete_s = _sum("completeBatch")
    # In serial mode completeBatch waits are NESTED inside the
    # storeCertificate envelope (subtract to isolate submit cost); in
    # overlap mode completes run on the drain consumer thread, outside
    # it, so the envelope already IS pure submit cost. Dispatch-lock
    # wait is its own sample (dispatchLockWait) and is taken BEFORE
    # the storeCertificate envelope opens on every path, so the submit
    # occupancy gauge below no longer folds lock contention into
    # submit cost (the r05 budget overstated it).
    store_s = _sum("storeCertificate")
    lock_s = _sum("dispatchLockWait")
    dispatch_s = store_s if overlap else max(store_s - complete_s, 0.0)
    budget = {
        "e2e_decode_s": round(_sum("decodeBatch"), 3),
        "e2e_h2d_submit_s": round(_sum("h2dSubmit"), 3),
        "e2e_dispatch_s": round(dispatch_s, 3),
        "e2e_lock_wait_s": round(lock_s, 3),
        "e2e_device_wait_s": round(complete_s, 3),
        "e2e_drain_s": round(drain_s, 3),
    }
    # Per-stage OCCUPANCY: busy seconds inside each stage over the wall
    # clock. These are the phase gauges the overlap work is judged by —
    # stage occupancies summing past 1.0 is decode/device/drain time
    # genuinely overlapping, not serialized (the r05 budget summed to
    # ~1.0 by construction: every stage ran on the caller thread).
    budget["e2e_wall_s"] = round(elapsed, 3)
    budget["e2e_overlap_workers"] = overlap
    budget["e2e_chunks_per_dispatch"] = staged_k
    if staged_k > 1:
        counters = budget_sink.snapshot()["counters"]
        budget["e2e_staged_h2d_bytes"] = int(
            counters.get("ingest.h2d_bytes", 0.0))
    for stage, busy_s in (("decode", _sum("decodeBatch")),
                          ("dispatch", dispatch_s),
                          ("device_wait", complete_s),
                          ("drain", drain_s)):
        budget[f"e2e_occ_{stage}"] = round(
            busy_s / elapsed if elapsed > 0 else 0.0, 3)
    sink.close()  # stop overlap threads (no-op in serial mode)
    log(f"e2e: {total} entries in {elapsed:.2f}s = {rate:,.0f} entries/s "
        f"(drained total {snap.total}); budget: "
        + ", ".join(f"{k[4:-2]}={v:.2f}s" for k, v in budget.items()
                    if k.endswith("_s"))
        + "; occupancy: "
        + ", ".join(f"{k[8:]}={budget[k]:.2f}" for k in budget
                    if k.startswith("e2e_occ_")))
    if snap.total != total:
        raise BenchError(
            f"e2e dedup mismatch: drained {snap.total} != fed {total}"
        )

    # Issuer-count parity on a prefix of the same stream, against BOTH
    # reference-shaped paths:
    #  (a) the exact host lane (per-entry parse + host dedup), and
    #  (b) the rediscache path — BASELINE config #4's parity gate is
    #      defined against it: DatabaseSink → FilesystemDatabase →
    #      RESP2 RedisCache over a real TCP socket (an in-process
    #      miniredis stands in for redis-server; RedisHost-style real
    #      servers interchange freely, tests/test_redis_live.py).
    from ct_mapreduce_tpu.ingest.leaf import decode_entry
    from ct_mapreduce_tpu.ingest.sync import DatabaseSink
    from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase
    from ct_mapreduce_tpu.storage.noop import NoopBackend
    from ct_mapreduce_tpu.storage.rediscache import RedisCache
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    host = TpuAggregator(capacity=1 << 17, batch_size=batch)
    redis_server = MiniRedis().start()
    try:
        rcache = RedisCache(redis_server.address)
        db = FilesystemDatabase(NoopBackend(), rcache)
        dsink = DatabaseSink(db)
        t0 = time.perf_counter()
        rb0 = raw_batches[0]
        for j in range(parity_n):
            e = decode_entry(j, base64.b64decode(rb0.leaf_inputs[j]),
                             base64.b64decode(rb0.extra_datas[j]))
            host._host_exact(
                e.cert_der, host.registry.get_or_assign(e.issuer_der)
            )
            dsink.store(e, "bench-log")
        host_snap = host.drain()
        parity_total = parity_n
        log(f"e2e parity: host lane {host_snap.total} vs expected "
            f"{parity_total} ({time.perf_counter() - t0:.1f}s host+redis)")
        if host_snap.total != parity_total:
            raise BenchError(
                f"e2e parity mismatch: host {host_snap.total} != "
                f"{parity_total}"
            )
        if sorted(host_snap.issuers()) != sorted(snap.issuers()):
            raise BenchError("e2e parity mismatch: issuer sets differ")

        # (b) drain the redis keyspace the way storage-statistics does
        # (SCAN serials::* + SCARD) and demand exact per-(issuer, exp)
        # equality with the host lane's counts on the same prefix.
        redis_counts: dict = {}
        for isd in db.get_issuer_and_dates_from_cache():
            for exp in isd.exp_dates:
                kc = db.get_known_certificates(exp, isd.issuer)
                redis_counts[(isd.issuer.id(), exp.id())] = kc.count()
        if redis_counts != dict(host_snap.counts):
            host_counts = dict(host_snap.counts)
            diff = [
                (k, redis_counts.get(k), host_counts.get(k))
                for k in sorted(set(redis_counts) | set(host_counts))
                if redis_counts.get(k) != host_counts.get(k)
            ]
            raise BenchError(
                "e2e rediscache-path parity mismatch on "
                f"{len(diff)} key(s); first: {diff[0][0]} "
                f"redis={diff[0][1]} host={diff[0][2]}"
            )
        log(f"e2e rediscache-path parity: {sum(redis_counts.values())} "
            f"serials across {len(redis_counts)} (issuer, expDate) keys "
            "match the host lane exactly")
    finally:
        redis_server.stop()

    # Per-issuer attribution: entries alternate issuers exactly, so
    # both lanes must report a perfect split (the reference's
    # per-issuer serial counts, storage-statistics.go:28-99).
    def per_issuer(s):
        out: dict = {}
        for (iss, _exp), c in s.counts.items():
            out[iss] = out.get(iss, 0) + c
        return out

    # BASELINE config #2's shape (issuerCNFilter, noop backend): replay
    # a prefix with the CN filter matching only issuer 0 — exactly that
    # half may land, the rest must be filtered ON DEVICE.
    # The CN leg ALWAYS recompiles: cn_prefixes is a traced uint8[P, K]
    # input of the step, so P=0 -> P=1 changes the jit cache key no
    # matter what capacity is. Keep the capacity equal anyway (same
    # shape family) and, critically, charge the compile to the
    # watchdog budget like every other compile in this file.
    cn_agg = TpuAggregator(capacity=capacity, batch_size=batch,
                           cn_prefixes=("Bench Issuer 0",))
    cn_sink = AggregatorSink(cn_agg, flush_size=batch, device_queue_depth=depth)
    t0 = time.perf_counter()
    for rb in raw_batches[:cn_batches]:
        cn_sink.store_raw_batch(rb)
    cn_sink.flush()
    cn_s = time.perf_counter() - t0
    extend_watchdog(cn_s)
    log(f"e2e CN leg (incl. P=1 recompile): {cn_s:.1f}s")
    cn_total = cn_agg.drain().total
    cn_want = cn_batches * ((batch + 1) // 2)
    cn_filtered = cn_agg.metrics["filtered_cn"]
    log(f"e2e CN filter: kept {cn_total} (want {cn_want}), "
        f"device-filtered {cn_filtered}")
    if cn_total != cn_want:
        raise BenchError(
            f"e2e CN-filter parity: kept {cn_total} != {cn_want}"
        )
    if cn_filtered != cn_batches * batch - cn_want:
        raise BenchError(
            f"e2e CN-filter parity: filtered {cn_filtered} != "
            f"{cn_batches * batch - cn_want}"
        )

    dev_by_iss = per_issuer(snap)
    host_by_iss = per_issuer(host_snap)
    # Entries alternate k = j & 1 per batch: issuer 0 takes ceil(n/2).
    dev_split = sorted([n_batches * (batch // 2),
                        n_batches * ((batch + 1) // 2)])
    host_split = sorted([parity_n // 2, (parity_n + 1) // 2])
    if sorted(dev_by_iss.values()) != dev_split:
        raise BenchError(f"e2e issuer split wrong on device: {dev_by_iss}")
    if sorted(host_by_iss.values()) != host_split:
        raise BenchError(f"e2e issuer split wrong on host: {host_by_iss}")
    return {
        "e2e_entries_per_sec": round(rate, 1),
        "e2e_entries": total,
        **({"e2e_mix": 1} if e2e_mix else {}),
        # CTMR_PREPARSED=1 routes the timed replay down the pre-parsed
        # lane (host sidecars + walker-free device step); record which
        # lane produced the number.
        **({"e2e_preparsed": 1} if sink.preparsed else {}),
        **budget,
    }


def run_smoke() -> dict:
    """CT_BENCH_SMOKE=1: the overlapped-ingest gate, CPU-only, <60 s.

    Replays one synthetic wire stream through the SAME AggregatorSink
    machinery twice — serial (deviceQueueDepth 0: reference-exact
    ordering) and overlapped (ingest/overlap.py) — plus the
    DatabaseSink → rediscache leg, and enforces:

      (1) serial/overlap parity EXACT on table_count, host_lane, and
          the drained per-(issuer, expDate) counts;
      (2) rediscache serials: per-key serial SETS from the redis
          keyspace equal the generated truth, and per-key counts equal
          the overlapped drain;
      (3) the overlap overlaps: overlapped wall <
          0.85 × (decode_s + device_wait_s + drain_s) measured on the
          same run — a pipeline silently regressed to serial stages
          sums to ≈ wall and fails this.

    Decode runs the pure-Python lane (CTMR_NATIVE=0) for the smoke:
    byte-identical results (conformance-tested), and stage costs stay
    balanced enough on one CPU core that the inequality is meaningful
    — with the native decoder the decode stage is ~5 ms per chunk and
    the gate would measure noise.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")  # a CPU gate by contract

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics
    from ct_mapreduce_tpu.telemetry import trace as ttrace
    from ct_mapreduce_tpu.utils import syncerts

    # Stage busy time comes from the span tracer (ingest.decode /
    # ingest.submit / ingest.drain spans recorded by the pipeline
    # itself) instead of hand-summed counters: CTMR_TRACE names the
    # export path, else the smoke traces into a temp file so the gate
    # below is always span-derived and the trace artifact always
    # exists for tools/traceview.py.
    trace_self_enabled = False
    if not ttrace.enabled():
        import tempfile

        ttrace.enable(os.path.join(
            tempfile.gettempdir(), f"ctmr-smoke-trace-{os.getpid()}.json"))
        trace_self_enabled = True

    chunk = int(os.environ.get("CT_BENCH_SMOKE_CHUNK", "1024"))
    n_chunks = int(os.environ.get("CT_BENCH_SMOKE_CHUNKS", "8"))
    total = chunk * n_chunks
    overlap_workers = int(os.environ.get("CT_BENCH_SMOKE_OVERLAP", "2"))
    tpls = [syncerts.make_template(issuer_cn=f"Smoke Issuer {k}")
            for k in range(2)]
    raw_batches = []
    for i in range(n_chunks):
        lis, eds = syncerts.make_wire_batch(tpls, i * chunk, chunk)
        raw_batches.append(RawBatch(lis, eds, i * chunk, "smoke-log"))
    capacity = 1 << max(14, (2 * total).bit_length())

    def replay(overlap: int, depth: int, preparsed: bool = False,
               sharded: bool = False, staged: int = 0):
        if sharded:
            import jax as _jax
            from jax.sharding import Mesh

            from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

            n_dev = len(_jax.devices())
            while n_dev > 1 and chunk % n_dev:
                n_dev -= 1
            mesh = Mesh(np.array(_jax.devices()[:n_dev]), ("shard",))
            agg = ShardedAggregator(mesh, capacity=capacity,
                                    batch_size=chunk)
        else:
            agg = TpuAggregator(capacity=capacity, batch_size=chunk)
        sink = AggregatorSink(agg, flush_size=chunk,
                              device_queue_depth=depth,
                              overlap_workers=overlap,
                              preparsed=preparsed,
                              chunks_per_dispatch=staged)
        budget_sink = tmetrics.InMemSink()
        prev = tmetrics.get_sink()
        tmetrics.set_sink(budget_sink)
        t_us0 = ttrace.now_us()
        try:
            t0 = time.perf_counter()
            for rb in raw_batches:
                sink.store_raw_batch(rb)
            sink.flush()
            t_drain = time.perf_counter()
            snap = agg.drain()
            wall = time.perf_counter() - t0
            drain_s = time.perf_counter() - t_drain
        finally:
            tmetrics.set_sink(prev)
            sink.close()
        samples = budget_sink.snapshot()["samples"]

        def s(key):
            return samples.get(f"ct-fetch.{key}", {}).get("sum", 0.0)

        # Span-derived stage busy seconds (this replay's window of the
        # trace ring): decode pool ‖ submit thread ‖ drain consumer.
        # The submit+drain split is where the device work lands —
        # varies by backend: CPU's synchronous dispatch charges the
        # jitted step to the SUBMIT span, real TPU async dispatch
        # charges the wait to the drain consumer — so the device term
        # is their SUM, robust to either placement.
        t_us1 = ttrace.now_us()
        spans = [e for e in ttrace.snapshot_events()
                 if e.get("ph") == "X"
                 and t_us0 <= e["ts"] and e["ts"] + e["dur"] <= t_us1]

        def span_busy(name):
            return sum(e["dur"] for e in spans if e["name"] == name) / 1e6

        def span_count(name):
            return sum(1 for e in spans if e["name"] == name)

        counters = budget_sink.snapshot()["counters"]
        smp = budget_sink.snapshot()["samples"]
        if overlap and spans:
            decode_s = span_busy("ingest.decode")
            device_wait_s = (span_busy("ingest.submit")
                             + span_busy("ingest.drain"))
        else:  # serial replays keep the metric-envelope budget
            decode_s = s("decodeBatch")
            device_wait_s = s("completeBatch") or s("storeCertificate")
        return {
            "agg": agg, "snap": snap, "wall": wall,
            "decode_s": decode_s,
            "device_wait_s": device_wait_s,
            "drain_s": drain_s,
            # Via the fill hook: TpuAggregator reads table.count, the
            # sharded leg sums its per-shard counts.
            "table_count": agg._table_fill_exact(),
            "host_lane": agg.metrics["host_lane"],
            "flag_bytes": counters.get("ingest.d2h_flag_bytes", 0.0),
            # Staged-leg accounting (zero in unstaged replays): span
            # busies for the staging H2D and the resident envelope, the
            # shipped staging bytes, and the chunks-per-dispatch curve.
            "h2d_s": span_busy("ingest.h2d"),
            "staged_device_s": span_busy("device.step_staged"),
            "h2d_bytes": counters.get("ingest.h2d_bytes", 0.0),
            "dispatch_chunks": smp.get(
                "ingest.dispatch_chunks", {}).get("mean", 0.0),
            # Ground truth for the staged-queue gate: how many device
            # EXECUTIONS this replay dispatched (each one pays the
            # tunneled stack's per-execution readback toll).
            "device_execs": (span_count("device.step")
                             + span_count("device.step_staged")
                             + span_count("device.step_preparsed")
                             + span_count("mesh.step")
                             + span_count("mesh.step_preparsed")),
        }

    prev_native = os.environ.get("CTMR_NATIVE")
    os.environ["CTMR_NATIVE"] = "0"
    try:
        # Warmup compiles the chunk-shaped step once (same capacity ⇒
        # same jit key), so both timed replays measure steady state.
        t0 = time.perf_counter()
        replay(overlap=0, depth=0)
        log(f"smoke warmup (compile): {time.perf_counter() - t0:.1f}s")

        serial = replay(overlap=0, depth=0)
        over = replay(overlap=overlap_workers, depth=2)
    finally:
        if prev_native is None:
            os.environ.pop("CTMR_NATIVE", None)
        else:
            os.environ["CTMR_NATIVE"] = prev_native

    log(f"smoke serial: wall={serial['wall']:.3f}s "
        f"decode={serial['decode_s']:.3f} device={serial['device_wait_s']:.3f} "
        f"drain={serial['drain_s']:.3f} table={serial['table_count']}")
    log(f"smoke overlap: wall={over['wall']:.3f}s "
        f"decode={over['decode_s']:.3f} device={over['device_wait_s']:.3f} "
        f"drain={over['drain_s']:.3f} table={over['table_count']}")

    # (1) serial/overlap parity, exact.
    if serial["table_count"] != over["table_count"]:
        raise BenchError(
            f"smoke parity: table_count serial {serial['table_count']} != "
            f"overlap {over['table_count']}")
    if serial["host_lane"] != over["host_lane"]:
        raise BenchError(
            f"smoke parity: host_lane serial {serial['host_lane']} != "
            f"overlap {over['host_lane']}")
    if serial["snap"].counts != over["snap"].counts:
        raise BenchError("smoke parity: drained counts differ")
    if over["snap"].total != total:
        raise BenchError(
            f"smoke dedup: drained {over['snap'].total} != fed {total}")

    # (2) rediscache serials on the same stream (DatabaseSink →
    # FilesystemDatabase → RESP2 over TCP → miniredis).
    import base64

    from ct_mapreduce_tpu.ingest.leaf import decode_entry
    from ct_mapreduce_tpu.ingest.sync import DatabaseSink
    from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase
    from ct_mapreduce_tpu.storage.noop import NoopBackend
    from ct_mapreduce_tpu.storage.rediscache import RedisCache
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis
    from ct_mapreduce_tpu.utils.syncerts import stamp_serial

    t0 = time.perf_counter()
    redis_server = MiniRedis().start()
    try:
        db = FilesystemDatabase(NoopBackend(), RedisCache(redis_server.address))
        dsink = DatabaseSink(db)
        for rb in raw_batches:
            for j in range(len(rb.leaf_inputs)):
                e = decode_entry(j, base64.b64decode(rb.leaf_inputs[j]),
                                 base64.b64decode(rb.extra_datas[j]))
                dsink.store(e, "smoke-log")
        redis_counts, redis_serials = {}, {}
        for isd in db.get_issuer_and_dates_from_cache():
            for exp in isd.exp_dates:
                kc = db.get_known_certificates(exp, isd.issuer)
                key = (isd.issuer.id(), exp.id())
                redis_counts[key] = kc.count()
                redis_serials[key] = {s.serial for s in kc.known()}
    finally:
        redis_server.stop()
    if redis_counts != dict(over["snap"].counts):
        raise BenchError(
            f"smoke rediscache parity: counts differ "
            f"(redis {sum(redis_counts.values())} vs overlap "
            f"{over['snap'].total})")
    # The stream's serials are generated, so the exact SET is known:
    # per template k, serials stamp_serial(tpl, j) for its lanes.
    want_serials = [set(), set()]
    for j in range(total):
        k = j % 2
        der = stamp_serial(tpls[k], j)
        # serial content bytes at the template's window
        off, ln = tpls[k].serial_off, tpls[k].serial_len
        want_serials[k].add(der[off:off + ln])
    got_union = set().union(*redis_serials.values()) if redis_serials else set()
    if got_union != want_serials[0] | want_serials[1]:
        raise BenchError(
            f"smoke rediscache parity: serial SET mismatch "
            f"({len(got_union)} redis vs {total} generated)")
    log(f"smoke rediscache leg: {sum(redis_counts.values())} serials across "
        f"{len(redis_counts)} keys match exactly "
        f"({time.perf_counter() - t0:.1f}s)")

    # (2b) pre-parsed lane parity + compact-readback gate. Runs with
    # the NATIVE decoder (the lane requires it — sidecars are the
    # native walker port); parity must be exact against the walker
    # lanes above, and the D2H flag traffic must be O(flagged), not
    # O(batch): with zero flagged lanes it is the fixed per-chunk
    # count+compacted-id block, orders below one int32 status row.
    from ct_mapreduce_tpu.native import available as native_available

    if native_available():
        pre = replay(overlap=overlap_workers, depth=2, preparsed=True)
        log(f"smoke preparsed: wall={pre['wall']:.3f}s "
            f"table={pre['table_count']} host_lane={pre['host_lane']} "
            f"flag_bytes={pre['flag_bytes']:.0f}")
        if pre["table_count"] != serial["table_count"]:
            raise BenchError(
                f"smoke parity: table_count preparsed {pre['table_count']}"
                f" != serial {serial['table_count']}")
        if pre["host_lane"] != serial["host_lane"]:
            raise BenchError(
                f"smoke parity: host_lane preparsed {pre['host_lane']} != "
                f"serial {serial['host_lane']}")
        if pre["snap"].counts != serial["snap"].counts:
            raise BenchError("smoke parity: preparsed drained counts differ")
        if sorted(pre["snap"].issuers()) != sorted(serial["snap"].issuers()):
            raise BenchError("smoke parity: preparsed issuer sets differ")
        # Per-chunk flag block: 2 count words + the compacted overflow
        # ids (cap scales sub-linearly and is bounded at 1024 lanes).
        flag_cap = min(1024, max(64, chunk // 64))
        flag_budget = 4 * (2 + flag_cap) * n_chunks
        if not (0 < pre["flag_bytes"] <= flag_budget):
            raise BenchError(
                f"smoke compact readback: flag bytes {pre['flag_bytes']:.0f}"
                f" outside (0, {flag_budget}] — flag traffic is not "
                "O(flagged)")
        if pre["flag_bytes"] >= 4 * chunk * n_chunks:
            raise BenchError(
                f"smoke compact readback: flag bytes {pre['flag_bytes']:.0f}"
                f" >= one int32 status row per chunk "
                f"({4 * chunk * n_chunks}) — readback regressed to O(batch)")

        # (2c) sharded pre-parsed leg: the SAME stream through
        # ShardedAggregator's host-routed pre-parsed step (fingerprint
        # home shards computed in numpy, no all_to_all). Parity must be
        # exact against the serial walker lane, and the compact-flag
        # budget is unchanged (the reassembled readback keeps the
        # per-chunk O(flagged) layout).
        shp = replay(overlap=0, depth=0, preparsed=True, sharded=True)
        log(f"smoke sharded-preparsed: wall={shp['wall']:.3f}s "
            f"table={shp['table_count']} host_lane={shp['host_lane']} "
            f"flag_bytes={shp['flag_bytes']:.0f}")
        if shp["table_count"] != serial["table_count"]:
            raise BenchError(
                f"smoke parity: table_count sharded-preparsed "
                f"{shp['table_count']} != serial {serial['table_count']}")
        if shp["host_lane"] != serial["host_lane"]:
            raise BenchError(
                f"smoke parity: host_lane sharded-preparsed "
                f"{shp['host_lane']} != serial {serial['host_lane']}")
        if shp["snap"].counts != serial["snap"].counts:
            raise BenchError(
                "smoke parity: sharded-preparsed drained counts differ")
        if not (0 < shp["flag_bytes"] <= flag_budget):
            raise BenchError(
                f"smoke compact readback (sharded): flag bytes "
                f"{shp['flag_bytes']:.0f} outside (0, {flag_budget}] — "
                "flag traffic is not O(flagged)")

        # (2d) intra-chunk decode-thread parity: the native worker
        # pool's threads>1 decode + sidecar extraction must be
        # byte-exact vs threads=1 on real wire bytes.
        from ct_mapreduce_tpu.native import leafpack

        lis0, eds0 = raw_batches[0].leaf_inputs, raw_batches[0].extra_datas
        d_1 = leafpack.decode_raw_batch(lis0, eds0, 1024, threads=1)
        d_n = leafpack.decode_raw_batch(lis0, eds0, 1024, threads=4)
        for fld in ("data", "length", "timestamp_ms", "entry_type",
                    "status", "issuer_group"):
            if not np.array_equal(getattr(d_1, fld), getattr(d_n, fld)):
                raise BenchError(
                    f"smoke decode-threads parity: {fld} differs "
                    "between threads=1 and threads=4")
        if d_1.group_issuers != d_n.group_issuers:
            raise BenchError(
                "smoke decode-threads parity: issuer groups differ")
        s_1 = leafpack.extract_sidecars(d_1.data, d_1.length, threads=1)
        s_n = leafpack.extract_sidecars(d_1.data, d_1.length, threads=4)
        for fld in vars(s_1):
            if not np.array_equal(getattr(s_1, fld), getattr(s_n, fld)):
                raise BenchError(
                    f"smoke decode-threads parity: sidecar {fld} differs")
        log("smoke decode-threads leg: threads=4 byte-exact vs threads=1 "
            f"({len(lis0)} wire entries)")

        # (2f) staged leg: the SAME stream through the staged device
        # queue (round 11) — K chunks per resident envelope, fed by
        # the double-buffered staging ring. Honesty note (BENCHLOG
        # round 11): on THIS 1-core CPU container the raw walls are
        # parity-neutral (~1.0x vs per-chunk overlap at every chunk
        # size tried — the XLA walker execution dominates and nothing
        # overlaps on one core), so the wall itself is gated only as
        # no-regression. What staging buys is STRUCTURAL and is gated
        # as ground truth from spans: the same corpus runs in
        # n_chunks/K device executions instead of n_chunks, and on the
        # tunneled TPU stack every execution charges ~0.2 s on its
        # first later D2H read (the platform toll measured in rounds
        # 3-5, BENCHLOG) — the toll-modeled e2e below is where the
        # >=1.3x acceptance gate lives.
        staged_k = int(os.environ.get("CT_BENCH_SMOKE_STAGED_K", "4"))
        # Warm the envelope shape outside the timed replay (its ~10 s
        # XLA compile would otherwise land in the staged wall).
        replay(overlap=0, depth=0, staged=staged_k)
        stg = replay(overlap=overlap_workers, depth=2, staged=staged_k)
        exec_toll_s = 0.2  # tunneled-stack per-execution readback toll
        over_modeled = over["wall"] + exec_toll_s * over["device_execs"]
        stg_modeled = stg["wall"] + exec_toll_s * stg["device_execs"]
        log(f"smoke staged: wall={stg['wall']:.3f}s K={staged_k} "
            f"table={stg['table_count']} host_lane={stg['host_lane']} "
            f"execs={stg['device_execs']} (overlap leg "
            f"{over['device_execs']}) h2d={stg['h2d_s'] * 1e3:.1f}ms/"
            f"{stg['h2d_bytes'] / 1e6:.1f}MB "
            f"device={stg['staged_device_s']:.3f}s "
            f"mean_chunks/dispatch={stg['dispatch_chunks']:.1f}; "
            f"tunneled-toll model ({exec_toll_s:.1f}s/exec): "
            f"{stg_modeled:.2f}s vs PR-1 {over_modeled:.2f}s "
            f"({over_modeled / stg_modeled:.2f}x)")
        if stg["table_count"] != serial["table_count"]:
            raise BenchError(
                f"smoke parity: table_count staged {stg['table_count']} "
                f"!= serial {serial['table_count']}")
        if stg["host_lane"] != serial["host_lane"]:
            raise BenchError(
                f"smoke parity: host_lane staged {stg['host_lane']} != "
                f"serial {serial['host_lane']}")
        if stg["snap"].counts != serial["snap"].counts:
            raise BenchError("smoke parity: staged drained counts differ")
        if sorted(stg["snap"].issuers()) != sorted(
                serial["snap"].issuers()):
            raise BenchError("smoke parity: staged issuer sets differ")
        # The staged path actually staged: every dispatch carried K
        # chunks (8 chunks / K dispatches, no ragged flushes on this
        # corpus) and the staging H2D went through its span.
        if abs(stg["dispatch_chunks"] - staged_k) > 1e-9:
            raise BenchError(
                f"smoke staged: mean chunks/dispatch "
                f"{stg['dispatch_chunks']:.2f} != {staged_k} — the "
                "staging ring is not filling")
        if not (stg["h2d_s"] > 0 and stg["h2d_bytes"] > 0):
            raise BenchError(
                "smoke staged: no ingest.h2d span/bytes recorded — the "
                "staging H2D path is not instrumented")
        if stg["staged_device_s"] <= 0:
            raise BenchError(
                "smoke staged: no device.step_staged span — the "
                "resident envelope did not run")
        # Span-derived budget: the staging H2D must be hidden behind
        # device compute, not serialize the pipeline — its enqueue
        # busy is a sliver of the replay wall (the dispatch span
        # itself is async-enqueue and can be sub-ms, so the wall is
        # the robust denominator).
        if stg["h2d_s"] >= 0.1 * stg["wall"]:
            raise BenchError(
                f"smoke staged H2D: h2d busy {stg['h2d_s']:.3f}s >= 10% "
                f"of the staged wall {stg['wall']:.3f}s — staging "
                "transfer is not overlapped with compute")
        # Structural gate (ground truth, span-counted): the staged
        # corpus ran in n/K device executions vs the PR-1 leg's n.
        if stg["device_execs"] * staged_k > over["device_execs"]:
            raise BenchError(
                f"smoke staged: {stg['device_execs']} device executions "
                f"x K={staged_k} > PR-1 leg's {over['device_execs']} — "
                "chunks are not actually fused per dispatch")
        # The acceptance gate, on the tunneled-stack execution-toll
        # model: each device execution charges ~0.2 s on its first
        # later D2H read (BENCHLOG rounds 3-5 platform notes), so the
        # modeled e2e must beat the PR-1 overlap baseline by >= 1.3x.
        # The RAW wall on this 1-core box is parity-neutral and gated
        # only against regression (15% noise allowance).
        if stg_modeled * 1.3 > over_modeled:
            raise BenchError(
                f"smoke staged: toll-modeled e2e {stg_modeled:.2f}s not "
                f">=1.3x below the PR-1 overlap baseline "
                f"{over_modeled:.2f}s")
        if stg["wall"] > 1.15 * over["wall"]:
            raise BenchError(
                f"smoke staged: raw wall {stg['wall']:.3f}s regressed "
                f"past 1.15x the PR-1 overlap wall {over['wall']:.3f}s")
    else:
        pre = shp = stg = None
        log("smoke preparsed leg skipped: native library unavailable")

    # (2e) serve leg: the query plane (ISSUE 5) over the overlapped
    # aggregator, WHILE a background thread keeps ingesting fresh
    # serials — parity against the known fed/absent truth, dynamic
    # batching effectiveness from the serve.batch spans, a span-derived
    # p99 wait bound, and an explicit load-shed gate.
    import threading as _threading
    import urllib.request as _urlreq

    from ct_mapreduce_tpu.core import der as _hostder
    from ct_mapreduce_tpu.core.types import ExpDate as _ExpDate
    from ct_mapreduce_tpu.core.types import Issuer as _Issuer
    from ct_mapreduce_tpu.serve.batcher import MicroBatcher, Overloaded
    from ct_mapreduce_tpu.serve.server import QueryServer

    agg = over["agg"]
    idents = []
    for tpl in tpls:
        iss_id = _Issuer.from_spki(
            _hostder.parse_cert(tpl.issuer_der).spki).id()
        eh = _hostder.parse_cert(tpl.leaf_der).not_after_unix_hour
        idents.append((iss_id, _ExpDate.from_unix_hour(eh).id()))

    def q_of(j):
        k = j % 2
        tpl = tpls[k]
        der = syncerts.stamp_serial(tpl, j)
        return {
            "issuer": idents[k][0], "expDate": idents[k][1],
            "serial": der[
                tpl.serial_off : tpl.serial_off + tpl.serial_len].hex(),
        }

    serve_delay = 0.003
    t_sv0 = ttrace.now_us()
    srv = QueryServer(agg, 0, host="127.0.0.1", max_batch=256,
                      max_delay_s=serve_delay, max_staleness_s=0.5).start()
    ingest_stop = _threading.Event()

    def bg_ingest():
        # Fresh serials [total, 2·total): the table keeps stepping (and
        # possibly growing) underneath the pinned views.
        j0 = total
        while not ingest_stop.is_set() and j0 < 2 * total:
            entries = [(syncerts.stamp_serial(tpls[j % 2], j),
                        tpls[j % 2].issuer_der)
                       for j in range(j0, j0 + 256)]
            agg.ingest(entries)
            j0 += 256

    lat: list[float] = []
    mism: list = []

    def http_client(seed):
        rng = np.random.default_rng(seed)
        for _ in range(12):
            pres = [int(rng.integers(total)) for _ in range(3)]
            # [3·total, 4·total): never fed by any leg, must be absent.
            absent = [int(rng.integers(3 * total, 4 * total))]
            body = json.dumps(
                {"queries": [q_of(j) for j in pres + absent]}).encode()
            req = _urlreq.Request(
                f"http://127.0.0.1:{srv.port}/query", data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with _urlreq.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            lat.append(time.perf_counter() - t0)  # GIL-atomic append
            got = [r["known"] for r in out["results"]]
            if got != [True, True, True, False]:
                mism.append((pres + absent, got))

    def burst_client(seed):
        # In-process single-lane floods: the cross-request coalescing
        # load (16 concurrent single queries MUST merge into batches).
        rng = np.random.default_rng(1000 + seed)
        iss_idx = agg.registry.index_of_issuer_id(idents[0][0])
        eh = _hostder.parse_cert(tpls[0].leaf_der).not_after_unix_hour
        for _ in range(18):
            j = int(rng.integers(0, total, endpoint=False)) & ~1  # tpl 0
            der = syncerts.stamp_serial(tpls[0], j)
            sb = der[tpls[0].serial_off:
                     tpls[0].serial_off + tpls[0].serial_len]
            res = srv.oracle.query_raw([(iss_idx, eh, sb)])
            if not res[0][0]:
                mism.append(("burst", j))

    bg = _threading.Thread(target=bg_ingest)
    bg.start()
    clients = ([_threading.Thread(target=http_client, args=(s,))
                for s in range(4)]
               + [_threading.Thread(target=burst_client, args=(s,))
                  for s in range(12)])
    t_serve0 = time.perf_counter()
    for c in clients:
        c.start()
    for c in clients:
        c.join()
    serve_wall = time.perf_counter() - t_serve0
    ingest_stop.set()
    bg.join()
    srv.stop()
    t_sv1 = ttrace.now_us()
    if mism:
        raise BenchError(
            f"smoke serve parity: {len(mism)} wrong answers, first "
            f"{mism[0]} — queries during concurrent ingest are not "
            "snapshot-consistent")
    spans = [e for e in ttrace.snapshot_events()
             if e.get("ph") == "X" and t_sv0 <= e["ts"] <= t_sv1]
    batch_spans = [e for e in spans if e["name"] == "serve.batch"]
    wait_spans = [e for e in spans if e["name"] == "serve.wait"]
    if not batch_spans or not wait_spans:
        raise BenchError(
            "smoke serve: no serve.batch/serve.wait spans — the serve "
            "path is not traced")
    mean_lanes = (sum(e["args"]["lanes"] for e in batch_spans)
                  / len(batch_spans))
    max_requests = max(e["args"]["requests"] for e in batch_spans)
    if mean_lanes <= 1.0:
        raise BenchError(
            f"smoke serve batching: mean lanes/batch {mean_lanes:.2f} "
            "<= 1 — the batcher is not forming batches")
    if max_requests <= 1:
        raise BenchError(
            "smoke serve batching: no batch ever coalesced more than "
            "one request — dynamic batching is not happening")
    max_batch_s = max(e["dur"] for e in batch_spans) / 1e6
    waits = sorted(e["dur"] / 1e6 for e in wait_spans)
    p50_wait = waits[len(waits) // 2]
    p99_wait = waits[min(len(waits) - 1, int(0.99 * len(waits)))]
    # A waiter sees: its batch forming (<= max_delay) + at most one
    # in-flight batch draining + its own batch executing.
    wait_budget = serve_delay + 2 * max_batch_s + 0.1
    if p99_wait > wait_budget:
        raise BenchError(
            f"smoke serve wait: p99 {p99_wait * 1e3:.1f}ms > max_delay "
            f"+ 2x batch execution + slack ({wait_budget * 1e3:.1f}ms)")
    lat.sort()
    serve_lanes = 4 * len(lat) + 12 * 18
    log(f"smoke serve: {len(lat)} http requests + {12 * 18} burst "
        f"queries in {serve_wall:.2f}s ({serve_lanes / serve_wall:,.0f} "
        f"lanes/s), {len(batch_spans)} batches, mean {mean_lanes:.1f} "
        f"lanes/batch (max {max_requests} reqs), wait p50 "
        f"{p50_wait * 1e3:.1f}ms p99 {p99_wait * 1e3:.1f}ms")

    # Load-shed gate: a stalled oracle behind a 4-lane admission queue
    # must reject loudly — and every admitted request still answers.
    hold = _threading.Event()

    def slow_oracle(items):
        hold.wait(timeout=10)
        return [True] * len(items)

    shed_b = MicroBatcher(slow_oracle, max_batch=8, max_delay_s=0.001,
                          max_queue_lanes=4)
    shed_ok: list[int] = []
    shed_rej: list[int] = []

    def shed_client(k):
        try:
            shed_b.submit([k])
            shed_ok.append(k)
        except Overloaded:
            shed_rej.append(k)

    shed_threads = [_threading.Thread(target=shed_client, args=(k,))
                    for k in range(16)]
    for t in shed_threads:
        t.start()
        time.sleep(0.002)
    hold.set()
    for t in shed_threads:
        t.join()
    shed_b.close()
    if not shed_rej:
        raise BenchError(
            "smoke serve shed: 16 requests against a 4-lane queue with "
            "a stalled oracle produced zero overloaded rejections")
    if not shed_ok or len(shed_ok) + len(shed_rej) != 16:
        raise BenchError(
            f"smoke serve shed: admitted {len(shed_ok)} + shed "
            f"{len(shed_rej)} != 16 — requests lost")
    log(f"smoke serve shed leg: {len(shed_rej)}/16 rejected overloaded, "
        f"{len(shed_ok)} served after the stall")

    # (2g) serve-device leg (ISSUE 7): the replicated device tier over
    # the same aggregator — ≥2 epoch-pinned replicas serving
    # round-robin through the jitted contains kernels while a
    # background thread keeps ingesting, with the hot-serial cache in
    # front of the batcher on a zipf-ish probe mix (a hot working set
    # probed repeatedly). Gates, all span-/counter-derived: exact
    # parity, serve.contains_device execution spans present, ≥2
    # distinct replicas actually answered batches, cache hits > 0, and
    # batch occupancy (mean lanes/batch) still > 1 for the misses.
    from ct_mapreduce_tpu.serve.server import MembershipOracle
    from ct_mapreduce_tpu.telemetry.metrics import get_sink as _get_sink

    sd_idx = [agg.registry.index_of_issuer_id(idents[k][0])
              for k in (0, 1)]
    sd_eh = [_hostder.parse_cert(tpls[k].leaf_der).not_after_unix_hour
             for k in (0, 1)]

    def sd_item(j):
        k = j % 2
        tpl = tpls[k]
        der = syncerts.stamp_serial(tpl, j)
        return (sd_idx[k], sd_eh[k],
                der[tpl.serial_off : tpl.serial_off + tpl.serial_len])

    dev_oracle = MembershipOracle(
        agg, max_batch=128, max_delay_s=0.003, max_staleness_s=0.3,
        device=True, replicas=2, cache_size=512)
    dev_oracle.snapshots.warm()
    # Compile the contains widths outside the timed window (keys in
    # [6·total, 7·total): never probed by any leg, absent forever).
    for w in (16, 32, 64, 128):
        dev_oracle.query_raw([sd_item(6 * total + k) for k in range(w)])
    sd_c0 = dict(_get_sink().snapshot().get("counters", {}))
    t_sd0 = ttrace.now_us()
    sd_stop = _threading.Event()

    def sd_ingest():
        # Fresh serials [5·total, 6·total): the table keeps stepping
        # (and possibly growing) while the replicas stagger-refresh.
        j0 = 5 * total
        while not sd_stop.is_set() and j0 < 6 * total:
            agg.ingest([(syncerts.stamp_serial(tpls[j % 2], j),
                         tpls[j % 2].issuer_der)
                        for j in range(j0, j0 + 256)])
            j0 += 256

    sd_mism: list = []

    def sd_client(seed):
        rng = np.random.default_rng(7000 + seed)
        hot = [int(rng.integers(total)) for _ in range(8)]
        for _ in range(40):
            r = rng.random()
            if r < 0.7:  # the zipf-ish head: repeats ⇒ cache hits
                j = hot[int(rng.integers(len(hot)))]
            elif r < 0.85:
                j = int(rng.integers(total))  # cold present
            else:
                j = int(rng.integers(3 * total, 4 * total))  # absent
            res = dev_oracle.query_raw([sd_item(j)])
            if res[0][0] != (j < total):
                sd_mism.append((j, res[0][0]))

    sd_bg = _threading.Thread(target=sd_ingest)
    sd_clients = [_threading.Thread(target=sd_client, args=(s,))
                  for s in range(12)]
    t_sd_wall = time.perf_counter()
    sd_bg.start()
    for c in sd_clients:
        c.start()
    for c in sd_clients:
        c.join()
    sd_wall = time.perf_counter() - t_sd_wall
    sd_stop.set()
    sd_bg.join()
    dev_oracle.close()
    t_sd1 = ttrace.now_us()
    sd_c1 = _get_sink().snapshot().get("counters", {})
    if sd_mism:
        raise BenchError(
            f"smoke serve-device parity: {len(sd_mism)} wrong answers, "
            f"first {sd_mism[0]} — the replicated device path is not "
            "snapshot-consistent under concurrent ingest")
    sd_spans = [e for e in ttrace.snapshot_events()
                if e.get("ph") == "X" and t_sd0 <= e["ts"] <= t_sd1]
    sd_lookups = [e for e in sd_spans if e["name"] == "serve.lookup"]
    sd_dev_lookups = [e for e in sd_lookups
                      if e["args"].get("device") == 1]
    if not sd_dev_lookups:
        raise BenchError(
            "smoke serve-device: no device-mode serve.lookup spans — "
            "the plane fell back to the host mirror")
    sd_replicas = {e["args"].get("replica") for e in sd_dev_lookups}
    if len(sd_replicas) < 2:
        raise BenchError(
            f"smoke serve-device: only replicas {sd_replicas} answered "
            "— the pool is not round-robin serving >=2 replicas")
    sd_contains = [e for e in sd_spans
                   if e["name"] == "serve.contains_device"]
    if not sd_contains:
        raise BenchError(
            "smoke serve-device: no serve.contains_device execution "
            "spans — membership did not run the jitted kernels")
    sd_batches = [e for e in sd_spans if e["name"] == "serve.batch"]
    sd_mean_lanes = (sum(e["args"]["lanes"] for e in sd_batches)
                     / len(sd_batches)) if sd_batches else 0.0
    if sd_mean_lanes <= 1.0:
        raise BenchError(
            f"smoke serve-device batching: mean lanes/batch "
            f"{sd_mean_lanes:.2f} <= 1 — misses are not coalescing")
    sd_hits = (sd_c1.get("serve.cache_hit", 0.0)
               - sd_c0.get("serve.cache_hit", 0.0))
    sd_misses = (sd_c1.get("serve.cache_miss", 0.0)
                 - sd_c0.get("serve.cache_miss", 0.0))
    if sd_hits <= 0:
        raise BenchError(
            "smoke serve-device cache: zero hits on a zipf-ish probe "
            "mix — the hot-serial cache is not serving")
    sd_fallback = (sd_c1.get("serve.device_fallback", 0.0)
                   - sd_c0.get("serve.device_fallback", 0.0))
    log(f"smoke serve-device: {12 * 40} zipf-ish queries in "
        f"{sd_wall:.2f}s under concurrent ingest — parity exact, "
        f"{len(sd_replicas)} replicas served "
        f"({len(sd_dev_lookups)} device lookups, {len(sd_contains)} "
        f"contains execs), cache {sd_hits:.0f} hits / "
        f"{sd_misses:.0f} misses "
        f"({sd_hits / max(1.0, sd_hits + sd_misses):.0%}), mean "
        f"{sd_mean_lanes:.1f} lanes/batch, fallbacks {sd_fallback:.0f}")

    # (3) the overlap inequality, on the overlapped run itself.
    budget_sum = over["decode_s"] + over["device_wait_s"] + over["drain_s"]
    ratio = over["wall"] / budget_sum if budget_sum > 0 else 99.0
    log(f"smoke overlap ratio: wall {over['wall']:.3f}s / "
        f"(decode+device+drain {budget_sum:.3f}s) = {ratio:.3f} "
        f"(gate < 0.85)")
    if ratio >= 0.85:
        raise BenchError(
            f"smoke overlap gate: wall {over['wall']:.3f}s >= 0.85 x "
            f"stage-budget sum {budget_sum:.3f}s (ratio {ratio:.3f}) — "
            "the pipeline is not overlapping its stages")

    # Export the trace the gate was computed from (CTMR_TRACE path, or
    # the temp file when self-enabled) — tools/traceview.py summarizes
    # it into the same per-stage occupancy.
    trace_path = ttrace.export()
    if trace_path:
        log(f"smoke trace: {trace_path} "
            f"(python tools/traceview.py {trace_path})")
    if trace_self_enabled:
        ttrace.disable()

    return {
        "metric": "ct_e2e_smoke",
        "value": round(total / over["wall"], 1),
        "unit": "entries/s",
        "smoke_entries": total,
        "smoke_serial_wall_s": round(serial["wall"], 3),
        "smoke_overlap_wall_s": round(over["wall"], 3),
        "smoke_decode_s": round(over["decode_s"], 3),
        "smoke_device_wait_s": round(over["device_wait_s"], 3),
        "smoke_drain_s": round(over["drain_s"], 3),
        "smoke_overlap_ratio": round(ratio, 3),
        "smoke_table_count": over["table_count"],
        "smoke_serve_parity": 1,
        "smoke_serve_lanes_per_s": round(serve_lanes / serve_wall, 1),
        "smoke_serve_batches": len(batch_spans),
        "smoke_serve_mean_batch_lanes": round(mean_lanes, 2),
        "smoke_serve_max_batch_requests": max_requests,
        "smoke_serve_wait_p50_ms": round(p50_wait * 1e3, 2),
        "smoke_serve_wait_p99_ms": round(p99_wait * 1e3, 2),
        "smoke_serve_shed": len(shed_rej),
        "smoke_serve_dev_parity": 1,
        "smoke_serve_dev_replicas": len(sd_replicas),
        "smoke_serve_dev_lookups": len(sd_dev_lookups),
        "smoke_serve_dev_contains_spans": len(sd_contains),
        "smoke_serve_dev_cache_hits": int(sd_hits),
        "smoke_serve_dev_cache_hit_rate": round(
            sd_hits / max(1.0, sd_hits + sd_misses), 3),
        "smoke_serve_dev_mean_batch_lanes": round(sd_mean_lanes, 2),
        "smoke_serve_dev_fallbacks": int(sd_fallback),
        **({"smoke_trace_path": trace_path} if trace_path else {}),
        **({"smoke_preparsed_wall_s": round(pre["wall"], 3),
            "smoke_preparsed_flag_bytes": int(pre["flag_bytes"]),
            "smoke_decode_threads_parity": 1}
           if pre is not None else {}),
        **({"smoke_sharded_preparsed_wall_s": round(shp["wall"], 3),
            "smoke_sharded_preparsed_flag_bytes": int(shp["flag_bytes"])}
           if shp is not None else {}),
        **({"smoke_staged_wall_s": round(stg["wall"], 3),
            "smoke_staged_raw_vs_overlap": round(
                over["wall"] / stg["wall"], 2) if stg["wall"] > 0 else 0,
            "smoke_staged_modeled_vs_overlap": round(
                over_modeled / stg_modeled, 2) if stg_modeled > 0 else 0,
            "smoke_staged_execs": stg["device_execs"],
            "smoke_overlap_execs": over["device_execs"],
            "smoke_staged_chunks_per_dispatch": round(
                stg["dispatch_chunks"], 2),
            "smoke_staged_h2d_s": round(stg["h2d_s"], 4),
            "smoke_staged_h2d_bytes": int(stg["h2d_bytes"]),
            "smoke_staged_device_s": round(stg["staged_device_s"], 3)}
           if stg is not None else {}),
    }


def run_verify_smoke() -> dict:
    """CT_BENCH_SMOKE verify leg (rounds 13 + 17): the signature-
    verification lane under the staged device queue, CPU-only.

    A mixed corpus — P-256 SCTs (valid and corrupted), P-384 SCTs
    (device lanes since round 17), RSA SCTs (host fallback), SCT-less
    certs, and unknown-log SCTs — replays through the SAME
    AggregatorSink machinery with ``verifySignatures`` on and
    ``chunksPerDispatch`` 2, and enforces:

      (1) verdict parity EXACT: per-outcome totals equal the truth
          recomputed independently per lane with the pure-python
          reference verifier;
      (2) the device kernels really ran and batched: span-counted
          ``device.verify`` executions with mean lanes/execution > 1;
      (3) the fallback lane count equals the undecidable-lane count
          (every lane the extractor or key registry routed around the
          device kernels — none silently dropped, none double-judged);
      (4) the windowed precompute really engaged: qtable hits > 0 and
          exactly one qtable miss per distinct device log key.

    Device batches pad to width 32 (the tier-1 parity suite's compiled
    width, so one process compiles each kernel once).
    """
    import base64
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
    from ct_mapreduce_tpu.telemetry import trace as ttrace
    from ct_mapreduce_tpu.utils import minicert
    from ct_mapreduce_tpu.verify import host as vhost
    from ct_mapreduce_tpu.verify import sct as sctlib

    owns_trace = not ttrace.enabled()
    if owns_trace:
        ttrace.enable(os.path.join(
            tempfile.mkdtemp(prefix="ctmr-verify-smoke-"),
            "verify_smoke_trace.json"))
    events_before = len(ttrace.snapshot_events())

    import datetime as _dt

    future = _dt.datetime(2031, 6, 15, tzinfo=_dt.timezone.utc)
    issuer = minicert.make_cert(serial=1, issuer_cn="Smoke Verify CA",
                                is_ca=True, not_after=future)
    p256 = sctlib.EcSctSigner("smoke-a")
    p384 = sctlib.EcSctSigner("smoke-b", vhost.P384)
    rsa = sctlib.RsaSctSigner()
    unknown = sctlib.EcSctSigner("smoke-unknown")

    n = 54
    pairs = []
    truth = {"verified": 0, "failed": 0, "no_sct": 0, "no_key": 0,
             "device": 0, "fallback": 0}
    for s in range(n):
        base = minicert.make_cert(
            serial=5000 + s, issuer_cn="Smoke Verify CA",
            subject_cn=f"sv{s}", is_ca=False, not_after=future)
        kind = s % 9
        if kind in (0, 1, 2, 3):
            der = sctlib.attach_sct(base, p256, 10**12 + s,
                                    corrupt_signature=(kind == 3),
                                    issuer_der=issuer)
            truth["device"] += 1
            truth["verified" if kind != 3 else "failed"] += 1
        elif kind == 4:
            der = sctlib.attach_sct(base, p384, 10**12 + s,
                                    issuer_der=issuer)
            truth["device"] += 1  # P-384 rides the device since r17
            truth["verified"] += 1
        elif kind == 5:
            der = sctlib.attach_sct(base, rsa, 10**12 + s,
                                    corrupt_signature=True,
                                    issuer_der=issuer)
            truth["fallback"] += 1
            truth["failed"] += 1
        elif kind in (6, 7):
            der = base
            truth["no_sct"] += 1
        else:
            der = sctlib.attach_sct(base, unknown, 10**12 + s,
                                    issuer_der=issuer)
            truth["no_key"] += 1
        pairs.append(der)

    lis = [base64.b64encode(leaflib.encode_leaf_input(
        d, timestamp_ms=1_700_000_000_000 + j)).decode()
        for j, d in enumerate(pairs)]
    eds = [base64.b64encode(
        leaflib.encode_extra_data([issuer])).decode()] * n

    t0 = time.monotonic()
    agg = TpuAggregator(capacity=1 << 12, batch_size=32)
    sink = AggregatorSink(agg, flush_size=32, device_queue_depth=0,
                          verify_signatures=True,
                          chunks_per_dispatch=2)
    sink.verifier.batch_width = 32
    for signer in (p256, p384, rsa):
        sink.verifier.keys.register_signer(signer)
    sink.store_raw_batch(RawBatch(lis, eds, 0, "verify-smoke-log"))
    sink.flush()
    wall = time.monotonic() - t0

    st = dict(sink.verifier.stats)
    for k_truth, k_stat in (("verified", "verified"),
                            ("failed", "failed"),
                            ("no_sct", "no_sct"),
                            ("no_key", "no_key"),
                            ("device", "device_lanes"),
                            ("fallback", "host_lanes")):
        if st[k_stat] != truth[k_truth]:
            raise BenchError(
                f"verify smoke parity: {k_stat}={st[k_stat]} != "
                f"truth {k_truth}={truth[k_truth]} ({st} vs {truth})")

    events = ttrace.snapshot_events()[events_before:]
    vspans = [e for e in events
              if e.get("name") == "device.verify" and e.get("ph") == "X"]
    span_lanes = sum(int(e.get("args", {}).get("lanes", 0))
                     for e in vspans)
    if not vspans or span_lanes != truth["device"]:
        raise BenchError(
            f"verify smoke spans: {len(vspans)} device.verify spans "
            f"covering {span_lanes} lanes != {truth['device']}")
    mean_lanes = span_lanes / len(vspans)
    if mean_lanes <= 1.0:
        raise BenchError(
            f"verify smoke batching: mean lanes/execution {mean_lanes}")
    per_issuer = agg.verify_counts()
    if (sum(v for v, _ in per_issuer.values()) != truth["verified"]
            or sum(f for _, f in per_issuer.values()) != truth["failed"]):
        raise BenchError(f"verify smoke per-issuer fold: {per_issuer}")
    # Round 17: the windowed precompute must really engage — one
    # qtable miss per distinct device log key (p256 + p384), hits for
    # every further lane under those keys, occupancy surfaced.
    if st["qtable_misses"] != 2 or st["qtable_hits"] \
            != truth["device"] - 2:
        raise BenchError(
            f"verify smoke qtable: misses={st['qtable_misses']} "
            f"hits={st['qtable_hits']} over {truth['device']} device "
            f"lanes / 2 keys")
    health = sink.verifier.health()
    if health["qtable"]["p256"]["occupancy"] != 1 \
            or health["qtable"]["p384"]["occupancy"] != 1:
        raise BenchError(f"verify smoke occupancy: {health['qtable']}")
    if owns_trace:
        ttrace.disable()

    log(f"verify smoke: {n} lanes in {wall:.2f}s — "
        f"{truth['device']} device / {truth['fallback']} fallback / "
        f"{truth['no_sct']} no-sct / {truth['no_key']} no-key; "
        f"{len(vspans)} device execs, {mean_lanes:.1f} lanes/exec")
    return {
        "metric": "ct_verify_smoke",
        "value": n / max(wall, 1e-9),
        "unit": "entries/s",
        "smoke_verify_lanes": n,
        "smoke_verify_verified": st["verified"],
        "smoke_verify_failed": st["failed"],
        "smoke_verify_device_lanes": st["device_lanes"],
        "smoke_verify_fallback_lanes": st["host_lanes"],
        "smoke_verify_no_sct": st["no_sct"],
        "smoke_verify_no_key": st["no_key"],
        "smoke_verify_device_execs": len(vspans),
        "smoke_verify_mean_batch_lanes": mean_lanes,
        "smoke_verify_qtable_hits": st["qtable_hits"],
        "smoke_verify_qtable_misses": st["qtable_misses"],
        "smoke_verify_window": sink.verifier.window,
        "smoke_verify_wall_s": wall,
    }


def run_audit_smoke() -> dict:
    """CT_BENCH_SMOKE audit leg (round 24): the recorded-shard audit
    pipeline at tier-1 scale, CPU-only.

    Replays the checked-in ``CTMRAU01`` shard (tests/data/
    recorded_shard.json.gz, 1024 entries signed by production-schema
    fixture logs) tiled to >= 10^5 entries through the FULL audit
    path — decode, native/mirror quarantine diff, log-list routing,
    device+host signature verification, per-issuer aggregation — and
    enforces:

      (1) every driver tally equals the fixture's MIX-derived ground
          truth × tile (verified/failed/no-key/retired/out-of-interval
          /device/host/no-sct — one wrong lane class anywhere fails);
      (2) the per-issuer verified/failed folds equal a HOST-recomputed
          oracle: one tile's SCT lanes re-extracted and re-verified
          lane-by-lane with the pure-python reference verifier,
          grouped by issuer key hash, scaled by tile;
      (3) quarantined == 0 PINNED — the native scanner and the python
          mirror agree on every real-corpus lane (a single divergence
          is a parity bug, not noise), and divergence was MEASURED
          whenever the native extractor is present;
      (4) tool-flow scale is linear by construction (the same driver
          tiles to >= 10^6: ``python tools/audit.py --recorded
          tests/data/recorded_shard.json.gz --tile 978``).

    Device batches pad to width 32 (the tier-1 parity suite's compiled
    width, so one process compiles each kernel once).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.audit import driver as audrvlib
    from ct_mapreduce_tpu.audit import fixture as auditfx
    from ct_mapreduce_tpu.audit import loglist as loglistlib
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.verify import sct as sctlib

    tile = int(os.environ.get("CT_BENCH_SMOKE_AUDIT_TILE", "98"))
    shard = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tests", "data", "recorded_shard.json.gz")
    doc = audrvlib.load_recorded(shard)
    log_list = loglistlib.parse_log_list(doc["log_list"])

    t0 = time.monotonic()
    drv = audrvlib.AuditDriver(log_list, batch_width=32)
    rep = drv.run_recorded(doc, tile=tile)
    wall = time.monotonic() - t0

    want = auditfx.expected_tallies()
    for name, got in (("entries", rep.entries),
                      ("sct_lanes", rep.sct_lanes),
                      ("no_sct", rep.no_sct),
                      ("verified", rep.verified),
                      ("failed", rep.failed),
                      ("no_key", rep.verifier_no_key),
                      ("device_lanes", rep.device_lanes),
                      ("host_lanes", rep.host_lanes),
                      ("retired", rep.retired),
                      ("out_of_interval", rep.out_of_interval),
                      ("unknown_log", rep.unknown_log)):
        if got != want[name] * tile:
            raise BenchError(
                f"audit smoke tally: {name}={got} != "
                f"{want[name]} x tile {tile}")
    if rep.quarantined != 0:
        raise BenchError(
            f"audit smoke: {rep.quarantined} lanes quarantined on the "
            f"real corpus — native/mirror extraction parity broke")
    try:
        from ct_mapreduce_tpu.native import load as _load_native

        native_ok = (os.environ.get("CTMR_NATIVE", "1") != "0"
                     and _load_native() is not None
                     and getattr(_load_native(), "has_sct", False))
    except Exception:
        native_ok = False
    if native_ok and not rep.divergence_measured:
        raise BenchError("audit smoke: native extractor present but "
                         "divergence was not measured")

    # Host-recomputed per-issuer oracle: ONE tile, every lane
    # re-extracted and re-verified with the pure-python reference,
    # grouped by issuer key hash (byte-identical tiles scale by tile).
    reg = log_list.registry()
    oracle: dict = {}
    for page in doc["pages"]:
        start = int(page.get("start", 0))
        for i, e in enumerate(page["entries"]):
            dec = leaflib.decode_json_entry(start + i, e)
            ikh = (sctlib.issuer_key_hash_of(dec.issuer_der)
                   if dec.issuer_der else sctlib.ZERO_IKH)
            status, sct, digest, _, _ = sctlib.extract_sct_lane(
                dec.cert_der, ikh)
            if status == sctlib.SCT_NONE or sct is None:
                continue
            key = reg.get(sct.log_id)
            if key is None:
                continue  # no_key lanes fold into no per-issuer row
            ok = sctlib.host_verify_sct(digest, sct, key)
            v, f = oracle.get(ikh, (0, 0))
            oracle[ikh] = (v + int(ok), f + int(not ok))
    want_folds = sorted((v * tile, f * tile)
                        for v, f in oracle.values())
    got_folds = sorted(rep.per_issuer.values())
    if want_folds != got_folds:
        raise BenchError(
            f"audit smoke per-issuer oracle: driver folds {got_folds} "
            f"!= host-recomputed {want_folds}")

    log(f"audit smoke: {rep.entries} entries (tile {tile}) in "
        f"{wall:.1f}s — verified {rep.verified} / failed {rep.failed} "
        f"/ no-key {rep.verifier_no_key}; flagged retired "
        f"{rep.retired}, out-of-interval {rep.out_of_interval}; "
        f"quarantined {rep.quarantined} "
        f"(measured={rep.divergence_measured}); "
        f"{len(rep.per_issuer)} issuer folds host-verified")
    return {
        "metric": "ct_audit_smoke",
        "value": rep.entries / max(wall, 1e-9),
        "unit": "entries/s",
        "smoke_audit_entries": rep.entries,
        "smoke_audit_tile": tile,
        "smoke_audit_verified": rep.verified,
        "smoke_audit_failed": rep.failed,
        "smoke_audit_no_key": rep.verifier_no_key,
        "smoke_audit_retired": rep.retired,
        "smoke_audit_out_of_interval": rep.out_of_interval,
        "smoke_audit_unknown_log": rep.unknown_log,
        "smoke_audit_device_lanes": rep.device_lanes,
        "smoke_audit_host_lanes": rep.host_lanes,
        "smoke_audit_quarantined": rep.quarantined,
        "smoke_audit_divergence_measured": int(rep.divergence_measured),
        "smoke_audit_per_issuer_groups": len(rep.per_issuer),
        "smoke_audit_wall_s": wall,
    }


def run_filter_smoke() -> dict:
    """CT_BENCH_SMOKE filter leg (round 15): filter-cascade emission
    from a fuzz-populated aggregation state, CPU-only.

    A randomized wire corpus (multiple issuers/expiry buckets,
    duplicate serials, the real AggregatorSink decode path) ingests at
    the overlap leg's exact compile shapes (chunk 1024, 2^14-slot
    table — one process pays the jit once across the smoke), then the
    checkpoint-time emission path compiles the filter artifact and the
    leg enforces:

      (1) ZERO false negatives over the FULL included set — every
          serial the aggregation state knows answers known through the
          cascade (and the capture's per-group sizes equal the drained
          report's counts exactly; filter-over-a-GROWN-table is pinned
          by tests/test_filter.py, which rehashes mid-corpus);
      (2) measured FP rate ≤ 2× the 0.01 target over a disjoint probe
          corpus (serial length outside the ingested space, so no
          probe can collide with an included identity);
      (3) determinism: a rebuild from the same state is byte-identical
          — and bits/entry + build rate are recorded for the BENCHLOG
          curve (tools/filtercost.py sweeps the full rate curve).
    """
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as _np

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.filter import read_artifact
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
    from ct_mapreduce_tpu.utils import syncerts

    fp_rate = 0.01
    chunk = 1024
    n_chunks = 2
    tpls = [syncerts.make_template(issuer_cn=f"Filter Smoke CA {k}")
            for k in range(3)]
    raw_batches = []
    for i in range(n_chunks):
        lis, eds = syncerts.make_wire_batch(tpls, i * chunk, chunk)
        raw_batches.append(RawBatch(lis, eds, i * chunk, "filter-smoke"))
    # Duplicate replay: the capture must not double-count dedup hits.
    lis, eds = syncerts.make_wire_batch(tpls, 0, chunk)
    raw_batches.append(RawBatch(lis, eds, n_chunks * chunk,
                                "filter-smoke"))

    agg = TpuAggregator(capacity=1 << 14, batch_size=chunk)
    sink = AggregatorSink(agg, flush_size=chunk, device_queue_depth=1)
    agg.enable_filter_capture()
    t0 = time.monotonic()
    for rb in raw_batches:
        sink.store_raw_batch(rb)
    sink.flush()
    ingest_s = time.monotonic() - t0
    snap = agg.drain()

    # (1a) capture == drained report, group for group.
    from ct_mapreduce_tpu.core.types import ExpDate

    cap_counts = {}
    for (idx, eh), serials in agg.filter_capture.items():
        key = (agg.registry.issuer_at(idx).id(),
               ExpDate.from_unix_hour(eh).id())
        cap_counts[key] = cap_counts.get(key, 0) + len(serials)
    if cap_counts != dict(snap.counts):
        raise BenchError(
            f"filter smoke: capture disagrees with the drained report "
            f"(capture {cap_counts} vs report {dict(snap.counts)})")

    state_dir = tempfile.mkdtemp(prefix="ct-filter-smoke-")
    state_path = os.path.join(state_dir, "agg.npz")
    filter_path = state_path + ".filter"
    agg.configure_filter_emission(filter_path, fp_rate)
    t0 = time.monotonic()
    agg.save_checkpoint(state_path)
    emit_s = time.monotonic() - t0
    art = read_artifact(filter_path)

    # (1b) zero false negatives over the full included set.
    total = fn = 0
    for (idx, eh), serials in sorted(agg.filter_capture.items()):
        g = art.group_for(agg.registry.issuer_at(idx).id(), eh)
        if g is None:
            raise BenchError(f"filter smoke: group missing for "
                             f"({idx}, {eh})")
        serials = sorted(serials)
        hits = art.query_group(g, serials)
        fn += int((~hits).sum())
        total += len(serials)
    if fn:
        raise BenchError(f"filter smoke: {fn}/{total} false negatives")

    # (2) measured FP over a disjoint probe corpus: 21-byte serials
    # cannot collide with any ingested identity (serial length is part
    # of the fingerprint message).
    rng = _np.random.default_rng(20260805)
    probes = [rng.integers(0, 256, 21, dtype=_np.uint8).tobytes()
              for _ in range(4000)]
    fp = probed = 0
    for (iss, exp_id), g in sorted(art.groups.items()):
        hits = art.query_group(g, probes)
        fp += int(_np.asarray(hits).sum())
        probed += len(probes)
    fp_measured = fp / max(1, probed)
    if fp_measured > 2 * fp_rate:
        raise BenchError(
            f"filter smoke: measured FP {fp_measured:.4f} > "
            f"2x target {fp_rate}")

    # (3) determinism: rebuild from the same state, byte for byte.
    from ct_mapreduce_tpu.filter import build_from_aggregator

    blob = art.to_bytes()
    if build_from_aggregator(agg, fp_rate=fp_rate).to_bytes() != blob:
        raise BenchError("filter smoke: rebuild is not byte-identical")

    sink.close()
    build_rate = total / max(emit_s, 1e-9)
    log(f"filter smoke: {total} serials / {len(art.groups)} groups -> "
        f"{len(blob)} B ({art.bits_per_entry():.2f} bits/entry, "
        f"{art.max_layers()} layers) in {emit_s:.2f}s; "
        f"measured FP {fp_measured:.4f} (target {fp_rate}), 0 FN")
    return {
        "metric": "ct_filter_smoke",
        "value": build_rate,
        "unit": "serials/s",
        "smoke_filter_serials": total,
        "smoke_filter_groups": len(art.groups),
        "smoke_filter_bytes": len(blob),
        "smoke_filter_bits_per_entry": art.bits_per_entry(),
        "smoke_filter_max_layers": art.max_layers(),
        "smoke_filter_false_negatives": fn,
        "smoke_filter_fp_target": fp_rate,
        "smoke_filter_fp_measured": fp_measured,
        "smoke_filter_probes": probed,
        "smoke_filter_table_capacity": agg.capacity,
        "smoke_filter_ingest_s": ingest_s,
        "smoke_filter_emit_s": emit_s,
    }


def run_filter_scale_smoke() -> dict:
    """CT_BENCH_SMOKE scaled-filter-build leg (round 19), CPU-only.

    A scaled-down packed corpus (40K serials / 12 groups, plus one
    list-sourced group carrying an oversized host-lane serial) builds
    through the fused multi-group dispatcher and the leg enforces the
    round-19 acceptance shape:

      (1) BYTE IDENTITY across every build path — fused (device),
          fused (NumPy lane), streamed at a prime chunk size, and the
          round-15 per-group reference path all serialize the same
          CTMRFL01 bytes;
      (2) the dispatch collapse really happened — fused scatter
          dispatches ≪ per-(group, layer) count, with >2 groups per
          dispatch on average (the lever is dispatch fusion, not
          hardware);
      (3) the capture spill ring changes nothing — a byte-budgeted
          ring spills segments (spilled bytes > 0) and its merged
          items build the same artifact as an in-memory dict capture.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as _np

    from ct_mapreduce_tpu.filter import (
        ListGroupSource,
        SpillCaptureRing,
        build_artifact,
        build_artifact_from_sources,
    )
    from ct_mapreduce_tpu.filter import artifact as fartifact
    from tools.filtercost import packed_sources

    n, groups, rate = 40_000, 12, 0.01

    def sources():
        srcs = packed_sources(n, groups, seed=20260805)
        big = [b"\x9c" * 61, b"\x9d" * 72]  # oversized host-lane keys
        small = [bytes([7, j % 251, 3]) for j in range(50)]
        srcs.append(ListGroupSource("scale-smoke-oversized", 777_000,
                                    small + big))
        return srcs

    t0 = time.monotonic()
    art = build_artifact_from_sources(sources(), fp_rate=rate)
    fused_s = time.monotonic() - t0
    stats = fartifact.LAST_BUILD_STATS
    blob = art.to_bytes()
    total = art.n_serials
    if stats is None:
        raise BenchError("filter scale smoke: fused build did not "
                         "record dispatch stats (fused path not taken)")

    # (2) dispatch collapse: the per-group path would issue one
    # scatter per (group, layer).
    if not (stats.dispatches < stats.layers):
        raise BenchError(
            f"filter scale smoke: no dispatch collapse "
            f"({stats.dispatches} dispatches vs {stats.layers} layers)")
    gpd = stats.mean_groups_per_dispatch()
    if gpd <= 2.0:
        raise BenchError(
            f"filter scale smoke: groups/dispatch {gpd:.2f} <= 2")

    # (1) byte identity across every path.
    legacy = build_artifact_from_sources(
        sources(), fp_rate=rate, fused=False).to_bytes()
    if legacy != blob:
        raise BenchError("filter scale smoke: fused != per-group bytes")
    streamed = build_artifact_from_sources(
        sources(), fp_rate=rate, stream_chunk=509,
        fused_lanes=4096).to_bytes()
    if streamed != blob:
        raise BenchError("filter scale smoke: streamed != fused bytes")
    host = build_artifact_from_sources(
        sources(), fp_rate=rate, use_device=False).to_bytes()
    if host != blob:
        raise BenchError("filter scale smoke: NumPy lane != device "
                         "bytes")

    # (3) spill ring parity: tiny byte budget forces real segment
    # spills; merged items == the dict capture's content.
    import tempfile as _tempfile

    rng = _np.random.default_rng(99)
    spill_dir = _tempfile.mkdtemp(prefix="ct-filter-spill-smoke-")
    ring = SpillCaptureRing(spill_dir, mem_bytes=4096)
    plain: dict = {}
    for j in range(3000):
        key = (int(rng.integers(0, 3)), 600_000 + int(rng.integers(0, 2)))
        sb = rng.integers(0, 256, 12, dtype=_np.uint8).tobytes()
        ring.add(key, sb)
        plain.setdefault(key, set()).add(sb)
    if not ring.spilled_bytes:
        raise BenchError("filter scale smoke: spill ring never spilled")
    ring_state = {(f"spill-{idx}", eh): serials
                  for (idx, eh), serials in ring.items()}
    dict_state = {(f"spill-{idx}", eh): serials
                  for (idx, eh), serials in sorted(plain.items())}
    if build_artifact(ring_state, fp_rate=rate).to_bytes() != \
            build_artifact(dict_state, fp_rate=rate).to_bytes():
        raise BenchError("filter scale smoke: spilled capture builds "
                         "different bytes than the dict capture")

    rate_sps = total / max(fused_s, 1e-9)
    log(f"filter scale smoke: {total} serials / {len(art.groups)} "
        f"groups -> {len(blob)} B in {fused_s:.2f}s "
        f"({rate_sps:.0f} serials/s); {stats.layers} layers in "
        f"{stats.dispatches} dispatches ({gpd:.1f} groups/dispatch, "
        f"{stats.escalations} escalations); spill ring "
        f"{ring.spilled_bytes} B over {ring.stats()['segments']} segs")
    return {
        "metric": "ct_filter_scale_smoke",
        "value": rate_sps,
        "unit": "serials/s",
        "smoke_fscale_serials": total,
        "smoke_fscale_groups": len(art.groups),
        "smoke_fscale_bytes": len(blob),
        "smoke_fscale_build_s": fused_s,
        "smoke_fscale_layers": stats.layers,
        "smoke_fscale_dispatches": stats.dispatches,
        "smoke_fscale_device_dispatches": stats.device_dispatches,
        "smoke_fscale_groups_per_dispatch": gpd,
        "smoke_fscale_layer_rounds": stats.rounds,
        "smoke_fscale_escalations": stats.escalations,
        "smoke_fscale_byte_identity": 1,
        "smoke_fscale_spilled_bytes": ring.spilled_bytes,
        "smoke_fscale_spill_segments": ring.stats()["segments"],
    }


def run_distrib_smoke() -> dict:
    """CT_BENCH_SMOKE distribution leg (round 18): a scaled-down
    client pull storm against a W=2 serving fleet, CPU-only — the
    tier-1 gate for ISSUE 13's acceptance:

      (1) FLEET PARITY — both workers serve byte-identical full
          artifacts AND byte-identical container encodings over HTTP
          (tools/pullstorm.py raises before the storm otherwise);
      (2) DELTA EXACTNESS — sampled delta pulls validate against the
          chain manifest and replay to the exact full-artifact bytes
          client-side (a mismatch fails the storm);
      (3) TRAFFIC SHAPE — the storm's warm/lagging clients (delta +
          304 traffic) move ≪ the bytes a full-pull fleet would
          (gated at <20% of their counterfactual), 304s really
          happen, and every pull class is exercised;
      (4) the p99 and pulls/s are recorded for BENCHLOG (the 1-core
          box number carries no scaling claim — the structure and
          byte gates carry the leg).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tools import pullstorm

    report = pullstorm.run_storm(
        clients=600, epochs=4, groups=24, per_group=30, churn=2,
        workers=2, threads=12, validate_every=10)
    if report["worker_parity"] != 1:
        raise BenchError("distrib smoke: worker parity not verified")
    pulls = report["pulls"]
    for kind in ("304", "delta", "full"):
        if pulls.get(kind, {}).get("count", 0) <= 0:
            raise BenchError(
                f"distrib smoke: pull class {kind} never exercised "
                f"({pulls})")
    if report["ratio_304"] <= 0.1:
        raise BenchError(
            f"distrib smoke: 304 ratio {report['ratio_304']} — warm "
            f"clients are not revalidating")
    if report["delta_304_vs_full"] >= 0.20:
        raise BenchError(
            f"distrib smoke: delta+304 traffic is "
            f"{report['delta_304_vs_full']:.2%} of the full-pull "
            f"counterfactual — not ≪")
    if report["p99_ms"] <= 0 or report["pulls_per_s"] <= 0:
        raise BenchError("distrib smoke: latency/throughput not "
                         "measured")
    log(f"distrib smoke: {report['clients']} pulls over "
        f"{report['workers']} workers -> "
        f"{report['bytes_on_wire']} B on wire "
        f"({report['wire_vs_counterfactual']:.1%} of full-pull), "
        f"304 ratio {report['ratio_304']:.2f}, delta+304 at "
        f"{report['delta_304_vs_full']:.1%} of counterfactual, "
        f"p50 {report['p50_ms']}ms p99 {report['p99_ms']}ms, "
        f"{report['pulls_per_s']}/s")
    return {
        "metric": "ct_distrib_smoke",
        "value": report["pulls_per_s"],
        "unit": "pulls/s",
        "smoke_distrib_clients": report["clients"],
        "smoke_distrib_workers": report["workers"],
        "smoke_distrib_parity": report["worker_parity"],
        "smoke_distrib_ratio_304": report["ratio_304"],
        "smoke_distrib_wire_bytes": report["bytes_on_wire"],
        "smoke_distrib_counterfactual_bytes":
            report["counterfactual_full_bytes"],
        "smoke_distrib_wire_vs_counterfactual":
            report["wire_vs_counterfactual"],
        "smoke_distrib_delta_304_vs_full": report["delta_304_vs_full"],
        "smoke_distrib_full_artifact_bytes":
            report["full_artifact_bytes"],
        "smoke_distrib_p50_ms": report["p50_ms"],
        "smoke_distrib_p99_ms": report["p99_ms"],
        "smoke_distrib_pulls": {k: v["count"]
                                for k, v in report["pulls"].items()},
    }


def run_fleet_smoke() -> dict:
    """CT_BENCH_SMOKE fleet leg (round 14): W ∈ {1, 2} local ct-fetch
    worker PROCESSES over a shared fakelog fixture, coordinated
    through miniredis (election + barrier + checkpoint epochs), with:

      (1) parity EXACT: each fleet's merged per-worker aggregate is
          byte-identical (serial counts per (issuer, expDate), CRL/DN
          metadata) to a serial single-process run of the same
          entries;
      (2) partition structure: the rendezvous map is disjoint and
          covering, and under W=2 both workers own work;
      (3) aggregate throughput recorded honestly: on this 1-core CI
          box the W processes share one core, so the aggregate
          entries/s number carries NO scaling claim — the parity +
          structure gates carry it (the rounds-11/12 convention);
          real scaling needs a multi-core/multi-host run.
    """
    import tempfile

    if os.environ.get("CT_TPU_TESTS", "") == "":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tools import fleet as harness

    from ct_mapreduce_tpu.ingest.fleet import partition_map
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    state_dir = tempfile.mkdtemp(prefix="ct-fleet-smoke-")
    fixture_path = os.path.join(state_dir, "fixture.json")
    fixture = harness.build_fixture(
        fixture_path, n_logs=2, entries_per_log=64, dupes=6, max_batch=64)
    urls = list(fixture["logs"])
    total = sum(len(v) for v in fixture["logs"].values())

    # Serial truth, in-process (this interpreter's jax is warm).
    ref = harness.run_serial_reference(fixture, state_dir)
    if ref["total"] <= 0:
        raise BenchError("serial reference ingested nothing")

    # The W=2 partition map must be disjoint+covering with work on
    # both sides before any process spawns.
    owners = partition_map(urls, 2)
    if sorted(owners) != sorted(urls) or set(owners.values()) != {0, 1}:
        raise BenchError(f"degenerate W=2 partition: {owners}")

    results = {}

    # W=1 leg IN-PROCESS: this interpreter IS the single fleet worker
    # (numWorkers=1 over a redis coordinator — the full election/
    # epoch/checkpoint machinery runs, without paying a process spawn
    # + jax import on the 1-core box).
    from ct_mapreduce_tpu.agg.aggregator import HostSnapshotAggregator
    from ct_mapreduce_tpu.cmd import ct_fetch
    from ct_mapreduce_tpu.ingest import ctclient

    import json as _json
    import socket as _socket
    import threading as _threading
    import urllib.request as _urlreq

    redis = MiniRedis().start()
    orig_transport = ctclient._urllib_transport
    try:
        # Throttled small batches pace the W=1 run past a few 150 ms
        # checkpoint epochs, so the /healthz poller below observes the
        # live fleet section (role, membership, partition, epoch).
        paced = harness.FixtureTransport(fixture, throttle_ms=150)
        paced.max_batch = 16
        ctclient._urllib_transport = paced
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        mport = s.getsockname()[1]
        s.close()
        w1_dir = os.path.join(state_dir, "f1-w0")
        os.makedirs(w1_dir, exist_ok=True)
        w1_ini = os.path.join(w1_dir, "worker.ini")
        w1_state = os.path.join(w1_dir, "agg.npz")
        harness.write_worker_ini(
            w1_ini, fixture, w1_state, redis_addr=redis.address,
            checkpoint_period="150ms", coordinator="redis")
        with open(w1_ini, "a") as fh:
            fh.write(f"metricsPort = {mport}\n")
        fleet_bodies = []
        poll_stop = _threading.Event()

        def poll_healthz():
            while not poll_stop.is_set():
                try:
                    with _urlreq.urlopen(
                            f"http://127.0.0.1:{mport}/healthz",
                            timeout=1) as resp:
                        body = _json.loads(resp.read())
                    if "fleet" in body:
                        fleet_bodies.append(body["fleet"])
                except Exception:
                    pass
                time.sleep(0.05)

        poller = _threading.Thread(target=poll_healthz, daemon=True)
        poller.start()
        t0 = time.monotonic()
        rc = ct_fetch.main(["-config", w1_ini, "-nobars"])
        wall = time.monotonic() - t0
        poll_stop.set()
        poller.join(5)
    finally:
        ctclient._urllib_transport = orig_transport
        redis.stop()
    if rc != 0:
        raise BenchError(f"fleet W=1 worker rc={rc}")
    agg1 = HostSnapshotAggregator(capacity=1 << 10)
    agg1.load_checkpoint(w1_state)
    if harness.snapshot_jsonable(agg1.drain()) != ref:
        raise BenchError("fleet W=1 aggregate diverged from serial run")
    # The /healthz fleet section served live: worker role, full
    # membership, the rendezvous partition map, and >=1 leader-
    # published checkpoint epoch observed mid-run.
    if not fleet_bodies:
        raise BenchError("no /healthz body carried the fleet section")
    last_fleet = fleet_bodies[-1]
    if last_fleet["role"] != "leader" or last_fleet["workers_alive"] != [0]:
        raise BenchError(f"W=1 fleet healthz wrong: {last_fleet}")
    part = next((f["partition"] for f in fleet_bodies if f["partition"]),
                None)
    if part is None or set(part) != set(urls) or set(part.values()) != {0}:
        raise BenchError(f"W=1 partition map not surfaced: {part}")
    if not any(f.get("checkpoint_epoch", 0) >= 1 for f in fleet_bodies):
        raise BenchError("no checkpoint epoch observed in /healthz")
    results[1] = {"wall_s": wall, "entries_per_s": total / wall,
                  "healthz_epoch": max(f.get("checkpoint_epoch", 0)
                                       for f in fleet_bodies)}
    log(f"fleet smoke W=1: parity exact, healthz fleet section live "
        f"(epoch {results[1]['healthz_epoch']}), "
        f"{total / wall:,.0f} entries/s (in-process, wall {wall:.1f}s)")

    # W=2 leg: two real worker PROCESSES over miniredis.
    redis = MiniRedis().start()
    try:
        t0 = time.monotonic()
        procs = [
            harness.spawn_worker(
                w, 2, fixture_path,
                os.path.join(state_dir, f"f2-w{w}"),
                redis.address, checkpoint_period="500ms",
                coordinator="redis")
            for w in range(2)
        ]
        outs = [p.communicate(timeout=420)[0] for p in procs]
        wall = time.monotonic() - t0
    finally:
        redis.stop()
    for w, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise BenchError(
                f"fleet W=2 worker {w} rc={p.returncode}: {out[-1500:]}")
    dones = [next(e for e in harness.child_events(out)
                  if e["event"] == "done") for out in outs]
    owned = {d["worker"]: d["owned_logs"] for d in dones}
    flat = [u for logs in owned.values() for u in logs]
    if sorted(flat) != sorted(urls):
        raise BenchError(f"W=2 partition not disjoint+covering: {owned}")
    if not all(owned.values()):
        raise BenchError(f"W=2 worker with empty partition: {owned}")
    merged = harness.merged_snapshot([d["state_path"] for d in dones])
    if merged != ref:
        raise BenchError(
            f"fleet W=2 merged aggregate diverged from the serial run: "
            f"merged {merged['total']} vs ref {ref['total']}")
    results[2] = {"wall_s": wall, "entries_per_s": total / wall}
    log(f"fleet smoke W=2: parity exact, {total / wall:,.0f} entries/s "
        f"aggregate (wall {wall:.1f}s, 1-core box — no scaling claim)")

    return {
        "metric": "ct_fleet_smoke",
        "value": results[2]["entries_per_s"],
        "unit": "entries/s",
        "smoke_fleet_entries": total,
        "smoke_fleet_parity": 1,
        "smoke_fleet_w1_wall_s": results[1]["wall_s"],
        "smoke_fleet_w2_wall_s": results[2]["wall_s"],
        "smoke_fleet_w1_entries_per_s": results[1]["entries_per_s"],
        "smoke_fleet_w2_entries_per_s": results[2]["entries_per_s"],
        "smoke_fleet_healthz_epoch": results[1]["healthz_epoch"],
        "smoke_fleet_ref_total": ref["total"],
    }


def run_ckpt_smoke() -> dict:
    """CT_BENCH_SMOKE checkpoint leg (round 22): the incremental-
    checkpoint plane (CTMRCK02, agg/ckpt.py) at a CPU-box scale —
    structure and parity gates carried in full, the 10⁷-scale ≥5×
    headline lives in the stagecost run recorded in BENCHLOG:

      (1) O(churn) TICK: after a base anchor, a 1%-churn epoch tick
          must save ≥5× faster than the full ck01 save of the same
          fixture (the real margin is far larger; 5× keeps the gate
          honest on noisy CI boxes);
      (2) RESTORE PARITY EXACT: base + chain replay digests
          (tune.harness.ckpt_state_digest) identical to the live
          writer AND to a ck01 oracle save of the same state;
      (3) CHAIN BOUNDED: ckptMaxChain segments force a compaction
          anchor (fresh base, chain reset, stale segments dropped).
    """
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.tune import harness

    entries = int(os.environ.get("CT_BENCH_SMOKE_CKPT_ENTRIES",
                                 "100000"))
    bits = 18
    agg, eh = harness.build_aggregator(entries, bits)
    tmp = tempfile.mkdtemp(prefix="bench-ckpt.")
    try:
        p01 = os.path.join(tmp, "ck01.npz")
        agg.configure_checkpointing(mode="ck01")
        t0 = time.perf_counter()
        agg.save_checkpoint(p01)
        full_s = time.perf_counter() - t0

        p02 = os.path.join(tmp, "ck02.npz")
        agg.configure_checkpointing(mode="ck02", max_chain=2)
        agg.save_checkpoint(p02)  # base anchor
        nch = max(1, entries // 100)
        start = entries
        harness.ckpt_churn(agg, eh, nch, start)
        start += nch
        t0 = time.perf_counter()
        agg.save_checkpoint(p02)
        tick_s = time.perf_counter() - t0
        speedup = full_s / tick_s
        if agg._ckpt_chain_len != 1:
            raise BenchError(
                f"ckpt smoke: 1%-churn tick did not append a segment "
                f"(chain {agg._ckpt_chain_len})")
        if speedup < 5.0:
            raise BenchError(
                f"ckpt smoke: 1%-churn tick {tick_s * 1e3:.1f} ms is "
                f"only {speedup:.1f}x faster than the {full_s * 1e3:.1f}"
                " ms full save (gate: >=5x)")

        # (2) parity: chain restore == live writer == ck01 oracle.
        want = harness.ckpt_state_digest(agg)
        r = TpuAggregator(capacity=1 << bits, batch_size=4096,
                          grow_at=0.0)
        t0 = time.perf_counter()
        r.load_checkpoint(p02)
        restore_s = time.perf_counter() - t0
        if harness.ckpt_state_digest(r) != want:
            raise BenchError("ckpt smoke: chain restore diverged "
                             "from the writer state")
        oracle_p = os.path.join(tmp, "oracle.npz")
        agg.configure_checkpointing(mode="ck01")
        agg.save_checkpoint(oracle_p)
        o = TpuAggregator(capacity=1 << bits, batch_size=4096,
                          grow_at=0.0)
        o.load_checkpoint(oracle_p)
        if harness.ckpt_state_digest(o) != want:
            raise BenchError("ckpt smoke: ck01 oracle restore "
                             "diverged from the writer state")

        # (3) chain bound: maxChain=2 → third tick anchors.
        agg.configure_checkpointing(mode="ck02", max_chain=2)
        anchored = False
        for _ in range(3):
            harness.ckpt_churn(agg, eh, nch, start)
            start += nch
            agg.save_checkpoint(p02)
            if agg._ckpt_chain_len == 0:
                anchored = True
        if not anchored or agg._ckpt_chain_len > 2:
            raise BenchError(
                f"ckpt smoke: chain not bounded by maxChain=2 "
                f"(chain {agg._ckpt_chain_len}, anchored={anchored})")
    except harness.ParityError as err:
        raise BenchError(f"ckpt smoke: {err}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    log(f"ckpt smoke: full {full_s * 1e3:.1f} ms vs 1%-churn tick "
        f"{tick_s * 1e3:.1f} ms ({speedup:.1f}x), restore "
        f"{restore_s * 1e3:.1f} ms, parity exact, chain bounded")
    return {
        "metric": "ct_ckpt_smoke",
        "value": round(speedup, 2),
        "unit": "x_vs_full_save",
        "smoke_ckpt_entries": entries,
        "smoke_ckpt_full_ms": round(full_s * 1e3, 1),
        "smoke_ckpt_tick_ms": round(tick_s * 1e3, 1),
        "smoke_ckpt_restore_ms": round(restore_s * 1e3, 1),
        "smoke_ckpt_parity": 1,
        "smoke_ckpt_chain_bounded": 1,
    }


def run_tune_smoke() -> dict:
    """CT_BENCH_SMOKE autotune leg (round 21): a scaled-down REAL
    sweep through the whole tune pipeline — measurement providers →
    coordinate-descent search → profile emission → the config layer
    actually loading it.

      (1) three providers (staging_e2e, serve_openloop, verify_lanes)
          sweep their smoke grids with real measurements (replays,
          open-loop serving, ECDSA kernels) under a tight rep budget;
      (2) the tuned profile is emitted (fingerprint + provenance) and
          set as the active platformProfile;
      (3) END-TO-END load gate: resolve_staging / resolve_serve /
          resolve_verify — the production resolution paths — must
          return exactly the tuned values (env and explicit layers
          silenced for the check).

    Honesty (the rounds-11/14 convention): on this 1-core CI box the
    per-dispatch toll inverts every K/B curve, so the WINNING POINTS
    carry no performance claim — what this leg gates is the machinery
    (measure → search → emit → resolve) with real measurements, not
    the numbers. The real curves come from tools/campaign.py on a
    device host.
    """
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")  # a CPU gate by contract

    from ct_mapreduce_tpu.config import profile as platprofile
    from ct_mapreduce_tpu.tune import emit as temit
    from ct_mapreduce_tpu.tune import measure as tmeasure
    from ct_mapreduce_tpu.tune import search as tsearch

    t_all = time.perf_counter()
    # (provider, reps split): staging replays are the heavy evals, one
    # rep each; verify/serve get a 2-rep confirm.
    plan = (("staging_e2e", (1, 1)), ("serve_openloop", (1, 1)),
            ("verify_lanes", (1, 2)))
    results = []
    stats = {}
    for name, reps in plan:
        m = tmeasure.get_measurement(name)
        sr = tsearch.coordinate_descent(
            m.grid("smoke"), m.evaluator("smoke"), maximize=m.maximize,
            seed=0, budget_evals=12, reps=reps, sweeps=1)
        if not sr.evaluations:
            raise BenchError(f"tune smoke {name}: no evaluations ran")
        if sr.best_value != sr.best_value:  # NaN
            raise BenchError(f"tune smoke {name}: no feasible point "
                             f"confirmed (best {sr.best})")
        if not all(c for c in sr.curves.values()):
            raise BenchError(f"tune smoke {name}: empty provenance "
                             f"curve: {sr.curves}")
        log(f"tune smoke {name}: best {sr.best} -> "
            f"{sr.best_value:,.1f} {m.unit} "
            f"({len(sr.evaluations)} evals, {sr.wall_s:.1f}s)")
        results.append((m, sr))
        stats[name] = {"best": dict(sr.best),
                       "best_value": sr.best_value,
                       "evals": len(sr.evaluations),
                       "wall_s": round(sr.wall_s, 2)}

    profile = temit.build_profile(results, platform="smoke-cpu")
    for section in ("staging", "serve", "verify"):
        if not profile["knobs"].get(section):
            raise BenchError(f"tune smoke: emitted profile has no "
                             f"knobs.{section}")
        if not profile["provenance"].get(section):
            raise BenchError(f"tune smoke: no provenance.{section}")
    path = temit.write_profile(
        os.path.join(tempfile.mkdtemp(prefix="ct-tune-smoke-"),
                     "tuned_profile.json"), profile)

    # End-to-end: the PRODUCTION resolve paths must see the tuned
    # values through the profile layer alone.
    knobs = profile["knobs"]
    silenced = ("CTMR_PLATFORM_PROFILE", "CTMR_CHUNKS_PER_DISPATCH",
                "CTMR_STAGING_DEPTH", "CTMR_SERVE_REPLICAS",
                "CTMR_VERIFY_BATCH", "CTMR_VERIFY_PRECOMP_WINDOW")
    saved = {env: os.environ.pop(env, None) for env in silenced}
    os.environ["CTMR_PLATFORM_PROFILE"] = path
    platprofile.invalidate_cache()
    try:
        from ct_mapreduce_tpu.ingest.sync import resolve_staging
        from ct_mapreduce_tpu.serve.server import resolve_serve
        from ct_mapreduce_tpu.verify.lane import resolve_verify

        k, depth = resolve_staging()
        want = (knobs["staging"]["chunksPerDispatch"],
                knobs["staging"]["stagingDepth"])
        if (k, depth) != want:
            raise BenchError(f"tune smoke: resolve_staging returned "
                             f"{(k, depth)}, profile says {want}")
        replicas, _device, _cache = resolve_serve()
        if replicas != knobs["serve"]["serveReplicas"]:
            raise BenchError(
                f"tune smoke: resolve_serve replicas {replicas}, "
                f"profile says {knobs['serve']['serveReplicas']}")
        _flag, _keys, batch, window, _q = resolve_verify()
        want_v = (knobs["verify"]["verifyBatch"],
                  knobs["verify"]["verifyPrecompWindow"])
        if (batch, window) != want_v:
            raise BenchError(f"tune smoke: resolve_verify returned "
                             f"{(batch, window)}, profile says {want_v}")
    finally:
        os.environ.pop("CTMR_PLATFORM_PROFILE", None)
        for env, v in saved.items():
            if v is not None:
                os.environ[env] = v
        platprofile.invalidate_cache()
    log(f"tune smoke: profile {path} loaded end-to-end "
        f"(staging {knobs['staging']}, serve {knobs['serve']}, "
        f"verify {knobs['verify']})")

    return {
        "metric": "ct_tune_smoke",
        "value": stats["staging_e2e"]["best_value"],
        "unit": "entries/s",
        "smoke_tune_profile_path": path,
        "smoke_tune_knobs": knobs,
        "smoke_tune_sweeps": stats,
        "smoke_tune_loaded": 1,
        "smoke_tune_wall_s": round(time.perf_counter() - t_all, 2),
    }


def run_obs_smoke() -> dict:
    """CT_BENCH_SMOKE observability leg (round 23): the fleet-wide
    observability plane driven LIVE over a W=2 worker fleet
    (tools/fleet.py worker processes, miniredis fabric, runForever):

      (1) cross-process trace correlation: ct-query requests from
          THIS process mint traceparent headers; the serving worker's
          spans carry the same trace_id, and fleetobs.merge_traces
          (the traceview --merge engine) stitches client + both
          worker trace exports into ONE timeline with per-worker
          tracks;
      (2) metrics fan-in parity EXACT: within one /metrics/fleet
          body every unlabeled fleet-summed counter equals the sum of
          its {worker=...} lines (fleet_counter_parity), and — once
          ingest quiesces — the fleet total of the insert counter
          equals the sum of live per-worker /metrics scrapes;
      (3) liveness -> health rollup: SIGSTOP'ing worker 1 flips
          worker 0's /healthz/fleet to 503 within the (shrunk)
          heartbeat-TTL'd liveness window; SIGCONT recovers it;
      (4) overhead gated HONESTLY (rounds-11/14 convention): raw
          walls on this 1-core box carry no timing claim; the gate is
          the MODELED obs cost — measured per-span emission cost x
          spans recorded + per-publish payload cost x fan-in
          publishes — under 2% of the workers' wall.
    """
    import json as _json
    import re as _re
    import signal as _signal
    import socket as _socket
    import tempfile
    import urllib.error as _urlerr
    import urllib.request as _urlreq

    if os.environ.get("CT_TPU_TESTS", "") == "":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tools import fleet as harness

    from ct_mapreduce_tpu.ingest.fleet import partition_map
    from ct_mapreduce_tpu.serve.client import QueryClient
    from ct_mapreduce_tpu.telemetry import fleetobs, trace
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    state_dir = tempfile.mkdtemp(prefix="ct-obs-smoke-")
    fixture_path = os.path.join(state_dir, "fixture.json")
    fixture = harness.build_fixture(
        fixture_path, n_logs=2, entries_per_log=48, dupes=4, max_batch=32)
    urls = list(fixture["logs"])
    owners = partition_map(urls, 2)
    if sorted(owners) != sorted(urls) or set(owners.values()) != {0, 1}:
        raise BenchError(f"degenerate W=2 partition: {owners}")

    def free_port() -> int:
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def http_get(url: str, timeout: float = 3.0) -> tuple[int, str]:
        try:
            with _urlreq.urlopen(url, timeout=timeout) as resp:
                return resp.getcode(), resp.read().decode()
        except _urlerr.HTTPError as err:
            try:
                return err.code, err.read().decode()
            except OSError:
                return err.code, ""
        except (OSError, _urlerr.URLError):
            return -1, ""

    def counter_of(body: str, name: str) -> float:
        m = _re.search(rf"(?m)^{_re.escape(name)} ([0-9eE.+-]+)$", body)
        return float(m.group(1)) if m else -1.0

    mports = [free_port(), free_port()]
    qport = free_port()
    trace_paths = [os.path.join(state_dir, f"w{w}-trace.json")
                   for w in range(2)]
    # Heartbeats fire every 2s (FleetService default); 4s liveness
    # keeps one full missed beat of slack against 1-core scheduling
    # jitter while the SIGSTOP flip still lands in seconds.
    liveness_s = 4.0
    fleet_url = f"http://127.0.0.1:{mports[0]}/healthz/fleet"
    insert_key = "ct_fetch_insertCertificate"

    if trace.enabled():  # a prior leg's tracer must not leak in
        trace.disable()

    redis = MiniRedis().start()
    procs: list = []
    try:
        t0 = time.monotonic()
        procs = [
            harness.spawn_worker(
                w, 2, fixture_path, os.path.join(state_dir, f"obs-w{w}"),
                redis.address, checkpoint_period="500ms",
                coordinator="redis", run_forever=True,
                query_port=(qport if w == 0 else 0),
                trace_path=trace_paths[w], metrics_port=mports[w],
                # Generous thresholds: the SLO rule layer runs (slo.*
                # gauges ride every payload) without breaching.
                ini_lines=("sloMaxIngestLag = 1000000",
                           "sloMaxServeP99Ms = 60000"),
                extra_env={"CTMR_FLEET_LIVENESS_S": str(liveness_s)})
            for w in range(2)
        ]

        def alive_or_raise():
            for w, p in enumerate(procs):
                if p.poll() is not None:
                    out = p.communicate()[0]
                    raise BenchError(
                        f"obs worker {w} died rc={p.returncode}: "
                        f"{out[-1500:]}")

        # (a) both per-worker metrics planes answer
        deadline = time.monotonic() + 300
        ready = [False, False]
        while not all(ready):
            if time.monotonic() > deadline:
                raise BenchError(f"workers not serving /healthz: {ready}")
            alive_or_raise()
            for w in range(2):
                if not ready[w]:
                    st, _ = http_get(
                        f"http://127.0.0.1:{mports[w]}/healthz")
                    ready[w] = st in (200, 503)
            time.sleep(0.25)

        # (b) the rollup reports the whole fleet healthy
        rollup = None
        while rollup is None:
            if time.monotonic() > deadline:
                raise BenchError("fleet rollup never became healthy")
            alive_or_raise()
            st, raw = http_get(fleet_url)
            if st == 200:
                body = _json.loads(raw)
                if (body.get("healthy")
                        and body.get("workers_reporting") == 2):
                    rollup = body
            time.sleep(0.25)
        if rollup["missing"] or rollup["leader_epoch_skew"] > 1:
            raise BenchError(f"inconsistent healthy rollup: {rollup}")
        roles = [e["role"] for e in rollup["workers"].values()]
        if "leader" not in roles:
            raise BenchError(f"no leader in the rollup: {roles}")

        # (c) ingest quiesces: fleet-summed insert counter == sum of
        # live per-worker scrapes (cross-scrape parity), and in-body
        # counter parity is exact on the same scrape.
        fleet_metrics_url = f"http://127.0.0.1:{mports[0]}/metrics/fleet"
        cross = None
        cross_deadline = time.monotonic() + 180
        while cross is None:
            if time.monotonic() > cross_deadline:
                raise BenchError(
                    "fleet/live insert-counter parity never converged")
            alive_or_raise()
            live = [counter_of(
                http_get(f"http://127.0.0.1:{p}/metrics")[1], insert_key)
                for p in mports]
            st, mf_body = http_get(fleet_metrics_url)
            total = counter_of(mf_body, insert_key)
            if st == 200 and min(live) > 0 and total == sum(live):
                cross = {"live": live, "total": total, "body": mf_body}
            else:
                time.sleep(0.5)
        mf_body = cross["body"]
        bad = fleetobs.fleet_counter_parity(mf_body)
        if bad:
            raise BenchError(f"/metrics/fleet counter parity broken: {bad}")
        for w in range(2):
            if f'{insert_key}{{worker="{w}"}}' not in mf_body:
                raise BenchError(f"no worker-{w} series in /metrics/fleet")
            if f'slo_degraded{{worker="{w}"}}' not in mf_body:
                raise BenchError(f"worker {w} published no slo.* gauges")
        n_counters = len(_re.findall(r"(?m)^# TYPE \S+ counter$", mf_body))
        log(f"obs smoke: fan-in parity exact over {n_counters} counters "
            f"({insert_key} fleet {cross['total']:.0f} == live "
            f"{cross['live']})")

        # (d) cross-process trace correlation: ct-query requests from
        # THIS process against worker 0's query plane.
        trace.enable(os.path.join(state_dir, "client-trace.json"))
        qdeadline = time.monotonic() + 60
        while True:
            st, _ = http_get(f"http://127.0.0.1:{qport}/healthz")
            if st == 200:
                break
            if time.monotonic() > qdeadline:
                raise BenchError("query plane never served /healthz")
            alive_or_raise()
            time.sleep(0.25)
        client = QueryClient(f":{qport}", timeout_s=10.0)
        n_queries = 4
        for i in range(n_queries):
            res = client.query_one(
                "obs-smoke-issuer", "2031-06-15", f"0bad{i:04x}")
            if "results" not in res:
                raise BenchError(f"query {i} malformed answer: {res}")
        client_doc_path = trace.export()
        trace.disable()
        with open(client_doc_path) as fh:
            client_doc = _json.load(fh)

        # fan-in publish counts for the overhead model, scraped live
        # before the shutdown tears the servers down
        publishes = sum(
            max(0.0, counter_of(
                http_get(f"http://127.0.0.1:{p}/metrics")[1],
                "fleet_obs_publishes"))
            for p in mports)

        # (e) SIGSTOP worker 1 -> worker 0's rollup flips 503 within
        # the liveness TTL; SIGCONT recovers it.
        os.kill(procs[1].pid, _signal.SIGSTOP)
        t_stop = time.monotonic()
        flip_s = None
        flip_body: dict = {}
        while time.monotonic() - t_stop < liveness_s * 4:
            st, raw = http_get(fleet_url)
            if st == 503:
                flip_s = time.monotonic() - t_stop
                flip_body = _json.loads(raw) if raw else {}
                break
            time.sleep(0.1)
        os.kill(procs[1].pid, _signal.SIGCONT)
        if flip_s is None:
            raise BenchError(f"SIGSTOP'd worker never degraded the "
                             f"rollup (TTL {liveness_s}s)")
        if flip_s > liveness_s + 1.5:
            raise BenchError(
                f"rollup flipped in {flip_s:.1f}s — past the "
                f"{liveness_s}s TTL (+1.5s scrape slack)")
        reasons = flip_body.get("degraded", [])
        if not any("worker 1" in r for r in reasons):
            raise BenchError(f"degradation blames nobody: {reasons}")
        recovered = None
        rec_deadline = time.monotonic() + 90
        while recovered is None:
            if time.monotonic() > rec_deadline:
                raise BenchError("rollup never recovered after SIGCONT")
            st, raw = http_get(fleet_url)
            if st == 200 and _json.loads(raw).get("healthy"):
                recovered = time.monotonic() - t_stop
            time.sleep(0.25)
        log(f"obs smoke: SIGSTOP->503 in {flip_s:.2f}s "
            f"(TTL {liveness_s}s), recovered {recovered:.1f}s after")

        # (f) clean shutdown -> each worker exports its trace ring
        for p in procs:
            os.kill(p.pid, _signal.SIGTERM)
        outs = [p.communicate(timeout=180)[0] for p in procs]
        wall = time.monotonic() - t0
    finally:
        if trace.enabled():
            trace.disable()
        for p in procs:
            if p.poll() is None:
                try:
                    os.kill(p.pid, _signal.SIGCONT)
                except OSError:
                    pass
                p.kill()
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass
        redis.stop()

    for w, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise BenchError(
                f"obs worker {w} rc={p.returncode}: {out[-1500:]}")
    dones = [next(e for e in harness.child_events(out)
                  if e["event"] == "done") for out in outs]
    worker_wall = sum(d["wall_s"] for d in dones)

    docs = []
    for w in range(2):
        if not os.path.exists(trace_paths[w]):
            raise BenchError(f"worker {w} exported no trace")
        with open(trace_paths[w]) as fh:
            docs.append(_json.load(fh))

    merged = fleetobs.merge_traces([client_doc] + docs)
    events = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    pids = {e.get("pid") for e in events}
    if len(pids) < 3:
        raise BenchError(f"merged timeline spans {len(pids)} pids "
                         f"(want client + 2 workers)")
    labels = {e["args"]["name"]
              for e in merged["traceEvents"]
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    for want in ("worker 0 (", "worker 1 ("):
        if not any(lab.startswith(want) for lab in labels):
            raise BenchError(f"no '{want}...' track in the merge: "
                             f"{labels}")
    my_pid = os.getpid()
    minted = {e["args"]["trace_id"]
              for e in client_doc.get("traceEvents", [])
              if e.get("name") == "query.client"
              and "trace_id" in e.get("args", {})}
    if len(minted) != n_queries:
        raise BenchError(f"client minted {len(minted)} trace ids for "
                         f"{n_queries} queries")
    correlated = {
        tid for tid in minted
        if any(e.get("args", {}).get("trace_id") == tid
               and e.get("pid") != my_pid for e in events)}
    if not correlated:
        raise BenchError("no client trace_id reached a worker span — "
                         "the traceparent header did not propagate")
    log(f"obs smoke: merged timeline over {len(pids)} processes, "
        f"{len(correlated)}/{n_queries} request trace ids correlated "
        f"across the process boundary")

    # (g) overhead, modeled (rounds-11/14 honesty convention): the
    # 1-core walls carry no timing claim; the model multiplies the
    # MEASURED per-event costs (span emission on a live ring, payload
    # build over this process's real sink) by the counts this leg
    # actually recorded.
    tr = trace.SpanTracer(path=None, ring_size=4096)
    n_bench = 20000
    t_b = time.perf_counter()
    for _ in range(n_bench):
        with tr.span("serve.wait", "bench"):
            pass
    per_span_s = (time.perf_counter() - t_b) / n_bench
    n_pub = 200
    t_b = time.perf_counter()
    for _ in range(n_pub):
        fleetobs.build_obs_payload(0, 2, fleet_stats={"role": "leader"},
                                   slo={"values": {}, "degraded": []})
    per_pub_s = (time.perf_counter() - t_b) / n_pub
    spans = sum(1 for doc in docs for e in doc.get("traceEvents", [])
                if e.get("ph") in ("X", "i"))
    if spans <= 0:
        raise BenchError("workers recorded no spans")
    if publishes <= 0:
        raise BenchError("no fan-in publishes counted")
    modeled_s = spans * per_span_s + publishes * per_pub_s
    overhead_pct = 100.0 * modeled_s / max(worker_wall, 1e-9)
    if overhead_pct >= 2.0:
        raise BenchError(
            f"modeled obs overhead {overhead_pct:.3f}% >= 2% "
            f"({spans} spans x {per_span_s * 1e6:.1f}us + "
            f"{publishes:.0f} publishes x {per_pub_s * 1e6:.0f}us over "
            f"{worker_wall:.1f}s)")
    log(f"obs smoke: modeled overhead {overhead_pct:.3f}% of "
        f"{worker_wall:.1f}s worker wall ({spans} spans @ "
        f"{per_span_s * 1e6:.1f}us, {publishes:.0f} publishes @ "
        f"{per_pub_s * 1e6:.0f}us)")

    return {
        "metric": "ct_obs_smoke",
        "value": float(len(events)),
        "unit": "events",
        "smoke_obs_workers": 2,
        "smoke_obs_merged_events": len(events),
        "smoke_obs_merged_pids": len(pids),
        "smoke_obs_trace_ids": n_queries,
        "smoke_obs_correlated": len(correlated),
        "smoke_obs_parity": 1,
        "smoke_obs_parity_counters": n_counters,
        "smoke_obs_cross_scrape_parity": 1,
        "smoke_obs_insert_total": cross["total"],
        "smoke_obs_liveness_s": liveness_s,
        "smoke_obs_flip_s": round(flip_s, 3),
        "smoke_obs_recover_s": round(recovered, 3),
        "smoke_obs_spans": spans,
        "smoke_obs_publishes": publishes,
        "smoke_obs_per_span_us": round(per_span_s * 1e6, 3),
        "smoke_obs_per_publish_us": round(per_pub_s * 1e6, 2),
        "smoke_obs_overhead_pct": round(overhead_pct, 4),
        "smoke_obs_wall_s": round(wall, 2),
        "smoke_obs_worker_wall_s": round(worker_wall, 2),
    }


def smoke_main() -> int:
    try:
        payload = run_smoke()
    except Exception as err:
        msg = f"{type(err).__name__}: {err}"
        emit({"metric": "ct_e2e_smoke", "value": 0, "unit": "entries/s",
              "error": msg[:500]})
        log(msg)
        return 1
    emit(payload)
    return 0


def launcher() -> int:
    """Scoreboard insurance: run the real bench as a CHILD process and
    guarantee stdout carries one JSON line even if the child dies
    without a word.

    Observed once on this stack (2026-07-31): a bench run vanished
    mid-e2e — no exception, no watchdog message, no OOM-kill record —
    after the headline rate was measured and logged to stderr but
    before the JSON line printed. An in-process defense cannot survive
    a SIGKILL-class death, so this tiny parent (no jax import, not a
    plausible kill target) relays the child's stderr, remembers the
    last heartbeat rate, and emits a partial-rate JSON itself if the
    child exits silently.
    """
    import re
    import subprocess

    env = dict(os.environ, CT_BENCH_INNER="1")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=env, text=True, bufsize=1,
    )
    state = {"rate": 0.0, "processed": 0, "elapsed": 0.0}
    rate_re = re.compile(
        r"chunk \d+: (\d+) entries in ([\d.]+)s cumulative ([\d,]+) ")

    def pump_stderr():
        for line in proc.stderr:
            sys.stderr.write(line)
            sys.stderr.flush()
            m = rate_re.search(line)
            if m:
                state["processed"] = int(m.group(1))
                state["elapsed"] = float(m.group(2))
                state["rate"] = float(m.group(3).replace(",", ""))

    t = threading.Thread(target=pump_stderr, daemon=True)
    t.start()
    out = proc.stdout.read()
    rc = proc.wait()
    t.join(timeout=5)
    json_line = next(
        (ln for ln in out.splitlines() if ln.startswith("{")), None)
    if json_line is not None:
        print(json_line, flush=True)
        return rc
    # Child died without emitting: surface the partial measured rate
    # (never a bare 0 once a chunk completed), like the watchdog does.
    if state["rate"] > 0:
        emit({
            "metric": "ct_entries_per_sec_per_chip",
            "value": state["rate"],
            "unit": "entries/s/chip",
            "vs_baseline": round(state["rate"] / 10_000_000, 4),
            "error": (
                f"partial: bench child exited rc={rc} without emitting "
                f"({state['processed']} entries in {state['elapsed']:.1f}s)"),
        })
    else:
        emit_error(f"bench child exited rc={rc} before any measurement")
    return 1


if __name__ == "__main__":
    if os.environ.get("CT_BENCH_SMOKE") == "1":
        # The CPU smoke gate replaces the hardware bench entirely: no
        # launcher child, no watchdog — it must finish in well under a
        # minute or fail loudly.
        sys.exit(smoke_main())
    if os.environ.get("CT_BENCH_INNER") != "1":
        sys.exit(launcher())
    # Whatever happens, stdout carries exactly one JSON line: a real
    # metric on success, a structured {"error": ...} on failure — never
    # a bare traceback (round 1's rc=1 left the driver nothing to parse).
    try:
        rc = main()
    except SystemExit:
        raise
    except Exception as err:
        msg = f"{type(err).__name__}: {err}"
        emit_error(msg)
        log(msg)
        # A hung backend-init thread must not block interpreter exit.
        sys.stderr.flush()
        os._exit(1)
    sys.exit(rc)
