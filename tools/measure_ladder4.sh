#!/bin/bash
# Round-4 master ladder, VALUE-ORDERED: after three pool-outage rounds,
# assume any hardware window may close early — measure the round's
# highest-stakes numbers first so a short window still decides the
# roofline. Order:
#   1. bench.py default (2^20 lanes, fused rows, full e2e + parity)
#        — the headline + the driver-shaped number + e2e breakdown
#   2. microbench 2^20 — per-stage costs of the reworked walker
#        (decides the 8.3M/s prediction in ARCHITECTURE.md)
#   3. bench 2^22 / 2^21 — batch-width sweep
#   4. load_sweep — insert throughput at 10/25/50/75% table load
#   5. CT_TPU_TESTS hardware tier (5 tests)
#   6. secondary probes: insert_sweep, opcost, sha_sweep, mosaic_probe
# Never SIGTERM a mid-claim python process; claims error on their own
# (~25 min during an outage).
#
#   nohup tools/measure_ladder4.sh >/dev/null 2>&1 &
#   tail -f /tmp/tpu_session4.log
cd "$(dirname "$0")/.."
log=${CT_LADDER4_LOG:-/tmp/tpu_session4.log}
echo "=== ladder4 start $(date) ===" >> "$log"
while true; do
  python tools/probe_pool.py >> "$log" 2>&1
  if [ $? -eq 0 ]; then break; fi
  echo "--- still down $(date) ---" >> "$log"
  sleep 45
done
echo "=== pool up $(date); running value-ordered ladder ===" >> "$log"

echo "--- [1] bench default (2^20 fused rows, full e2e) ---" >> "$log"
CT_BENCH_WATCHDOG_SECS=520 timeout 1200 python bench.py >> "$log" 2>&1
echo "--- [2] stagecost 1048576 (trusted per-stage) ---" >> "$log"
timeout 2400 python tools/stagecost.py 1048576 >> "$log" 2>&1
echo "--- [2b] randacc (trusted primitive prices) ---" >> "$log"
timeout 2400 python tools/randacc.py >> "$log" 2>&1
echo "--- [3a] bench 2^22 lanes ---" >> "$log"
CT_BENCH_BATCH=4194304 CT_BENCH_WATCHDOG_SECS=520 CT_BENCH_E2E=0 \
  timeout 1200 python bench.py >> "$log" 2>&1
echo "--- [3b] bench 2^21 lanes ---" >> "$log"
CT_BENCH_BATCH=2097152 CT_BENCH_WATCHDOG_SECS=520 CT_BENCH_E2E=0 \
  timeout 1200 python bench.py >> "$log" 2>&1
echo "--- [4] load_sweep 24 ---" >> "$log"
timeout 3000 python tools/load_sweep.py 24 0.10 0.25 0.50 0.75 >> "$log" 2>&1
echo "--- [5] hardware test tier ---" >> "$log"
CT_TPU_TESTS=1 timeout 2400 python -m pytest tests/test_tpu_hw.py -v >> "$log" 2>&1
echo "--- [6a] profstep (op-level trace) ---" >> "$log"
timeout 1800 python tools/profstep.py >> "$log" 2>&1
echo "--- [6b] e2eprof ---" >> "$log"
timeout 1800 python tools/e2eprof.py >> "$log" 2>&1
echo "--- [6c] mosaic_probe compiled ---" >> "$log"
timeout 1800 python tools/mosaic_probe.py >> "$log" 2>&1
echo "--- [6d] bench CTMR_TABLE=open (layout comparison) ---" >> "$log"
CTMR_TABLE=open CT_BENCH_WATCHDOG_SECS=520 CT_BENCH_E2E=0 \
  timeout 1200 python bench.py >> "$log" 2>&1
echo "=== ladder4 done $(date) ===" >> "$log"
