"""Per-component device-step microbenchmark on the real chip.

UNRELIABLE ON THIS STACK — kept for history. Timings here rely on
``jax.block_until_ready``, which the tunneled axon backend does not
honor (measured 2026-07-31: a 1 GB parse "in 0.18 ms" = 7x HBM
bandwidth). Use tools/stagecost.py / tools/randacc.py, which time
with bench.py's synchronous-read contract.

Times, at one batch width, the stages of the fused step in isolation:
  h2d     — fixed 64 MB device_put probe (tunnel/PCIe bandwidth;
            batch bytes themselves are synthesized on device)
  parse   — der_kernel.parse_certs (rows pack + TLV walk)
  sha     — fingerprint build + SHA-256 (one 64B block/lane)
  insert  — hashtable.insert (all-fresh worst case)
  fused   — pipeline.ingest_core (optional: CT_MB_FUSED=1)
Each stage prints immediately (unbuffered) so a killed run still
leaves partial results. Run:  python tools/microbench.py [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def timeit(fn, sync, n=3):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        sync(fn())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    import jax
    import jax.numpy as jnp

    # Load-bearing despite looking redundant: the ambient axon
    # sitecustomize imports jax at interpreter start, after which the
    # env var alone no longer selects the platform (verified: without
    # this, JAX_PLATFORMS=cpu still initialized the axon backend).
    # Same workaround as tests/conftest.py and bench.py.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import der_kernel, hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 131072
    pad_len = int(os.environ.get("CT_MB_PADLEN", "1024"))
    cap = 1 << int(os.environ.get("CT_MB_LOG2_CAP", "26"))
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) "
        f"acquired in {time.perf_counter() - t0:.1f}s; batch={batch}")
    sync = jax.block_until_ready

    tpl = syncerts.make_template()
    # Fixed-size H2D probe (64 MB): measures the tunnel/PCIe link
    # without tying transfer size to the batch width under test.
    probe = np.zeros((64 << 20,), np.uint8)
    t0 = time.perf_counter()
    sync(jax.device_put(probe))
    dt = time.perf_counter() - t0
    say(f"h2d 64MB probe: {dt:.2f}s = {64 / dt:.1f} MB/s")
    del probe

    # Batch bytes are synthesized ON DEVICE from the 1KB template
    # (shared with bench.py): a 2^20-lane batch would otherwise ship
    # ~1 GB through the tunnel before measuring anything.
    t0 = time.perf_counter()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    data, length = sync(datas)[0], sync(lens)[0]
    say(f"on-device batch build: {time.perf_counter() - t0:.1f}s")
    issuer_idx = sync(jax.device_put(np.zeros((batch,), np.int32)))
    valid = sync(jax.device_put(np.ones((batch,), bool)))

    def report(name, t):
        say(f"{name:7s} {t * 1e3:9.2f} ms  {batch / t / 1e6:7.2f} M/s")

    parse = jax.jit(der_kernel.parse_certs)
    t0 = time.perf_counter()
    p = sync(parse(data, length))
    say(f"parse compile+run: {time.perf_counter() - t0:.1f}s")
    report("parse", timeit(lambda: parse(data, length), sync))

    rows = der_kernel.pack_rows(data)
    serials, _ = der_kernel.gather_serials_rows(
        rows, p.serial_off, p.serial_len, packing.MAX_SERIAL_BYTES)
    serials = sync(serials)
    fp = jax.jit(pipeline.fingerprints)
    t0 = time.perf_counter()
    f = sync(fp(issuer_idx, p.not_after_hour, serials, p.serial_len))
    say(f"sha compile+run: {time.perf_counter() - t0:.1f}s")
    report("sha", timeit(lambda: fp(issuer_idx, p.not_after_hour, serials,
                                    p.serial_len), sync))

    # Committed device buffers must be jit ARGUMENTS, never closures —
    # a closure over one permanently degrades dispatch on the axon
    # stack (see bench.py's CRITICAL note).
    meta = jnp.zeros((batch,), jnp.uint32)
    ins = jax.jit(hashtable.insert, donate_argnums=(0,))
    stamp = jax.jit(lambda f, e: f.at[:, 3].set(
        f[:, 3] ^ (e.astype(jnp.uint32) << 20)))
    tbl = hashtable.make_table(cap)
    t0 = time.perf_counter()
    tbl, wu, ovf = ins(tbl, stamp(f, jnp.uint32(0)), meta, valid)
    sync(wu)
    say(f"insert compile+run: {time.perf_counter() - t0:.1f}s")
    ts = []
    for e in range(1, 4):
        k = sync(stamp(f, jnp.uint32(e)))
        t0 = time.perf_counter()
        tbl, wu, ovf = ins(tbl, k, meta, valid)
        sync(wu)
        ts.append(time.perf_counter() - t0)
    report("insert", float(np.median(ts)))

    if os.environ.get("CT_MB_FUSED", "0") == "1":
        ecols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)

        def fused(tbl2, d, ln, ii, vd, e):
            eb = jnp.stack([(e >> 24) & 0xFF, (e >> 16) & 0xFF,
                            (e >> 8) & 0xFF, e & 0xFF]).astype(jnp.uint8)
            d = d.at[:, ecols].set(eb[None, :])
            return pipeline.ingest_core(
                tbl2, d, ln, ii, vd,
                jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
                jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0, 2), jnp.int32))

        fused_j = jax.jit(fused, donate_argnums=(0,))
        tbl2 = hashtable.make_table(cap)
        t0 = time.perf_counter()
        tbl2, out = fused_j(tbl2, data, length, issuer_idx, valid,
                            jnp.uint32(100))
        sync(out.was_unknown)
        say(f"fused compile+run: {time.perf_counter() - t0:.1f}s")
        ts = []
        for e in range(101, 104):
            t0 = time.perf_counter()
            tbl2, out = fused_j(tbl2, data, length, issuer_idx, valid,
                                jnp.uint32(e))
            sync(out.was_unknown)
            ts.append(time.perf_counter() - t0)
        report("fused", float(np.median(ts)))


if __name__ == "__main__":
    main()
