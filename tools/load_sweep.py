"""Insert throughput vs table load factor, on hardware.

The r03 manual run showed per-chunk time growing 4.92s → 7.12s as the
table loaded to 36% (docs/bench_r03_manual_run.log:8-10): the headline
rate is a function of load. This sweep measures entries/s at a ladder
of load factors so the grow-at threshold (TpuAggregator.grow_at,
default 0.7) is chosen from data, not folklore.

Method per platform rules (BENCHLOG.md contract): sweeps run inside a
jitted fori_loop (few device executions, each ~CT_SWEEP_EXEC_SECS),
every timed block ends with a synchronous device-value read.

Usage: python tools/load_sweep.py [log2_capacity] [loads...]
Writes one JSON line per load point on stdout.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    # NOT redundant on this stack: the axon sitecustomize imports jax at
    # interpreter start, before the env var can take effect, so CPU
    # selection must go through jax.config (same workaround as bench.py
    # and tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    log2_cap = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    loads = ([float(x) for x in sys.argv[2:]]
             if len(sys.argv) > 2 else [0.10, 0.25, 0.50, 0.75])
    capacity = 1 << log2_cap
    batch = int(os.environ.get("CT_SWEEP_BATCH", str(1 << 17)))
    pad_len = 1024
    exec_target_s = float(os.environ.get("CT_SWEEP_EXEC_SECS", "6.0"))
    timed_sweeps = int(os.environ.get("CT_SWEEP_TIMED", "8"))
    now_hour = 500_000

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind}); "
          f"capacity=2^{log2_cap} batch={batch}", file=sys.stderr)

    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    issuer_idx = jax.device_put(np.zeros((batch,), np.int32))
    valid = jax.device_put(np.ones((batch,), bool))
    epoch_cols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)

    # All device arrays are ARGUMENTS (closure over a committed buffer
    # permanently degrades dispatch on this stack — ARCHITECTURE.md).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_sweeps(table, fresh_acc, epoch_base, n_sweeps,
                   datas, lens, issuer_idx, valid):
        def body(s, carry):
            table, fresh_acc = carry
            e = (epoch_base + s).astype(jnp.uint32)
            eb = jnp.stack(
                [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF,
                 e & 0xFF]).astype(jnp.uint8)
            data = datas[0].at[:, epoch_cols].set(eb[None, :])
            table, out = pipeline.ingest_core(
                table, data, lens[0], issuer_idx, valid,
                jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
                jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0, 2), jnp.int32),
            )
            return table, fresh_acc + out.was_unknown.sum().astype(jnp.int32)

        return jax.lax.fori_loop(0, n_sweeps, body, (table, fresh_acc))

    _fetch = jax.jit(lambda a: a + a.dtype.type(0))

    # The shipping layout (CTMR_TABLE, default bucket) — load curves
    # must describe the table production runs.
    table = pipeline.make_table(capacity)
    capacity = getattr(table, "capacity", capacity)
    fresh = jax.device_put(np.int32(0))

    # Compile + calibrate with one sweep.
    t0 = time.perf_counter()
    table, fresh = run_sweeps(table, fresh, np.uint32(0), np.int32(1),
                              datas, lens, issuer_idx, valid)
    int(_fetch(fresh))
    print(f"compile+warm: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    t0 = time.perf_counter()
    table, fresh = run_sweeps(table, fresh, np.uint32(1), np.int32(1),
                              datas, lens, issuer_idx, valid)
    int(_fetch(fresh))
    per_sweep = max(time.perf_counter() - t0, 1e-4)
    chunk_sweeps = max(1, int(exec_target_s / per_sweep))
    print(f"calibration: {per_sweep * 1e3:.0f} ms/sweep → "
          f"chunk={chunk_sweeps}", file=sys.stderr)

    epoch = 2
    for target in loads:
        want_fill = int(target * capacity)
        # Fill (unmeasured) to the target load in chunked executions.
        prev_fill = -1
        while True:
            fill = int(_fetch(table.count))
            if fill == prev_fill:
                # Probe overflow plateaus the fill below pathological
                # targets; a stalled loop must break, not spin forever.
                print(f"fill stalled at {fill} ({fill / capacity:.0%}) "
                      f"short of {target:.0%}; measuring there",
                      file=sys.stderr)
                break
            prev_fill = fill
            need = (want_fill - fill) // batch
            if need < 1:
                break
            n = min(need, chunk_sweeps)
            table, fresh = run_sweeps(
                table, fresh, np.uint32(epoch), np.int32(n),
                datas, lens, issuer_idx, valid)
            int(_fetch(fresh))
            epoch += n
        fill = int(_fetch(table.count))
        # Timed block at this load: all-fresh inserts, synced read.
        t0 = time.perf_counter()
        done = 0
        while done < timed_sweeps:
            n = min(chunk_sweeps, timed_sweeps - done)
            table, fresh = run_sweeps(
                table, fresh, np.uint32(epoch), np.int32(n),
                datas, lens, issuer_idx, valid)
            int(_fetch(fresh))
            epoch += n
            done += n
        dt = time.perf_counter() - t0
        rate = timed_sweeps * batch / dt
        point = {
            "load": round(fill / capacity, 4),
            "entries_per_sec": round(rate, 1),
            "ms_per_batch": round(1e3 * dt / timed_sweeps, 2),
            "fill": fill,
            "capacity": capacity,
        }
        print(json.dumps(point), flush=True)
        print(f"load {point['load']:.0%}: {rate:,.0f} entries/s",
              file=sys.stderr)

    total = int(_fetch(table.count))
    expect = epoch * batch  # every sweep stamped unique serials
    missed = expect - total
    # At high load some unique inserts probe-overflow instead of
    # landing (production routes those to the exact host lane); they
    # surface here as fill shortfall. Below ~75% load expect ~0.
    print(f"final fill {total}/{expect} stamped; "
          f"{missed} probe-overflow spills", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
