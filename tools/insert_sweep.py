"""Probe-width x batch-width sweep of the dedup insert on real TPU.

UNRELIABLE ON THIS STACK — kept for history. Timings here rely on
``jax.block_until_ready``, which the tunneled axon backend does not
honor (measured 2026-07-31: a 1 GB parse "in 0.18 ms" = 7x HBM
bandwidth). Use tools/stagecost.py / tools/randacc.py, which time
with bench.py's synchronous-read contract.

For each (PROBE_WIDTH, batch) combination this re-execs itself so the
width (a module-load-time constant) recompiles cleanly, then times
all-fresh inserts exactly like tools/microbench.py. Run with no args
to sweep; results print as one line per combo.

    python tools/insert_sweep.py            # full sweep
    CTMR_PROBE_WIDTH=8 python tools/insert_sweep.py 1048576 --one
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

WIDTHS = (2, 4, 8, 16)
BATCHES = (131072, 1048576)


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_one(batch: int) -> None:
    import jax
    import jax.numpy as jnp

    # Same platform workaround as bench.py (the ambient sitecustomize
    # imports jax before the env var can take effect).
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.ops import hashtable

    cap = 1 << int(os.environ.get("CT_IS_LOG2_CAP", "26"))
    dev = jax.devices()[0]
    sync = jax.block_until_ready
    rng = np.random.RandomState(7)
    fps = rng.randint(0, 2**31, size=(batch, 4)).astype(np.uint32)
    f = sync(jax.device_put(fps))
    meta = jnp.zeros((batch,), jnp.uint32)
    valid = sync(jax.device_put(np.ones((batch,), bool)))

    ins = jax.jit(hashtable.insert, donate_argnums=(0,))
    stamp = jax.jit(lambda f, e: f.at[:, 3].set(
        f[:, 3] ^ (e.astype(jnp.uint32) << 8)))
    tbl = hashtable.make_table(cap)
    t0 = time.perf_counter()
    tbl, wu, ovf = ins(tbl, stamp(f, jnp.uint32(0)), meta, valid)
    sync(wu)
    compile_s = time.perf_counter() - t0
    ts = []
    for e in range(1, 5):
        k = sync(stamp(f, jnp.uint32(e)))
        t0 = time.perf_counter()
        tbl, wu, ovf = ins(tbl, k, meta, valid)
        sync(wu)  # timing matches tools/microbench.py: no extra dispatch
        ts.append(time.perf_counter() - t0)
    n_new = int(wu.sum())  # outside the timed region
    dt = float(np.median(ts))
    say(f"W={hashtable.PROBE_WIDTH:2d} batch={batch:8d} "
        f"cap=2^{cap.bit_length() - 1} [{dev.device_kind}]: "
        f"{dt * 1e3:8.2f} ms  {batch / dt / 1e6:6.2f} M/s "
        f"(compile {compile_s:.0f}s, last fresh={n_new})")


def main() -> None:
    if "--one" in sys.argv:
        batch = int(sys.argv[1])
        run_one(batch)
        return
    for width in WIDTHS:
        for batch in BATCHES:
            env = dict(os.environ, CTMR_PROBE_WIDTH=str(width))
            try:
                subprocess.run(
                    [sys.executable, os.path.abspath(__file__), str(batch),
                     "--one"],
                    env=env, check=False, timeout=600,
                )
            except subprocess.TimeoutExpired:
                say(f"W={width} batch={batch}: timed out; continuing")


if __name__ == "__main__":
    main()
