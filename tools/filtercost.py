"""Filter-compilation cost curves: build rate, bits/entry, and layer
count vs the target false-positive rate (round 15).

Sweeps a synthetic aggregation state (G (issuer, expDate) groups of N
serials each, a disjoint probe corpus for the measured-FP column)
through :func:`ct_mapreduce_tpu.filter.artifact.build_artifact` at a
range of target rates and prints one JSON line per point:

    python tools/filtercost.py --serials 20000 --groups 8 \\
        --rates 0.5,0.1,0.01,0.001 --probes 20000

Columns: build wall + serials/s, artifact bytes, bits/entry (the
compactness headline — crlite's whole point), max cascade depth, the
MEASURED false-positive rate over the disjoint probes (compare to the
target; included serials are exact by construction and verified here
too), and per-query probe cost through the cascade.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def synth_state(n_serials: int, n_groups: int, seed: int = 7,
                serial_bytes: int = 16):
    """{(issuerID, expHour): serial list} + a disjoint probe list.
    Serials are distinct random byte strings; probes never collide
    with them (distinct length ⇒ distinct fingerprint messages)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    per = max(1, n_serials // n_groups)
    state = {}
    for g in range(n_groups):
        key = (f"synth-issuer-{g % max(1, n_groups // 2)}", 500_000 + g)
        serials = [rng.integers(0, 256, serial_bytes,
                                dtype=np.uint8).tobytes()
                   for _ in range(per)]
        state[key] = serials
    return state


def synth_probes(n: int, seed: int = 11, serial_bytes: int = 17):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, serial_bytes, dtype=np.uint8).tobytes()
            for _ in range(n)]


def run_point(state: dict, probes: list, rate: float) -> dict:
    import numpy as np

    from ct_mapreduce_tpu.filter import build_artifact

    n = sum(len(v) for v in state.values())
    t0 = time.perf_counter()
    art = build_artifact(state, fp_rate=rate)
    build_s = time.perf_counter() - t0
    blob = art.to_bytes()

    # Zero-FN verification over the full included set.
    fn = 0
    for (iss, eh), serials in state.items():
        g = art.group_for(iss, eh)
        fn += int((~art.query_group(g, serials)).sum())

    # Measured FP over the disjoint probe corpus, spread across groups.
    fp = probed = 0
    t0 = time.perf_counter()
    for (iss, eh), _ in state.items():
        g = art.group_for(iss, eh)
        hits = art.query_group(g, probes)
        fp += int(np.asarray(hits).sum())
        probed += len(probes)
    probe_s = time.perf_counter() - t0

    return {
        "metric": "ct_filter_cost",
        "fp_rate_target": rate,
        "serials": n,
        "groups": len(art.groups),
        "build_s": round(build_s, 4),
        "serials_per_s": round(n / max(build_s, 1e-9), 1),
        "artifact_bytes": len(blob),
        "bits_per_entry": round(art.bits_per_entry(), 3),
        "max_layers": art.max_layers(),
        "false_negatives": fn,
        "probes": probed,
        "fp_measured": round(fp / max(1, probed), 6),
        "probe_ns": round(1e9 * probe_s / max(1, probed), 1),
    }


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serials", type=int, default=20000)
    ap.add_argument("--groups", type=int, default=8)
    ap.add_argument("--probes", type=int, default=20000)
    ap.add_argument("--rates", default="0.5,0.1,0.01,0.001")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)

    state = synth_state(args.serials, args.groups, seed=args.seed)
    probes = synth_probes(args.probes, seed=args.seed + 4)
    rc = 0
    for rate in (float(r) for r in args.rates.split(",") if r):
        point = run_point(state, probes, rate)
        print(json.dumps(point))
        if point["false_negatives"]:
            print(f"FALSE NEGATIVES at rate {rate}: "
                  f"{point['false_negatives']}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
