"""Filter-compilation cost curves: build rate, bits/entry, and layer
count vs the target false-positive rate (round 15).

Sweeps a synthetic aggregation state (G (issuer, expDate) groups of N
serials each, a disjoint probe corpus for the measured-FP column)
through :func:`ct_mapreduce_tpu.filter.artifact.build_artifact` at a
range of target rates and prints one JSON line per point:

    python tools/filtercost.py --serials 20000 --groups 8 \\
        --rates 0.5,0.1,0.01,0.001 --probes 20000

Columns: build wall + serials/s, artifact bytes, bits/entry (the
compactness headline — crlite's whole point), max cascade depth, the
MEASURED false-positive rate over the disjoint probes (compare to the
target; included serials are exact by construction and verified here
too), and per-query probe cost through the cascade.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def synth_state(n_serials: int, n_groups: int, seed: int = 7,
                serial_bytes: int = 16):
    """{(issuerID, expHour): serial list} + a disjoint probe list.
    Serials are distinct random byte strings; probes never collide
    with them (distinct length ⇒ distinct fingerprint messages)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    per = max(1, n_serials // n_groups)
    state = {}
    for g in range(n_groups):
        key = (f"synth-issuer-{g % max(1, n_groups // 2)}", 500_000 + g)
        serials = [rng.integers(0, 256, serial_bytes,
                                dtype=np.uint8).tobytes()
                   for _ in range(per)]
        state[key] = serials
    return state


def synth_probes(n: int, seed: int = 11, serial_bytes: int = 17):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, serial_bytes, dtype=np.uint8).tobytes()
            for _ in range(n)]


def run_point(state: dict, probes: list, rate: float) -> dict:
    import numpy as np

    from ct_mapreduce_tpu.filter import build_artifact

    n = sum(len(v) for v in state.values())
    t0 = time.perf_counter()
    art = build_artifact(state, fp_rate=rate)
    build_s = time.perf_counter() - t0
    blob = art.to_bytes()

    # Zero-FN verification over the full included set.
    fn = 0
    for (iss, eh), serials in state.items():
        g = art.group_for(iss, eh)
        fn += int((~art.query_group(g, serials)).sum())

    # Measured FP over the disjoint probe corpus, spread across groups.
    fp = probed = 0
    t0 = time.perf_counter()
    for (iss, eh), _ in state.items():
        g = art.group_for(iss, eh)
        hits = art.query_group(g, probes)
        fp += int(np.asarray(hits).sum())
        probed += len(probes)
    probe_s = time.perf_counter() - t0

    return {
        "metric": "ct_filter_cost",
        "fp_rate_target": rate,
        "serials": n,
        "groups": len(art.groups),
        "build_s": round(build_s, 4),
        "serials_per_s": round(n / max(build_s, 1e-9), 1),
        "artifact_bytes": len(blob),
        "bits_per_entry": round(art.bits_per_entry(), 3),
        "max_layers": art.max_layers(),
        "false_negatives": fn,
        "probes": probed,
        "fp_measured": round(fp / max(1, probed), 6),
        "probe_ns": round(1e9 * probe_s / max(1, probed), 1),
    }


def packed_sources(n_serials: int, n_groups: int, seed: int = 7,
                   serial_bytes: int = 16, epoch_extra: int = 0,
                   churn_groups: int = 1):
    """Scale-leg corpora as PackedGroupSources: serials are an 8-byte
    big-endian per-group counter (unique BY CONSTRUCTION) followed by
    deterministic pseudo-random tail bytes, generated chunk by chunk —
    no per-serial Python objects, nothing corpus-sized resident.
    ``epoch_extra`` appends that many further serials to each of the
    first ``churn_groups`` groups (the delta leg's epoch-2 corpus:
    epoch 1 plus growth concentrated where churn really lands —
    untouched groups must cost zero delta bytes).

    Each source carries an analytic ``content_token``: the serial set
    is a pure function of (seed, serial_bytes, group, count), so that
    tuple IS the content identity — no O(corpus) hashing just to feed
    the dirty-group cache (the token is opaque to the build; only
    equality matters)."""
    from ct_mapreduce_tpu.filter import PackedGroupSource

    base_per = max(1, n_serials // n_groups)
    sources = []
    for g in range(n_groups):
        per = base_per + (epoch_extra if g < churn_groups else 0)

        def provider(chunk_size, g=g, per=per):
            import numpy as np

            for start in range(0, per, chunk_size):
                c = min(chunk_size, per - start)
                lens = np.full((c,), serial_bytes, np.int64)
                mat = np.zeros((c, 46), np.uint8)
                idx = start + np.arange(c, dtype=np.uint64)
                shifts = np.arange(7, -1, -1, dtype=np.uint64) * \
                    np.uint64(8)
                mat[:, :8] = ((idx[:, None] >> shifts[None, :])
                              & np.uint64(0xFF)).astype(np.uint8)
                # Pseudo-random tail as a pure function of the serial
                # INDEX (splitmix64), so the corpus is identical at
                # every chunk size — chunk boundaries must not change
                # the bytes the build sees.
                x = (idx ^ (np.uint64(seed) * np.uint64(0x100000001))
                     ^ (np.uint64(g) << np.uint64(40)))
                x = (x + np.uint64(0x9E3779B97F4A7C15))
                x ^= x >> np.uint64(30)
                x = x * np.uint64(0xBF58476D1CE4E5B9)
                x ^= x >> np.uint64(27)
                x = x * np.uint64(0x94D049BB133111EB)
                x ^= x >> np.uint64(31)
                mat[:, 8:serial_bytes] = (
                    (x[:, None] >> shifts[None, :8])
                    & np.uint64(0xFF)).astype(np.uint8)[
                        :, : serial_bytes - 8]
                yield lens, mat, []

        src = PackedGroupSource(
            f"scale-issuer-{g % max(1, n_groups // 2)}",
            500_000 + 24 * g, per, provider)
        src.content_token = ("packed", seed, serial_bytes, g, per)
        sources.append(src)
    return sources


def run_scale_leg(n: int, n_groups: int, rate: float, seed: int,
                  fused: bool = True, use_device=None,
                  stream_chunk: int = 0,
                  fmt: str | None = None) -> tuple[dict, bytes]:
    """One scale leg: packed corpus → artifact; serials/s, sampled
    peak RSS, and the layer/dispatch collapse."""
    import time as _time

    from ct_mapreduce_tpu.filter import artifact as fartifact
    from ct_mapreduce_tpu.telemetry.metrics import get_sink

    sources = packed_sources(n, n_groups, seed=seed)
    t0 = time.perf_counter()
    art = fartifact.build_artifact_from_sources(
        sources, fp_rate=rate, fused=fused, use_device=use_device,
        stream_chunk=stream_chunk, fmt=fmt)
    build_s = time.perf_counter() - t0
    gauges = get_sink().snapshot().get("gauges", {})
    stats = fartifact.LAST_BUILD_STATS
    blob = art.to_bytes()
    point = {
        "metric": "ct_filter_scale",
        "format": art.fmt,
        "serials": art.n_serials,
        "groups": len(art.groups),
        "fused": bool(fused),
        "build_s": round(build_s, 2),
        "serials_per_s": round(art.n_serials / max(build_s, 1e-9), 1),
        "peak_rss_bytes": int(gauges.get("filter.build_rss_bytes", 0)),
        "artifact_bytes": len(blob),
        "bits_per_entry": round(art.bits_per_entry(), 3),
        "max_layers": art.max_layers(),
        "layers_total": (stats.layers if stats else
                         sum(len(g.cascade.layers)
                             for g in art.groups.values())),
        "scatter_dispatches": stats.dispatches if stats else None,
        "layer_rounds": stats.rounds if stats else None,
        "groups_per_dispatch": (
            round(stats.mean_groups_per_dispatch(), 2)
            if stats else None),
        "wall_clock": _time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    return point, blob


def run_delta_leg(n: int, n_groups: int, rate: float, seed: int,
                  base_blob: bytes, churn: int,
                  fmt: str | None = None) -> dict:
    """Delta bits-on-wire at scale (ROADMAP 4(b) residue): epoch 2
    = epoch 1 + ``churn`` serials in ONE group (churn is localized —
    the other groups must contribute zero delta payload); measure the
    delta link (raw + gzip) against the full artifact pull. The wire
    magic (CTMRDL01/CTMRDL02) follows the artifacts' format."""
    import gzip

    from ct_mapreduce_tpu.distrib import delta as delta_mod
    from ct_mapreduce_tpu.filter import artifact as fartifact

    sources = packed_sources(n, n_groups, seed=seed, epoch_extra=churn)
    art2 = fartifact.build_artifact_from_sources(sources, fp_rate=rate,
                                                 fmt=fmt)
    blob2 = art2.to_bytes()
    link = delta_mod.compute_delta(base_blob, blob2, 1, 2)
    replay = delta_mod.apply_delta(base_blob, link)
    assert replay == blob2, "delta replay mismatch at scale"
    gz = lambda b: len(gzip.compress(b, mtime=0))  # noqa: E731
    return {
        "metric": "ct_filter_scale_delta",
        "format": art2.fmt,
        "serials": art2.n_serials,
        "churn_serials": churn,
        "churn_groups": 1,
        "full_bytes": len(blob2),
        "delta_bytes": len(link),
        "delta_vs_full": round(len(link) / max(1, len(blob2)), 6),
        "full_gzip_bytes": gz(blob2),
        "delta_gzip_bytes": gz(link),
        "delta_vs_full_gzip": round(
            gz(link) / max(1, gz(blob2)), 6),
    }


def run_incremental_leg(n: int, n_groups: int, rate: float, seed: int,
                        churn: int, use_device=None) -> tuple[dict, int]:
    """The CTMRFL02 dirty-group epoch tick: build epoch 1 through a
    :class:`GroupBuildCache`, then epoch 2 (= epoch 1 + ``churn``
    serials in ONE group) through the SAME cache — clean groups reuse
    their serialized blocks verbatim, only the churned group rebuilds.
    Honesty checks: the incremental artifact must be byte-identical to
    an epoch-2 build from scratch (which is also the full-rebuild wall
    the speedup is measured against). Returns (point, mismatch_rc)."""
    from ct_mapreduce_tpu.filter import GroupBuildCache
    from ct_mapreduce_tpu.filter import artifact as fartifact

    cache = GroupBuildCache()
    t0 = time.perf_counter()
    fartifact.build_artifact_from_sources(
        packed_sources(n, n_groups, seed=seed), fp_rate=rate,
        fmt="fl02", cache=cache, use_device=use_device)
    epoch1_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    art2 = fartifact.build_artifact_from_sources(
        packed_sources(n, n_groups, seed=seed, epoch_extra=churn),
        fp_rate=rate, fmt="fl02", cache=cache, use_device=use_device)
    incremental_s = time.perf_counter() - t0
    blob2 = art2.to_bytes()

    # Full-rebuild reference: same epoch-2 corpus, no cache. Doubles
    # as the byte-identity oracle for the incremental path.
    t0 = time.perf_counter()
    ref = fartifact.build_artifact_from_sources(
        packed_sources(n, n_groups, seed=seed, epoch_extra=churn),
        fp_rate=rate, fmt="fl02", use_device=use_device)
    full_s = time.perf_counter() - t0
    identical = blob2 == ref.to_bytes()
    if not identical:
        print(f"BYTE MISMATCH incremental vs from-scratch at n={n}",
              file=sys.stderr)
    return {
        "metric": "ct_filter_incremental",
        "format": art2.fmt,
        "serials": art2.n_serials,
        "groups": len(art2.groups),
        "churn_serials": churn,
        "churn_groups": 1,
        "churn_frac": round(churn / max(1, n), 4),
        "groups_reused": cache.hits,
        "epoch1_build_s": round(epoch1_s, 2),
        "incremental_build_s": round(incremental_s, 2),
        "full_rebuild_s": round(full_s, 2),
        "speedup": round(full_s / max(incremental_s, 1e-9), 2),
        "bytes_identical": identical,
    }, (0 if identical else 1)


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serials", type=int, default=20000)
    ap.add_argument("--groups", type=int, default=8)
    ap.add_argument("--probes", type=int, default=20000)
    ap.add_argument("--rates", default="0.5,0.1,0.01,0.001")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--scale", default="",
                    help="comma list of corpus sizes (e.g. 1e6,1e7,1e8)"
                         ": run the round-19 scaled-build legs instead "
                         "of the rate sweep")
    ap.add_argument("--scale-rate", type=float, default=0.01)
    ap.add_argument("--legacy", action="store_true",
                    help="also run each scale leg through the "
                         "per-group (round-15) build path")
    ap.add_argument("--host-lane", action="store_true",
                    help="force the NumPy build lane "
                         "(CTMR_FILTER_DEVICE=0 equivalent)")
    ap.add_argument("--delta", type=int, default=0, metavar="CHURN",
                    help="after each scale leg, measure the "
                         "CTMRDL01/CTMRDL02 delta for an epoch adding "
                         "CHURN serials to one group")
    ap.add_argument("--incremental", type=int, default=0,
                    metavar="CHURN",
                    help="after each scale leg, measure the fl02 "
                         "dirty-group epoch tick: rebuild with CHURN "
                         "serials added to one group through a warm "
                         "GroupBuildCache vs a full rebuild")
    ap.add_argument("--format", default="", choices=("", "fl01", "fl02"),
                    help="artifact format for the scale/delta legs "
                         "(default: the CTMR_FILTER_FORMAT ladder, "
                         "fl02)")
    args = ap.parse_args(argv)
    fmt = args.format or None

    if args.scale:
        use_device = False if args.host_lane else None
        rc = 0
        for spec in (s for s in args.scale.split(",") if s):
            n = int(float(spec))
            point, blob = run_scale_leg(
                n, args.groups, args.scale_rate, args.seed,
                use_device=use_device, fmt=fmt)
            print(json.dumps(point), flush=True)
            if args.legacy:
                lpoint, lblob = run_scale_leg(
                    n, args.groups, args.scale_rate, args.seed,
                    fused=False, use_device=use_device, fmt=fmt)
                print(json.dumps(lpoint), flush=True)
                if lblob != blob:
                    print(f"BYTE MISMATCH fused vs legacy at n={n}",
                          file=sys.stderr)
                    rc = 1
            if args.delta:
                print(json.dumps(run_delta_leg(
                    n, args.groups, args.scale_rate, args.seed, blob,
                    args.delta, fmt=fmt)), flush=True)
            if args.incremental:
                ipoint, irc = run_incremental_leg(
                    n, args.groups, args.scale_rate, args.seed,
                    args.incremental, use_device=use_device)
                print(json.dumps(ipoint), flush=True)
                rc = rc or irc
        return rc

    state = synth_state(args.serials, args.groups, seed=args.seed)
    probes = synth_probes(args.probes, seed=args.seed + 4)
    rc = 0
    for rate in (float(r) for r in args.rates.split(",") if r):
        point = run_point(state, probes, rate)
        print(json.dumps(point))
        if point["false_negatives"]:
            print(f"FALSE NEGATIVES at rate {rate}: "
                  f"{point['false_negatives']}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
