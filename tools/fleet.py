"""Local multi-worker ingest fleet harness (CPU-verifiable).

Drives the real pod-scale path end to end on one machine: W
`ct-fetch` worker PROCESSES (the actual `cmd/ct_fetch.py` main, fleet
directives and all) coordinate through an in-process miniredis —
leader election, start barrier, heartbeats, leader-published
checkpoint epochs — over disjoint rendezvous partitions of a shared
deterministic fakelog fixture, then the per-worker aggregate
checkpoints merge (`agg/merge.py`) into one storage-statistics view
that is compared against a single-process serial run of the same
entries.

    python tools/fleet.py --workers 2 --logs 4 --entries-per-log 256

Child mode (`--child`) is one worker process; the parent (and
tests/test_multiprocess.py, bench.run_fleet_smoke) spawns it. A child
killed mid-run (SIGKILL) and respawned resumes from its checkpoint
cursor in miniredis — the warm-restart contract — which the
kill-and-resume test drives directly.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import subprocess
import sys
import time
from pathlib import Path
from urllib.parse import parse_qs, urlparse

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


# -- deterministic fixture ----------------------------------------------


def build_fixture(path: str, n_logs: int = 2, entries_per_log: int = 128,
                  dupes: int = 8, max_batch: int = 256,
                  shared_issuer: bool = True) -> dict:
    """A wire-faithful multi-log corpus (utils/minicert — dependency-
    free canonical DER): per-log disjoint serial ranges (so partitions
    never share a certificate identity — see agg/merge.py's honest-
    limit note), intra-log duplicate serials (dedup exercised inside a
    partition), per-log issuers with CRL DPs, plus one issuer SHARED
    across logs (the cross-worker registry-merge case). JSON on disk
    so subprocess workers rebuild the exact same transport."""
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.utils import minicert

    logs: dict[str, list[dict]] = {}
    shared_der = minicert.make_cert(
        serial=7, issuer_cn="Fleet Shared CA", is_ca=True)
    for li in range(n_logs):
        url = f"https://ct.example.com/fleet{li}"
        issuer_der = minicert.make_cert(
            serial=2 + li, issuer_cn=f"Fleet CA {li}", is_ca=True)
        entries = []
        for e in range(entries_per_log):
            # Tail entries replay early serials: duplicates within the
            # partition (deduped on device) without crossing logs.
            serial = (1000 + li * 1_000_000
                      + (e % (entries_per_log - dupes)
                         if dupes and entries_per_log > dupes else e))
            use_shared = shared_issuer and e % 5 == 0
            cert = minicert.make_cert(
                serial=serial,
                issuer_cn=("Fleet Shared CA" if use_shared
                           else f"Fleet CA {li}"),
                subject_cn=f"w{li}-{e}.fleet.example",
                crl_dps=(f"http://crl.example/fleet{li}.crl",),
            )
            li_b = leaflib.encode_leaf_input(
                cert, timestamp_ms=1_700_000_000_000 + e)
            ed_b = leaflib.encode_extra_data(
                [shared_der if use_shared else issuer_der])
            entries.append({
                "leaf_input": base64.b64encode(li_b).decode(),
                "extra_data": base64.b64encode(ed_b).decode(),
            })
        logs[url] = entries
    fixture = {"max_batch": max_batch, "logs": logs}
    with open(path, "w") as fh:
        json.dump(fixture, fh)
    return fixture


class FixtureTransport:
    """The injectable HTTP transport over a fixture dict: answers
    get-sth / get-entries for every fixture log, like tests/fakelog
    but multi-log and subprocess-reconstructible. ``throttle_ms``
    delays each get-entries response — paces the download so
    checkpoint epochs land mid-ingest (the kill-window the resume
    tests need)."""

    def __init__(self, fixture: dict, throttle_ms: float = 0.0):
        self.logs = {
            urlparse(url).path: entries
            for url, entries in fixture["logs"].items()
        }
        self.max_batch = int(fixture.get("max_batch", 256))
        self.throttle_ms = float(throttle_ms)
        # get-entries start indices served, in order (resume evidence:
        # a warm restart's first fetch is the checkpoint cursor, not 0).
        self.entry_requests: list[int] = []

    def __call__(self, url: str) -> tuple[int, dict, bytes]:
        parsed = urlparse(url)
        path = parsed.path
        for prefix, entries in self.logs.items():
            if not path.startswith(prefix + "/"):
                continue
            if path.endswith("/ct/v1/get-sth"):
                return 200, {}, json.dumps(
                    {"tree_size": len(entries),
                     "timestamp": 1_700_000_000_000}).encode()
            if path.endswith("/ct/v1/get-entries"):
                if self.throttle_ms:
                    time.sleep(self.throttle_ms / 1000.0)
                q = parse_qs(parsed.query)
                start = int(q["start"][0])
                self.entry_requests.append(start)
                end = min(int(q["end"][0]), start + self.max_batch - 1,
                          len(entries) - 1)
                if start >= len(entries):
                    return 400, {}, b"range beyond tree size"
                return 200, {}, json.dumps(
                    {"entries": entries[start:end + 1]}).encode()
            return 404, {}, b"not found"
        return 404, {}, b"unknown log"


def install_transport(fixture: dict, throttle_ms: float = 0.0) -> None:
    """Route CTLogClient's default transport to the fixture."""
    from ct_mapreduce_tpu.ingest import ctclient

    ctclient._urllib_transport = FixtureTransport(fixture, throttle_ms)


# -- snapshots -----------------------------------------------------------


def snapshot_jsonable(snap) -> dict:
    """Canonical JSON form of an AggregateSnapshot — the byte-
    comparable parity object (sorted keys, sets → sorted lists)."""
    return {
        "counts": {f"{iss}|{exp}": n
                   for (iss, exp), n in sorted(snap.counts.items())},
        "crls": {iss: sorted(v) for iss, v in sorted(snap.crls.items())},
        "dns": {iss: sorted(v) for iss, v in sorted(snap.dns.items())},
        "total": snap.total,
        "verified": dict(sorted(snap.verified.items())),
        "failed": dict(sorted(snap.failed.items())),
    }


def merged_snapshot(state_paths: list[str]) -> dict:
    from ct_mapreduce_tpu.agg import merge

    return snapshot_jsonable(merge.load_checkpoints(state_paths).drain())


def filter_bytes(state_paths: list[str], fp_rate: float = 0.01) -> bytes:
    """The filter artifact compiled from one or many checkpoints —
    the byte-comparable parity object of the round-15 determinism
    contract: a W-worker fleet's merged filter must equal the serial
    run's bit for bit (canonical keys hash under sorted-issuerID
    ordinals, so worker-local registry numbering cancels out)."""
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.filter import build_from_merged

    merged = merge.load_checkpoints(state_paths)
    return build_from_merged(merged, fp_rate=fp_rate).to_bytes()


def _enable_compile_cache() -> None:
    """CT_COMPILE_CACHE for worker processes (same contract as
    bench.maybe_enable_compile_cache): the W children compile the same
    tiny CPU programs — share one cache dir and only the first pays.

    CT_COMPILE_CACHE_READONLY=1 makes this process consume the cache
    without ever writing entries (the min-compile-time gate set
    unreachably high): the mode for SIGKILL targets, which must never
    write a shared cache (a kill mid-write leaves a truncated
    executable that poisons every later reader — see spawn_worker)."""
    path = os.environ.get("CT_COMPILE_CACHE", "")
    if not path:
        return
    read_only = os.environ.get("CT_COMPILE_CACHE_READONLY", "0") == "1"
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1e9 if read_only else 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # jax-version dependent; the cache is an optimization only


# -- one worker process --------------------------------------------------


def write_worker_ini(path: str, fixture: dict, state_path: str,
                     redis_addr: str = "", worker_id: int = 0,
                     num_workers: int = 1, checkpoint_period: str = "",
                     batch_size: int = 64, table_bits: int = 12,
                     coordinator: str = "", emit_filter: bool = True,
                     query_port: int = 0,
                     run_forever: bool = False,
                     trace_path: str = "",
                     metrics_port: int = 0,
                     extra_lines: tuple = ()) -> None:
    lines = [
        f"logList = {','.join(fixture['logs'])}",
        "backend = tpu",
        f"batchSize = {batch_size}",
        f"tableBits = {table_bits}",
        "meshShape = shard:1",
        f"aggStatePath = {state_path}",
        "healthAddr = ",
        "nobars = true",
        "savePeriod = 15m",
    ]
    if emit_filter:
        # Filter capture in every harness checkpoint (round 15): the
        # --verify path builds the merged fleet filter from the worker
        # snapshots and byte-compares it against the serial run's.
        lines += ["emitFilter = true", "filterFpRate = 0.01"]
    if redis_addr:
        lines.append(f"redisHost = {redis_addr}")
    if num_workers > 1 or coordinator:
        lines += [
            f"numWorkers = {num_workers}",
            f"workerId = {worker_id}",
            f"coordinatorBackend = {coordinator or 'redis'}",
        ]
    if checkpoint_period:
        lines.append(f"checkpointPeriod = {checkpoint_period}")
    if query_port:
        # The live-storm leg (tools/pullstorm.py --live-fleet) pulls
        # /filter + /filter/delta from the WORKERS themselves while
        # they ingest; a deep distribution history keeps lagging
        # clients on the delta path for the whole leg.
        # Deep history + chain budget: every epoch the leg captures
        # stays delta-servable (no mid-leg anchors/evictions), so the
        # failover-straddling span is always a pure chain replay.
        lines += [f"queryPort = {query_port}", "distribHistory = 128",
                  "maxDeltaChain = 128"]
    if run_forever:
        lines += ["runForever = true", "pollingDelayMean = 1s",
                  "pollingDelayStdDev = 0"]
    if trace_path:
        # Per-worker span ring (round 23): the obs smoke merges these
        # into one skew-corrected timeline (traceview --merge).
        lines.append(f"tracePath = {trace_path}")
    if metrics_port:
        lines.append(f"metricsPort = {metrics_port}")
    lines += list(extra_lines)
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")


def read_cursors(redis_addr: str, fixture: dict,
                 num_workers: int = 1) -> dict[str, int]:
    """Durable per-log (and per-stripe) cursor positions from the
    shared cache — the warm-restart evidence."""
    from ct_mapreduce_tpu.ingest.ctclient import short_url
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    cache = RedisCache(redis_addr)
    out: dict[str, int] = {}
    try:
        for url in fixture["logs"]:
            keys = [short_url(url)]
            keys += [f"{short_url(url)}#w{w}" for w in range(num_workers)]
            for key in keys:
                state = cache.load_log_state(key)
                if state is not None:
                    out[key] = state.max_entry
    finally:
        cache.close()
    return out


def child_main(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _enable_compile_cache()
    with open(args.fixture) as fh:
        fixture = json.load(fh)
    install_transport(fixture, throttle_ms=args.throttle_ms)

    # Resume evidence BEFORE the run: the durable cursors this worker
    # will start from (0 on a cold start; the checkpoint position on a
    # warm restart). Printed first so a later SIGKILL can't lose it.
    resume = read_cursors(args.redis, fixture, args.workers) \
        if args.redis else {}
    print("FLEET-CHILD " + json.dumps(
        {"event": "start", "worker": args.worker_id,
         "resume_cursors": resume}), flush=True)

    ini = os.path.join(args.state_dir, f"worker{args.worker_id}.ini")
    state_path = os.path.join(args.state_dir, "agg.npz")
    write_worker_ini(
        ini, fixture, state_path, redis_addr=args.redis,
        worker_id=args.worker_id, num_workers=args.workers,
        checkpoint_period=args.checkpoint_period,
        batch_size=args.batch_size, table_bits=args.table_bits,
        coordinator=args.coordinator, query_port=args.query_port,
        run_forever=args.run_forever,
        trace_path=args.trace_path, metrics_port=args.metrics_port,
        extra_lines=tuple(args.ini_line or ()),
    )
    from ct_mapreduce_tpu.cmd import ct_fetch
    from ct_mapreduce_tpu.ingest.fleet import (
        partition_logs,
        worker_state_path,
    )

    t0 = time.monotonic()
    rc = ct_fetch.main(["-config", ini, "-nobars"])
    wall = time.monotonic() - t0

    urls = list(fixture["logs"])
    mine = (urls if args.workers <= 1 or len(urls) == 1
            else partition_logs(urls, args.worker_id, args.workers))
    print("FLEET-CHILD " + json.dumps({
        "event": "done", "worker": args.worker_id, "rc": rc,
        "wall_s": round(wall, 3),
        "owned_logs": mine,
        "state_path": worker_state_path(
            state_path, args.worker_id, args.workers),
    }), flush=True)
    return rc


# -- the parent orchestration -------------------------------------------


def spawn_worker(worker_id: int, workers: int, fixture_path: str,
                 state_dir: str, redis_addr: str,
                 checkpoint_period: str = "", batch_size: int = 64,
                 table_bits: int = 12, throttle_ms: float = 0.0,
                 coordinator: str = "",
                 compile_cache: bool = True,
                 compile_cache_readonly: bool = False,
                 query_port: int = 0,
                 run_forever: bool = False,
                 trace_path: str = "",
                 metrics_port: int = 0,
                 ini_lines: tuple = (),
                 extra_env: dict = None) -> subprocess.Popen:
    """Spawn one worker process. Pass ``compile_cache=False`` (no
    persistent cache) for every process involved in a kill-and-resume
    sequence. Observed on this jax/XLA CPU build (stress data in
    BENCHLOG round 14): when the restarted worker shares a persistent
    compilation cache, its native heap intermittently corrupts — XLA
    ``Check failed: allocation.size() == ...`` / ``is_tuple_``
    aborts, glibc ``corrupted size vs. prev_size``, or (worst)
    a clean exit whose checkpointed table rows are recycled-heap
    garbage. The trigger wasn't fully pinned (a read-only cache for
    the victim did not clear it; a clean no-kill restart never
    reproduces), but cache exclusion is the configuration repeatedly
    validated corruption-free. ``compile_cache_readonly=True``
    (consume without writing) remains for processes that only need
    protection against truncated-entry WRITES."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("CT_TPU_TESTS", None)
    if not compile_cache:
        env.pop("CT_COMPILE_CACHE", None)
    if compile_cache_readonly:
        env["CT_COMPILE_CACHE_READONLY"] = "1"
    env["PYTHONPATH"] = str(REPO)
    argv = [
        sys.executable, str(Path(__file__).resolve()), "--child",
        "--worker-id", str(worker_id), "--workers", str(workers),
        "--fixture", fixture_path, "--state-dir", state_dir,
        "--redis", redis_addr,
        "--batch-size", str(batch_size), "--table-bits", str(table_bits),
        "--throttle-ms", str(throttle_ms),
    ]
    if checkpoint_period:
        argv += ["--checkpoint-period", checkpoint_period]
    if coordinator:
        argv += ["--coordinator", coordinator]
    if query_port:
        argv += ["--query-port", str(query_port)]
    if run_forever:
        argv += ["--run-forever"]
    if trace_path:
        argv += ["--trace-path", trace_path]
    if metrics_port:
        argv += ["--metrics-port", str(metrics_port)]
    for line in ini_lines:
        argv += ["--ini-line", line]
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)


def child_events(output: str) -> list[dict]:
    return [json.loads(line.split(" ", 1)[1])
            for line in output.splitlines()
            if line.startswith("FLEET-CHILD ")]


def run_serial_reference(fixture: dict, state_dir: str,
                         batch_size: int = 64,
                         table_bits: int = 12) -> dict:
    """The single-worker truth, computed in-process (no fleet
    directives, in-process mock cache): the parity target."""
    from ct_mapreduce_tpu.agg.aggregator import HostSnapshotAggregator
    from ct_mapreduce_tpu.cmd import ct_fetch
    from ct_mapreduce_tpu.ingest import ctclient

    ini = os.path.join(state_dir, "serial.ini")
    state = os.path.join(state_dir, "serial.npz")
    write_worker_ini(ini, fixture, state)
    orig_transport = ctclient._urllib_transport
    install_transport(fixture)
    try:
        rc = ct_fetch.main(["-config", ini, "-nobars"])
    finally:
        ctclient._urllib_transport = orig_transport
    if rc != 0:
        raise RuntimeError(f"serial reference run failed rc={rc}")
    agg = HostSnapshotAggregator(capacity=1 << 10)
    agg.load_checkpoint(state)
    return snapshot_jsonable(agg.drain())


def run_fleet(workers: int = 2, n_logs: int = 4, entries_per_log: int = 256,
              dupes: int = 16, max_batch: int = 256, state_dir: str = "",
              checkpoint_period: str = "", batch_size: int = 64,
              table_bits: int = 12, throttle_ms: float = 0.0,
              verify: bool = False, coordinator: str = "") -> dict:
    """Spawn a W-worker fleet over a fresh fixture; returns the
    summary dict (aggregate entries/s, per-worker walls, merged
    snapshot, optional serial-parity verdict)."""
    import tempfile

    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    state_dir = state_dir or tempfile.mkdtemp(prefix="ct-fleet-")
    os.makedirs(state_dir, exist_ok=True)
    fixture_path = os.path.join(state_dir, "fixture.json")
    fixture = build_fixture(
        fixture_path, n_logs=n_logs, entries_per_log=entries_per_log,
        dupes=dupes, max_batch=max_batch)
    total_entries = sum(len(v) for v in fixture["logs"].values())

    redis = MiniRedis().start()
    try:
        t0 = time.monotonic()
        procs = [
            spawn_worker(
                w, workers, fixture_path,
                os.path.join(state_dir, f"w{w}"), redis.address,
                checkpoint_period=checkpoint_period,
                batch_size=batch_size, table_bits=table_bits,
                throttle_ms=throttle_ms, coordinator=coordinator)
            for w in range(workers)
        ]
        outs = [p.communicate(timeout=600)[0] for p in procs]
        wall = time.monotonic() - t0
    finally:
        redis.stop()
    for w, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(f"worker {w} failed rc={p.returncode}:\n{out}")
    events = [child_events(out) for out in outs]
    dones = [next(e for e in evs if e["event"] == "done") for evs in events]
    state_paths = [d["state_path"] for d in dones]
    merged = merged_snapshot(state_paths)
    result = {
        "workers": workers,
        "logs": n_logs,
        "entries": total_entries,
        "wall_s": round(wall, 3),
        "entries_per_s": round(total_entries / wall, 1),
        "worker_walls_s": [d["wall_s"] for d in dones],
        "owned_logs": {d["worker"]: d["owned_logs"] for d in dones},
        "merged_total": merged["total"],
        "state_paths": state_paths,
        "state_dir": state_dir,
    }
    if verify:
        ref = run_serial_reference(
            fixture, state_dir, batch_size=batch_size,
            table_bits=table_bits)
        result["parity"] = int(merged == ref)
        if merged != ref:
            result["merged"] = merged
            result["reference"] = ref
        # Round-15 artifact determinism: merged fleet filter ==
        # serial-run filter, byte for byte.
        fleet_blob = filter_bytes(state_paths)
        serial_blob = filter_bytes(
            [os.path.join(state_dir, "serial.npz")])
        result["filter_parity"] = int(fleet_blob == serial_blob)
        result["filter_bytes"] = len(fleet_blob)
        if fleet_blob != serial_blob:
            result["filter_bytes_serial"] = len(serial_blob)
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--fixture", default="")
    ap.add_argument("--state-dir", default="")
    ap.add_argument("--redis", default="")
    ap.add_argument("--checkpoint-period", default="")
    ap.add_argument("--coordinator", default="")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--table-bits", type=int, default=12)
    ap.add_argument("--throttle-ms", type=float, default=0.0)
    ap.add_argument("--query-port", type=int, default=0)
    ap.add_argument("--run-forever", action="store_true")
    ap.add_argument("--trace-path", default="")
    ap.add_argument("--metrics-port", type=int, default=0)
    ap.add_argument("--ini-line", action="append", default=[],
                    help="extra raw config line(s) for the worker ini "
                         "(e.g. 'sloMaxIngestLag = 10')")
    ap.add_argument("--logs", type=int, default=4)
    ap.add_argument("--entries-per-log", type=int, default=256)
    ap.add_argument("--dupes", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--verify", action="store_true",
                    help="also run the serial reference and check parity")
    args = ap.parse_args(argv)
    if args.child:
        os.makedirs(args.state_dir, exist_ok=True)
        rc = child_main(args)
        # Hard exit: every result line is already flushed, and jax's
        # CPU client intermittently segfaults in interpreter teardown
        # (observed -11 AFTER a clean "done" event) — skip atexit so a
        # finished worker can't be scored as crashed.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    out = run_fleet(
        workers=args.workers, n_logs=args.logs,
        entries_per_log=args.entries_per_log, dupes=args.dupes,
        max_batch=args.max_batch, state_dir=args.state_dir,
        checkpoint_period=args.checkpoint_period,
        batch_size=args.batch_size, table_bits=args.table_bits,
        throttle_ms=args.throttle_ms, verify=args.verify)
    print(json.dumps(out, indent=2))
    if args.verify and not out.get("parity"):
        print("PARITY MISMATCH", file=sys.stderr)
        return 1
    if args.verify and not out.get("filter_parity"):
        print("FILTER ARTIFACT MISMATCH", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
