"""pullstorm: simulated client pull storm against a multi-worker
filter-distribution fleet (ISSUE 13's load proof; ROADMAP item 4).

Builds an epoch sequence of deterministic filter artifacts (synthetic
(issuer, expDate) serial sets with per-epoch churn), publishes every
epoch into W serving workers through the SAME fan-out path ct-fetch
uses in a fleet (``oracle.publish_artifact(..., source="fleet")`` —
byte-identical input on every worker, exactly what the leader's
merged-artifact tick delivers), verifies the workers really serve
byte-identical artifacts (full + every container) over HTTP, then
storms them with N simulated clients:

- **warm** clients (zipf lag 0) hold the latest ETag and issue a
  conditional GET — the steady state, answered ``304`` with zero body
  bytes;
- **lagging** clients (zipf-distributed epoch lag) pull
  ``GET /filter/delta/<theirs>/<latest>``, validate each link against
  the chain manifest, and replay — falling back to a full pull when
  the chain is anchored/evicted away (404);
- **cold** clients full-pull ``GET /filter`` with gzip negotiation
  (a configurable fraction pulls an upstream container instead).

Reports bytes-on-wire against the full-pull counterfactual, the 304
ratio, and latency percentiles. A scaled-down leg gates in tier-1
via ``bench.run_distrib_smoke``; the full 10K-client run is recorded
in BENCHLOG.

    python tools/pullstorm.py --clients 10000 --epochs 6 --workers 2
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import queue
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_epoch_blobs(n_epochs: int, groups: int, per_group: int,
                      churn: int, seed: int) -> list[bytes]:
    """Deterministic epoch sequence: ``groups`` (issuer, expDate)
    sets, ``churn`` groups gaining serials per epoch (the crlite
    shape: most groups untouched epoch to epoch)."""
    from ct_mapreduce_tpu.filter import build_artifact

    rng = np.random.default_rng(seed)
    sets = {
        (f"issuer-{g:03d}", 500_000 + 24 * g): {
            bytes([g % 251, s % 251, 7])
            + bytes([int(x) for x in rng.integers(0, 256, 3)])
            for s in range(per_group)
        }
        for g in range(groups)
    }
    blobs = []
    for e in range(n_epochs):
        if e:
            keys = sorted(sets)
            for i in range(churn):
                key = keys[(e * churn + i) % len(keys)]
                sets[key] = set(sets[key]) | {
                    bytes([e % 251, i % 251])
                    + bytes([int(x) for x in rng.integers(0, 256, 3)])
                    for _ in range(max(1, per_group // 10))}
        blobs.append(build_artifact(sets, fp_rate=0.01,
                                    use_device=False).to_bytes())
    return blobs


def start_fleet(blobs: list[bytes], workers: int,
                max_chain: int) -> list:
    """W serving workers, each fed every epoch through the fleet
    fan-out path. Returns the started QueryServers."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.serve.server import QueryServer

    servers = []
    for _ in range(workers):
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
        agg.enable_filter_capture()
        srv = QueryServer(agg, 0, filter_first=True,
                          max_delta_chain=max_chain,
                          distrib_history=len(blobs) + 1).start()
        for e, blob in enumerate(blobs):
            srv.oracle.publish_artifact(e, blob, source="fleet")
        servers.append(srv)
    return servers


def verify_fleet_parity(bases: list[str]) -> dict:
    """Every worker serves byte-identical artifacts: full, every
    container, and the manifest's latest hash. Returns the reference
    payload sizes."""
    fulls, etags = [], []
    for base in bases:
        r = urllib.request.urlopen(base + "/filter")
        fulls.append(r.read())
        etags.append(r.headers["ETag"])
    if len({f for f in fulls}) != 1 or len(set(etags)) != 1:
        raise RuntimeError("workers serve DIFFERENT full artifacts")
    sizes = {"full": len(fulls[0]), "etag": etags[0]}
    man = json.loads(urllib.request.urlopen(
        bases[0] + "/filter/manifest").read())
    for kind in man["containers"]:
        payloads = []
        for base in bases:
            payloads.append(urllib.request.urlopen(
                f"{base}/filter/container/{kind}").read())
        if len(set(payloads)) != 1:
            raise RuntimeError(f"workers serve DIFFERENT {kind} "
                               f"containers")
        sizes[kind] = len(payloads[0])
    sizes["manifest"] = man
    return sizes


def run_storm(clients: int = 10_000, epochs: int = 5, groups: int = 40,
              per_group: int = 50, churn: int = 2, workers: int = 2,
              threads: int = 32, max_chain: int = 4,
              cold_fraction: float = 0.05,
              container_fraction: float = 0.2, zipf_a: float = 1.6,
              seed: int = 20260805, validate_every: int = 50,
              force_zstd: bool = False) -> dict:
    """The full storm. Returns the report dict (also printed as JSON
    by the CLI). ``force_zstd`` makes every compressible pull demand
    zstd and fails loudly when the fleet can't serve it (validating
    the zstd wire leg — ROADMAP item 4(c); needs the optional
    ``zstandard`` module on BOTH ends)."""
    from ct_mapreduce_tpu.distrib import (
        ChainManifest,
        apply_chain,
        split_bundle,
    )

    blobs = build_epoch_blobs(epochs, groups, per_group, churn, seed)
    servers = start_fleet(blobs, workers, max_chain)
    bases = [f"http://127.0.0.1:{s.port}" for s in servers]
    try:
        sizes = verify_fleet_parity(bases)
        man = sizes.pop("manifest")
        latest = man["latestEpoch"]
        manifest = ChainManifest.from_json(man)
        latest_etag = sizes["etag"]
        full_size = sizes["full"]

        if force_zstd:
            from ct_mapreduce_tpu.distrib.publish import zstd_available

            if "zstd" not in man["encodings"] or not zstd_available():
                raise RuntimeError(
                    "--force-zstd: the fleet does not advertise zstd "
                    "(install the optional `zstandard` module)")
            import zstandard as _zstd_mod

            accept = "zstd"

            def _decode(body: bytes, encoding) -> bytes:
                if encoding != "zstd":
                    raise RuntimeError(
                        f"--force-zstd: server answered "
                        f"Content-Encoding={encoding!r}, wanted zstd")
                return _zstd_mod.ZstdDecompressor().decompress(body)
        else:
            accept = "gzip"

            def _decode(body: bytes, encoding) -> bytes:
                return (gzip.decompress(body) if encoding == "gzip"
                        else body)

        # Client plan: zipf epoch lag (0 = warm), a cold slice, a
        # container-pulling slice of the colds.
        rng = np.random.default_rng(seed + 1)
        lags = (rng.zipf(zipf_a, size=clients) - 1).clip(0, epochs - 1)
        cold = rng.random(clients) < cold_fraction
        wants_container = rng.random(clients) < container_fraction
        kinds = sorted(k for k in sizes if k not in ("full", "etag"))

        tasks: queue.Queue = queue.Queue()
        for i in range(clients):
            tasks.put(i)
        lock = threading.Lock()
        results = []
        errors = []

        def one_pull(i: int) -> tuple:
            base = bases[i % len(bases)]
            t0 = time.monotonic()
            if cold[i]:
                if wants_container[i] and kinds:
                    kind = kinds[i % len(kinds)]
                    r = urllib.request.urlopen(
                        f"{base}/filter/container/{kind}")
                    return "container", len(r.read()), t0
                req = urllib.request.Request(
                    base + "/filter",
                    headers={"Accept-Encoding": accept})
                r = urllib.request.urlopen(req)
                body = r.read()
                # Client really can use the negotiated encoding.
                _decode(body, r.headers.get("Content-Encoding"))
                return "full", len(body), t0
            lag = int(lags[i])
            if lag == 0:
                req = urllib.request.Request(
                    base + "/filter",
                    headers={"If-None-Match": latest_etag})
                try:
                    r = urllib.request.urlopen(req)
                    return "full", len(r.read()), t0  # ETag rotated
                except urllib.error.HTTPError as err:
                    if err.code != 304:
                        raise
                    err.read()
                    return "304", 0, t0
            try:
                req = urllib.request.Request(
                    f"{base}/filter/delta/{latest - lag}/{latest}",
                    headers={"Accept-Encoding": accept})
                r = urllib.request.urlopen(req)
                wire = r.read()
                bundle = _decode(wire,
                                 r.headers.get("Content-Encoding"))
            except urllib.error.HTTPError as err:
                if err.code != 404:
                    raise
                err.read()
                # Anchored/evicted out: the documented fallback.
                r = urllib.request.urlopen(base + "/filter")
                return "fallback_full", len(r.read()), t0
            if i % validate_every == 0:
                links = split_bundle(bundle)
                manifest.validate_chain(latest - lag, latest, links)
                if apply_chain(blobs[latest - lag], links) \
                        != blobs[latest]:
                    raise RuntimeError(
                        f"delta replay mismatch (lag {lag})")
            return "delta", len(wire), t0

        def worker_loop():
            while True:
                try:
                    i = tasks.get_nowait()
                except queue.Empty:
                    return
                try:
                    kind, n_bytes, t0 = one_pull(i)
                    dt = time.monotonic() - t0
                    with lock:
                        results.append((kind, n_bytes, dt))
                except Exception as err:  # noqa: BLE001 — report, don't hang
                    with lock:
                        errors.append(f"client {i}: "
                                      f"{type(err).__name__}: {err}")

        t_start = time.monotonic()
        pool = [threading.Thread(target=worker_loop, daemon=True)
                for _ in range(threads)]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        wall = time.monotonic() - t_start
        if errors:
            raise RuntimeError(
                f"{len(errors)} client failures, first: {errors[0]}")

        by_kind: dict = {}
        for kind, n_bytes, _ in results:
            cnt, tot = by_kind.get(kind, (0, 0))
            by_kind[kind] = (cnt + 1, tot + n_bytes)
        lat = np.array(sorted(dt for _, _, dt in results))
        bytes_on_wire = sum(tot for _, tot in by_kind.values())
        n304 = by_kind.get("304", (0, 0))[0]
        n_delta, delta_bytes = by_kind.get("delta", (0, 0))
        counterfactual = len(results) * full_size
        d3_clients = n304 + n_delta
        d3_bytes = delta_bytes  # 304s add zero body bytes
        report = {
            "clients": len(results),
            "workers": workers,
            "epochs": epochs,
            "full_artifact_bytes": full_size,
            "pulls": {k: {"count": c, "bytes": b}
                      for k, (c, b) in sorted(by_kind.items())},
            "ratio_304": round(n304 / max(1, len(results)), 4),
            "bytes_on_wire": bytes_on_wire,
            "counterfactual_full_bytes": counterfactual,
            "wire_vs_counterfactual": round(
                bytes_on_wire / max(1, counterfactual), 4),
            "delta_304_clients": d3_clients,
            "delta_304_bytes": d3_bytes,
            "delta_304_counterfactual": d3_clients * full_size,
            "delta_304_vs_full": round(
                d3_bytes / max(1, d3_clients * full_size), 4),
            "p50_ms": round(float(lat[len(lat) // 2]) * 1e3, 3),
            "p99_ms": round(float(lat[int(len(lat) * 0.99)]) * 1e3, 3),
            "wall_s": round(wall, 3),
            "pulls_per_s": round(len(results) / max(wall, 1e-9), 1),
            "worker_parity": 1,
            "zstd_available": "zstd" in man["encodings"],
        }
        return report
    finally:
        for s in servers:
            s.stop()


# -- the live-fleet leg (ROADMAP 4(a)) -----------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(url: str, timeout: float = 5.0) -> dict:
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def run_live_fleet_storm(clients: int = 900, threads: int = 12,
                         n_logs: int = 4, entries_per_log: int = 640,
                         throttle_ms: float = 500.0,
                         state_dir: str = "") -> dict:
    """ROADMAP 4(a): the storm driven against a LIVE ``tools/fleet.py``
    fleet instead of the direct fan-out path. Two real ct-fetch worker
    processes ingest a throttled fixture under a 500 ms checkpoint
    cadence, each serving ``/filter`` + ``/filter/delta`` from its own
    queryPort while the leader's merged CTMRFL02 artifact fans out
    every epoch tick. Mid-storm the LEADER is SIGKILLed; the parent
    expires its election lease (the 5-minute production TTL compressed
    to harness timescale — exactly the expiry ``maybe_promote``
    inherits from), the surviving follower promotes itself and keeps
    publishing epochs, and the dead worker is respawned and warm-
    rejoins. The leg then proves delta-chain continuity end to end:
    every consecutive captured epoch pair AND one span straddling the
    failover replay byte-identical via the survivor's chain, the
    final artifact is byte-identical on both workers, and an offline
    merge of the worker checkpoints reproduces the served bytes."""
    import hashlib
    import http.client
    import signal
    import subprocess
    import tempfile
    from datetime import datetime, timezone

    from tools import fleet as harness

    from ct_mapreduce_tpu.distrib import (
        ChainManifest,
        apply_chain,
        split_bundle,
    )
    from ct_mapreduce_tpu.storage.rediscache import RedisCache
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    state_dir = state_dir or tempfile.mkdtemp(prefix="ct-livestorm-")
    os.makedirs(state_dir, exist_ok=True)
    fixture_path = os.path.join(state_dir, "fixture.json")
    # Small batches + a heavy per-batch throttle stretch the ingest
    # window far past worker startup, so the leader SIGKILL lands
    # MID-INGEST and the promoted follower still has real churn to
    # publish (post-failover epochs require changing bytes).
    fixture = harness.build_fixture(
        fixture_path, n_logs=n_logs, entries_per_log=entries_per_log,
        dupes=16, max_batch=16)
    total_entries = sum(len(v) for v in fixture["logs"].values())
    ports = [_free_port(), _free_port()]
    bases = [f"http://127.0.0.1:{p}" for p in ports]

    redis = MiniRedis().start()
    cache = RedisCache(redis.address)
    procs: list = []  # (worker_id, Popen)
    captured: list[dict] = []  # {epoch, blob, etag, t}
    cap_lock = threading.Lock()
    cap_stop = threading.Event()
    t0 = time.monotonic()

    def spawn(worker_id: int):
        # No persistent compile cache for any process in a
        # kill-and-resume sequence (tools/fleet.py::spawn_worker).
        p = harness.spawn_worker(
            worker_id, 2, fixture_path, state_dir, redis.address,
            checkpoint_period="1s", throttle_ms=throttle_ms,
            compile_cache=False, query_port=ports[worker_id],
            run_forever=True)
        procs.append((worker_id, p))
        return p

    def capture_loop():
        """Tail the SURVIVOR's (/w1's) distribution store: one entry
        per store epoch, blob pinned to the manifest's latestSha256
        (re-polls when a publish races the full-artifact GET)."""
        while not cap_stop.is_set():
            try:
                man = _http_json(bases[1] + "/filter/manifest")
                latest = man.get("latestEpoch", -1)
                with cap_lock:
                    have = captured[-1]["epoch"] if captured else -1
                if latest > have:
                    r = urllib.request.urlopen(bases[1] + "/filter",
                                               timeout=5)
                    blob = r.read()
                    if hashlib.sha256(blob).hexdigest() \
                            == man["latestSha256"]:
                        with cap_lock:
                            if not captured \
                                    or captured[-1]["epoch"] < latest:
                                captured.append({
                                    "epoch": latest, "blob": blob,
                                    "etag": r.headers["ETag"],
                                    "t": time.monotonic()})
            except Exception:
                pass  # worker mid-start / mid-restart: retry
            cap_stop.wait(0.2)

    def wait_for(cond, what: str, deadline_s: float):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if cond():
                return
            for wid, p in procs:
                if p.returncode is None and p.poll() is not None \
                        and p.returncode != -signal.SIGKILL:
                    out = p.stdout.read() if p.stdout else ""
                    raise RuntimeError(
                        f"worker {wid} died rc={p.returncode} while "
                        f"waiting for {what}:\n{out[-4000:]}")
            time.sleep(0.25)
        raise RuntimeError(f"timed out waiting for {what}")

    def storm_phase(n: int, label: str) -> dict:
        """n mixed clients against both workers; a connection-refused
        replica (the killed leader, or the respawn window) retries on
        the peer and is counted as a failover retry."""
        with cap_lock:
            snap = list(captured)
        latest = snap[-1]
        rng = np.random.default_rng(20260807 + n)
        lags = rng.integers(0, max(1, len(snap)), size=n)
        cold = rng.random(n) < 0.1
        lock = threading.Lock()
        results, errors = [], []
        retries = [0]
        tasks: queue.Queue = queue.Queue()
        for i in range(n):
            tasks.put(i)

        def one_pull(i: int) -> tuple:
            t_req = time.monotonic()
            attempt = 0
            base = bases[i % len(bases)]
            while True:
                try:
                    if cold[i]:
                        r = urllib.request.urlopen(base + "/filter",
                                                   timeout=10)
                        return "full", len(r.read()), t_req
                    lag = int(lags[i])
                    if lag == 0:
                        req = urllib.request.Request(
                            base + "/filter",
                            headers={"If-None-Match": latest["etag"]})
                        try:
                            r = urllib.request.urlopen(req, timeout=10)
                            return "full", len(r.read()), t_req
                        except urllib.error.HTTPError as err:
                            if err.code != 304:
                                raise
                            err.read()
                            return "304", 0, t_req
                    mine = snap[len(snap) - 1 - lag]
                    try:
                        r = urllib.request.urlopen(
                            f"{base}/filter/delta/{mine['epoch']}"
                            f"/{latest['epoch']}", timeout=10)
                        wire = r.read()
                    except urllib.error.HTTPError as err:
                        if err.code != 404:
                            raise
                        err.read()
                        # Evicted/anchored away (e.g. the respawned
                        # worker's fresh store): documented fallback.
                        r = urllib.request.urlopen(base + "/filter",
                                                   timeout=10)
                        return "fallback_full", len(r.read()), t_req
                    if apply_chain(mine["blob"], split_bundle(wire)) \
                            != latest["blob"]:
                        raise RuntimeError(
                            f"delta replay mismatch (lag {lag})")
                    return "delta", len(wire), t_req
                except (urllib.error.URLError, ConnectionError,
                        TimeoutError, http.client.HTTPException):
                    attempt += 1
                    if attempt >= 4:
                        raise
                    with lock:
                        retries[0] += 1
                    base = bases[(i + attempt) % len(bases)]
                    time.sleep(0.2)

        def worker_loop():
            while True:
                try:
                    i = tasks.get_nowait()
                except queue.Empty:
                    return
                try:
                    kind, n_bytes, t_req = one_pull(i)
                    with lock:
                        results.append(
                            (kind, n_bytes, time.monotonic() - t_req))
                except Exception as err:  # noqa: BLE001
                    with lock:
                        errors.append(f"client {i}: "
                                      f"{type(err).__name__}: {err}")

        pool = [threading.Thread(target=worker_loop, daemon=True)
                for _ in range(threads)]
        t_start = time.monotonic()
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        if errors:
            raise RuntimeError(f"{label}: {len(errors)} client "
                               f"failures, first: {errors[0]}")
        by_kind: dict = {}
        for kind, n_bytes, _ in results:
            cnt, tot = by_kind.get(kind, (0, 0))
            by_kind[kind] = (cnt + 1, tot + n_bytes)
        lat = sorted(dt for _, _, dt in results)
        return {
            "clients": len(results),
            "pulls": {k: {"count": c, "bytes": b}
                      for k, (c, b) in sorted(by_kind.items())},
            "failover_retries": retries[0],
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
            "wall_s": round(time.monotonic() - t_start, 3),
        }

    try:
        # Leader first, alone, so the kill target is deterministic.
        spawn(0)
        wait_for(lambda: cache.get("leader-ct-fetch") is not None,
                 "leader election", 300)
        spawn(1)
        cap_thread = threading.Thread(target=capture_loop, daemon=True)
        cap_thread.start()
        wait_for(lambda: len(captured) >= 2,
                 "two published fleet epochs", 300)

        phase1 = storm_phase(clients // 3, "pre-failover")

        # Mid-storm failover: kill the leader 1 s into phase 2, then
        # expire its election lease so the follower's maybe_promote
        # can win now rather than at the 5-minute production TTL.
        phase2_out: dict = {}

        def phase2_run():
            phase2_out.update(storm_phase(clients // 3, "mid-failover"))

        p2 = threading.Thread(target=phase2_run)
        p2.start()
        time.sleep(1.0)
        victim = next(p for wid, p in procs if wid == 0)
        os.kill(victim.pid, signal.SIGKILL)
        t_kill = time.monotonic()
        cache.expire_at("leader-ct-fetch",
                        datetime(1970, 1, 2, tzinfo=timezone.utc))
        kill_cursors = harness.read_cursors(redis.address, fixture, 2)
        ingest_frac_at_kill = round(
            sum(max(0, v + 1) for v in kill_cursors.values())
            / max(1, total_entries), 3)
        p2.join()
        victim.wait(timeout=30)
        victim_out = victim.stdout.read() if victim.stdout else ""
        victim.stdout.close()

        def post_kill_epochs():
            with cap_lock:
                return [c for c in captured if c["t"] > t_kill]

        wait_for(lambda: len(post_kill_epochs()) >= 1,
                 "a post-failover epoch from the promoted follower",
                 180)
        failover_s = post_kill_epochs()[0]["t"] - t_kill

        # Respawn the dead leader: warm rejoin as a follower.
        respawn = spawn(0)
        wait_for(lambda: _can_reach(bases[0]), "leader respawn", 300)
        phase3 = storm_phase(clients - 2 * (clients // 3),
                             "post-respawn")

        # Quiescence: every log cursor at tree size, then the captured
        # chain stable (the final merged artifact covers the corpus).
        def ingest_done():
            cur = harness.read_cursors(redis.address, fixture, 2)
            per_log = {}
            for key, pos in cur.items():
                root = key.split("#")[0]
                per_log[root] = max(per_log.get(root, 0), pos)
            return len(per_log) == n_logs and all(
                pos >= entries_per_log - 1 for pos in per_log.values())

        wait_for(ingest_done, "ingest completion", 600)

        def chain_stable():
            with cap_lock:
                return captured and time.monotonic() - captured[-1]["t"] > 6.0

        wait_for(chain_stable, "chain quiescence", 120)

        # -- continuity + parity verdicts --------------------------------
        with cap_lock:
            snap = list(captured)
        pre = [c for c in snap if c["t"] <= t_kill]
        post = [c for c in snap if c["t"] > t_kill]
        if not pre or not post:
            raise RuntimeError(
                f"failover not straddled: {len(pre)} pre-kill epochs, "
                f"{len(post)} post-kill")
        man = _http_json(bases[1] + "/filter/manifest")
        manifest = ChainManifest.from_json(man)
        pairs_replayed, pairs_404 = 0, 0
        for a, b in zip(snap, snap[1:]):
            try:
                wire = urllib.request.urlopen(
                    f"{bases[1]}/filter/delta/{a['epoch']}"
                    f"/{b['epoch']}", timeout=10).read()
            except urllib.error.HTTPError as err:
                if err.code != 404:
                    raise
                err.read()
                pairs_404 += 1  # evicted/anchored away: fallback path
                continue
            links = split_bundle(wire)
            manifest.validate_chain(a["epoch"], b["epoch"], links)
            if apply_chain(a["blob"], links) != b["blob"]:
                raise RuntimeError(
                    f"chain replay {a['epoch']}→{b['epoch']} diverged")
            pairs_replayed += 1
        if not pairs_replayed:
            raise RuntimeError("no consecutive epoch pair replayed")
        # The leg's reason to exist: one chain span straddling the
        # leader failover must replay byte-identically.
        boundary = pre[-1]
        wire = urllib.request.urlopen(
            f"{bases[1]}/filter/delta/{boundary['epoch']}"
            f"/{snap[-1]['epoch']}", timeout=10).read()
        links = split_bundle(wire)
        manifest.validate_chain(boundary["epoch"], snap[-1]["epoch"],
                                links)
        if apply_chain(boundary["blob"], links) != snap[-1]["blob"]:
            raise RuntimeError("failover-straddling chain diverged")

        finals, final_etags = [], []
        for base in bases:
            r = urllib.request.urlopen(base + "/filter", timeout=10)
            finals.append(r.read())
            final_etags.append(r.headers["ETag"])
        if len(set(finals)) != 1 or len(set(final_etags)) != 1:
            raise RuntimeError("workers serve DIFFERENT final "
                               "artifacts after failover")

        # Shutdown broadcast, then the offline determinism cross-check:
        # merging the workers' final checkpoints must reproduce the
        # bytes the fleet served.
        cache.put("fleet-stop-ct-fetch", "storm complete")
        outs = {}
        for wid, p in procs:
            if p is victim:
                continue
            out, _ = p.communicate(timeout=180)
            outs[wid] = out
            if p.returncode != 0:
                raise RuntimeError(
                    f"worker {wid} exited rc={p.returncode}:\n"
                    f"{out[-4000:]}")
        state_paths = [os.path.join(state_dir, f"agg.w{w}.npz")
                       for w in range(2)]
        offline = harness.filter_bytes(state_paths)
        if offline != finals[0]:
            raise RuntimeError(
                "offline checkpoint merge does not reproduce the "
                f"served artifact ({len(offline)} vs "
                f"{len(finals[0])} bytes)")

        if "(leader" not in victim_out:
            raise RuntimeError(
                "kill target was not the leader — leg invalid:\n"
                + victim_out[-2000:])
        respawn_events = harness.child_events(outs[0])
        resume = next(e for e in respawn_events
                      if e["event"] == "start")["resume_cursors"]
        if not resume or not any(v > 0 for v in resume.values()):
            raise RuntimeError(
                f"respawned leader did not warm-resume: {resume}")

        return {
            "metric": "ct_filter_live_fleet_storm",
            "workers": 2,
            "logs": n_logs,
            "entries": total_entries,
            "format": finals[0][:8].decode(),
            "full_artifact_bytes": len(finals[0]),
            "epochs_captured": len(snap),
            "epochs_pre_kill": len(pre),
            "epochs_post_kill": len(post),
            "failover_s": round(failover_s, 3),
            "ingest_frac_at_kill": ingest_frac_at_kill,
            "chain_pairs_replayed": pairs_replayed,
            "chain_pairs_404": pairs_404,
            "chain_spans_failover": 1,
            "worker_parity": 1,
            "offline_merge_parity": 1,
            "leader_warm_resume": 1,
            "storm": {"pre_failover": phase1, "mid_failover": phase2_out,
                      "post_respawn": phase3},
            "wall_s": round(time.monotonic() - t0, 3),
        }
    finally:
        cap_stop.set()
        for _, p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
        cache.close()
        redis.stop()


def _can_reach(base: str) -> bool:
    try:
        urllib.request.urlopen(base + "/filter", timeout=2).read()
        return True
    except Exception:
        return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="pullstorm")
    p.add_argument("--clients", type=int, default=10_000)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--groups", type=int, default=40)
    p.add_argument("--per-group", type=int, default=50)
    p.add_argument("--churn", type=int, default=2)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--threads", type=int, default=32)
    p.add_argument("--max-chain", type=int, default=4)
    p.add_argument("--cold", type=float, default=0.05)
    p.add_argument("--containers", type=float, default=0.2)
    p.add_argument("--zipf", type=float, default=1.6)
    p.add_argument("--seed", type=int, default=20260805)
    p.add_argument("--force-zstd", action="store_true",
                   help="every compressible pull demands zstd; fails "
                        "when the optional zstandard module is absent "
                        "(validates the zstd wire leg)")
    p.add_argument("--live-fleet", action="store_true",
                   help="drive the storm against a LIVE tools/fleet.py "
                        "run with a leader SIGKILL + lease-expiry "
                        "failover mid-storm (ROADMAP 4(a))")
    args = p.parse_args(argv)
    if args.live_fleet:
        report = run_live_fleet_storm(clients=args.clients,
                                      threads=args.threads)
        print(json.dumps(report, indent=2))
        return 0
    report = run_storm(
        clients=args.clients, epochs=args.epochs, groups=args.groups,
        per_group=args.per_group, churn=args.churn,
        workers=args.workers, threads=args.threads,
        max_chain=args.max_chain, cold_fraction=args.cold,
        container_fraction=args.containers, zipf_a=args.zipf,
        seed=args.seed, force_zstd=args.force_zstd)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
