"""pullstorm: simulated client pull storm against a multi-worker
filter-distribution fleet (ISSUE 13's load proof; ROADMAP item 4).

Builds an epoch sequence of deterministic filter artifacts (synthetic
(issuer, expDate) serial sets with per-epoch churn), publishes every
epoch into W serving workers through the SAME fan-out path ct-fetch
uses in a fleet (``oracle.publish_artifact(..., source="fleet")`` —
byte-identical input on every worker, exactly what the leader's
merged-artifact tick delivers), verifies the workers really serve
byte-identical artifacts (full + every container) over HTTP, then
storms them with N simulated clients:

- **warm** clients (zipf lag 0) hold the latest ETag and issue a
  conditional GET — the steady state, answered ``304`` with zero body
  bytes;
- **lagging** clients (zipf-distributed epoch lag) pull
  ``GET /filter/delta/<theirs>/<latest>``, validate each link against
  the chain manifest, and replay — falling back to a full pull when
  the chain is anchored/evicted away (404);
- **cold** clients full-pull ``GET /filter`` with gzip negotiation
  (a configurable fraction pulls an upstream container instead).

Reports bytes-on-wire against the full-pull counterfactual, the 304
ratio, and latency percentiles. A scaled-down leg gates in tier-1
via ``bench.run_distrib_smoke``; the full 10K-client run is recorded
in BENCHLOG.

    python tools/pullstorm.py --clients 10000 --epochs 6 --workers 2
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import queue
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_epoch_blobs(n_epochs: int, groups: int, per_group: int,
                      churn: int, seed: int) -> list[bytes]:
    """Deterministic epoch sequence: ``groups`` (issuer, expDate)
    sets, ``churn`` groups gaining serials per epoch (the crlite
    shape: most groups untouched epoch to epoch)."""
    from ct_mapreduce_tpu.filter import build_artifact

    rng = np.random.default_rng(seed)
    sets = {
        (f"issuer-{g:03d}", 500_000 + 24 * g): {
            bytes([g % 251, s % 251, 7])
            + bytes([int(x) for x in rng.integers(0, 256, 3)])
            for s in range(per_group)
        }
        for g in range(groups)
    }
    blobs = []
    for e in range(n_epochs):
        if e:
            keys = sorted(sets)
            for i in range(churn):
                key = keys[(e * churn + i) % len(keys)]
                sets[key] = set(sets[key]) | {
                    bytes([e % 251, i % 251])
                    + bytes([int(x) for x in rng.integers(0, 256, 3)])
                    for _ in range(max(1, per_group // 10))}
        blobs.append(build_artifact(sets, fp_rate=0.01,
                                    use_device=False).to_bytes())
    return blobs


def start_fleet(blobs: list[bytes], workers: int,
                max_chain: int) -> list:
    """W serving workers, each fed every epoch through the fleet
    fan-out path. Returns the started QueryServers."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.serve.server import QueryServer

    servers = []
    for _ in range(workers):
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
        agg.enable_filter_capture()
        srv = QueryServer(agg, 0, filter_first=True,
                          max_delta_chain=max_chain,
                          distrib_history=len(blobs) + 1).start()
        for e, blob in enumerate(blobs):
            srv.oracle.publish_artifact(e, blob, source="fleet")
        servers.append(srv)
    return servers


def verify_fleet_parity(bases: list[str]) -> dict:
    """Every worker serves byte-identical artifacts: full, every
    container, and the manifest's latest hash. Returns the reference
    payload sizes."""
    fulls, etags = [], []
    for base in bases:
        r = urllib.request.urlopen(base + "/filter")
        fulls.append(r.read())
        etags.append(r.headers["ETag"])
    if len({f for f in fulls}) != 1 or len(set(etags)) != 1:
        raise RuntimeError("workers serve DIFFERENT full artifacts")
    sizes = {"full": len(fulls[0]), "etag": etags[0]}
    man = json.loads(urllib.request.urlopen(
        bases[0] + "/filter/manifest").read())
    for kind in man["containers"]:
        payloads = []
        for base in bases:
            payloads.append(urllib.request.urlopen(
                f"{base}/filter/container/{kind}").read())
        if len(set(payloads)) != 1:
            raise RuntimeError(f"workers serve DIFFERENT {kind} "
                               f"containers")
        sizes[kind] = len(payloads[0])
    sizes["manifest"] = man
    return sizes


def run_storm(clients: int = 10_000, epochs: int = 5, groups: int = 40,
              per_group: int = 50, churn: int = 2, workers: int = 2,
              threads: int = 32, max_chain: int = 4,
              cold_fraction: float = 0.05,
              container_fraction: float = 0.2, zipf_a: float = 1.6,
              seed: int = 20260805, validate_every: int = 50,
              force_zstd: bool = False) -> dict:
    """The full storm. Returns the report dict (also printed as JSON
    by the CLI). ``force_zstd`` makes every compressible pull demand
    zstd and fails loudly when the fleet can't serve it (validating
    the zstd wire leg — ROADMAP item 4(c); needs the optional
    ``zstandard`` module on BOTH ends)."""
    from ct_mapreduce_tpu.distrib import (
        ChainManifest,
        apply_chain,
        split_bundle,
    )

    blobs = build_epoch_blobs(epochs, groups, per_group, churn, seed)
    servers = start_fleet(blobs, workers, max_chain)
    bases = [f"http://127.0.0.1:{s.port}" for s in servers]
    try:
        sizes = verify_fleet_parity(bases)
        man = sizes.pop("manifest")
        latest = man["latestEpoch"]
        manifest = ChainManifest.from_json(man)
        latest_etag = sizes["etag"]
        full_size = sizes["full"]

        if force_zstd:
            from ct_mapreduce_tpu.distrib.publish import zstd_available

            if "zstd" not in man["encodings"] or not zstd_available():
                raise RuntimeError(
                    "--force-zstd: the fleet does not advertise zstd "
                    "(install the optional `zstandard` module)")
            import zstandard as _zstd_mod

            accept = "zstd"

            def _decode(body: bytes, encoding) -> bytes:
                if encoding != "zstd":
                    raise RuntimeError(
                        f"--force-zstd: server answered "
                        f"Content-Encoding={encoding!r}, wanted zstd")
                return _zstd_mod.ZstdDecompressor().decompress(body)
        else:
            accept = "gzip"

            def _decode(body: bytes, encoding) -> bytes:
                return (gzip.decompress(body) if encoding == "gzip"
                        else body)

        # Client plan: zipf epoch lag (0 = warm), a cold slice, a
        # container-pulling slice of the colds.
        rng = np.random.default_rng(seed + 1)
        lags = (rng.zipf(zipf_a, size=clients) - 1).clip(0, epochs - 1)
        cold = rng.random(clients) < cold_fraction
        wants_container = rng.random(clients) < container_fraction
        kinds = sorted(k for k in sizes if k not in ("full", "etag"))

        tasks: queue.Queue = queue.Queue()
        for i in range(clients):
            tasks.put(i)
        lock = threading.Lock()
        results = []
        errors = []

        def one_pull(i: int) -> tuple:
            base = bases[i % len(bases)]
            t0 = time.monotonic()
            if cold[i]:
                if wants_container[i] and kinds:
                    kind = kinds[i % len(kinds)]
                    r = urllib.request.urlopen(
                        f"{base}/filter/container/{kind}")
                    return "container", len(r.read()), t0
                req = urllib.request.Request(
                    base + "/filter",
                    headers={"Accept-Encoding": accept})
                r = urllib.request.urlopen(req)
                body = r.read()
                # Client really can use the negotiated encoding.
                _decode(body, r.headers.get("Content-Encoding"))
                return "full", len(body), t0
            lag = int(lags[i])
            if lag == 0:
                req = urllib.request.Request(
                    base + "/filter",
                    headers={"If-None-Match": latest_etag})
                try:
                    r = urllib.request.urlopen(req)
                    return "full", len(r.read()), t0  # ETag rotated
                except urllib.error.HTTPError as err:
                    if err.code != 304:
                        raise
                    err.read()
                    return "304", 0, t0
            try:
                req = urllib.request.Request(
                    f"{base}/filter/delta/{latest - lag}/{latest}",
                    headers={"Accept-Encoding": accept})
                r = urllib.request.urlopen(req)
                wire = r.read()
                bundle = _decode(wire,
                                 r.headers.get("Content-Encoding"))
            except urllib.error.HTTPError as err:
                if err.code != 404:
                    raise
                err.read()
                # Anchored/evicted out: the documented fallback.
                r = urllib.request.urlopen(base + "/filter")
                return "fallback_full", len(r.read()), t0
            if i % validate_every == 0:
                links = split_bundle(bundle)
                manifest.validate_chain(latest - lag, latest, links)
                if apply_chain(blobs[latest - lag], links) \
                        != blobs[latest]:
                    raise RuntimeError(
                        f"delta replay mismatch (lag {lag})")
            return "delta", len(wire), t0

        def worker_loop():
            while True:
                try:
                    i = tasks.get_nowait()
                except queue.Empty:
                    return
                try:
                    kind, n_bytes, t0 = one_pull(i)
                    dt = time.monotonic() - t0
                    with lock:
                        results.append((kind, n_bytes, dt))
                except Exception as err:  # noqa: BLE001 — report, don't hang
                    with lock:
                        errors.append(f"client {i}: "
                                      f"{type(err).__name__}: {err}")

        t_start = time.monotonic()
        pool = [threading.Thread(target=worker_loop, daemon=True)
                for _ in range(threads)]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        wall = time.monotonic() - t_start
        if errors:
            raise RuntimeError(
                f"{len(errors)} client failures, first: {errors[0]}")

        by_kind: dict = {}
        for kind, n_bytes, _ in results:
            cnt, tot = by_kind.get(kind, (0, 0))
            by_kind[kind] = (cnt + 1, tot + n_bytes)
        lat = np.array(sorted(dt for _, _, dt in results))
        bytes_on_wire = sum(tot for _, tot in by_kind.values())
        n304 = by_kind.get("304", (0, 0))[0]
        n_delta, delta_bytes = by_kind.get("delta", (0, 0))
        counterfactual = len(results) * full_size
        d3_clients = n304 + n_delta
        d3_bytes = delta_bytes  # 304s add zero body bytes
        report = {
            "clients": len(results),
            "workers": workers,
            "epochs": epochs,
            "full_artifact_bytes": full_size,
            "pulls": {k: {"count": c, "bytes": b}
                      for k, (c, b) in sorted(by_kind.items())},
            "ratio_304": round(n304 / max(1, len(results)), 4),
            "bytes_on_wire": bytes_on_wire,
            "counterfactual_full_bytes": counterfactual,
            "wire_vs_counterfactual": round(
                bytes_on_wire / max(1, counterfactual), 4),
            "delta_304_clients": d3_clients,
            "delta_304_bytes": d3_bytes,
            "delta_304_counterfactual": d3_clients * full_size,
            "delta_304_vs_full": round(
                d3_bytes / max(1, d3_clients * full_size), 4),
            "p50_ms": round(float(lat[len(lat) // 2]) * 1e3, 3),
            "p99_ms": round(float(lat[int(len(lat) * 0.99)]) * 1e3, 3),
            "wall_s": round(wall, 3),
            "pulls_per_s": round(len(results) / max(wall, 1e-9), 1),
            "worker_parity": 1,
            "zstd_available": "zstd" in man["encodings"],
        }
        return report
    finally:
        for s in servers:
            s.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="pullstorm")
    p.add_argument("--clients", type=int, default=10_000)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--groups", type=int, default=40)
    p.add_argument("--per-group", type=int, default=50)
    p.add_argument("--churn", type=int, default=2)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--threads", type=int, default=32)
    p.add_argument("--max-chain", type=int, default=4)
    p.add_argument("--cold", type=float, default=0.05)
    p.add_argument("--containers", type=float, default=0.2)
    p.add_argument("--zipf", type=float, default=1.6)
    p.add_argument("--seed", type=int, default=20260805)
    p.add_argument("--force-zstd", action="store_true",
                   help="every compressible pull demands zstd; fails "
                        "when the optional zstandard module is absent "
                        "(validates the zstd wire leg)")
    args = p.parse_args(argv)
    report = run_storm(
        clients=args.clients, epochs=args.epochs, groups=args.groups,
        per_group=args.per_group, churn=args.churn,
        workers=args.workers, threads=args.threads,
        max_chain=args.max_chain, cold_fraction=args.cold,
        container_fraction=args.containers, zipf_a=args.zipf,
        seed=args.seed, force_zstd=args.force_zstd)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
