"""Capture a jax.profiler trace of the fused step and print top ops.

Runs a few full-step sweeps under ``jax.profiler.trace`` on the real
chip (same honest structure as stagecost's `full` stage), then parses
the captured xplane proto with the installed xprof/tensorboard-profile
tooling and prints the top device ops by self time — the op-level
truth that stage-subtraction probes cannot see.

Run:  python tools/profstep.py [batch] [outdir]
"""

from __future__ import annotations

import functools
import glob
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def capture(batch: int, outdir: str) -> None:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import pipeline
    from ct_mapreduce_tpu.utils import syncerts

    cap = 1 << int(os.environ.get("CT_SC_LOG2_CAP", "26"))
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}); batch={batch}")

    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, 1024)
    issuer_idx = jax.device_put(np.zeros((batch,), np.int32))
    valid = jax.device_put(np.ones((batch,), bool))
    epoch_cols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)
    no_cn = np.zeros((0, 32), np.uint8)
    no_cn_lens = np.zeros((0, 2), np.int32)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def mega(table, acc, n_sweeps, datas, lens, issuer_idx, valid):
        def body(s, carry):
            table, acc = carry
            e = acc + jnp.uint32(s)
            eb = jnp.stack(
                [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF,
                 e & 0xFF]).astype(jnp.uint8)
            data = datas[0].at[:, epoch_cols].set(eb[None, :])
            table, out = pipeline.ingest_core(
                table, data, lens[0], issuer_idx, valid,
                jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
                no_cn, no_cn_lens)
            return table, acc + out.was_unknown.sum().astype(jnp.uint32)
        return jax.lax.fori_loop(0, n_sweeps, body, (table, acc))

    fetch = jax.jit(lambda a: a + jnp.uint32(0))
    table = pipeline.make_table(cap)
    acc = jax.device_put(np.uint32(0))
    t0 = time.perf_counter()
    table, acc = mega(table, acc, np.int32(1), datas, lens, issuer_idx, valid)
    int(fetch(acc))
    say(f"compile+warmup {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    with jax.profiler.trace(outdir):
        table, acc = mega(table, acc, np.int32(4), datas, lens,
                          issuer_idx, valid)
        int(fetch(acc))
    say(f"profiled 4 sweeps in {time.perf_counter() - t0:.1f}s")


def report(outdir: str, top: int = 40) -> None:
    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        say(f"no xplane.pb under {outdir}")
        return
    path = max(paths, key=os.path.getmtime)
    say(f"parsing {path}")
    from xprof.convert import _pywrap_profiler_plugin as pp

    try:
        raw = pp.xspace_to_tools_data([path], "op_profile")
    except Exception as err:
        say(f"op_profile failed ({err}); trying overview")
        raw = pp.xspace_to_tools_data([path], "overview_page")
    data = raw[0] if isinstance(raw, tuple) else raw
    out = data.decode("utf-8", "replace") if isinstance(data, bytes) else str(data)
    print(out[: 20000])


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    outdir = sys.argv[2] if len(sys.argv) > 2 else "/tmp/ctmr_trace"
    if os.environ.get("CT_PROF_REPORT_ONLY") != "1":
        capture(batch, outdir)
    report(outdir)


if __name__ == "__main__":
    main()
