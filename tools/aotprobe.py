"""Compile-tax experiment: can jax.export serialization cut the cold
start (VERDICT r04 #6)?

The headline step compiles ~200s cold in EVERY process on the tunneled
remote compiler, and jax's persistent compilation cache was measured
SLOWER (306.8s vs 198.8s, r04). This probe measures the other standard
route — ``jax.export`` StableHLO serialization: process A exports the
insert-sweep program (the representative ~160s compile) to disk,
process B deserializes and calls it, and both report time-to-first-
result. If the remote compiler is the cost (as the cache result
suggests), deserialization won't help either — but then the negative
result is recorded with numbers, closing the VERDICT item honestly.

  python tools/aotprobe.py save /tmp/ins.bin   # trace+compile+serialize
  python tools/aotprobe.py load /tmp/ins.bin   # deserialize+first call
  python tools/aotprobe.py cold                # baseline: plain compile
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH = 1 << 20
CAP = 1 << 26


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _register(jexport) -> None:
    from ct_mapreduce_tpu.ops import buckettable, hashtable

    try:
        jexport.register_namedtuple_serialization(
            buckettable.BucketTable, serialized_name="ctmr.BucketTable")
        jexport.register_namedtuple_serialization(
            hashtable.TableState, serialized_name="ctmr.TableState")
    except ValueError:
        pass  # already registered in this process


def build():
    import functools

    import jax
    import jax.numpy as jnp

    from ct_mapreduce_tpu.ops import pipeline

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def mega(table, acc, epoch_base, n_sweeps, lane, meta, valid):
        def keygen(e):
            a = lane * jnp.uint32(0x9E3779B9) + e * jnp.uint32(0x85EBCA6B)
            b = (a ^ (a >> 15)) * jnp.uint32(0xC2B2AE35)
            c = (b ^ (b >> 13)) * jnp.uint32(0x27D4EB2F)
            d = (c ^ (c >> 16)) * jnp.uint32(0x165667B1)
            return jnp.stack([a ^ e, b, c, d], axis=1)

        def body(s, carry):
            table, acc = carry
            keys = keygen((epoch_base + s).astype(jnp.uint32))
            table, unknown, ovf = pipeline.table_insert(
                table, keys, meta, valid)
            return table, (acc + unknown.sum(dtype=jnp.int32)
                           + ovf.sum(dtype=jnp.int32))

        return jax.lax.fori_loop(0, n_sweeps, body, (table, acc))

    return mega


def args_for():
    import jax

    from ct_mapreduce_tpu.ops import buckettable

    table = buckettable.make_table(CAP)
    acc = jax.device_put(np.int32(0))
    lane = jax.device_put(np.arange(BATCH, dtype=np.uint32))
    meta = jax.device_put(np.zeros((BATCH,), np.uint32))
    valid = jax.device_put(np.ones((BATCH,), bool))
    return table, acc, np.uint32(0), np.int32(1), lane, meta, valid


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    mode = sys.argv[1] if len(sys.argv) > 1 else "cold"
    path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/aot_insert.bin"

    t_start = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) in "
        f"{time.perf_counter() - t_start:.1f}s; mode={mode}")

    fetch = jax.jit(lambda a: a + a.dtype.type(0))

    if mode == "save":
        from jax import export as jexport

        from ct_mapreduce_tpu.ops import buckettable, hashtable

        _register(jexport)
        mega = build()
        a = args_for()
        t0 = time.perf_counter()
        exp = jexport.export(mega)(*a)
        t_trace = time.perf_counter() - t0
        blob = exp.serialize()
        with open(path, "wb") as fh:
            fh.write(blob)
        say(f"export+serialize: {t_trace:.1f}s, {len(blob)} bytes -> {path}")
        # First real call from the exported artifact in THIS process.
        t0 = time.perf_counter()
        table, acc = exp.call(*a)
        int(fetch(acc))
        say(f"first call via export artifact: {time.perf_counter() - t0:.1f}s")
    elif mode == "load":
        from jax import export as jexport

        from ct_mapreduce_tpu.ops import buckettable, hashtable

        _register(jexport)
        t0 = time.perf_counter()
        with open(path, "rb") as fh:
            exp = jexport.deserialize(fh.read())
        t_de = time.perf_counter() - t0
        a = args_for()
        t0 = time.perf_counter()
        table, acc = exp.call(*a)
        int(fetch(acc))
        t_first = time.perf_counter() - t0
        say(f"deserialize: {t_de:.1f}s; first call (incl. any backend "
            f"compile): {t_first:.1f}s; total-to-first-result "
            f"{time.perf_counter() - t_start:.1f}s")
    else:  # cold baseline
        mega = build()
        a = args_for()
        t0 = time.perf_counter()
        table, acc = mega(*a)
        int(fetch(acc))
        say(f"cold jit compile+first result: {time.perf_counter() - t0:.1f}s")


if __name__ == "__main__":
    main()
