"""Honest per-stage cost of the fused step, bench-methodology edition.

tools/microbench.py times stages with `jax.block_until_ready`, which
this stack does not reliably honor (BENCHLOG round-1 postmortem) — its
per-stage numbers can be off by orders of magnitude (0.18 ms for a
1 GB parse = 5.8 TB/s, 7x the chip's HBM). This probe times each stage
the way bench.py times the headline: the stage runs inside a jitted
`lax.fori_loop` sweep whose input is re-stamped per sweep (so nothing
is loop-invariant), accumulates a scalar that depends on every stage
output (so nothing is dead), and every chunk ends with a synchronous
device-value read. Stage deltas then give real per-stage costs:

  read    — one full HBM pass over the stamped uint8[B, L] batch
  pack    — + word-pack into uint32 rows
  parse   — + the DER walker (offsets, lengths, flags)
  serial  — + serial TLV gather to uint8[B, 46]
  sha     — + fingerprint block build + SHA-256
  lanes   — the full communication-free prefix (local_lanes)
  full    — ingest_core (adds the dedup-table insert, donated state)
  preparsed — the pre-parsed lane's whole device step (fingerprint +
            insert + compact readback from host-extracted sidecars;
            compare against `full` — the delta is what the host-side
            sidecar extraction buys the device)
  decode  — the HOST feed (native wire decode + sidecar extraction),
            swept over intra-chunk thread counts {1, 2, 4, cpu_count}
            with byte-exact parity asserted at each point; ns/entry
            per thread count is the host-feed scaling curve
            (CT_SC_DECODE_N overrides the wire batch size).
  dispatch — per-chunk Python-dispatch + H2D overhead of the staged
            device queue: the same 8 chunks run as 8/K resident
            envelopes at K ∈ {1, 2, 4, 8} chunks/dispatch
            (pipeline.staged_core), each dispatch paying one
            device_put + one jit call; byte parity of the packed
            readbacks and the final table is asserted against K=1.
            The wall delta across K is the per-dispatch toll that
            staging amortizes (CT_SC_DISPATCH_B overrides the chunk
            lane count).
  ckpt    — checkpoint-plane walls (round 22): full ck01 save vs
            incremental CTMRCK02 tick at churn {0.1%, 1%, 10%} of the
            fixture, restore wall at several chain depths; restored-
            state parity (tune.harness.ckpt_state_digest) asserted at
            every point against a ck01 oracle (CT_SC_CKPT_ENTRIES /
            _BITS / _CHURN / _DEPTHS override the fixture and sweeps).
  verify  — the batched ECDSA-P256 verification kernel
            (ops/ecdsa.verify_p256) at B ∈ {256, 1024, 4096}:
            ns/signature per batch width on a mixed valid/invalid
            corpus, verdict parity asserted against the pure-python
            host verifier at every width. The curve is the
            amortized-dispatch story — per-op overhead inside the
            256-bit ladder is fixed per op, so wider batches spread
            it over more lanes (CT_SC_VERIFY_B overrides the width
            list, comma-separated).

Run:  python tools/stagecost.py [batch] [stage ...]
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import buckettable, der_kernel, hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    # The `full` stage builds whichever table layout the aggregator
    # would (CTMR_TABLE, default bucket) — ingest_core dispatches.
    if os.environ.get("CTMR_TABLE", "bucket").strip().lower() == "open":
        mk_table = hashtable.make_table
    else:
        mk_table = buckettable.make_table

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    only = set(sys.argv[2:])
    pad_len = int(os.environ.get("CT_SC_PADLEN", "1024"))
    cap = 1 << int(os.environ.get("CT_SC_LOG2_CAP", "26"))
    exec_target_s = float(os.environ.get("CT_SC_EXEC_SECS", "4.0"))

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) acquired in "
        f"{time.perf_counter() - t0:.1f}s; batch={batch} pad={pad_len}")

    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    issuer_idx = jax.device_put(np.zeros((batch,), np.int32))
    valid = jax.device_put(np.ones((batch,), bool))
    epoch_cols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)
    now_hour = 500_000
    no_cn = np.zeros((0, 32), np.uint8)
    no_cn_lens = np.zeros((0, 2), np.int32)

    def stamp(data, e):
        eb = jnp.stack(
            [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF, e & 0xFF]
        ).astype(jnp.uint8)
        return data.at[:, epoch_cols].set(eb[None, :])

    # Each stage maps the stamped batch to a uint32 scalar that depends
    # on every output it claims to compute (keeps the work live under
    # DCE while adding only a reduce).
    def s_read(data, length):
        return data.astype(jnp.uint32).sum()

    def s_pack(data, length):
        return der_kernel.pack_rows(data).words.sum()

    def s_pack2(data, length):
        # Experimental formulation: bitcast u8[B, L] -> u32[B, L/4]
        # (little-endian grouping) + in-register byteswap to the
        # big-endian words pack_rows produces via strided slices.
        # MEASURED SLOWER on v5e (36 vs 22 ns/entry standalone,
        # 2026-07-31) — kept as the recorded negative result; the
        # strided-slice pack_rows stays the shipping formulation.
        le = jax.lax.bitcast_convert_type(
            data.reshape(data.shape[0], -1, 4), jnp.uint32)
        be = ((le & 0xFF) << 24) | ((le & 0xFF00) << 8) \
            | ((le >> 8) & 0xFF00) | (le >> 24)
        return be.sum()

    def _parse(data, length):
        rows = der_kernel.pack_rows(data)
        p = der_kernel.parse_certs_rows(rows, length, scan_issuer_cn=False)
        return rows, p

    def s_parse(data, length):
        _, p = _parse(data, length)
        return (
            p.serial_off + p.serial_len + p.not_after_hour
            + p.ok.astype(jnp.int32) + p.is_ca.astype(jnp.int32)
            + p.crldp_off + p.issuer_off
        ).astype(jnp.uint32).sum()

    def s_serial(data, length):
        rows, p = _parse(data, length)
        serials, fits = der_kernel.gather_serials_rows(
            rows, p.serial_off, p.serial_len, packing.MAX_SERIAL_BYTES)
        return (serials.astype(jnp.uint32).sum()
                + fits.astype(jnp.uint32).sum()
                + p.not_after_hour.astype(jnp.uint32).sum())

    def s_sha(data, length):
        rows, p = _parse(data, length)
        serials, fits = der_kernel.gather_serials_rows(
            rows, p.serial_off, p.serial_len, packing.MAX_SERIAL_BYTES)
        fps = pipeline.fingerprints(
            issuer_idx, p.not_after_hour, serials, p.serial_len)
        return fps.sum() + fits.astype(jnp.uint32).sum()

    def s_lanes(data, length):
        lanes = pipeline.local_lanes(
            data, length, issuer_idx, valid, jnp.int32(now_hour),
            jnp.int32(packing.DEFAULT_BASE_HOUR), no_cn, no_cn_lens,
            packing.MAX_ISSUERS)
        return (lanes.fps.sum() + lanes.meta.sum()
                + lanes.insertable.astype(jnp.uint32).sum()
                + lanes.serials.astype(jnp.uint32).sum())

    def run_stage(name, stage_fn):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def mega(acc, n_sweeps, datas, lens):
            def body(s, acc):
                data = stamp(datas[0], acc % jnp.uint32(1 << 20)
                             + jnp.uint32(s))
                return acc + stage_fn(data, lens[0])
            return jax.lax.fori_loop(0, n_sweeps, body, acc)

        fetch = jax.jit(lambda a: a + jnp.uint32(0))
        acc = jax.device_put(np.uint32(0))
        t0 = time.perf_counter()
        acc = mega(acc, np.int32(1), datas, lens)
        int(fetch(acc))
        say(f"  {name}: compile+warmup {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        acc = mega(acc, np.int32(1), datas, lens)
        int(fetch(acc))
        per_sweep = max(time.perf_counter() - t0, 1e-4)
        n = max(2, min(int(exec_target_s / per_sweep), 200))
        t0 = time.perf_counter()
        acc = mega(acc, np.int32(n), datas, lens)
        int(fetch(acc))
        dt = (time.perf_counter() - t0) / n
        say(f"{name:7s} {dt * 1e3:9.2f} ms/sweep  "
            f"{dt / batch * 1e9:8.1f} ns/entry  ({n} sweeps)")
        return dt

    def run_full():
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def mega(table, acc, n_sweeps, datas, lens, issuer_idx, valid):
            def body(s, carry):
                table, acc = carry
                data = stamp(datas[0], acc + jnp.uint32(s))
                table, out = pipeline.ingest_core(
                    table, data, lens[0], issuer_idx, valid,
                    jnp.int32(now_hour),
                    jnp.int32(packing.DEFAULT_BASE_HOUR), no_cn, no_cn_lens)
                return table, acc + out.was_unknown.sum().astype(jnp.uint32)
            return jax.lax.fori_loop(0, n_sweeps, body, (table, acc))

        fetch = jax.jit(lambda a: a + jnp.uint32(0))
        table = mk_table(cap)
        acc = jax.device_put(np.uint32(0))
        t0 = time.perf_counter()
        table, acc = mega(table, acc, np.int32(1), datas, lens,
                          issuer_idx, valid)
        int(fetch(acc))
        say(f"  full: compile+warmup {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        table, acc = mega(table, acc, np.int32(1), datas, lens,
                          issuer_idx, valid)
        int(fetch(acc))
        per_sweep = max(time.perf_counter() - t0, 1e-4)
        budget = max(1, int(cap * 0.5) // batch - 3)
        n = max(2, min(int(exec_target_s / per_sweep), budget, 200))
        t0 = time.perf_counter()
        table, acc = mega(table, acc, np.int32(n), datas, lens,
                          issuer_idx, valid)
        int(fetch(acc))
        dt = (time.perf_counter() - t0) / n
        say(f"{'full':7s} {dt * 1e3:9.2f} ms/sweep  "
            f"{dt / batch * 1e9:8.1f} ns/entry  ({n} sweeps)")
        return dt

    def run_preparsed():
        """The walker-free step, timed with the headline methodology:
        host-shaped compact inputs resident on device, the serial
        epoch window restamped per sweep (unique identities, all-fresh
        inserts), one fori_loop execution per chunk, synchronous value
        read. Its rate vs `full` is the pre-parsed lane's device-side
        win (the ISSUE-7 acceptance gate runs exactly this on CPU)."""
        rows = np.asarray(datas[0] if hasattr(datas, "shape") else datas[0])
        rows = np.asarray(rows, np.uint8)
        s = packing.MAX_SERIAL_BYTES
        cols = tpl.serial_off + np.arange(s)
        serials0 = rows[:, cols].copy()
        serials0[:, tpl.serial_len:] = 0
        serials_s = serials0[None]  # [K=1, B, 46]
        slen = np.full((1, batch), tpl.serial_len, np.int32)
        nah = np.full((1, batch), packing.DEFAULT_BASE_HOUR + 1000,
                      np.int32)
        iidx = np.zeros((1, batch), np.int32)
        ins = np.ones((1, batch), bool)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def mega(table, acc, n_sweeps, serials, slen, nah, iidx, ins):
            def body(sw, carry):
                table, acc = carry
                e = acc + jnp.uint32(sw) + jnp.uint32(1)
                eb = jnp.stack(
                    [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF,
                     e & 0xFF]).astype(jnp.uint8)
                # Epoch window at serial bytes 4..8 (the headline's
                # schema); the lane counter sits in the last 4 bytes.
                sers = serials.at[:, :, 4:8].set(eb[None, None, :])
                table, out = pipeline.preparsed_core(
                    table, sers, slen, nah, iidx, ins,
                    jnp.int32(packing.DEFAULT_BASE_HOUR))
                return table, acc + out.packed[:, 0].sum().astype(jnp.uint32)
            return jax.lax.fori_loop(0, n_sweeps, body, (table, acc))

        fetch = jax.jit(lambda a: a + jnp.uint32(0))
        table = mk_table(cap)
        acc = jax.device_put(np.uint32(0))
        t0 = time.perf_counter()
        table, acc = mega(table, acc, np.int32(1), serials_s, slen, nah,
                          iidx, ins)
        int(fetch(acc))
        say(f"  preparsed: compile+warmup {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        table, acc = mega(table, acc, np.int32(1), serials_s, slen, nah,
                          iidx, ins)
        int(fetch(acc))
        per_sweep = max(time.perf_counter() - t0, 1e-4)
        budget = max(1, int(cap * 0.5) // batch - 3)
        n = max(2, min(int(exec_target_s / per_sweep), budget, 200))
        t0 = time.perf_counter()
        table, acc = mega(table, acc, np.int32(n), serials_s, slen, nah,
                          iidx, ins)
        int(fetch(acc))
        dt = (time.perf_counter() - t0) / n
        say(f"{'prepar.':7s} {dt * 1e3:9.2f} ms/sweep  "
            f"{dt / batch * 1e9:8.1f} ns/entry  ({n} sweeps)")
        return dt

    def run_decode():
        """Host decode + sidecar throughput vs intra-chunk threads.

        Pure host work (no device involved): one wire batch decoded at
        each thread count through the native worker pool, best-of-3,
        with BYTE-EXACT parity asserted against threads=1 at every
        point — the scaling number is only meaningful if the parallel
        split is invisible in the outputs."""
        from ct_mapreduce_tpu.native import available as nat_available
        from ct_mapreduce_tpu.native import leafpack

        if not nat_available():
            say("decode  skipped: native library unavailable")
            return None
        n = int(os.environ.get("CT_SC_DECODE_N", str(min(batch, 1 << 16))))
        tpls = [syncerts.make_template(issuer_cn=f"Decode {k}")
                for k in range(2)]
        t0 = time.perf_counter()
        lis, eds = syncerts.make_wire_batch(tpls, 0, n)
        say(f"  decode: wire setup {time.perf_counter() - t0:.1f}s "
            f"({n} entries)")
        cpu = os.cpu_count() or 1
        base = None
        curve = {}
        for t in sorted({1, 2, 4, cpu}):
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                dec = leafpack.decode_raw_batch(lis, eds, 1024, threads=t)
                sc = leafpack.extract_sidecars(dec.data, dec.length,
                                               threads=t)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            if base is None:
                base = (dec, sc, best)
            else:
                for fld in ("data", "length", "timestamp_ms",
                            "entry_type", "status", "issuer_group"):
                    assert np.array_equal(
                        getattr(base[0], fld), getattr(dec, fld)), (
                        f"decode threads={t}: {fld} diverged from "
                        "threads=1")
                assert base[0].group_issuers == dec.group_issuers
                for fld in vars(base[1]):
                    assert np.array_equal(
                        getattr(base[1], fld), getattr(sc, fld)), (
                        f"sidecar threads={t}: {fld} diverged from "
                        "threads=1")
            curve[t] = best
            speedup = base[2] / best
            say(f"decode  t={t:<3d} {best * 1e3:9.2f} ms/batch  "
                f"{best / n * 1e9:8.1f} ns/entry  ({speedup:.2f}x vs t=1, "
                "parity exact)")
        return curve

    def run_dispatch():
        """Staged-envelope K-curve: fixed total work (8 chunks of B
        lanes), varying chunks/dispatch. Every dispatch is the REAL
        production shape — host rows → one device_put → one
        ingest_step_staged call — so the K=1 vs K=8 wall delta is
        exactly the per-dispatch Python + H2D + readback toll the
        staging ring amortizes. Byte parity (packed readbacks + final
        table rows) is asserted against K=1 at every point.

        Since round 21 the corpus build and the per-K sweep live in
        tune.harness (shared with the autotuner's staging provider)."""
        from ct_mapreduce_tpu.tune import harness

        b = int(os.environ.get("CT_SC_DISPATCH_B", "1024"))
        n_chunks = 8
        corpus = harness.staged_dispatch_corpus(b=b, n_chunks=n_chunks,
                                                pad_len=pad_len)
        say(f"  dispatch: {n_chunks} chunks x {b} lanes, pad {pad_len}")

        base = None
        for k in (1, 2, 4, 8):
            harness.staged_dispatch_run(corpus, k, mk_table=mk_table)
            best = None
            for _ in range(3):
                dt, packed, rows = harness.staged_dispatch_run(
                    corpus, k, mk_table=mk_table)
                best = dt if best is None else min(best, dt)
            if base is None:
                base = (packed, rows, best)
            else:
                assert np.array_equal(base[0], packed), (
                    f"dispatch K={k}: packed readback diverged from K=1")
                assert np.array_equal(base[1], rows), (
                    f"dispatch K={k}: table rows diverged from K=1")
            per_chunk = best / n_chunks
            say(f"dispatch K={k:<2d} {best * 1e3:9.2f} ms/8chunks  "
                f"{per_chunk * 1e3:8.2f} ms/chunk  "
                f"{per_chunk / b * 1e9:8.1f} ns/entry  "
                f"({base[2] / best:.2f}x vs K=1, parity exact)")

    def run_verify():
        """Device ns/signature: precompute on/off × window-size curve
        at each batch width, plus a P-384 leg — host-parity asserted
        at EVERY (curve, window, width) point.

        Methodology matches the headline: jitted kernel, warmup run
        (compile + table builds excluded), best-of-3 timed runs each
        ending in the synchronous verdict readback. Window > 0 legs
        measure the lane's steady state: G/Q tables device-resident
        before the timed region (100% qtable hits — the production
        regime under <100 log keys). The corpus tiles 64 unique
        signatures under 7 distinct keys (3/4 valid, 1/4 mutated) so
        host-side generation stays cheap at B=4096.

        Since round 21 the corpus build and the per-point measurement
        (tables, warmup, best-of-3, host parity every run) live in
        tune.harness — shared with the autotuner's verify provider.

        Env: CT_SC_VERIFY_B (widths, default 256,1024,4096),
        CT_SC_VERIFY_W (windows, default 0,2,4,8; 0 = legacy ladder),
        CT_SC_VERIFY_P384_B (P-384 widths, default 256; empty
        disables), CT_SC_VERIFY_P384_W (default 0,8)."""
        from ct_mapreduce_tpu.ops import ecdsa
        from ct_mapreduce_tpu.tune import harness

        def sweep(ops, widths, windows, n_uniq=64, n_keys=7):
            corpus = harness.verify_corpus(ops, n_uniq, n_keys)
            for w in widths:
                base_ns = None
                for win in windows:
                    tr = harness.verify_point(ops, w, win, corpus,
                                              reps=3)
                    say(f"  verify {ops.name} B={w} w={win}: "
                        f"compile+warmup {tr.compile_s:.1f}s")
                    ns = tr.best / w * 1e9
                    if base_ns is None:
                        base_ns = ns
                    say(f"verify  {ops.name} B={w:<5d} w={win:<2d} "
                        f"{tr.best * 1e3:9.2f} ms/batch  "
                        f"{ns:12.1f} ns/sig"
                        f"  ({base_ns / ns:.2f}x vs w={windows[0]}, "
                        f"parity exact)")

        widths = [int(w) for w in os.environ.get(
            "CT_SC_VERIFY_B", "256,1024,4096").split(",") if w]
        windows = [int(w) for w in os.environ.get(
            "CT_SC_VERIFY_W", "0,2,4,8").split(",") if w != ""]
        sweep(ecdsa.P256_OPS, widths, windows)
        p384_b = [int(w) for w in os.environ.get(
            "CT_SC_VERIFY_P384_B", "256").split(",") if w]
        p384_w = [int(w) for w in os.environ.get(
            "CT_SC_VERIFY_P384_W", "0,8").split(",") if w != ""]
        if p384_b:
            sweep(ecdsa.P384_OPS, p384_b, p384_w, n_uniq=16, n_keys=3)

    def run_ckpt():
        """Checkpoint-plane cost (CTMRCK02, round 22): full ck01 save
        wall vs incremental ck02 tick wall at churn ∈ {0.1%, 1%, 10%}
        of the fixture, plus restore wall at several chain depths —
        restored-state parity (tune.harness.ckpt_state_digest)
        asserted at every point, against both the live writer and a
        ck01 oracle save of the same state.

        The fixture pre-fills via the bulk path (setup, untimed);
        churn folds through the pre-parsed lane so the per-tick dirty
        log records it exactly as production folds would.

        Env: CT_SC_CKPT_ENTRIES (default 10**7), CT_SC_CKPT_BITS
        (table log2 capacity, default 25), CT_SC_CKPT_CHURN (default
        0.001,0.01,0.1), CT_SC_CKPT_DEPTHS (restore chain depths,
        default 1,4,8). CT_SC_CKPT_STATE names a reusable fixture
        checkpoint: the 10^7 pre-fill (host-side SHA-256 of every
        serial) dwarfs the measured section, so build it once, save
        it as a plain ck01 snapshot, and let later invocations
        restore instead of rebuild (same-topology restores load rows
        directly — no rehash)."""
        import shutil
        import tempfile

        from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
        from ct_mapreduce_tpu.tune import harness

        entries = int(os.environ.get("CT_SC_CKPT_ENTRIES", str(10**7)))
        bits = int(os.environ.get("CT_SC_CKPT_BITS", "25"))
        churns = [float(c) for c in os.environ.get(
            "CT_SC_CKPT_CHURN", "0.001,0.01,0.1").split(",") if c]
        depths = [int(x) for x in os.environ.get(
            "CT_SC_CKPT_DEPTHS", "1,4,8").split(",") if x]

        t0 = time.perf_counter()
        state = os.environ.get("CT_SC_CKPT_STATE", "")
        if state and os.path.exists(state):
            say(f"ckpt: restoring {entries:,}-entry fixture from {state}")
            agg = TpuAggregator(capacity=1 << bits, batch_size=4096,
                                grow_at=0.0)
            agg.load_checkpoint(state)
            eh = agg.base_hour + 1000
            if int(agg._table_fill) != entries:
                raise SystemExit(
                    f"fixture state {state} holds {int(agg._table_fill):,}"
                    f" entries, wanted {entries:,}: rebuild it")
            say(f"ckpt: fixture restored in {time.perf_counter() - t0:.1f}s")
        else:
            say(f"ckpt: building {entries:,}-entry fixture (2^{bits} slots)")
            agg, eh = harness.build_aggregator(entries, bits)
            say(f"ckpt: fixture built in {time.perf_counter() - t0:.1f}s")
            if state:
                agg.configure_checkpointing(mode="ck01")
                agg.save_checkpoint(state)
                say(f"ckpt: fixture cached to {state}")
        tmp = tempfile.mkdtemp(prefix="stagecost-ckpt.")
        try:
            def fresh_reader():
                return TpuAggregator(capacity=1 << bits,
                                     batch_size=4096, grow_at=0.0)

            p01 = os.path.join(tmp, "ck01.npz")
            agg.configure_checkpointing(mode="ck01")
            t0 = time.perf_counter()
            agg.save_checkpoint(p01)
            full_s = time.perf_counter() - t0
            say(f"ckpt  ck01 full save   {full_s * 1e3:10.1f} ms  "
                f"({os.path.getsize(p01) / 1e6:.1f} MB)")

            p02 = os.path.join(tmp, "ck02.npz")
            agg.configure_checkpointing(mode="ck02",
                                        max_chain=len(churns) + 1)
            t0 = time.perf_counter()
            agg.save_checkpoint(p02)
            say(f"ckpt  ck02 base anchor {(time.perf_counter() - t0) * 1e3:10.1f} ms")

            start = entries
            speedups = {}
            for c in churns:
                nch = max(1, int(entries * c))
                harness.ckpt_churn(agg, eh, nch, start)
                start += nch
                t0 = time.perf_counter()
                agg.save_checkpoint(p02)
                seg_s = time.perf_counter() - t0
                speedups[c] = full_s / seg_s
                seq = agg._ckpt_chain_len
                seg_mb = os.path.getsize(
                    os.path.join(tmp, f"ck02.npz.ckseg-{seq:08d}")) / 1e6
                say(f"ckpt  ck02 tick churn={c:7.2%} ({nch:>9,} rows) "
                    f"{seg_s * 1e3:10.1f} ms  ({seg_mb:.1f} MB, "
                    f"{full_s / seg_s:.1f}x vs full)")

            # Parity at the tip: chain restore == live writer == a
            # ck01 oracle save of the same state.
            want = harness.ckpt_state_digest(agg)
            r = fresh_reader()
            t0 = time.perf_counter()
            r.load_checkpoint(p02)
            say(f"ckpt  ck02 restore (chain {len(churns)})"
                f" {(time.perf_counter() - t0) * 1e3:10.1f} ms")
            harness.require(harness.ckpt_state_digest(r) == want,
                            "ck02 chain restore diverged from writer")
            p01b = os.path.join(tmp, "oracle.npz")
            agg.configure_checkpointing(mode="ck01")
            agg.save_checkpoint(p01b)
            o = fresh_reader()
            o.load_checkpoint(p01b)
            harness.require(harness.ckpt_state_digest(o) == want,
                            "ck01 oracle restore diverged from writer")
            say("ckpt  restore parity exact (ck02 chain == ck01 oracle)")

            # Restore wall vs chain depth (1% churn per tick).
            agg.configure_checkpointing(mode="ck02",
                                        max_chain=max(depths) + 1)
            pd = os.path.join(tmp, "depth.npz")
            agg.save_checkpoint(pd)
            nch = max(1, int(entries * 0.01))
            done = 0
            for d in sorted(depths):
                while done < d:
                    harness.ckpt_churn(agg, eh, nch, start)
                    start += nch
                    agg.save_checkpoint(pd)
                    done += 1
                r = fresh_reader()
                t0 = time.perf_counter()
                r.load_checkpoint(pd)
                w = time.perf_counter() - t0
                harness.require(
                    harness.ckpt_state_digest(r)
                    == harness.ckpt_state_digest(agg),
                    f"restore parity broke at chain depth {d}")
                say(f"ckpt  restore depth={d}  {w * 1e3:10.1f} ms "
                    "(parity exact)")

            one_pct = speedups.get(0.01)
            if one_pct is not None:
                say(f"ckpt  headline: 1%-churn tick {one_pct:.1f}x "
                    "faster than full ck01 save")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    stages = [
        ("read", s_read), ("pack", s_pack), ("pack2", s_pack2),
        ("parse", s_parse),
        ("serial", s_serial), ("sha", s_sha), ("lanes", s_lanes),
    ]
    results = {}
    if not only or "ckpt" in only:
        run_ckpt()
    if only == {"ckpt"}:
        return
    if not only or "decode" in only:
        run_decode()
    if only == {"decode"}:
        return
    if not only or "dispatch" in only:
        run_dispatch()
    if only == {"dispatch"}:
        return
    if not only or "verify" in only:
        run_verify()
    if only == {"verify"}:
        return
    for name, fn in stages:
        if only and name not in only:
            continue
        results[name] = run_stage(name, fn)
    if not only or "full" in only:
        results["full"] = run_full()
    if not only or "preparsed" in only:
        results["preparsed"] = run_preparsed()

    order = [n for n, _ in stages] + ["full"]
    got = [n for n in order if n in results]
    say("")
    say("stage deltas (cost of each added phase):")
    prev = 0.0
    for n in got:
        d = results[n] - prev
        say(f"  +{n:7s} {d * 1e3:9.2f} ms  {d / batch * 1e9:8.1f} ns/entry")
        prev = results[n]
    if "preparsed" in results and "full" in results:
        f, pp = results["full"], results["preparsed"]
        say("")
        say(f"preparsed step vs walker step: {pp / batch * 1e9:.1f} vs "
            f"{f / batch * 1e9:.1f} ns/entry "
            f"({'WIN' if pp < f else 'LOSS'}, {f / max(pp, 1e-12):.2f}x)")


if __name__ == "__main__":
    main()
