#!/bin/bash
# Round-5 master ladder, VALUE-ORDERED (short pool windows decide the
# round — highest-stakes numbers first):
#   1. bench default — the headline + the NEW wide-dispatch e2e leg
#   2. randacc walker-select experiments (oh_* vs dot_*: can the MXU
#      take the walker's block selects?) + primitive prices refresh
#   3. shardcost mesh=1 + stagecost full — the sharded-overhead delta
#      (VERDICT #2's hardware number)
#   4. bench CT_BENCH_MIX=rsa / mixed — the realistic-regime headline
#      (VERDICT #3)
#   5. CT_TPU_TESTS hardware tier (VERDICT #7)
#   6. aotprobe cold/save/load — the compile-tax experiment (VERDICT #6)
#   7. decodebench — host decode scaling, quiet host (VERDICT #4)
# Never SIGTERM a mid-claim python process; kill by explicit PID only.
#
#   nohup tools/measure_ladder5.sh >/dev/null 2>&1 &
#   tail -f /tmp/tpu_session5.log
cd "$(dirname "$0")/.."
log=${CT_LADDER5_LOG:-/tmp/tpu_session5.log}
echo "=== ladder5 start $(date) ===" >> "$log"
while true; do
  python tools/probe_pool.py >> "$log" 2>&1
  if [ $? -eq 0 ]; then break; fi
  echo "--- still down $(date) ---" >> "$log"
  sleep 45
done
echo "--- [1] bench default (headline + 2^20-lane e2e) ---" >> "$log"
CT_BENCH_WATCHDOG_SECS=700 timeout 1800 python bench.py >> "$log" 2>&1
echo "--- [2a] randacc walker-select experiments ---" >> "$log"
timeout 1800 python tools/randacc.py 1048576 26 oh_pair dot_pair oh_sup dot_sup >> "$log" 2>&1
echo "--- [2b] randacc primitive refresh ---" >> "$log"
timeout 2400 python tools/randacc.py 1048576 26 g_row128 s_row128 sort4 >> "$log" 2>&1
echo "--- [3a] shardcost mesh=1 2^20 ---" >> "$log"
timeout 1800 python tools/shardcost.py 1048576 26 >> "$log" 2>&1
echo "--- [3b] stagecost full 2^20 (plain-step reference) ---" >> "$log"
timeout 1800 python tools/stagecost.py 1048576 lanes full >> "$log" 2>&1
echo "--- [4a] bench rsa (pad 2048, rich extensions) ---" >> "$log"
CT_BENCH_MIX=rsa CT_BENCH_E2E=0 CT_BENCH_WATCHDOG_SECS=700 \
  timeout 1800 python bench.py >> "$log" 2>&1
echo "--- [4b] bench mixed (16 issuers, Zipf, EC+RSA) ---" >> "$log"
CT_BENCH_MIX=mixed CT_BENCH_E2E=0 CT_BENCH_WATCHDOG_SECS=700 \
  timeout 1800 python bench.py >> "$log" 2>&1
echo "--- [4c] bench pad ladder 1536 (ec template) ---" >> "$log"
CT_BENCH_PADLEN=1536 CT_BENCH_E2E=0 CT_BENCH_WATCHDOG_SECS=700 \
  timeout 1800 python bench.py >> "$log" 2>&1
echo "--- [5] hardware test tier ---" >> "$log"
CT_TPU_TESTS=1 timeout 2400 python -m pytest tests/test_tpu_hw.py -v >> "$log" 2>&1
echo "--- [6a] aotprobe cold baseline ---" >> "$log"
timeout 1200 python tools/aotprobe.py cold >> "$log" 2>&1
echo "--- [6b] aotprobe save ---" >> "$log"
timeout 1200 python tools/aotprobe.py save /tmp/aot_insert.bin >> "$log" 2>&1
echo "--- [6c] aotprobe load (fresh process) ---" >> "$log"
timeout 1200 python tools/aotprobe.py load /tmp/aot_insert.bin >> "$log" 2>&1
echo "--- [7] decodebench (quiet host, no chip) ---" >> "$log"
timeout 1800 python tools/decodebench.py 262144 1 2 4 0 >> "$log" 2>&1
echo "=== ladder5 done $(date) ===" >> "$log"
