"""The ONE consolidated device campaign (ROADMAP item 1), resumable.

Rounds 11–19 each left a sweep that needs a real accelerator host —
the 1-core CPU box inverts every dispatch/batch curve (BENCHLOG rounds
11–14: per-dispatch toll dominates, so K=1 and B=256 "win" where a
device amortizes them). Device minutes are scarce and preemptible, so
this script folds the five pending runs into one campaign that
survives being killed at any instant:

  staged_e2e      staged-queue chunksPerDispatch x stagingDepth e2e
                  ingest rate (vs the 4.8–5M/s sweep rate target)
  serve_openloop  open-loop serving sweep (replicas x max_batch x
                  max_delay), p99 bounded under concurrent ingest
  verify_sweep    CTMR_VERIFY_BATCH x precomp-window lanes/s
  fleet_scale     fleet aggregate entries/s vs W (real worker
                  subprocesses, serial-reference parity)
  filter_device   device lane of the scaled filter build (fused
                  scatter kernel vs its bit-identical NumPy twin)
  tuned_profile   fold every leg's search result into one versioned
                  tuned profile the config layer loads (tune/emit.py)

Each leg runs the tune registry's measurement provider through the
coordinate-descent driver and checkpoints its serialized result to
``<state>/leg-<name>.json`` ATOMICALLY (tmp + fsync + rename) only
after the leg completes. A rerun with the same ``--state`` dir skips
every checkpointed leg and resumes at the first missing one — a
preempted host pays only for unfinished work. The final leg rebuilds
the profile purely from checkpoints, so it works even when every
measurement leg ran in an earlier life of the process.

Fault injection for the resume tests: ``CTMR_CAMPAIGN_FAULT=<leg>``
SIGKILLs the process after that leg's work finishes but BEFORE its
checkpoint lands — the worst preemption instant (work lost, leg must
rerun). ``--stub`` swaps every evaluator for a deterministic synthetic
surface (no jax, no devices) so the resume machinery is testable on
any box in milliseconds.

Output: one BENCH-style JSON line on stdout (legs, best points, the
profile path), human progress on stderr.

Usage:
    python tools/campaign.py --state /var/tmp/ctmr-campaign \\
        [--scale full] [--out tuned_profile.json] [--legs a,b] \\
        [--seed 0] [--budget-wall-s 600] [--stub]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.tune.harness import say  # noqa: E402

# Leg name -> measurement provider name (tune/measure.py). Order is
# the execution order; tuned_profile always runs last.
MEASURE_LEGS = (
    ("staged_e2e", "staging_e2e"),
    ("serve_openloop", "serve_openloop"),
    ("verify_sweep", "verify_lanes"),
    ("fleet_scale", "fleet_rate"),
    ("filter_device", "filter_build"),
)
PROFILE_LEG = "tuned_profile"
LEGS = tuple(n for n, _ in MEASURE_LEGS) + (PROFILE_LEG,)


def _ckpt_path(state_dir: str, leg: str) -> str:
    return os.path.join(state_dir, f"leg-{leg}.json")


def _write_ckpt(state_dir: str, leg: str, payload: dict) -> None:
    """Atomic checkpoint: a preempted write leaves the old state (or
    nothing), never a torn file a resume would trust."""
    path = _ckpt_path(state_dir, leg)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, sort_keys=True, indent=1)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _read_ckpt(state_dir: str, leg: str):
    """A checkpoint counts only if it parses and matches the leg — a
    torn or foreign file means the leg reruns."""
    try:
        with open(_ckpt_path(state_dir, leg)) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    if payload.get("leg") != leg:
        return None
    return payload


def _maybe_fault(leg: str) -> None:
    if os.environ.get("CTMR_CAMPAIGN_FAULT") == leg:
        say(f"# fault injection: SIGKILL before {leg} checkpoint")
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)


def _serialize_search(m, sr) -> dict:
    """The checkpointable slice of a SearchResult + its measurement's
    identity — everything profile emission needs to run later from
    disk alone."""
    return {
        "measurement": m.name,
        "section": m.section,
        "metric": m.metric,
        "unit": m.unit,
        "best": dict(sr.best),
        "best_value": float(sr.best_value),
        "curves": {k: [[v, float(y)] for v, y in c]
                   for k, c in sr.curves.items()},
        "eval_reps": [int(n) for _, n, _ in sr.evaluations],
        "wall_s": float(sr.wall_s),
        "budget_exhausted": bool(sr.budget_exhausted),
    }


class _CkptSearch:
    """A SearchResult lookalike rebuilt from a checkpoint — carries
    exactly the fields tune/emit.build_profile reads."""

    def __init__(self, d: dict) -> None:
        self.best = dict(d["best"])
        self.best_value = float(d["best_value"])
        self.curves = {k: [tuple(p) for p in c]
                       for k, c in d["curves"].items()}
        self.evaluations = [({}, n, None) for n in d["eval_reps"]]
        self.wall_s = float(d["wall_s"])
        self.budget_exhausted = bool(d["budget_exhausted"])


class _CkptMeasurement:
    def __init__(self, d: dict) -> None:
        self.name = d["measurement"]
        self.section = d["section"]
        self.metric = d["metric"]
        self.unit = d["unit"]


def _stub_evaluator(leg: str, grid: dict):
    """Deterministic synthetic surface for --stub: the planted optimum
    is each ladder's middle rung, with a leg-keyed deterministic
    ripple so different legs don't look identical. No clock, no RNG —
    resume must replay byte-identically."""
    from ct_mapreduce_tpu.tune.search import EvalResult

    axes = {k: list(v) for k, v in grid.items()}

    def evaluate(point: dict, reps: int) -> EvalResult:
        score = 1000.0
        for k, ladder in axes.items():
            ix = ladder.index(point[k])
            score -= 100.0 * abs(ix - len(ladder) // 2)
        ripple = sum((ord(c) for c in leg), 0) % 7
        return EvalResult(mean=score + ripple, reps=reps,
                          wall_s=0.001 * reps)

    return evaluate


def _run_measure_leg(leg: str, measure_name: str, args) -> dict:
    from ct_mapreduce_tpu.tune import measure, search

    m = measure.get_measurement(measure_name)
    grid = m.grid(args.scale)
    if args.stub:
        evaluate = _stub_evaluator(leg, grid)
    else:
        evaluate = m.evaluator(args.scale)
    say(f"# leg {leg}: sweeping {measure_name} over "
        f"{json.dumps(grid)}")
    sr = search.coordinate_descent(
        grid, evaluate, maximize=m.maximize, seed=args.seed,
        budget_evals=args.budget_evals,
        budget_wall_s=args.budget_wall_s,
        reps=(args.reps_lo, args.reps_hi))
    say(f"# leg {leg}: best {json.dumps(sr.best)} -> "
        f"{sr.best_value:.1f} {m.unit} "
        f"({len(sr.evaluations)} evals, {sr.wall_s:.1f}s"
        f"{', budget exhausted' if sr.budget_exhausted else ''})")
    return _serialize_search(m, sr)


def _run_profile_leg(state_dir: str, args) -> dict:
    """Assemble the tuned profile purely from leg checkpoints (this
    leg must work when every measurement ran in a previous process)."""
    from ct_mapreduce_tpu.tune import emit

    results = []
    for leg, _measure_name in MEASURE_LEGS:
        ck = _read_ckpt(state_dir, leg)
        if ck is None:
            raise SystemExit(f"leg {leg} has no checkpoint; cannot "
                             "emit the profile (rerun the campaign)")
        d = ck["result"]
        results.append((_CkptMeasurement(d), _CkptSearch(d)))
    fp = {"stub": True} if args.stub else None
    profile = emit.build_profile(results, platform=args.platform,
                                 fingerprint=fp)
    out = args.out or os.path.join(state_dir, "tuned_profile.json")
    emit.write_profile(out, profile)
    say(f"# leg {PROFILE_LEG}: wrote {out} "
        f"(sections: {sorted(profile['knobs'])})")
    return {"profile_path": os.path.abspath(out),
            "knobs": profile["knobs"],
            "platform": profile["platform"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="one resumable device campaign: five sweeps + the "
        "tuned profile")
    ap.add_argument("--state", required=True,
                    help="checkpoint directory (reuse it to resume)")
    ap.add_argument("--scale", default="full",
                    choices=("smoke", "full"))
    ap.add_argument("--out", default="",
                    help="tuned profile path "
                    "(default <state>/tuned_profile.json)")
    ap.add_argument("--platform", default="", help="profile label")
    ap.add_argument("--legs", default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget-evals", type=int, default=0)
    ap.add_argument("--budget-wall-s", type=float, default=0.0,
                    help="per-leg search wall budget (0 = unbounded)")
    ap.add_argument("--reps-lo", type=int, default=1)
    ap.add_argument("--reps-hi", type=int, default=3)
    ap.add_argument("--stub", action="store_true",
                    help="deterministic synthetic evaluators (no jax) "
                    "— exercises search + checkpoints + resume only")
    args = ap.parse_args(argv)

    os.makedirs(args.state, exist_ok=True)
    wanted = set(args.legs.split(",")) - {""} or set(LEGS)
    unknown = wanted - set(LEGS)
    if unknown:
        raise SystemExit(f"unknown legs {sorted(unknown)}; "
                         f"have {list(LEGS)}")

    status: dict = {}
    for leg, measure_name in MEASURE_LEGS:
        if leg not in wanted:
            status[leg] = {"state": "skipped"}
            continue
        ck = _read_ckpt(args.state, leg)
        if ck is not None:
            say(f"# leg {leg}: checkpoint found, skipping")
            status[leg] = {"state": "resumed",
                           "best": ck["result"]["best"],
                           "best_value": ck["result"]["best_value"],
                           "unit": ck["result"]["unit"]}
            continue
        result = _run_measure_leg(leg, measure_name, args)
        _maybe_fault(leg)
        _write_ckpt(args.state, leg, {"leg": leg, "result": result})
        status[leg] = {"state": "ran", "best": result["best"],
                       "best_value": result["best_value"],
                       "unit": result["unit"]}

    if PROFILE_LEG in wanted:
        # The profile is a pure function of the checkpoints — always
        # re-derivable, so it reruns on every pass (cheap, and a
        # resumed campaign picks up legs finished since last time).
        result = _run_profile_leg(args.state, args)
        _maybe_fault(PROFILE_LEG)
        _write_ckpt(args.state, PROFILE_LEG,
                    {"leg": PROFILE_LEG, "result": result})
        status[PROFILE_LEG] = dict(result, state="ran")
    else:
        status[PROFILE_LEG] = {"state": "skipped"}

    print(json.dumps({
        "metric": "ct_device_campaign",
        "scale": args.scale,
        "stub": bool(args.stub),
        "legs": status,
        "state_dir": os.path.abspath(args.state),
    }, sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
