#!/bin/bash
# Unattended hardware-measurement ladder for the tunneled axon TPU.
#
# Waits for the pool (each claim attempt blocks ~25 min before erring
# UNAVAILABLE during an outage), then runs the full probe sequence and
# the headline bench, logging everything to $CT_LADDER_LOG. Safe to
# leave running across a pool outage: claims exit on their own — never
# SIGTERM a mid-claim process (observed to extend outages).
#
#   nohup tools/measure_ladder.sh >/dev/null 2>&1 &
#   tail -f /tmp/tpu_session.log
cd "$(dirname "$0")/.."
log=${CT_LADDER_LOG:-/tmp/tpu_session.log}
echo "=== session start $(date) ===" >> "$log"
while true; do
  # No timeout on the probe: a claim errors out on its own (~25 min during
  # an outage), and SIGTERMing a mid-claim process has been observed to
  # extend outages. Let it finish either way.
  python tools/probe_pool.py >> "$log" 2>&1
  if [ $? -eq 0 ]; then break; fi
  echo "--- still down $(date) ---" >> "$log"
  sleep 45
done
echo "=== pool up $(date); running ladder ===" >> "$log"
echo "--- opcost 131072 ---" >> "$log"
timeout 1500 python tools/opcost.py 131072 >> "$log" 2>&1
echo "--- microbench 131072 ---" >> "$log"
timeout 1500 python tools/microbench.py 131072 >> "$log" 2>&1
echo "--- microbench 1048576 ---" >> "$log"
timeout 1500 python tools/microbench.py 1048576 >> "$log" 2>&1
echo "--- insert_sweep ---" >> "$log"
timeout 3000 python tools/insert_sweep.py >> "$log" 2>&1
echo "--- bench.py full ---" >> "$log"
CT_BENCH_WATCHDOG_SECS=520 timeout 1200 python bench.py >> "$log" 2>&1
echo "=== ladder done $(date) ===" >> "$log"
