"""Honest cost of TPU random-access primitives at insert shapes.

stagecost.py showed the dedup insert owns ~85% of the fused step
(~710 of ~840 ns/entry at 2^20 lanes), and the insert is built from
exactly four random-access primitives. This probe times each primitive
standalone — same trusted contract as bench.py/stagecost.py (per-sweep
varying indices inside a jitted fori_loop, synchronous value read) —
so the insert redesign is driven by measured op costs, not folklore:

  g_scalar  — uint32[B] gather from uint32[cap]        (SoA probe read)
  g_row5    — uint32[B, 5] row gather from [cap, 5]    (current fused row)
  g_row128  — uint32[B, 128] block gather from [cap/128, 128]
              (bucketed design: one dense 512 B block per lane)
  s_scalar  — [B] scatter-min into [cap]               (claim election)
  s_row5    — [B, 5] row scatter into [cap, 5]         (current commit)
  s_row128  — [B, 128] block scatter into [cap/128, 128]
  sort1     — jnp.sort of uint32[B]
  sort_kv   — lax.sort of (uint32[B] keys, int32[B] payload)
  sort4     — lax.sort of 4-word keys + payload (full 128-bit lexsort)

Run:  python tools/randacc.py [batch] [log2_cap] [name ...]
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    log2_cap = int(sys.argv[2]) if len(sys.argv) > 2 else 26
    only = set(sys.argv[3:])
    cap = 1 << log2_cap
    exec_target_s = float(os.environ.get("CT_RA_EXEC_SECS", "4.0"))

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) acquired in "
        f"{time.perf_counter() - t0:.1f}s; batch={batch} cap=2^{log2_cap}")

    # Index stream: a cheap per-sweep LCG keeps indices varying (no
    # loop-invariant hoisting) and uniformly spread over the table.
    lane = jax.device_put(np.arange(batch, dtype=np.uint32))
    # Table factories, not shared arrays: each case DONATES its tables,
    # so sharing one buffer across cases would hand later cases a
    # deleted array.
    mk_t1 = lambda: jax.device_put(np.zeros((cap,), np.uint32))
    mk_t5 = lambda: jax.device_put(np.zeros((cap, 5), np.uint32))
    mk_tb = lambda: jax.device_put(np.zeros((cap // 128, 128), np.uint32))

    def idx(seed):
        h = (lane * np.uint32(0x9E3779B9)) ^ seed
        h = h * np.uint32(0x85EBCA6B)
        return (h & np.uint32(cap - 1)).astype(jnp.int32)

    def bidx(seed):
        h = (lane * np.uint32(0x9E3779B9)) ^ seed
        h = h * np.uint32(0x85EBCA6B)
        return (h & np.uint32(cap // 128 - 1)).astype(jnp.int32)

    # Each case: (name, tables_in, body(seed, *tables) -> (tables, scalar)).
    def g_scalar(seed, t1):
        return (t1,), t1[idx(seed)].sum()

    def g_row5(seed, t5):
        return (t5,), t5[idx(seed)].sum()

    def g_row128(seed, tb):
        return (tb,), tb[bidx(seed)].sum()

    def s_scalar(seed, t1):
        t1 = t1.at[idx(seed)].min(lane)
        return (t1,), t1[0]

    def s_row5(seed, t5):
        rows = jnp.tile(lane[:, None], (1, 5))
        t5 = t5.at[idx(seed)].set(rows)
        return (t5,), t5[0].sum()

    def s_row128(seed, tb):
        rows = jnp.tile(lane[:, None], (1, 128))
        tb = tb.at[bidx(seed)].set(rows)
        return (tb,), tb[0].sum()

    def sort1(seed, t1):
        h = (lane * np.uint32(0x9E3779B9)) ^ seed
        return (t1,), jnp.sort(h)[0] + jnp.uint32(0)

    def sort_kv(seed, t1):
        h = (lane * np.uint32(0x9E3779B9)) ^ seed
        k, v = jax.lax.sort((h, lane.astype(jnp.int32)), num_keys=1)
        return (t1,), k[0] + v[0].astype(jnp.uint32)

    def sort4(seed, t1):
        h0 = (lane * np.uint32(0x9E3779B9)) ^ seed
        h1 = h0 * np.uint32(0x85EBCA6B)
        h2 = h1 ^ (h0 >> 13)
        h3 = h2 * np.uint32(0xC2B2AE35)
        out = jax.lax.sort(
            (h0, h1, h2, h3, lane.astype(jnp.int32)), num_keys=4)
        return (t1,), out[0][0] + out[4][0].astype(jnp.uint32)

    # --- walker-select experiments: the DER walker's dominant cost is
    # the dynamic-position block select over resident [B, 256]-word
    # rows (der_kernel._window / _sup_fetch). Two formulations of the
    # same fetch: the shipping VPU one-hot select-reduce, and an MXU
    # int8 batched dot (one-hot as a 1x16 matrix; bytes are exact in
    # int8 up to reinterpretation, fixable with a +128 bias if the dot
    # wins). The walker is VPU-bound while the MXU idles, so a dot win
    # here would offload the biggest parse term onto the idle unit.
    mk_rows = lambda: jax.device_put(
        np.arange(batch * 256, dtype=np.uint32).reshape(batch, 256))

    def widx(seed):
        h = (lane * np.uint32(0x9E3779B9)) ^ seed
        return ((h * np.uint32(0x85EBCA6B)) % np.uint32(239)).astype(
            jnp.int32)

    def oh_pair(seed, tr):
        base = widx(seed)
        bi = base // 16
        blk = tr.reshape(batch, 16, 16)
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (batch, 16), 1)
        lo = jnp.sum(jnp.where((iota_k == bi[:, None])[:, :, None], blk,
                               jnp.uint32(0)), axis=1)
        hi = jnp.sum(jnp.where((iota_k == bi[:, None] + 1)[:, :, None], blk,
                               jnp.uint32(0)), axis=1)
        return (tr,), lo.sum() + hi.sum()

    def dot_pair(seed, tr):
        base = widx(seed)
        bi = base // 16
        blk8 = jax.lax.bitcast_convert_type(
            tr.reshape(batch, 16, 16), jnp.uint8
        ).reshape(batch, 16, 64).astype(jnp.int8)
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (batch, 16), 1)
        oh = jnp.stack(
            [(iota_k == bi[:, None]), (iota_k == bi[:, None] + 1)],
            axis=1).astype(jnp.int8)  # [B, 2, 16]
        pair = jnp.einsum("bmk,bkc->bmc", oh, blk8,
                          preferred_element_type=jnp.int32)
        return (tr,), pair.sum().astype(jnp.uint32)

    def oh_sup(seed, tr):
        # Clamp like the real _sup_fetch caller must: all 8 blocks
        # (bi0..bi0+7) stay inside the 16-block row, so the probe
        # times a realizable fetch on every lane.
        bi0 = jnp.minimum(widx(seed) // 16, 8)
        blk = tr.reshape(batch, 16, 16)
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (batch, 16), 1)
        parts = [
            jnp.sum(jnp.where((iota_k == bi0[:, None] + m)[:, :, None],
                              blk, jnp.uint32(0)), axis=1)
            for m in range(8)
        ]
        return (tr,), sum(p.sum() for p in parts)

    def dot_sup(seed, tr):
        bi0 = jnp.minimum(widx(seed) // 16, 8)  # see oh_sup
        blk8 = jax.lax.bitcast_convert_type(
            tr.reshape(batch, 16, 16), jnp.uint8
        ).reshape(batch, 16, 64).astype(jnp.int8)
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (batch, 16), 1)
        oh = jnp.stack(
            [iota_k == bi0[:, None] + m for m in range(8)],
            axis=1).astype(jnp.int8)  # [B, 8, 16]
        sup = jnp.einsum("bmk,bkc->bmc", oh, blk8,
                         preferred_element_type=jnp.int32)
        return (tr,), sup.sum().astype(jnp.uint32)

    cases = {
        "oh_pair": (oh_pair, (mk_rows,)),
        "dot_pair": (dot_pair, (mk_rows,)),
        "oh_sup": (oh_sup, (mk_rows,)),
        "dot_sup": (dot_sup, (mk_rows,)),
        "g_scalar": (g_scalar, (mk_t1,)),
        "g_row5": (g_row5, (mk_t5,)),
        "g_row128": (g_row128, (mk_tb,)),
        "s_scalar": (s_scalar, (mk_t1,)),
        "s_row5": (s_row5, (mk_t5,)),
        "s_row128": (s_row128, (mk_tb,)),
        "sort1": (sort1, (mk_t1,)),
        "sort_kv": (sort_kv, (mk_t1,)),
        "sort4": (sort4, (mk_t1,)),
    }

    for name, (body, mk_tabs) in cases.items():
        if only and name not in only:
            continue
        tabs = tuple(mk() for mk in mk_tabs)

        @functools.partial(jax.jit, donate_argnums=tuple(range(len(tabs))))
        def mega(*args, _body=body, _n=len(tabs)):
            tabs_in, acc, n_sweeps = args[:_n], args[_n], args[_n + 1]

            def sweep(s, carry):
                tabs_c, acc = carry
                tabs_c, v = _body(acc + jnp.uint32(s), *tabs_c)
                return tabs_c, acc + v.astype(jnp.uint32)

            tabs_out, acc = jax.lax.fori_loop(
                0, n_sweeps, sweep, (tuple(tabs_in), acc))
            return tabs_out, acc

        fetch = jax.jit(lambda a: a + jnp.uint32(0))
        acc = jax.device_put(np.uint32(0))
        t0 = time.perf_counter()
        tabs, acc = mega(*tabs, acc, np.int32(1))
        int(fetch(acc))
        say(f"  {name}: compile+warmup {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        tabs, acc = mega(*tabs, acc, np.int32(1))
        int(fetch(acc))
        per = max(time.perf_counter() - t0, 1e-4)
        n = max(2, min(int(exec_target_s / per), 400))
        t0 = time.perf_counter()
        tabs, acc = mega(*tabs, acc, np.int32(n))
        int(fetch(acc))
        dt = (time.perf_counter() - t0) / n
        say(f"{name:9s} {dt * 1e3:9.3f} ms  {dt / batch * 1e9:8.2f} ns/elem "
            f" ({n} sweeps)")


if __name__ == "__main__":
    main()
