"""qps_sweep: the query plane's serving frontier, closed- and open-loop.

Two modes, one pre-filled dedup table:

- **Closed-loop** (default; the round-10 shape): N client threads
  hammer a MembershipOracle back-to-back per (max_batch, max_delay)
  point. Measures the batching tradeoff the way an inference team
  tunes a model server, but the arrival process is throttled by the
  clients' own latency — it can never show overload.
- **Open-loop** (``--open-loop``; the round-12 shape): arrivals land
  at a FIXED offered rate regardless of completion, the way real
  traffic arrives. Dispatcher threads pull a precomputed arrival
  schedule; a request's latency is measured from its *scheduled*
  arrival instant, so backlog shows up as latency (and, past the
  admission bound, as explicit shed) instead of silently throttling
  the load generator. Sweeping offered rates maps achieved QPS,
  p50/p99, and the shed fraction — where the plane saturates, not
  just how fast a closed loop spins.

The serving tier under test is the round-12 stack: snapshot replica
pool (``--replicas``), device `contains` by default (``--host`` forces
the round-10 numpy mirror), hot-serial cache (``--cache``; -1
disables), with ``--zipf`` skewing the probe mix the way membership
traffic actually looks (a hot working set, not uniform keys).

The measurement machinery itself (table fill, oracle warmup, the two
loop shapes, parity checks) lives in
:mod:`ct_mapreduce_tpu.tune.harness` since round 21 — shared with the
autotuner's ``serve_openloop`` provider so the sweep a human runs and
the sweep the campaign runs are the same code.

Usage:
    python tools/qps_sweep.py [--entries 200000] [--threads 8]
        [--duration 0.5] [--batches 16,64,256,1024] [--delays-ms 0.5,2,5]
    python tools/qps_sweep.py --open-loop --rates 2000,10000,50000
        [--arrival-batch 16] [--zipf 1.2] [--replicas 2] [--cache 4096]
        [--host] [--json]

CPU-friendly (JAX_PLATFORMS=cpu works); on a TPU host the same sweep
measures the pinned-device `contains` path at real widths.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.tune.harness import (  # noqa: E402
    ParityError,
    build_aggregator,
    make_oracle,
    probe_indices,
    run_open_loop,
    run_point,
    serial_bytes,
)

__all__ = [
    "build_aggregator", "serial_bytes", "make_oracle", "probe_indices",
    "run_point", "run_open_loop", "main",
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=200_000)
    ap.add_argument("--table-bits", type=int, default=20)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--duration", type=float, default=0.5,
                    help="seconds per sweep point")
    ap.add_argument("--batches", default="16,64,256,1024")
    ap.add_argument("--delays-ms", default="0.5,2,5")
    ap.add_argument("--device", action="store_true",
                    help="force device serving (pinned replicas + "
                    "jitted contains) — this is the default")
    ap.add_argument("--host", action="store_true",
                    help="force the round-10 host-numpy mirror")
    ap.add_argument("--replicas", type=int, default=2,
                    help="snapshot replicas in the serving pool")
    ap.add_argument("--cache", type=int, default=4096,
                    help="hot-serial cache entries (-1 disables)")
    ap.add_argument("--open-loop", action="store_true",
                    help="fixed-arrival-rate mode (see --rates)")
    ap.add_argument("--rates", default="2000,5000,10000,20000,50000",
                    help="open-loop offered rates, lanes/s")
    ap.add_argument("--arrival-batch", type=int, default=16,
                    help="lanes per scheduled arrival (bulk size)")
    ap.add_argument("--zipf", type=float, default=0.0,
                    help="zipf skew for the probe mix (0 = uniform "
                    "over 2x entries; 1.2 is a realistic hot set)")
    ap.add_argument("--max-batch", type=int, default=1024,
                    help="open-loop oracle max_batch")
    ap.add_argument("--max-delay-ms", type=float, default=1.0,
                    help="open-loop oracle max_delay")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    device = not args.host  # device by default, exactly like the plane
    agg, eh = build_aggregator(args.entries, args.table_bits)
    mode = "open-loop" if args.open_loop else "closed-loop"
    print(f"# table: {args.entries} entries in 2^{args.table_bits} slots, "
          f"{args.threads} {mode} threads, "
          f"{'device' if device else 'host'} contains, "
          f"{args.replicas} replicas, cache {args.cache}, "
          f"zipf {args.zipf}", file=sys.stderr)
    rows = []
    try:
        if args.open_loop:
            for rate in (float(x) for x in args.rates.split(",")):
                r = run_open_loop(
                    agg, eh, args.entries, rate, args.duration,
                    args.arrival_batch, args.threads, args.max_batch,
                    args.max_delay_ms / 1e3, device, args.replicas,
                    args.cache, args.zipf)
                rows.append(r)
                print(f"# {r}", file=sys.stderr)
            hdr = ("offered_qps", "achieved_qps", "p50_ms", "p99_ms",
                   "shed_frac", "mean_batch_lanes", "cache_hit_rate")
        else:
            for mb in (int(x) for x in args.batches.split(",")):
                for dly in (float(x) for x in args.delays_ms.split(",")):
                    r = run_point(agg, eh, args.entries, mb, dly / 1e3,
                                  args.threads, args.duration, device,
                                  replicas=args.replicas,
                                  cache_size=args.cache)
                    rows.append(r)
                    print(f"# {r}", file=sys.stderr)
            hdr = ("max_batch", "max_delay_ms", "qps", "p50_ms", "p99_ms",
                   "mean_batch_lanes", "shed")
    except ParityError as err:
        raise SystemExit(str(err)) from err
    if args.json:
        json.dump(rows, sys.stdout, indent=2)
        print()
    else:
        print("\t".join(hdr))
        for r in rows:
            print("\t".join(str(r[h]) for h in hdr))
    return 0


if __name__ == "__main__":
    sys.exit(main())
