"""qps_sweep: the query plane's batch-size × max-delay frontier.

Maps the dynamic-batching tradeoff of serve/batcher.py the way an
inference-serving team tunes a model server: for each
(max_batch, max_delay) point, closed-loop client threads hammer a
MembershipOracle over a pre-filled dedup table and we record achieved
QPS, client p50/p99 latency, mean lanes per executed batch, and the
shed rate. Small max_delay buys latency at the cost of batch
amortization; large max_batch only pays off once concurrency can fill
it — the frontier says which knee to run at.

Usage:
    python tools/qps_sweep.py [--entries 200000] [--threads 8]
        [--duration 0.5] [--batches 16,64,256,1024]
        [--delays-ms 0.5,2,5] [--json]

CPU-friendly (JAX_PLATFORMS=cpu works); on a TPU host the same sweep
measures the device `contains` path via --device.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_aggregator(entries: int, table_bits: int):
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.core import packing

    agg = TpuAggregator(capacity=1 << table_bits, batch_size=4096,
                        grow_at=0.0)
    eh = agg.base_hour + 1000
    serials = np.zeros((entries, packing.MAX_SERIAL_BYTES), np.uint8)
    counters = np.arange(entries, dtype=np.uint64)
    for i in range(8):
        serials[:, 15 - i] = ((counters >> np.uint64(8 * i))
                              & np.uint64(0xFF)).astype(np.uint8)
    slen = np.full((entries,), 16, np.int64)
    keys = packing.fingerprints_np(
        np.zeros((entries,), np.int64), np.full((entries,), eh, np.int64),
        serials, slen)
    meta = np.full((entries,), packing.pack_meta(0, eh, agg.base_hour),
                   np.uint32)
    ovf = agg._bulk_reinsert(keys, meta)
    if ovf:
        raise SystemExit(f"table too small: {ovf} overflow rows; "
                         "raise --table-bits")
    agg._table_fill = entries
    agg._device_written = True
    return agg, eh


def serial_bytes(j: int) -> bytes:
    return b"\x00" * 8 + int(j).to_bytes(8, "big")


def run_point(agg, eh: int, entries: int, max_batch: int,
              max_delay_s: float, threads: int, duration_s: float,
              device: bool) -> dict:
    from ct_mapreduce_tpu.serve.batcher import Overloaded
    from ct_mapreduce_tpu.serve.server import MembershipOracle
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    oracle = MembershipOracle(
        agg, max_batch=max_batch, max_delay_s=max_delay_s,
        max_queue_lanes=max(4 * max_batch, 1024),
        max_staleness_s=60.0, device=device)
    oracle.snapshots.refresh()  # capture outside the timed window
    lat: list[float] = []
    shed = [0]
    stop = time.perf_counter() + duration_s

    def client(seed: int) -> None:
        rng = np.random.default_rng(seed)
        while time.perf_counter() < stop:
            j = int(rng.integers(2 * entries))  # half present, half not
            t0 = time.perf_counter()
            try:
                res = oracle.query_raw([(0, eh, serial_bytes(j))])
            except Overloaded:
                shed.append(1)
                continue
            lat.append(time.perf_counter() - t0)
            assert res[0][0] == (j < entries), f"parity broke at {j}"

    ts = [threading.Thread(target=client, args=(s,)) for s in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    oracle.close()
    tmetrics.set_sink(prev)
    snap = sink.snapshot()
    lanes = snap["counters"].get("serve.lanes", 0.0)
    batches = snap["counters"].get("serve.batches", 0.0)
    lat.sort()
    n = len(lat)
    return {
        "max_batch": max_batch,
        "max_delay_ms": round(max_delay_s * 1e3, 3),
        "qps": round(n / wall, 1),
        "p50_ms": round(lat[n // 2] * 1e3, 3) if n else None,
        "p99_ms": (round(lat[min(n - 1, int(0.99 * n))] * 1e3, 3)
                   if n else None),
        "mean_batch_lanes": round(lanes / batches, 2) if batches else 0.0,
        "shed": len(shed) - 1,
        "queries": n,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=200_000)
    ap.add_argument("--table-bits", type=int, default=20)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--duration", type=float, default=0.5,
                    help="seconds per sweep point")
    ap.add_argument("--batches", default="16,64,256,1024")
    ap.add_argument("--delays-ms", default="0.5,2,5")
    ap.add_argument("--device", action="store_true",
                    help="serve from a pinned device copy (jitted "
                    "contains) instead of the host mirror")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    agg, eh = build_aggregator(args.entries, args.table_bits)
    print(f"# table: {args.entries} entries in 2^{args.table_bits} slots, "
          f"{args.threads} closed-loop threads, "
          f"{args.duration}s/point, "
          f"{'device' if args.device else 'host'} contains",
          file=sys.stderr)
    rows = []
    for mb in (int(x) for x in args.batches.split(",")):
        for dly in (float(x) for x in args.delays_ms.split(",")):
            r = run_point(agg, eh, args.entries, mb, dly / 1e3,
                          args.threads, args.duration, args.device)
            rows.append(r)
            print(f"# {r}", file=sys.stderr)
    if args.json:
        json.dump(rows, sys.stdout, indent=2)
        print()
    else:
        hdr = ("max_batch", "max_delay_ms", "qps", "p50_ms", "p99_ms",
               "mean_batch_lanes", "shed")
        print("\t".join(hdr))
        for r in rows:
            print("\t".join(str(r[h]) for h in hdr))
    return 0


if __name__ == "__main__":
    sys.exit(main())
