"""qps_sweep: the query plane's serving frontier, closed- and open-loop.

Two modes, one pre-filled dedup table:

- **Closed-loop** (default; the round-10 shape): N client threads
  hammer a MembershipOracle back-to-back per (max_batch, max_delay)
  point. Measures the batching tradeoff the way an inference team
  tunes a model server, but the arrival process is throttled by the
  clients' own latency — it can never show overload.
- **Open-loop** (``--open-loop``; the round-12 shape): arrivals land
  at a FIXED offered rate regardless of completion, the way real
  traffic arrives. Dispatcher threads pull a precomputed arrival
  schedule; a request's latency is measured from its *scheduled*
  arrival instant, so backlog shows up as latency (and, past the
  admission bound, as explicit shed) instead of silently throttling
  the load generator. Sweeping offered rates maps achieved QPS,
  p50/p99, and the shed fraction — where the plane saturates, not
  just how fast a closed loop spins.

The serving tier under test is the round-12 stack: snapshot replica
pool (``--replicas``), device `contains` by default (``--host`` forces
the round-10 numpy mirror), hot-serial cache (``--cache``; -1
disables), with ``--zipf`` skewing the probe mix the way membership
traffic actually looks (a hot working set, not uniform keys).

Usage:
    python tools/qps_sweep.py [--entries 200000] [--threads 8]
        [--duration 0.5] [--batches 16,64,256,1024] [--delays-ms 0.5,2,5]
    python tools/qps_sweep.py --open-loop --rates 2000,10000,50000
        [--arrival-batch 16] [--zipf 1.2] [--replicas 2] [--cache 4096]
        [--host] [--json]

CPU-friendly (JAX_PLATFORMS=cpu works); on a TPU host the same sweep
measures the pinned-device `contains` path at real widths.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_aggregator(entries: int, table_bits: int):
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.core import packing

    agg = TpuAggregator(capacity=1 << table_bits, batch_size=4096,
                        grow_at=0.0)
    eh = agg.base_hour + 1000
    serials = np.zeros((entries, packing.MAX_SERIAL_BYTES), np.uint8)
    counters = np.arange(entries, dtype=np.uint64)
    for i in range(8):
        serials[:, 15 - i] = ((counters >> np.uint64(8 * i))
                              & np.uint64(0xFF)).astype(np.uint8)
    slen = np.full((entries,), 16, np.int64)
    keys = packing.fingerprints_np(
        np.zeros((entries,), np.int64), np.full((entries,), eh, np.int64),
        serials, slen)
    meta = np.full((entries,), packing.pack_meta(0, eh, agg.base_hour),
                   np.uint32)
    ovf = agg._bulk_reinsert(keys, meta)
    if ovf:
        raise SystemExit(f"table too small: {ovf} overflow rows; "
                         "raise --table-bits")
    agg._table_fill = entries
    agg._device_written = True
    return agg, eh


def serial_bytes(j: int) -> bytes:
    return b"\x00" * 8 + int(j).to_bytes(8, "big")


def make_oracle(agg, eh: int, entries: int, max_batch: int,
                max_delay_s: float, device: bool, replicas: int,
                cache_size: int, max_queue_lanes: int = 0):
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    oracle = MembershipOracle(
        agg, max_batch=max_batch, max_delay_s=max_delay_s,
        max_queue_lanes=max_queue_lanes or max(4 * max_batch, 1024),
        max_staleness_s=60.0, device=device, replicas=replicas,
        cache_size=cache_size if cache_size != 0 else -1)
    oracle.snapshots.warm()  # captures + pins outside the timed window
    # Warm the contains kernel at every pow2 width the batcher can
    # form: compiles are per-shape and must not bill the timed window.
    # Probe keys sit outside [0, 2*entries) so they never alias the
    # sweep's probe domain through the cache.
    w = 16
    while w <= max_batch:
        oracle.query_raw([(0, eh, serial_bytes(2 * entries + k))
                          for k in range(w)])
        w *= 2
    return oracle


def probe_indices(rng, n: int, entries: int, zipf: float) -> np.ndarray:
    """Probe mix over [0, 2*entries): uniform (zipf=0 — half present,
    half absent) or zipf-skewed ranks (a hot working set, the traffic
    shape the hot-serial cache exists for)."""
    if zipf <= 0:
        return rng.integers(0, 2 * entries, size=n)
    return np.minimum(rng.zipf(zipf, size=n) - 1, 2 * entries - 1)


def run_point(agg, eh: int, entries: int, max_batch: int,
              max_delay_s: float, threads: int, duration_s: float,
              device: bool, replicas: int = 1,
              cache_size: int = -1) -> dict:
    from ct_mapreduce_tpu.serve.batcher import Overloaded
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    oracle = make_oracle(agg, eh, entries, max_batch, max_delay_s,
                         device, replicas, cache_size)
    lat: list[float] = []
    shed = [0]
    stop = time.perf_counter() + duration_s

    def client(seed: int) -> None:
        rng = np.random.default_rng(seed)
        while time.perf_counter() < stop:
            j = int(rng.integers(2 * entries))  # half present, half not
            t0 = time.perf_counter()
            try:
                res = oracle.query_raw([(0, eh, serial_bytes(j))])
            except Overloaded:
                shed.append(1)
                continue
            lat.append(time.perf_counter() - t0)
            assert res[0][0] == (j < entries), f"parity broke at {j}"

    ts = [threading.Thread(target=client, args=(s,)) for s in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    oracle.close()
    tmetrics.set_sink(prev)
    snap = sink.snapshot()
    lanes = snap["counters"].get("serve.lanes", 0.0)
    batches = snap["counters"].get("serve.batches", 0.0)
    lat.sort()
    n = len(lat)
    return {
        "max_batch": max_batch,
        "max_delay_ms": round(max_delay_s * 1e3, 3),
        "qps": round(n / wall, 1),
        "p50_ms": round(lat[n // 2] * 1e3, 3) if n else None,
        "p99_ms": (round(lat[min(n - 1, int(0.99 * n))] * 1e3, 3)
                   if n else None),
        "mean_batch_lanes": round(lanes / batches, 2) if batches else 0.0,
        "shed": len(shed) - 1,
        "queries": n,
    }


def run_open_loop(agg, eh: int, entries: int, rate: float,
                  duration_s: float, arrival_batch: int, threads: int,
                  max_batch: int, max_delay_s: float, device: bool,
                  replicas: int, cache_size: int, zipf: float) -> dict:
    """One offered-rate point: arrivals of ``arrival_batch`` lanes land
    every ``arrival_batch / rate`` seconds on a fixed schedule;
    latency is measured from the SCHEDULED instant, so dispatcher
    backlog is latency, not hidden throttling."""
    from ct_mapreduce_tpu.serve.batcher import Overloaded
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    oracle = make_oracle(agg, eh, entries, max_batch, max_delay_s,
                         device, replicas, cache_size,
                         max_queue_lanes=max(8 * max_batch, 4096))
    interval = arrival_batch / rate
    n_arrivals = max(1, int(duration_s / interval))
    rng = np.random.default_rng(42)
    sched = probe_indices(rng, n_arrivals * arrival_batch, entries,
                          zipf).reshape(n_arrivals, arrival_batch)
    lat: list[float] = []
    shed_lanes = [0]
    errors: list[str] = []
    next_ix = [0]
    ix_lock = threading.Lock()
    t_start = time.perf_counter() + 0.05  # let every worker reach the gate

    def worker() -> None:
        while True:
            with ix_lock:
                i = next_ix[0]
                next_ix[0] += 1
            if i >= n_arrivals:
                return
            t_i = t_start + i * interval
            now = time.perf_counter()
            if now < t_i:
                time.sleep(t_i - now)
            js = sched[i]
            items = [(0, eh, serial_bytes(int(j))) for j in js]
            try:
                res = oracle.query_raw(items)
            except Overloaded:
                shed_lanes.append(arrival_batch)
                continue
            lat.append(time.perf_counter() - t_i)  # GIL-atomic append
            for r, j in zip(res, js):
                if r[0] != (j < entries):
                    errors.append(f"parity broke at {j}")

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = max(time.perf_counter() - t_start, 1e-9)
    oracle.close()
    tmetrics.set_sink(prev)
    if errors:
        raise SystemExit(f"open-loop parity: {errors[:3]}")
    snap = sink.snapshot()
    counters = snap["counters"]
    lanes = counters.get("serve.lanes", 0.0)
    batches = counters.get("serve.batches", 0.0)
    hits = counters.get("serve.cache_hit", 0.0)
    misses = counters.get("serve.cache_miss", 0.0)
    done = len(lat) * arrival_batch
    offered = n_arrivals * arrival_batch
    lat.sort()
    n = len(lat)
    return {
        "offered_qps": round(rate, 1),
        "achieved_qps": round(done / wall, 1),
        "p50_ms": round(lat[n // 2] * 1e3, 3) if n else None,
        "p99_ms": (round(lat[min(n - 1, int(0.99 * n))] * 1e3, 3)
                   if n else None),
        "shed_frac": round(sum(shed_lanes) / offered, 4),
        "mean_batch_lanes": round(lanes / batches, 2) if batches else 0.0,
        "cache_hit_rate": (round(hits / (hits + misses), 4)
                           if hits + misses else 0.0),
        "lanes_done": done,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=200_000)
    ap.add_argument("--table-bits", type=int, default=20)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--duration", type=float, default=0.5,
                    help="seconds per sweep point")
    ap.add_argument("--batches", default="16,64,256,1024")
    ap.add_argument("--delays-ms", default="0.5,2,5")
    ap.add_argument("--device", action="store_true",
                    help="force device serving (pinned replicas + "
                    "jitted contains) — this is the default")
    ap.add_argument("--host", action="store_true",
                    help="force the round-10 host-numpy mirror")
    ap.add_argument("--replicas", type=int, default=2,
                    help="snapshot replicas in the serving pool")
    ap.add_argument("--cache", type=int, default=4096,
                    help="hot-serial cache entries (-1 disables)")
    ap.add_argument("--open-loop", action="store_true",
                    help="fixed-arrival-rate mode (see --rates)")
    ap.add_argument("--rates", default="2000,5000,10000,20000,50000",
                    help="open-loop offered rates, lanes/s")
    ap.add_argument("--arrival-batch", type=int, default=16,
                    help="lanes per scheduled arrival (bulk size)")
    ap.add_argument("--zipf", type=float, default=0.0,
                    help="zipf skew for the probe mix (0 = uniform "
                    "over 2x entries; 1.2 is a realistic hot set)")
    ap.add_argument("--max-batch", type=int, default=1024,
                    help="open-loop oracle max_batch")
    ap.add_argument("--max-delay-ms", type=float, default=1.0,
                    help="open-loop oracle max_delay")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    device = not args.host  # device by default, exactly like the plane
    agg, eh = build_aggregator(args.entries, args.table_bits)
    mode = "open-loop" if args.open_loop else "closed-loop"
    print(f"# table: {args.entries} entries in 2^{args.table_bits} slots, "
          f"{args.threads} {mode} threads, "
          f"{'device' if device else 'host'} contains, "
          f"{args.replicas} replicas, cache {args.cache}, "
          f"zipf {args.zipf}", file=sys.stderr)
    rows = []
    if args.open_loop:
        for rate in (float(x) for x in args.rates.split(",")):
            r = run_open_loop(
                agg, eh, args.entries, rate, args.duration,
                args.arrival_batch, args.threads, args.max_batch,
                args.max_delay_ms / 1e3, device, args.replicas,
                args.cache, args.zipf)
            rows.append(r)
            print(f"# {r}", file=sys.stderr)
        hdr = ("offered_qps", "achieved_qps", "p50_ms", "p99_ms",
               "shed_frac", "mean_batch_lanes", "cache_hit_rate")
    else:
        for mb in (int(x) for x in args.batches.split(",")):
            for dly in (float(x) for x in args.delays_ms.split(",")):
                r = run_point(agg, eh, args.entries, mb, dly / 1e3,
                              args.threads, args.duration, device,
                              replicas=args.replicas,
                              cache_size=args.cache)
                rows.append(r)
                print(f"# {r}", file=sys.stderr)
        hdr = ("max_batch", "max_delay_ms", "qps", "p50_ms", "p99_ms",
               "mean_batch_lanes", "shed")
    if args.json:
        json.dump(rows, sys.stdout, indent=2)
        print()
    else:
        print("\t".join(hdr))
        for r in rows:
            print("\t".join(str(r[h]) for h in hdr))
    return 0


if __name__ == "__main__":
    sys.exit(main())
