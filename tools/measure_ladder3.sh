#!/bin/bash
# Round-4 third-stage measurement ladder: runs AFTER ladder2 (waits for
# its "ladder2 done" marker; or for the pool directly if ladder2 isn't
# running). Measures the session's later additions:
#   - sha_sweep: Pallas SHA-256 lanes-per-grid-step curve + XLA point
#   - bench.py at 2^22 lanes (batch-width sweep, one step past 2^21)
#   - microbench at 2^20 (per-stage costs of the reworked walker)
# Never SIGTERM a mid-claim python process; claims error on their own.
#
#   nohup tools/measure_ladder3.sh >/dev/null 2>&1 &
#   tail -f /tmp/tpu_session3.log
cd "$(dirname "$0")/.."
log=${CT_LADDER3_LOG:-/tmp/tpu_session3.log}
prev=${CT_LADDER2_LOG:-/tmp/tpu_session2.log}
echo "=== ladder3 start $(date) ===" >> "$log"

if pgrep -f measure_ladder2.sh >/dev/null 2>&1; then
  echo "waiting for ladder2 ($prev)" >> "$log"
  while pgrep -f measure_ladder2.sh >/dev/null 2>&1 \
        && ! grep -q "=== ladder2 done" "$prev" 2>/dev/null; do
    sleep 60
  done
  echo "ladder2 done $(date)" >> "$log"
else
  while true; do
    python tools/probe_pool.py >> "$log" 2>&1
    if [ $? -eq 0 ]; then break; fi
    echo "--- still down $(date) ---" >> "$log"
    sleep 45
  done
fi

echo "=== running ladder3 $(date) ===" >> "$log"
echo "--- sha_sweep 2^20 ---" >> "$log"
timeout 1800 python tools/sha_sweep.py >> "$log" 2>&1
echo "--- microbench 1048576 (reworked walker) ---" >> "$log"
timeout 1500 python tools/microbench.py 1048576 >> "$log" 2>&1
echo "--- bench 2^22 lanes ---" >> "$log"
CT_BENCH_BATCH=4194304 CT_BENCH_WATCHDOG_SECS=520 CT_BENCH_E2E=0 \
  timeout 1200 python bench.py >> "$log" 2>&1
echo "=== ladder3 done $(date) ===" >> "$log"
