#!/usr/bin/env python3
"""ctmrlint — project-invariant static analysis over ct_mapreduce_tpu.

Thin launcher for ``ct_mapreduce_tpu.analysis.cli`` (also installed as
the ``ctmrlint`` console script). Run from the repo root:

    python tools/ctmrlint.py                # text report, exit 0/1/2
    python tools/ctmrlint.py --json         # machine-readable
    python tools/ctmrlint.py --rules lock-order,determinism

See docs/ANALYSIS.md for the rule set and the baseline workflow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
