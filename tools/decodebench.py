"""Native-decoder throughput vs worker count (the e2e budget's host leg).

The e2e ingest rate is host-decode-bound on single-core rigs (the
native C++ decoder measures ~150-250k entries/s per core; a 5M/s chip
needs tens of cores feeding it). This records the scaling table with
the host otherwise QUIET — run it alone: concurrent device probes on
the same host produced 5x scatter in earlier ad-hoc numbers.

  python tools/decodebench.py [n_entries] [workers...]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from ct_mapreduce_tpu.native import leafpack
    from ct_mapreduce_tpu.utils import syncerts

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 262144
    workers = [int(w) for w in sys.argv[2:]] or [1, 2, 4, 0]
    print(f"host: {os.cpu_count()} cpu(s); entries={n}", file=sys.stderr)

    tpls = [syncerts.make_template(issuer_cn=f"Dec {k}") for k in range(2)]
    t0 = time.time()
    lis, edl = syncerts.make_wire_batch(tpls, 0, n)
    print(f"wire setup {time.time() - t0:.1f}s", file=sys.stderr)

    for w in workers:
        best = None
        for _ in range(3):  # best-of-3: scheduling noise on small hosts
            t0 = time.time()
            db = leafpack.decode_raw_batch(lis, edl, 1024,
                                           workers=(w or None))
            dt = time.time() - t0
            assert int(db.ok_mask().sum()) == n
            best = dt if best is None else min(best, dt)
        print(json.dumps({
            "workers": w or "auto",
            "best_s": round(best, 3),
            "entries_per_sec": round(n / best, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
