"""One-shot TPU pool probe: claim the device, time it, run a trivial op.

Writes status lines to stdout (redirect to a file — see the bash pitfalls
note in the project memory: never pipe long runs through tail under
timeout). Exits 0 iff a device was claimed and a tiny op round-tripped.
"""
import sys
import time

t0 = time.time()
print(f"probe start {time.strftime('%Y-%m-%d %H:%M:%S')}", flush=True)
try:
    import jax

    devs = jax.devices()
    t1 = time.time()
    print(f"CLAIMED after {t1 - t0:.1f}s: {devs}", flush=True)
    import jax.numpy as jnp

    x = jnp.arange(8)
    val = int(jnp.sum(x))
    t2 = time.time()
    print(f"op ok ({val}) after {t2 - t1:.1f}s", flush=True)
    sys.exit(0)
except Exception as e:  # noqa: BLE001 - report any claim failure
    print(f"FAILED after {time.time() - t0:.1f}s: {type(e).__name__}: {e}", flush=True)
    sys.exit(1)
