"""Insert-only cost probe: the dedup-table insert under the trusted
timing contract (jitted fori_loop sweeps + synchronous value read —
`jax.block_until_ready` is not honored on this stack, BENCHLOG.md).

Isolates the table insert from the rest of the fused step so insert
formulation changes iterate without the full ~200s step compile: keys
are synthesized on device (SHA-free — four counter-derived words mixed
with an epoch), all-fresh per sweep, exactly the access pattern of the
headline's insert leg.

Run:  python tools/insertcost.py [batch] [log2_cap]
Env:  CTMR_TABLE=bucket|open, CT_IC_EXEC_SECS, CT_IC_SWEEPS
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.ops import buckettable, hashtable, pipeline

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    log2_cap = int(sys.argv[2]) if len(sys.argv) > 2 else 26
    cap = 1 << log2_cap
    exec_target_s = float(os.environ.get("CT_IC_EXEC_SECS", "4.0"))

    if os.environ.get("CTMR_TABLE", "bucket").strip().lower() == "open":
        mk_table = hashtable.make_table
    else:
        mk_table = buckettable.make_table

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) acquired in "
        f"{time.perf_counter() - t0:.1f}s; batch={batch} cap=2^{log2_cap}")

    lane = np.arange(batch, dtype=np.uint32)
    meta = jax.device_put(np.zeros((batch,), np.uint32))
    valid = jax.device_put(np.ones((batch,), bool))
    lane_dev = jax.device_put(lane)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def mega(table, acc, epoch_base, n_sweeps, lane, meta, valid):
        def keygen(e):
            # 4 well-mixed words from (epoch, lane): unique per sweep,
            # uniform over buckets — the all-fresh worst case.
            a = lane * jnp.uint32(0x9E3779B9) + e * jnp.uint32(0x85EBCA6B)
            b = (a ^ (a >> 15)) * jnp.uint32(0xC2B2AE35)
            c = (b ^ (b >> 13)) * jnp.uint32(0x27D4EB2F)
            d = (c ^ (c >> 16)) * jnp.uint32(0x165667B1)
            return jnp.stack([a ^ e, b, c, d], axis=1)

        def body(s, carry):
            table, acc = carry
            keys = keygen((epoch_base + s).astype(jnp.uint32))
            table, unknown, ovf = pipeline.table_insert(
                table, keys, meta, valid)
            return table, (acc + unknown.sum(dtype=jnp.int32)
                           + ovf.sum(dtype=jnp.int32))

        return jax.lax.fori_loop(0, n_sweeps, body, (table, acc))

    fetch = jax.jit(lambda a: a + a.dtype.type(0))
    table = mk_table(cap)
    acc = jax.device_put(np.int32(0))

    t0 = time.perf_counter()
    table, acc = mega(table, acc, np.uint32(0), np.int32(1),
                      lane_dev, meta, valid)
    int(fetch(acc))
    say(f"compile+warmup: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    table, acc = mega(table, acc, np.uint32(1), np.int32(1),
                      lane_dev, meta, valid)
    int(fetch(acc))
    per_sweep = max(time.perf_counter() - t0, 1e-4)
    budget = max(2, int(cap * 0.45) // batch - 3)
    n = max(2, min(int(exec_target_s / per_sweep), budget, 200))
    t0 = time.perf_counter()
    table, acc = mega(table, acc, np.uint32(2), np.int32(n),
                      lane_dev, meta, valid)
    int(fetch(acc))
    dt = (time.perf_counter() - t0) / n
    total = int(fetch(acc))
    load = total / (getattr(table, "capacity", cap))
    say(f"insert  {dt * 1e3:9.2f} ms/sweep  {dt / batch * 1e9:8.1f} ns/entry"
        f"  ({n} sweeps; end load {load:.1%}; fresh+ovf={total})")
    expect = (n + 2) * batch
    if total != expect:
        say(f"WARNING: fresh+overflow {total} != stamped {expect} "
            "(duplicate keygen or dropped lanes)")


if __name__ == "__main__":
    main()
