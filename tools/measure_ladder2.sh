#!/bin/bash
# Round-4 follow-up measurement ladder. The primary ladder
# (tools/measure_ladder.sh) was already running when these tools were
# built, and editing a live bash script corrupts its execution — so
# this one WAITS for the primary's "ladder done" marker (or for the
# pool if the primary isn't running) and then measures the round-4
# additions:
#   - load_sweep: insert throughput at 10/25/50/75% table load
#   - mosaic_probe: the Pallas walker bisect ladder, compiled
#   - CT_TPU_TESTS=1 hardware test tier (5 tests)
#   - bench.py at 2^21 lanes (batch-width sweep past the 2^20 default)
#   - PROBE_WIDTH=8 variant of the headline bench
# Never SIGTERM a mid-claim python process; claims error on their own.
#
#   nohup tools/measure_ladder2.sh >/dev/null 2>&1 &
#   tail -f /tmp/tpu_session2.log
cd "$(dirname "$0")/.."
log=${CT_LADDER2_LOG:-/tmp/tpu_session2.log}
primary=${CT_LADDER_LOG:-/tmp/tpu_session.log}
echo "=== ladder2 start $(date) ===" >> "$log"

# Phase 1: wait for the primary ladder to finish (it holds the chip),
# or — if it isn't running — for the pool itself.
if pgrep -f measure_ladder.sh >/dev/null 2>&1; then
  echo "waiting for primary ladder ($primary)" >> "$log"
  while pgrep -f measure_ladder.sh >/dev/null 2>&1 \
        && ! grep -q "=== ladder done" "$primary" 2>/dev/null; do
    sleep 60
  done
  echo "primary done $(date)" >> "$log"
else
  while true; do
    python tools/probe_pool.py >> "$log" 2>&1
    if [ $? -eq 0 ]; then break; fi
    echo "--- still down $(date) ---" >> "$log"
    sleep 45
  done
fi

echo "=== pool free $(date); running round-4 ladder ===" >> "$log"
echo "--- load_sweep 24 ---" >> "$log"
timeout 3000 python tools/load_sweep.py 24 0.10 0.25 0.50 0.75 >> "$log" 2>&1
echo "--- mosaic_probe compiled ---" >> "$log"
timeout 1800 python tools/mosaic_probe.py >> "$log" 2>&1
echo "--- hardware test tier ---" >> "$log"
CT_TPU_TESTS=1 timeout 2400 python -m pytest tests/test_tpu_hw.py -v >> "$log" 2>&1
echo "--- bench 2^21 lanes ---" >> "$log"
CT_BENCH_BATCH=2097152 CT_BENCH_WATCHDOG_SECS=520 CT_BENCH_E2E=0 \
  timeout 1200 python bench.py >> "$log" 2>&1
echo "--- bench default, PROBE_WIDTH=8 ---" >> "$log"
CTMR_PROBE_WIDTH=8 CT_BENCH_WATCHDOG_SECS=520 CT_BENCH_E2E=0 \
  timeout 1200 python bench.py >> "$log" 2>&1
echo "--- bench default (fused rows, full e2e) ---" >> "$log"
CT_BENCH_WATCHDOG_SECS=520 timeout 1200 python bench.py >> "$log" 2>&1
echo "=== ladder2 done $(date) ===" >> "$log"
