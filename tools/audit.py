#!/usr/bin/env python3
"""ct-audit — run the real-log audit pipeline (docs/AUDIT.md).

Recorded mode (default) replays a checked-in ``CTMRAU01`` shard
through decode → RFC 6962 TBS-reconstructed verify → aggregate, with
the native/mirror quarantine lane in front; ``--live`` fetches the
range from a real log over the production transport instead.

    python tools/audit.py --recorded tests/data/recorded_shard.json.gz
    python tools/audit.py --recorded shard.gz --tile 978   # ~1e6 entries
    python tools/audit.py --live https://ct.example/log \
        --log-list list.json --start 0 --end 9999
    python tools/audit.py ... --json --quarantine-dir /var/spool/ctmr

``--log-list`` (or ``CTMR_AUDIT_LOG_LIST`` / profile ``knobs.audit``)
names a log-list v3 JSON; recorded shards may embed their own, used
when no explicit list is given. Exit 0 on a clean run, 1 when any
lane was quarantined (counts are still correct — quarantined lanes
are excluded — but the divergence needs a human).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ct-audit", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--recorded", help="CTMRAU01 recorded-shard path")
    ap.add_argument("--tile", type=int, default=1,
                    help="resubmit the recorded pages N times "
                         "(shifted indices) for scale runs")
    ap.add_argument("--live", metavar="LOG_URL",
                    help="fetch from a live log instead")
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--end", type=int, default=999)
    ap.add_argument("--log-list", default=None,
                    help="log-list v3 JSON path (default: resolved "
                         "auditLogList knob, else the recorded "
                         "shard's embedded list)")
    ap.add_argument("--quarantine-dir", default=None,
                    help="durable divergence spool (default: resolved "
                         "auditQuarantineDir knob; empty = in-memory)")
    ap.add_argument("--flush-size", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--state", default=None,
                    help="save the aggregation checkpoint here after "
                         "the run (statistics/serve load it)")
    ap.add_argument("--emit-filter", default=None, metavar="PATH",
                    help="compile the audited corpus into a filter "
                         "artifact at PATH (written at checkpoint "
                         "time; implies --state PATH.state.npz)")
    ap.add_argument("--filter-fp", type=float, default=0.01)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    if bool(args.recorded) == bool(args.live):
        ap.error("exactly one of --recorded / --live is required")

    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu import audit as auditpkg
    from ct_mapreduce_tpu.audit import driver as drvlib
    from ct_mapreduce_tpu.audit import loglist as loglistlib

    list_path, qdir = auditpkg.resolve_audit(args.log_list,
                                             args.quarantine_dir)
    doc = drvlib.load_recorded(args.recorded) if args.recorded else None
    if list_path:
        log_list = loglistlib.load_log_list(list_path)
    elif doc is not None and doc.get("log_list"):
        log_list = loglistlib.parse_log_list(doc["log_list"])
    else:
        ap.error("no log list: pass --log-list, set "
                 "CTMR_AUDIT_LOG_LIST, or use a recorded shard that "
                 "embeds one")

    drv = drvlib.AuditDriver(
        log_list, quarantine_dir=qdir,
        flush_size=args.flush_size, batch_size=args.batch_size,
        filter_path=args.emit_filter or "",
        filter_fp=args.filter_fp)
    if args.recorded:
        rep = drv.run_recorded(doc, tile=args.tile)
    else:
        rep = drv.run_live(args.live, args.start, args.end)

    state_path = args.state or (
        args.emit_filter + ".state.npz" if args.emit_filter else None)
    if state_path:
        drv.aggregator.save_checkpoint(state_path)

    if args.json:
        json.dump(rep.to_json(), sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        r = rep
        print(f"audited {r.entries} entries ({r.pages} pages x "
              f"tile {r.tile}) in {r.wall_s:.1f}s")
        print(f"  verified {r.verified}  failed {r.failed}  "
              f"no-sct {r.verifier_no_sct}  no-key {r.verifier_no_key}")
        print(f"  device lanes {r.device_lanes}  host lanes "
              f"{r.host_lanes}")
        print(f"  flagged: retired {r.retired}  out-of-interval "
              f"{r.out_of_interval}  unknown-log {r.unknown_log}")
        div = ("measured" if r.divergence_measured
               else "NOT MEASURED (no native extractor)")
        print(f"  quarantined {r.quarantined} (divergence {div})")
        print("  per-issuer verified/failed:")
        for iss, (v, f) in sorted(rep.per_issuer.items()):
            print(f"    {iss}: {v}/{f}")
    return 1 if rep.quarantined else 0


if __name__ == "__main__":
    sys.exit(main())
