"""Hardware sweep: Pallas SHA-256 lanes-per-grid-step (CTMR_SHA_TILE).

The r03 measurement (0.50 ms @ 16,384 lanes, tile 512) sits ~30x above
the VPU's theoretical throughput for the 64 unrolled rounds; if the gap
is per-grid-step overhead, wider tiles close it. Times the kernel at a
production batch width across tile sizes, platform rules applied (many
invocations inside one jitted fori_loop, one synchronous value read;
a per-iteration block mutation stops XLA hoisting the call).

  python tools/sha_sweep.py [batch] [tile ...]   # defaults: 2^20 lanes,
                                                 # tiles 512 2048 8192
"""
import os
import sys
import time


def main() -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from ct_mapreduce_tpu.ops import pallas_sha256, sha256

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    tiles = [int(t) for t in sys.argv[2:]] or [512, 2048, 8192]
    reps = int(os.environ.get("CT_SHA_SWEEP_REPS", "16"))
    interpret = jax.default_backend() != "tpu"
    if interpret:
        print("WARNING: no TPU; interpret-mode numbers are meaningless "
              "as measurements (harness smoke only)", file=sys.stderr)
        batch, reps, tiles = 1024, 2, [128, 512]

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    print(f"device: {dev.platform} acquired in {time.perf_counter() - t0:.1f}s; "
          f"batch={batch} reps={reps}", flush=True)
    rng = np.random.default_rng(0)
    block_np = rng.integers(0, 2**32, size=(batch, 16), dtype=np.uint32)

    def timed(tile: int) -> float:
        @jax.jit
        def run(block):
            def body(i, carry):
                block, acc = carry
                block = block.at[0, 0].set(i.astype(jnp.uint32))
                d = pallas_sha256.sha256_fingerprint64_pallas(
                    block, interpret=interpret
                )
                return block, acc + d[0, 0]

            _, acc = jax.lax.fori_loop(
                0, reps, body, (block, jnp.uint32(0)))
            return acc

        os.environ["CTMR_SHA_TILE"] = str(tile)
        blk = jax.device_put(jnp.asarray(block_np))
        t0 = time.perf_counter()
        int(run(blk))  # compile + warm
        warm = time.perf_counter() - t0
        t0 = time.perf_counter()
        int(run(blk))
        dt = time.perf_counter() - t0
        lanes = batch * reps
        print(f"tile {tile:6d}: {dt:.3f}s / {lanes} lanes = "
              f"{dt / lanes * 1e9:6.1f} ns/lane "
              f"({lanes / dt / 1e6:8.2f}M lanes/s)  [compile+warm {warm:.1f}s]",
              flush=True)
        return dt

    # XLA-scan reference point at the same width (one tile value only).
    @jax.jit
    def run_xla(block):
        def body(i, carry):
            block, acc = carry
            block = block.at[0, 0].set(i.astype(jnp.uint32))
            d = sha256.sha256_single_block(block)[..., 4:]
            return block, acc + d[0, 0]

        _, acc = jax.lax.fori_loop(0, reps, body, (block, jnp.uint32(0)))
        return acc

    for tile in tiles:
        timed(tile)
    blk = jax.device_put(jnp.asarray(block_np))
    t0 = time.perf_counter()
    int(run_xla(blk))
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    int(run_xla(blk))
    dt = time.perf_counter() - t0
    lanes = batch * reps
    print(f"xla scan   : {dt:.3f}s / {lanes} lanes = "
          f"{dt / lanes * 1e9:6.1f} ns/lane  [compile+warm {warm:.1f}s]",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
