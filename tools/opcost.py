"""Raw XLA op-cost probe for the random-access primitives the dedup
table is built from: gather / scatter / scatter-min on an HBM-resident
table, at several table sizes, plus batch sort.

UNRELIABLE ON THIS STACK — kept for history. Despite the fori_loop
structure, measurements here disagree with the trusted probes by
orders of magnitude (2026-07-31 hardware run reported 0.002 ms for
ops tools/randacc.py prices at 13-15 ms with synchronous value
reads); loop-invariant operands likely let XLA hoist the op under
test. Use tools/randacc.py / tools/stagecost.py instead.

Run: python tools/opcost.py [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


REPS = int(os.environ.get("CT_OC_REPS", "32"))


def main():
    import jax
    import jax.numpy as jnp

    # Load-bearing despite looking redundant: the ambient axon
    # sitecustomize imports jax at interpreter start, after which the
    # env var alone no longer selects the platform (verified: without
    # this, JAX_PLATFORMS=cpu still initialized the axon backend).
    # Same workaround as tests/conftest.py and bench.py.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 131072
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) "
        f"in {time.perf_counter() - t0:.1f}s; batch={batch} reps={REPS}")
    sync = jax.block_until_ready

    # NumPy closures lower to HLO literals — no committed device
    # buffers may be closed over by jitted bodies (the axon dispatch
    # pathology bench.py documents), and donated carries must be
    # rebuilt per probe.
    rng = np.random.RandomState(0)
    idx = rng.randint(0, 1 << 20, size=(batch,)).astype(np.int32)
    vals = rng.randint(0, 2**31 - 1, size=(batch, 4)).astype(np.uint32)
    lane = np.arange(batch, dtype=np.int32)

    def loop_time(body, init_fn, reps=REPS):
        """Median wall time per rep of body, run inside one execution.
        ``init_fn`` builds a fresh carry per probe — the carry is
        DONATED through the loop (realistic in-place updates), so it
        must not be shared between probes."""
        fn = jax.jit(lambda c: jax.lax.fori_loop(0, reps, body, c),
                     donate_argnums=(0,))
        c = fn(init_fn())     # compile + first run
        sync(c)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            c = fn(c)
            sync(c)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) / reps, c

    for log2cap in (21, 24, 26):
        cap = 1 << log2cap
        slots = (idx * 7919) & (cap - 1)
        mb = cap * 16 / 2**20

        def mk_table():
            return (jnp.zeros((cap, 4), jnp.uint32), jnp.uint32(0))

        # gather rows
        def g_body(i, c):
            t, acc = c
            cur = t[(slots + i) & (cap - 1)]
            return t, acc + cur.sum(dtype=jnp.uint32)

        dt, _ = loop_time(g_body, mk_table)
        say(f"cap 2^{log2cap} ({mb:5.0f}MB): gather-row   "
            f"{dt * 1e3:7.3f} ms/op")

        # scatter rows (set)
        def s_body(i, c):
            t, _ = c
            t = t.at[(slots + i) & (cap - 1)].set(vals, mode="drop")
            return (t, c[1])

        dt, _ = loop_time(s_body, mk_table)
        say(f"cap 2^{log2cap} ({mb:5.0f}MB): scatter-row  "
            f"{dt * 1e3:7.3f} ms/op")

        # Fused-row read path: gather uint32[cap, 5] rows then slice
        # the 4 key words (exactly hashtable._probe_window's access) vs
        # gathering through a pre-sliced [:, :4] view — answers whether
        # XLA narrows the gather or fetches the dead meta word (and
        # whether the view formulation materializes a 4/5-size copy).
        def mk_table5():
            return (jnp.zeros((cap, 5), jnp.uint32), jnp.uint32(0))

        def g5_body(i, c):
            t, acc = c
            cur = t[(slots + i) & (cap - 1)][..., :4]
            return t, acc + cur.sum(dtype=jnp.uint32)

        dt, _ = loop_time(g5_body, mk_table5)
        say(f"cap 2^{log2cap} ({mb * 5 / 4:5.0f}MB): gather5-slice4 "
            f"{dt * 1e3:7.3f} ms/op")

        def g5v_body(i, c):
            t, acc = c
            cur = t[:, :4][(slots + i) & (cap - 1)]
            return t, acc + cur.sum(dtype=jnp.uint32)

        dt, _ = loop_time(g5v_body, mk_table5)
        say(f"cap 2^{log2cap} ({mb * 5 / 4:5.0f}MB): view4-gather   "
            f"{dt * 1e3:7.3f} ms/op")

        # scatter-min on int32[cap]
        def mk_claim():
            return (jnp.full((cap,), 2**31 - 1, jnp.int32),)

        def m_body(i, c):
            t, = c
            t = t.at[(slots + i) & (cap - 1)].min(lane, mode="drop")
            return (t,)

        dt, _ = loop_time(m_body, mk_claim)
        say(f"cap 2^{log2cap} ({mb / 4:5.0f}MB): scatter-min  "
            f"{dt * 1e3:7.3f} ms/op")

        # full-array fill (the per-call claim reset)
        def f_body(i, c):
            t, = c
            t = jnp.full((cap,), 2**31 - 1, jnp.int32) + i
            return (t,)

        dt, _ = loop_time(f_body, mk_claim)
        say(f"cap 2^{log2cap} ({mb / 4:5.0f}MB): fill         "
            f"{dt * 1e3:7.3f} ms/op")

    # Batch sorts. x64 is disabled by default (uint64 silently becomes
    # uint32), so probe what the code actually uses: a single uint32
    # key sort, and the stable 2-word lexsort (the wide-mesh dispatch
    # ranking and the old insert design's primitive).
    k32 = vals[:, 0]  # numpy → HLO literal in the probe bodies

    def sort_body(i, c):
        k, acc = c
        s = jnp.sort(k ^ i.astype(jnp.uint32))  # noqa: E501
        return k, acc + s[0]

    dt, _ = loop_time(sort_body, lambda: (jnp.asarray(k32), jnp.uint32(0)), reps=8)
    say(f"sort u32[{batch}]: {dt * 1e3:7.3f} ms/op")

    def argsort_body(i, c):
        k, acc = c
        s = jnp.argsort(k ^ i.astype(jnp.uint32))
        return k, acc + s[0]

    dt, _ = loop_time(argsort_body, lambda: (jnp.asarray(k32), jnp.int32(0)), reps=8)
    say(f"argsort u32[{batch}]: {dt * 1e3:7.3f} ms/op")

    def lexsort_body(i, c):
        k, acc = c
        order = jnp.lexsort((jnp.arange(batch, dtype=jnp.int32),
                             k ^ i.astype(jnp.uint32)))
        return k, acc + order[0]

    dt, _ = loop_time(lexsort_body, lambda: (jnp.asarray(k32), jnp.int32(0)), reps=8)
    say(f"lexsort (iota, u32)[{batch}]: {dt * 1e3:7.3f} ms/op")

    # gather/scatter over the BATCH (small array) for comparison
    sidx = (idx * 31) & (batch - 1) if batch & (batch - 1) == 0 else idx % batch

    def gs_body(i, c):
        t, acc = c
        cur = t[(sidx + i) % batch]
        return t.at[(sidx + i) % batch].set(cur + 1, mode="drop"), acc

    dt, _ = loop_time(
        gs_body, lambda: (jnp.zeros((batch, 4), jnp.uint32), jnp.uint32(0)))
    say(f"batch-sized gather+scatter [{batch},4]: {dt * 1e3:7.3f} ms/op")


if __name__ == "__main__":
    main()
