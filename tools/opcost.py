"""Raw XLA op-cost probe for the random-access primitives the dedup
table is built from: gather / scatter / scatter-min on an HBM-resident
table, at several table sizes, plus batch sort. Each measurement runs
R repetitions of the op INSIDE one jitted fori_loop (so per-dispatch
overhead is excluded — same structure as the bench's mega_step) and
reports per-op device time. Prints immediately per stage.

Run: python tools/opcost.py [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


REPS = int(os.environ.get("CT_OC_REPS", "32"))


def main():
    import jax
    import jax.numpy as jnp

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 131072
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) "
        f"in {time.perf_counter() - t0:.1f}s; batch={batch} reps={REPS}")
    sync = jax.block_until_ready

    key = jax.random.PRNGKey(0)
    idx = jax.random.randint(key, (batch,), 0, 1 << 20, dtype=jnp.int32)
    vals = jax.random.randint(key, (batch, 4), 0, 2**31 - 1,
                              dtype=jnp.int32).astype(jnp.uint32)
    lane = jnp.arange(batch, dtype=jnp.int32)
    sync((idx, vals))

    def loop_time(body, init, reps=REPS):
        """Median wall time per rep of body, run inside one execution."""
        fn = jax.jit(lambda c: jax.lax.fori_loop(0, reps, body, c),
                     donate_argnums=(0,))
        c = fn(init)          # compile + first run
        sync(c)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            c = fn(c)
            sync(c)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) / reps, c

    for log2cap in (21, 24, 26):
        cap = 1 << log2cap
        table = jnp.zeros((cap, 4), jnp.uint32)
        slots = (idx * 7919) & (cap - 1)
        mb = cap * 16 / 2**20

        # gather rows
        def g_body(i, c):
            t, acc = c
            cur = t[(slots + i) & (cap - 1)]
            return t, acc + cur.sum(dtype=jnp.uint32)

        dt, _ = loop_time(g_body, (table, jnp.uint32(0)))
        say(f"cap 2^{log2cap} ({mb:5.0f}MB): gather-row   "
            f"{dt * 1e3:7.3f} ms/op")

        # scatter rows (set)
        def s_body(i, c):
            t, = c
            t = t.at[(slots + i) & (cap - 1)].set(vals, mode="drop")
            return (t,)

        dt, _ = loop_time(s_body, (table,))
        say(f"cap 2^{log2cap} ({mb:5.0f}MB): scatter-row  "
            f"{dt * 1e3:7.3f} ms/op")

        # scatter-min on int32[cap]
        claim = jnp.full((cap,), 2**31 - 1, jnp.int32)

        def m_body(i, c):
            t, = c
            t = t.at[(slots + i) & (cap - 1)].min(lane, mode="drop")
            return (t,)

        dt, _ = loop_time(m_body, (claim,))
        say(f"cap 2^{log2cap} ({mb / 4:5.0f}MB): scatter-min  "
            f"{dt * 1e3:7.3f} ms/op")

        # full-array fill (the per-call claim reset)
        def f_body(i, c):
            t, = c
            t = jnp.full((cap,), 2**31 - 1, jnp.int32) + i
            return (t,)

        dt, _ = loop_time(f_body, (claim,))
        say(f"cap 2^{log2cap} ({mb / 4:5.0f}MB): fill         "
            f"{dt * 1e3:7.3f} ms/op")

    # sort of the batch (64-bit packed as 2x uint32 lexsort vs single)
    k64 = vals[:, 0].astype(jnp.uint64) << 32 | vals[:, 1].astype(jnp.uint64)

    def sort_body(i, c):
        k, acc = c
        s = jnp.sort(k + i.astype(jnp.uint64))
        return k, acc + s[0]

    dt, _ = loop_time(sort_body, (k64, jnp.uint64(0)), reps=8)
    say(f"sort u64[{batch}]: {dt * 1e3:7.3f} ms/op")

    def argsort_body(i, c):
        k, acc = c
        s = jnp.argsort(k + i.astype(jnp.uint64))
        return k, acc + s[0]

    dt, _ = loop_time(argsort_body, (k64, jnp.int32(0)), reps=8)
    say(f"argsort u64[{batch}]: {dt * 1e3:7.3f} ms/op")

    # gather/scatter over the BATCH (small array) for comparison
    small = jnp.zeros((batch, 4), jnp.uint32)
    sidx = (idx * 31) & (batch - 1) if batch & (batch - 1) == 0 else idx % batch

    def gs_body(i, c):
        t, acc = c
        cur = t[(sidx + i) % batch]
        return t.at[(sidx + i) % batch].set(cur + 1, mode="drop"), acc

    dt, _ = loop_time(gs_body, (small, jnp.uint32(0)))
    say(f"batch-sized gather+scatter [{batch},4]: {dt * 1e3:7.3f} ms/op")


if __name__ == "__main__":
    main()
