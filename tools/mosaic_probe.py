"""Graduated Mosaic probe ladder for the Pallas DER-walker retry.

Round 3 built a full Pallas walker that was parity-exact in interpret
mode but crashed this environment's remote Mosaic compiler with no
diagnostics (ARCHITECTURE.md "Performance engineering notes"); probe
kernels with the same primitives compiled fine. This ladder makes the
bisect repeatable: a sequence of kernels, each adding ONE construct on
the road from "elementwise add" to "chained TLV walk", run in
interpret mode (parity oracle) and then compiled on the real backend.
The first stage that compiles interpreted-but-crashes-compiled names
the guilty construct.

Usage:
  JAX_PLATFORMS=cpu python tools/mosaic_probe.py --interpret
      # interpret-mode run checked against pure-NumPy references
  python tools/mosaic_probe.py    # on TPU: compile + parity vs interpret

Prints one line per stage: PASS / FAIL(<error head>), and a final
summary. Exit code 0 iff every attempted stage passed.
"""

from __future__ import annotations

import os
import sys
import traceback

import numpy as np

LANES = 128  # one register tile of lanes
WORDS = 64  # 256-byte rows, word-packed like ops/der_kernel.py


def _setup():
    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    return jax


def _rows(seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(WORDS, LANES), dtype=np.uint32)


def _cert_rows() -> tuple[np.ndarray, np.ndarray]:
    """Word-pack LANES real DER certificates (one per lane) and return
    (rows uint32[WORDS, LANES], expected int32[1, LANES]) where
    expected mirrors stage 9's checksum via the exact host parser."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for path in (repo, os.path.join(repo, "tests")):
        if path not in sys.path:
            sys.path.insert(0, path)
    from certgen import make_cert  # tests fixture generator

    from ct_mapreduce_tpu.core import der as hostder

    rows = np.zeros((WORDS, LANES), np.uint32)
    expected = np.zeros((LANES,), np.int32)
    for i in range(LANES):
        # Serial lengths 1..20 bytes incl. leading-zero cases.
        serial = int.from_bytes(
            bytes([(i % 19) + 1]) * ((i % 20) + 1), "big")
        der = make_cert(serial=serial, is_ca=False,
                        subject_cn=f"probe{i}.example.com")
        padded = der[: WORDS * 4].ljust(WORDS * 4, b"\x00")
        w = np.frombuffer(padded, np.uint8).reshape(WORDS, 4)
        rows[:, i] = (
            (w[:, 0].astype(np.uint32) << 24)
            | (w[:, 1].astype(np.uint32) << 16)
            | (w[:, 2].astype(np.uint32) << 8)
            | w[:, 3].astype(np.uint32)
        )
        f = hostder.parse_cert(der)
        expected[i] = sum(f.serial) + len(f.serial) * 1000
    return rows, expected[None, :]


# --- stage bodies -------------------------------------------------------
# Every kernel takes words[WORDS, LANES] (lanes on the 128-axis, the
# layout the SHA kernel ships with) and writes out[1, LANES] int32.


def _read_vec(w, off, clip=False):
    """Shared one-hot byte read (big-endian word packing, the same
    convention as ops/der_kernel.py): int32[LANES] byte values.

    The reduction runs in INT32 over a bitcast of the uint32 words —
    exact for a one-hot sum (single nonzero term, bit pattern
    preserved) and required by Mosaic, whose 2026-07 toolchain names
    the old formulation's failure outright: "Reductions over unsigned
    integers not implemented" (the silent r03 crash, finally
    diagnosed)."""
    import jax
    import jax.numpy as jnp

    if clip:
        off = jnp.clip(off, 0, WORDS * 4 - 1)
    widx = off // 4
    sel = (jnp.arange(WORDS, dtype=jnp.int32)[:, None] == widx[None, :])
    w_i32 = jax.lax.bitcast_convert_type(w, jnp.int32)
    word = jax.lax.bitcast_convert_type(
        jnp.sum(jnp.where(sel, w_i32, 0), axis=0), jnp.uint32)
    shift = (3 - (off % 4)) * 8
    return ((word >> shift.astype(jnp.uint32)) & 0xFF).astype(jnp.int32)


def _read_np(w, off, clip=False):
    """NumPy mirror of _read_vec (the interpret-mode oracle)."""
    off = np.asarray(off, np.int64)
    if clip:
        off = np.clip(off, 0, WORDS * 4 - 1)
    word = w[off // 4, np.arange(LANES)]
    shift = (3 - (off % 4)) * 8
    return ((word >> shift.astype(np.uint32)) & 0xFF).astype(np.int32)


def k_elementwise(w_ref, o_ref):
    """Stage 0: pure elementwise + int32 sum — known-good baseline."""
    import jax.numpy as jnp

    w = w_ref[...].astype(jnp.int32)
    o_ref[...] = jnp.sum(w & 0xFF, axis=0, keepdims=True)


def k_onehot_read(w_ref, o_ref):
    """Stage 1: ONE one-hot byte read at a fixed offset — the walker's
    core primitive (word select × byte extract)."""
    import jax.numpy as jnp

    w = w_ref[...]
    off = jnp.full((LANES,), 17, jnp.int32)  # byte offset per lane
    o_ref[...] = _read_vec(w, off)[None, :]


def k_onehot_dyn(w_ref, o_ref):
    """Stage 2: one-hot read at a DATA-DEPENDENT offset (offset derived
    from row bytes — what a real TLV walk does)."""
    import jax.numpy as jnp

    w = w_ref[...]
    first = (w[0] >> 24).astype(jnp.int32) % (WORDS * 4 - 4)
    o_ref[...] = _read_vec(w, first)[None, :]


def k_fori_reads(w_ref, o_ref):
    """Stage 3: 16 sequential one-hot reads in a fori_loop with a
    carried per-lane offset (the walk loop skeleton)."""
    import jax
    import jax.numpy as jnp

    w = w_ref[...]

    def body(i, carry):
        off, acc = carry
        byte = _read_vec(w, off)
        off = (off + 1 + (byte & 3)) % (WORDS * 4 - 4)
        return off, acc + byte

    off0 = jnp.zeros((LANES,), jnp.int32)
    _, acc = jax.lax.fori_loop(0, 16, body, (off0, off0))
    o_ref[...] = acc[None, :]


def k_fori_masked(w_ref, o_ref):
    """Stage 4: fori body containing a MASKED reduction — one of the
    constructs round 3 suspected."""
    import jax
    import jax.numpy as jnp

    w = w_ref[...]

    def body(i, acc):
        mask = (w >> 8) % 3 == i % 3
        return acc + jnp.sum(
            jnp.where(mask, w & 0xFF, 0).astype(jnp.int32), axis=0)

    acc = jax.lax.fori_loop(0, 8, body, jnp.zeros((LANES,), jnp.int32))
    o_ref[...] = acc[None, :]


def k_uint_reduce(w_ref, o_ref):
    """Stage 5: the uint32-reduction WORKAROUND — bitcast to int32,
    reduce, bitcast back. A raw `jnp.sum(uint32)` is what Mosaic
    rejects ("Reductions over unsigned integers not implemented",
    diagnosed 2026-07-31 — the construct behind r03's silent crash);
    this stage proves the replacement compiles and matches."""
    import jax
    import jax.numpy as jnp

    w = w_ref[...]
    masked = jax.lax.bitcast_convert_type(w & jnp.uint32(0xFF), jnp.int32)
    o_ref[...] = jnp.sum(masked, axis=0).astype(jnp.int32)[None, :]


def k_while_early_exit(w_ref, o_ref):
    """Stage 6: while_loop with an any()-based early exit — the
    walker's scan loop shape."""
    import jax
    import jax.numpy as jnp

    w = w_ref[...]
    read = lambda off: _read_vec(w, off)  # noqa: E731

    def cond(carry):
        off, done, n = carry
        return (~jnp.all(done)) & (n < 32)

    def body(carry):
        off, done, n = carry
        byte = read(off)
        done = done | (byte == 0)
        off = jnp.where(done, off, (off + 1) % (WORDS * 4 - 4))
        return off, done, n + 1

    off0 = jnp.zeros((LANES,), jnp.int32)
    off, _, _ = jax.lax.while_loop(
        cond, body, (off0, jnp.zeros((LANES,), bool), jnp.int32(0)))
    o_ref[...] = off[None, :]


def k_tlv_step(w_ref, o_ref):
    """Stage 7: one real TLV header decode — tag byte, short/long length
    forms, offset advance (the walker's inner step, selects + shifts)."""
    import jax.numpy as jnp

    w = w_ref[...]
    read = lambda off: _read_vec(w, off)  # noqa: E731

    off = jnp.zeros((LANES,), jnp.int32)
    l0 = read(off + 1)
    long_form = l0 >= 0x80
    nlen = jnp.where(long_form, l0 & 0x7F, 0)
    l1 = read(off + 2)
    l2 = read(off + 3)
    content_len = jnp.where(
        long_form,
        jnp.where(nlen == 1, l1, l1 * 256 + l2),
        l0,
    )
    hdr = 2 + jnp.where(long_form, nlen, 0)
    o_ref[...] = jnp.clip(off + hdr + content_len, 0,
                          WORDS * 4 - 1)[None, :]


def k_tlv_walk(w_ref, o_ref):
    """Stage 8: chained TLV walk — 12 header decodes in a fori_loop,
    data-dependent offsets, the full walker shape in miniature."""
    import jax
    import jax.numpy as jnp

    w = w_ref[...]
    read = lambda off: _read_vec(w, off, clip=True)  # noqa: E731

    def body(i, carry):
        off, acc = carry
        l0 = read(off + 1)
        long_form = l0 >= 0x80
        nlen = jnp.where(long_form, l0 & 0x7F, 0)
        l1 = read(off + 2)
        l2 = read(off + 3)
        content = jnp.where(
            long_form, jnp.where(nlen == 1, l1, l1 * 256 + l2), l0)
        hdr = 2 + jnp.where(long_form, nlen, 0)
        # Descend into constructed tags, skip primitives — both paths
        # appear in the real walker.
        tag = read(off)
        constructed = (tag & 0x20) != 0
        nxt = jnp.where(constructed, off + hdr, off + hdr + content)
        nxt = nxt % (WORDS * 4 - 4)
        return nxt, acc + (tag & 0xFF)

    off0 = jnp.zeros((LANES,), jnp.int32)
    off, acc = jax.lax.fori_loop(0, 12, body, (off0, off0))
    o_ref[...] = (off + acc)[None, :]


# --- NumPy references (the TRUE oracle; interpret mode is checked
# against these, compiled mode against interpret) ------------------------


def r_elementwise(w):
    return (w & 0xFF).astype(np.int64).sum(0).astype(np.int32)[None, :]


def r_onehot_read(w):
    return _read_np(w, np.full((LANES,), 17))[None, :]


def r_onehot_dyn(w):
    first = (w[0] >> np.uint32(24)).astype(np.int32) % (WORDS * 4 - 4)
    return _read_np(w, first)[None, :]


def r_fori_reads(w):
    off = np.zeros((LANES,), np.int64)
    acc = np.zeros((LANES,), np.int64)
    for _ in range(16):
        byte = _read_np(w, off)
        off = (off + 1 + (byte & 3)) % (WORDS * 4 - 4)
        acc += byte
    return acc.astype(np.int32)[None, :]


def r_fori_masked(w):
    acc = np.zeros((LANES,), np.int64)
    for i in range(8):
        mask = (w >> np.uint32(8)) % 3 == i % 3
        acc += np.where(mask, w & 0xFF, 0).astype(np.int64).sum(0)
    return acc.astype(np.int32)[None, :]


def r_uint_reduce(w):
    return (w & 0xFF).astype(np.int64).sum(0).astype(np.int32)[None, :]


def r_while_early_exit(w):
    off = np.zeros((LANES,), np.int64)
    done = np.zeros((LANES,), bool)
    for _ in range(32):
        if done.all():
            break
        byte = _read_np(w, off)
        done = done | (byte == 0)
        off = np.where(done, off, (off + 1) % (WORDS * 4 - 4))
    return off.astype(np.int32)[None, :]


def _tlv_np(w, off):
    l0 = _read_np(w, off + 1, clip=True)
    long_form = l0 >= 0x80
    nlen = np.where(long_form, l0 & 0x7F, 0)
    l1 = _read_np(w, off + 2, clip=True)
    l2 = _read_np(w, off + 3, clip=True)
    content = np.where(long_form, np.where(nlen == 1, l1, l1 * 256 + l2), l0)
    hdr = 2 + np.where(long_form, nlen, 0)
    return content.astype(np.int64), hdr.astype(np.int64)


def r_tlv_step(w):
    off = np.zeros((LANES,), np.int64)
    content, hdr = _tlv_np(w, off)
    return np.clip(off + hdr + content, 0, WORDS * 4 - 1).astype(
        np.int32)[None, :]


def r_tlv_walk(w):
    off = np.zeros((LANES,), np.int64)
    acc = np.zeros((LANES,), np.int64)
    for _ in range(12):
        content, hdr = _tlv_np(w, off)
        tag = _read_np(w, off, clip=True)
        constructed = (tag & 0x20) != 0
        nxt = np.where(constructed, off + hdr, off + hdr + content)
        off = nxt % (WORDS * 4 - 4)
        acc += tag
    return (off + acc).astype(np.int32)[None, :]


def k_serial_extract(w_ref, o_ref):
    """Stage 9: REAL walker fragment — serial extraction from genuine
    DER certificates: three nested TLV header decodes (cert SEQUENCE →
    TBS SEQUENCE → optional [0] version → serial INTEGER), then a
    masked byte-sum over the serial content window. Combines every
    suspected construct on real data."""
    import jax.numpy as jnp

    w = w_ref[...]
    read = lambda off: _read_vec(w, off, clip=True)  # noqa: E731

    def hdr(off):
        l0 = read(off + 1)
        long_form = l0 >= 0x80
        nlen = jnp.where(long_form, l0 & 0x7F, 0)
        l1 = read(off + 2)
        l2 = read(off + 3)
        content = jnp.where(
            long_form, jnp.where(nlen == 1, l1, l1 * 256 + l2), l0)
        return read(off), 2 + jnp.where(long_form, nlen, 0), content

    # cert SEQUENCE → TBS SEQUENCE → (maybe [0] version) → serial
    _tag0, h0, _c0 = hdr(jnp.zeros((LANES,), jnp.int32))
    tbs_off = h0
    _tag1, h1, _c1 = hdr(tbs_off)
    el = tbs_off + h1
    tag_e, h_e, c_e = hdr(el)
    has_version = tag_e == 0xA0
    ser_el = jnp.where(has_version, el + h_e + c_e, el)
    tag_s, h_s, c_s = hdr(ser_el)
    ser_off = ser_el + h_s
    ser_len = c_s
    # Masked byte-sum over [ser_off, ser_off+ser_len): unpack every
    # word into its 4 bytes via shifts (vector work), mask on a byte-
    # position iota, reduce.
    pos_w = jnp.arange(WORDS, dtype=jnp.int32)[:, None]  # word index
    total = jnp.zeros((LANES,), jnp.int32)
    for k in range(4):
        byte = ((w >> jnp.uint32((3 - k) * 8)) & 0xFF).astype(jnp.int32)
        bpos = pos_w * 4 + k  # [WORDS, 1] byte position
        mask = (bpos >= ser_off[None, :]) & (bpos < (ser_off + ser_len)[None, :])
        total = total + jnp.sum(jnp.where(mask, byte, 0), axis=0)
    ok = (tag_s == 0x02).astype(jnp.int32)
    o_ref[...] = (total * ok + ser_len * 1000 * ok)[None, :]



STAGES = [
    ("0-elementwise", k_elementwise, r_elementwise),
    ("1-onehot-fixed", k_onehot_read, r_onehot_read),
    ("2-onehot-dynamic", k_onehot_dyn, r_onehot_dyn),
    ("3-fori-reads", k_fori_reads, r_fori_reads),
    ("4-fori-masked-reduce", k_fori_masked, r_fori_masked),
    ("5-uint32-reduce", k_uint_reduce, r_uint_reduce),
    ("6-while-early-exit", k_while_early_exit, r_while_early_exit),
    ("7-tlv-header", k_tlv_step, r_tlv_step),
    ("8-tlv-walk", k_tlv_walk, r_tlv_walk),
    ("9-serial-extract", k_serial_extract, None),  # oracle: host parser
]


def run_stage(jax, name, kernel, ref_fn, interpret: bool):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if ref_fn is None:  # stage 9: real certs, host-parser oracle
        w, oracle_out = _cert_rows()
        ref_fn = lambda _w: oracle_out  # noqa: E731
    else:
        w = _rows()

    def call(interp):
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec((WORDS, LANES), lambda: (0, 0))],
            out_specs=pl.BlockSpec((1, LANES), lambda: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            interpret=interp,
        )(jnp.asarray(w))

    ref = np.asarray(call(True))
    oracle = ref_fn(w)
    if not np.array_equal(ref, oracle):
        return False, "INTERPRET-WRONG (kernel disagrees with NumPy oracle)"
    if interpret:
        return True, "interpret matches NumPy oracle"
    got = np.asarray(call(False))
    if not np.array_equal(got, ref):
        return False, "COMPILED-BUT-WRONG (parity mismatch vs interpret)"
    return True, "compiled, parity exact"


def time_stage9(jax) -> None:
    """Compiled throughput of the stage-9 walker fragment at 2^20
    lanes: the number that validates (or kills) the ~0.06 µs/entry
    Pallas-walker projection in ARCHITECTURE's roofline table.

    Platform rules apply: many kernel invocations inside ONE jitted
    fori_loop execution, one synchronous readback. The loop carry
    mutates one lane's words per iteration so XLA cannot hoist the
    loop-invariant call."""
    import time

    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    G = int(os.environ.get("CT_PROBE_TIME_TILES", "8192"))  # 2^20 lanes
    reps = int(os.environ.get("CT_PROBE_TIME_REPS", "32"))
    w, _ = _cert_rows()
    big = np.tile(w, (1, G))

    # CT_PROBE_TIME_INTERPRET=1: run the harness under the interpreter
    # (CPU smoke of the timing plumbing; meaningless as a measurement).
    interp = os.environ.get("CT_PROBE_TIME_INTERPRET") == "1"
    fn = pl.pallas_call(
        k_serial_extract,
        grid=(G,),
        in_specs=[pl.BlockSpec((WORDS, LANES), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, LANES), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, G * LANES), jnp.int32),
        interpret=interp,
    )

    @jax.jit
    def run(big):
        def body(i, carry):
            big, acc = carry
            big = big.at[:, :1].set(
                jnp.broadcast_to(i.astype(jnp.uint32), (WORDS, 1)))
            return big, acc + fn(big)[0, LANES]
        _, acc = jax.lax.fori_loop(
            0, reps, body, (big, jnp.int32(0)))
        return acc

    dev_big = jax.device_put(jnp.asarray(big))
    t0 = time.perf_counter()
    int(run(dev_big))  # compile + warm
    print(f"stage-9 timing: compile+warm {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    int(run(dev_big))
    dt = time.perf_counter() - t0
    lanes = G * LANES * reps
    print(f"stage-9 serial-extract: {dt:.3f}s for {lanes} lanes = "
          f"{dt / lanes * 1e9:.1f} ns/lane "
          f"({lanes / dt / 1e6:.2f}M lanes/s)", flush=True)


def main() -> int:
    jax = _setup()
    interpret = "--interpret" in sys.argv
    backend = jax.default_backend()
    print(f"backend: {backend}; mode: "
          f"{'interpret parity' if interpret else 'compile + parity'}",
          file=sys.stderr)
    failures = []
    for name, kernel, ref_fn in STAGES:
        try:
            ok, msg = run_stage(jax, name, kernel, ref_fn, interpret)
        except Exception as err:  # noqa: BLE001 — report, keep probing
            head = f"{type(err).__name__}: {err}".splitlines()[0][:160]
            ok, msg = False, f"CRASH {head}"
            if os.environ.get("CT_PROBE_VERBOSE"):
                traceback.print_exc()
        print(f"{'PASS' if ok else 'FAIL'} {name}: {msg}", flush=True)
        if not ok:
            failures.append(name)
    print(f"{len(STAGES) - len(failures)}/{len(STAGES)} stages passed"
          + (f"; first failure: {failures[0]}" if failures else ""))
    if not interpret and backend == "tpu" and "9-serial-extract" not in failures:
        try:
            time_stage9(jax)
        except Exception as err:  # noqa: BLE001 — timing is best-effort
            print(f"stage-9 timing failed: "
                  f"{type(err).__name__}: {err}"[:200], file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
