"""cProfile harness for the e2e store+flush path on the real chip.

Builds three 64K-entry wire-format batches, warms the compiled step,
then profiles two batches through AggregatorSink — the tool that found
the round-4 e2e readback pathologies (twelve per-chunk device reads,
the 64 MB device-batch re-fetch, the np.unique(axis=0) lexsort; see
BENCHLOG round 4). Run on TPU:  python tools/e2eprof.py
"""
import base64, cProfile, pstats, sys, time, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
from ct_mapreduce_tpu.utils import syncerts

batch = 65536
tpls = [syncerts.make_template(issuer_cn=f"Bench Issuer {k}") for k in range(2)]
eds_cache = [base64.b64encode(leaflib.encode_extra_data([t.issuer_der])).decode() for t in tpls]
def mk(i):
    lis, eds = [], []
    for j in range(batch):
        k = j & 1
        der = syncerts.stamp_serial(tpls[k], i * batch + j)
        lis.append(base64.b64encode(leaflib.encode_leaf_input(der, 1_700_000_000_000 + j)).decode())
        eds.append(eds_cache[k])
    return RawBatch(lis, eds, i * batch, "bench-log")

rb0, rb1, rb2 = mk(0), mk(1), mk(2)
cap = 1 << 19
agg = TpuAggregator(capacity=cap, batch_size=batch)
sink = AggregatorSink(agg, flush_size=batch, device_queue_depth=2)
t0=time.perf_counter(); sink.store_raw_batch(rb0); sink.flush()
print(f"warm {time.perf_counter()-t0:.1f}s", file=sys.stderr)

pr = cProfile.Profile()
pr.enable()
t0=time.perf_counter()
sink.store_raw_batch(rb1)
sink.store_raw_batch(rb2)
sink.flush()
dt = time.perf_counter()-t0
pr.disable()
print(f"2 batches in {dt:.2f}s = {2*batch/dt:,.0f}/s", file=sys.stderr)
st = pstats.Stats(pr, stream=sys.stderr)
st.sort_stats('cumulative').print_stats(25)
