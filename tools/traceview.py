"""Summarize a Chrome trace-event JSON (telemetry/trace.py output)
into per-stage occupancy and gap statistics.

The question a pipelined-ingest trace answers is "which stage starved
which": per span name this prints span count, total busy seconds,
occupancy (busy / trace wall), and the largest gap between consecutive
spans of that stage — a stage with low occupancy and large gaps is
waiting on its upstream; stages whose occupancies sum past 1.0 are
genuinely overlapping.

Usage:
  python tools/traceview.py /tmp/trace.json [--stages name1,name2,...]
  python tools/traceview.py --merge w0.json w1.json ... \
      [--skew pairs.json] [--out merged.json]

``--merge`` (round 23) stitches the per-process trace files of a live
``tools/fleet.py`` run into ONE Perfetto-loadable timeline: each file
is shifted onto the corrected wall clock using the (wall, monotonic)
pairs the workers exchanged through the coordinator fabric (``--skew``
is a JSON object ``{"<worker_id>": {"wall": ..., "mono": ...}}`` —
e.g. fleetobs.clock_pairs_from_obs output; without it each trace's own
startup pair is used, exact on a shared-boot host). Tracks are named
per worker/pid, so one ct-query request reads as one flow across both
processes under one ``trace_id``.

Also importable: ``load(path)`` / ``stage_summary(events)`` are the
parsing half of bench.py's span-derived smoke occupancy and of
tests/test_trace.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load(path: str) -> list[dict]:
    """Read trace events from either JSON form (object with
    ``traceEvents`` or a bare event array)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    if isinstance(doc, list):
        return doc
    raise ValueError(f"{path}: not a Chrome trace-event JSON")


def load_doc(path: str) -> dict:
    """Read one trace file as a full doc (``otherData`` kept — the
    merge needs the clock anchors and process attrs)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        doc = {"traceEvents": doc, "otherData": {}}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a Chrome trace-event JSON")
    return doc


def merge(paths: list[str], skew_path: str = "",
          out_path: str = "") -> dict:
    """Stitch per-process traces into one skew-corrected doc; writes
    ``out_path`` when given. The correction math lives in
    telemetry/fleetobs.py (unit-tested there)."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from ct_mapreduce_tpu.telemetry import fleetobs

    pairs = None
    if skew_path:
        with open(skew_path) as fh:
            raw = json.load(fh)
        pairs = {int(k): v for k, v in raw.items()
                 if isinstance(v, dict) and "wall" in v and "mono" in v}
    merged = fleetobs.merge_traces([load_doc(p) for p in paths],
                                   pairs=pairs)
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(merged, fh)
    return merged


def complete_spans(events: list[dict]) -> list[dict]:
    """The duration ("X") events, sorted by start timestamp."""
    return sorted(
        (e for e in events if e.get("ph") == "X"),
        key=lambda e: (e.get("ts", 0.0), -e.get("dur", 0.0)),
    )


def stage_summary(events: list[dict], stages=None,
                  t0_us: float = None, t1_us: float = None) -> dict:
    """Per-name span statistics over ``events`` (optionally windowed
    to [t0_us, t1_us] and filtered to ``stages``).

    Returns ``{name: {"count", "busy_s", "first_us", "last_us",
    "max_gap_s", "occupancy"}}`` plus a ``"_wall_s"`` entry — the span
    of the whole selection, the denominator of every occupancy.
    Same-name spans never self-nest in this codebase, so per-name busy
    is a plain duration sum (distinct-name nesting does not
    double-count within a name).
    """
    spans = complete_spans(events)
    if t0_us is not None:
        spans = [e for e in spans if e["ts"] >= t0_us]
    if t1_us is not None:
        spans = [e for e in spans if e["ts"] + e.get("dur", 0.0) <= t1_us]
    if stages is not None:
        stages = set(stages)
        spans = [e for e in spans if e["name"] in stages]
    if not spans:
        return {"_wall_s": 0.0}
    wall_us = (max(e["ts"] + e.get("dur", 0.0) for e in spans)
               - min(e["ts"] for e in spans))
    by_name: dict[str, list[dict]] = defaultdict(list)
    for e in spans:
        by_name[e["name"]].append(e)
    out: dict = {"_wall_s": wall_us / 1e6}
    for name, evs in by_name.items():
        busy_us = sum(e.get("dur", 0.0) for e in evs)
        max_gap = 0.0
        prev_end = None
        for e in evs:  # already ts-sorted
            if prev_end is not None:
                max_gap = max(max_gap, e["ts"] - prev_end)
            prev_end = max(prev_end or 0.0, e["ts"] + e.get("dur", 0.0))
        out[name] = {
            "count": len(evs),
            "busy_s": busy_us / 1e6,
            "first_us": evs[0]["ts"],
            "last_us": prev_end,
            "max_gap_s": max_gap / 1e6,
            "occupancy": (busy_us / wall_us) if wall_us > 0 else 0.0,
        }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="Chrome trace-event JSON path(s); several "
                         "with --merge")
    ap.add_argument("--stages", default="",
                    help="comma-separated span names to include "
                         "(default: all)")
    ap.add_argument("--merge", action="store_true",
                    help="stitch per-process traces into one "
                         "skew-corrected timeline")
    ap.add_argument("--skew", default="",
                    help="worker→(wall, mono) clock-pair JSON from the "
                         "coordinator fabric (with --merge)")
    ap.add_argument("--out", default="",
                    help="write the merged trace here (with --merge)")
    args = ap.parse_args(argv)
    stages = [s for s in args.stages.split(",") if s] or None
    if args.merge:
        merged = merge(args.trace, skew_path=args.skew,
                       out_path=args.out)
        n = sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
        print(f"merged {merged['otherData']['merged_from']} traces, "
              f"{n} events"
              + (f" -> {args.out}" if args.out else ""))
        events = merged["traceEvents"]
    elif len(args.trace) > 1:
        print("multiple trace files need --merge", file=sys.stderr)
        return 2
    else:
        events = load(args.trace[0])
    summary = stage_summary(events, stages=stages)
    wall = summary.pop("_wall_s")
    if not summary:
        print("no complete spans in trace", file=sys.stderr)
        return 1
    print(f"trace wall: {wall:.3f}s over "
          f"{sum(s['count'] for s in summary.values())} spans")
    hdr = f"{'stage':<28} {'count':>7} {'busy_s':>9} {'occ':>6} {'max_gap_s':>10}"
    print(hdr)
    print("-" * len(hdr))
    occ_sum = 0.0
    for name in sorted(summary, key=lambda n: -summary[n]["busy_s"]):
        s = summary[name]
        occ_sum += s["occupancy"]
        print(f"{name:<28} {s['count']:>7} {s['busy_s']:>9.3f} "
              f"{s['occupancy']:>6.2f} {s['max_gap_s']:>10.3f}")
    print(f"{'(sum)':<28} {'':>7} {'':>9} {occ_sum:>6.2f}")
    if occ_sum > 1.05:
        print("occupancies sum past 1.0: stages are overlapping")
    return 0


if __name__ == "__main__":
    sys.exit(main())
