"""Sharded-step overhead on real hardware, mesh=1 (VERDICT r04 #2).

ARCHITECTURE.md's scale-out projection assumed the routing stages
(fixed-cap dispatch, all_to_all, shard-local insert, inverse route,
psum) cost little; until round 5 that had only ever run on virtual CPU
meshes. This probe times the FULL sharded step (shard_map over a
1-device mesh — all_to_all degenerates to a copy but every routing
stage still executes) under the trusted contract (jitted fori_loop
sweeps, synchronous value read), on the same resident batches as the
plain fused step, so

    overhead = sharded ns/entry  -  plain ns/entry   (same data)

is a measured number. Run both on one chip:

    python tools/shardcost.py [batch] [log2_cap]     # sharded step
    python tools/stagecost.py [batch] full           # plain step

Env: CT_SC_EXEC_SECS, CT_SC_PADLEN, CTMR_TABLE (bucket default).
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from ct_mapreduce_tpu.agg import sharded
    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import pipeline
    from ct_mapreduce_tpu.utils import syncerts

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    log2_cap = int(sys.argv[2]) if len(sys.argv) > 2 else 26
    cap_slots = 1 << log2_cap
    pad_len = int(os.environ.get("CT_SC_PADLEN", "1024"))
    exec_target_s = float(os.environ.get("CT_SC_EXEC_SECS", "4.0"))

    t0 = time.perf_counter()
    dev = jax.devices()[0]
    say(f"device: {dev.platform} ({dev.device_kind}) acquired in "
        f"{time.perf_counter() - t0:.1f}s; batch={batch} cap=2^{log2_cap} "
        f"mesh=1")

    mesh = Mesh(np.array(jax.devices()[:1]), (sharded.AXIS,))
    dedup = sharded.ShardedDedup(
        mesh, capacity=sharded.mesh_capacity(1, cap_slots))
    n = dedup.n_shards
    b_loc = batch // n
    cap = min(b_loc, max(8, int(dedup.dispatch_factor * b_loc / n)))

    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    row_sh = NamedSharding(mesh, P(sharded.AXIS))
    issuer_idx = jax.device_put(np.zeros((batch,), np.int32), row_sh)
    valid = jax.device_put(np.ones((batch,), bool), row_sh)
    epoch_cols = tpl.serial_off + np.arange(4, 8, dtype=np.int32)
    no_cn = jnp.zeros((0, 32), jnp.uint8)
    no_cn_lens = jnp.zeros((0, 2), jnp.int32)
    now_hour = 500_000

    local = functools.partial(
        sharded._local_step,
        n_shards=n, cap=cap, num_issuers=dedup.num_issuers,
        max_probes=dedup.max_probes, bucket=dedup.layout == "bucket",
        axis=dedup.axis,
    )
    A = P(sharded.AXIS)
    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=(A, A, A, A, A, A, P(), P(), P(), P()),
        out_specs=(
            A, A,
            sharded.ShardedStepOut(
                was_unknown=A, host_lane=A, filtered_ca=A,
                filtered_expired=A, filtered_cn=A, not_after_hour=A,
                serials=A, serial_len=A, issuer_unknown_counts=P(),
                has_crldp=A, crldp_off=A, crldp_len=A,
                issuer_name_off=A, issuer_name_len=A,
                probe_overflow=A, dispatch_dropped=A,
            ),
        ),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def mega(rows, count, acc, epoch_base, n_sweeps, datas, lens,
             issuer_idx, valid):
        def body(s, carry):
            rows, count, acc = carry
            e = (epoch_base + s).astype(jnp.uint32)
            eb = jnp.stack(
                [(e >> 24) & 0xFF, (e >> 16) & 0xFF, (e >> 8) & 0xFF,
                 e & 0xFF]).astype(jnp.uint8)
            data = datas[0].at[:, epoch_cols].set(eb[None, :])
            rows, count, out = mapped(
                rows, count, data, lens[0], issuer_idx, valid,
                jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
                no_cn, no_cn_lens)
            return rows, count, (
                acc + out.was_unknown.sum(dtype=jnp.int32)
                + out.host_lane.sum(dtype=jnp.int32)
                + out.dispatch_dropped.sum(dtype=jnp.int32))
        return jax.lax.fori_loop(0, n_sweeps, body, (rows, count, acc))

    fetch = jax.jit(lambda a: a + a.dtype.type(0))
    acc = jax.device_put(np.int32(0))
    rows, count = dedup.rows, dedup.count

    t0 = time.perf_counter()
    rows, count, acc = mega(rows, count, acc, np.uint32(0), np.int32(1),
                            datas, lens, issuer_idx, valid)
    int(fetch(acc))
    say(f"compile+warmup: {time.perf_counter() - t0:.1f}s "
        f"(dispatch cap={cap}/lane-pair)")
    t0 = time.perf_counter()
    rows, count, acc = mega(rows, count, acc, np.uint32(1), np.int32(1),
                            datas, lens, issuer_idx, valid)
    int(fetch(acc))
    per_sweep = max(time.perf_counter() - t0, 1e-4)
    budget = max(2, int(dedup.capacity * 0.45) // batch - 3)
    # Floor of 8 sweeps: the calibration sweep carries the whole
    # per-execution readback toll (~0.2-0.5s), so trusting it alone
    # can shrink the timed run to 2 sweeps and leave the toll as a
    # ~100 ns/entry bias in the reported number.
    nswp = max(8, min(max(int(exec_target_s / per_sweep), 8), budget, 200))
    t0 = time.perf_counter()
    rows, count, acc = mega(rows, count, acc, np.uint32(2), np.int32(nswp),
                            datas, lens, issuer_idx, valid)
    int(fetch(acc))
    dt = (time.perf_counter() - t0) / nswp
    total = int(fetch(acc))
    say(f"sharded {dt * 1e3:9.2f} ms/sweep  {dt / batch * 1e9:8.1f} "
        f"ns/entry  ({nswp} sweeps; accounted={total} "
        f"expect={(nswp + 2) * batch})")
    if total != (nswp + 2) * batch:
        say("WARNING: accounted lanes != stamped lanes")


if __name__ == "__main__":
    main()
