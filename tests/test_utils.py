"""Duration parsing, backoff, and telemetry sink tests."""

import io

import pytest

from ct_mapreduce_tpu.telemetry import metrics
from ct_mapreduce_tpu.telemetry.metrics import InMemSink, MetricsDumper
from ct_mapreduce_tpu.utils import JitteredBackoff, format_duration, parse_duration


def test_parse_duration_go_syntax():
    assert parse_duration("15m") == 900
    assert parse_duration("125ms") == 0.125
    assert parse_duration("5s") == 5
    assert parse_duration("2h45m") == 2 * 3600 + 45 * 60
    assert parse_duration("10m") == 600
    assert parse_duration("1.5s") == 1.5
    assert parse_duration("-30s") == -30
    assert parse_duration("0") == 0


def test_parse_duration_rejects_garbage():
    for bad in ("", "fifteen", "15", "m15", "15 m"):
        with pytest.raises(ValueError):
            parse_duration(bad)


def test_format_duration():
    assert format_duration(900) == "15m"
    assert format_duration(0.125) == "125ms"
    assert format_duration(2 * 3600 + 45 * 60) == "2h45m"
    assert format_duration(0) == "0s"
    assert parse_duration(format_duration(3725.5)) == 3725.5


def test_backoff_growth_and_cap():
    b = JitteredBackoff(min_s=0.5, max_s=300, jitter=False)
    ds = [b.duration() for _ in range(12)]
    assert ds[0] == 0.5
    assert ds[1] == 1.0
    assert all(x <= 300 for x in ds)
    assert ds[-1] == 300
    b.reset()
    assert b.duration() == 0.5


def test_backoff_jitter_bounds():
    b = JitteredBackoff(min_s=0.5, max_s=300, jitter=True)
    for _ in range(50):
        d = b.duration()
        assert 0.5 <= d <= 300


def test_metrics_sink_and_dumper():
    sink = InMemSink()
    metrics.set_sink(sink)
    metrics.incr_counter("certIsFilteredOut", "CA")
    metrics.incr_counter("certIsFilteredOut", "CA")
    metrics.incr_counter("insertCTWorker", "Inserted", value=5)
    metrics.set_gauge("entries_per_sec_per_chip", value=1e7)
    with metrics.measure("insertCTWorker", "Store"):
        pass
    snap = sink.snapshot()
    assert snap["counters"]["certIsFilteredOut.CA"] == 2
    assert snap["counters"]["insertCTWorker.Inserted"] == 5
    assert snap["gauges"]["entries_per_sec_per_chip"] == 1e7
    assert snap["samples"]["insertCTWorker.Store"]["count"] == 1

    out = io.StringIO()
    dumper = MetricsDumper(sink, period_s=3600, out=out)
    dumper.dump()
    text = out.getvalue()
    assert "certIsFilteredOut.CA: 2" in text
    assert "entries_per_sec_per_chip" in text
    metrics.set_sink(InMemSink())  # reset global for other tests


def test_build_device_batches_unique_valid_rows():
    """The shared on-device batch synthesis: every row is the signed
    template with a unique serial, lane counters span [0, G*B), epoch
    bytes 4..8 stay zero for the caller, and the oversize guard fires."""
    import numpy as np
    import pytest

    from ct_mapreduce_tpu.core import der as hostder
    from ct_mapreduce_tpu.utils import syncerts

    tpl = syncerts.make_template()
    g, b, pad = 2, 64, 1024
    datas, lens = syncerts.build_device_batches(tpl, g, b, pad)
    datas = np.asarray(datas)
    lens = np.asarray(lens)
    assert datas.shape == (g, b, pad)
    assert (lens == len(tpl.leaf_der)).all()

    seen = set()
    for gi in range(g):
        for li in (0, 1, b - 1):
            row = bytes(datas[gi, li, : lens[gi, li]])
            fields = hostder.parse_cert(row)  # still canonical DER
            assert fields.serial_len == syncerts.SERIAL_LEN
            serial = row[tpl.serial_off : tpl.serial_off + tpl.serial_len]
            assert serial[4:8] == b"\x00" * 4  # epoch bytes left zero
            cnt = int.from_bytes(serial[12:16], "big")
            assert cnt == gi * b + li  # lane counter layout
            assert serial not in seen
            seen.add(serial)

    with pytest.raises(ValueError):
        syncerts.build_device_batches(tpl, 1, 4, len(tpl.leaf_der) - 1)


def test_build_mixed_device_batches_realistic_mix():
    """The realistic-mix synthesis: RSA + ECDSA templates, many
    issuers, varied serial lengths (8..20) in ONE device batch; every
    lane is canonical DER for ITS template, epoch window (serial bytes
    1..4) left zero, lane counters unique, and the fused step ingests
    the whole mix with exact counts."""
    import numpy as np

    from ct_mapreduce_tpu.core import der as hostder
    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import buckettable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    tpls = [
        syncerts.make_template("Mix CA ec8", serial_len=8),
        syncerts.make_template("Mix CA ec16", serial_len=16),
        syncerts.make_template("Mix CA rsa20", key_type="rsa2048",
                               serial_len=20, rich_extensions=True),
        syncerts.make_template("Mix CA ec9", serial_len=9,
                               rich_extensions=True),
    ]
    assert len(tpls[2].leaf_der) > 1024  # RSA leaves the friendly regime
    g, b, pad = 2, 256, 2048
    w = syncerts.zipf_weights(len(tpls))
    ms = syncerts.build_mixed_device_batches(tpls, w, g, b, pad, seed=3)
    datas = np.asarray(ms.datas)
    lens = np.asarray(ms.lens)
    assert datas.shape == (g, b, pad)
    assert set(np.unique(ms.template_of)) == {0, 1, 2, 3}

    for gi in range(g):
        for li in (0, 1, 7, b - 1):
            t = tpls[ms.template_of[li]]
            row = bytes(datas[gi, li, : lens[gi, li]])
            assert lens[gi, li] == len(t.leaf_der)
            fields = hostder.parse_cert(row)  # still canonical DER
            assert fields.serial_len == t.serial_len
            serial = row[t.serial_off : t.serial_off + t.serial_len]
            assert serial[0] == 0x4D
            assert serial[1:4] == b"\x00" * 3  # epoch window untouched
            cnt = int.from_bytes(serial[-4:], "big")
            assert cnt == gi * b + li

    # The fused step ingests the mix exactly: all fresh on the first
    # pass, all known on the replay, per-issuer counts match the draw.
    table = buckettable.make_table(1 << 12)
    now_hour = 500_000
    no_cn = (np.zeros((0, 32), np.uint8), np.zeros((0, 2), np.int32))
    import jax.numpy as jnp

    table, out = pipeline.ingest_core(
        table, jnp.asarray(datas[0]), jnp.asarray(lens[0]),
        jnp.asarray(ms.issuer_idx), jnp.asarray(np.ones((b,), bool)),
        jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.asarray(no_cn[0]), jnp.asarray(no_cn[1]))
    assert bool(np.asarray(out.was_unknown).all())
    assert not np.asarray(out.host_lane).any()
    counts = np.asarray(out.issuer_unknown_counts)
    for t_id in range(len(tpls)):
        assert counts[t_id] == (ms.template_of == t_id).sum()
    table, out2 = pipeline.ingest_core(
        table, jnp.asarray(datas[0]), jnp.asarray(lens[0]),
        jnp.asarray(ms.issuer_idx), jnp.asarray(np.ones((b,), bool)),
        jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.asarray(no_cn[0]), jnp.asarray(no_cn[1]))
    assert not np.asarray(out2.was_unknown).any()
    # Batch g=1 differs only in lane counters — all fresh again.
    table, out3 = pipeline.ingest_core(
        table, jnp.asarray(datas[1]), jnp.asarray(lens[1]),
        jnp.asarray(ms.issuer_idx), jnp.asarray(np.ones((b,), bool)),
        jnp.int32(now_hour), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.asarray(no_cn[0]), jnp.asarray(no_cn[1]))
    assert bool(np.asarray(out3.was_unknown).all())
    assert int(np.asarray(table.count)) == 2 * b
