"""Duration parsing, backoff, and telemetry sink tests."""

import io

import pytest

from ct_mapreduce_tpu.telemetry import metrics
from ct_mapreduce_tpu.telemetry.metrics import InMemSink, MetricsDumper
from ct_mapreduce_tpu.utils import JitteredBackoff, format_duration, parse_duration


def test_parse_duration_go_syntax():
    assert parse_duration("15m") == 900
    assert parse_duration("125ms") == 0.125
    assert parse_duration("5s") == 5
    assert parse_duration("2h45m") == 2 * 3600 + 45 * 60
    assert parse_duration("10m") == 600
    assert parse_duration("1.5s") == 1.5
    assert parse_duration("-30s") == -30
    assert parse_duration("0") == 0


def test_parse_duration_rejects_garbage():
    for bad in ("", "fifteen", "15", "m15", "15 m"):
        with pytest.raises(ValueError):
            parse_duration(bad)


def test_format_duration():
    assert format_duration(900) == "15m"
    assert format_duration(0.125) == "125ms"
    assert format_duration(2 * 3600 + 45 * 60) == "2h45m"
    assert format_duration(0) == "0s"
    assert parse_duration(format_duration(3725.5)) == 3725.5


def test_backoff_growth_and_cap():
    b = JitteredBackoff(min_s=0.5, max_s=300, jitter=False)
    ds = [b.duration() for _ in range(12)]
    assert ds[0] == 0.5
    assert ds[1] == 1.0
    assert all(x <= 300 for x in ds)
    assert ds[-1] == 300
    b.reset()
    assert b.duration() == 0.5


def test_backoff_jitter_bounds():
    b = JitteredBackoff(min_s=0.5, max_s=300, jitter=True)
    for _ in range(50):
        d = b.duration()
        assert 0.5 <= d <= 300


def test_metrics_sink_and_dumper():
    sink = InMemSink()
    metrics.set_sink(sink)
    metrics.incr_counter("certIsFilteredOut", "CA")
    metrics.incr_counter("certIsFilteredOut", "CA")
    metrics.incr_counter("insertCTWorker", "Inserted", value=5)
    metrics.set_gauge("entries_per_sec_per_chip", value=1e7)
    with metrics.measure("insertCTWorker", "Store"):
        pass
    snap = sink.snapshot()
    assert snap["counters"]["certIsFilteredOut.CA"] == 2
    assert snap["counters"]["insertCTWorker.Inserted"] == 5
    assert snap["gauges"]["entries_per_sec_per_chip"] == 1e7
    assert snap["samples"]["insertCTWorker.Store"]["count"] == 1

    out = io.StringIO()
    dumper = MetricsDumper(sink, period_s=3600, out=out)
    dumper.dump()
    text = out.getvalue()
    assert "certIsFilteredOut.CA: 2" in text
    assert "entries_per_sec_per_chip" in text
    metrics.set_sink(InMemSink())  # reset global for other tests


def test_build_device_batches_unique_valid_rows():
    """The shared on-device batch synthesis: every row is the signed
    template with a unique serial, lane counters span [0, G*B), epoch
    bytes 4..8 stay zero for the caller, and the oversize guard fires."""
    import numpy as np
    import pytest

    from ct_mapreduce_tpu.core import der as hostder
    from ct_mapreduce_tpu.utils import syncerts

    tpl = syncerts.make_template()
    g, b, pad = 2, 64, 1024
    datas, lens = syncerts.build_device_batches(tpl, g, b, pad)
    datas = np.asarray(datas)
    lens = np.asarray(lens)
    assert datas.shape == (g, b, pad)
    assert (lens == len(tpl.leaf_der)).all()

    seen = set()
    for gi in range(g):
        for li in (0, 1, b - 1):
            row = bytes(datas[gi, li, : lens[gi, li]])
            fields = hostder.parse_cert(row)  # still canonical DER
            assert fields.serial_len == syncerts.SERIAL_LEN
            serial = row[tpl.serial_off : tpl.serial_off + tpl.serial_len]
            assert serial[4:8] == b"\x00" * 4  # epoch bytes left zero
            cnt = int.from_bytes(serial[12:16], "big")
            assert cnt == gi * b + li  # lane counter layout
            assert serial not in seen
            seen.add(serial)

    with pytest.raises(ValueError):
        syncerts.build_device_batches(tpl, 1, 4, len(tpl.leaf_der) - 1)
