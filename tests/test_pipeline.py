"""Fused device ingest step vs the exact host path.

The oracle replays the reference's per-cert logic
(certIsFilteredOut + Store dedup,
/root/reference/cmd/ct-fetch/ct-fetch.go:44-70,180-246) in Python and
must agree lane-for-lane with the device step."""

import datetime

import numpy as np
import pytest

from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.ops import hashtable, pipeline

from certgen import make_cert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2024, 6, 1, tzinfo=UTC)
NOW_HOUR = int(NOW.timestamp()) // 3600
BASE = packing.DEFAULT_BASE_HOUR
NO_PREFIX = (np.zeros((0, 32), np.uint8), np.zeros((0, 2), np.int32))


def run_step(table, entries, prefixes=NO_PREFIX, batch_size=None):
    batch = packing.pack_entries(entries, batch_size=batch_size)
    table, out = pipeline.ingest_step(
        table,
        batch.data,
        batch.length,
        batch.issuer_idx,
        batch.valid,
        np.int32(NOW_HOUR),
        np.int32(BASE),
        prefixes[0],
        prefixes[1],
    )
    return table, out


def test_fingerprint_parity_with_host():
    certs = [make_cert(serial=s) for s in (1, 0xAABB, 0x00AA00BB, (1 << 150) + 7)]
    entries = [(c, i % 3) for i, c in enumerate(certs)]
    import jax.numpy as jnp

    batch = packing.pack_entries(entries)
    from ct_mapreduce_tpu.ops import der_kernel

    parsed = der_kernel.parse_certs(batch.data, batch.length)
    serials, _ = der_kernel.gather_serials(
        batch.data, parsed.serial_off, parsed.serial_len, packing.MAX_SERIAL_BYTES
    )
    fps = np.asarray(
        pipeline.fingerprints(
            jnp.asarray(batch.issuer_idx), parsed.not_after_hour, serials,
            parsed.serial_len,
        )
    )
    for i, (der, idx) in enumerate(entries):
        ref = hostder.parse_cert(der)
        want = packing.fingerprint_host(idx, ref.not_after_unix_hour, ref.serial)
        assert tuple(int(x) for x in fps[i]) == want, i


def test_dedup_and_filters_end_to_end():
    table = hashtable.make_table(1 << 12)
    good1 = make_cert(serial=100, is_ca=False, subject_cn="a.example.com")
    good2 = make_cert(serial=101, is_ca=False, subject_cn="b.example.com")
    ca = make_cert(serial=102, is_ca=True)
    expired = make_cert(
        serial=103, is_ca=False, subject_cn="old.example.com",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2021, 1, 1, tzinfo=UTC),
    )
    entries = [(good1, 0), (good2, 0), (ca, 0), (expired, 0), (good1, 0)]
    table, out = run_step(table, entries, batch_size=8)

    assert list(np.asarray(out.filtered_ca)[:5]) == [False, False, True, False, False]
    assert list(np.asarray(out.filtered_expired)[:5]) == [
        False, False, False, True, False,
    ]
    # lane 4 duplicates lane 0 within the batch → known
    assert list(np.asarray(out.was_unknown)[:5]) == [True, True, False, False, False]
    assert not np.asarray(out.host_lane).any()
    assert int(table.count) == 2

    # Re-ingesting the same batch: nothing new.
    table, out2 = run_step(table, entries, batch_size=8)
    assert not np.asarray(out2.was_unknown).any()
    assert int(table.count) == 2


def test_issuer_counts():
    table = hashtable.make_table(1 << 12)
    entries = []
    for i in range(6):
        entries.append(
            (make_cert(serial=200 + i, is_ca=False, subject_cn=f"h{i}.example.com"),
             i % 2)
        )
    table, out = run_step(table, entries)
    counts = np.asarray(out.issuer_unknown_counts)
    assert counts[0] == 3 and counts[1] == 3
    assert counts[2:].sum() == 0


def test_cn_prefix_filter():
    table = hashtable.make_table(1 << 12)
    keep = make_cert(serial=300, is_ca=False, issuer_cn="KeepMe CA 1")
    drop = make_cert(serial=301, is_ca=False, issuer_cn="DropMe CA 1")
    prefixes = np.zeros((2, 32), np.uint8)
    for i, pfx in enumerate([b"KeepMe", b"Other"]):
        prefixes[i, : len(pfx)] = np.frombuffer(pfx, np.uint8)
    plens = np.array([[6, 6], [5, 5]], np.int32)  # (device len, true len)
    table, out = run_step(table, [(keep, 0), (drop, 0)], prefixes=(prefixes, plens))
    assert list(np.asarray(out.filtered_cn)) == [False, True]
    assert list(np.asarray(out.was_unknown)) == [True, False]


def test_cn_prefix_longer_than_device_window_routes_host():
    """A configured prefix longer than the device-comparable window
    must never be silently decided on its truncated head: lanes whose
    CN matches the head go to the exact host lane; lanes that do not
    match the head are filtered on device as usual."""
    from ct_mapreduce_tpu.ops import der_kernel as dk

    cap = dk.MAX_FIXED_WINDOW_BYTES
    # 64 chars: the longest legal CN (ub-common-name), > the 61-byte
    # device-comparable cap.
    long_pfx = ("KeepMe CA " + "x" * 64)[:64]
    assert len(long_pfx) > cap
    matching_cn = long_pfx  # head matches; host must decide the tail
    other_cn = "DropMe CA 1"  # device decides: filtered
    table = hashtable.make_table(1 << 12)
    m = make_cert(serial=310, is_ca=False, issuer_cn=matching_cn)
    d = make_cert(serial=311, is_ca=False, issuer_cn=other_cn)
    enc = long_pfx.encode()
    prefixes = np.frombuffer(enc[:cap], np.uint8)[None, :].copy()
    plens = np.array([[cap, len(enc)]], np.int32)
    table, out = run_step(table, [(m, 0), (d, 0)], prefixes=(prefixes, plens))
    # Lane 0: undecidable on device -> host lane, not filtered, not
    # device-inserted.
    assert list(np.asarray(out.host_lane)) == [True, False]
    assert list(np.asarray(out.filtered_cn)) == [False, True]
    assert list(np.asarray(out.was_unknown)) == [False, False]


def test_host_lane_on_garbage():
    table = hashtable.make_table(1 << 12)
    good = make_cert(serial=400, is_ca=False, subject_cn="x.example.com")
    entries = [(good, 0), (b"\x30\x05junk", 0)]
    table, out = run_step(table, entries)
    assert list(np.asarray(out.host_lane)) == [False, True]
    assert list(np.asarray(out.was_unknown)) == [True, False]


def test_crldp_flag_surfaced():
    table = hashtable.make_table(1 << 12)
    with_dp = make_cert(
        serial=500, is_ca=False, crl_dps=("http://crl.example.com/c.crl",)
    )
    without = make_cert(serial=501, is_ca=False, subject_cn="nodp.example.com")
    table, out = run_step(table, [(with_dp, 0), (without, 0)])
    assert list(np.asarray(out.has_crldp)) == [True, False]
