"""Host storage layer tests: mock cache semantics, KnownCertificates
dedup, IssuerMetadata accumulation, backend conformance, and the
FilesystemDatabase store flow (reference:
storage/{mockcache,knowncertificates,issuermetadata,filesystemdatabase}_test.go)."""

from datetime import datetime, timedelta, timezone

import pytest

from ct_mapreduce_tpu.core.types import CertificateLog, ExpDate, Issuer, Serial
from ct_mapreduce_tpu.storage import (
    FilesystemDatabase,
    IssuerMetadata,
    KnownCertificates,
    LocalDiskBackend,
    MockBackend,
    MockRemoteCache,
    NoopBackend,
)
from ct_mapreduce_tpu.storage.conformance import run_full_conformance

from certgen import make_cert


# -- MockRemoteCache ----------------------------------------------------


def test_set_semantics():
    c = MockRemoteCache()
    assert c.set_insert("k", "a") is True
    assert c.set_insert("k", "a") is False
    assert c.set_insert("k", "b") is True
    assert c.set_contains("k", "a")
    assert not c.set_contains("k", "z")
    assert c.set_cardinality("k") == 2
    assert c.set_list("k") == ["a", "b"]
    assert c.set_remove("k", "a") is True
    assert c.set_remove("k", "a") is False
    assert c.set_cardinality("k") == 1


def test_ttl_expiry():
    c = MockRemoteCache()
    c.set_insert("gone", "x")
    c.expire_at("gone", datetime.now(timezone.utc) - timedelta(seconds=1))
    assert not c.exists("gone")
    c.set_insert("stays", "x")
    c.expire_in("stays", timedelta(hours=1))
    assert c.exists("stays")


def test_queue_semantics():
    c = MockRemoteCache()
    assert c.queue("q", "one") == 1
    assert c.queue("q", "two") == 2
    assert c.queue_length("q") == 2
    assert c.pop("q") == "one"
    with pytest.raises(KeyError):
        c.pop("empty")
    # BRPOPLPUSH pops the tail into dest's head
    c.queue("src", "a")
    c.queue("src", "b")
    assert c.blocking_pop_copy("src", "dst", timedelta(seconds=1)) == "b"
    assert c.pop("dst") == "b"
    with pytest.raises(TimeoutError):
        c.blocking_pop_copy("empty", "dst", timedelta(milliseconds=20))


def test_try_set_is_first_writer_wins():
    c = MockRemoteCache()
    assert c.try_set("lock", "alice", timedelta(minutes=5)) == "alice"
    assert c.try_set("lock", "bob", timedelta(minutes=5)) == "alice"


def test_keys_matching():
    c = MockRemoteCache()
    c.set_insert("serials::2050-01-01::issA", "x")
    c.set_insert("serials::2050-01-02::issB", "x")
    c.set_insert("crl::issA", "x")
    keys = sorted(c.keys_matching("serials::*"))
    assert keys == [
        "serials::2050-01-01::issA",
        "serials::2050-01-02::issB",
    ]


def test_log_state_roundtrip():
    c = MockRemoteCache()
    assert c.load_log_state("nope") is None
    c.store_log_state(CertificateLog(short_url="l.example/x", max_entry=9))
    assert c.load_log_state("l.example/x").max_entry == 9


# -- KnownCertificates --------------------------------------------------


def test_was_unknown_dedups():
    # knowncertificates_test.go semantics
    cache = MockRemoteCache()
    kc = KnownCertificates(ExpDate.parse("2050-01-01"), Issuer.from_string("i"), cache)
    s1 = Serial.from_hex("00aa")
    s2 = Serial.from_hex("bb")
    assert kc.was_unknown(s1) is True
    assert kc.was_unknown(s1) is False
    assert kc.was_unknown(s2) is True
    assert kc.count() == 2
    known = {s.hex_string() for s in kc.known()}
    assert known == {"00aa", "bb"}


def test_known_dedups_scan_duplicates():
    # The Duplicate knob simulates Redis SSCAN replay
    # (mockcache.go:14-24,109-118; knowncertificates.go:65-96)
    cache = MockRemoteCache(duplicate=2)
    kc = KnownCertificates(ExpDate.parse("2050-01-01"), Issuer.from_string("i"), cache)
    kc.was_unknown(Serial.from_hex("01"))
    kc.was_unknown(Serial.from_hex("02"))
    assert len(list(cache.set_to_iter(kc.serial_id()))) == 6  # duplicated stream
    assert len(kc.known()) == 2  # client-side dedup absorbs it


def test_serials_key_format():
    kc = KnownCertificates(
        ExpDate.parse("2050-01-01-05"), Issuer.from_string("issuerX"), MockRemoteCache()
    )
    assert kc.serial_id() == "serials::2050-01-01-05::issuerX"


def test_expiry_set_once_to_bucket_expiry():
    cache = MockRemoteCache()
    exp = ExpDate.parse("2050-01-01")
    kc = KnownCertificates(exp, Issuer.from_string("i"), cache)
    kc.was_unknown(Serial.from_hex("01"))
    assert cache._expirations[kc.serial_id()] == exp.expire_time()


# -- IssuerMetadata -----------------------------------------------------


def test_accumulate_metadata():
    cache = MockRemoteCache()
    meta = IssuerMetadata(Issuer.from_string("iss"), cache)
    exp = ExpDate.parse("2050-01-01")
    seen = meta.accumulate(exp, "CN=Foo CA,O=Foo", ["http://crl.foo/x.crl"])
    assert seen is False  # first time this bucket
    seen = meta.accumulate(exp, "CN=Foo CA,O=Foo", ["http://crl.foo/x.crl"])
    assert seen is True
    assert meta.issuers() == ["CN=Foo CA,O=Foo"]
    assert meta.crls() == ["http://crl.foo/x.crl"]


def test_crl_scheme_filtering():
    # issuermetadata.go:48-73: ldap(s) silently dropped, unknown schemes
    # ignored, http/https kept
    cache = MockRemoteCache()
    meta = IssuerMetadata(Issuer.from_string("iss"), cache)
    meta.accumulate(
        ExpDate.parse("2050-01-01"),
        "CN=X",
        [
            "http://ok.example/a.crl",
            "https://ok.example/b.crl",
            "ldap://dropped.example/x",
            "ldaps://dropped.example/y",
            "ftp://ignored.example/z",
        ],
    )
    assert sorted(meta.crls()) == [
        "http://ok.example/a.crl",
        "https://ok.example/b.crl",
    ]


def test_metadata_keys():
    cache = MockRemoteCache()
    meta = IssuerMetadata(Issuer.from_string("issuerQ"), cache)
    meta.accumulate(ExpDate.parse("2050-01-01"), "CN=Q", ["http://q/crl"])
    assert cache.set_list("crl::issuerQ") == ["http://q/crl"]
    assert cache.set_list("issuer::issuerQ") == ["CN=Q"]


# -- backends -----------------------------------------------------------


def test_mock_backend_conformance():
    run_full_conformance(MockBackend())


def test_localdisk_backend_conformance(tmp_path):
    run_full_conformance(LocalDiskBackend(tmp_path / "certs"))


def test_localdisk_layout(tmp_path):
    # localdiskbackend.go:194-199: <root>/<expDate>/<issuerID>/<serialID>
    root = tmp_path / "certs"
    b = LocalDiskBackend(root)
    exp = ExpDate.parse("2050-01-01")
    issuer = Issuer.from_string("issuerDir")
    serial = Serial.from_hex("0042")
    b.store_certificate_pem(serial, exp, issuer, b"PEMDATA")
    assert (root / "2050-01-01" / "issuerDir" / serial.id()).read_bytes() == b"PEMDATA"
    b.mark_dirty("2050-01-01")
    assert (root / "2050-01-01" / ".dirty").exists()


def test_noop_backend():
    # noopbackend.go:16-69: stores succeed, loads fail
    b = NoopBackend()
    exp = ExpDate.parse("2050-01-01")
    issuer = Issuer.from_string("i")
    b.store_certificate_pem(Serial.from_hex("01"), exp, issuer, b"x")
    with pytest.raises(NotImplementedError):
        b.load_certificate_pem(Serial.from_hex("01"), exp, issuer)
    assert b.list_expiration_dates(datetime(2049, 1, 1)) == []


# -- FilesystemDatabase -------------------------------------------------


@pytest.fixture
def db():
    return FilesystemDatabase(MockBackend(), MockRemoteCache())


def test_store_flow(db):
    # filesystemdatabase_test.go:67-140 analog over generated certs
    issuer_der = make_cert(issuer_cn="Root CA", key_seed=1)
    leaf = make_cert(
        serial=0x1001,
        issuer_cn="Root CA",
        subject_cn="site.example",
        is_ca=False,
        crl_dps=("http://crl.root/ca.crl",),
        not_after=datetime(2049, 6, 1, 12, 30, tzinfo=timezone.utc),
    )
    db.store(leaf, issuer_der, "log.example/x", 1)
    db.store(leaf, issuer_der, "log.example/x", 2)  # duplicate

    from ct_mapreduce_tpu.core import der as derlib

    issuer = Issuer.from_spki(derlib.parse_cert(issuer_der).spki)
    exp = ExpDate.from_time(datetime(2049, 6, 1, 12, 30, tzinfo=timezone.utc))
    kc = db.get_known_certificates(exp, issuer)
    assert kc.count() == 1  # dedup worked
    meta = db.get_issuer_metadata(issuer)
    assert meta.crls() == ["http://crl.root/ca.crl"]
    assert "2049-06-01" in db.backend.dirty

    # Backend got the PEM under the right identity
    serials = db.backend.list_serials_for_expiration_date_and_issuer(exp, issuer)
    assert [s.as_int() for s in serials] == [0x1001]


def test_issuer_and_dates_from_cache(db):
    issuer_der = make_cert(issuer_cn="Enum CA", key_seed=2)
    for i, hour in enumerate((1, 2)):
        leaf = make_cert(
            serial=0x2000 + i,
            issuer_cn="Enum CA",
            is_ca=False,
            not_after=datetime(2049, 7, 1, hour, tzinfo=timezone.utc),
        )
        db.store(leaf, issuer_der, "log.example/x", i)
    result = db.get_issuer_and_dates_from_cache()
    assert len(result) == 1
    assert len(result[0].exp_dates) == 2
    assert [e.id() for e in result[0].exp_dates] == ["2049-07-01-01", "2049-07-01-02"]


def test_log_state_dual_write(db):
    # filesystemdatabase.go:110-139: dual write, cache-first read
    log = CertificateLog(short_url="log.example/y", max_entry=7)
    db.save_log_state(log)
    assert db.ext_cache.load_log_state("log.example/y").max_entry == 7
    assert db.backend.load_log_state("log.example/y").max_entry == 7
    assert db.get_log_state("log.example/y").max_entry == 7
    # Unknown log yields a fresh zero-state record
    fresh = db.get_log_state("never.seen/log")
    assert fresh.max_entry == 0 and fresh.short_url == "never.seen/log"
