"""Sharded dedup over a virtual 8-device mesh (SURVEY.md §4 tier 3
analog: multi-chip behavior exercised without hardware, like the
reference gating real-Redis tests behind RedisHost)."""

import datetime

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from ct_mapreduce_tpu.agg.sharded import ShardedDedup
from ct_mapreduce_tpu.core import packing

from certgen import make_cert

UTC = datetime.timezone.utc
NOW_HOUR = int(datetime.datetime(2024, 6, 1, tzinfo=UTC).timestamp()) // 3600


def mesh8():
    devs = np.array(jax.devices()[:8])
    assert devs.size == 8, "conftest must provide 8 virtual devices"
    return Mesh(devs, ("shard",))


def packed_batch(entries, batch_size=32):
    # ONE 32-lane compile shape for every mesh-step test in this file
    # (round-17 budget audit: 24- and 8-lane variants each paid their
    # own ~10 s shard_map compile for width-independent assertions).
    b = packing.pack_entries(entries, batch_size=batch_size)
    return b.data, b.length, b.issuer_idx, b.valid


@pytest.fixture(scope="module")
def certs():
    return [
        make_cert(serial=50000 + i, is_ca=False, subject_cn=f"sh{i}.example.com")
        for i in range(24)
    ]


def test_sharded_dedup_matches_oracle(certs):
    sd = ShardedDedup(mesh8(), capacity=1 << 13)
    entries = [(c, i % 3) for i, c in enumerate(certs)]
    data, length, issuer_idx, valid = packed_batch(entries, 32)

    out = sd.step(data, length, issuer_idx, valid, NOW_HOUR)
    wu = np.asarray(out.was_unknown)
    hl = np.asarray(out.host_lane)
    assert not hl.any()
    assert wu[: len(entries)].all()
    assert not wu[len(entries):].any()
    assert sd.total_count() == len(entries)

    # Replay: everything known, count unchanged.
    out2 = sd.step(data, length, issuer_idx, valid, NOW_HOUR)
    assert not np.asarray(out2.was_unknown).any()
    assert not np.asarray(out2.host_lane).any()
    assert sd.total_count() == len(entries)


def test_sharded_within_batch_duplicates(certs):
    sd = ShardedDedup(mesh8(), capacity=1 << 13)
    # Each cert appears twice in the same batch, on different lanes (and
    # usually different source devices): exactly one lane wins each.
    entries = [(c, 0) for c in certs[:12]] + [(c, 0) for c in certs[:12]]
    data, length, issuer_idx, valid = packed_batch(entries)
    out = sd.step(data, length, issuer_idx, valid, NOW_HOUR)
    wu = np.asarray(out.was_unknown)
    assert not np.asarray(out.host_lane).any()
    assert wu.sum() == 12
    for i in range(12):
        assert wu[i] != wu[12 + i] or (wu[i] and not wu[12 + i])
    assert sd.total_count() == 12


def test_sharded_issuer_counts(certs):
    sd = ShardedDedup(mesh8(), capacity=1 << 13)
    entries = [(c, i % 4) for i, c in enumerate(certs)]
    data, length, issuer_idx, valid = packed_batch(entries)
    out = sd.step(data, length, issuer_idx, valid, NOW_HOUR)
    counts = np.asarray(out.issuer_unknown_counts)
    assert counts[:4].tolist() == [6, 6, 6, 6]
    assert counts[4:].sum() == 0


def test_sharded_drain_meta(certs):
    sd = ShardedDedup(mesh8(), capacity=1 << 13)
    entries = [(c, 5) for c in certs[:8]]
    data, length, issuer_idx, valid = packed_batch(entries)
    sd.step(data, length, issuer_idx, valid, NOW_HOUR)
    keys, meta = sd.drain_np()
    assert keys.shape[0] == 8
    for m in meta:
        idx, eh = packing.unpack_meta(int(m))
        assert idx == 5
        assert eh > NOW_HOUR


def test_sharded_zipfian_issuer_skew():
    """A Zipf-hot issuer distribution (one issuer ~70% of a batch) must
    NOT skew shard routing: routing hashes the whole fingerprint
    (expHour, issuerID, serial), and serials are distinct per cert, so
    spills past the per-(src,dst) dispatch cap stay binomial-tail-rare.
    Counts remain exact either way — spilled lanes surface in
    `dispatch_dropped`/host_lane, never vanish."""
    import jax.numpy as jnp

    from ct_mapreduce_tpu.core.packing import fingerprint_host

    rng = np.random.RandomState(7)
    b = 512
    # Zipf-ish issuer assignment over 8 issuers: issuer 0 dominates.
    weights = 1.0 / np.arange(1, 9) ** 1.5
    weights /= weights.sum()
    issuer_idx = rng.choice(8, size=b, p=weights).astype(np.int32)
    assert (issuer_idx == 0).sum() > 0.5 * b  # actually skewed

    # Distinct serials → distinct fingerprints, regardless of issuer.
    fps = np.array([
        fingerprint_host(int(issuer_idx[i]), NOW_HOUR + 100,
                         b"\x01" + i.to_bytes(8, "big"))
        for i in range(b)
    ], dtype=np.uint32)

    from ct_mapreduce_tpu.agg.sharded import _dispatch, _shard_of

    n_shards = 8
    dest = np.asarray(_shard_of(jnp.asarray(fps), n_shards))
    # Routing spreads despite issuer skew: no shard holds > 2x its share.
    counts = np.bincount(dest, minlength=n_shards)
    assert counts.max() <= 2 * b // n_shards

    # With the production headroom factor (cap = 2 * b_loc / n), nothing
    # spills on this batch; with a tiny artificial cap, spills are
    # reported, not lost.
    payload = np.concatenate(
        [fps, np.zeros((b, 1), np.uint32)], axis=1)
    _, send_valid, slot_of_lane, _ = _dispatch(
        jnp.asarray(payload), jnp.asarray(dest),
        jnp.ones((b,), bool), n_shards, cap=2 * b // n_shards,
    )
    assert int((np.asarray(slot_of_lane) < 0).sum()) == 0
    _, tight_valid, tight_slot, _ = _dispatch(
        jnp.asarray(payload), jnp.asarray(dest),
        jnp.ones((b,), bool), n_shards, cap=8,
    )
    spilled = int((np.asarray(tight_slot) < 0).sum())
    assert spilled == b - int(np.asarray(tight_valid).sum())
    assert spilled > 0  # the tiny cap really bites; nothing silently lost


def test_sharded_dispatch_spill_metric(certs):
    """The aggregator surfaces routing-cap spills as `dispatch_spill`
    and the spilled lanes still land exactly via the host lane."""
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    a = ShardedAggregator(
        mesh8(), capacity=1 << 13, batch_size=32,
        now=datetime.datetime(2024, 6, 1, tzinfo=UTC),
        dispatch_factor=0.0,  # floor kicks in: cap = max(8, 0) = 8
    )
    # 32 lanes / 8 shards / src-dev → b_loc=4; cap floor 8 ⇒ no spill in
    # tiny batches by design: assert the metric plumbing (zero spills
    # recorded) and that totals stay exact.
    ca = make_cert(issuer_cn="Spill CA")
    entries = [(c, ca) for c in certs]
    res = a.ingest(entries)
    assert res.was_unknown[: len(certs)].all()
    assert a.metrics["dispatch_spill"] == 0
    assert a.drain().total == len(certs)


def test_sharded_parity_with_single_chip(certs):
    from ct_mapreduce_tpu.ops import hashtable, pipeline

    sd = ShardedDedup(mesh8(), capacity=1 << 13)
    entries = [(c, i % 2) for i, c in enumerate(certs)]
    data, length, issuer_idx, valid = packed_batch(entries, 32)
    out_sh = sd.step(data, length, issuer_idx, valid, NOW_HOUR)

    table = hashtable.make_table(1 << 13)
    no_pfx = (np.zeros((0, 32), np.uint8), np.zeros((0, 2), np.int32))
    table, out_1c = pipeline.ingest_step(
        table, data, length, issuer_idx, valid,
        np.int32(NOW_HOUR), np.int32(packing.DEFAULT_BASE_HOUR),
        no_pfx[0], no_pfx[1],
    )
    np.testing.assert_array_equal(
        np.asarray(out_sh.was_unknown), np.asarray(out_1c.was_unknown)
    )
    np.testing.assert_array_equal(
        np.asarray(out_sh.issuer_unknown_counts),
        np.asarray(out_1c.issuer_unknown_counts),
    )
    assert sd.total_count() == int(table.count)


def test_dispatch_rank_parity_cumsum_vs_lexsort():
    """The two in-dest ranking schemes — per-shard cumsum (narrow
    meshes, n <= 32) and stable lexsort (wide-mesh fallback) — must
    produce identical (send, send_valid, slot_of_lane, rank) for the
    same inputs. No test mesh exceeds 32 shards, so the fallback is
    exercised here directly by comparing both branches on the same
    random dest/active arrays."""
    import jax.numpy as jnp

    from ct_mapreduce_tpu.agg import sharded

    rng = np.random.RandomState(11)
    b, n_shards, cap = 257, 8, 24  # odd b: no tiling accidents
    payload = rng.randint(0, 2**31, size=(b, 5)).astype(np.uint32)
    dest = rng.randint(0, n_shards, size=(b,)).astype(np.int32)
    active = rng.rand(b) < 0.85

    def run(force_wide: bool):
        # The branch is selected on static n_shards; drive the wide
        # branch by inflating n_shards past 32 with empty extra bins.
        n = 40 if force_wide else n_shards
        return sharded._dispatch(
            jnp.asarray(payload), jnp.asarray(dest),
            jnp.asarray(active), n, cap,
        )

    send_n, valid_n, slot_n, rank_n = (np.asarray(x) for x in run(False))
    send_w, valid_w, slot_w, rank_w = (np.asarray(x) for x in run(True))

    # Bins 0..7 must agree exactly; the wide run's extra bins are empty.
    np.testing.assert_array_equal(send_n, send_w[:n_shards])
    np.testing.assert_array_equal(valid_n, valid_w[:n_shards])
    assert not valid_w[n_shards:].any()
    np.testing.assert_array_equal(slot_n, slot_w)
    # Ranks must agree wherever a lane was placed (dummy-bin lanes'
    # ranks are don't-care in the narrow scheme).
    placed = slot_n >= 0
    np.testing.assert_array_equal(rank_n[placed], rank_w[placed])
