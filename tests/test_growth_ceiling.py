"""Grow-livelock regression at the clamped ceiling (ADVICE r05).

The bucket layout can only build 24·2^k slots while the configured
ceiling rounds to 2^m, so before the fix ``capacity >= max_capacity``
was unreachable: once fill crossed ``grow_at × capacity`` near the
ceiling, EVERY batch re-ran a full drain+rebuild+reinsert that
produced the identical bucket count — multi-minute rebuilds, zero
slots gained. The fix floors ``max_capacity`` to the layout-achievable
capacity at construction so the at-ceiling guard can fire.

Pure-minicert fixtures: runs without the ``cryptography`` package.
"""

import datetime

import numpy as np

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.telemetry import metrics as tmetrics
from ct_mapreduce_tpu.utils import minicert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2025, 1, 1, tzinfo=UTC)

ISSUER = minicert.make_cert(serial=1, issuer_cn="Ceil CA", is_ca=True)


def entries(start: int, n: int):
    return [
        (minicert.make_cert(serial=10_000 + start + i, issuer_cn="Ceil CA",
                            subject_cn="c.example", is_ca=False), ISSUER)
        for i in range(n)
    ]


def grow_count(sink) -> int:
    return int(sink.snapshot()["counters"].get("aggregator.table_grow", 0))


def test_ceiling_is_layout_achievable_and_guard_fires():
    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        a = TpuAggregator(capacity=256, batch_size=64, now=NOW,
                          grow_at=0.5, max_capacity=768)
        # 768 = 32 buckets × 24 slots: exactly achievable, so the
        # ceiling survives the construction-time floor verbatim.
        assert a.max_capacity == a._layout_capacity_floor(768)
        assert a.capacity < a.max_capacity

        # Cross the threshold well below the ceiling: exactly ONE
        # rebuild, landing AT the ceiling.
        a.ingest(entries(0, 300))
        assert grow_count(sink) == 1
        assert a.capacity == a.max_capacity

        # Keep driving fill past grow_at × capacity AT the ceiling —
        # the pre-fix livelock re-ran a full rebuild per batch here.
        # The guard must fire instead: zero further rebuilds.
        a.ingest(entries(300, 300))
        a.ingest(entries(600, 200))
        assert grow_count(sink) == 1, "rebuilt at the ceiling (livelock)"
        assert a.capacity == a.max_capacity

        # Counts stay exact regardless (overflow spills to the exact
        # host lane past the ceiling).
        assert a.drain().total == 800
    finally:
        tmetrics.set_sink(prev)


def test_ragged_ceiling_floors_to_power_of_two_then_layout():
    a = TpuAggregator(capacity=256, batch_size=64, now=NOW,
                      grow_at=0.6, max_capacity=(1 << 12) + 7)
    # 2^12+7 → 2^12 (power-of-two floor) → the layout floor below it.
    assert a.max_capacity == a._layout_capacity_floor(1 << 12)
    assert a.max_capacity <= 1 << 12
    # The floor itself is a fixed point: flooring twice changes nothing.
    assert a._layout_capacity_floor(a.max_capacity) == a.max_capacity
