"""Round-13 verification kernels: known-answer corpus + parity fuzz.

The contract under test: the batched device kernel
(ops/ecdsa.verify_p256) is bit-identical to the pure-python reference
verifier (verify/host.verify_ecdsa) on EVERY input — valid
signatures, Wycheproof-style edge classes (r/s = 0, r/s ≥ n,
non-canonical s, off-curve and out-of-range public keys, wrong
digests), and a ≥400-case mutation fuzz — and the native SCT
extraction pass (ctmr_extract_scts) is bit-identical to its python
mirror (verify/sct.extract_scts_np) on well-formed and mutated rows.

Compile budget: the ECDSA ladder compiles in ~20 s per batch width on
the 1-core CI box, so every tier-1 device call in this file — and in
the verify bench leg and the lane tests — pads to ONE shared width
(32): one compile per process, total. The explicit multi-width parity
sweep runs as a ``slow`` test (widths 64/128 add a compile each).
"""

import hashlib
import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.ops import bigint, ecdsa  # noqa: E402
from ct_mapreduce_tpu.verify import host, sct as sctlib  # noqa: E402

C = host.P256
WIDTH = 32


def _b32(v: int) -> np.ndarray:
    return np.frombuffer(v.to_bytes(32, "big"), np.uint8).copy()


def _key(seed: str):
    d = host.derive_scalar(seed)
    return d, host._point_mul(C, d, (C.gx, C.gy))


def _sign(seed: str, msg: bytes):
    d, q = _key(seed)
    digest = hashlib.sha256(msg).digest()
    r, s = host.sign_ecdsa(C, digest, d, host.derive_nonce(seed, msg))
    return digest, r, s, q


def _dverify(rows, width: int = WIDTH):
    """Device verdicts for [(digest, r, s, x, y)] int/bytes tuples,
    padded to the shared compile width."""
    assert len(rows) <= width
    n = len(rows)
    z = np.zeros((width, 32), np.uint8)
    digest, r, s, qx, qy = (z.copy() for _ in range(5))
    for i, (dg, ri, si, xi, yi) in enumerate(rows):
        digest[i] = np.frombuffer(dg, np.uint8)
        r[i], s[i] = _b32(ri % (1 << 256)), _b32(si % (1 << 256))
        qx[i], qy[i] = _b32(xi % (1 << 256)), _b32(yi % (1 << 256))
    valid = np.zeros((width,), bool)
    valid[:n] = True
    out = np.asarray(ecdsa.verify_p256_jit(digest, r, s, qx, qy, valid))
    return out[:n].tolist()


def _hverify(rows):
    return [
        host.verify_ecdsa(C, dg, ri % (1 << 256), si % (1 << 256),
                          xi % (1 << 256), yi % (1 << 256))
        for dg, ri, si, xi, yi in rows
    ]


def _kat_corpus():
    """(name, row, expected) — the pinned edge classes."""
    cases = []
    dg, r, s, q = _sign("kat-a", b"hello ct")
    dg2, r2, s2, q2 = _sign("kat-b", b"second key")
    cases += [
        ("valid-a", (dg, r, s, q[0], q[1]), True),
        ("valid-b", (dg2, r2, s2, q2[0], q2[1]), True),
        ("wrong-digest", (hashlib.sha256(b"x").digest(), r, s, q[0], q[1]),
         False),
        ("wrong-key", (dg, r, s, q2[0], q2[1]), False),
        ("r-zero", (dg, 0, s, q[0], q[1]), False),
        ("s-zero", (dg, r, 0, q[0], q[1]), False),
        ("r-eq-n", (dg, C.n, s, q[0], q[1]), False),
        ("s-eq-n", (dg, r, C.n, q[0], q[1]), False),
        ("r-over-n", (dg, C.n + 5, s, q[0], q[1]), False),
        ("s-over-n", (dg, r, (C.n + r) % (1 << 256), q[0], q[1]), False),
        # (r, n - s) is the alternate encoding of a VALID signature —
        # plain ECDSA accepts the non-canonical s.
        ("noncanonical-s", (dg, r, C.n - s, q[0], q[1]), True),
        ("swapped-rs", (dg, s, r, q[0], q[1]), False),
        ("pub-off-curve", (dg, r, s, q[0], q[1] ^ 1), False),
        ("pub-zero", (dg, r, s, 0, 0), False),
        ("pub-x-eq-p", (dg, r, s, C.p, q[1]), False),
        ("pub-y-over-p", (dg, r, s, q[0], C.p + q[1]), False),
        # x = 0 with a matching on-curve y: y^2 = b — may not have a
        # root; use negated-y instead (on curve, wrong key half).
        ("pub-neg-y", (dg, r, s, q[0], C.p - q[1]), False),
    ]
    return cases


def test_known_answer_corpus():
    cases = _kat_corpus()
    rows = [c[1] for c in cases]
    expected = [c[2] for c in cases]
    hv = _hverify(rows)
    assert hv == expected, [c[0] for c, h, e in
                            zip(cases, hv, expected) if h != e]
    dv = _dverify(rows)
    assert dv == expected, [c[0] for c, d, e in
                            zip(cases, dv, expected) if d != e]


def test_all_valid_and_all_invalid_batches():
    valid_rows = []
    for i in range(WIDTH):
        dg, r, s, q = _sign(f"fill-{i % 5}", b"m%d" % i)
        valid_rows.append((dg, r, s, q[0], q[1]))
    assert _dverify(valid_rows) == [True] * WIDTH
    invalid_rows = [(dg, 0, s, x, y) for dg, _r, s, x, y in valid_rows]
    assert _dverify(invalid_rows) == [False] * WIDTH


def test_padding_mask_parity():
    """Verdicts are invariant to where lanes sit in the padded batch:
    the same rows scattered behind invalid filler lanes answer
    identically (the valid mask really gates, padding garbage cannot
    leak into live lanes)."""
    cases = _kat_corpus()[:10]
    rows = [c[1] for c in cases]
    base = _dverify(rows)
    filler = _sign("pad-filler", b"pad")
    mixed = []
    for r in rows:
        mixed.append((filler[0], 0, 0, 0, 0))  # dead-invalid lane
        mixed.append(r)
    out = _dverify(mixed)
    assert out[1::2] == base


@pytest.mark.slow
def test_batch_width_parity_wide():
    """Same lanes at freshly-compiled widths 64 and 128 → identical
    verdicts (width-invariance of the pow2-padded dispatch). Slow:
    each width is its own ~20 s XLA compile on the CI box."""
    cases = _kat_corpus()
    rows = [c[1] for c in cases]
    expected = [c[2] for c in cases]
    assert _dverify(rows, width=64) == expected
    assert _dverify(rows, width=128) == expected


@pytest.mark.slow
def test_mutation_fuzz_device_host_parity():
    """≥400 mutated signatures: the device verdict equals the host
    verdict on every lane (acceptance gate). Mutations hit every
    input field; ~1/8 lanes are left untouched (valid).

    @slow since round 15 (tier-1 budget banking, ISSUE 10): the
    device/host verdict-parity contract stays tier-1-gated by the KAT
    corpus, the padding-mask and all-valid/all-invalid batch tests,
    and the CT_BENCH_SMOKE verify leg's mixed corpus; this 416-case
    sweep re-walks the same kernel at ~16s and runs in the full
    (unmarked) suite."""
    rng = random.Random(0x5C7)
    rows = []
    for i in range(13 * WIDTH):  # 416 cases
        dg, r, s, q = _sign(f"fuzz-{i % 7}", b"fz%d" % (i % 29))
        row = [bytearray(dg), r, s, q[0], q[1]]
        kind = rng.randrange(8)
        if kind == 1:
            row[0][rng.randrange(32)] ^= 1 << rng.randrange(8)
        elif kind == 2:
            row[1] ^= 1 << rng.randrange(256)
        elif kind == 3:
            row[2] ^= 1 << rng.randrange(256)
        elif kind == 4:
            row[3] ^= 1 << rng.randrange(256)
        elif kind == 5:
            row[4] ^= 1 << rng.randrange(256)
        elif kind == 6:
            row[rng.randrange(1, 5)] = rng.getrandbits(256)
        elif kind == 7:
            row[2] = C.n - row[2]  # stays valid
        rows.append((bytes(row[0]), row[1], row[2], row[3], row[4]))
    mismatches = []
    for lo in range(0, len(rows), WIDTH):
        chunk = rows[lo : lo + WIDTH]
        dv = _dverify(chunk)
        hv = _hverify(chunk)
        mismatches += [lo + j for j, (d, h) in enumerate(zip(dv, hv))
                       if d != h]
    assert not mismatches, mismatches
    assert len(rows) >= 400


# -- big-int layer -------------------------------------------------------

def test_montgomery_arithmetic_against_python_ints():
    import jax

    rng = random.Random(7)
    mod = bigint.P256_P
    a_int = [rng.getrandbits(256) % bigint.P256_P_INT for _ in range(32)]
    b_int = [rng.getrandbits(256) % bigint.P256_P_INT for _ in range(32)]
    a = np.stack([bigint.limbs_from_int(v) for v in a_int])
    b = np.stack([bigint.limbs_from_int(v) for v in b_int])

    @jax.jit
    def modmul(a, b):
        am = bigint.to_mont(a, mod)
        bm = bigint.to_mont(b, mod)
        return (
            bigint.from_mont(bigint.mont_mul(am, bm, mod), mod),
            bigint.add_mod(a, b, mod),
            bigint.sub_mod(a, b, mod),
        )

    prod, s, d = modmul(a, b)
    for i in range(32):
        p = bigint.P256_P_INT
        assert bigint.int_from_limbs(np.asarray(prod)[i]) \
            == a_int[i] * b_int[i] % p
        assert bigint.int_from_limbs(np.asarray(s)[i]) \
            == (a_int[i] + b_int[i]) % p
        assert bigint.int_from_limbs(np.asarray(d)[i]) \
            == (a_int[i] - b_int[i]) % p


def test_mont_inv_random():
    import jax

    rng = random.Random(9)
    mod = bigint.P256_N
    vals = [rng.getrandbits(255) % (bigint.P256_N_INT - 1) + 1
            for _ in range(8)]
    a = np.stack([bigint.limbs_from_int(v) for v in vals])

    @jax.jit
    def inv(a):
        return bigint.from_mont(
            bigint.mont_inv(bigint.to_mont(a, mod), mod), mod)

    out = np.asarray(inv(a))
    for i, v in enumerate(vals):
        assert bigint.int_from_limbs(out[i]) \
            == pow(v, -1, bigint.P256_N_INT)


# -- extraction parity ---------------------------------------------------

def _sct_corpus():
    from ct_mapreduce_tpu.utils import minicert

    base = minicert.make_cert(serial=7, issuer_cn="Extract CA",
                              crl_dps=("http://crl.example/x",))
    plain = minicert.make_cert(serial=9, issuer_cn="NoExt CA",
                               add_basic_constraints=False)
    p256 = sctlib.EcSctSigner("ext-a")
    p384 = sctlib.EcSctSigner("ext-b", host.P384)
    rsa = sctlib.RsaSctSigner()
    certs = [
        sctlib.attach_sct(base, p256, 1_700_000_000_000),
        sctlib.attach_sct(base, p256, 1_700_000_000_001,
                          corrupt_signature=True),
        sctlib.attach_sct(base, p384, 1_700_000_000_002),
        sctlib.attach_sct(base, rsa, 1_700_000_000_003),
        base,
        sctlib.attach_sct(base, p256, 1_700_000_000_004,
                          extensions=b"hello"),
        sctlib.attach_sct(plain, p256, 5),
    ]
    rng = random.Random(1)
    for k in range(64):
        c = bytearray(certs[k % 7])
        for _ in range(rng.randrange(1, 4)):
            c[rng.randrange(len(c))] ^= 1 << rng.randrange(8)
        certs.append(bytes(c))
    pad = max(len(c) for c in certs) + 32
    data = np.zeros((len(certs), pad), np.uint8)
    length = np.zeros((len(certs),), np.int32)
    for i, c in enumerate(certs):
        data[i, : len(c)] = np.frombuffer(c, np.uint8)
        length[i] = len(c)
    return data, length


def test_sct_extraction_classes():
    data, length = _sct_corpus()
    out = sctlib.extract_scts_np(data, length)
    assert out.ok[:7].tolist() == [1, 1, 2, 2, 0, 1, 1]


def test_native_extraction_parity():
    from ct_mapreduce_tpu.native import available, leafpack

    if not available() or not getattr(
            __import__("ct_mapreduce_tpu.native", fromlist=["load"]).load(),
            "has_sct", False):
        pytest.skip("native SCT extractor unavailable")
    data, length = _sct_corpus()
    py = sctlib.extract_scts_np(data, length)
    for threads in (1, 4):
        nat = leafpack.extract_scts(data, length, threads=threads)
        for fld in ("ok", "digest", "log_id", "timestamp_ms", "r", "s",
                    "hash_alg", "sig_alg"):
            assert np.array_equal(getattr(nat, fld), getattr(py, fld)), \
                (threads, fld)


def test_extract_scts_python_fallback(monkeypatch):
    """CTMR_NATIVE=0 routes leafpack.extract_scts down the python
    mirror — same outputs (the degradation contract)."""
    from ct_mapreduce_tpu.native import leafpack

    data, length = _sct_corpus()
    monkeypatch.setenv("CTMR_NATIVE", "0")
    fb = leafpack.extract_scts(data, length)
    monkeypatch.delenv("CTMR_NATIVE")
    py = sctlib.extract_scts_np(data, length)
    assert np.array_equal(fb.ok, py.ok)
    assert np.array_equal(fb.digest, py.digest)


def test_registry_json_roundtrip(tmp_path):
    from ct_mapreduce_tpu.verify.lane import LogKeyRegistry

    reg = LogKeyRegistry()
    signers = [sctlib.EcSctSigner("rt-a"),
               sctlib.EcSctSigner("rt-b", host.P384),
               sctlib.RsaSctSigner()]
    for s in signers:
        reg.register_signer(s)
    # exercise the coordinate cache, then round-trip
    from ct_mapreduce_tpu.verify.lane import _key_coord

    _key_coord(reg.get(signers[0].log_id), "x")
    path = tmp_path / "keys.json"
    path.write_text(reg.to_json())
    reg2 = LogKeyRegistry.from_json_file(str(path))
    assert len(reg2) == 3
    assert reg2.is_p256(signers[0].log_id)
    assert not reg2.is_p256(signers[1].log_id)
    assert reg2.get(signers[2].log_id)["alg"] == "rsa"
