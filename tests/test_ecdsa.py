"""Verification kernels: known-answer corpus + parity fuzz.

The contract under test: BOTH batched device formulations — the
windowed-precompute ladder (round 17, the default) and the legacy
Jacobian Shamir ladder (window = 0) — are bit-identical to the
pure-python reference verifier (verify/host.verify_ecdsa) on EVERY
input: valid signatures, Wycheproof-style edge classes (r/s = 0,
r/s ≥ n, non-canonical s, off-curve and out-of-range public keys,
wrong digests), windowed-ladder edge cases (u1 = 0, all-zero window
digits, point-at-infinity intermediates, accumulator/table-point
collisions, zero-denominator lanes inside the batch-inversion
product), and a ≥400-case mutation fuzz — on P-256 AND P-384. The
native SCT extraction pass (ctmr_extract_scts) stays bit-identical to
its python mirror (verify/sct.extract_scts_np).

Compile budget: each (curve, window, width) shape is its own ~15-20 s
XLA compile on the 1-core CI box, so tier-1 pays exactly THREE
compiles — legacy P-256, windowed P-256, windowed P-384, all at the
shared width 32 (and the lane tests + bench smoke reuse the windowed
ones). The multi-window/multi-width sweeps and the 416-case fuzz
matrix run as ``slow`` tests.
"""

import hashlib
import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.ops import bigint, ecdsa  # noqa: E402
from ct_mapreduce_tpu.verify import host, sct as sctlib  # noqa: E402

C = host.P256
C384 = host.P384
WIDTH = 32
W = ecdsa.DEFAULT_WINDOW  # the tier-1 windowed compile (8)


def _bn(v: int, nbytes: int = 32) -> np.ndarray:
    return np.frombuffer(
        (v % (1 << (8 * nbytes))).to_bytes(nbytes, "big"), np.uint8
    ).copy()


def _b32(v: int) -> np.ndarray:
    return _bn(v, 32)


def _key(seed: str, c: host.Curve = C):
    d = host.derive_scalar(seed, c)
    return d, host._point_mul(c, d, (c.gx, c.gy))


def _sign(seed: str, msg: bytes, c: host.Curve = C):
    d, q = _key(seed, c)
    digest = hashlib.sha256(msg).digest()
    r, s = host.sign_ecdsa(c, digest, d, host.derive_nonce(seed, msg, c))
    return digest, r, s, q


def _rows_to_arrays(rows, c: host.Curve = C):
    nb = c.byte_len
    digest = np.stack([np.frombuffer(dg, np.uint8) for dg, *_ in rows])
    r = np.stack([_bn(ri, nb) for _dg, ri, *_ in rows])
    s = np.stack([_bn(si, nb) for _dg, _r, si, *_ in rows])
    qx = np.stack([_bn(xi, nb) for *_x, xi, _yi in rows])
    qy = np.stack([_bn(yi, nb) for *_x, yi in rows])
    return digest, r, s, qx, qy


def _dverify(rows, width: int = WIDTH, window: int = W,
             c: host.Curve = C):
    """Device verdicts for [(digest, r, s, x, y)] int/bytes tuples at
    an explicit padded width (pow2; 32 is the shared tier-1 shape)."""
    assert len(rows) <= width
    n = len(rows)
    nb = c.byte_len
    digest = np.zeros((width, 32), np.uint8)
    r, s, qx, qy = (np.zeros((width, nb), np.uint8) for _ in range(4))
    dg_a, r_a, s_a, qx_a, qy_a = _rows_to_arrays(rows, c)
    digest[:n], r[:n], s[:n], qx[:n], qy[:n] = dg_a, r_a, s_a, qx_a, qy_a
    valid = np.zeros((width,), bool)
    valid[:n] = True
    fn = ecdsa.verify_p256 if c is C else ecdsa.verify_p384
    out = fn(digest, r, s, qx, qy, valid, window=window)
    return out[:n].tolist()


def _hverify(rows, c: host.Curve = C):
    lim = 1 << (8 * c.byte_len)
    return [
        host.verify_ecdsa(c, dg, ri % lim, si % lim, xi % lim, yi % lim)
        for dg, ri, si, xi, yi in rows
    ]


def _kat_corpus(c: host.Curve = C):
    """(name, row, expected) — the pinned edge classes."""
    cases = []
    dg, r, s, q = _sign("kat-a", b"hello ct", c)
    dg2, r2, s2, q2 = _sign("kat-b", b"second key", c)
    lim = 1 << (8 * c.byte_len)
    cases += [
        ("valid-a", (dg, r, s, q[0], q[1]), True),
        ("valid-b", (dg2, r2, s2, q2[0], q2[1]), True),
        ("wrong-digest", (hashlib.sha256(b"x").digest(), r, s, q[0], q[1]),
         False),
        ("wrong-key", (dg, r, s, q2[0], q2[1]), False),
        ("r-zero", (dg, 0, s, q[0], q[1]), False),
        ("s-zero", (dg, r, 0, q[0], q[1]), False),
        ("r-eq-n", (dg, c.n, s, q[0], q[1]), False),
        ("s-eq-n", (dg, r, c.n, q[0], q[1]), False),
        ("r-over-n", (dg, c.n + 5, s, q[0], q[1]), False),
        ("s-over-n", (dg, r, (c.n + r) % lim, q[0], q[1]), False),
        # (r, n - s) is the alternate encoding of a VALID signature —
        # plain ECDSA accepts the non-canonical s.
        ("noncanonical-s", (dg, r, c.n - s, q[0], q[1]), True),
        ("swapped-rs", (dg, s, r, q[0], q[1]), False),
        ("pub-off-curve", (dg, r, s, q[0], q[1] ^ 1), False),
        ("pub-zero", (dg, r, s, 0, 0), False),
        ("pub-x-eq-p", (dg, r, s, c.p, q[1]), False),
        ("pub-y-over-p", (dg, r, s, q[0], c.p + q[1]), False),
        # x = 0 with a matching on-curve y: y^2 = b — may not have a
        # root; use negated-y instead (on curve, wrong key half).
        ("pub-neg-y", (dg, r, s, q[0], c.p - q[1]), False),
    ]
    return cases


def _window_edge_corpus(c: host.Curve = C):
    """(name, row, expected) — the round-17 windowed-ladder edge
    classes, each constructed from the group math so the interesting
    condition REALLY occurs mid-ladder. The SHA-256 digest bounds
    z < 2^256, so the cases needing z to hit an arbitrary mod-n value
    (valid u1 = 1 / valid doubling collisions) exist only on P-256;
    P-384 pins the same ladder states through False-verdict rows."""
    d, q = _key("edge-a", c)
    # u1 = 0 (every G window digit zero): zero digest → z = 0;
    # s = r·d·k⁻¹ makes u2·Q = k·G, so the signature is VALID with the
    # G side of the dual scalar contributing nothing.
    k = host.derive_nonce("edge-a", b"u1zero", c)
    rp = host._point_mul(c, k, (c.gx, c.gy))
    r0 = rp[0] % c.n
    s0 = r0 * d % c.n * pow(k, -1, c.n) % c.n
    # Q = -G with u1 = u2 (digest bytes = r): every window's G-add is
    # cancelled by its Q-add — the accumulator passes through the
    # point at infinity REPEATEDLY mid-ladder, and the result is
    # infinity (verdict False; host sees R = None).
    rx = 0x1234_5678_9ABC_DEF0_1357
    # Accumulator == table point (the P = Q doubling collision the
    # complete formulas must absorb): u1 = 2, u2 = 1, Q = 2G — after
    # the window-0 G-add the accumulator is 2G and the Q-add folds in
    # the SAME affine point. s = z·2⁻¹ and r = s force those scalars
    # for any digest z (False verdict: r is not x(4G)).
    q2g = host._point_mul(c, 2, (c.gx, c.gy))
    z_c = int.from_bytes(hashlib.sha256(b"collide").digest(), "big")
    s_c = z_c * pow(2, -1, c.n) % c.n
    cases = [
        ("u1-zero", (bytes(32), r0, s0, q[0], q[1]), True),
        ("mid-ladder-infinity",
         (rx.to_bytes(32, "big"), rx, 7, c.gx, c.p - c.gy), False),
        ("dbl-collision-false",
         (z_c.to_bytes(32, "big"), s_c, s_c, q2g[0], q2g[1]), False),
    ]
    if c is C:
        # u1 = 1: z = r·d·(k-1)⁻¹ and s = z — every u1 window digit
        # above the lowest is zero, and the signature stays VALID.
        k1 = host.derive_nonce("edge-b", b"u1one", c)
        r1 = host._point_mul(c, k1, (c.gx, c.gy))[0] % c.n
        z1 = r1 * d % c.n * pow(k1 - 1, -1, c.n) % c.n
        # Valid doubling collision: u1 = 2, u2 = 1, r = x(4G), s = r,
        # z = 2r — same ladder state as above but the verdict is True.
        r4 = host._point_mul(c, 4, (c.gx, c.gy))[0] % c.n
        cases += [
            ("u1-one-zero-digits",
             (z1.to_bytes(32, "big"), r1, z1, q[0], q[1]), True),
            ("dbl-collision-valid",
             ((2 * r4 % c.n).to_bytes(32, "big"), r4, r4,
              q2g[0], q2g[1]), True),
        ]
    return cases


def _run_corpus(cases, window: int, c: host.Curve = C):
    rows = [cs[1] for cs in cases]
    expected = [cs[2] for cs in cases]
    hv = _hverify(rows, c)
    assert hv == expected, [cs[0] for cs, h, e in
                            zip(cases, hv, expected) if h != e]
    dv = _dverify(rows, window=window, c=c)
    assert dv == expected, (window, [cs[0] for cs, d, e in
                                     zip(cases, dv, expected) if d != e])


def test_known_answer_corpus():
    """The full KAT corpus pinned host == windowed == legacy (the two
    tier-1 P-256 compiles)."""
    cases = _kat_corpus()
    _run_corpus(cases, window=W)
    _run_corpus(cases, window=0)


def test_windowed_edge_cases():
    """Round-17 windowed-ladder edges, pinned bit-identical vs the
    host reference AND vs the legacy (window = 0) ladder."""
    cases = _window_edge_corpus()
    _run_corpus(cases, window=W)
    _run_corpus(cases, window=0)


def test_batch_inversion_zero_lane_isolation():
    """Batches mixing zero-denominator lanes into the batch-inversion
    product: s = 0 lanes (zero through the s⁻¹ product) and
    R-at-infinity lanes (zero through the x_R = X/Z normalization)
    interleaved with valid lanes — every lane answers exactly what it
    answers alone (adversarial inputs cannot desync a neighbor)."""
    dg, r, s, q = _sign("iso-a", b"isolation")
    inf_row = _window_edge_corpus()[2][1]  # R = infinity lane
    rows = [
        (dg, r, s, q[0], q[1]),
        (dg, r, 0, q[0], q[1]),  # s = 0
        (dg, r, s, q[0], q[1]),
        inf_row,  # Z = 0 in the final normalization
        (dg, r, C.n - s, q[0], q[1]),  # still valid (non-canonical s)
        (hashlib.sha256(b"no").digest(), r, s, q[0], q[1]),  # failed
    ]
    batch = _dverify(rows, window=W)
    assert batch == _hverify(rows)
    for i, row in enumerate(rows):
        assert _dverify([row], window=W) == [batch[i]], i


def test_all_valid_and_all_invalid_batches():
    valid_rows = []
    for i in range(WIDTH):
        dg, r, s, q = _sign(f"fill-{i % 5}", b"m%d" % i)
        valid_rows.append((dg, r, s, q[0], q[1]))
    assert _dverify(valid_rows, window=0) == [True] * WIDTH
    invalid_rows = [(dg, 0, s, x, y) for dg, _r, s, x, y in valid_rows]
    assert _dverify(invalid_rows, window=0) == [False] * WIDTH


def test_padding_mask_parity():
    """Verdicts are invariant to where lanes sit in the padded batch:
    the same rows scattered behind invalid filler lanes answer
    identically (the valid mask really gates, padding garbage cannot
    leak into live lanes)."""
    cases = _kat_corpus()[:10]
    rows = [cs[1] for cs in cases]
    base = _dverify(rows, window=W)
    filler = _sign("pad-filler", b"pad")
    mixed = []
    for row in rows:
        mixed.append((filler[0], 0, 0, 0, 0))  # dead-invalid lane
        mixed.append(row)
    out = _dverify(mixed, window=W)
    assert out[1::2] == base


def test_p384_known_answer_corpus():
    """The P-384 device lane's own KAT corpus (full edge classes +
    windowed edges), verdict-bit-identical to the host reference —
    the ONE tier-1 P-384 compile (windowed, width 32; the lane tests
    and bench smoke reuse it)."""
    cases = _kat_corpus(C384) + _window_edge_corpus(C384)
    _run_corpus(cases, window=W, c=C384)


@pytest.mark.slow
def test_p384_window0_parity():
    """P-384 through the legacy (window = 0) Jacobian ladder — its
    own 384-iteration compile, so slow-tier; the windowed↔legacy↔host
    triangle is tier-1 on P-256 and the P-384 windowed↔host edge is
    tier-1 above."""
    cases = _kat_corpus(C384) + _window_edge_corpus(C384)
    _run_corpus(cases, window=0, c=C384)


@pytest.mark.slow
def test_batch_width_parity_wide():
    """Same lanes at freshly-compiled widths 64 and 128 → identical
    verdicts (width-invariance of the pow2-padded dispatch). Slow:
    each width is its own XLA compile on the CI box."""
    cases = _kat_corpus()
    rows = [cs[1] for cs in cases]
    expected = [cs[2] for cs in cases]
    assert _dverify(rows, width=64, window=0) == expected
    assert _dverify(rows, width=128, window=0) == expected
    assert _dverify(rows, width=64, window=W) == expected


@pytest.mark.slow
@pytest.mark.parametrize("window,curve", [
    (0, "p256"), (2, "p256"), (4, "p256"), (8, "p256"),
    (0, "p384"), (8, "p384"),
])
def test_mutation_fuzz_device_host_parity(window, curve):
    """≥400 mutated signatures (P-256; 128 for the slower P-384
    host reference): the device verdict equals the host verdict on
    every lane at every (window, curve) configuration, including the
    window = 0 legacy path (acceptance gate). Mutations hit every
    input field; ~1/8 lanes are left untouched (valid).

    @slow since round 15 (tier-1 budget banking): the verdict-parity
    contract stays tier-1-gated by the KAT corpora, the windowed-edge
    and zero-lane-isolation batches, and the CT_BENCH_SMOKE verify
    leg; this sweep re-walks the same kernels per configuration."""
    c = C if curve == "p256" else C384
    nbits = 8 * c.byte_len
    rng = random.Random(0x5C7 + window)
    count = 13 * WIDTH if curve == "p256" else 4 * WIDTH
    rows = []
    for i in range(count):
        dg, r, s, q = _sign(f"fuzz-{i % 7}", b"fz%d" % (i % 29), c)
        row = [bytearray(dg), r, s, q[0], q[1]]
        kind = rng.randrange(8)
        if kind == 1:
            row[0][rng.randrange(32)] ^= 1 << rng.randrange(8)
        elif kind == 2:
            row[1] ^= 1 << rng.randrange(nbits)
        elif kind == 3:
            row[2] ^= 1 << rng.randrange(nbits)
        elif kind == 4:
            row[3] ^= 1 << rng.randrange(nbits)
        elif kind == 5:
            row[4] ^= 1 << rng.randrange(nbits)
        elif kind == 6:
            row[rng.randrange(1, 5)] = rng.getrandbits(nbits)
        elif kind == 7:
            row[2] = c.n - row[2]  # stays valid
        rows.append((bytes(row[0]), row[1], row[2], row[3], row[4]))
    mismatches = []
    for lo in range(0, len(rows), WIDTH):
        chunk = rows[lo : lo + WIDTH]
        dv = _dverify(chunk, window=window, c=c)
        hv = _hverify(chunk, c)
        mismatches += [lo + j for j, (d, h) in enumerate(zip(dv, hv))
                       if d != h]
    assert not mismatches, mismatches
    assert len(rows) >= (400 if curve == "p256" else 128)


# -- big-int layer -------------------------------------------------------

@pytest.mark.parametrize("mod,p_int", [
    (bigint.P256_P, bigint.P256_P_INT),
    (bigint.P384_P, bigint.P384_P_INT),
])
def test_montgomery_arithmetic_against_python_ints(mod, p_int):
    import jax

    rng = random.Random(7)
    nbits = bigint.RADIX * mod.nlimb
    a_int = [rng.getrandbits(nbits) % p_int for _ in range(32)]
    b_int = [rng.getrandbits(nbits) % p_int for _ in range(32)]
    a = np.stack([bigint.limbs_from_int(v, mod.nlimb) for v in a_int])
    b = np.stack([bigint.limbs_from_int(v, mod.nlimb) for v in b_int])

    @jax.jit
    def modmul(a, b):
        am = bigint.to_mont(a, mod)
        bm = bigint.to_mont(b, mod)
        return (
            bigint.from_mont(bigint.mont_mul(am, bm, mod), mod),
            bigint.add_mod(a, b, mod),
            bigint.sub_mod(a, b, mod),
        )

    prod, s, d = modmul(a, b)
    for i in range(32):
        assert bigint.int_from_limbs(np.asarray(prod)[i]) \
            == a_int[i] * b_int[i] % p_int
        assert bigint.int_from_limbs(np.asarray(s)[i]) \
            == (a_int[i] + b_int[i]) % p_int
        assert bigint.int_from_limbs(np.asarray(d)[i]) \
            == (a_int[i] - b_int[i]) % p_int


def test_mont_inv_random():
    import jax

    rng = random.Random(9)
    mod = bigint.P256_N
    vals = [rng.getrandbits(255) % (bigint.P256_N_INT - 1) + 1
            for _ in range(8)]
    a = np.stack([bigint.limbs_from_int(v) for v in vals])

    @jax.jit
    def inv(a):
        return bigint.from_mont(
            bigint.mont_inv(bigint.to_mont(a, mod), mod), mod)

    out = np.asarray(inv(a))
    for i, v in enumerate(vals):
        assert bigint.int_from_limbs(out[i]) \
            == pow(v, -1, bigint.P256_N_INT)


@pytest.mark.parametrize("mod,n_int", [
    (bigint.P256_N, bigint.P256_N_INT),
    (bigint.P384_N, bigint.P384_N_INT),
])
def test_batch_inv_mont_matches_fermat(mod, n_int):
    """batch_inv_mont ≡ pow(v, -1, n) per lane, with zero lanes
    (masked through the product) inverting to zero and not disturbing
    their neighbors."""
    import jax

    rng = random.Random(11)
    nbits = bigint.RADIX * mod.nlimb
    vals = [rng.getrandbits(nbits - 1) % (n_int - 1) + 1
            for _ in range(12)]
    vals[3] = 0
    vals[7] = 0
    a = np.stack([bigint.limbs_from_int(v, mod.nlimb) for v in vals])

    @jax.jit
    def binv(a):
        return bigint.from_mont(
            bigint.batch_inv_mont(bigint.to_mont(a, mod), mod), mod)

    out = np.asarray(binv(a))
    for i, v in enumerate(vals):
        got = bigint.int_from_limbs(out[i])
        assert got == (pow(v, -1, n_int) if v else 0), i


def test_point_table_independently_derivable():
    """Window-table entries equal d·2^(w·j)·G computed through the
    reference scalar multiplication — the precompute constants are
    derivable without the incremental builder that made them."""
    tab = ecdsa.point_table_np(C, C.gx, C.gy, 8)
    r_mont = 1 << 256
    for j, d in ((0, 1), (0, 255), (3, 17), (31, 2)):
        pt = host._point_mul(C, d << (8 * j), (C.gx, C.gy))
        assert bigint.int_from_limbs(tab[j, d, 0]) \
            == pt[0] * r_mont % C.p
        assert bigint.int_from_limbs(tab[j, d, 1]) \
            == pt[1] * r_mont % C.p
    assert not tab[:, 0].any()  # digit 0 = identity slots stay zero


# -- extraction parity ---------------------------------------------------

def _sct_corpus():
    from ct_mapreduce_tpu.utils import minicert

    base = minicert.make_cert(serial=7, issuer_cn="Extract CA",
                              crl_dps=("http://crl.example/x",))
    plain = minicert.make_cert(serial=9, issuer_cn="NoExt CA",
                               add_basic_constraints=False)
    p256 = sctlib.EcSctSigner("ext-a")
    p384 = sctlib.EcSctSigner("ext-b", host.P384)
    rsa = sctlib.RsaSctSigner()
    certs = [
        sctlib.attach_sct(base, p256, 1_700_000_000_000),
        sctlib.attach_sct(base, p256, 1_700_000_000_001,
                          corrupt_signature=True),
        sctlib.attach_sct(base, p384, 1_700_000_000_002),
        sctlib.attach_sct(base, rsa, 1_700_000_000_003),
        base,
        sctlib.attach_sct(base, p256, 1_700_000_000_004,
                          extensions=b"hello"),
        sctlib.attach_sct(plain, p256, 5),
    ]
    rng = random.Random(1)
    for k in range(64):
        c = bytearray(certs[k % 7])
        for _ in range(rng.randrange(1, 4)):
            c[rng.randrange(len(c))] ^= 1 << rng.randrange(8)
        certs.append(bytes(c))
    pad = max(len(c) for c in certs) + 32
    data = np.zeros((len(certs), pad), np.uint8)
    length = np.zeros((len(certs),), np.int32)
    for i, c in enumerate(certs):
        data[i, : len(c)] = np.frombuffer(c, np.uint8)
        length[i] = len(c)
    return data, length


def test_sct_extraction_classes():
    data, length = _sct_corpus()
    out = sctlib.extract_scts_np(data, length)
    assert out.ok[:7].tolist() == [1, 1, 2, 2, 0, 1, 1]


def test_native_extraction_parity():
    from ct_mapreduce_tpu.native import available, leafpack

    if not available() or not getattr(
            __import__("ct_mapreduce_tpu.native", fromlist=["load"]).load(),
            "has_sct", False):
        pytest.skip("native SCT extractor unavailable")
    data, length = _sct_corpus()
    py = sctlib.extract_scts_np(data, length)
    for threads in (1, 4):
        nat = leafpack.extract_scts(data, length, threads=threads)
        for fld in ("ok", "digest", "log_id", "timestamp_ms", "r", "s",
                    "hash_alg", "sig_alg"):
            assert np.array_equal(getattr(nat, fld), getattr(py, fld)), \
                (threads, fld)


def test_extract_scts_python_fallback(monkeypatch):
    """CTMR_NATIVE=0 routes leafpack.extract_scts down the python
    mirror — same outputs (the degradation contract)."""
    from ct_mapreduce_tpu.native import leafpack

    data, length = _sct_corpus()
    monkeypatch.setenv("CTMR_NATIVE", "0")
    fb = leafpack.extract_scts(data, length)
    monkeypatch.delenv("CTMR_NATIVE")
    py = sctlib.extract_scts_np(data, length)
    assert np.array_equal(fb.ok, py.ok)
    assert np.array_equal(fb.digest, py.digest)


def test_registry_json_roundtrip(tmp_path):
    from ct_mapreduce_tpu.verify.lane import LogKeyRegistry

    reg = LogKeyRegistry()
    signers = [sctlib.EcSctSigner("rt-a"),
               sctlib.EcSctSigner("rt-b", host.P384),
               sctlib.RsaSctSigner()]
    for s in signers:
        reg.register_signer(s)
    # exercise the coordinate cache, then round-trip (the "_"-prefixed
    # runtime caches — coords, registry epoch — must not serialize)
    from ct_mapreduce_tpu.verify.lane import _key_coord

    _key_coord(reg.get(signers[0].log_id), "x")
    assert reg.epoch == 3
    path = tmp_path / "keys.json"
    path.write_text(reg.to_json())
    assert "_epoch" not in path.read_text()
    reg2 = LogKeyRegistry.from_json_file(str(path))
    assert len(reg2) == 3
    assert reg2.is_p256(signers[0].log_id)
    assert not reg2.is_p256(signers[1].log_id)
    assert reg2.get(signers[2].log_id)["alg"] == "rsa"
