"""TpuAggregator: end-to-end reduce-state tests.

The parity oracle replays the reference's Store semantics through the
host-side mock-cache path (the framework's analog of the reference's
MockRemoteCache harness, /root/reference/storage/filesystemdatabase_test.go),
then compares drained counts — the "issuer-count parity" gate from
BASELINE.md."""

import datetime

import numpy as np
import pytest

from ct_mapreduce_tpu.agg import TpuAggregator
from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.core.types import ExpDate, Issuer

from certgen import make_cert, requires_cryptography, spki_of

UTC = datetime.timezone.utc
NOW = datetime.datetime(2024, 6, 1, tzinfo=UTC)


def agg(**kw):
    kw.setdefault("capacity", 1 << 14)
    kw.setdefault("batch_size", 64)
    kw.setdefault("now", NOW)
    return TpuAggregator(**kw)


def leaf(serial, issuer_cn="Agg CA", **kw):
    kw.setdefault("is_ca", False)
    kw.setdefault("subject_cn", f"s{serial}.example.com")
    return make_cert(serial=serial, issuer_cn=issuer_cn, **kw)


def test_basic_dedup_and_counts():
    a = agg()
    ca = make_cert(issuer_cn="Agg CA")
    leaves = [leaf(1000 + i) for i in range(10)]
    entries = [(l, ca) for l in leaves]
    res = a.ingest(entries)
    assert res.was_unknown.all()
    # Same batch again: all known.
    res2 = a.ingest(entries)
    assert not res2.was_unknown.any()
    snap = a.drain()
    assert snap.total == 10
    iid = Issuer.from_spki(spki_of(ca)).id()
    # All leaves share one expiry hour in certgen defaults.
    ref = hostder.parse_cert(leaves[0])
    exp_id = ExpDate.from_unix_hour(ref.not_after_unix_hour).id()
    assert snap.counts == {(iid, exp_id): 10}


def test_multi_issuer_counts():
    a = agg()
    cas = [make_cert(issuer_cn=f"Multi CA {i}", key_seed=i) for i in range(3)]
    entries = []
    for i, ca in enumerate(cas):
        for s in range(i + 1):
            entries.append((leaf(5000 + 100 * i + s, issuer_cn=f"Multi CA {i}"), ca))
    res = a.ingest(entries)
    assert res.was_unknown.all()
    snap = a.drain()
    assert snap.total == 6
    per_issuer = {}
    for (iid, _), c in snap.counts.items():
        per_issuer[iid] = per_issuer.get(iid, 0) + c
    for i, ca in enumerate(cas):
        iid = Issuer.from_spki(spki_of(ca)).id()
        assert per_issuer[iid] == i + 1


def test_metadata_accumulation():
    a = agg()
    ca = make_cert(issuer_cn="Meta CA")
    l1 = leaf(7000, issuer_cn="Meta CA",
              crl_dps=("http://crl.example.com/m.crl",))
    l2 = leaf(7001, issuer_cn="Meta CA",
              crl_dps=("http://crl.example.com/m.crl",
                       "ldap://ignore.me/x",
                       "https://crl2.example.com/n.crl"))
    a.ingest([(l1, ca), (l2, ca)])
    snap = a.drain()
    iid = Issuer.from_spki(spki_of(ca)).id()
    assert snap.crls[iid] == {
        "http://crl.example.com/m.crl",
        "https://crl2.example.com/n.crl",
    }
    ref = hostder.parse_cert(l1)
    assert snap.dns[iid] == {ref.issuer_dn}


def test_filters_counted():
    a = agg()
    ca_cert = make_cert(issuer_cn="Filter CA")
    expired = leaf(
        8000, issuer_cn="Filter CA",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2021, 1, 1, tzinfo=UTC),
    )
    is_ca = make_cert(issuer_cn="Filter CA", serial=8001)  # CA cert
    good = leaf(8002, issuer_cn="Filter CA")
    res = a.ingest([(expired, ca_cert), (is_ca, ca_cert), (good, ca_cert)])
    assert list(res.filtered) == [True, True, False]
    assert list(res.was_unknown) == [False, False, True]
    assert a.metrics["filtered_expired"] == 1
    assert a.metrics["filtered_ca"] == 1


def test_expiring_this_hour_exact_boundary():
    """Expiry-filter granularity at the bucket boundary: the device
    compares hour buckets while the reference compares instants
    (/root/reference/cmd/ct-fetch/ct-fetch.go:52-55 via
    `NotAfter.Before(now)`). Certs expiring WITHIN the current hour are
    routed to the exact host lane (ops/pipeline.py device_exact gate),
    so the combined system matches the reference instant-exactly:
    NotAfter just before `now` filters, just after `now` survives."""
    now = datetime.datetime(2024, 6, 1, 14, 45, tzinfo=UTC)
    a = agg(now=now)
    ca = make_cert(issuer_cn="Edge CA")
    prev_hour = leaf(  # NotAfter 13:50 — earlier bucket, device-filtered
        7100, issuer_cn="Edge CA",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2024, 6, 1, 13, 50, tzinfo=UTC),
    )
    just_gone = leaf(  # NotAfter 14:30 < now — boundary bucket, expired
        7101, issuer_cn="Edge CA",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2024, 6, 1, 14, 30, tzinfo=UTC),
    )
    still_ok = leaf(  # NotAfter 14:55 > now — boundary bucket, valid
        7102, issuer_cn="Edge CA",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2024, 6, 1, 14, 55, tzinfo=UTC),
    )
    next_hour = leaf(  # NotAfter 15:05 — later bucket, device-kept
        7103, issuer_cn="Edge CA",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2024, 6, 1, 15, 5, tzinfo=UTC),
    )
    res = a.ingest([(prev_hour, ca), (just_gone, ca),
                    (still_ok, ca), (next_hour, ca)])
    assert list(res.filtered) == [True, True, False, False]
    assert list(res.was_unknown) == [False, False, True, True]
    assert a.metrics["filtered_expired"] == 2
    # Both boundary-bucket lanes took the exact host lane.
    assert res.host_lane_count == 2
    assert a.drain().total == 2


def test_host_then_device_duplicate_counts_once():
    """The pathological cross-encoding order — an oversized cert takes
    the host lane FIRST, then a device-sized cert with the same
    (issuer, serial, expiry) identity arrives — must still count once,
    like the reference's single SADD set. Count-only sinks rely on
    drain()'s batched overlap subtraction; serial-materializing sinks
    additionally get the per-entry report corrected in-flight."""
    for want_serials in (False, True):
        a = agg(capacity=1 << 12, batch_size=16)
        a.want_serials = want_serials
        _host_then_device(a, want_serials)


def _host_then_device(a, want_serials):
    ca = make_cert(issuer_cn="Guard CA")
    exp = datetime.datetime(2031, 6, 15, 14, 0, tzinfo=UTC)
    big = make_cert(
        serial=0xABCD, issuer_cn="Guard CA", subject_cn="big.example.com",
        is_ca=False, not_after=exp,
        crl_dps=tuple(f"http://crl{i}.g.example/{'q' * 90}.crl"
                      for i in range(80)),
    )
    small = make_cert(serial=0xABCD, issuer_cn="Guard CA",
                      subject_cn="small.example.com", is_ca=False,
                      not_after=exp)
    assert len(big) > packing.LENGTH_BUCKETS[-1] >= len(small)
    r1 = a.ingest([(big, ca)])  # oversized → exact host lane
    assert r1.was_unknown[0] and r1.host_lane_count == 1
    r2 = a.ingest([(small, ca)])  # device lane, same (issuer, serial, hour)
    if want_serials:
        # In-flight guard corrects the per-entry report too.
        assert not r2.was_unknown[0]
    assert a.drain().total == 1  # the counting contract, both modes
    assert a.drain().total == 1  # drain is idempotent


def test_boundary_migration_no_double_count():
    """A cert deduped on DEVICE whose later duplicate arrives during its
    expiry hour migrates to the host lane (boundary routing). The host
    lane's cross-domain guard must consult the device table so the
    serial counts once — the reference's single Redis SADD set can
    never double count (/root/reference/storage/knowncertificates.go:38-55)."""
    ca = make_cert(issuer_cn="Mig CA")
    x = leaf(
        7300, issuer_cn="Mig CA",
        not_before=datetime.datetime(2020, 1, 1, tzinfo=UTC),
        not_after=datetime.datetime(2024, 6, 1, 14, 30, tzinfo=UTC),
    )
    a = agg(now=datetime.datetime(2024, 6, 1, 13, 10, tzinfo=UTC))
    r1 = a.ingest([(x, ca)])
    assert r1.was_unknown[0] and r1.host_lane_count == 0
    # Same cert again, now inside its expiry hour: boundary → host lane.
    a._fixed_now = datetime.datetime(2024, 6, 1, 14, 10, tzinfo=UTC)
    r2 = a.ingest([(x, ca)])
    assert r2.host_lane_count == 1
    assert not r2.was_unknown[0]  # known via the device table, not re-counted
    assert not r2.filtered[0]  # 14:30 > 14:10 — still valid
    assert a.drain().total == 1
    assert a.metrics["inserted"] == 1 and a.metrics["known"] == 1


def test_host_lane_garbage_and_oversize():
    a = agg()
    ca = make_cert(issuer_cn="Host CA")
    good = leaf(9000, issuer_cn="Host CA")
    res = a.ingest([(good, ca), (b"\x30\x82junkjunk", ca)])
    assert list(res.was_unknown) == [True, False]
    assert a.metrics["parse_errors"] == 1
    assert a.drain().total == 1


def test_overflow_falls_back_to_host_exact():
    # Tiny table + tiny probe budget: overflowed lanes must still dedup
    # exactly via the host lane, and counts stay exact.
    a = agg(capacity=16, max_probes=2, batch_size=16)
    ca = make_cert(issuer_cn="Ovf CA")
    leaves = [leaf(20000 + i, issuer_cn="Ovf CA") for i in range(40)]
    entries = [(l, ca) for l in leaves]
    r1 = a.ingest(entries)
    assert r1.was_unknown.all()
    r2 = a.ingest(entries)
    assert not r2.was_unknown.any()
    snap = a.drain()
    assert snap.total == 40


def test_checkpoint_roundtrip(tmp_path):
    a = agg()
    ca = make_cert(issuer_cn="Ckpt CA")
    leaves = [leaf(30000 + i, issuer_cn="Ckpt CA",
                   crl_dps=("http://crl.example.com/c.crl",)) for i in range(8)]
    a.ingest([(l, ca) for l in leaves])
    path = str(tmp_path / "agg.npz")
    a.save_checkpoint(path)

    b = agg()
    b.load_checkpoint(path)
    # Restored state dedups against the original inserts.
    res = b.ingest([(l, ca) for l in leaves])
    assert not res.was_unknown.any()
    assert b.drain().counts == a.drain().counts
    assert b.drain().crls == a.drain().crls


def test_cn_prefix_filter_through_aggregator():
    a = agg(cn_prefixes=("Keep",))
    keep_ca = make_cert(issuer_cn="Keep CA", key_seed=1)
    drop_ca = make_cert(issuer_cn="Drop CA", key_seed=2)
    res = a.ingest([
        (leaf(40000, issuer_cn="Keep CA"), keep_ca),
        (leaf(40001, issuer_cn="Drop CA"), drop_ca),
    ])
    assert list(res.was_unknown) == [True, False]
    assert list(res.filtered) == [False, True]
    assert a.metrics["filtered_cn"] == 1


@requires_cryptography
def test_rsa_certificates_device_path():
    """RSA certs (the dominant real-CT key type): ~270-byte SPKI and a
    different AlgorithmIdentifier shape than every ECDSA fixture in
    this suite. The device walker must extract the same identity the
    host parser does, including a 20-byte serial (RFC 5280 maximum)."""
    import datetime as dt

    from cryptography import x509 as cx509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    now = dt.datetime(2024, 1, 1, tzinfo=UTC)

    def build(cn, serial, ca):
        name = cx509.Name([cx509.NameAttribute(NameOID.COMMON_NAME, cn)])
        issuer = cx509.Name(
            [cx509.NameAttribute(NameOID.COMMON_NAME, "RSA Agg CA")])
        b = (cx509.CertificateBuilder()
             .subject_name(name).issuer_name(issuer)
             .public_key(key.public_key())
             .serial_number(serial)
             .not_valid_before(now)
             .not_valid_after(now + dt.timedelta(days=900))
             .add_extension(cx509.BasicConstraints(
                 ca=ca, path_length=None), critical=True))
        return b.sign(key, hashes.SHA256()).public_bytes(
            serialization.Encoding.DER)

    ca_der = build("RSA Agg CA", 1, True)
    max_serial = int.from_bytes(b"\x7f" + b"\xab" * 19, "big")  # 20 bytes
    leaves = [build(f"rsa{i}.example.com", max_serial - i, False)
              for i in range(4)]

    a = agg()
    res = a.ingest([(l, ca_der) for l in leaves])
    assert res.was_unknown.all()
    assert not res.filtered.any()
    res2 = a.ingest([(l, ca_der) for l in leaves])
    assert not res2.was_unknown.any()
    snap = a.drain()
    assert snap.total == 4

    # Identity ground truth straight from cryptography, not our parser.
    spki = key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    assert snap.issuers() == [Issuer.from_spki(spki).id()]
    # Serial bytes: raw DER integer encoding (leading 0x7f, 20 bytes).
    assert res.serials[0] == (b"\x7f" + b"\xab" * 19)


def test_registry_overflow_routes_to_host_lane():
    # A full-log replay can exceed META_ISSUER_BITS worth of issuers;
    # issuers past the device meta range must degrade to the exact
    # host lane (count-exact), not crash ingest.
    a = agg()
    reg = a.registry
    while len(reg._issuers) < packing.MAX_ISSUERS:
        iss = Issuer.from_string(f"pad-{len(reg._issuers)}")
        reg._by_issuer_id[iss.id()] = len(reg._issuers)
        reg._issuers.append(iss)

    cas = [make_cert(issuer_cn=f"Ovf CA {i}", key_seed=60 + i)
           for i in range(2)]
    entries = []
    for i, ca in enumerate(cas):
        for s in range(3):
            entries.append(
                (leaf(77000 + 10 * i + s, issuer_cn=f"Ovf CA {i}"), ca))
    res = a.ingest(entries)
    assert (res.issuer_idx >= packing.MAX_ISSUERS).all()
    assert res.was_unknown.all()
    assert not res.filtered.any()
    assert res.host_lane_count == len(entries)  # all took the exact lane

    # Re-ingest dedups exactly; totals stay put.
    res2 = a.ingest(entries)
    assert not res2.was_unknown.any()

    snap = a.drain()
    assert snap.total == len(entries)
    per_issuer = {}
    for (iss_id, _), c in snap.counts.items():
        per_issuer[iss_id] = per_issuer.get(iss_id, 0) + c
    for ca in cas:
        iid = Issuer.from_spki(spki_of(ca)).id()
        assert per_issuer[iid] == 3
        idx = reg.index_of_issuer_id(iid)
        assert idx >= packing.MAX_ISSUERS
        assert a.issuer_totals[idx] == 3
