"""True multi-process scale-out.

Two lanes:

1. **Simulated ingest fleet (tier-1, CPU-complete):** W=2 real
   ``ct-fetch`` worker PROCESSES coordinated through miniredis — SETNX
   election, start barrier, heartbeats, leader-published checkpoint
   epochs — over disjoint rendezvous partitions of a shared fakelog
   fixture (tools/fleet.py harness), with the merged per-worker
   aggregates byte-identical to a single-worker run of the same
   entries; plus the SIGKILL-and-resume warm-restart contract. No XLA
   multiprocess collectives required, so these gates run (not skip) on
   the CPU CI backend.

2. **Global-mesh collectives:** the explicit-arguments path of
   ``initialize_multihost`` (``jax.distributed.initialize``) with one
   mesh-global ShardedDedup step whose row-sharded table spans both
   processes' devices. Still capability-gated: this jax build's CPU
   backend cannot run cross-process collectives (the fleet lane above
   is the one that must always run).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_CHILD = textwrap.dedent("""
    import os, sys

    port, pid, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ.pop("CT_TPU_TESTS", None)

    from ct_mapreduce_tpu.parallel.distributed import (
        DistributedCoordinator,
        initialize_multihost,
        is_leader,
    )

    initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )

    import jax
    import numpy as np

    assert jax.process_index() == pid, (jax.process_index(), pid)
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == 2 * nprocs  # global device view
    assert is_leader() == (pid == 0)

    coord = DistributedCoordinator("mp-test")
    if coord.await_leader():
        print(f"proc{pid}: leader", flush=True)
        coord.send_start()
    else:
        print(f"proc{pid}: follower", flush=True)
        coord.await_start(timeout_s=120)
    print(f"proc{pid}: barrier released", flush=True)

    # One global-mesh sharded dedup step: the table's rows are sharded
    # over all 4 devices across BOTH processes; key routing rides
    # all_to_all, per-issuer counts come back psum'd (replicated, so
    # every process can read them).
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg import sharded

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.environ["CT_GRAFT_ENTRY"])
    ge = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ge)

    mesh = Mesh(np.asarray(jax.devices()), (sharded.AXIS,))
    n = mesh.devices.size
    batch = 16 * n
    data, length, issuer_idx, valid, _ = ge._packed_batch(
        batch, 1024, n_issuers=2)
    # Each process generated its own signing keys — broadcast proc 0's
    # batch so every controller feeds identical global values (the
    # same-value contract of multi-process device_put), riding the
    # distributed runtime's own collective.
    from jax.experimental import multihost_utils

    data, length, issuer_idx, valid = (
        np.asarray(multihost_utils.broadcast_one_to_all(x))
        for x in (data, length, issuer_idx, valid)
    )

    dedup = sharded.ShardedDedup(mesh, capacity=1024 * n)
    out = dedup.step(data, length, issuer_idx, valid,
                     now_hour=ge._NOW_HOUR)
    counts = np.asarray(out.issuer_unknown_counts)  # replicated → readable
    total = dedup.total_count()
    host_lane_ct = int(np.asarray(
        jax.jit(lambda x: x.sum())(out.host_lane)))
    assert total + host_lane_ct == batch, (total, host_lane_ct, batch)
    assert int(counts.sum()) == total, (int(counts.sum()), total)

    out2 = dedup.step(data, length, issuer_idx, valid,
                      now_hour=ge._NOW_HOUR)
    assert dedup.total_count() == total  # replay inserted nothing
    print(f"proc{pid}: sharded step OK total={total}", flush=True)

    # Auto-growth must be forced OFF under multi-host: its trigger is
    # per-process and would fire out of lockstep (collective deadlock).
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    agg = ShardedAggregator(mesh, capacity=1024 * n, batch_size=batch,
                            grow_at=0.7)
    assert agg.grow_at == 0, agg.grow_at
    print(f"proc{pid}: multi-host growth guard OK", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- the simulated ingest fleet (tier-1, no collectives needed) ---------


@pytest.fixture()
def compile_cache(tmp_path_factory, monkeypatch):
    """One persistent XLA compile cache shared by every worker
    subprocess in this module: the W children compile identical tiny
    CPU programs, so only the first pays (spawn_worker forwards the
    env)."""
    path = str(tmp_path_factory.getbasetemp().parent / "fleet-xla-cache")
    monkeypatch.setenv("CT_COMPILE_CACHE", path)
    return path


@pytest.mark.timeout(340)
def test_fleet_two_worker_parity(tmp_path, compile_cache):
    """ISSUE 9 acceptance #1: two ct-fetch worker processes over
    miniredis and disjoint fakelog partitions produce a merged
    aggregate byte-identical (serial counts per (issuer, expDate),
    issuer CRL/DN metadata, verify counts) to a single-worker run of
    the same entries."""
    from tools import fleet as harness

    from ct_mapreduce_tpu.ingest import ctclient
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    fixture_path = str(tmp_path / "fixture.json")
    fixture = harness.build_fixture(
        fixture_path, n_logs=3, entries_per_log=64, dupes=8, max_batch=64)
    total = sum(len(v) for v in fixture["logs"].values())

    server = MiniRedis().start()
    try:
        procs = [
            harness.spawn_worker(
                w, 2, fixture_path, str(tmp_path / f"w{w}"),
                server.address, checkpoint_period="500ms")
            for w in range(2)
        ]
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        server.stop()
    for w, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {w} failed:\n{out[-4000:]}"
    events = [harness.child_events(out) for out in outs]
    dones = [next(e for e in evs if e["event"] == "done")
             for evs in events]

    # The partition really was disjoint and covering, and both workers
    # had work (3 fixture logs split 1/2 under the rendezvous hash).
    owned = {d["worker"]: d["owned_logs"] for d in dones}
    flat = [u for logs in owned.values() for u in logs]
    assert sorted(flat) == sorted(fixture["logs"])
    assert all(len(logs) >= 1 for logs in owned.values()), owned

    # Merged aggregate == the single-worker truth, byte-identical.
    merged = harness.merged_snapshot([d["state_path"] for d in dones])
    ref = harness.run_serial_reference(fixture, str(tmp_path))
    assert merged == ref
    assert 0 < merged["total"] <= total

    # Round-15 artifact determinism (the tools/fleet.py --verify
    # contract): the merged fleet FILTER compiled from the two worker
    # checkpoints is byte-identical to the serial run's — worker-local
    # issuer indices cancel out of the canonical keys.
    fleet_blob = harness.filter_bytes([d["state_path"] for d in dones])
    serial_blob = harness.filter_bytes([str(tmp_path / "serial.npz")])
    assert fleet_blob == serial_blob
    assert len(fleet_blob) > 12  # a real artifact, not an empty header


@pytest.mark.timeout(340)
def test_fleet_kill_and_resume(tmp_path, compile_cache):
    """ISSUE 9 acceptance #2: a worker SIGKILLed mid-ingest after >=1
    checkpoint resumes from its checkpoint cursor — NOT entry 0 — and
    the final aggregate equals the uninterrupted run's."""
    from tools import fleet as harness

    from ct_mapreduce_tpu.ingest.ctclient import short_url
    from ct_mapreduce_tpu.storage.rediscache import RedisCache
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    fixture_path = str(tmp_path / "fixture.json")
    fixture = harness.build_fixture(
        fixture_path, n_logs=1, entries_per_log=224, dupes=16,
        max_batch=32)
    url = next(iter(fixture["logs"]))
    total = len(fixture["logs"][url])
    wdir = str(tmp_path / "w0")

    server = MiniRedis().start()
    try:
        # Victim run: throttled downloads + a 300 ms checkpoint cadence
        # guarantee >=1 durable (cursor, aggregate) checkpoint lands
        # mid-ingest; then SIGKILL — no graceful shutdown path runs.
        # Cache policy (see tools/fleet.py::spawn_worker and BENCHLOG
        # round 14): the victim consumes the suite's warm cache
        # READ-ONLY (a kill can then never leave a truncated entry),
        # and the RESUMED process runs with NO persistent cache at all
        # — with one, this box's jax build intermittently corrupts the
        # resumed process's native heap (XLA CHECK aborts, glibc
        # aborts, or silently garbage table rows in its final
        # checkpoint — ~1 in 3 runs). The contract under test is the
        # CHECKPOINT's, not the compile cache's.
        victim = harness.spawn_worker(
            0, 1, fixture_path, wdir, server.address,
            checkpoint_period="300ms", throttle_ms=150,
            coordinator="redis", compile_cache_readonly=True)
        cache = RedisCache(server.address)
        npz = os.path.join(wdir, "agg.npz")
        kill_cursor = 0
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            state = cache.load_log_state(short_url(url))
            cursor = state.max_entry if state else 0
            if os.path.exists(npz) and 0 < cursor < total:
                kill_cursor = cursor
                break
            assert victim.poll() is None, (
                "worker finished before a mid-ingest checkpoint:\n"
                + victim.communicate()[0][-4000:])
            time.sleep(0.05)
        assert 0 < kill_cursor < total, "no mid-ingest checkpoint seen"
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=30)
        victim.stdout.close()

        # The durable contract at the moment of death: the atomically-
        # written checkpoint must be a VALID readable aggregate (the
        # temp-file + rename discipline means a kill can never leave a
        # torn snapshot behind).
        victim_snap = harness.merged_snapshot([npz])
        assert 0 < victim_snap["total"] <= total

        # Restart the same worker id — IN-PROCESS (this interpreter is
        # the restarted worker; kernels are warm, and no fresh
        # cache-consuming child exists for the environment bug noted
        # above to bite): it must resume from the durable checkpoint
        # cursor (the post-checkpoint tail re-folds idempotently
        # through the dedup table) and run to completion. The
        # full-subprocess restart stays drivable via tools/fleet.py.
        from ct_mapreduce_tpu.cmd import ct_fetch
        from ct_mapreduce_tpu.ingest import ctclient

        resume_state = cache.load_log_state(short_url(url))
        resume_cursor = resume_state.max_entry if resume_state else 0
        transport = harness.FixtureTransport(fixture)
        orig_transport = ctclient._urllib_transport
        ctclient._urllib_transport = transport
        try:
            ini = os.path.join(wdir, "resume.ini")
            harness.write_worker_ini(
                ini, fixture, npz, redis_addr=server.address,
                checkpoint_period="300ms", coordinator="redis")
            rc = ct_fetch.main(["-config", ini, "-nobars"])
        finally:
            ctclient._urllib_transport = orig_transport
        cache.close()
    finally:
        server.stop()
    assert rc == 0
    # The span evidence: the restarted worker's durable cursor equals
    # the checkpoint position (>= where the kill was observed, > 0),
    # and its FIRST get-entries fetch started there — no replay from
    # entry 0.
    assert resume_cursor >= kill_cursor > 0, (resume_cursor, kill_cursor)
    assert transport.entry_requests, "restart fetched nothing"
    assert min(transport.entry_requests) == resume_cursor

    merged = harness.merged_snapshot([npz])
    ref = harness.run_serial_reference(fixture, str(tmp_path))
    assert merged == ref
    assert merged["total"] > 0


@pytest.mark.parametrize("scenario,kill_env", [
    # Die right after the delta segment's rename, BEFORE the manifest
    # update: the durable chain is still the pre-tick one; the stray
    # renamed segment is unlisted and must be ignored (and later
    # overwritten) by the resumed worker.
    ("mid-segment", {"CTMR_CKPT_KILL": "seg-post-rename"}),
    # Die inside a COMPACTION, after the fresh anchor base's rename
    # but before its fresh manifest: the old manifest's baseSha256 no
    # longer matches the on-disk base, so the loader must heal to
    # base-alone (the anchor IS the full state at its cut).
    ("mid-compaction", {"CTMR_CKPT_KILL": "base-post-rename:2",
                        "CTMR_CKPT_MAX_CHAIN": "1"}),
])
@pytest.mark.timeout(340)
def test_fleet_kill_points_ck02(tmp_path, compile_cache, scenario,
                                kill_env):
    """ISSUE 18 acceptance: a worker SIGKILLed at the exact CTMRCK02
    write boundaries (mid-delta-segment, mid-compaction) leaves a
    chain that VALIDATES and restores to the last durable tick, and a
    restarted worker resumes through it to the uninterrupted run's
    aggregate. The self-kill rides ckpt.kill_point (CTMR_CKPT_KILL),
    so death lands deterministically at the boundary under test —
    victim cache policy as in the round-14 test (read-only consume)."""
    from tools import fleet as harness

    from ct_mapreduce_tpu.agg import ckpt
    from ct_mapreduce_tpu.ingest import ctclient
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    fixture_path = str(tmp_path / "fixture.json")
    fixture = harness.build_fixture(
        fixture_path, n_logs=1, entries_per_log=192, dupes=16,
        max_batch=32)
    url = next(iter(fixture["logs"]))
    wdir = str(tmp_path / "w0")
    npz = os.path.join(wdir, "agg.npz")

    server = MiniRedis().start()
    try:
        # spawn_worker forwards os.environ, so the kill spec (and for
        # the compaction case a maxChain=1 override that forces an
        # anchor on the 2nd tick) reaches only the victim child.
        for k, v in kill_env.items():
            os.environ[k] = v
        try:
            victim = harness.spawn_worker(
                0, 1, fixture_path, wdir, server.address,
                checkpoint_period="300ms", throttle_ms=150,
                coordinator="redis", compile_cache_readonly=True)
            out = victim.communicate(timeout=300)[0]
        finally:
            for k in kill_env:
                os.environ.pop(k, None)
        assert victim.returncode == -signal.SIGKILL, (
            f"{scenario}: victim did not die at the kill point "
            f"(rc={victim.returncode}):\n{out[-4000:]}")

        # Durable contract at the moment of death: the on-disk chain
        # validates and loads — death between the segment/base rename
        # and the manifest update never publishes a torn state.
        chain = ckpt.resolve_chain(npz)
        assert len(chain.segments) == 0, scenario
        if scenario == "mid-segment":
            # The renamed-but-unlisted segment really is on disk.
            assert os.path.exists(ckpt.segment_path(npz, 1)), scenario
        victim_snap = harness.merged_snapshot([npz])
        assert victim_snap["total"] > 0

        # Resume in-process (round-14 discipline) with the kill spec
        # cleared: the worker must extend/anchor past the stray
        # artifacts and finish with the uninterrupted run's aggregate.
        from ct_mapreduce_tpu.cmd import ct_fetch

        transport = harness.FixtureTransport(fixture)
        orig_transport = ctclient._urllib_transport
        ctclient._urllib_transport = transport
        try:
            ini = os.path.join(wdir, "resume.ini")
            harness.write_worker_ini(
                ini, fixture, npz, redis_addr=server.address,
                checkpoint_period="300ms", coordinator="redis")
            rc = ct_fetch.main(["-config", ini, "-nobars"])
        finally:
            ctclient._urllib_transport = orig_transport
    finally:
        server.stop()
    assert rc == 0
    merged = harness.merged_snapshot([npz])
    ref = harness.run_serial_reference(fixture, str(tmp_path))
    assert merged == ref
    assert merged["total"] > 0


# -- global-mesh collectives (capability-gated) -------------------------


@pytest.mark.timeout(360)
def test_two_process_global_mesh(tmp_path):
    repo = Path(__file__).resolve().parent.parent
    child = tmp_path / "mp_child.py"
    child.write_text(_CHILD)
    port = _free_port()
    import os

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the child sets its own
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = str(repo)
    env["CT_GRAFT_ENTRY"] = str(repo / "__graft_entry__.py")
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), str(port), str(i), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process run timed out")
        outs.append(out)
    # Backend capability gate: some jax/XLA CPU builds (including the
    # one in the CI container) cannot run cross-process collectives at
    # all — every child dies inside its first mesh-global op with
    # "Multiprocess computations aren't implemented on the CPU
    # backend". That is an environment limit, not a regression in the
    # distributed path (the same test passes where the capability
    # exists), so skip with the reason instead of carrying a known-red
    # tier-1 entry. Any OTHER failure still fails loudly.
    cap_msgs = (
        "Multiprocess computations aren't implemented",
        "multiprocess computations aren't implemented",
    )
    if any(p.returncode != 0 for p in procs) and any(
            m in out for out in outs for m in cap_msgs):
        pytest.skip(
            "CPU backend in this jax build cannot run multiprocess "
            "collectives (XLA: \"Multiprocess computations aren't "
            "implemented on the CPU backend\")")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{i} failed:\n{out}"
    assert "proc0: leader" in outs[0]
    assert "proc1: follower" in outs[1]
    for i in range(2):
        assert f"proc{i}: barrier released" in outs[i]
        assert f"proc{i}: sharded step OK" in outs[i]
