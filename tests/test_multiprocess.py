"""True multi-process scale-out: two OS processes, one global mesh.

Exercises the explicit-arguments path of ``initialize_multihost``
(``jax.distributed.initialize(coordinator_address, num_processes,
process_id)``) beyond a single process — the TPU-native analog of the
reference's Redis coordinator contract
(/root/reference/coordinator/coordinator.go:44-138): host-0 leadership,
start-barrier release, and one mesh-global ShardedDedup step whose
row-sharded table spans both processes' devices.

Runs on the CPU backend with 2 virtual devices per process (global
mesh of 4); both processes feed identical batches (single-controller-
per-process SPMD) and verify the psum'd issuer counts and the global
dedup count from their own side.
"""

import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

_CHILD = textwrap.dedent("""
    import os, sys

    port, pid, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ.pop("CT_TPU_TESTS", None)

    from ct_mapreduce_tpu.parallel.distributed import (
        DistributedCoordinator,
        initialize_multihost,
        is_leader,
    )

    initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )

    import jax
    import numpy as np

    assert jax.process_index() == pid, (jax.process_index(), pid)
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == 2 * nprocs  # global device view
    assert is_leader() == (pid == 0)

    coord = DistributedCoordinator("mp-test")
    if coord.await_leader():
        print(f"proc{pid}: leader", flush=True)
        coord.send_start()
    else:
        print(f"proc{pid}: follower", flush=True)
        coord.await_start(timeout_s=120)
    print(f"proc{pid}: barrier released", flush=True)

    # One global-mesh sharded dedup step: the table's rows are sharded
    # over all 4 devices across BOTH processes; key routing rides
    # all_to_all, per-issuer counts come back psum'd (replicated, so
    # every process can read them).
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg import sharded

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.environ["CT_GRAFT_ENTRY"])
    ge = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ge)

    mesh = Mesh(np.asarray(jax.devices()), (sharded.AXIS,))
    n = mesh.devices.size
    batch = 16 * n
    data, length, issuer_idx, valid, _ = ge._packed_batch(
        batch, 1024, n_issuers=2)
    # Each process generated its own signing keys — broadcast proc 0's
    # batch so every controller feeds identical global values (the
    # same-value contract of multi-process device_put), riding the
    # distributed runtime's own collective.
    from jax.experimental import multihost_utils

    data, length, issuer_idx, valid = (
        np.asarray(multihost_utils.broadcast_one_to_all(x))
        for x in (data, length, issuer_idx, valid)
    )

    dedup = sharded.ShardedDedup(mesh, capacity=1024 * n)
    out = dedup.step(data, length, issuer_idx, valid,
                     now_hour=ge._NOW_HOUR)
    counts = np.asarray(out.issuer_unknown_counts)  # replicated → readable
    total = dedup.total_count()
    host_lane_ct = int(np.asarray(
        jax.jit(lambda x: x.sum())(out.host_lane)))
    assert total + host_lane_ct == batch, (total, host_lane_ct, batch)
    assert int(counts.sum()) == total, (int(counts.sum()), total)

    out2 = dedup.step(data, length, issuer_idx, valid,
                      now_hour=ge._NOW_HOUR)
    assert dedup.total_count() == total  # replay inserted nothing
    print(f"proc{pid}: sharded step OK total={total}", flush=True)

    # Auto-growth must be forced OFF under multi-host: its trigger is
    # per-process and would fire out of lockstep (collective deadlock).
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    agg = ShardedAggregator(mesh, capacity=1024 * n, batch_size=batch,
                            grow_at=0.7)
    assert agg.grow_at == 0, agg.grow_at
    print(f"proc{pid}: multi-host growth guard OK", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.timeout(360)
def test_two_process_global_mesh(tmp_path):
    repo = Path(__file__).resolve().parent.parent
    child = tmp_path / "mp_child.py"
    child.write_text(_CHILD)
    port = _free_port()
    import os

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the child sets its own
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = str(repo)
    env["CT_GRAFT_ENTRY"] = str(repo / "__graft_entry__.py")
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), str(port), str(i), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process run timed out")
        outs.append(out)
    # Backend capability gate: some jax/XLA CPU builds (including the
    # one in the CI container) cannot run cross-process collectives at
    # all — every child dies inside its first mesh-global op with
    # "Multiprocess computations aren't implemented on the CPU
    # backend". That is an environment limit, not a regression in the
    # distributed path (the same test passes where the capability
    # exists), so skip with the reason instead of carrying a known-red
    # tier-1 entry. Any OTHER failure still fails loudly.
    cap_msgs = (
        "Multiprocess computations aren't implemented",
        "multiprocess computations aren't implemented",
    )
    if any(p.returncode != 0 for p in procs) and any(
            m in out for out in outs for m in cap_msgs):
        pytest.skip(
            "CPU backend in this jax build cannot run multiprocess "
            "collectives (XLA: \"Multiprocess computations aren't "
            "implemented on the CPU backend\")")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{i} failed:\n{out}"
    assert "proc0: leader" in outs[0]
    assert "proc1: follower" in outs[1]
    for i in range(2):
        assert f"proc{i}: barrier released" in outs[i]
        assert f"proc{i}: sharded step OK" in outs[i]
