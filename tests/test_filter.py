"""Filter emission (round 15): the crlite-style cascade artifact
compiled from the aggregation state.

Pins the acceptance contract of ISSUE 10:
- zero false negatives BY CONSTRUCTION over the full included set,
  fuzzed across bucket/open/sharded layouts and through table growth;
- artifact determinism (same state → identical bytes; ingest order
  and worker-local registry numbering cancel out), including the
  merged-fleet == serial-run byte identity;
- checkpoint interplay (emitFilter off leaves the .npz byte-identical
  and pre-round-15 snapshots load cleanly);
- the serve plane's filter-first → table-confirm tier staying
  parity-exact with the table-backed oracle under concurrent ingest,
  plus the /filter artifact-download routes and the ct-filter CLI.
"""

import io
import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.agg.aggregator import (  # noqa: E402
    HostSnapshotAggregator,
    TpuAggregator,
)
from ct_mapreduce_tpu.core.types import ExpDate  # noqa: E402
from ct_mapreduce_tpu.filter import (  # noqa: E402
    FilterArtifact,
    FilterCascade,
    build_artifact,
    build_from_aggregator,
    canonical_keys,
    read_artifact,
    resolve_filter,
)
from ct_mapreduce_tpu.filter import cascade as cascade_mod  # noqa: E402
from ct_mapreduce_tpu.utils import minicert  # noqa: E402

ISSUER_DER = minicert.make_cert(serial=1, issuer_cn="Filter CA",
                                is_ca=True)
ISSUER_DER_B = minicert.make_cert(serial=2, issuer_cn="Filter CA B",
                                  is_ca=True)


def corpus(n=180, dupes=30, issuer_cn="Filter CA", issuer=ISSUER_DER,
           base=1000):
    entries = [
        (minicert.make_cert(serial=base + s, issuer_cn=issuer_cn,
                            subject_cn=f"f{s}.example"), issuer)
        for s in range(n)
    ]
    return entries + entries[:dupes]


def capture_identity_items(agg):
    """[(issuerID, expHour, serial)] for every captured serial."""
    items = []
    for (idx, eh), serials in agg.filter_capture.items():
        iss = agg.registry.issuer_at(idx).id()
        for sb in sorted(serials):
            items.append((iss, eh, sb))
    return items


# -- cascade primitive ----------------------------------------------------


def test_cascade_exact_over_disjoint_sets():
    rng = np.random.default_rng(2026)
    inc = rng.integers(0, 2**32, size=(400, 4), dtype=np.uint32)
    exc = rng.integers(0, 2**32, size=(3000, 4), dtype=np.uint32)
    c = FilterCascade.build(inc, exc, 0.01)
    assert c.contains(inc).all()
    assert not c.contains(exc).any()
    assert len(c.layers) >= 1
    assert c.bits_per_entry() < 64  # compact vs 128-bit fingerprints


def test_cascade_empty_edges():
    empty = np.zeros((0, 4), np.uint32)
    keys = np.arange(40, dtype=np.uint32).reshape(10, 4)
    # No included keys → no layers → everything answers excluded.
    c = FilterCascade.build(empty, keys, 0.01)
    assert not c.layers and not c.contains(keys).any()
    # No excluded universe → a single Bloom layer, still exact on
    # the included side.
    c = FilterCascade.build(keys, empty, 0.01)
    assert len(c.layers) == 1 and c.contains(keys).all()


def test_cascade_device_host_bit_parity():
    """The jitted scatter and the NumPy lane must produce bit-equal
    bitmaps — the device/host parity contract of the build."""
    rng = np.random.default_rng(7)
    inc = rng.integers(0, 2**32, size=(257, 4), dtype=np.uint32)
    exc = rng.integers(0, 2**32, size=(999, 4), dtype=np.uint32)
    host = FilterCascade.build(inc, exc, 0.02, use_device=False)
    dev = FilterCascade.build(inc, exc, 0.02, use_device=True)
    assert len(host.layers) == len(dev.layers)
    for a, b in zip(host.layers, dev.layers):
        assert (a.m, a.k) == (b.m, b.k)
        assert np.array_equal(a.words, b.words)


def test_cascade_env_disables_device(monkeypatch):
    monkeypatch.setenv("CTMR_FILTER_DEVICE", "0")
    assert not cascade_mod.device_enabled()
    monkeypatch.delenv("CTMR_FILTER_DEVICE")
    assert cascade_mod.device_enabled()


def test_canonical_keys_oversized_host_lane():
    """Serials past MAX_SERIAL_BYTES hash through the hashlib lane;
    distinct from every conforming key and from each other."""
    big_a, big_b = b"\x41" * 60, b"\x42" * 60
    small = b"\x41" * 8
    keys = canonical_keys(np.array([3, 3, 3]), np.array([500_000] * 3),
                          [big_a, big_b, small])
    assert len({k.tobytes() for k in keys}) == 3
    # Deterministic.
    again = canonical_keys(np.array([3]), np.array([500_000]), [big_a])
    assert np.array_equal(again[0], keys[0])


# -- zero false negatives across layouts and growth -----------------------


@pytest.mark.parametrize("layout,grow", [("bucket", True),
                                         ("open", False)])
def test_zero_false_negatives_across_layouts(monkeypatch, layout, grow):
    """Bucket runs with a tiny initial table + low threshold so growth
    fires mid-corpus and the capture spans a rehash (growth machinery
    is layout-shared, so the open variant skips the rehash and its
    extra per-capacity compiles — tier-1 budget)."""
    monkeypatch.setenv("CTMR_TABLE", layout)
    if grow:
        agg = TpuAggregator(capacity=1 << 8, batch_size=64, grow_at=0.5,
                            max_capacity=1 << 14)
    else:
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=150, dupes=25))
    if grow:
        assert agg.capacity > (1 << 8), "growth never fired"
    snap = agg.drain()
    total_cap = sum(len(v) for v in agg.filter_capture.values())
    assert total_cap == snap.total
    art = build_from_aggregator(agg, fp_rate=0.01)
    for iss, eh, sb in capture_identity_items(agg):
        assert art.query(iss, eh, sb), (iss, eh, sb.hex())


def test_zero_false_negatives_sharded_layout():
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    mesh = Mesh(np.array(jax.devices()), ("shard",))
    agg = ShardedAggregator(mesh, capacity=1 << 13, batch_size=32)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=120, dupes=20))
    snap = agg.drain()
    assert sum(len(v) for v in agg.filter_capture.values()) == snap.total
    art = build_from_aggregator(agg, fp_rate=0.01)
    for iss, eh, sb in capture_identity_items(agg):
        assert art.query(iss, eh, sb), (iss, eh, sb.hex())
    # Cross-group exactness is an fl01 (global-universe) guarantee:
    # every other group's keys sit in this group's excluded set, so a
    # known serial answers False for a neighbouring expiry bucket with
    # certainty (fl02 answers False only at 1 - fpRate).
    art01 = build_from_aggregator(agg, fp_rate=0.01, fmt="fl01")
    iss, eh, sb = capture_identity_items(agg)[0]
    assert not art01.query(iss, eh + 24, sb)


def test_oversized_serial_rides_capture_and_artifact():
    """Host-lane-only identities (oversized serials the device never
    sees) flow through capture → artifact → exact answers."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=40, dupes=0))
    big = b"\x9a" * 60
    idx, eh = next(iter(agg.filter_capture))
    # The host lane's insert path is _host_dedup; drive it directly
    # with a parsed-fields stand-in (minicert serials cap at 20 bytes,
    # so a real >46-byte cert cannot be minted here).
    class F:
        serial = big
        issuer_dn = "CN=Filter CA"
        crl_distribution_points = []

    agg.host_serials.setdefault((idx, eh), set())
    agg._host_dedup(F(), idx, eh)
    art = build_from_aggregator(agg, fp_rate=0.01)
    assert art.query(agg.registry.issuer_at(idx).id(), eh, big)


# -- determinism ----------------------------------------------------------


def test_artifact_deterministic_across_ingest_order():
    ents = corpus(n=90, dupes=0)
    rev = list(reversed(ents))
    blobs = []
    for order in (ents, rev):
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
        agg.enable_filter_capture()
        agg.ingest(order)
        blobs.append(build_from_aggregator(agg, fp_rate=0.01).to_bytes())
    assert blobs[0] == blobs[1]


def test_merged_fleet_filter_matches_serial_run(tmp_path):
    """The headline determinism gate: two 'workers' over disjoint
    halves, checkpointed and merged (agg/merge.py), must compile to
    the same bytes as one serial run over everything — worker-local
    issuer indices must cancel out of the canonical keys."""
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.filter import build_from_merged

    half_a = corpus(n=60, dupes=10, issuer_cn="Filter CA",
                    issuer=ISSUER_DER, base=1000)
    half_b = corpus(n=60, dupes=10, issuer_cn="Filter CA B",
                    issuer=ISSUER_DER_B, base=500_000)
    paths = []
    # Worker 0 sees B-then-A issuer ordering relative to the serial
    # run, so registry indices genuinely differ.
    for w, ents in enumerate((half_b, half_a)):
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
        agg.enable_filter_capture()
        agg.ingest(ents)
        p = str(tmp_path / f"agg.w{w}.npz")
        agg.save_checkpoint(p)
        paths.append(p)
    serial = TpuAggregator(capacity=1 << 10, batch_size=64)
    serial.enable_filter_capture()
    serial.ingest(half_a + half_b)
    sp = str(tmp_path / "agg.serial.npz")
    serial.save_checkpoint(sp)

    merged_blob = build_from_merged(
        merge.load_checkpoints(paths), fp_rate=0.01).to_bytes()
    serial_blob = build_from_merged(
        merge.load_checkpoints([sp]), fp_rate=0.01).to_bytes()
    assert merged_blob == serial_blob
    # And the in-memory serial build agrees with its checkpointed form.
    assert build_from_aggregator(serial, fp_rate=0.01).to_bytes() \
        == serial_blob


def test_merged_refuses_captureless_checkpoint(tmp_path):
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.filter import build_from_merged

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.ingest(corpus(n=20, dupes=0))  # capture OFF
    p = str(tmp_path / "nocap.npz")
    agg.save_checkpoint(p)
    merged = merge.load_checkpoints([p])
    assert merged.capture_missing == [p]
    with pytest.raises(ValueError, match="emitFilter"):
        build_from_merged(merged, fp_rate=0.01)
    art = build_from_merged(merged, fp_rate=0.01, allow_partial=True)
    assert art.n_serials == 0  # honest: nothing recoverable


# -- checkpoint interplay -------------------------------------------------


def test_checkpoint_unperturbed_when_filter_off(tmp_path):
    """emitFilter off: the .npz carries no filter keys and repeated
    saves of the same state are byte-identical (round-15 code must be
    invisible to pre-round-15 consumers)."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.ingest(corpus(n=30, dupes=5))
    p1, p2 = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    agg.save_checkpoint(p1)
    agg.save_checkpoint(p2)
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2
    z = np.load(p1, allow_pickle=True)
    assert "filter_keys" not in z.files and "filter_vals" not in z.files


def test_pre_round15_checkpoint_loads_cleanly(tmp_path):
    """A snapshot without filter keys (any pre-round-15 writer, or an
    emitFilter-off run) restores with capture off; enabling capture
    afterwards re-seeds from the restored host sets."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.ingest(corpus(n=25, dupes=0))
    p = str(tmp_path / "legacy.npz")
    agg.save_checkpoint(p)
    fresh = HostSnapshotAggregator(capacity=1 << 10)
    fresh.load_checkpoint(p)
    assert fresh.filter_capture is None
    assert fresh.drain().total == agg.drain().total


def test_capture_survives_checkpoint_roundtrip(tmp_path):
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=40, dupes=8))
    p = str(tmp_path / "cap.npz")
    agg.save_checkpoint(p)
    back = HostSnapshotAggregator(capacity=1 << 10)
    back.load_checkpoint(p)
    assert back.filter_capture == agg.filter_capture
    # want_serials re-arms so a resumed ingest keeps capturing.
    assert back.want_serials


def test_emission_writes_artifact_next_to_snapshot(tmp_path):
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.configure_filter_emission(str(tmp_path / "agg.filter"),
                                  fp_rate=0.02)
    agg.ingest(corpus(n=30, dupes=0))
    agg.save_checkpoint(str(tmp_path / "agg.npz"))
    art = read_artifact(str(tmp_path / "agg.filter"))
    assert art.fp_rate == 0.02
    assert art.n_serials == agg.drain().total


# -- config surface -------------------------------------------------------


def test_resolve_filter_layering(monkeypatch):
    monkeypatch.delenv("CTMR_EMIT_FILTER", raising=False)
    monkeypatch.delenv("CTMR_FILTER_PATH", raising=False)
    monkeypatch.delenv("CTMR_FILTER_FP_RATE", raising=False)
    r = resolve_filter(state_path="/x/agg.npz")
    assert (r.emit, r.path, r.fp_rate) == \
        (False, "/x/agg.npz.filter", 0.01)
    monkeypatch.setenv("CTMR_EMIT_FILTER", "1")
    monkeypatch.setenv("CTMR_FILTER_FP_RATE", "0.05")
    r = resolve_filter(state_path="/x/agg.npz")
    assert (r.emit, r.fp_rate) == (True, 0.05)
    # Explicit values beat env.
    r = resolve_filter(emit=False, path="/y/f.bin", fp_rate=0.2)
    assert (r.emit, r.path, r.fp_rate) == (False, "/y/f.bin", 0.2)
    # Unparseable env rate falls back to the default.
    monkeypatch.setenv("CTMR_FILTER_FP_RATE", "nope")
    assert resolve_filter().fp_rate == 0.01


def test_config_directives(tmp_path):
    from ct_mapreduce_tpu.config import CTConfig

    ini = tmp_path / "f.ini"
    ini.write_text("emitFilter = true\nfilterPath = /tmp/f.bin\n"
                   "filterFpRate = 0.001\n")
    cfg = CTConfig.load(["-config", str(ini)], env={})
    assert cfg.emit_filter and cfg.filter_path == "/tmp/f.bin"
    assert cfg.filter_fp_rate == 0.001
    assert "emitFilter" in cfg.usage() and "filterFpRate" in cfg.usage()


# -- serve integration ----------------------------------------------------


def test_filter_first_parity_under_concurrent_ingest():
    """The two-tier lookup answers exactly what the table-backed
    oracle answers while ingest keeps mutating the table: cascade
    false positives die at the table-confirm tier, cascade negatives
    are exact for the build-time corpus."""
    from ct_mapreduce_tpu.serve.server import MembershipOracle

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=120, dupes=20))
    items_known = [(idx, eh, sb)
                   for (idx, eh), serials in agg.filter_capture.items()
                   for sb in sorted(serials)[:40]]
    idx0, eh0 = next(iter(agg.filter_capture))
    items_unknown = [(idx0, eh0, bytes([200 + (j % 50), j % 251, 7]))
                     for j in range(60)]
    items_other = [(idx0 + 999, eh0, b"\x01\x02"),  # unseen issuer
                   (-1, eh0, b"\x01\x02"),
                   (idx0, eh0 + 999, b"\x01\x02")]
    items = items_known + items_unknown + items_other

    tiered = MembershipOracle(agg, filter_first=True, max_delay_s=0.001)
    plain = MembershipOracle(agg, filter_first=False, max_delay_s=0.001)
    assert tiered.filter_tier is not None
    stop = threading.Event()

    def churn():
        s = 0
        while not stop.is_set():
            agg.ingest(corpus(n=10, dupes=0, base=700_000 + s))
            s += 10

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for _ in range(6):
            a = [r[0] for r in tiered.query_raw(items)]
            b = [r[0] for r in plain.query_raw(items)]
            assert a == b
    finally:
        stop.set()
        t.join(timeout=5)
        tiered.close()
        plain.close()
    # The tier actually answered negatives without the table.
    from ct_mapreduce_tpu.telemetry.metrics import get_sink

    counters = get_sink().snapshot()["counters"]
    assert counters.get("serve.filter_negative", 0) > 0
    assert counters.get("serve.filter_forward", 0) > 0


def test_filter_routes_serve_artifact():
    import urllib.error
    import urllib.request

    from ct_mapreduce_tpu.serve.server import QueryServer

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=50, dupes=0))
    (idx, eh), serials = next(iter(agg.filter_capture.items()))
    iss = agg.registry.issuer_at(idx).id()
    exp_id = ExpDate.from_unix_hour(eh).id()
    sb = next(iter(serials))
    srv = QueryServer(agg, 0, filter_first=True).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        full = FilterArtifact.from_bytes(
            urllib.request.urlopen(f"{base}/filter").read())
        assert full.query(iss, eh, sb)
        part = FilterArtifact.from_bytes(
            urllib.request.urlopen(f"{base}/filter/{iss}/{exp_id}").read())
        assert part.query(iss, eh, sb)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/filter/unknown/2031-01-01")
        assert err.value.code == 404
        stats = srv.oracle.stats()
        assert stats["filter_first"] and stats["filter_serials"] > 0
    finally:
        srv.stop()


def test_filter_route_cold_tier_404():
    import urllib.error
    import urllib.request

    from ct_mapreduce_tpu.serve.server import QueryServer

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    srv = QueryServer(agg, 0).start()  # filter_first off → cold tier
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/filter")
        assert err.value.code == 404
    finally:
        srv.stop()


# -- CLI ------------------------------------------------------------------


def test_ct_filter_cli_build_inspect_query(tmp_path):
    from ct_mapreduce_tpu.cmd import ct_filter

    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    agg.enable_filter_capture()
    agg.ingest(corpus(n=40, dupes=5))
    state = str(tmp_path / "agg.npz")
    agg.save_checkpoint(state)
    out_path = str(tmp_path / "run.filter")

    buf = io.StringIO()
    rc = ct_filter.main(["build", "-state", state, "-out", out_path],
                        out=buf)
    assert rc == 0
    built = json.loads(buf.getvalue())
    assert built["serials"] == agg.drain().total
    assert os.path.exists(out_path)

    buf = io.StringIO()
    assert ct_filter.main(
        ["inspect", "-artifact", out_path, "-json"], out=buf) == 0
    assert json.loads(buf.getvalue())["serials"] == built["serials"]

    (idx, eh), serials = next(iter(agg.filter_capture.items()))
    iss = agg.registry.issuer_at(idx).id()
    exp_id = ExpDate.from_unix_hour(eh).id()
    known = next(iter(serials)).hex()
    buf = io.StringIO()
    assert ct_filter.main(
        ["query", "-artifact", out_path, "-issuer", iss,
         "-expDate", exp_id, "-serial", known], out=buf) == 0
    assert ct_filter.main(
        ["query", "-artifact", out_path, "-issuer", iss,
         "-expDate", exp_id, "-serial", "deadbeefcafe" * 4],
        out=io.StringIO()) in (0, 1)  # FP possible, never an error
    assert ct_filter.main(
        ["query", "-artifact", out_path, "-issuer", "nobody",
         "-expDate", exp_id, "-serial", known],
        out=io.StringIO()) == 1
    # Captureless checkpoints are refused without -allowPartial.
    nocap = TpuAggregator(capacity=1 << 10, batch_size=64)
    nocap.ingest(corpus(n=10, dupes=0))
    ns = str(tmp_path / "nocap.npz")
    nocap.save_checkpoint(ns)
    assert ct_filter.main(
        ["build", "-state", ns, "-out", str(tmp_path / "x.filter")],
        out=io.StringIO()) == 2
