"""CI coverage for bench.py's e2e replay leg.

The e2e leg is the only place the three implementations — device
pipeline, byte-exact host lane, and the rediscache path over a real
TCP socket — are parity-checked against each other on one stream
(BASELINE config #4's gate). Locking it into the suite means a parity
regression fails CI, not just a hardware bench run."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(600)
def test_bench_e2e_three_way_parity(monkeypatch):
    monkeypatch.setenv("CT_BENCH_E2E_BATCH", "256")
    monkeypatch.setenv("CT_BENCH_E2E_BATCHES", "2")
    # Same ambient-sitecustomize workaround as bench.main(): keep this
    # smoke test off the real TPU even outside pytest/conftest.
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    import bench

    out = bench.run_e2e()
    assert out["e2e_entries"] == 512
    assert out["e2e_entries_per_sec"] > 0
