"""Real-hardware tier (CT_TPU_TESTS=1): the fused step on the chip.

The reference gates its integration tier on a reachable Redis
(rediscache_test.go:16-28); the analog here is a reachable TPU. Keep
this tier tiny — one compile, a few seconds of chip time — it exists
to prove the shipping step (device build -> parse -> filter ->
fingerprint -> dedup insert -> counts) runs end to end on real
hardware with exact results, not to benchmark it (bench.py does that).
"""

import numpy as np
import pytest

from tests.conftest import on_tpu, requires_tpu


@requires_tpu
@pytest.mark.timeout(300)
def test_fused_step_on_hardware():
    import jax
    import jax.numpy as jnp

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    assert on_tpu(), "CT_TPU_TESTS=1 requires a TPU backend"
    batch, pad_len = 4096, 1024
    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    issuer_idx = jnp.zeros((batch,), jnp.int32)
    valid = jnp.ones((batch,), bool)

    step = jax.jit(pipeline.ingest_core, donate_argnums=(0,),
                   static_argnames=("num_issuers", "max_probes"))
    table = hashtable.make_table(1 << 14)
    table, out = step(
        table, datas[0], lens[0], issuer_idx, valid,
        jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0, 2), jnp.int32),
    )
    wu = np.asarray(out.was_unknown)
    assert wu.sum() == batch  # every lane unique → all fresh inserts
    assert not np.asarray(out.host_lane).any()
    assert int(np.asarray(table.count)) == batch

    # Replay: nothing is fresh the second time (Redis SADD semantics).
    table, out2 = step(
        table, datas[0], lens[0], issuer_idx, valid,
        jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0, 2), jnp.int32),
    )
    assert int(np.asarray(out2.was_unknown).sum()) == 0
    assert int(np.asarray(table.count)) == batch


@requires_tpu
@pytest.mark.timeout(300)
def test_pallas_vs_xla_sha256_equality_on_device():
    """The Pallas fingerprint kernel and the XLA scan must agree
    bit-for-bit ON THE CHIP (CI covers interpret mode only), and both
    must match hashlib ground truth."""
    import hashlib

    import jax.numpy as jnp

    from ct_mapreduce_tpu.ops import pallas_sha256, sha256

    assert on_tpu()
    rng = np.random.default_rng(42)
    msgs = [rng.bytes(int(n)) for n in rng.integers(1, 56, size=512)]
    blocks = np.stack([sha256.pad_message_np(m, 1)[0] for m in msgs])

    xla = np.asarray(sha256.sha256_single_block(jnp.asarray(blocks)))
    pal = np.asarray(
        pallas_sha256.sha256_single_block_pallas(jnp.asarray(blocks))
    )
    np.testing.assert_array_equal(pal, xla)
    for i in (0, 1, 255, 511):
        want = hashlib.sha256(msgs[i]).digest()
        got = b"".join(int(w).to_bytes(4, "big") for w in xla[i])
        assert got == want


@requires_tpu
@pytest.mark.timeout(480)
def test_fused_step_parity_at_production_width():
    """One step at the production batch width (131,072 lanes — the
    width behind the recorded 1.31M entries/s): exact all-fresh parity,
    nothing spilled."""
    import jax
    import jax.numpy as jnp

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    assert on_tpu()
    batch, pad_len = 131_072, 1024
    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    issuer_idx = jnp.zeros((batch,), jnp.int32)
    valid = jnp.ones((batch,), bool)

    step = jax.jit(pipeline.ingest_core, donate_argnums=(0,),
                   static_argnames=("num_issuers", "max_probes"))
    table = hashtable.make_table(1 << 20)
    table, out = step(
        table, datas[0], lens[0], issuer_idx, valid,
        jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0, 2), jnp.int32),
    )
    assert int(np.asarray(out.was_unknown).sum()) == batch
    assert not np.asarray(out.host_lane).any()
    assert int(np.asarray(table.count)) == batch


@requires_tpu
@pytest.mark.timeout(300)
def test_sharded_step_on_chip_mesh():
    """The mesh-sharded path (shard_map + all_to_all + psum) compiles
    and runs on the real backend — a 1-chip mesh here; the 8-way
    virtual mesh runs in CI and the driver's dryrun."""
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg import sharded
    from ct_mapreduce_tpu.utils import syncerts

    assert on_tpu()
    mesh = Mesh(np.asarray(jax.devices()[:1]), (sharded.AXIS,))
    batch, pad_len = 1024, 1024
    tpl = syncerts.make_template()
    data, length = syncerts.stamp_batch_array(tpl, start=0, batch=batch,
                                              pad_len=pad_len)
    dedup = sharded.ShardedDedup(mesh, capacity=1 << 14)
    out = dedup.step(data, length, np.zeros((batch,), np.int32),
                     np.ones((batch,), bool), now_hour=500_000)
    fresh = int(np.asarray(out.was_unknown).sum())
    host = int(np.asarray(out.host_lane).sum())
    assert fresh + host == batch
    assert fresh > 0
    out2 = dedup.step(data, length, np.zeros((batch,), np.int32),
                      np.ones((batch,), bool), now_hour=500_000)
    assert int(np.asarray(out2.was_unknown).sum()) == 0


@requires_tpu
@pytest.mark.timeout(480)
def test_e2e_ingest_leg_small_on_hardware():
    """Wire format → decode → pack → H2D → device step → drain, on the
    chip, at small scale: the production AggregatorSink path with exact
    totals and per-issuer attribution (the shape bench.py's e2e leg
    measures at full size)."""
    import base64

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
    from ct_mapreduce_tpu.utils import syncerts

    assert on_tpu()
    batch = 2048
    tpls = [syncerts.make_template(issuer_cn=f"HW Issuer {k}")
            for k in range(2)]
    eds = [base64.b64encode(
        leaflib.encode_extra_data([t.issuer_der])).decode() for t in tpls]
    lis, ed_col = [], []
    for j in range(batch):
        k = j & 1
        der = syncerts.stamp_serial(tpls[k], j)
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(der, 1_700_000_000_000 + j)).decode())
        ed_col.append(eds[k])

    agg = TpuAggregator(capacity=1 << 14, batch_size=batch)
    sink = AggregatorSink(agg, flush_size=batch, device_queue_depth=1)
    sink.store_raw_batch(RawBatch(lis, ed_col, 0, "hw-log"))
    sink.flush()
    snap = agg.drain()
    assert snap.total == batch
    by_issuer = {}
    for (iss, _exp), c in snap.counts.items():
        by_issuer[iss] = by_issuer.get(iss, 0) + c
    assert sorted(by_issuer.values()) == [batch // 2, batch // 2]
