"""Real-hardware tier (CT_TPU_TESTS=1): the fused step on the chip.

The reference gates its integration tier on a reachable Redis
(rediscache_test.go:16-28); the analog here is a reachable TPU. Keep
this tier tiny — one compile, a few seconds of chip time — it exists
to prove the shipping step (device build -> parse -> filter ->
fingerprint -> dedup insert -> counts) runs end to end on real
hardware with exact results, not to benchmark it (bench.py does that).
"""

import numpy as np
import pytest

from tests.conftest import on_tpu, requires_tpu


@requires_tpu
@pytest.mark.timeout(300)
def test_fused_step_on_hardware():
    import jax
    import jax.numpy as jnp

    from ct_mapreduce_tpu.core import packing
    from ct_mapreduce_tpu.ops import hashtable, pipeline
    from ct_mapreduce_tpu.utils import syncerts

    assert on_tpu(), "CT_TPU_TESTS=1 requires a TPU backend"
    batch, pad_len = 4096, 1024
    tpl = syncerts.make_template()
    datas, lens = syncerts.build_device_batches(tpl, 1, batch, pad_len)
    issuer_idx = jnp.zeros((batch,), jnp.int32)
    valid = jnp.ones((batch,), bool)

    step = jax.jit(pipeline.ingest_core, donate_argnums=(0,),
                   static_argnames=("num_issuers", "max_probes"))
    table = hashtable.make_table(1 << 14)
    table, out = step(
        table, datas[0], lens[0], issuer_idx, valid,
        jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0,), jnp.int32),
    )
    wu = np.asarray(out.was_unknown)
    assert wu.sum() == batch  # every lane unique → all fresh inserts
    assert not np.asarray(out.host_lane).any()
    assert int(np.asarray(table.count)) == batch

    # Replay: nothing is fresh the second time (Redis SADD semantics).
    table, out2 = step(
        table, datas[0], lens[0], issuer_idx, valid,
        jnp.int32(500_000), jnp.int32(packing.DEFAULT_BASE_HOUR),
        jnp.zeros((0, 32), jnp.uint8), jnp.zeros((0,), jnp.int32),
    )
    assert int(np.asarray(out2.was_unknown).sum()) == 0
    assert int(np.asarray(table.count)) == batch
