"""Pallas SHA-256 kernel: bit-equality with the XLA path and hashlib.

Runs in interpret mode on the CPU test platform; the real-TPU tier
(CT_TPU_TESTS=1) compiles the actual Mosaic kernel.
"""

import hashlib
import os

import numpy as np
import pytest

from ct_mapreduce_tpu.ops import pallas_sha256, sha256

from tests.conftest import on_tpu


def _blocks(n: int, seed: int = 7) -> tuple[np.ndarray, list[bytes]]:
    """n random ≤55-byte messages, FIPS-padded into single blocks."""
    rng = np.random.default_rng(seed)
    blocks = np.zeros((n, 16), np.uint32)
    msgs = []
    for i in range(n):
        msg = rng.integers(0, 256, rng.integers(0, 56), dtype=np.uint8).tobytes()
        msgs.append(msg)
        blocks[i] = sha256.pad_message_np(msg, total_blocks=1)[0]
    return blocks, msgs


def test_pallas_matches_xla_and_hashlib():
    interpret = not on_tpu()
    blocks, msgs = _blocks(256)
    got = np.asarray(
        pallas_sha256.sha256_single_block_pallas(blocks, interpret=interpret)
    )
    ref = np.asarray(sha256.sha256_single_block(blocks))
    np.testing.assert_array_equal(got, ref)
    for i, msg in enumerate(msgs):
        assert sha256.digest_np(got[i]) == hashlib.sha256(msg).digest()


def test_pallas_fingerprint_tail_words():
    interpret = not on_tpu()
    blocks, _ = _blocks(128)
    fp = np.asarray(
        pallas_sha256.sha256_fingerprint64_pallas(blocks, interpret=interpret)
    )
    full = np.asarray(sha256.sha256_single_block(blocks))
    np.testing.assert_array_equal(fp, full[:, 4:])


def test_pallas_grid_tiling():
    """Batch larger than one lane tile exercises the grid."""
    interpret = not on_tpu()
    blocks, _ = _blocks(pallas_sha256.LANE_TILE * 2)
    got = np.asarray(
        pallas_sha256.sha256_single_block_pallas(blocks, interpret=interpret)
    )
    ref = np.asarray(sha256.sha256_single_block(blocks))
    np.testing.assert_array_equal(got, ref)


def test_dispatcher_stays_on_xla_off_tpu(monkeypatch):
    monkeypatch.setenv("CTMR_PALLAS", "1")
    blocks, _ = _blocks(64)
    # CPU backend → dispatcher must fall back to the XLA path (no error).
    out = np.asarray(sha256.sha256_fingerprint64(blocks))
    assert out.shape == (64, 4)
