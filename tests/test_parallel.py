"""parallel/ mesh + distributed helpers, ShardedAggregator parity, and
the config→model composition root (models.build_aggregator)."""

import datetime
import os
import threading

import jax
import numpy as np
import pytest

from ct_mapreduce_tpu.config import CTConfig
from ct_mapreduce_tpu.parallel import (
    DistributedCoordinator,
    device_barrier,
    is_leader,
    make_mesh,
    parse_mesh_shape,
)

from tests import certgen

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)
NOW = datetime.datetime(2025, 1, 1, tzinfo=UTC)


# -- mesh spec --------------------------------------------------------------


def test_parse_mesh_shape_default():
    spec = parse_mesh_shape("")
    assert spec.axis_names == ("shard",)
    assert spec.resolve(8) == (8,)


def test_parse_mesh_shape_named():
    spec = parse_mesh_shape("data:4,expert:2")
    assert spec.axis_names == ("data", "expert")
    assert spec.resolve(8) == (4, 2)
    assert spec.resolve(64) == (4, 2)  # extra devices unused


def test_parse_mesh_shape_wildcard():
    spec = parse_mesh_shape("data:2,rest:-1")
    assert spec.resolve(8) == (2, 4)


def test_parse_mesh_shape_errors():
    with pytest.raises(ValueError):
        parse_mesh_shape("data=4")
    with pytest.raises(ValueError):
        parse_mesh_shape("a:2,a:2")
    with pytest.raises(ValueError):
        parse_mesh_shape("a:-1,b:-1").resolve(8)
    with pytest.raises(ValueError):
        parse_mesh_shape("a:16").resolve(8)


def test_make_mesh():
    mesh = make_mesh("")
    assert mesh.devices.size == len(jax.devices())
    mesh2 = make_mesh("data:2,expert:4")
    assert mesh2.devices.shape == (2, 4)
    assert mesh2.axis_names == ("data", "expert")


# -- distributed helpers (single-process semantics) -------------------------


def test_leader_and_barrier():
    assert is_leader()  # process_index 0 in single-process runs
    device_barrier("test")


def test_distributed_coordinator_protocol():
    c = DistributedCoordinator("t")
    with pytest.raises(RuntimeError):
        c.send_start()
    assert c.await_leader() is True
    c.send_start()
    with pytest.raises(RuntimeError):
        c.await_start()  # leaders must not await
    c.close()


# -- ShardedAggregator parity ----------------------------------------------


def _entries(n_issuers=2, per=6, dupes=2):
    out = []
    for i in range(n_issuers):
        cn = f"Shard CA {i}"
        issuer = certgen.make_cert(serial=1, issuer_cn=cn, is_ca=True,
                                   not_after=FUTURE, key_seed=i)
        uniq = per - dupes
        for s in range(per):
            leaf = certgen.make_cert(
                serial=5000 + (s % uniq), issuer_cn=cn,
                subject_cn="s.example.com", is_ca=False,
                not_after=FUTURE, key_seed=i,
                crl_dps=(f"http://crl{i}.example/x.crl",),
            )
            out.append((leaf, issuer))
    return out


def test_sharded_aggregator_matches_single_chip():
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    entries = _entries()
    mesh = make_mesh("")
    sharded = ShardedAggregator(mesh, capacity=1 << 12, batch_size=32, now=NOW)
    single = TpuAggregator(capacity=1 << 12, batch_size=32, now=NOW)

    r_sh = sharded.ingest(entries)
    r_si = single.ingest(entries)
    np.testing.assert_array_equal(r_sh.was_unknown, r_si.was_unknown)
    np.testing.assert_array_equal(r_sh.filtered, r_si.filtered)

    snap_sh, snap_si = sharded.drain(), single.drain()
    assert snap_sh.counts == snap_si.counts
    assert snap_sh.crls == snap_si.crls
    assert snap_sh.dns == snap_si.dns
    assert snap_sh.total == snap_si.total == 8  # 2 issuers × 4 unique


@pytest.mark.slow
def test_sharded_aggregator_checkpoint_roundtrip(tmp_path):
    """@slow since round 17 (tier-1 budget banking, ISSUE 12): the
    sharded save → sharded load → dedup-after-restore contract is a
    strict subset of tier-1
    test_layouts::test_checkpoint_topology_mismatch_rehashes[bucket]
    (sharded → single → sharded legs over the same mesh); this
    same-topology re-run stays in the full suite."""
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    entries = _entries(n_issuers=1)
    mesh = make_mesh("")
    agg = ShardedAggregator(mesh, capacity=1 << 12, batch_size=32, now=NOW)
    agg.ingest(entries)
    before = agg.drain()
    path = str(tmp_path / "sharded.npz")
    agg.save_checkpoint(path)

    agg2 = ShardedAggregator(mesh, capacity=1 << 12, batch_size=32, now=NOW)
    agg2.load_checkpoint(path)
    assert agg2.drain().counts == before.counts
    # Replaying the same entries after restore finds nothing new.
    r = agg2.ingest(entries)
    assert int(np.asarray(r.was_unknown).sum()) == 0


def test_cross_topology_restore_single_to_sharded(tmp_path):
    """A single-chip checkpoint restores onto a mesh by reinsertion —
    home shards and probe sequences are topology-dependent, so raw row
    copies would lose keys."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    entries = _entries(n_issuers=2)
    single = TpuAggregator(capacity=1 << 12, batch_size=32, now=NOW)
    single.ingest(entries)
    before = single.drain()
    path = str(tmp_path / "single.npz")
    single.save_checkpoint(path)

    mesh = make_mesh("")
    sharded = ShardedAggregator(mesh, capacity=1 << 12, batch_size=32, now=NOW)
    sharded.load_checkpoint(path)
    assert sharded.drain().counts == before.counts
    r = sharded.ingest(entries)  # replay: everything already known
    assert int(np.asarray(r.was_unknown).sum()) == 0


def test_pre_save_hook_ordering():
    """The engine's checkpoint hook must run before the durable cursor
    write (aggregate durability precedes cursor advance)."""
    from ct_mapreduce_tpu.ingest.ctclient import CTLogClient
    from ct_mapreduce_tpu.ingest.sync import LogWorker
    from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase
    from ct_mapreduce_tpu.storage.mockbackend import MockBackend
    from ct_mapreduce_tpu.storage.mockcache import MockRemoteCache

    from tests.fakelog import FakeLog

    log = FakeLog()
    issuer = certgen.make_cert(serial=1, issuer_cn="Hook CA", is_ca=True,
                               not_after=FUTURE)
    leaf = certgen.make_cert(serial=2, issuer_cn="Hook CA", is_ca=False,
                             not_after=FUTURE)
    log.add_cert(leaf, issuer)

    calls = []
    db = FilesystemDatabase(MockBackend(), MockRemoteCache())
    orig = db.save_log_state
    db.save_log_state = lambda s: (calls.append("cursor"), orig(s))[1]
    client = CTLogClient(log.url, transport=log.transport)
    w = LogWorker(client, db, pre_save=lambda: calls.append("snapshot"))
    w.position = 1
    w.save_state()
    assert calls == ["snapshot", "cursor"]


# -- composition root -------------------------------------------------------


def test_build_aggregator_selects_path():
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator
    from ct_mapreduce_tpu.models import build_aggregator

    cfg = CTConfig(table_bits=12, batch_size=64)
    agg = build_aggregator(cfg)  # 8 virtual devices → sharded
    assert isinstance(agg, ShardedAggregator)
    assert agg.dedup.n_shards == len(jax.devices())

    cfg1 = CTConfig(table_bits=12, batch_size=64, mesh_shape="shard:1")
    assert isinstance(build_aggregator(cfg1), TpuAggregator)
    assert not isinstance(build_aggregator(cfg1), ShardedAggregator)


def test_build_aggregator_multi_axis_mesh_flattens():
    """The config docs' own example ("data:4,expert:2") must not crash:
    the 1-D dedup flattens multi-axis meshes over the same devices."""
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator
    from ct_mapreduce_tpu.models import build_aggregator

    cfg = CTConfig(table_bits=12, batch_size=60,  # 60 % 8 != 0 → rounds up
                   mesh_shape="data:4,expert:2")
    agg = build_aggregator(cfg)
    assert isinstance(agg, ShardedAggregator)
    assert agg.dedup.n_shards == 8
    assert agg.batch_size % 8 == 0
    # capacity rounded UP, never below the configured size
    assert agg.dedup.capacity >= (1 << 12)


def test_ingest_model_from_config(tmp_path):
    from ct_mapreduce_tpu.models import IngestModel

    state = tmp_path / "m.npz"
    cfg = CTConfig(table_bits=12, batch_size=64, agg_state_path=str(state))
    model = IngestModel.from_config(cfg)
    model.ingest(_entries(n_issuers=1))
    model.save()
    assert state.exists()

    model2 = IngestModel.from_config(cfg)
    assert model2.drain().total == model.drain().total == 4


def test_checkpoint_atomic_and_exact_path(tmp_path):
    """Snapshot writes are temp+rename: a crash mid-write leaves the
    previous good snapshot intact, and the file lands at EXACTLY the
    configured path (numpy's silent '.npz' suffixing would break the
    bare-path resume/report lookups)."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator

    path = str(tmp_path / "agg.state")  # deliberately no .npz suffix
    agg = TpuAggregator(capacity=1 << 12, batch_size=32, now=NOW)
    agg.ingest(_entries(n_issuers=2))
    agg.save_checkpoint(path)
    assert os.path.exists(path)  # exact path, no suffix appended
    good = open(path, "rb").read()

    # Crash mid-write: the inner writer dies after the temp file opens.
    def boom(fh, host_items):
        fh.write(b"partial garbage")
        raise RuntimeError("simulated crash mid-save")

    agg2 = TpuAggregator(capacity=1 << 12, batch_size=32, now=NOW)
    agg2.ingest(_entries(n_issuers=1))
    agg2._write_npz = boom
    with pytest.raises(RuntimeError):
        agg2.save_checkpoint(path)
    assert open(path, "rb").read() == good  # previous snapshot survives
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    restored = TpuAggregator(capacity=1 << 12, batch_size=32, now=NOW)
    restored.load_checkpoint(path)
    assert restored.drain().counts == agg.drain().counts


@pytest.mark.timeout(580)
@pytest.mark.slow
def test_sixteen_device_virtual_mesh():
    """Scale the full multichip dryrun (binding dispatch caps,
    host-lane spills, exact totals, growth guard) to a 16-device
    virtual mesh — twice the width every other test uses. Subprocess:
    the parent's jax is pinned to 8 devices.

    @slow since round 17 (tier-1 budget banking, ISSUE 12): every
    dispatch/spill/growth invariant this checks is tier-1-gated on
    the 8-device mesh (test_sharded.py, test_growth.py, the dryrun in
    test_ingest_model_from_config); this leg re-runs the same code at
    2x width in a ~25 s subprocess and stays in the full suite."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.pop("CT_TPU_TESTS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(repo)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import importlib.util\n"
        f"spec = importlib.util.spec_from_file_location('ge', {str(repo / '__graft_entry__.py')!r})\n"
        "ge = importlib.util.module_from_spec(spec); spec.loader.exec_module(ge)\n"
        "ge.dryrun_multichip(16)\n"
        "print('OK16')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK16" in proc.stdout, (proc.stdout, proc.stderr[-500:])


def test_pre_cursor_save_not_starved_by_other_logs():
    """A periodic cursor save for log A must not wait on log B's
    in-flight entries (the old global entry_queue.join() could be
    starved indefinitely by other downloaders)."""
    from ct_mapreduce_tpu.ingest.sync import LogSyncEngine, _QueueItem

    class _NullSink:
        def store(self, entry, log_url):
            pass

        def flush(self):
            pass

    engine = LogSyncEngine(_NullSink(), database=None, num_threads=1)

    class _E:
        index = 0

    # Log B has an item sitting unprocessed in the shared queue (no
    # store threads running) — under join() semantics this would block.
    item_b = _QueueItem(_E(), "https://b.example.com/log")
    engine.entry_queue.put(item_b)
    engine._account_enqueued(item_b)

    done = threading.Event()
    t = threading.Thread(
        target=lambda: (engine._pre_cursor_save("https://a.example.com/log"),
                        done.set()),
        daemon=True,
    )
    t.start()
    assert done.wait(timeout=5.0), "save for log A starved by log B's backlog"
