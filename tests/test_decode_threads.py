"""Intra-chunk native decode threads: byte-exact across thread counts.

The persistent C++ worker pool (ctmr_native.cpp) splits
``ctmr_decode_entries`` / ``ctmr_extract_sidecars`` / ``ctmr_pack_ders``
over contiguous lane ranges. The determinism contract pinned here: for
ANY thread count, every output of the decode and sidecar passes is
byte-identical to the serial pass — per-lane arrays trivially (disjoint
writes), and the issuer grouping because per-chunk groups merge by DER
bytes in lane order, reproducing the serial first-appearance order.

The corpora deliberately include every status class (OK, BAD_B64,
BAD_LEAF, NO_CHAIN, precerts) and the sidecar fuzz includes
walker-REJECTED and undecidable lanes — the parity claim is about the
whole output surface, not just the happy path.
"""

import base64
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.native import available, leafpack

from tests import certgen
from tests.test_der_kernel import fixture_certs, pack

pytestmark = pytest.mark.skipif(
    not available(), reason="native library unavailable (no C++ compiler)")

DECODE_FIELDS = ("data", "length", "timestamp_ms", "entry_type", "status")


def _wire_corpus():
    """Mixed wire batch: clean x509 + precert entries, plus every
    malformed flavor the decoder classifies (bad base64, truncated
    leaves, chainless entries, garbage extra_data)."""
    from ct_mapreduce_tpu.ingest import leaf as leaflib

    rng = np.random.default_rng(20260804)
    issuer = certgen.make_cert(serial=1, issuer_cn="MT CA", is_ca=True)
    lis, eds = [], []
    for j in range(600):
        leaf = certgen.make_cert(serial=1000 + j, issuer_cn="MT CA")
        li = leaflib.encode_leaf_input(leaf, timestamp_ms=1700000000000 + j)
        ed = leaflib.encode_extra_data([issuer])
        li_b64 = base64.b64encode(li).decode()
        ed_b64 = base64.b64encode(ed).decode()
        kind = j % 6
        if kind == 1:  # bad base64 character
            li_b64 = li_b64[:7] + "!" + li_b64[8:]
        elif kind == 2:  # truncated leaf bytes
            li_b64 = base64.b64encode(li[: int(rng.integers(1, 12))]).decode()
        elif kind == 3:  # no chain
            ed_b64 = ""
        elif kind == 4:  # mutated extra_data bytes
            raw = bytearray(ed)
            raw[int(rng.integers(len(raw)))] ^= int(rng.integers(1, 256))
            ed_b64 = base64.b64encode(bytes(raw)).decode()
        lis.append(li_b64)
        eds.append(ed_b64)
    return lis, eds


def _assert_batches_equal(a, b, ctx):
    for fld in DECODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(a, fld), getattr(b, fld), err_msg=f"{ctx}: {fld}")
    np.testing.assert_array_equal(
        a.issuer_group, b.issuer_group, err_msg=f"{ctx}: issuer_group")
    assert a.group_issuers == b.group_issuers, ctx
    assert a.issuers == b.issuers, ctx


def test_decode_byte_exact_across_thread_counts():
    lis, eds = _wire_corpus()
    base = leafpack.decode_raw_batch(lis, eds, 2048, threads=1)
    # The corpus must actually exercise the status taxonomy.
    assert len(set(base.status.tolist())) >= 4
    for t in (2, 3, 7, 16):
        got = leafpack.decode_raw_batch(lis, eds, 2048, threads=t)
        _assert_batches_equal(base, got, f"threads={t}")


def test_sidecars_byte_exact_across_thread_counts_mutation_fuzz():
    """threads=N sidecar extraction over the SAME mutation-fuzz corpus
    test_preparsed.py pins against the device walker — including the
    walker-rejected (ok=0) and undecidable lanes, whose zeroed fields
    must also stitch back byte-exact."""
    rng = np.random.default_rng(20260804)
    bases = fixture_certs()
    mutants = []
    for _ in range(400):
        b = bytearray(bases[int(rng.integers(len(bases)))])
        for _k in range(int(rng.integers(1, 4))):
            b[int(rng.integers(len(b)))] ^= int(rng.integers(1, 256))
        mutants.append(bytes(b))
    data, length = pack(mutants, pad_to=1024)
    base = leafpack.extract_sidecars(data, length, threads=1)
    rejected = int((base.ok == 0).sum())
    assert rejected > 10, "fuzz corpus must include rejected lanes"
    for t in (2, 5, 13):
        got = leafpack.extract_sidecars(data, length, threads=t)
        for fld in vars(base):
            np.testing.assert_array_equal(
                getattr(base, fld), getattr(got, fld),
                err_msg=f"threads={t}: sidecar {fld}")


def test_pack_ders_byte_exact_across_thread_counts():
    rng = np.random.default_rng(7)
    ders = [bytes(rng.integers(0, 256, int(rng.integers(1, 900)),
                               dtype=np.uint8).tobytes())
            for _ in range(300)]
    ders.append(b"\x00" * 700)  # oversize lane (pad 512): length 0, ok 0
    base = leafpack.pack_ders(ders, 512, threads=1)
    for t in (2, 9):
        got = leafpack.pack_ders(ders, 512, threads=t)
        for i in range(3):
            np.testing.assert_array_equal(base[i], got[i])
        assert base[3] == got[3]
    want = sum(1 for d in ders if len(d) <= 512)
    assert base[3] == want and want < len(ders)  # oversize lanes skipped


def test_resolve_threads_policy(monkeypatch):
    """Explicit > env CTMR_DECODE_THREADS > legacy CTMR_DECODE_WORKERS
    > cpu count; auto keeps >= 2048 lanes per chunk."""
    monkeypatch.delenv("CTMR_DECODE_THREADS", raising=False)
    monkeypatch.delenv("CTMR_DECODE_WORKERS", raising=False)
    assert leafpack.resolve_threads(100, 8) == 8  # explicit wins, any n
    assert leafpack.resolve_threads(3, 8) == 3  # clamped to lanes
    assert leafpack.resolve_threads(1000) == 1  # small batch → serial
    monkeypatch.setenv("CTMR_DECODE_THREADS", "3")
    assert leafpack.resolve_threads(1 << 20) == 3
    monkeypatch.setenv("CTMR_DECODE_THREADS", "0")
    monkeypatch.setenv("CTMR_DECODE_WORKERS", "2")
    assert leafpack.resolve_threads(1 << 20) == 2


def test_legacy_workers_alias_routes_through_pool():
    """decode_raw_batch(workers=N) — the pre-pool knob — must keep
    producing identical results through the native worker pool."""
    lis, eds = _wire_corpus()
    a = leafpack.decode_raw_batch(lis, eds, 2048, workers=1)
    b = leafpack.decode_raw_batch(lis, eds, 2048, workers=4)
    _assert_batches_equal(a, b, "workers=4")
