"""Coordinator tests: election under contention, start barrier, lease
renewal — simulated as many threads sharing one cache, exactly as the
reference simulates multi-node with goroutines sharing one Redis
(/root/reference/coordinator/coordinator_test.go:61-220)."""

import threading
import time
from datetime import timedelta

from ct_mapreduce_tpu.coordinator import Coordinator
from ct_mapreduce_tpu.storage import MockRemoteCache


def _elect(n: int, cache: MockRemoteCache) -> list[Coordinator]:
    coords = [Coordinator(cache, "test") for _ in range(n)]
    results = [None] * n
    threads = []

    def contend(i):
        results[i] = coords[i].await_leader()

    for i in range(n):
        t = threading.Thread(target=contend, args=(i,))
        threads.append(t)
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(1 for r in results if r) == 1, f"expected one leader, got {results}"
    return coords


def test_two_contenders_one_winner():
    _elect(2, MockRemoteCache())


def test_forty_contenders_one_winner():
    # coordinator_test.go:61-104
    _elect(40, MockRemoteCache())


def test_start_barrier_with_followers():
    # coordinator_test.go:137-177: 16 followers unblock on leader start
    cache = MockRemoteCache()
    coords = _elect(16, cache)
    leader = next(c for c in coords if c.is_leader)
    followers = [c for c in coords if not c.is_leader]
    for f in followers:
        f.await_sleep_period_s = 0.01

    released = []
    threads = [
        threading.Thread(target=lambda f=f: (f.await_start(timeout_s=5), released.append(f)))
        for f in followers
    ]
    for t in threads:
        t.start()
    time.sleep(0.05)
    assert not released  # nobody through before start
    leader.send_start()
    for t in threads:
        t.join(timeout=5)
    assert len(released) == len(followers)
    for c in coords:
        c.close()


def test_follower_misuse_raises():
    cache = MockRemoteCache()
    c = Coordinator(cache, "misuse")
    try:
        c.await_start()
        assert False, "should raise before await_leader"
    except RuntimeError:
        pass
    assert c.await_leader() is True
    try:
        c.await_start()
        assert False, "leader must not await_start"
    except RuntimeError:
        pass
    c.close()


def test_lease_renewal_keeps_leadership():
    # coordinator_test.go:179-220, at high speed: initial lease is short,
    # renewal keeps the key alive past it
    cache = MockRemoteCache()
    c = Coordinator(
        cache,
        "renewal",
        key_life_initial=timedelta(milliseconds=80),
        key_life_renewal=timedelta(milliseconds=200),
        renewal_period_s=0.05,
    )
    assert c.await_leader() is True
    time.sleep(0.3)  # well past the initial 80ms lease
    assert cache.exists("leader-renewal"), "renewal thread should keep the lease"
    c.close()


def test_failover_after_lease_expiry():
    # Elastic failover: once the lease lapses with no renewal, a new
    # contender wins (coordinator.go:57,71-81 behavior)
    cache = MockRemoteCache()
    first = Coordinator(
        cache,
        "fo",
        key_life_initial=timedelta(milliseconds=50),
        key_life_renewal=timedelta(milliseconds=50),
        renewal_period_s=999,
    )
    assert first.await_leader() is True
    first.close()
    # Null out the renewal the close() above stopped, let lease lapse
    time.sleep(0.12)
    second = Coordinator(cache, "fo")
    assert second.await_leader() is True
    second.close()
