"""Test-certificate factory.

Mirrors the reference's on-the-fly fixture generation (`makeCert`,
/root/reference/storage/issuermetadata_test.go:62-98): self-signed CA
certs with chosen DN / expiry / serial / CRL distribution points, built
with the `cryptography` package.
"""

from __future__ import annotations

import datetime
from functools import lru_cache

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID


@lru_cache(maxsize=8)
def _key(seed: int = 0):
    # Key generation dominates fixture cost; cache a few keys.
    return ec.generate_private_key(ec.SECP256R1())


def make_cert(
    serial: int | None = None,
    issuer_cn: str = "Test Issuer CA",
    subject_cn: str | None = None,
    org: str = "Unit Test Corp",
    country: str = "US",
    not_before: datetime.datetime | None = None,
    not_after: datetime.datetime | None = None,
    crl_dps: tuple[str, ...] = (),
    is_ca: bool = True,
    add_basic_constraints: bool = True,
    key_seed: int = 0,
    extra_extensions: int = 0,
    extra_ext_size: int = 40,
    extras_first: bool = True,
) -> bytes:
    """Build a self-signed certificate, returning DER bytes."""
    now = datetime.datetime(2024, 1, 1, tzinfo=datetime.timezone.utc)
    not_before = not_before or now
    not_after = not_after or now + datetime.timedelta(days=365)
    key = _key(key_seed)

    name_attrs = [
        x509.NameAttribute(NameOID.COUNTRY_NAME, country),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        x509.NameAttribute(NameOID.COMMON_NAME, issuer_cn),
    ]
    issuer_name = x509.Name(name_attrs)
    subject_name = (
        x509.Name(
            [
                x509.NameAttribute(NameOID.COUNTRY_NAME, country),
                x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
                x509.NameAttribute(NameOID.COMMON_NAME, subject_cn),
            ]
        )
        if subject_cn
        else issuer_name
    )

    builder = (
        x509.CertificateBuilder()
        .subject_name(subject_name)
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(serial if serial is not None else x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
    )
    def _add_extras(b):
        # Unrecognized private-arc extensions (OCTET STRING payloads):
        # pad the extension list to stress budget/refetch paths in the
        # device walker's extension scan.
        for i in range(extra_extensions):
            b = b.add_extension(
                x509.UnrecognizedExtension(
                    x509.ObjectIdentifier(f"1.3.6.1.4.1.99999.{i}"),
                    bytes([i & 0xFF]) * extra_ext_size,
                ),
                critical=False,
            )
        return b

    if extras_first:
        builder = _add_extras(builder)
    if add_basic_constraints:
        builder = builder.add_extension(
            x509.BasicConstraints(ca=is_ca, path_length=None), critical=True
        )
    if not extras_first:
        builder = _add_extras(builder)
    if crl_dps:
        builder = builder.add_extension(
            x509.CRLDistributionPoints(
                [
                    x509.DistributionPoint(
                        full_name=[x509.UniformResourceIdentifier(u)],
                        relative_name=None,
                        reasons=None,
                        crl_issuer=None,
                    )
                    for u in crl_dps
                ]
            ),
            critical=False,
        )
    cert = builder.sign(key, hashes.SHA256())
    return cert.public_bytes(serialization.Encoding.DER)


def spki_of(der: bytes) -> bytes:
    cert = x509.load_der_x509_certificate(der)
    return cert.public_key().public_bytes(
        serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo
    )
