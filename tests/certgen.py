"""Test-certificate factory.

Mirrors the reference's on-the-fly fixture generation (`makeCert`,
/root/reference/storage/issuermetadata_test.go:62-98): self-signed CA
certs with chosen DN / expiry / serial / CRL distribution points, built
with the `cryptography` package.

Hosts without `cryptography` (some CI containers) fall back to
`ct_mapreduce_tpu.utils.minicert`'s hand-assembled canonical DER: same
fields in the same places with a deterministic per-key-seed SPKI, only
the signature bytes are synthetic — the contract of every consumer
here, which parses and never verifies. Tests that need the real
package (signed RSA/PSS fixtures, cryptography-as-ground-truth
comparisons) gate on :data:`requires_cryptography`.
"""

from __future__ import annotations

import datetime
import itertools
from functools import lru_cache

import pytest

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    HAVE_CRYPTOGRAPHY = True
except ImportError:
    HAVE_CRYPTOGRAPHY = False

requires_cryptography = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY, reason="needs the cryptography package"
)

if HAVE_CRYPTOGRAPHY:
    @lru_cache(maxsize=8)
    def _key(seed: int = 0):
        # Key generation dominates fixture cost; cache a few keys.
        return ec.generate_private_key(ec.SECP256R1())


def make_cert(
    serial: int | None = None,
    issuer_cn: str = "Test Issuer CA",
    subject_cn: str | None = None,
    org: str = "Unit Test Corp",
    country: str = "US",
    not_before: datetime.datetime | None = None,
    not_after: datetime.datetime | None = None,
    crl_dps: tuple[str, ...] = (),
    is_ca: bool = True,
    add_basic_constraints: bool = True,
    key_seed: int = 0,
    extra_extensions: int = 0,
    extra_ext_size: int = 40,
    extras_first: bool = True,
) -> bytes:
    """Build a self-signed certificate, returning DER bytes."""
    if not HAVE_CRYPTOGRAPHY:
        return _make_cert_minicert(
            serial=serial, issuer_cn=issuer_cn, subject_cn=subject_cn,
            org=org, country=country, not_before=not_before,
            not_after=not_after, crl_dps=crl_dps, is_ca=is_ca,
            add_basic_constraints=add_basic_constraints, key_seed=key_seed,
            extra_extensions=extra_extensions,
            extra_ext_size=extra_ext_size, extras_first=extras_first)
    now = datetime.datetime(2024, 1, 1, tzinfo=datetime.timezone.utc)
    not_before = not_before or now
    not_after = not_after or now + datetime.timedelta(days=365)
    key = _key(key_seed)

    name_attrs = [
        x509.NameAttribute(NameOID.COUNTRY_NAME, country),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        x509.NameAttribute(NameOID.COMMON_NAME, issuer_cn),
    ]
    issuer_name = x509.Name(name_attrs)
    subject_name = (
        x509.Name(
            [
                x509.NameAttribute(NameOID.COUNTRY_NAME, country),
                x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
                x509.NameAttribute(NameOID.COMMON_NAME, subject_cn),
            ]
        )
        if subject_cn
        else issuer_name
    )

    builder = (
        x509.CertificateBuilder()
        .subject_name(subject_name)
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(serial if serial is not None else x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
    )
    def _add_extras(b):
        # Unrecognized private-arc extensions (OCTET STRING payloads):
        # pad the extension list to stress budget/refetch paths in the
        # device walker's extension scan.
        for i in range(extra_extensions):
            b = b.add_extension(
                x509.UnrecognizedExtension(
                    x509.ObjectIdentifier(f"1.3.6.1.4.1.99999.{i}"),
                    bytes([i & 0xFF]) * extra_ext_size,
                ),
                critical=False,
            )
        return b

    if extras_first:
        builder = _add_extras(builder)
    if add_basic_constraints:
        builder = builder.add_extension(
            x509.BasicConstraints(ca=is_ca, path_length=None), critical=True
        )
    if not extras_first:
        builder = _add_extras(builder)
    if crl_dps:
        builder = builder.add_extension(
            x509.CRLDistributionPoints(
                [
                    x509.DistributionPoint(
                        full_name=[x509.UniformResourceIdentifier(u)],
                        relative_name=None,
                        reasons=None,
                        crl_issuer=None,
                    )
                    for u in crl_dps
                ]
            ),
            critical=False,
        )
    cert = builder.sign(key, hashes.SHA256())
    return cert.public_bytes(serialization.Encoding.DER)


# Fallback serials: deterministic stand-in for random_serial_number()
# (callers only rely on distinctness across calls).
_serial_counter = itertools.count(0x6E00_0000_0001)


def _make_cert_minicert(*, serial, issuer_cn, subject_cn, org, country,
                        not_before, not_after, crl_dps, is_ca,
                        add_basic_constraints, key_seed, extra_extensions,
                        extra_ext_size, extras_first) -> bytes:
    from ct_mapreduce_tpu.utils import minicert

    now = datetime.datetime(2024, 1, 1, tzinfo=datetime.timezone.utc)
    return minicert.make_cert(
        serial=next(_serial_counter) if serial is None else serial,
        issuer_cn=issuer_cn, subject_cn=subject_cn, org=org,
        country=country, not_before=not_before or now,
        not_after=not_after or now + datetime.timedelta(days=365),
        is_ca=is_ca, add_basic_constraints=add_basic_constraints,
        crl_dps=tuple(crl_dps),
        serial_len=None,  # minimal DER INTEGER, like the builder
        # cryptography keys depend only on key_seed (not the CN), so
        # certs sharing a seed share an SPKI identity — preserve that.
        spki_seed=f"certgen-key:{key_seed}",
        extra_extensions=extra_extensions, extra_ext_size=extra_ext_size,
        extras_first=extras_first)


def sct_signer(seed: str = "certgen-log:0", kind: str = "p256"):
    """Deterministic fixture CT-log signer (shared across tests so
    log ids — and with them key registries — are stable per seed).
    ``kind``: p256 (device-decidable) | p384 | rsa (host fallback)."""
    from ct_mapreduce_tpu.verify import host, sct as sctlib

    if kind == "rsa":
        return sctlib.RsaSctSigner()
    curve = host.CURVES[kind]
    return sctlib.EcSctSigner(seed, curve)


def make_sct_cert(
    signer=None,
    sct_timestamp_ms: int = 1_700_000_000_000,
    sct_extensions: bytes = b"",
    corrupt_signature: bool = False,
    issuer_der: bytes = b"",
    **kwargs,
) -> bytes:
    """An SCT-embedded fixture cert: :func:`make_cert` (cryptography
    when present, minicert otherwise — identical degradation contract)
    plus DER surgery embedding a genuinely-signed SCT
    (:func:`ct_mapreduce_tpu.verify.sct.attach_sct`). ``issuer_der``
    feeds the RFC 6962 issuer_key_hash; pass the chain issuer when the
    cert rides a pipeline lane with one."""
    from ct_mapreduce_tpu.verify import sct as sctlib

    der = make_cert(**kwargs)
    if signer is None:
        signer = sct_signer()
    return sctlib.attach_sct(
        der, signer, sct_timestamp_ms, extensions=sct_extensions,
        corrupt_signature=corrupt_signature, issuer_der=issuer_der,
    )


def spki_of(der: bytes) -> bytes:
    if HAVE_CRYPTOGRAPHY:
        cert = x509.load_der_x509_certificate(der)
        return cert.public_key().public_bytes(
            serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo
        )
    # Minimal TLV walk, independent of the production parser (so the
    # parser tests that compare against spki_of stay a real check):
    # Certificate -> tbsCertificate -> [version] serial sigalg issuer
    # validity subject -> subjectPublicKeyInfo.
    def header(off: int) -> tuple[int, int, int]:
        tag, first = der[off], der[off + 1]
        off += 2
        if first & 0x80:
            n = first & 0x7F
            first = int.from_bytes(der[off:off + n], "big")
            off += n
        return tag, off, first

    _, cert_content, _ = header(0)          # Certificate SEQ
    _, tbs_content, _ = header(cert_content)  # tbsCertificate SEQ
    off = tbs_content
    tag, content_off, content_len = header(off)
    if tag == 0xA0:  # explicit [0] version
        off = content_off + content_len
    for _ in range(4):  # serial, signature alg, issuer, validity
        _, content_off, content_len = header(off)
        off = content_off + content_len
    _, content_off, content_len = header(off)  # subject
    off = content_off + content_len
    _, content_off, content_len = header(off)  # SPKI
    return der[off:content_off + content_len]
