"""Bench failure-mode contract: one parseable JSON line, always.

Three consecutive rounds of ``value: 0`` scoreboard records (BENCHLOG.md)
came from the bench dying without a useful stdout line. The contract
under test (bench.py docstring "Robustness contract"):

- an unreachable/hung backend → rc=1 plus a structured
  ``{"value": 0, "error": ...}`` line before the driver's own timeout;
- a watchdog firing mid-measurement → rc=1 plus the PARTIAL measured
  rate (``"error": "partial: watchdog ..."``), never a bare 0.

Both legs drive the real ``bench.py`` in a subprocess, exactly as the
driver runs it.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env_extra: dict, timeout: float):
    env = dict(os.environ)
    env.update(env_extra)
    # Extend, never replace: the axon shim (and anything else) must
    # survive on PYTHONPATH or the subprocess fails for unrelated
    # import reasons.
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.environ.get("PYTHONPATH", ""), REPO) if p
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, (
        f"bench must print exactly one stdout line; got {proc.stdout!r} "
        f"(stderr tail: {proc.stderr[-500:]!r})"
    )
    return proc.returncode, json.loads(lines[0])


def test_unreachable_backend_emits_structured_error():
    """Pool outage analog: a backend that can never initialize (the
    cuda plugin is absent in this image) must yield rc=1 and a
    driver-parseable error JSON inside the watchdog budget."""
    rc, j = _run_bench(
        {"JAX_PLATFORMS": "cuda", "CT_BENCH_WATCHDOG_SECS": "12"},
        timeout=120,
    )
    assert rc == 1
    assert j["metric"] == "ct_entries_per_sec_per_chip"
    assert j["value"] == 0
    assert j["unit"] == "entries/s/chip"
    assert "error" in j and j["error"]


def test_watchdog_mid_measurement_emits_partial_rate():
    """A watchdog that fires after ≥1 timed chunk must report the
    partial measured rate, not 0 (the round-2 failure mode)."""
    rc, j = _run_bench(
        {
            "JAX_PLATFORMS": "cpu",
            "CT_BENCH_E2E": "0",
            "CT_BENCH_BATCH": "16384",
            "CT_BENCH_LOG2_CAPACITY": "24",
            "CT_BENCH_SECS": "9999",  # never finish on its own
            "CT_BENCH_EXEC_SECS": "2",
            # Must fire AFTER >=1 timed chunk: the 16K-lane headline
            # compiles in ~8 s on this image and chunks take ~2 s, so
            # 24 s leaves ~2x margin while keeping this (deliberate
            # wait) test inside the tier-1 budget (round-17 trim;
            # round 6 took it 75 → 35, round 14 → 30).
            "CT_BENCH_WATCHDOG_SECS": "24",
        },
        timeout=300,
    )
    assert rc == 1
    assert j["metric"] == "ct_entries_per_sec_per_chip"
    assert j["value"] > 0, j
    assert j["error"].startswith("partial: watchdog")
    assert j["vs_baseline"] > 0


def test_silent_child_death_emits_partial_rate():
    """The launcher's insurance (observed 2026-07-31: a run vanished
    mid-e2e with the headline measured but never emitted): a child
    killed with SIGKILL after the timed phase must still yield one
    stdout JSON line carrying the partial measured rate."""
    rc, j = _run_bench(
        {
            "JAX_PLATFORMS": "cpu",
            "CT_BENCH_E2E": "0",
            "CT_BENCH_BATCH": "16384",
            "CT_BENCH_LOG2_CAPACITY": "24",
            "CT_BENCH_EXEC_SECS": "2",
            "CT_BENCH_SECS": "4",
            "CT_BENCH_WATCHDOG_SECS": "280",
            "CT_BENCH_TEST_DIE": "post-measure",
        },
        timeout=420,
    )
    assert rc == 1
    assert j["metric"] == "ct_entries_per_sec_per_chip"
    assert j["value"] > 0, j
    assert "without emitting" in j["error"]
    assert j["vs_baseline"] > 0
