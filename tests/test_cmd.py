"""CLI binaries: ct-fetch, storage-statistics, ct-getcert.

End-to-end over the in-process fake log and tmp state, matching the
reference binaries' flows (cmd/ct-fetch/ct-fetch.go:490-638,
cmd/storage-statistics/storage-statistics.go:22-100,
cmd/ct-getcert/ct-getcert.go:16-57).
"""

import datetime
import io
import sys
from unittest import mock

import pytest

from ct_mapreduce_tpu.cmd import ct_fetch, ct_getcert, storage_statistics
from ct_mapreduce_tpu.config import CTConfig

from tests import certgen
from tests.fakelog import FakeLog

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)


def _fake_log(n=6, issuer_cn="CLI CA", dupes=0):
    log = FakeLog()
    issuer_der = certgen.make_cert(serial=1, issuer_cn=issuer_cn, is_ca=True,
                                   not_after=FUTURE)
    for s in range(n):
        leaf = certgen.make_cert(
            serial=1000 + (s % (n - dupes) if dupes else s),
            issuer_cn=issuer_cn, subject_cn="cli.example.com",
            is_ca=False, not_after=FUTURE,
        )
        log.add_cert(leaf, issuer_der, timestamp_ms=1700000000000 + s)
    return log


def _patch_transport(monkeypatch, log):
    """Route CTLogClient's default transport to the fake log."""
    from ct_mapreduce_tpu.ingest import ctclient

    monkeypatch.setattr(ctclient, "_urllib_transport", log.transport)


@pytest.mark.parametrize("mesh_shape,expect_sharded", [
    ("shard:1", False),  # explicit single chip -> TpuAggregator
    ("", True),          # default: all 8 virtual devices, sharded
    ("shard:8", True),   # explicit mesh (BASELINE config #5's shape)
])
def test_ct_fetch_tpu_backend_and_statistics(tmp_path, monkeypatch, capsys,
                                             mesh_shape, expect_sharded):
    """TPU-backend CLI flow across aggregator selections: ct-fetch
    ingests through the device pipeline (single-chip or all_to_all
    mesh-sharded per meshShape), snapshots, and storage-statistics
    drains the snapshot identically in every case."""
    from ct_mapreduce_tpu.agg import sharded_agg

    sharded_built = []
    orig_sharded = sharded_agg.ShardedAggregator

    class SpyShardedAggregator(orig_sharded):
        def __init__(self, *a, **k):
            sharded_built.append(True)
            super().__init__(*a, **k)

    monkeypatch.setattr(sharded_agg, "ShardedAggregator",
                        SpyShardedAggregator)
    log = _fake_log(n=6, dupes=2)
    _patch_transport(monkeypatch, log)
    ini = tmp_path / "ct.ini"
    state = tmp_path / "agg.npz"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        + (f"meshShape = {mesh_shape}\n" if mesh_shape else "")
        + f"aggStatePath = {state}\n"
        "healthAddr = \n"
        "nobars = true\n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    assert state.exists()
    # meshShape really drives aggregator selection (empty = all
    # visible devices -> sharded on the 8-device virtual mesh).
    assert bool(sharded_built) == expect_sharded

    rc = storage_statistics.main(["-config", str(ini), "-v", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "overall totals: 1 issuers, 4 serials" in out
    assert "Issuer: " in out and "CLI CA" in out


def test_ct_fetch_database_backend(tmp_path, monkeypatch, capsys):
    log = _fake_log(n=5)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        f"certPath = {certs}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    # PEMs landed in the <exp>/<issuer>/<serial> tree
    pems = list(certs.rglob("*"))
    assert any(p.is_file() for p in pems)
    # checkpoint file written under state/
    assert (certs / "state").exists()


def test_ct_fetch_tpu_backend_with_certpath_writes_pems(tmp_path, monkeypatch):
    """backend=tpu + certPath keeps the reference's durable PEM tree
    (filesystemdatabase.go:189-208): one PEM per first-seen cert in
    <exp>/<issuer>/<serial>, plus dirty markers."""
    log = _fake_log(n=5, dupes=1)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"certPath = {certs}\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    pems = [p for p in certs.rglob("*") if p.is_file()
            and "state" not in p.parts and not p.name.startswith(".")]
    assert len(pems) == 4  # 5 entries, 1 dupe
    assert pems[0].read_bytes().startswith(b"-----BEGIN CERTIFICATE-----")
    assert list(certs.rglob(".dirty")) or list(certs.rglob("*dirty*"))


def test_storage_statistics_tpu_v2_v3(tmp_path, monkeypatch, capsys):
    """--backend=tpu verbosity parity (storage-statistics.go:28-99):
    -v2 lists serials (PEM-tree + host-lane), -v3 dumps the PEMs. With
    certPath set during the fetch, every first-seen cert is listable."""
    log = _fake_log(n=5, dupes=1)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"certPath = {certs}\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        "healthAddr = \n"
    )
    assert ct_fetch.main(["-config", str(ini), "-nobars"]) == 0

    rc = storage_statistics.main(["-config", str(ini), "-v", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Serials: [" in out
    # 4 distinct serials (5 entries, 1 dupe), all listable via the tree.
    import re

    listed = re.findall(r"Serials: \[([^\]]*)\]", out)
    n_listed = sum(len([x for x in blob.split(",") if x.strip()])
                   for blob in listed)
    assert n_listed == 4
    assert "count-only" not in out  # nothing unlisted when certPath set

    rc = storage_statistics.main(["-config", str(ini), "-v", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("-----BEGIN CERTIFICATE-----") == 4
    assert "Certificate serial={" in out

    # Without the PEM tree, device-lane serials are count-only and say so.
    ini2 = tmp_path / "ct2.ini"
    ini2.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        "healthAddr = \n"
    )
    rc = storage_statistics.main(["-config", str(ini2), "-v", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "count-only" in out


def test_storage_statistics_tpu_log_status_and_host_only(
        tmp_path, monkeypatch, capsys):
    """TPU mode prints the per-log "Log status:" section exactly like
    database mode (storage-statistics.go:86-98) — the cursor is
    dual-written through the same facade regardless of backend — and
    the report is pure host work: the snapshot reader's table state
    stays NumPy end to end (report must run during TPU pool outages)."""
    import numpy as np

    log = _fake_log(n=5)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    state = tmp_path / "agg.npz"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"certPath = {certs}\n"
        f"aggStatePath = {state}\n"
        "healthAddr = \n"
    )
    assert ct_fetch.main(["-config", str(ini), "-nobars"]) == 0

    rc = storage_statistics.main(["-config", str(ini)])
    assert rc == 0
    tpu_out = capsys.readouterr().out
    assert "Log status:" in tpu_out
    assert "MaxEntry=5" in tpu_out
    tpu_status = tpu_out.split("Log status:")[1]

    # Database mode over the same certPath prints the identical status
    # lines (same facade walk, backend-fallback read of the cursor).
    buf = io.StringIO()
    rc = storage_statistics.report_from_database(
        CTConfig.load(["-config", str(ini)]), buf)
    assert rc == 0
    db_status = buf.getvalue().split("Log status:")[1]
    # LastUpdateTime differs per read only if rewritten; here both read
    # the same stored state — the lines must match byte for byte.
    assert tpu_status == db_status

    # Host-only residency: no jax arrays anywhere in the read path, and
    # the drain matches the device aggregator's drain on the same file.
    from ct_mapreduce_tpu.agg.aggregator import (
        HostSnapshotAggregator, TpuAggregator)

    host = HostSnapshotAggregator(capacity=1 << 10)
    host.load_checkpoint(str(state))
    assert isinstance(host.table.keys, np.ndarray)
    host_snap = host.drain()
    assert isinstance(host.table.keys, np.ndarray)
    dev = TpuAggregator(capacity=1 << 10)
    dev.load_checkpoint(str(state))
    dev_snap = dev.drain()
    assert host_snap.counts == dev_snap.counts
    assert host_snap.crls == dev_snap.crls
    assert host_snap.dns == dev_snap.dns


def test_ct_fetch_requires_loglist(capsys):
    rc = ct_fetch.main(["-nobars"])
    assert rc == 2


def test_ct_fetch_offset_limit(tmp_path, monkeypatch):
    log = _fake_log(n=10)
    _patch_transport(monkeypatch, log)
    ini = tmp_path / "ct.ini"
    state = tmp_path / "agg.npz"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "tableBits = 12\n"
        f"aggStatePath = {state}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(
        ["-config", str(ini), "-nobars", "-offset", "2", "-limit", "3"]
    )
    assert rc == 0
    # entries 2,3,4 → 3 distinct serials
    out = io.StringIO()
    cfg = CTConfig.load(["-config", str(ini)])
    storage_statistics.report_from_tpu_snapshot(cfg, out)
    assert "3 serials" in out.getvalue()


def test_storage_statistics_parity_mode(tmp_path, monkeypatch, capsys):
    # Parity mode walks the same database the fetch wrote (in-process
    # MockRemoteCache means both must share one engine invocation).
    from ct_mapreduce_tpu.engine import get_configured_storage
    from ct_mapreduce_tpu.ingest.sync import DatabaseSink, LogSyncEngine

    log = _fake_log(n=4)
    cfg = CTConfig.load([])
    database, cache, backend = get_configured_storage(cfg)
    sink = DatabaseSink(database, now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    engine = LogSyncEngine(sink, database, num_threads=1)
    engine.start_store_threads()
    engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=30)
    engine.stop()

    out = io.StringIO()
    with mock.patch(
        "ct_mapreduce_tpu.cmd.storage_statistics.get_configured_storage",
        return_value=(database, cache, backend),
    ):
        rc = storage_statistics.report_from_database(cfg, out, verbosity=2)
    assert rc == 0
    text = out.getvalue()
    assert "overall totals: 1 issuers, 4 serials" in text
    assert "Serials:" in text


def test_storage_statistics_json_parity(tmp_path, monkeypatch, capsys):
    """--json emits the same numbers the text report prints (ISSUE 5
    satellite): totals line vs totals object, per-expDate -v1 counts
    vs the expDates maps, and the Log status walk."""
    import json
    import re

    log = _fake_log(n=6, dupes=2)
    _patch_transport(monkeypatch, log)
    ini = tmp_path / "ct.ini"
    state = tmp_path / "agg.npz"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"aggStatePath = {state}\n"
        "healthAddr = \n"
    )
    assert ct_fetch.main(["-config", str(ini), "-nobars"]) == 0

    rc = storage_statistics.main(["-config", str(ini), "-v", "1"])
    assert rc == 0
    text = capsys.readouterr().out

    rc = storage_statistics.main(["-config", str(ini), "-json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)

    m = re.search(r"overall totals: (\d+) issuers, (\d+) serials, "
                  r"(\d+) crls", text)
    assert m
    assert report["totals"] == {
        "issuers": int(m.group(1)),
        "serials": int(m.group(2)),
        "crls": int(m.group(3)),
    }
    # Per-expDate counts match the -v1 bullet lines number for number.
    text_counts = dict(re.findall(r"- (\S+) \((\d+) serials\)", text))
    json_counts = {
        exp: str(n)
        for iss in report["issuers"]
        for exp, n in iss["expDates"].items()
    }
    assert json_counts == text_counts
    for iss in report["issuers"]:
        assert iss["serials"] == sum(iss["expDates"].values())
        assert f"Issuer: {iss['id']}" in text
    # Log status rides along as data.
    status_lines = text.split("Log status:")[1].strip().splitlines()
    assert report["logStatus"] == [ln for ln in status_lines if ln]

    # Database mode --json: same collector shape over the cache walk.
    from ct_mapreduce_tpu.engine import get_configured_storage
    from ct_mapreduce_tpu.ingest.sync import DatabaseSink, LogSyncEngine

    cfg = CTConfig.load([])
    database, cache, backend = get_configured_storage(cfg)
    sink = DatabaseSink(database,
                       now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    engine = LogSyncEngine(sink, database, num_threads=1)
    engine.start_store_threads()
    engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=30)
    engine.stop()
    with mock.patch(
        "ct_mapreduce_tpu.cmd.storage_statistics.get_configured_storage",
        return_value=(database, cache, backend),
    ):
        db_report = storage_statistics.collect_database_report(cfg)
    assert db_report["totals"] == report["totals"]


def test_ct_getcert(capsys):
    log = _fake_log(n=3)
    out = io.StringIO()
    rc = ct_getcert.main(
        ["-log", log.url, "-index", "1"], transport=log.transport, out=out
    )
    assert rc == 0
    pem = out.getvalue()
    assert pem.startswith("-----BEGIN CERTIFICATE-----")
    # round-trip: the PEM decodes back to the cert at index 1
    import base64

    body = "".join(pem.splitlines()[1:-1])
    der = base64.b64decode(body)
    from ct_mapreduce_tpu.core import der as hostder

    fields = hostder.parse_cert(der)
    assert fields.serial == (1001).to_bytes(2, "big")


def test_ct_getcert_routes_via_query_plane(tmp_path):
    """queryPort satellite: with a query plane up, ct-getcert fetches
    through its /getcert proxy (no direct log transport at all); with
    the plane down, it falls back to the direct transport."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.serve.server import QueryServer

    log = _fake_log(n=3)
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    srv = QueryServer(agg, 0, host="127.0.0.1",
                      transport=log.transport).start()
    try:
        out = io.StringIO()
        # transport=None: a direct log fetch would hit the network and
        # fail — success proves the plane served the PEM.
        rc = ct_getcert.main(
            ["-log", log.url, "-index", "1",
             "-queryAddr", f"127.0.0.1:{srv.port}"],
            transport=None, out=out,
        )
        assert rc == 0
        assert out.getvalue().startswith("-----BEGIN CERTIFICATE-----")

        # The config path resolves queryPort the same way.
        ini = tmp_path / "q.ini"
        ini.write_text(f"queryPort = {srv.port}\n")
        out = io.StringIO()
        rc = ct_getcert.main(
            ["-log", log.url, "-index", "0", "-config", str(ini)],
            transport=None, out=out,
        )
        assert rc == 0
        assert out.getvalue().startswith("-----BEGIN CERTIFICATE-----")
    finally:
        srv.stop()

    # Plane gone: the same invocation falls back to the given direct
    # transport and still succeeds.
    out = io.StringIO()
    rc = ct_getcert.main(
        ["-log", log.url, "-index", "1",
         "-queryAddr", f"127.0.0.1:{srv.port}"],
        transport=log.transport, out=out,
    )
    assert rc == 0
    assert out.getvalue().startswith("-----BEGIN CERTIFICATE-----")


def test_ct_query_cli(capsys):
    """ct-query end to end: known serial exits 0, unknown exits 1,
    issuer metadata and health print JSON, unreachable plane exits 2."""
    import json

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.cmd import ct_query
    from ct_mapreduce_tpu.core import der as hostder
    from ct_mapreduce_tpu.core.types import ExpDate, Issuer
    from ct_mapreduce_tpu.serve.server import QueryServer
    from ct_mapreduce_tpu.utils import syncerts

    tpl = syncerts.make_template(issuer_cn="Query CLI CA")
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(tpl, j), tpl.issuer_der)
                for j in range(5)])
    issuer_id = Issuer.from_spki(
        hostder.parse_cert(tpl.issuer_der).spki).id()
    eh = hostder.parse_cert(tpl.leaf_der).not_after_unix_hour
    exp_id = ExpDate.from_unix_hour(eh).id()

    def serial_hex(j):
        der = syncerts.stamp_serial(tpl, j)
        return der[tpl.serial_off:tpl.serial_off + tpl.serial_len].hex()

    srv = QueryServer(agg, 0, host="127.0.0.1", max_delay_s=0.001).start()
    try:
        addr = f"127.0.0.1:{srv.port}"
        out = io.StringIO()
        rc = ct_query.main(
            ["-addr", addr, "-issuer", issuer_id, "-expDate", exp_id,
             "-serial", serial_hex(0), "-serial", serial_hex(4)],
            out=out,
        )
        assert rc == 0
        resp = json.loads(out.getvalue())
        assert [r["known"] for r in resp["results"]] == [True, True]
        assert resp["epoch"] >= 1 and "staleness_s" in resp

        out = io.StringIO()
        rc = ct_query.main(
            ["-addr", addr, "-issuer", issuer_id, "-expDate", exp_id,
             "-serial", serial_hex(999)],
            out=out,
        )
        assert rc == 1  # unknown serial, grep-style exit

        out = io.StringIO()
        rc = ct_query.main(["-addr", addr, "-issuerMeta", issuer_id],
                           out=out)
        assert rc == 0
        assert json.loads(out.getvalue())["unknown_total"] == 5

        out = io.StringIO()
        rc = ct_query.main(["-addr", addr, "-health"], out=out)
        assert rc == 0
        assert json.loads(out.getvalue())["healthy"] is True
    finally:
        srv.stop()
    # Plane gone: transport error exits 2.
    rc = ct_query.main(
        ["-addr", f"127.0.0.1:{srv.port}", "-health"], out=io.StringIO())
    assert rc == 2


def test_ct_fetch_starts_query_plane(tmp_path, monkeypatch):
    """queryPort on ct-fetch: the query plane answers membership for
    the serials the run just ingested — asserted from inside the run
    via the engine's store path (the server outlives sync_log but not
    main), so we probe after main() via a spy that captured the port.

    The plane binds an ephemeral port (queryPort directive value 0 is
    'off', so the test patches QueryServer to record the bound port
    and uses a fixed free one)."""
    import socket

    from ct_mapreduce_tpu.serve import server as serve_server

    log = _fake_log(n=5, dupes=1)
    _patch_transport(monkeypatch, log)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    probed = {}
    orig_stop = serve_server.QueryServer.stop

    def spy_stop(self):
        # Probe while the plane is still serving (just before ct-fetch
        # tears it down): the live aggregator answers.
        try:
            from ct_mapreduce_tpu.serve.client import QueryClient

            probed["health"] = QueryClient(
                f"127.0.0.1:{self.port}").healthz()
        finally:
            orig_stop(self)

    monkeypatch.setattr(serve_server.QueryServer, "stop", spy_stop)
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        f"queryPort = {port}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    assert probed["health"]["healthy"] is True
