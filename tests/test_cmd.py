"""CLI binaries: ct-fetch, storage-statistics, ct-getcert.

End-to-end over the in-process fake log and tmp state, matching the
reference binaries' flows (cmd/ct-fetch/ct-fetch.go:490-638,
cmd/storage-statistics/storage-statistics.go:22-100,
cmd/ct-getcert/ct-getcert.go:16-57).
"""

import datetime
import io
import sys
from unittest import mock

import pytest

from ct_mapreduce_tpu.cmd import ct_fetch, ct_getcert, storage_statistics
from ct_mapreduce_tpu.config import CTConfig

from tests import certgen
from tests.fakelog import FakeLog

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)


def _fake_log(n=6, issuer_cn="CLI CA", dupes=0):
    log = FakeLog()
    issuer_der = certgen.make_cert(serial=1, issuer_cn=issuer_cn, is_ca=True,
                                   not_after=FUTURE)
    for s in range(n):
        leaf = certgen.make_cert(
            serial=1000 + (s % (n - dupes) if dupes else s),
            issuer_cn=issuer_cn, subject_cn="cli.example.com",
            is_ca=False, not_after=FUTURE,
        )
        log.add_cert(leaf, issuer_der, timestamp_ms=1700000000000 + s)
    return log


def _patch_transport(monkeypatch, log):
    """Route CTLogClient's default transport to the fake log."""
    from ct_mapreduce_tpu.ingest import ctclient

    monkeypatch.setattr(ctclient, "_urllib_transport", log.transport)


@pytest.mark.parametrize("mesh_shape,expect_sharded", [
    ("shard:1", False),  # explicit single chip -> TpuAggregator
    ("", True),          # default: all 8 virtual devices, sharded
    ("shard:8", True),   # explicit mesh (BASELINE config #5's shape)
])
def test_ct_fetch_tpu_backend_and_statistics(tmp_path, monkeypatch, capsys,
                                             mesh_shape, expect_sharded):
    """TPU-backend CLI flow across aggregator selections: ct-fetch
    ingests through the device pipeline (single-chip or all_to_all
    mesh-sharded per meshShape), snapshots, and storage-statistics
    drains the snapshot identically in every case."""
    from ct_mapreduce_tpu.agg import sharded_agg

    sharded_built = []
    orig_sharded = sharded_agg.ShardedAggregator

    class SpyShardedAggregator(orig_sharded):
        def __init__(self, *a, **k):
            sharded_built.append(True)
            super().__init__(*a, **k)

    monkeypatch.setattr(sharded_agg, "ShardedAggregator",
                        SpyShardedAggregator)
    log = _fake_log(n=6, dupes=2)
    _patch_transport(monkeypatch, log)
    ini = tmp_path / "ct.ini"
    state = tmp_path / "agg.npz"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        + (f"meshShape = {mesh_shape}\n" if mesh_shape else "")
        + f"aggStatePath = {state}\n"
        "healthAddr = \n"
        "nobars = true\n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    assert state.exists()
    # meshShape really drives aggregator selection (empty = all
    # visible devices -> sharded on the 8-device virtual mesh).
    assert bool(sharded_built) == expect_sharded

    rc = storage_statistics.main(["-config", str(ini), "-v", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "overall totals: 1 issuers, 4 serials" in out
    assert "Issuer: " in out and "CLI CA" in out


def test_ct_fetch_database_backend(tmp_path, monkeypatch, capsys):
    log = _fake_log(n=5)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        f"certPath = {certs}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    # PEMs landed in the <exp>/<issuer>/<serial> tree
    pems = list(certs.rglob("*"))
    assert any(p.is_file() for p in pems)
    # checkpoint file written under state/
    assert (certs / "state").exists()


def test_ct_fetch_tpu_backend_with_certpath_writes_pems(tmp_path, monkeypatch):
    """backend=tpu + certPath keeps the reference's durable PEM tree
    (filesystemdatabase.go:189-208): one PEM per first-seen cert in
    <exp>/<issuer>/<serial>, plus dirty markers."""
    log = _fake_log(n=5, dupes=1)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"certPath = {certs}\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(["-config", str(ini), "-nobars"])
    assert rc == 0
    pems = [p for p in certs.rglob("*") if p.is_file()
            and "state" not in p.parts and not p.name.startswith(".")]
    assert len(pems) == 4  # 5 entries, 1 dupe
    assert pems[0].read_bytes().startswith(b"-----BEGIN CERTIFICATE-----")
    assert list(certs.rglob(".dirty")) or list(certs.rglob("*dirty*"))


def test_storage_statistics_tpu_v2_v3(tmp_path, monkeypatch, capsys):
    """--backend=tpu verbosity parity (storage-statistics.go:28-99):
    -v2 lists serials (PEM-tree + host-lane), -v3 dumps the PEMs. With
    certPath set during the fetch, every first-seen cert is listable."""
    log = _fake_log(n=5, dupes=1)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"certPath = {certs}\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        "healthAddr = \n"
    )
    assert ct_fetch.main(["-config", str(ini), "-nobars"]) == 0

    rc = storage_statistics.main(["-config", str(ini), "-v", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Serials: [" in out
    # 4 distinct serials (5 entries, 1 dupe), all listable via the tree.
    import re

    listed = re.findall(r"Serials: \[([^\]]*)\]", out)
    n_listed = sum(len([x for x in blob.split(",") if x.strip()])
                   for blob in listed)
    assert n_listed == 4
    assert "count-only" not in out  # nothing unlisted when certPath set

    rc = storage_statistics.main(["-config", str(ini), "-v", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("-----BEGIN CERTIFICATE-----") == 4
    assert "Certificate serial={" in out

    # Without the PEM tree, device-lane serials are count-only and say so.
    ini2 = tmp_path / "ct2.ini"
    ini2.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"aggStatePath = {tmp_path / 'agg.npz'}\n"
        "healthAddr = \n"
    )
    rc = storage_statistics.main(["-config", str(ini2), "-v", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "count-only" in out


def test_storage_statistics_tpu_log_status_and_host_only(
        tmp_path, monkeypatch, capsys):
    """TPU mode prints the per-log "Log status:" section exactly like
    database mode (storage-statistics.go:86-98) — the cursor is
    dual-written through the same facade regardless of backend — and
    the report is pure host work: the snapshot reader's table state
    stays NumPy end to end (report must run during TPU pool outages)."""
    import numpy as np

    log = _fake_log(n=5)
    _patch_transport(monkeypatch, log)
    certs = tmp_path / "certs"
    state = tmp_path / "agg.npz"
    ini = tmp_path / "ct.ini"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "batchSize = 64\n"
        "tableBits = 12\n"
        f"certPath = {certs}\n"
        f"aggStatePath = {state}\n"
        "healthAddr = \n"
    )
    assert ct_fetch.main(["-config", str(ini), "-nobars"]) == 0

    rc = storage_statistics.main(["-config", str(ini)])
    assert rc == 0
    tpu_out = capsys.readouterr().out
    assert "Log status:" in tpu_out
    assert "MaxEntry=5" in tpu_out
    tpu_status = tpu_out.split("Log status:")[1]

    # Database mode over the same certPath prints the identical status
    # lines (same facade walk, backend-fallback read of the cursor).
    buf = io.StringIO()
    rc = storage_statistics.report_from_database(
        CTConfig.load(["-config", str(ini)]), buf)
    assert rc == 0
    db_status = buf.getvalue().split("Log status:")[1]
    # LastUpdateTime differs per read only if rewritten; here both read
    # the same stored state — the lines must match byte for byte.
    assert tpu_status == db_status

    # Host-only residency: no jax arrays anywhere in the read path, and
    # the drain matches the device aggregator's drain on the same file.
    from ct_mapreduce_tpu.agg.aggregator import (
        HostSnapshotAggregator, TpuAggregator)

    host = HostSnapshotAggregator(capacity=1 << 10)
    host.load_checkpoint(str(state))
    assert isinstance(host.table.keys, np.ndarray)
    host_snap = host.drain()
    assert isinstance(host.table.keys, np.ndarray)
    dev = TpuAggregator(capacity=1 << 10)
    dev.load_checkpoint(str(state))
    dev_snap = dev.drain()
    assert host_snap.counts == dev_snap.counts
    assert host_snap.crls == dev_snap.crls
    assert host_snap.dns == dev_snap.dns


def test_ct_fetch_requires_loglist(capsys):
    rc = ct_fetch.main(["-nobars"])
    assert rc == 2


def test_ct_fetch_offset_limit(tmp_path, monkeypatch):
    log = _fake_log(n=10)
    _patch_transport(monkeypatch, log)
    ini = tmp_path / "ct.ini"
    state = tmp_path / "agg.npz"
    ini.write_text(
        f"logList = {log.url}\n"
        "backend = tpu\n"
        "tableBits = 12\n"
        f"aggStatePath = {state}\n"
        "healthAddr = \n"
    )
    rc = ct_fetch.main(
        ["-config", str(ini), "-nobars", "-offset", "2", "-limit", "3"]
    )
    assert rc == 0
    # entries 2,3,4 → 3 distinct serials
    out = io.StringIO()
    cfg = CTConfig.load(["-config", str(ini)])
    storage_statistics.report_from_tpu_snapshot(cfg, out)
    assert "3 serials" in out.getvalue()


def test_storage_statistics_parity_mode(tmp_path, monkeypatch, capsys):
    # Parity mode walks the same database the fetch wrote (in-process
    # MockRemoteCache means both must share one engine invocation).
    from ct_mapreduce_tpu.engine import get_configured_storage
    from ct_mapreduce_tpu.ingest.sync import DatabaseSink, LogSyncEngine

    log = _fake_log(n=4)
    cfg = CTConfig.load([])
    database, cache, backend = get_configured_storage(cfg)
    sink = DatabaseSink(database, now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    engine = LogSyncEngine(sink, database, num_threads=1)
    engine.start_store_threads()
    engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=30)
    engine.stop()

    out = io.StringIO()
    with mock.patch(
        "ct_mapreduce_tpu.cmd.storage_statistics.get_configured_storage",
        return_value=(database, cache, backend),
    ):
        rc = storage_statistics.report_from_database(cfg, out, verbosity=2)
    assert rc == 0
    text = out.getvalue()
    assert "overall totals: 1 issuers, 4 serials" in text
    assert "Serials:" in text


def test_ct_getcert(capsys):
    log = _fake_log(n=3)
    out = io.StringIO()
    rc = ct_getcert.main(
        ["-log", log.url, "-index", "1"], transport=log.transport, out=out
    )
    assert rc == 0
    pem = out.getvalue()
    assert pem.startswith("-----BEGIN CERTIFICATE-----")
    # round-trip: the PEM decodes back to the cert at index 1
    import base64

    body = "".join(pem.splitlines()[1:-1])
    der = base64.b64decode(body)
    from ct_mapreduce_tpu.core import der as hostder

    fields = hostder.parse_cert(der)
    assert fields.serial == (1001).to_bytes(2, "big")
