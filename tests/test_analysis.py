"""Round-16 analysis framework tests: engine mechanics, each checker
on synthetic violations, baseline workflow, and the runtime lock
witness self-test (injected order violation + 2-lock cycle, both
landing in a flight-recorder dump).

Witness self-tests use PRIVATE LockWitness instances so the injected
violations never pollute the suite-wide witness that conftest gates
the session on."""

import json
import pathlib
import textwrap
import threading

import pytest

from ct_mapreduce_tpu.analysis import lockspec, witness
from ct_mapreduce_tpu.analysis.config_parity import ConfigParityChecker
from ct_mapreduce_tpu.analysis.determinism import DeterminismChecker
from ct_mapreduce_tpu.analysis.donation import DonationChecker
from ct_mapreduce_tpu.analysis.engine import (
    AnalysisEngine,
    apply_baseline,
    load_baseline,
)
from ct_mapreduce_tpu.analysis.jit_purity import JitPurityChecker
from ct_mapreduce_tpu.analysis.lock_order import LockOrderChecker
from ct_mapreduce_tpu.analysis.metric_registry import MetricRegistryChecker

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_on(tmp_path, files: dict, checkers, pkg="ct_mapreduce_tpu"):
    """Write a synthetic package (named like the real one so checker
    scope patterns match) and run the engine over it."""
    root = tmp_path / pkg
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    engine = AnalysisEngine(checkers)
    return engine.run(root)


# -- lock-order ----------------------------------------------------------

def test_lock_order_flags_undeclared_lock(tmp_path):
    findings = run_on(tmp_path, {"serve/extra.py": """
        import threading

        class Thing:
            def __init__(self):
                self._adhoc_lock = threading.Lock()
        """}, [LockOrderChecker()])
    assert [f for f in findings if f.rule == "lock-order"
            and "Thing._adhoc_lock" in f.symbol]


def test_lock_order_flags_inverted_nest(tmp_path):
    findings = run_on(tmp_path, {"agg/x.py": """
        def bad(agg):
            with agg._table_lock:
                with agg._fold_lock:
                    pass

        def good(agg):
            with agg._fold_lock:
                with agg._table_lock:
                    pass
        """}, [LockOrderChecker()])
    bad = [f for f in findings if f.symbol == "agg.table->agg.fold"]
    assert bad and "rank" in bad[0].message
    assert not [f for f in findings if f.symbol == "agg.fold->agg.table"]


def test_lock_order_multi_item_with_and_closure_scope(tmp_path):
    findings = run_on(tmp_path, {"agg/y.py": """
        def multi(agg):
            with agg._save_lock, agg._dispatch_lock:  # 24 then 20: bad
                pass

        def closure(agg):
            with agg._fold_lock:
                def later():
                    with agg._dispatch_lock:  # runs outside the fold
                        pass
                return later
        """}, [LockOrderChecker()])
    assert [f for f in findings if f.symbol == "agg.save->ingest.dispatch"]
    assert not [f for f in findings
                if f.symbol == "agg.fold->ingest.dispatch"]


def test_lockspec_covers_every_package_lock():
    """The undeclared-lock sub-rule over the REAL package: the spec in
    lockspec.py declares every threading lock (zero live findings is
    the ctmrlint gate; here we pin the inventory is non-trivial)."""
    checker = LockOrderChecker()
    AnalysisEngine([checker]).run(REPO / "ct_mapreduce_tpu")
    undeclared = [f for f in checker.findings if "not declared" in f.message]
    assert not undeclared, "\n".join(f.render() for f in undeclared)
    table = lockspec.build_site_table(REPO / "ct_mapreduce_tpu")
    assert len(table) >= 25  # ~30 locks across 15 modules (ISSUE 11)


# -- donation-safety -----------------------------------------------------

def test_donation_flags_use_after_donate(tmp_path):
    findings = run_on(tmp_path, {"ops/z.py": """
        def bad(table, rows):
            table, out = ingest_step_donated(table, rows, 3)
            return rows.sum()

        def good(table, rows):
            table, out = ingest_step_donated(table, rows, 3)
            return table, out
        """}, [DonationChecker()])
    assert [f for f in findings if f.symbol == "bad:rows"]
    assert not [f for f in findings if "good" in f.symbol]


def test_donation_tracks_conditional_alias_and_self_attrs(tmp_path):
    findings = run_on(tmp_path, {"agg/w.py": """
        def aliased(self, data):
            step = (ingest_step_donated if fast else ingest_step)
            self.table, out = step(self.table, data, 1)
            return data.nbytes  # data donated via the alias

        def reassigned(self, data):
            step = ingest_step_donated
            self.table, out = step(self.table, data, 1)
            data = out.fresh
            return data.nbytes
        """}, [DonationChecker()])
    assert [f for f in findings if f.symbol == "aliased:data"]
    assert not [f for f in findings if f.symbol.startswith("reassigned")]


# -- determinism ---------------------------------------------------------

def test_determinism_rules(tmp_path):
    findings = run_on(tmp_path, {"filter/artifact.py": """
        import time, random

        def serialize(groups):
            stamp = time.time()  # flagged
            salt = random.random()  # flagged
            for k, v in groups.items():  # flagged
                emit(k, v)
            for k in sorted(groups.items()):  # fine
                emit(k)
            total = sum(len(v) for v in groups.values())  # order-free
            return stamp, salt, total
        """}, [DeterminismChecker()])
    kinds = {f.symbol.split(":")[1] for f in findings}
    assert kinds == {"clock", "random", "unsorted"}
    assert len([f for f in findings if ":unsorted:" in f.symbol]) == 1


def test_determinism_out_of_scope_module_is_silent(tmp_path):
    findings = run_on(tmp_path, {"ingest/anything.py": """
        import time

        def poll():
            return time.time()
        """}, [DeterminismChecker()])
    assert findings == []


# -- jit-purity ----------------------------------------------------------

def test_jit_purity(tmp_path):
    findings = run_on(tmp_path, {"ops/k.py": """
        import jax, functools

        def core(x):
            print("tracing")  # flagged
            incr_counter("a", "b")  # flagged
            return x + 1

        step = functools.partial(jax.jit, donate_argnums=(0,))(core)

        def loop(x):
            def body(i, c):
                with self._table_lock:  # flagged
                    return c
            return jax.lax.fori_loop(0, 4, body, x)

        def host_only(x):
            print("fine outside jit")
            return x
        """}, [JitPurityChecker()])
    syms = {f.symbol for f in findings}
    assert "core:print" in syms
    assert "core:metric:incr_counter" in syms
    assert "body:lock:_table_lock" in syms
    assert not any(s.startswith("host_only") for s in syms)


# -- metric-registry / config-parity (synthetic) -------------------------

def test_metric_registry_checker(tmp_path):
    root = tmp_path / "ct_mapreduce_tpu"
    root.mkdir()
    (root / "m.py").write_text(
        "incr_counter('lane', 'hits')\nset_gauge('lane', 'depth')\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "METRICS.md").write_text(
        "- `lane.hits` — counter\n- `lane.ghost` — counter\n")
    checker = MetricRegistryChecker()
    AnalysisEngine([checker]).run(root)
    syms = {f.symbol for f in checker.findings}
    assert "lane.depth" in syms  # emitted, undocumented
    assert "stale:lane.ghost" in syms  # documented, never emitted
    assert not any(s == "lane.hits" for s in syms)


def test_config_parity_checker(tmp_path):
    files = {
        "config/config.py": """
            class CTConfig:
                _DIRECTIVES = {
                    "alpha": ("alpha", int),
                    "beta": ("beta", str),
                    "certPath": ("cert_path", str),
                }

                def usage(self):
                    lines = [
                        "alpha = the alpha knob",
                        "ghost = documented but unparsed",
                    ]
                    return "\\n".join(lines)
            """,
        "serve/s.py": """
            import os

            def resolve_thing(v=None):
                return v or os.environ.get("CTMR_THING", "")
            """,
    }
    root = tmp_path / "ct_mapreduce_tpu"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    (tmp_path / "MIGRATING.md").write_text("alpha is documented here\n")
    checker = ConfigParityChecker()
    AnalysisEngine([checker]).run(root)
    syms = {f.symbol for f in checker.findings}
    assert "usage:beta" in syms  # parsed, not in usage()
    assert "usage-unknown:ghost" in syms  # usage() ghost
    assert "migrating:beta" in syms  # TPU-native, not in MIGRATING
    assert "migrating-env:CTMR_THING" in syms
    assert "migrating:certPath" not in syms  # reference directive
    assert "usage:alpha" not in syms


# -- baseline ------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    from ct_mapreduce_tpu.analysis.engine import Finding
    base = tmp_path / "b.baseline"
    base.write_text(
        "# comment\n"
        "ruleA:pkg/x.py:sym | known quirk, tracked in ISSUE 99\n"
        "ruleB:pkg/y.py:gone | stale entry\n")
    entries = load_baseline(base)
    assert entries["ruleA:pkg/x.py:sym"].startswith("known quirk")
    live, suppressed, unused = apply_baseline(
        [Finding("ruleA", "pkg/x.py", 3, "sym", "m"),
         Finding("ruleA", "pkg/x.py", 9, "other", "m2")], entries)
    assert [f.symbol for f in live] == ["other"]
    assert [f.symbol for f in suppressed] == ["sym"]
    assert unused == ["ruleB:pkg/y.py:gone"]


def test_baseline_requires_justification(tmp_path):
    base = tmp_path / "b.baseline"
    base.write_text("ruleA:pkg/x.py:sym\n")
    with pytest.raises(ValueError, match="justification"):
        load_baseline(base)


# -- runtime witness -----------------------------------------------------

def make_witness():
    """Private instance with a tiny two-lock spec (never touches the
    installed suite-wide witness)."""
    w = witness.LockWitness(ranks={"t.outer": 10, "t.inner": 20})
    outer = w.wrap(threading.Lock(), "t.outer")
    inner = w.wrap(threading.Lock(), "t.inner")
    return w, outer, inner


def test_witness_clean_order_and_reentrancy():
    w, outer, inner = make_witness()
    with outer:
        with inner:
            pass
    r = w.wrap(threading.RLock(), "t.r")
    with r:
        with r:  # reentrant: no self-edge, no violation
            pass
    assert w.findings() == []
    assert w.edges() == {"t.outer": ["t.inner"]}


def test_witness_detects_out_of_order_acquisition():
    w, outer, inner = make_witness()
    with inner:
        with outer:  # rank 20 held, acquiring rank 10
            pass
    v = w.findings()
    assert len(v) == 1 and v[0]["kind"] == "order"
    assert v[0]["held"] == "t.inner" and v[0]["acquiring"] == "t.outer"
    assert "test_analysis.py" in v[0]["where"]


def test_witness_detects_two_lock_cycle():
    w = witness.LockWitness(ranks={})  # unranked: pure cycle detection
    a = w.wrap(threading.Lock(), "t.a")
    b = w.wrap(threading.Lock(), "t.b")
    with a:
        with b:
            pass
    done = threading.Event()

    def other():  # opposite order from a second thread
        with b:
            with a:
                pass
        done.set()

    threading.Thread(target=other, daemon=True).start()
    assert done.wait(5.0)
    v = [f for f in w.findings() if f["kind"] == "cycle"]
    assert len(v) == 1
    assert v[0]["closing_edge"] == "t.b->t.a"
    assert v[0]["cycle"][0] == v[0]["cycle"][-1] == "t.a"


def test_witness_nonblocking_and_out_of_lifo():
    w, outer, inner = make_witness()
    assert outer.acquire(blocking=False)
    assert inner.acquire(blocking=False)
    outer.release()  # out-of-LIFO: legal, bookkeeping must survive
    inner.release()
    assert not outer.locked() and not inner.locked()
    assert w.findings() == []


def test_witness_findings_land_in_flight_dump(tmp_path):
    """Satellite 3's second half: injected violations flow through the
    existing flight recorder as a dump section."""
    from ct_mapreduce_tpu.telemetry import flight

    w, outer, inner = make_witness()
    with inner:
        with outer:
            pass
    with w.wrap(threading.Lock(), "t.a"):
        with w.wrap(threading.Lock(), "t.b"):
            pass
    flight.install(dir_path=str(tmp_path), signals=False,
                   excepthook=False)
    flight.register_section("lock_witness_selftest", w.report)
    try:
        path = flight.dump("witness self-test")
        assert path is not None
        doc = json.loads(pathlib.Path(path).read_text())
        section = doc["lock_witness_selftest"]
        assert section["violations"] and \
            section["violations"][0]["kind"] == "order"
        assert section["edge_count"] >= 2
        assert any("t.a" in a or "t.b" in a for a in section["edges"])
    finally:
        flight.unregister_section("lock_witness_selftest")
        flight.uninstall()


def test_suite_witness_wraps_package_locks():
    """End-to-end: under the conftest-installed witness, locks created
    by package code are WitnessLocks named from the lockspec site
    table."""
    w = witness.active()
    if w is None:
        pytest.skip("CTMR_LOCK_WITNESS=0 for this run")
    from ct_mapreduce_tpu.agg.aggregator import IssuerRegistry

    reg = IssuerRegistry()
    assert isinstance(reg._lock, witness.WitnessLock)
    assert reg._lock.name == "agg.registry"
    assert reg._lock.rank == lockspec.rank_of("agg.registry")


def test_lockspec_rank_table_is_consistent():
    """Every ranked decl resolves; the documented trunk order holds."""
    assert lockspec.rank_of("ingest.dispatch") < lockspec.rank_of(
        "agg.save") < lockspec.rank_of("agg.pending") < \
        lockspec.rank_of("agg.fold") < lockspec.rank_of("agg.table")
    assert lockspec.unique_attr_name("_fold_lock") == "agg.fold"
    assert lockspec.unique_attr_name("_lock") is None  # ambiguous
