"""Tier-1 value-type tests, porting the reference's semantics suite
(/root/reference/storage/types_test.go): lazy issuer IDs, serial
leading-zero preservation, JSON round-trips, expiry-bucket boundaries,
and composite-ID parsing."""

import base64
import hashlib
from datetime import datetime, timedelta, timezone

import pytest

from ct_mapreduce_tpu.core.types import (
    CertificateLog,
    ExpDate,
    Issuer,
    IssuerAndDate,
    Serial,
    SPKI,
    UniqueCertIdentifier,
    certificate_log_id_from_short_url,
)

from certgen import make_cert, spki_of


def test_issuer_id_is_b64url_sha256_of_spki():
    # types_test.go:41-57 — ID is base64url(SHA-256(SPKI)), computed lazily
    der = make_cert()
    spki = spki_of(der)
    issuer = Issuer.from_spki(spki)
    expected = base64.urlsafe_b64encode(hashlib.sha256(spki).digest()).decode()
    assert issuer.id() == expected
    assert len(issuer.id()) == 44  # 32 bytes → 44 b64 chars with padding
    # From-string constructor short-circuits the hash
    assert Issuer.from_string("abc").id() == "abc"


def test_issuer_digest_roundtrip():
    issuer = Issuer.from_spki(b"\x01\x02\x03")
    assert issuer.digest() == hashlib.sha256(b"\x01\x02\x03").digest()
    assert Issuer.from_string(issuer.id()).digest() == issuer.digest()


def test_issuer_equality_and_json():
    a = Issuer.from_spki(b"same")
    b = Issuer.from_spki(b"same")
    c = Issuer.from_spki(b"different")
    assert a == b and a != c
    assert Issuer.from_json(a.to_json()) == a


def test_spki_encodings():
    spki = SPKI(b"\x00\x01\xfe")
    assert spki.id() == base64.urlsafe_b64encode(b"\x00\x01\xfe").decode()
    assert str(spki) == "0001fe"


def test_serial_preserves_leading_zeros():
    # types_test.go:81-101 — the defining property of Serial
    raw = bytes([0x00, 0xAA, 0xBB, 0xCC])
    s = Serial.from_bytes(raw)
    assert s.binary_string() == raw
    assert s.hex_string() == "00aabbcc"
    assert Serial.from_hex("00aabbcc").binary_string() == raw
    assert Serial.from_id_string(s.id()).binary_string() == raw


def test_serial_from_der_cert_preserves_leading_zero():
    # A serial with a high bit forces DER to emit a 0x00 pad byte, which
    # must be preserved (types.go:165-178 re-parses the TBS raw bytes).
    der = make_cert(serial=0x80FFEE)
    s = Serial.from_der_cert(der)
    assert s.binary_string() == bytes([0x00, 0x80, 0xFF, 0xEE])
    assert s.as_int() == 0x80FFEE


def test_serial_json_roundtrip():
    s = Serial.from_hex("00deadbeef")
    assert s.to_json() == '"00deadbeef"'
    assert Serial.from_json(s.to_json()) == s
    with pytest.raises(ValueError):
        Serial.from_json("123")  # not a quoted string


def test_serial_ordering():
    sl = [Serial.from_hex(h) for h in ("03", "01", "0102", "00ff")]
    ordered = sorted(sl)
    assert [x.hex_string() for x in ordered] == ["00ff", "01", "0102", "03"]


def test_expdate_from_time_truncates_to_hour():
    # types.go:339-346
    t = datetime(2027, 3, 4, 5, 45, 39, 123456, tzinfo=timezone.utc)
    e = ExpDate.from_time(t)
    assert e.id() == "2027-03-04-05"
    assert e.hour_resolution
    assert e.expire_time() == datetime(2027, 3, 4, 5, tzinfo=timezone.utc)


def test_expdate_parse_day_and_hour():
    # types.go:348-365 — >10 chars tries hour format first
    day = ExpDate.parse("2027-03-04")
    assert not day.hour_resolution
    assert day.id() == "2027-03-04"
    hour = ExpDate.parse("2027-03-04-05")
    assert hour.hour_resolution
    assert hour.id() == "2027-03-04-05"


def test_expdate_is_expired_at_boundaries():
    # types_test.go:203-252 — lastGood = bucket end minus 1ms
    day = ExpDate.parse("2027-03-04")
    assert not day.is_expired_at(datetime(2027, 3, 4, 23, 59, 59, tzinfo=timezone.utc))
    assert day.is_expired_at(datetime(2027, 3, 5, 0, 0, 0, tzinfo=timezone.utc))
    hour = ExpDate.parse("2027-03-04-05")
    assert not hour.is_expired_at(datetime(2027, 3, 4, 5, 59, 59, tzinfo=timezone.utc))
    assert hour.is_expired_at(datetime(2027, 3, 4, 6, 0, 0, tzinfo=timezone.utc))


def test_expdate_unix_hour_roundtrip():
    e = ExpDate.from_time(datetime(2030, 6, 15, 7, 30, tzinfo=timezone.utc))
    assert ExpDate.from_unix_hour(e.unix_hour()).id() == e.id()


def test_expdate_parse_rejects_garbage():
    with pytest.raises(ValueError):
        ExpDate.parse("not-a-date")


def test_unique_cert_identifier_roundtrip():
    # types_test.go:254-269
    uci = UniqueCertIdentifier(
        exp_date=ExpDate.parse("2030-01-02-03"),
        issuer=Issuer.from_string("issuerXYZ"),
        serial=Serial.from_hex("00cafe"),
    )
    s = str(uci)
    assert s == f"2030-01-02-03::issuerXYZ::{Serial.from_hex('00cafe').id()}"
    parsed = UniqueCertIdentifier.parse(s)
    assert parsed.exp_date.id() == "2030-01-02-03"
    assert parsed.issuer.id() == "issuerXYZ"
    assert parsed.serial.binary_string() == bytes([0x00, 0xCA, 0xFE])
    with pytest.raises(ValueError):
        UniqueCertIdentifier.parse("only::two")


def test_issuer_and_date_roundtrip():
    iad = IssuerAndDate(
        exp_date=ExpDate.parse("2030-01-02"), issuer=Issuer.from_string("iss")
    )
    assert str(iad) == "2030-01-02/iss"
    parsed = IssuerAndDate.parse(str(iad))
    assert parsed.exp_date.id() == "2030-01-02"
    assert parsed.issuer.id() == "iss"
    with pytest.raises(ValueError):
        IssuerAndDate.parse("a/b/c")


def test_certificate_log_id_and_json():
    # types.go:25-42
    log = CertificateLog(
        short_url="ct.example.com/2027",
        max_entry=1234,
        last_entry_time=datetime(2026, 7, 1, 2, 3, 4, tzinfo=timezone.utc),
        last_update_time=datetime(2026, 7, 2, tzinfo=timezone.utc),
    )
    assert log.id() == certificate_log_id_from_short_url("ct.example.com/2027")
    assert log.id() == base64.urlsafe_b64encode(b"ct.example.com/2027").decode()
    restored = CertificateLog.from_json(log.to_json())
    assert restored.short_url == log.short_url
    assert restored.max_entry == 1234
    assert restored.last_entry_time == log.last_entry_time
    assert restored.last_update_time == log.last_update_time


def test_certificate_log_parses_go_nano_timestamps():
    # Go writes RFC3339Nano (up to 9 fractional digits)
    raw = (
        '{"ShortURL":"ct.example/x","MaxEntry":5,'
        '"LastEntryTime":"2026-07-29T12:00:00.123456789Z",'
        '"LastUpdateTime":"2026-07-29T12:00:01Z"}'
    )
    log = CertificateLog.from_json(raw)
    assert log.last_entry_time is not None
    assert log.last_entry_time.microsecond == 123456
    assert log.last_update_time is not None


def test_certificate_log_naive_datetime_is_utc():
    log = CertificateLog(short_url="x", last_entry_time=datetime(2026, 1, 1, 12))
    assert '"LastEntryTime": "2026-01-01T12:00:00Z"' in log.to_json().replace(
        '","', '", "'
    ) or "2026-01-01T12:00:00Z" in log.to_json()
