"""Membership-oracle parity fuzz (ISSUE 5 satellite): the three
``contains`` surfaces the query plane can route through —
``hashtable.contains_np``, ``buckettable.contains_np``, and the
jitted device ``contains`` of each layout — must agree lane for lane
on a shared corpus of present, absent, and just-evicted keys.

"Just-evicted" pins the rebuild hazard: keys that lived in an earlier
epoch of the table (drained away by a rebuild that reinserted only a
subset — exactly what grow-and-rehash does) must read absent
everywhere, not linger as stale positives in any one probe
implementation."""

import numpy as np
import pytest

from ct_mapreduce_tpu.ops import buckettable, hashtable


def _corpus(seed: int, n: int):
    """Random fingerprint rows split into kept / evicted / absent."""
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 2**32, size=(3 * n, 4), dtype=np.uint32)
    # Distinct rows (collisions would blur the class boundaries).
    _, first = np.unique(
        keys.view([("", np.uint32)] * 4), return_index=True)
    keys = keys[np.sort(first)]
    n = len(keys) // 3
    return keys[:n], keys[n : 2 * n], keys[2 * n : 3 * n]


def _build_open(kept, evicted, max_probes):
    """Open-addressed table holding exactly ``kept``: insert
    kept+evicted, then rebuild (fresh epoch) with only kept — the
    grow-and-rehash shape."""
    import jax.numpy as jnp

    cap = 1 << (int(len(kept) * 4).bit_length())
    meta = jnp.arange(len(kept) + len(evicted), dtype=jnp.uint32) + 1
    both = jnp.asarray(np.concatenate([kept, evicted]))
    valid = jnp.ones((both.shape[0],), bool)
    state = hashtable.make_table(cap)
    state, wu, ovf = hashtable.insert(state, both, meta, valid,
                                      max_probes=max_probes)
    assert not bool(np.asarray(ovf).any()), "corpus overflowed; raise cap"
    state2 = hashtable.make_table(cap)
    state2, wu, ovf = hashtable.insert(
        state2, jnp.asarray(kept), meta[: len(kept)],
        valid[: len(kept)], max_probes=max_probes)
    assert bool(np.asarray(wu).all()) and not bool(np.asarray(ovf).any())
    return state2


def _build_bucket(kept, evicted, max_probes):
    import jax.numpy as jnp

    cap = int(len(kept) * 4)
    meta = jnp.arange(len(kept) + len(evicted), dtype=jnp.uint32) + 1
    both = jnp.asarray(np.concatenate([kept, evicted]))
    valid = jnp.ones((both.shape[0],), bool)
    state = buckettable.make_table(cap)
    state, wu, ovf = buckettable.insert(state, both, meta, valid,
                                        max_probes=max_probes)
    assert not bool(np.asarray(ovf).any()), "corpus overflowed; raise cap"
    state2 = buckettable.make_table(cap)
    state2, wu, ovf = buckettable.insert(
        state2, jnp.asarray(kept), meta[: len(kept)],
        valid[: len(kept)], max_probes=max_probes)
    assert bool(np.asarray(wu).all()) and not bool(np.asarray(ovf).any())
    return state2


# Seeds 17/91 are @slow since round 15 (tier-1 budget banking, ISSUE
# 10): three seeds of one fuzz sweep walk the same layout/device code
# paths — seed 3 keeps the tier-1 gate, the redundant re-rolls run in
# the full (unmarked) suite.
@pytest.mark.parametrize("seed", [
    3,
    pytest.param(17, marks=pytest.mark.slow),
    pytest.param(91, marks=pytest.mark.slow),
])
def test_contains_parity_open_vs_bucket_vs_device(seed):
    max_probes = 32
    kept, evicted, absent = _corpus(seed, 512)
    probe = np.concatenate([kept, evicted, absent])
    want = np.concatenate([
        np.ones((len(kept),), bool),
        np.zeros((len(evicted) + len(absent),), bool),
    ])

    open_state = _build_open(kept, evicted, max_probes)
    bucket_state = _build_bucket(kept, evicted, max_probes)

    import jax.numpy as jnp

    results = {
        "hashtable.contains_np": hashtable.contains_np(
            np.asarray(open_state.rows), probe, max_probes=max_probes),
        "hashtable.contains": np.asarray(hashtable.contains(
            open_state, jnp.asarray(probe), max_probes=max_probes)),
        "buckettable.contains_np": buckettable.contains_np(
            np.asarray(bucket_state.rows), probe, max_probes=max_probes),
        "buckettable.contains": np.asarray(buckettable.contains(
            bucket_state, jnp.asarray(probe), max_probes=max_probes)),
    }
    for name, got in results.items():
        miss = np.nonzero(got != want)[0]
        assert miss.size == 0, (
            f"{name} disagrees with ground truth on {miss.size} lanes "
            f"(first at {miss[:5]}; lane classes: kept<{len(kept)}, "
            f"evicted<{len(kept) + len(evicted)}, then absent)")


def test_contains_parity_within_batch_duplicates():
    """Duplicate probe lanes (the batcher coalesces independent
    requests, so the same key can appear many times in one contains
    batch) answer identically on every surface."""
    max_probes = 32
    kept, evicted, absent = _corpus(23, 128)
    open_state = _build_open(kept, evicted, max_probes)
    bucket_state = _build_bucket(kept, evicted, max_probes)
    rng = np.random.default_rng(5)
    pool = np.concatenate([kept, evicted, absent])
    pick = rng.integers(0, len(pool), size=1024)
    probe = pool[pick]
    want = pick < len(kept)

    import jax.numpy as jnp

    for name, got in (
        ("open np", hashtable.contains_np(
            np.asarray(open_state.rows), probe, max_probes=max_probes)),
        ("open dev", np.asarray(hashtable.contains(
            open_state, jnp.asarray(probe), max_probes=max_probes))),
        ("bucket np", buckettable.contains_np(
            np.asarray(bucket_state.rows), probe, max_probes=max_probes)),
        ("bucket dev", np.asarray(buckettable.contains(
            bucket_state, jnp.asarray(probe), max_probes=max_probes))),
    ):
        assert np.array_equal(got, want), f"{name} diverged"


def test_contains_parity_sharded_view():
    """The sharded global contains (device) vs the query plane's
    routed host probe (shard_of_np + per-block contains_np) on the
    same sharded rows."""
    import jax
    import jax.numpy as jnp

    from ct_mapreduce_tpu.agg import sharded

    max_probes = 32
    n_shards = len(jax.devices())
    kept, evicted, absent = _corpus(41, 256)
    # Build per-shard open tables by routing, then concatenate blocks —
    # the layout ShardedDedup's row array has.
    cap_loc = 1 << int((len(kept) * 4 // n_shards).bit_length())
    blocks = []
    dest = sharded.shard_of_np(kept, n_shards)
    for s in range(n_shards):
        state = hashtable.make_table(cap_loc)
        sel = kept[dest == s]
        if len(sel):
            state, wu, ovf = hashtable.insert(
                state, jnp.asarray(sel),
                jnp.arange(len(sel), dtype=jnp.uint32) + 1,
                jnp.ones((len(sel),), bool), max_probes=max_probes)
            assert not bool(np.asarray(ovf).any())
        blocks.append(np.asarray(state.rows))
    rows = np.concatenate(blocks)

    probe = np.concatenate([kept, evicted, absent])
    want = np.concatenate([
        np.ones((len(kept),), bool),
        np.zeros((len(evicted) + len(absent),), bool),
    ])
    dev = np.asarray(sharded._contains_global(
        jnp.asarray(rows), jnp.asarray(probe),
        n_shards=n_shards, max_probes=max_probes))
    # The routed host probe, as the query plane's sharded view runs it.
    dest_p = sharded.shard_of_np(probe, n_shards)
    host = np.zeros((len(probe),), bool)
    for s in np.unique(dest_p):
        sel = dest_p == s
        host[sel] = hashtable.contains_np(
            rows[s * cap_loc : (s + 1) * cap_loc], probe[sel],
            max_probes=max_probes)
    assert np.array_equal(dev, want)
    assert np.array_equal(host, want)
