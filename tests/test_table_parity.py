"""Layout equivalence: both dedup tables implement the same contract.

The bucketized table (ops/buckettable.py) and the slot-granular table
(ops/hashtable.py) must be observationally identical wherever neither
overflows: same was_unknown bits (first-in-batch-order for duplicates,
the reference's sequential SADD semantics), same counts, same
membership answers — across random batches with duplicates, invalid
lanes, and re-inserts. Runs the same op sequence through both layouts
and a plain Python-set oracle.
"""

import numpy as np

from ct_mapreduce_tpu.ops import buckettable as bt
from ct_mapreduce_tpu.ops import hashtable as ht


def test_random_workload_equivalence():
    rng = np.random.default_rng(42)
    cap = 1 << 11  # plenty of room: no overflow in either layout
    s_open = ht.make_table(cap)
    s_bkt = bt.make_table(cap)
    oracle: set = set()
    pool = rng.integers(0, 2**32, size=(500, 4), dtype=np.uint32)

    for round_i in range(8):
        n = int(rng.integers(32, 200))
        pick = rng.integers(0, len(pool), size=n)
        # Fixed 256-lane insert width (padding lanes invalid): the
        # random round sizes still vary through the valid mask, but
        # the jitted inserts compile ONCE per layout instead of once
        # per ragged n — this test was ~22 s of compiles on the CPU
        # CI box with per-round shapes.
        pad = 256
        keys = np.zeros((pad, 4), np.uint32)
        keys[:n] = pool[pick]
        meta = np.zeros((pad,), np.uint32)
        meta[:n] = rng.integers(0, 2**16, size=n).astype(np.uint32)
        valid = np.zeros((pad,), bool)
        valid[:n] = rng.random(n) > 0.1

        s_open, u_open, o_open = ht.insert(s_open, keys, meta, valid)
        s_bkt, u_bkt, o_bkt = bt.insert(s_bkt, keys, meta, valid)
        u_open, u_bkt = np.asarray(u_open)[:n], np.asarray(u_bkt)[:n]
        assert not np.asarray(o_open).any()
        assert not np.asarray(o_bkt).any()
        # Bit-for-bit agreement on who reports unknown...
        assert (u_open == u_bkt).all(), round_i
        # ...and both match the sequential-set oracle.
        batch_first = set()
        for i in range(n):
            t = tuple(int(x) for x in keys[i])
            expect = valid[i] and t not in oracle and t not in batch_first
            assert bool(u_bkt[i]) == expect, (round_i, i)
            if valid[i]:
                batch_first.add(t)
        oracle.update(
            tuple(int(x) for x in keys[i]) for i in range(n) if valid[i]
        )
        assert int(s_open.count) == int(s_bkt.count) == len(oracle)

    # Membership parity on members and non-members alike.
    probe = np.concatenate(
        [pool[:200], rng.integers(0, 2**32, size=(64, 4), dtype=np.uint32)])
    got_open = np.asarray(ht.contains(s_open, probe))
    got_bkt = np.asarray(bt.contains(s_bkt, probe))
    want = np.array(
        [tuple(int(x) for x in k) in oracle for k in probe])
    assert (got_open == want).all()
    assert (got_bkt == want).all()

    # Drained contents agree exactly (keys and meta).
    ko, mo = ht.drain_np(s_open)
    kb, mb = bt.drain_np(s_bkt)
    as_map = lambda k, m: {  # noqa: E731
        tuple(int(x) for x in kk): int(mm) for kk, mm in zip(k, m)}
    assert as_map(ko, mo) == as_map(kb, mb)
