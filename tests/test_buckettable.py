"""Bucketized dedup table: Redis SADD semantics on device, sort-based.

Parity oracle is a plain Python set — the same oracle the slot-granular
table uses (tests/test_hashtable.py), because both implement the
reference's WasUnknown contract
(/root/reference/storage/knowncertificates.go:38-55). Extra coverage
targets the bucket layout's own edges: full buckets hopping at bucket
granularity, window-limited merges needing extra rounds, contiguous
slot fill, and the cross-layout checkpoint positions.
"""

import numpy as np
import pytest

from ct_mapreduce_tpu.ops import buckettable as bt


def rand_keys(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**32, size=(n, 4), dtype=np.uint32)


def as_tuple(k):
    return tuple(int(x) for x in k)


def test_make_table_rounds_up_to_buckets():
    state = bt.make_table(1)
    assert state.n_buckets == 1 and state.capacity == bt.SLOTS
    state = bt.make_table(bt.SLOTS + 1)
    assert state.n_buckets == 2
    state = bt.make_table(1 << 10)
    assert state.capacity >= 1 << 10
    assert state.n_buckets & (state.n_buckets - 1) == 0


def test_insert_then_reinsert():
    state = bt.make_table(256)
    keys = rand_keys(16)
    valid = np.ones(16, bool)
    meta = np.arange(16, dtype=np.uint32)
    state, unknown, overflow = bt.insert(state, keys, meta, valid)
    assert np.asarray(unknown).all()
    assert not np.asarray(overflow).any()
    assert int(state.count) == 16
    state, unknown2, overflow2 = bt.insert(state, keys, meta, valid)
    assert not np.asarray(unknown2).any()
    assert not np.asarray(overflow2).any()
    assert int(state.count) == 16


def test_within_batch_duplicates_first_lane_wins():
    state = bt.make_table(256)
    base = rand_keys(4, seed=1)
    keys = np.concatenate([base, base, base[:2]])
    valid = np.ones(len(keys), bool)
    meta = np.zeros(len(keys), np.uint32)
    state, unknown, _ = bt.insert(state, keys, meta, valid)
    unknown = np.asarray(unknown)
    assert unknown.sum() == 4
    # The FIRST lane in batch order of each distinct key reports
    # unknown (lane is the sort tiebreak — reference semantics are
    # sequential first-writer-wins).
    first = {}
    for i, k in enumerate(keys):
        first.setdefault(as_tuple(k), i)
    for i, k in enumerate(keys):
        assert unknown[i] == (first[as_tuple(k)] == i)
    assert int(state.count) == 4


def test_invalid_lanes_ignored():
    state = bt.make_table(64)
    keys = rand_keys(8, seed=2)
    valid = np.array([True, False] * 4)
    meta = np.zeros(8, np.uint32)
    state, unknown, _ = bt.insert(state, keys, meta, valid)
    unknown = np.asarray(unknown)
    assert unknown[valid].all()
    assert not unknown[~valid].any()
    assert int(state.count) == 4


def test_invalid_then_valid_same_key():
    state = bt.make_table(64)
    k = rand_keys(1, seed=3)
    keys = np.concatenate([k, k])
    valid = np.array([False, True])
    meta = np.zeros(2, np.uint32)
    state, unknown, _ = bt.insert(state, keys, meta, valid)
    assert list(np.asarray(unknown)) == [False, True]
    assert int(state.count) == 1


def test_parity_vs_python_set_across_batches():
    state = bt.make_table(1 << 9)  # 32 buckets — real collisions
    oracle = set()
    rng = np.random.default_rng(7)
    pool = rand_keys(600, seed=8)
    for r in range(6):
        pick = rng.integers(0, len(pool), size=128)
        keys = pool[pick]
        meta = np.zeros(len(keys), np.uint32)
        valid = np.ones(len(keys), bool)
        state, unknown, overflow = bt.insert(state, keys, meta, valid)
        unknown, overflow = np.asarray(unknown), np.asarray(overflow)
        batch_first = set()
        for i, k in enumerate(keys):
            t = as_tuple(k)
            if overflow[i]:
                continue
            expect = t not in oracle and t not in batch_first
            assert bool(unknown[i]) == expect, (r, i)
            batch_first.add(t)
        oracle.update(
            as_tuple(k) for i, k in enumerate(keys) if not overflow[i]
        )
        assert not overflow.any()  # plenty of buckets for 600 keys
    assert int(state.count) == len(oracle)


def test_full_bucket_hops_then_overflows():
    # Single bucket: 24 slots. All keys hash to bucket 0 (nb=1), so
    # keys 25.. must hop — and with nowhere to hop (nb=1, hop wraps to
    # the same full bucket), they overflow to the host lane.
    state = bt.make_table(bt.SLOTS)
    keys = rand_keys(40, seed=9)
    meta = np.arange(40, dtype=np.uint32)
    valid = np.ones(40, bool)
    state, unknown, overflow = bt.insert(
        state, keys, meta, valid, max_probes=4)
    unknown, overflow = np.asarray(unknown), np.asarray(overflow)
    assert unknown.sum() == bt.SLOTS
    assert overflow.sum() == 40 - bt.SLOTS
    assert not (unknown & overflow).any()
    assert int(state.count) == bt.SLOTS
    # The table still answers membership exactly for what it holds.
    got = np.asarray(bt.contains(state, keys))
    assert (got == unknown).all()


def test_hop_chain_spills_to_next_bucket():
    # Two buckets; over-fill bucket h of each key's home so spill keys
    # land in the neighbor and contains() follows the hop chain.
    state = bt.make_table(2 * bt.SLOTS)
    keys = rand_keys(2 * bt.SLOTS + 10, seed=11)
    meta = np.zeros(len(keys), np.uint32)
    valid = np.ones(len(keys), bool)
    state, unknown, overflow = bt.insert(state, keys, meta, valid)
    unknown, overflow = np.asarray(unknown), np.asarray(overflow)
    # Everything fits (48 slots, 58 keys → 48 inserted, 10 overflow)
    assert unknown.sum() == 2 * bt.SLOTS
    assert overflow.sum() == 10
    got = np.asarray(bt.contains(state, keys))
    assert (got == unknown).all()
    got_np = bt.contains_np(np.asarray(state.rows), keys)
    assert (got_np == unknown).all()


def test_window_limited_merge_retries_resolve():
    # More distinct new keys in one bucket in one batch than WINDOW:
    # later key-heads must retry (same bucket, next round) and still
    # land, with contiguous fill.
    state = bt.make_table(bt.SLOTS)  # nb=1: every key same bucket
    n = bt.SLOTS  # 24 distinct > WINDOW (8)
    keys = rand_keys(n, seed=12)
    meta = np.arange(n, dtype=np.uint32)
    valid = np.ones(n, bool)
    state, unknown, overflow = bt.insert(state, keys, meta, valid)
    assert np.asarray(unknown).all()
    assert not np.asarray(overflow).any()
    assert int(state.count) == n
    rows = np.asarray(state.rows)
    slots = rows[:, : bt.SLOTS * 5].reshape(-1, bt.SLOTS, 5)
    occ = slots[0, :, :4].any(axis=-1)
    assert occ.all()  # contiguous fill to exactly SLOTS


def test_zero_key_desentinel():
    state = bt.make_table(64)
    keys = np.zeros((2, 4), np.uint32)
    meta = np.zeros(2, np.uint32)
    valid = np.ones(2, bool)
    state, unknown, _ = bt.insert(state, keys, meta, valid)
    assert list(np.asarray(unknown)) == [True, False]
    assert int(state.count) == 1
    assert np.asarray(bt.contains(state, np.zeros((1, 4), np.uint32)))[0]


def test_meta_round_trips_through_drain():
    state = bt.make_table(512)
    keys = rand_keys(50, seed=13)
    meta = np.arange(50, dtype=np.uint32) + 1000
    valid = np.ones(50, bool)
    state, _, _ = bt.insert(state, keys, meta, valid)
    dkeys, dmeta = bt.drain_np(state)
    assert dkeys.shape == (50, 4)
    got = {as_tuple(k): int(m) for k, m in zip(dkeys, dmeta)}
    want = {as_tuple(k): int(m) for k, m in zip(keys, meta)}
    assert got == want


def test_bulk_insert_np_matches_device_contains():
    state = bt.make_table(1 << 9)
    rows = np.asarray(state.rows).copy()
    keys = rand_keys(200, seed=14)
    meta = np.arange(200, dtype=np.uint32)
    left = bt.bulk_insert_np(rows, keys, meta)
    assert left == 0
    state = bt.BucketTable(rows=rows, count=np.int32(200))
    assert bt.contains_np(rows, keys).all()
    assert not bt.contains_np(rows, rand_keys(64, seed=15)).any()
    # Device insert of the same keys sees them as known.
    import jax.numpy as jnp

    dstate = bt.BucketTable(rows=jnp.asarray(rows),
                            count=jnp.asarray(np.int32(200)))
    dstate, unknown, _ = bt.insert(
        dstate, keys[:64], meta[:64], np.ones(64, bool))
    assert not np.asarray(unknown).any()


def test_checkpoint_slot_positions_reconstruct():
    # keys/meta positional views → rebuild rows → identical behavior
    # (the aggregator checkpoint codec round-trip, layout="bucket").
    state = bt.make_table(256)
    keys = rand_keys(60, seed=16)
    meta = np.arange(60, dtype=np.uint32)
    state, _, _ = bt.insert(state, keys, meta, np.ones(60, bool))
    k = np.asarray(state.keys)
    m = np.asarray(state.meta)
    nb = state.n_buckets
    rows = np.zeros((nb, bt.ROW_WORDS), np.uint32)
    fused = np.concatenate([k, m[:, None]], axis=1)
    rows[:, : bt.SLOTS * 5] = fused.reshape(nb, -1)
    # The codec's restore recomputes the cached fill word (positional
    # keys/meta don't carry it) — mirror that before comparing.
    bt.fill_counts_np(rows)
    assert (rows == np.asarray(state.rows)).all()


def test_pipeline_dispatch_picks_bucket_insert():
    from ct_mapreduce_tpu.ops import pipeline

    state = bt.make_table(256)
    keys = rand_keys(8, seed=17)
    meta = np.zeros(8, np.uint32)
    state2, unknown, _ = pipeline.table_insert(
        state, keys, meta, np.ones(8, bool))
    assert isinstance(state2, bt.BucketTable)
    assert np.asarray(unknown).all()


def test_skewed_flood_single_key():
    # A whole batch of one repeated key: one True, rest False, one slot.
    state = bt.make_table(64)
    keys = np.tile(rand_keys(1, seed=18), (256, 1))
    meta = np.zeros(256, np.uint32)
    state, unknown, overflow = bt.insert(
        state, keys, meta, np.ones(256, bool))
    unknown = np.asarray(unknown)
    assert unknown.sum() == 1 and unknown[0]
    assert not np.asarray(overflow).any()
    assert int(state.count) == 1
