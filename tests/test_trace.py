"""Span tracer (telemetry/trace.py): Chrome trace-event JSON schema,
span nesting, ring bound, the disabled fast path, and the overlap
pipeline's per-stage spans summarized by tools/traceview.py."""

import json
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.telemetry import trace  # noqa: E402
from tools import traceview  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_tracer():
    prev = trace.get_tracer()
    trace.disable()
    yield
    trace._tracer = prev


def _validate_schema(events):
    """The subset of the Trace Event Format this repo emits: complete
    spans (X: ts+dur), instants (i), thread metadata (M)."""
    assert events, "empty trace"
    for e in events:
        assert e["ph"] in ("X", "i", "M"), e
        assert isinstance(e["name"], str) and e["name"]
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        elif e["ph"] == "i":
            assert isinstance(e["ts"], (int, float))
        else:  # M
            assert e["name"] == "thread_name"
            assert isinstance(e["args"]["name"], str)


def _validate_nesting(events):
    """Per-tid stack discipline: any two X spans on a thread are
    disjoint or properly contained (what thread-local begin/end
    guarantees; Perfetto renders anything else as corrupt)."""
    by_tid = {}
    for e in events:
        if e.get("ph") == "X":
            by_tid.setdefault(e["tid"], []).append(e)
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in evs:
            end = e["ts"] + e["dur"]
            while stack and stack[-1] <= e["ts"]:
                stack.pop()
            if stack:
                assert end <= stack[-1] + 1e-3, (
                    f"tid {tid}: span {e['name']} crosses its parent")
            stack.append(end)


def test_export_schema_and_nesting(tmp_path):
    trace.enable(ring_size=1024)

    def worker(k):
        with trace.span("outer", cat="test", k=k):
            with trace.span("mid"):
                with trace.span("inner"):
                    time.sleep(0.002)
            trace.instant("tick", k=k)

    threads = [threading.Thread(target=worker, args=(k,), name=f"w{k}")
               for k in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    worker(99)  # main thread too

    path = str(tmp_path / "trace.json")
    assert trace.export(path) == path
    doc = json.load(open(path))
    assert "traceEvents" in doc
    events = doc["traceEvents"]
    _validate_schema(events)
    _validate_nesting(events)
    # 4 workers x 3 spans, 4 instants, >= 4 thread-name records.
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == 12
    assert {e["name"] for e in xs} == {"outer", "mid", "inner"}
    assert sum(1 for e in events if e["ph"] == "i") == 4
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"w0", "w1", "w2"} <= names
    # Span args survive the round trip.
    assert any(e.get("args", {}).get("k") == 99 for e in xs)


def test_disabled_is_shared_noop():
    """Tracing off: span() returns the SAME no-op object every call —
    no allocation on the hot path, nothing recorded."""
    assert not trace.enabled()
    a, b = trace.span("x"), trace.span("y", cat="c", k=1)
    assert a is b
    with a:
        pass
    trace.instant("nothing")
    assert trace.snapshot_events() == []
    assert trace.export() is None
    assert trace.now_us() == 0.0


def test_ring_bound():
    """The event ring is bounded (a week-long run cannot grow without
    limit) and keeps the newest window."""
    trace.enable(ring_size=32)
    for i in range(200):
        with trace.span("s", i=i):
            pass
    xs = [e for e in trace.snapshot_events() if e["ph"] == "X"]
    assert len(xs) == 32
    assert xs[-1]["args"]["i"] == 199
    assert xs[0]["args"]["i"] == 168


def test_enable_idempotent_keeps_ring():
    trace.enable(ring_size=64)
    with trace.span("kept"):
        pass
    t2 = trace.enable(path="/tmp/whatever.json")
    assert any(e["name"] == "kept" for e in t2.events())
    assert t2.path == "/tmp/whatever.json"


class _FakeSink:
    """Stage stand-in with the duck-typed surface the overlap scheduler
    drives, each stage sleeping so spans have real extent."""

    def __init__(self):
        self._dispatch_lock = threading.Lock()
        self.completed = []

    def _prepare_chunk(self, pairs):
        time.sleep(0.02)
        return pairs

    def _submit_chunk(self, prep):
        time.sleep(0.01)
        return [("pending", prep, None)]

    def _complete_item(self, payload, der_of):
        time.sleep(0.015)
        self.completed.append(payload)

    def _store_pems(self, payload, der_of):
        pass


def test_overlap_pipeline_stage_spans_and_traceview(tmp_path):
    """The pipeline's decode/submit/drain spans land in the trace, and
    tools/traceview.py summarizes them into per-stage occupancy that
    shows the stages actually overlapping (busy sum > wall)."""
    from ct_mapreduce_tpu.ingest.overlap import OverlapIngestPipeline

    trace.enable(ring_size=4096)
    sink = _FakeSink()
    pipe = OverlapIngestPipeline(sink, decode_workers=2, queue_depth=2)
    n_chunks = 6
    for i in range(n_chunks):
        pipe.submit_chunk([("li", "ed")] * 4)
    pipe.drain_all()
    pipe.close()
    assert len(sink.completed) == n_chunks

    path = str(tmp_path / "overlap.json")
    trace.export(path)
    events = traceview.load(path)
    _validate_schema(events)
    _validate_nesting(events)
    summary = traceview.stage_summary(
        events, stages=("ingest.decode", "ingest.submit", "ingest.drain"))
    wall = summary.pop("_wall_s")
    assert set(summary) == {"ingest.decode", "ingest.submit",
                            "ingest.drain"}
    busy = 0.0
    for name, s in summary.items():
        assert s["count"] == n_chunks, (name, s)
        assert s["busy_s"] > 0
        busy += s["busy_s"]
    # Two decode workers ran ahead of submit/drain: total stage busy
    # exceeds the wall clock — the overlap, read straight off the
    # trace (the serialized sum here is ~0.045s x 6 vs ~0.02s x 3 + e).
    assert busy > wall * 1.05, (busy, wall)
    # The submit span nests inside the submit_locked envelope.
    locked = traceview.stage_summary(events,
                                     stages=("ingest.submit_locked",))
    assert locked["ingest.submit_locked"]["count"] == n_chunks
    assert (locked["ingest.submit_locked"]["busy_s"]
            >= summary["ingest.submit"]["busy_s"] * 0.9)


def test_traceview_cli(tmp_path, capsys):
    trace.enable(ring_size=256)
    for _ in range(3):
        with trace.span("stage.a"):
            time.sleep(0.002)
        with trace.span("stage.b"):
            pass
    path = str(tmp_path / "cli.json")
    trace.export(path)
    assert traceview.main([path]) == 0
    out = capsys.readouterr().out
    assert "stage.a" in out and "stage.b" in out
    assert "trace wall:" in out
