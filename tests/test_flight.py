"""Flight recorder (telemetry/flight.py): a wedged/crashed run leaves
a post-mortem artifact — last trace spans + metric snapshots — on an
injected drain-stage failure (the OverlapError latch), on signals, and
via the excepthook."""

import base64
import datetime
import json
import os
import signal

import pytest

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.ingest.overlap import OverlapError
from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
from ct_mapreduce_tpu.telemetry import flight, metrics, trace
from ct_mapreduce_tpu.utils import minicert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2025, 1, 1, tzinfo=UTC)

ISSUER = minicert.make_cert(serial=1, issuer_cn="Flight CA", is_ca=True)


def wire_batch(start: int, n: int) -> RawBatch:
    lis, eds = [], []
    for j in range(n):
        leaf = minicert.make_cert(
            serial=start + j, issuer_cn="Flight CA",
            subject_cn="flight.example", is_ca=False,
        )
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(leaf, 1000 + start + j)).decode())
        eds.append(base64.b64encode(
            leaflib.encode_extra_data([ISSUER])).decode())
    return RawBatch(lis, eds, start, "flight-log")


@pytest.fixture(autouse=True)
def _clean_recorder():
    prev_tracer = trace.get_tracer()
    yield
    flight.uninstall()
    trace._tracer = prev_tracer
    metrics.set_sink(metrics.InMemSink())


def test_drain_failure_leaves_flight_dump(tmp_path):
    """Injected exception in the drain stage mid-ingest: the overlap
    pipeline latches OverlapError AND the flight recorder writes a dump
    containing the last spans and a metric snapshot."""
    trace.disable()
    trace.enable(ring_size=4096)
    metrics.set_sink(metrics.InMemSink())
    rec = flight.install(str(tmp_path), signals=False, excepthook=False)

    agg = TpuAggregator(capacity=1 << 12, batch_size=32, now=NOW)
    sink = AggregatorSink(agg, flush_size=32, device_queue_depth=2,
                          overlap_workers=2)
    boom = RuntimeError("drain stage exploded")
    calls = {"n": 0}
    orig_complete = sink._complete_item

    def failing_complete(pending, der_of):
        calls["n"] += 1
        if calls["n"] == 2:
            raise boom
        orig_complete(pending, der_of)

    sink._complete_item = failing_complete
    with pytest.raises(OverlapError) as exc_info:
        for i in range(4):
            sink.store_raw_batch(wire_batch(i * 32, 32))
        sink.flush()
    assert exc_info.value.__cause__ is boom

    assert rec.dumps, "no flight dump written on drain failure"
    doc = json.load(open(rec.dumps[0]))
    assert "drain stage exploded" in doc["reason"]
    assert doc["pid"] == os.getpid()
    # The last spans are in the artifact — including the ingest stages
    # that ran before the failure and the latch instant itself.
    names = {e["name"] for e in doc["trace_events"]}
    assert "ingest.decode" in names
    assert "ingest.drain" in names
    assert "overlap.stage_error" in names
    # ... and a metric snapshot taken at dump time.
    assert doc["current_metrics"] is not None
    counters = doc["current_metrics"]["counters"]
    assert counters.get("overlap.stage_error") == 1
    with pytest.raises(OverlapError):
        sink.close()
    # The latch dumps ONCE (the close() re-raise must not write a
    # second artifact for the same failure).
    assert len(rec.dumps) == 1


def test_snapshot_ring_is_bounded_and_in_dump(tmp_path):
    metrics.set_sink(metrics.InMemSink())
    rec = flight.install(str(tmp_path), max_snapshots=4, signals=False,
                         excepthook=False)
    for i in range(10):
        metrics.incr_counter("tick", value=1)
        flight.record_snapshot()
    path = flight.dump("manual")
    doc = json.load(open(path))
    snaps = doc["metric_snapshots"]
    assert len(snaps) == 4  # last N only
    # Newest-window: the final retained snapshot saw all 10 ticks.
    assert snaps[-1]["metrics"]["counters"]["tick"] == 10
    assert snaps[0]["metrics"]["counters"]["tick"] == 7


def test_dump_noop_when_not_installed(tmp_path):
    assert not flight.installed()
    assert flight.dump("nobody listening") is None
    flight.record_snapshot()  # no-op, no raise
    assert list(tmp_path.iterdir()) == []


def test_sigusr1_dumps_without_dying(tmp_path):
    rec = flight.install(str(tmp_path), signals=True, excepthook=False)
    os.kill(os.getpid(), signal.SIGUSR1)
    # Signal delivery is synchronous for the main thread on the next
    # bytecode boundary; the dump happened and we are still alive.
    assert rec.dumps and os.path.exists(rec.dumps[-1])
    doc = json.load(open(rec.dumps[-1]))
    assert "signal" in doc["reason"]


def test_excepthook_chains_and_dumps(tmp_path):
    rec = flight.install(str(tmp_path), signals=False, excepthook=True)
    seen = {}
    prev = flight._prev_excepthook

    def spy(exc_type, exc, tb):
        seen["exc"] = exc

    flight._prev_excepthook = spy
    try:
        import sys

        err = ValueError("unhandled crash")
        sys.excepthook(ValueError, err, None)
        assert seen["exc"] is err  # chained to the previous hook
        assert rec.dumps
        assert "unhandled crash" in json.load(open(rec.dumps[0]))["reason"]
    finally:
        flight._prev_excepthook = prev
