"""Device SHA-256 vs hashlib (the kernel-parity tier, SURVEY.md §4)."""

import hashlib

import numpy as np
import pytest

from ct_mapreduce_tpu.ops import sha256 as dsha


def _ref(msg: bytes) -> bytes:
    return hashlib.sha256(msg).digest()


@pytest.mark.parametrize(
    "msg",
    [
        b"",
        b"abc",
        b"a" * 55,  # max single-block payload
        b"a" * 56,  # first length that spills to 2 blocks
        b"a" * 64,
        b"hello world" * 13,
        bytes(range(256)) * 5,
    ],
)
def test_blocks_matches_hashlib(msg):
    blocks = dsha.pad_message_np(msg)[None, ...]
    out = np.asarray(dsha.sha256_blocks(blocks))
    assert dsha.digest_np(out[0]) == _ref(msg)


def test_batched_blocks():
    msgs = [b"x" * i for i in range(0, 50, 7)]
    blocks = np.stack([dsha.pad_message_np(m, total_blocks=1) for m in msgs])
    out = np.asarray(dsha.sha256_blocks(blocks))
    for i, m in enumerate(msgs):
        assert dsha.digest_np(out[i]) == _ref(m)


def test_var_blocks():
    msgs = [b"", b"q" * 30, b"r" * 70, b"s" * 200, b"t" * 119]
    nmax = 4
    blocks = np.stack([dsha.pad_message_np(m, total_blocks=nmax) for m in msgs])
    n_blocks = np.array([len(dsha.pad_message_np(m)) for m in msgs], dtype=np.int32)
    out = np.asarray(dsha.sha256_var_blocks(blocks, n_blocks))
    for i, m in enumerate(msgs):
        assert dsha.digest_np(out[i]) == _ref(m)


def test_single_block_and_fingerprint():
    msg = b"fingerprint-me" * 3  # 42 bytes, single block
    block = dsha.pad_message_np(msg, total_blocks=1)[0][None, ...]
    full = np.asarray(dsha.sha256_single_block(block))
    assert dsha.digest_np(full[0]) == _ref(msg)
    fp = np.asarray(dsha.sha256_fingerprint64(block))
    assert dsha.digest_np(full[0])[16:] == np.asarray(fp[0], dtype=">u4").tobytes()
