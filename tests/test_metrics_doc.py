"""Metric-name registry check: every metric key emitted anywhere in
ct_mapreduce_tpu/ must be documented in docs/METRICS.md (the
name-stability contract of telemetry/metrics.py:8-10, made
enforceable — prevents silent dashboard drift).

Round 16: the AST walk that used to live here is now the framework's
``metric-registry`` checker (ct_mapreduce_tpu/analysis/
metric_registry.py) — one walker shared by this gate and the
``ctmrlint`` CLI; these tests are thin assertions over its findings,
split by direction so a failure names the drift kind."""

import pathlib

from ct_mapreduce_tpu.analysis.engine import AnalysisEngine
from ct_mapreduce_tpu.analysis.metric_registry import MetricRegistryChecker

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "ct_mapreduce_tpu"


def run_registry_check():
    checker = MetricRegistryChecker()
    AnalysisEngine([checker]).run(PKG)
    return checker


def test_every_emitted_key_is_documented():
    checker = run_registry_check()
    assert checker.call_sites, (
        "AST walk found no metric call sites — checker broken?")
    missing = [f for f in checker.findings
               if not f.symbol.startswith("stale:")]
    assert not missing, (
        "metric keys emitted but missing from docs/METRICS.md "
        "(add them there — dashboards key on these names):\n"
        + "\n".join(f"  {f.render()}" for f in missing)
    )


def test_documented_keys_still_emitted():
    """The reverse direction: a documented key no one emits anymore is
    stale. Kept strict — deleting a metric must update the registry
    too."""
    checker = run_registry_check()
    stale = [f for f in checker.findings if f.symbol.startswith("stale:")]
    assert not stale, (
        "docs/METRICS.md lists keys no call site emits (stale entries):\n"
        + "\n".join(f"  {f.render()}" for f in stale)
    )
