"""Metric-name registry check: every metric key emitted anywhere in
ct_mapreduce_tpu/ must be documented in docs/METRICS.md (the
name-stability contract of telemetry/metrics.py:8-10, made
enforceable — prevents silent dashboard drift)."""

import ast
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "ct_mapreduce_tpu"
DOC = REPO / "docs" / "METRICS.md"

EMIT_FUNCS = {"incr_counter", "set_gauge", "add_sample", "measure"}


def call_site_keys() -> dict[str, list[str]]:
    """Dotted key pattern -> ["path:line", ...] for every metric-emit
    call in the package; non-literal argument segments become ``*``."""
    keys: dict[str, list[str]] = {}
    for path in sorted(PKG.rglob("*.py")):
        if path.name == "metrics.py":
            continue  # the emit API itself, not a call site
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in EMIT_FUNCS or not node.args:
                continue
            parts = [
                a.value
                if isinstance(a, ast.Constant) and isinstance(a.value, str)
                else "*"
                for a in node.args
            ]
            where = f"{path.relative_to(REPO)}:{node.lineno}"
            keys.setdefault(".".join(parts), []).append(where)
    return keys


def documented_keys() -> set[str]:
    """Backtick-quoted keys from the registry's bullet lines."""
    keys = set()
    for line in DOC.read_text().splitlines():
        m = re.match(r"- `([^`]+)`", line.strip())
        if m:
            keys.add(m.group(1))
    return keys


def _matches(call_key: str, doc_key: str) -> bool:
    """Wildcards may sit on either side: a dynamic call segment (``*``
    from an f-string/variable) matches a doc wildcard, and a doc
    wildcard covers literal call keys."""
    call_re = re.escape(call_key).replace(r"\*", ".*")
    doc_re = re.escape(doc_key).replace(r"\*", ".*")
    return (re.fullmatch(call_re, doc_key) is not None
            or re.fullmatch(doc_re, call_key) is not None)


def test_every_emitted_key_is_documented():
    emitted = call_site_keys()
    assert emitted, "AST walk found no metric call sites — test broken?"
    docs = documented_keys()
    assert docs, f"{DOC} lists no keys — format changed?"
    missing = {
        key: sites
        for key, sites in emitted.items()
        if not any(_matches(key, d) for d in docs)
    }
    assert not missing, (
        "metric keys emitted but missing from docs/METRICS.md "
        "(add them there — dashboards key on these names):\n"
        + "\n".join(f"  {k}  ({', '.join(v)})"
                    for k, v in sorted(missing.items()))
    )


def test_documented_keys_still_emitted():
    """The reverse direction as a WARNING-grade check: a documented key
    no one emits anymore is stale. Kept strict — deleting a metric
    must update the registry too."""
    emitted = call_site_keys()
    docs = documented_keys()
    stale = {
        d for d in docs
        if not any(_matches(key, d) for key in emitted)
    }
    assert not stale, (
        "docs/METRICS.md lists keys no call site emits (stale entries):"
        f" {sorted(stale)}"
    )
