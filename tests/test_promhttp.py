"""Prometheus /metrics + /healthz endpoint (telemetry/promhttp.py):
text-exposition validity (asserted by a parser), value parity with
InMemSink.snapshot(), and the health surface."""

import json
import re
import urllib.error
import urllib.request

import pytest

from ct_mapreduce_tpu.telemetry.metrics import InMemSink
from ct_mapreduce_tpu.telemetry.promhttp import (
    MetricsServer,
    metric_name,
    render_prometheus,
)

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})? (?P<value>\S+)$")


def parse_exposition(text: str) -> dict:
    """Minimal Prometheus text-format (0.0.4) parser: every sample line
    must parse, every sample's base name must have a TYPE declared
    first. Returns {name: {"type": ..., "samples": [(labels, value)]}}."""
    families: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, typ = rest.split()
            assert _NAME.match(name), name
            assert typ in ("counter", "gauge", "summary", "histogram",
                           "untyped")
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = {"type": typ, "samples": []}
            continue
        assert not line.startswith("#"), f"unexpected comment: {line}"
        m = _SAMPLE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name = m.group("name")
        base = re.sub(r"_(sum|count)$", "", name)
        fam = families.get(name) or families.get(base)
        assert fam is not None, f"sample {name} without TYPE"
        fam["samples"].append((m.group("labels"), float(m.group("value"))))
    return families


def _populated_sink() -> InMemSink:
    sink = InMemSink()
    sink.incr_counter("ct-fetch.insertCertificate", 42)
    sink.incr_counter("aggregator.batches", 7)
    sink.set_gauge("overlap.decode_occupancy", 0.75)
    sink.set_gauge("aggregator.table_load", 0.12)
    for i in range(1, 101):
        sink.add_sample("ct-fetch.dispatchLockWait", i / 1000.0)
    return sink


def test_render_is_valid_exposition_and_matches_snapshot():
    sink = _populated_sink()
    snap = sink.snapshot()
    fams = parse_exposition(render_prometheus(snap))

    for key, val in snap["counters"].items():
        fam = fams[metric_name(key)]
        assert fam["type"] == "counter"
        assert fam["samples"] == [(None, val)]
    for key, val in snap["gauges"].items():
        fam = fams[metric_name(key)]
        assert fam["type"] == "gauge"
        assert fam["samples"] == [(None, val)]
    for key, s in snap["samples"].items():
        name = metric_name(key)
        fam = fams[name]
        assert fam["type"] == "summary"
        by_label = dict(fam["samples"])
        assert by_label['quantile="0.5"'] == s["p50"]
        assert by_label['quantile="0.95"'] == s["p95"]
        assert by_label['quantile="0.99"'] == s["p99"]


def test_metric_name_sanitization():
    assert metric_name("ct-fetch.storeCertificate") == \
        "ct_fetch_storeCertificate"
    assert metric_name("LogWorker.log/a.saveState") == \
        "LogWorker_log_a_saveState"
    assert _NAME.match(metric_name("0weird.key"))


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_server_metrics_and_healthz():
    sink = _populated_sink()
    depths = {"prepared": 1, "prepared_capacity": 3,
              "drain_queue": 2, "drain_queue_capacity": 2}
    srv = MetricsServer(
        0, host="127.0.0.1", sink=sink,
        health=lambda: {"stage": "syncing",
                        "last_progress": "2026-08-04T00:00:00+00:00",
                        "overlap_queues": depths}).start()
    try:
        code, text = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert code == 200
        fams = parse_exposition(text)
        snap = sink.snapshot()
        # Counter/gauge values match the snapshot exactly.
        assert fams["ct_fetch_insertCertificate"]["samples"] == [(None, 42.0)]
        assert fams["overlap_decode_occupancy"]["samples"] == [(None, 0.75)]
        flat = dict(fams["ct_fetch_dispatchLockWait"]["samples"])
        assert flat['quantile="0.99"'] == \
            snap["samples"]["ct-fetch.dispatchLockWait"]["p99"]

        code, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert code == 200
        health = json.loads(body)
        assert health["healthy"] is True
        assert health["stage"] == "syncing"
        assert health["last_progress"].startswith("2026-08-04")
        assert health["overlap_queues"] == depths

        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv.port}/nope")
        assert err.value.code == 404
    finally:
        srv.stop()


def test_server_healthz_unhealthy_and_failing_provider():
    srv = MetricsServer(0, host="127.0.0.1", sink=InMemSink(),
                        health=lambda: {"healthy": False,
                                        "stage": "wedged"}).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert err.value.code == 503
        assert json.loads(err.value.read().decode())["stage"] == "wedged"
    finally:
        srv.stop()

    def boom():
        raise RuntimeError("probe exploded")

    srv2 = MetricsServer(0, host="127.0.0.1", sink=InMemSink(),
                         health=boom).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{srv2.port}/healthz")
        assert err.value.code == 503
        assert "probe exploded" in err.value.read().decode()
    finally:
        srv2.stop()


def test_server_tracks_live_sink_updates():
    """/metrics renders the sink's CURRENT state per scrape (pull
    semantics), not a bind-time copy."""
    sink = InMemSink()
    srv = MetricsServer(0, host="127.0.0.1", sink=sink).start()
    try:
        sink.incr_counter("live.counter", 1)
        _, text = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert dict(parse_exposition(text)["live_counter"]["samples"]) \
            == {None: 1.0}
        sink.incr_counter("live.counter", 2)
        _, text = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert dict(parse_exposition(text)["live_counter"]["samples"]) \
            == {None: 3.0}
    finally:
        srv.stop()
