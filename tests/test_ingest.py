"""Ingest layer: leaf decode, client retry, sync engine end-to-end.

Mirrors the reference's ingest behaviors: RFC 6962 leaf handling
(ct-fetch.go:452), 429 backoff (ct-fetch.go:409-437), resume-from-
checkpoint (ct-fetch.go:288-305), tolerate-bad-entries
(ct-fetch.go:452-460), and the queue → worker store path
(ct-fetch.go:140-246).
"""

import datetime
import queue
import threading

import pytest

from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core.types import CertificateLog, ExpDate, Issuer, Serial
from ct_mapreduce_tpu.ingest import (
    CTLogClient,
    LogSyncEngine,
    LogWorker,
    decode_entry,
    short_url,
)
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.ingest.health import HealthServer
from ct_mapreduce_tpu.ingest.sync import AggregatorSink, DatabaseSink, polling_delay
from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase
from ct_mapreduce_tpu.storage.mockbackend import MockBackend
from ct_mapreduce_tpu.storage.mockcache import MockRemoteCache

from tests import certgen
from tests.fakelog import FakeLog

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)


def _leaf_and_issuer(serial: int, issuer_cn: str = "Ingest CA"):
    issuer_der = certgen.make_cert(
        serial=1, issuer_cn=issuer_cn, is_ca=True, not_after=FUTURE
    )
    leaf_der = certgen.make_cert(
        serial=serial,
        issuer_cn=issuer_cn,
        subject_cn="leaf.example.com",
        is_ca=False,
        not_after=FUTURE,
    )
    return leaf_der, issuer_der


# -- leaf codec -------------------------------------------------------------


def test_leaf_roundtrip_x509():
    leaf_der, issuer_der = _leaf_and_issuer(7)
    li = leaflib.encode_leaf_input(leaf_der, timestamp_ms=1234)
    ed = leaflib.encode_extra_data([issuer_der])
    e = decode_entry(42, li, ed)
    assert e.index == 42
    assert e.timestamp_ms == 1234
    assert not e.is_precert
    assert e.cert_der == leaf_der
    assert e.issuer_der == issuer_der


def test_leaf_roundtrip_precert():
    leaf_der, issuer_der = _leaf_and_issuer(9)
    li = leaflib.encode_leaf_input(
        b"\x01" * 8, timestamp_ms=99, entry_type=leaflib.PRECERT_ENTRY,
        issuer_key_hash=b"\xab" * 32,
    )
    ed = leaflib.encode_extra_data(
        [issuer_der], entry_type=leaflib.PRECERT_ENTRY, pre_certificate=leaf_der
    )
    e = decode_entry(0, li, ed)
    assert e.is_precert
    # The stored cert is the SUBMITTED precert from extra_data
    # (ct-fetch.go:202-204), not the leaf_input TBS.
    assert e.cert_der == leaf_der
    assert e.issuer_key_hash == b"\xab" * 32
    assert e.issuer_der == issuer_der


def test_leaf_truncated_raises():
    with pytest.raises(leaflib.LeafDecodeError):
        leaflib.decode_leaf_input(b"\x00\x00\x01")


def test_short_url():
    assert short_url("https://ct.example.com/log/") == "ct.example.com/log"
    assert short_url("ct.example.com/log") == "ct.example.com/log"


# -- client -----------------------------------------------------------------


def test_client_sth_and_entries():
    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(1)
    for s in range(5):
        log.add_cert(leaf, issuer, timestamp_ms=s)
    c = CTLogClient(log.url, transport=log.transport)
    sth = c.get_sth()
    assert sth.tree_size == 5
    entries = c.get_raw_entries(1, 3)
    assert [e.index for e in entries] == [1, 2, 3]


def test_client_429_backoff_and_retry_after():
    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(2)
    log.add_cert(leaf, issuer)
    log.rate_limit_hits = 2
    log.retry_after = "3"
    sleeps = []
    c = CTLogClient(log.url, transport=log.transport, sleep=sleeps.append)
    sth = c.get_sth()
    assert sth.tree_size == 1
    assert sleeps == [3.0, 3.0]  # Retry-After honored, then success

    log.rate_limit_hits = 1
    log.retry_after = None
    sleeps.clear()
    c.get_sth()
    assert len(sleeps) == 1 and 0 < sleeps[0] <= 300.0  # jittered window


def test_client_5xx_backoff_same_lane():
    # Transient 5xx takes the exact 429 lane: backoff + jitter +
    # Retry-After clamp, then the same range retried.
    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(2)
    log.add_cert(leaf, issuer)
    log.server_error_hits = 2
    log.server_error_status = 503
    log.retry_after = "7"
    sleeps = []
    c = CTLogClient(log.url, transport=log.transport, sleep=sleeps.append)
    sth = c.get_sth()
    assert sth.tree_size == 1
    assert sleeps == [7.0, 7.0]  # Retry-After honored on 5xx too

    log.server_error_hits = 1
    log.server_error_status = 502
    log.retry_after = None
    sleeps.clear()
    c.get_sth()
    assert len(sleeps) == 1 and 0 < sleeps[0] <= 300.0


def test_client_non_retryable_status_still_raises():
    from ct_mapreduce_tpu.ingest.ctclient import CTClientError

    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(2)
    log.add_cert(leaf, issuer)
    sleeps = []
    c = CTLogClient(log.url, transport=log.transport, sleep=sleeps.append)
    with pytest.raises(CTClientError):
        c.get_raw_entries(5, 9)  # beyond tree size → 400: no retry
    assert sleeps == []


def test_client_window_clamps_to_served_page():
    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(4)
    for s in range(10):
        log.add_cert(leaf, issuer, timestamp_ms=s)
    log.max_batch = 3  # the server's real cap, discovered on the wire
    c = CTLogClient(log.url, transport=log.transport)
    got = c.get_raw_entries(0, 9)
    assert [e.index for e in got] == [0, 1, 2]
    assert c.page_size == 3
    # The next window is pre-clamped: the wire shows end = start + 2.
    got = c.get_raw_entries(3, 9)
    assert [e.index for e in got] == [3, 4, 5]
    assert log.requests[-1].endswith("start=3&end=5")
    # A tail page shorter than the clamp (tree ends) must not shrink
    # the window further: 9..9 is a full answer for the asked range.
    got = c.get_raw_entries(9, 9)
    assert [e.index for e in got] == [9]
    assert c.page_size == 3


# -- LogWorker resume window ------------------------------------------------


def _db():
    return FilesystemDatabase(MockBackend(), MockRemoteCache())


def test_worker_resume_and_limit():
    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(3)
    for s in range(10):
        log.add_cert(leaf, issuer)
    db = _db()
    state = CertificateLog(short_url="ct.example.com/fake", max_entry=4)
    db.save_log_state(state)

    c = CTLogClient(log.url, transport=log.transport)
    w = LogWorker(c, db)
    assert (w.start_pos, w.end_pos) == (4, 9)

    w2 = LogWorker(c, db, offset=7)
    assert w2.start_pos == 7
    w3 = LogWorker(c, db, limit=2)
    assert (w3.start_pos, w3.end_pos) == (4, 5)


# -- end-to-end sync: DatabaseSink ------------------------------------------


def test_sync_end_to_end_database_sink():
    log = FakeLog()
    issuer_der = certgen.make_cert(serial=1, issuer_cn="E2E CA", is_ca=True,
                                   not_after=FUTURE)
    serials = [100, 101, 102, 101, 100, 103]  # dupes dedup to 4
    for s in serials:
        leaf = certgen.make_cert(
            serial=s, issuer_cn="E2E CA", subject_cn="x.example.com",
            is_ca=False, not_after=FUTURE,
        )
        log.add_cert(leaf, issuer_der)
    log.add_garbage()  # tolerated, skipped (ct-fetch.go:452-460)
    ca_cert = certgen.make_cert(serial=200, issuer_cn="E2E CA", is_ca=True,
                                not_after=FUTURE)
    log.add_cert(ca_cert, issuer_der)  # filtered out: CA
    log.add_garbage()  # TRAILING garbage: cursor must still advance past it

    db = _db()
    sink = DatabaseSink(db, now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    engine = LogSyncEngine(sink, db, num_threads=2)
    engine.start_store_threads()
    engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=30)
    engine.stop()

    issuer = Issuer.from_spki(certgen.spki_of(issuer_der))
    exp = ExpDate.from_time(hostder.parse_cert(issuer_der).not_after)
    known = db.get_known_certificates(exp, issuer)
    assert known.count() == 4
    for s in (100, 101, 102, 103):
        assert not known.was_unknown(Serial.from_der_cert(
            certgen.make_cert(serial=s, issuer_cn="E2E CA",
                              subject_cn="x.example.com", is_ca=False,
                              not_after=FUTURE)))
    # Checkpoint advanced to tree size — including past trailing
    # undecodable entries (tolerated skips are durable).
    st = db.get_log_state("ct.example.com/fake")
    assert st.max_entry == 9
    assert st.last_update_time is not None


def test_sync_stop_event_checkpoints():
    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(5)
    for _ in range(30):
        log.add_cert(leaf, issuer)
    db = _db()
    sink = DatabaseSink(db, now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    engine = LogSyncEngine(sink, db, num_threads=1, limit=10)
    engine.start_store_threads()
    engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=30)
    engine.stop()
    st = db.get_log_state("ct.example.com/fake")
    assert st.max_entry == 10  # limit clamp honored


# -- end-to-end sync: AggregatorSink (device path) --------------------------


def test_sync_end_to_end_aggregator_sink():
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator

    log = FakeLog()
    issuer_der = certgen.make_cert(serial=1, issuer_cn="Agg CA", is_ca=True,
                                   not_after=FUTURE)
    for s in [500, 501, 500, 502]:
        leaf = certgen.make_cert(
            serial=s, issuer_cn="Agg CA", subject_cn="y.example.com",
            is_ca=False, not_after=FUTURE,
        )
        log.add_cert(leaf, issuer_der)

    agg = TpuAggregator(
        capacity=1 << 12, batch_size=64,
        now=datetime.datetime(2025, 1, 1, tzinfo=UTC),
    )
    db = _db()
    sink = AggregatorSink(agg, flush_size=3)
    engine = LogSyncEngine(sink, db, num_threads=1)
    engine.start_store_threads()
    engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=60)
    engine.stop()

    snap = agg.drain()
    assert snap.total == 3  # 500, 501, 502
    assert sink.entries_in == 4


def test_sync_raw_batch_mode_matches_per_entry():
    """The native raw-batch fast path must produce the same aggregate
    state as the per-entry path, including garbage tolerance and
    checkpoint semantics."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator

    def build_log():
        log = FakeLog()
        issuer_der = certgen.make_cert(serial=1, issuer_cn="Raw CA",
                                       is_ca=True, not_after=FUTURE)
        for s in [700, 701, 700, 702, 703, 701]:
            leaf = certgen.make_cert(
                serial=s, issuer_cn="Raw CA", subject_cn="r.example.com",
                is_ca=False, not_after=FUTURE,
            )
            log.add_cert(leaf, issuer_der, timestamp_ms=1700000000000 + s)
        log.add_garbage()
        ca = certgen.make_cert(serial=900, issuer_cn="Raw CA", is_ca=True,
                               not_after=FUTURE)
        log.add_cert(ca, issuer_der)
        return log

    results = []
    for raw in (False, True):
        log = build_log()
        agg = TpuAggregator(capacity=1 << 12, batch_size=64,
                            now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
        db = _db()
        sink = AggregatorSink(agg, flush_size=4)
        engine = LogSyncEngine(sink, db, num_threads=2, raw_batches=raw)
        engine.start_store_threads()
        engine.sync_log(log.url, transport=log.transport)
        engine.wait_for_downloads(timeout=60)
        engine.stop()
        assert not engine.errors, engine.errors
        snap = agg.drain()
        st = db.get_log_state("ct.example.com/fake")
        results.append((snap.counts, snap.total, st.max_entry,
                        st.last_entry_time))
    assert results[0][:3] == results[1][:3]
    assert results[1][1] == 4  # 700,701,702,703
    assert results[1][2] == 8  # cursor past garbage + CA
    assert results[1][3] is not None  # timestamp recovered from prefix


def test_raw_batch_oversized_cert_host_lane():
    """A cert above the raw-path pad bucket takes the exact host lane
    and still lands in the aggregate."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import RawBatch
    import base64

    from ct_mapreduce_tpu.ingest import leaf as leaflib

    issuer_der = certgen.make_cert(serial=1, issuer_cn="Big CA", is_ca=True,
                                   not_after=FUTURE)
    big = certgen.make_cert(
        serial=41, issuer_cn="Big CA", subject_cn="b.example.com",
        is_ca=False, not_after=FUTURE,
        crl_dps=tuple(f"http://crl{i}.big.example/{'p' * 60}.crl"
                      for i in range(12)),
    )
    small = certgen.make_cert(serial=42, issuer_cn="Big CA",
                              is_ca=False, not_after=FUTURE)
    assert len(small) <= 768 < len(big), (len(small), len(big))
    agg = TpuAggregator(capacity=1 << 12, batch_size=64,
                        now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    sink = AggregatorSink(agg, flush_size=64)
    sink.PAD_LEN = 768  # force the big cert over the bucket
    lis, eds = [], []
    for der in (big, small):
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(der, 1)).decode())
        eds.append(base64.b64encode(
            leaflib.encode_extra_data([issuer_der])).decode())
    sink.store_raw_batch(RawBatch(lis, eds, 0, "log"))
    sink.flush()
    assert agg.drain().total == 2


def test_device_queue_depth_pipelines_submissions():
    """SURVEY §2.2 PP row: at deviceQueueDepth >= 2 the sink SUBMITS
    batch N+1 before COMPLETING batch N — decode overlaps the device
    step, like the reference's downloader/worker channel overlap
    (ct-fetch.go:132,398-488). At depth 0 every dispatch completes
    synchronously. Both depths produce identical aggregate state."""
    import base64

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import RawBatch

    issuer_der = certgen.make_cert(serial=1, issuer_cn="Pipe CA",
                                   is_ca=True, not_after=FUTURE)

    def raw_batch(serials):
        lis, eds = [], []
        for s in serials:
            der = certgen.make_cert(
                serial=s, issuer_cn="Pipe CA", subject_cn="p.example.com",
                is_ca=False, not_after=FUTURE,
            )
            lis.append(base64.b64encode(
                leaflib.encode_leaf_input(der, 1)).decode())
            eds.append(base64.b64encode(
                leaflib.encode_extra_data([issuer_der])).decode())
        return RawBatch(lis, eds, 0, "log")

    def run(depth):
        agg = TpuAggregator(capacity=1 << 12, batch_size=16,
                            now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
        events = []
        submit_orig = agg.ingest_packed_submit

        def submit(*a, **k):
            p = submit_orig(*a, **k)
            events.append(("submit", id(p)))
            orig_complete = p.complete

            def complete():
                if not p._done:
                    events.append(("complete", id(p)))
                return orig_complete()

            p.complete = complete
            return p

        agg.ingest_packed_submit = submit
        sink = AggregatorSink(agg, flush_size=16, device_queue_depth=depth)
        for i in range(4):
            base = 1000 + 16 * i
            sink.store_raw_batch(raw_batch(range(base, base + 16)))
        sink.flush()
        snap = agg.drain()
        return events, snap

    ev0, snap0 = run(0)
    ev2, snap2 = run(2)
    assert snap0.counts == snap2.counts
    assert snap0.total == snap2.total == 64
    kinds0 = [k for k, _ in ev0]
    assert kinds0 == ["submit", "complete"] * 4  # depth 0: fully serial
    kinds2 = [k for k, _ in ev2]
    # depth 2: three submissions are in flight before the first readback.
    assert kinds2.index("complete") == 3
    # FIFO: completion order equals submission order.
    sub_ids = [i for k, i in ev2 if k == "submit"]
    com_ids = [i for k, i in ev2 if k == "complete"]
    assert com_ids == sub_ids


def test_raw_batch_row_narrowing():
    """When every cert fits half the pad, the sink ships the narrow row
    view (H2D bytes halve on tunneled links) and results are identical."""
    import base64

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import RawBatch

    issuer_der = certgen.make_cert(serial=1, issuer_cn="Narrow CA",
                                   is_ca=True, not_after=FUTURE)
    lis, eds = [], []
    for s in (61, 62, 63):
        der = certgen.make_cert(serial=s, issuer_cn="Narrow CA",
                                is_ca=False, not_after=FUTURE)
        assert len(der) <= 1024
        lis.append(base64.b64encode(leaflib.encode_leaf_input(der, 1)).decode())
        eds.append(base64.b64encode(
            leaflib.encode_extra_data([issuer_der])).decode())

    agg = TpuAggregator(capacity=1 << 12, batch_size=16,
                        now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    seen_widths = []
    orig = agg.ingest_packed_submit

    def spy(data, *a, **kw):
        seen_widths.append(data.shape[1])
        return orig(data, *a, **kw)

    agg.ingest_packed_submit = spy
    sink = AggregatorSink(agg, flush_size=16)
    sink.store_raw_batch(RawBatch(lis, eds, 0, "log"))
    sink.flush()
    assert seen_widths == [sink.PAD_LEN // 2]  # narrow view shipped
    assert agg.drain().total == 3


# -- health -----------------------------------------------------------------


class _FakeEngine:
    def __init__(self):
        self.updates = {}

    def last_updates(self):
        return dict(self.updates)


def test_health_transitions():
    eng = _FakeEngine()
    h = HealthServer(eng, polling_delay_mean_s=10.0, addr="127.0.0.1:0")
    code, body = h.status()
    assert code == 503  # before first update (ct-fetch.go:584-588)
    eng.updates["log"] = datetime.datetime.now(UTC)
    code, body = h.status()
    assert code == 200 and body["status"] == "ok"
    eng.updates["log"] = datetime.datetime.now(UTC) - datetime.timedelta(seconds=25)
    code, body = h.status()
    assert code == 500 and "log" in body["stalled"]


def test_health_http_server():
    import urllib.request

    eng = _FakeEngine()
    eng.updates["log"] = datetime.datetime.now(UTC)
    h = HealthServer(eng, polling_delay_mean_s=10.0, addr="127.0.0.1:0")
    h.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{h.port}/health", timeout=5
        ) as resp:
            assert resp.status == 200
    finally:
        h.stop()


def test_polling_delay_positive():
    for _ in range(100):
        assert polling_delay(600.0, 10) >= 1.0


def test_cursor_saved_on_download_error():
    """A transport failure mid-range must still run the exit state save
    (reference saves on error paths too, ct-fetch.go:367): progress up
    to the failure survives; re-fetch of the failed range is dedup-safe."""
    from ct_mapreduce_tpu.ingest.ctclient import CTClientError

    log = FakeLog()
    leaf, issuer = _leaf_and_issuer(5)
    for _ in range(6):
        log.add_cert(leaf, issuer)
    log.max_batch = 2  # 3 get-entries requests for the full range

    calls = {"n": 0}

    def failing_transport(url):
        if "get-entries" in url:
            calls["n"] += 1
            if calls["n"] >= 2:
                return 500, {}, b"transport down"
        return log.transport(url)

    db = _db()
    c = CTLogClient(log.url, transport=failing_transport)
    w = LogWorker(c, db)
    q = queue.Queue()
    with pytest.raises(CTClientError):
        w.run(q, threading.Event(), save_period_s=1e9)
    st = db.get_log_state("ct.example.com/fake")
    assert st.max_entry == 2  # first batch durable, not lost


def test_sync_multi_log_shared_sink():
    """BASELINE config #5's shape: several logs, one downloader thread
    each (the reference's per-log goroutines, ct-fetch.go:527-565),
    all feeding ONE shared aggregator. Dedup spans logs (the identity
    is (expDate, issuer, serial), not the log), and each log keeps an
    independent resumable cursor."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator

    issuer_der = certgen.make_cert(serial=1, issuer_cn="Multi CA",
                                   is_ca=True, not_after=FUTURE)

    def leaf(s):
        return certgen.make_cert(
            serial=s, issuer_cn="Multi CA", subject_cn="m.example.com",
            is_ca=False, not_after=FUTURE,
        )

    log_a = FakeLog(url="https://ct.example.com/a")
    log_b = FakeLog(url="https://ct.example.com/b")
    for s in (700, 701, 702):
        log_a.add_cert(leaf(s), issuer_der)
    # b overlaps a on 701/702 — cross-log duplicates must dedup.
    for s in (701, 702, 703, 704):
        log_b.add_cert(leaf(s), issuer_der)

    agg = TpuAggregator(
        capacity=1 << 12, batch_size=64,
        now=datetime.datetime(2025, 1, 1, tzinfo=UTC),
    )
    db = _db()
    sink = AggregatorSink(agg, flush_size=3)
    engine = LogSyncEngine(sink, db, num_threads=2)
    engine.start_store_threads()
    engine.sync_log(log_a.url, transport=log_a.transport)
    engine.sync_log(log_b.url, transport=log_b.transport)
    engine.wait_for_downloads(timeout=60)
    engine.stop()

    snap = agg.drain()
    assert snap.total == 5  # 700..704 exactly once across both logs
    assert sink.entries_in == 7
    # Independent per-log cursors at each tree size.
    assert db.get_log_state("ct.example.com/a").max_entry == 3
    assert db.get_log_state("ct.example.com/b").max_entry == 4


def test_sync_contention_stress_exact_totals():
    """The -race-tier analog (the reference runs `go test -race`,
    .travis.yml:13): four logs with overlapping serials, four store
    workers, a deliberately ragged flush size and pipelining depth 3 —
    any lost/duplicated dispatch under contention breaks the exact
    totals, which are asserted to the entry."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator

    issuer_der = certgen.make_cert(serial=1, issuer_cn="Race CA",
                                   is_ca=True, not_after=FUTURE)

    def leaf(s):
        return certgen.make_cert(
            serial=s, issuer_cn="Race CA", subject_cn="r.example.com",
            is_ca=False, not_after=FUTURE,
        )

    logs = []
    unique = set()
    for k in range(4):
        log = FakeLog(url=f"https://ct.example.com/race{k}")
        # Serial windows overlap between neighboring logs.
        for s in range(900 + 10 * k, 900 + 10 * k + 17):
            log.add_cert(leaf(s), issuer_der)
            unique.add(s)
        logs.append(log)

    agg = TpuAggregator(
        capacity=1 << 12, batch_size=32,
        now=datetime.datetime(2025, 1, 1, tzinfo=UTC),
    )
    db = _db()
    sink = AggregatorSink(agg, flush_size=7, device_queue_depth=3)
    engine = LogSyncEngine(sink, db, num_threads=4)
    engine.start_store_threads()
    for log in logs:
        engine.sync_log(log.url, transport=log.transport)
    engine.wait_for_downloads(timeout=90)
    engine.stop()

    snap = agg.drain()
    assert snap.total == len(unique), (snap.total, len(unique))
    assert sink.entries_in == 4 * 17
    for k in range(4):
        st = db.get_log_state(f"ct.example.com/race{k}")
        assert st.max_entry == 17


def test_raw_batch_narrow_decode_and_redecode():
    """The raw path picks the narrow row width BEFORE decoding when
    every leaf_input provably fits (base64 length bound), and
    redecodes at full width when a precert-style entry turns out
    TOO_LONG for the narrow rows — counts exact either way."""
    import base64

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.ingest.sync import RawBatch
    from ct_mapreduce_tpu.native import leafpack

    issuer_der = certgen.make_cert(serial=1, issuer_cn="Nar CA",
                                   is_ca=True, not_after=FUTURE)
    small = [certgen.make_cert(serial=50 + i, issuer_cn="Nar CA",
                               subject_cn=f"n{i}.example.com",
                               is_ca=False, not_after=FUTURE)
             for i in range(4)]
    ed = base64.b64encode(leaflib.encode_extra_data([issuer_der])).decode()

    pads_seen = []
    orig = leafpack.decode_raw_batch

    def spy(lis, eds, pad_len, workers=None, threads=None):
        pads_seen.append(pad_len)
        return orig(lis, eds, pad_len, workers=workers, threads=threads)


    # (a) all-small batch: ONE decode at the narrow width.
    agg = TpuAggregator(capacity=1 << 12, batch_size=64,
                        now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    sink = AggregatorSink(agg, flush_size=64)
    lis = [base64.b64encode(
        leaflib.encode_leaf_input(der, i)).decode()
        for i, der in enumerate(small)]
    leafpack.decode_raw_batch = spy
    try:
        sink.store_raw_batch(RawBatch(lis, [ed] * len(lis), 0, "log"))
        sink.flush()
        assert pads_seen == [sink.PAD_LEN // 2]
        assert agg.drain().total == len(small)

        # (b) a precert whose cert rides in extra_data and exceeds the
        # narrow width: leaf_input stays tiny (the bound can't see it),
        # the narrow decode flags TOO_LONG, and ONE full-width
        # redecode lands everything exactly.
        pads_seen.clear()
        big = certgen.make_cert(
            serial=77, issuer_cn="Nar CA", subject_cn="pc.example.com",
            is_ca=False, not_after=FUTURE,
            extra_extensions=30, extra_ext_size=40)
        assert sink.PAD_LEN // 2 < len(big) <= sink.PAD_LEN, len(big)
        pre_li = base64.b64encode(leaflib.encode_leaf_input(
            b"\x00" * 10, 7,
            entry_type=leaflib.PRECERT_ENTRY)).decode()
        pre_ed = base64.b64encode(leaflib.encode_extra_data(
            [issuer_der], entry_type=leaflib.PRECERT_ENTRY,
            pre_certificate=big)).decode()
        agg2 = TpuAggregator(capacity=1 << 12, batch_size=64,
                             now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
        sink2 = AggregatorSink(agg2, flush_size=64)
        sink2.store_raw_batch(RawBatch(
            lis + [pre_li], [ed] * len(lis) + [pre_ed], 0, "log"))
        sink2.flush()
        assert pads_seen == [sink2.PAD_LEN // 2, sink2.PAD_LEN]
        assert agg2.drain().total == len(small) + 1
    finally:
        leafpack.decode_raw_batch = orig


def test_oversized_issuer_gets_own_status_no_redecode():
    """ADVICE r05: a >=2 MiB issuer DER used to come back as TOO_LONG,
    so any batch containing one paid a futile full-width redecode of
    the whole batch. It now gets ISSUER_TOO_LONG — no redecode — and
    the entry still lands via the exact per-entry host lane."""
    import base64

    import numpy as np

    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import RawBatch
    from ct_mapreduce_tpu.native import leafpack

    # A real-signed certificate inflated past the 2 MiB span-packing
    # bound with one opaque private-arc extension.
    huge_issuer = certgen.make_cert(
        serial=1, issuer_cn="Huge CA", is_ca=True, not_after=FUTURE,
        extra_extensions=1, extra_ext_size=(1 << 21) + 256,
    )
    assert len(huge_issuer) >= (1 << 21)
    normal_issuer = certgen.make_cert(serial=1, issuer_cn="Ovs CA",
                                      is_ca=True, not_after=FUTURE)
    small = [certgen.make_cert(serial=80 + i, issuer_cn="Ovs CA",
                               subject_cn="o.example.com", is_ca=False,
                               not_after=FUTURE) for i in range(3)]
    victim = certgen.make_cert(serial=99, issuer_cn="Huge CA",
                               subject_cn="h.example.com", is_ca=False,
                               not_after=FUTURE)
    lis = [base64.b64encode(leaflib.encode_leaf_input(d, i)).decode()
           for i, d in enumerate(small + [victim])]
    eds = ([base64.b64encode(
        leaflib.encode_extra_data([normal_issuer])).decode()] * len(small)
        + [base64.b64encode(
            leaflib.encode_extra_data([huge_issuer])).decode()])

    dec = leafpack.decode_raw_batch(lis, eds, 2048)
    assert dec.status[-1] == leafpack.ISSUER_TOO_LONG
    assert dec.length[-1] == len(victim)  # cert row packed fine
    np.testing.assert_array_equal(
        dec.status, leafpack._decode_python(lis, eds, 2048).status)

    pads_seen = []
    orig = leafpack.decode_raw_batch

    def spy(l, e, pad_len, workers=None, threads=None):
        pads_seen.append(pad_len)
        return orig(l, e, pad_len, workers=workers, threads=threads)

    agg = TpuAggregator(capacity=1 << 12, batch_size=64,
                        now=datetime.datetime(2025, 1, 1, tzinfo=UTC))
    sink = AggregatorSink(agg, flush_size=64)
    leafpack.decode_raw_batch = spy
    try:
        sink.store_raw_batch(RawBatch(lis, eds, 0, "log"))
        sink.flush()
    finally:
        leafpack.decode_raw_batch = orig
    # Narrow pre-decode, ONE decode — the overloaded status used to
    # force [narrow, full] here.
    assert pads_seen == [sink.PAD_LEN // 2], pads_seen
    assert agg.drain().total == len(small) + 1
