"""Staged device queue (round 11): parity, growth, failure, and
observability semantics of the K-chunk resident envelope path
(`chunksPerDispatch` > 1 — ops/pipeline.staged_core, the sink's
staging ring, and PendingStaged's one-readback fold).

Fixtures are ``ct_mapreduce_tpu.utils.minicert`` wire entries (no
``cryptography`` dependency), mirroring tests/test_overlap.py — and
deliberately narrow: every sink here pins ``PAD_LEN`` down so chunks
decode into 512-byte rows (the minicert fixtures fit with room), which
roughly HALVES the walker's per-shape XLA compile cost on the CPU CI
box — and all tests share one (flush 32, capacity 1<<12, width 512)
shape so each program compiles once for the whole file.
"""

import base64
import datetime
import threading

import numpy as np
import pytest

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.ingest.overlap import OverlapError
from ct_mapreduce_tpu.ingest.sync import (
    AggregatorSink,
    RawBatch,
    resolve_staging,
)
from ct_mapreduce_tpu.storage.mockbackend import MockBackend
from ct_mapreduce_tpu.telemetry import metrics as tmetrics
from ct_mapreduce_tpu.utils import minicert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2025, 1, 1, tzinfo=UTC)

FLUSH = 32  # lanes per chunk — matches test_overlap's walker shape
CAP = 1 << 12
K = 4  # chunks per dispatch: ONE staged-envelope compile for the file

ISSUERS = [minicert.make_cert(serial=1, issuer_cn=f"Stg CA {k}",
                              is_ca=True)
           for k in range(2)]


def wire_batch(start: int, n: int, duplicate_of: int | None = None,
               junk_lane: bool = False, oversized_serial: bool = False):
    """n wire entries alternating two issuers. ``junk_lane`` replaces
    one leaf with undecodable bytes (parse-error path);
    ``oversized_serial`` gives one cert a serial wider than the device
    schema (exact-host-lane spill path)."""
    lis, eds = [], []
    base = duplicate_of if duplicate_of is not None else start
    for j in range(n):
        k = j % 2
        if junk_lane and j == n // 2:
            lis.append(base64.b64encode(b"\x00\x01garbage-leaf").decode())
            eds.append(base64.b64encode(
                leaflib.encode_extra_data([ISSUERS[k]])).decode())
            continue
        serial = base + j
        serial_len = 16
        if oversized_serial and j == 1:
            # > MAX_SERIAL_BYTES (46): device-exactness gate routes the
            # lane to the exact host path on every ingest flavor.
            serial = (base + j) | (1 << 400)
            serial_len = None  # minicert sizes the body to the value
        leaf = minicert.make_cert(
            serial=serial, issuer_cn=f"Stg CA {k}",
            subject_cn="stg.example", is_ca=False, serial_len=serial_len,
        )
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(leaf, 1000 + start + j)).decode())
        eds.append(base64.b64encode(
            leaflib.encode_extra_data([ISSUERS[k]])).decode())
    return RawBatch(lis, eds, start, "stg-log")


def make_sink(overlap_workers: int, k_per: int, capacity: int = CAP,
              backend=None, staging_depth: int = 2, grow_at: float = 0.55,
              aggregator=None):
    agg = aggregator or TpuAggregator(capacity=capacity, batch_size=FLUSH,
                                      now=NOW, grow_at=grow_at)
    sink = AggregatorSink(agg, flush_size=FLUSH, backend=backend,
                          device_queue_depth=2 if overlap_workers else 0,
                          overlap_workers=overlap_workers,
                          chunks_per_dispatch=k_per,
                          staging_depth=staging_depth)
    # Narrow rows: minicert fixtures fit 512-byte rows, and the
    # compiled walker/envelope shapes stay file-wide shared (see
    # module docstring).
    sink.PAD_LEN = 1024
    return agg, sink


def replay(batches, overlap_workers: int, k_per: int, **kw):
    backend = MockBackend()
    agg, sink = make_sink(overlap_workers, k_per, backend=backend, **kw)
    for rb in batches:
        sink.store_raw_batch(rb)
    sink.close()
    snap = agg.drain()
    return {
        "counts": snap.counts,
        "total": snap.total,
        "table_count": agg._table_fill_exact(),
        "host_lane": agg.metrics["host_lane"],
        "inserted": agg.metrics["inserted"],
        "known": agg.metrics["known"],
        "overflow": agg.metrics["overflow"],
        "issuer_totals": agg.issuer_totals.copy(),
        "capacity": agg.capacity,
        # Per-(expDate, issuer) sets of stored serial ids — the
        # "serials parity" surface (first-seen PEM writes).
        "pems": {k: sorted(v) for k, v in backend.serials.items()},
        "agg": agg,
    }


def test_staged_exact_parity_with_serial():
    """Serial (per-chunk dispatch) vs staged (K-chunk envelope, both
    serial-dispatch and overlap-scheduler flavors) on a stream with
    cross-batch duplicates, an undecodable lane, and an
    oversized-serial host-lane spill: was-unknown attribution, host
    lane counts, probe-overflow spills, per-issuer totals, drained
    per-(issuer, expDate) counts, AND the per-entry serial sets the
    PEM backend stored must all match exactly."""
    batches = [
        wire_batch(0, FLUSH),
        wire_batch(FLUSH, FLUSH, junk_lane=True),
        wire_batch(2 * FLUSH, FLUSH, oversized_serial=True),
        wire_batch(3 * FLUSH, FLUSH),
        wire_batch(4 * FLUSH, FLUSH, duplicate_of=0),  # dedup window
        wire_batch(5 * FLUSH, FLUSH),
    ]
    # Fixture guard: the corpus must fit the narrow 512-byte rows the
    # whole file's compile-sharing rests on (see module docstring).
    from ct_mapreduce_tpu.native import leafpack

    dec = leafpack.decode_raw_batch(
        batches[2].leaf_inputs, batches[2].extra_datas, 512)
    assert not (dec.status == leafpack.TOO_LONG).any()

    serial = replay(batches, overlap_workers=0, k_per=1)
    staged = replay(batches, overlap_workers=0, k_per=K)
    staged_ovl = replay(batches, overlap_workers=2, k_per=K)
    assert serial["host_lane"] > 0  # the spill lane really spilled
    assert serial["known"] >= FLUSH  # the duplicate window really hit
    for name, got in (("staged", staged), ("staged+overlap", staged_ovl)):
        for field in ("counts", "total", "table_count", "host_lane",
                      "inserted", "known", "overflow", "pems"):
            assert got[field] == serial[field], (name, field)
        np.testing.assert_array_equal(got["issuer_totals"],
                                      serial["issuer_totals"])


def test_staged_open_layout_parity(monkeypatch):
    """Same parity contract on the open-addressed table layout (the
    envelope's table_insert dispatches by state type at trace time),
    with a ragged 7th chunk so the open-layout run also exercises the
    padded partial-envelope flush."""
    monkeypatch.setenv("CTMR_TABLE", "open")
    batches = [wire_batch(i * FLUSH, FLUSH) for i in range(6)]
    batches.append(wire_batch(6 * FLUSH, FLUSH, duplicate_of=0))
    serial = replay(batches, overlap_workers=0, k_per=1)
    staged = replay(batches, overlap_workers=2, k_per=K)
    assert serial["known"] >= FLUSH
    for field in ("counts", "total", "table_count", "host_lane",
                  "inserted", "known", "overflow", "pems"):
        assert staged[field] == serial[field], field
    np.testing.assert_array_equal(staged["issuer_totals"],
                                  serial["issuer_totals"])


def test_staged_partial_ring_flushes_at_barrier():
    """A ring holding fewer than K chunks must dispatch (as a padded
    partial envelope) at the flush barrier, and the
    ingest.dispatch_chunks sample must record the REAL chunk count —
    the early-flush visibility the metric exists for."""
    sink_m = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink_m)
    try:
        batches = [wire_batch(i * FLUSH, FLUSH) for i in range(K - 1)]
        serial = replay(batches, overlap_workers=0, k_per=1)
        staged = replay(batches, overlap_workers=2, k_per=K)
    finally:
        tmetrics.set_sink(prev)
    assert staged["total"] == serial["total"] == (K - 1) * FLUSH
    assert staged["counts"] == serial["counts"]
    samples = sink_m.snapshot()["samples"]
    assert samples["ingest.dispatch_chunks"]["max"] == K - 1


def test_staged_ring_survives_error_latch():
    """A drain-stage failure latches the overlap pipeline mid-staging:
    close() raises OverlapError, chunks parked in the ring are dropped
    (never half-dispatched), and the aggregator — whose table buffer
    rode through donated envelope dispatches — remains fully usable
    for a follow-up serial ingest with exact counts."""
    agg, sink = make_sink(overlap_workers=2, k_per=K)
    boom = RuntimeError("drain exploded")
    orig = sink._complete_item
    calls = {"n": 0}

    def failing_complete(pending, der_of):
        calls["n"] += 1
        if calls["n"] == 1:
            raise boom
        return orig(pending, der_of)

    sink._complete_item = failing_complete
    with pytest.raises(OverlapError) as err:
        for i in range(3 * K):
            sink.store_raw_batch(wire_batch(i * FLUSH, FLUSH))
        sink.flush()
    assert err.value.__cause__ is boom
    with pytest.raises(OverlapError):
        sink.close()
    # The table state was not corrupted by the latch: whatever folded
    # before/after the failure is consistent, and fresh ingest over
    # the same aggregator (a new serial sink — same compiled walker
    # shape) keeps exact dedup behavior.
    before = agg.drain().total
    assert before % FLUSH == 0
    agg2, sink2 = make_sink(overlap_workers=0, k_per=1, aggregator=agg)
    sink2.store_raw_batch(wire_batch(900_000, 2 * FLUSH))
    sink2.flush()
    assert agg.drain().total == before + 2 * FLUSH


def test_staged_ring_depth_surfaces_in_healthz():
    """Satellite: the staging-ring occupancy rides queue_depths() (the
    /healthz surface) next to the prepared/drain gauges, and
    publish_highwater exports the ring gauges through the metrics
    API."""
    sink_m = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink_m)
    try:
        agg, sink = make_sink(overlap_workers=2, k_per=K)
        for i in range(K + 1):
            sink.store_raw_batch(wire_batch(i * FLUSH, FLUSH))
        ovl = sink._overlap
        ovl.drain_all()
        depths = ovl.queue_depths()
        ovl.publish_highwater()
        sink.close()
    finally:
        tmetrics.set_sink(prev)
    for key in ("staging_ring", "staging_ring_capacity",
                "staging_ring_highwater"):
        assert key in depths, sorted(depths)
    assert depths["staging_ring_capacity"] == K
    assert 1 <= depths["staging_ring_highwater"] <= K
    assert depths["staging_ring"] == 0  # barrier flushed it
    gauges = sink_m.snapshot()["gauges"]
    assert gauges["overlap.staging_ring_capacity"] == K
    assert gauges["overlap.staging_ring_highwater"] >= 1
    # An unstaged sink must NOT grow the surface (no stale keys).
    agg2, sink2 = make_sink(overlap_workers=2, k_per=1)
    assert "staging_ring" not in sink2._overlap.queue_depths()
    sink2.close()


def test_staged_growth_mid_stream():
    """Mid-stream table growth under staging: the ring is (by
    construction) empty-or-dispatched when the envelope submit trips
    maybe_grow, outstanding envelopes fold, the table rebuilds, and
    the next envelopes re-enter the resident loop at the grown
    capacity — with every count matching the exact truth of the
    unique-serial stream (what the serial path produces by its own
    pinned tests). Capacities are chosen so the POST-grow envelope
    shape equals the parity tests' (already compiled; only the
    pre-grow shape pays a fresh compile)."""
    # Bucket layout rounds 1<<11 up to 3072 slots; at grow_at 0.55 the
    # 1,920 unique serials below trip a grow into 6144 slots — the
    # exact shape CAP=1<<12 rounds to in the tests above.
    start_cap = 1 << 11
    n_batches = 60
    total = n_batches * FLUSH
    batches = [wire_batch(i * FLUSH, FLUSH) for i in range(n_batches)]
    staged = replay(batches, overlap_workers=2, k_per=K,
                    capacity=start_cap)
    # Growth really happened mid-stream (the as-built slot count is
    # what the layout rounds start_cap to).
    start_slots = TpuAggregator(capacity=start_cap, batch_size=FLUSH,
                                now=NOW).capacity
    assert staged["capacity"] > start_slots
    # Exact truth of the stream: every serial unique, two issuers
    # alternating, one expDate per issuer, nothing spilled or lost
    # through the flush-ring → grow → re-enter sequence.
    assert staged["total"] == total
    assert staged["table_count"] == total
    assert staged["inserted"] == total and staged["known"] == 0
    assert staged["host_lane"] == 0 and staged["overflow"] == 0
    assert sorted(staged["counts"].values()) == [total // 2, total // 2]
    assert sum(len(v) for v in staged["pems"].values()) == total
    assert sorted(staged["issuer_totals"][staged["issuer_totals"] > 0]
                  .tolist()) == [total // 2, total // 2]


def test_staged_sharded_parity():
    """Staged lane over the mesh (ShardedAggregator delegates the
    envelope to per-chunk host-routed mesh steps — staged_h2d off, one
    deferred fold per staged flush): drained counts must match the
    single-chip serial path exactly."""
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    batches = [wire_batch(i * FLUSH, FLUSH) for i in range(6)]
    serial = replay(batches, overlap_workers=0, k_per=1)

    mesh = Mesh(np.array(jax.devices()[:4]), ("shard",))
    agg = ShardedAggregator(mesh, capacity=CAP, batch_size=FLUSH, now=NOW)
    assert agg.staged_h2d is False
    sink = AggregatorSink(agg, flush_size=FLUSH, overlap_workers=2,
                          chunks_per_dispatch=K)
    sink.PAD_LEN = 1024  # narrow rows, like make_sink
    for rb in batches:
        sink.store_raw_batch(rb)
    sink.close()
    snap = agg.drain()
    assert snap.total == serial["total"]
    assert snap.counts == serial["counts"]
    assert agg.metrics["host_lane"] == serial["host_lane"]


def test_resolve_staging_env_layering(monkeypatch):
    """Knob resolution: explicit kwarg > CTMR_* env > defaults; junk
    env values are ignored like the config layer does."""
    monkeypatch.delenv("CTMR_CHUNKS_PER_DISPATCH", raising=False)
    monkeypatch.delenv("CTMR_STAGING_DEPTH", raising=False)
    assert resolve_staging(0, 0) == (1, 2)  # defaults: off, double buf
    assert resolve_staging(8, 3) == (8, 3)  # explicit wins
    monkeypatch.setenv("CTMR_CHUNKS_PER_DISPATCH", "6")
    monkeypatch.setenv("CTMR_STAGING_DEPTH", "5")
    assert resolve_staging(0, 0) == (6, 5)  # env fills the gaps
    assert resolve_staging(2, 0) == (2, 5)  # kwarg beats env per-knob
    monkeypatch.setenv("CTMR_CHUNKS_PER_DISPATCH", "banana")
    monkeypatch.setenv("CTMR_STAGING_DEPTH", "")
    assert resolve_staging(0, 0) == (1, 2)  # junk env → defaults
