"""Fleet-mode units: the deterministic partitioner, the unified
FleetCoordinator protocol over both the mock cache and a live
miniredis-backed RedisCache, the FleetService checkpoint cadence, and
the per-worker aggregate merge (agg/merge.py).

The end-to-end multi-PROCESS contracts — 2-worker parity with a
serial run, SIGKILL-and-resume from checkpoint — live in
tests/test_multiprocess.py; this file covers the pieces in-process."""

import threading
import time

import pytest

from ct_mapreduce_tpu.ingest import fleet
from ct_mapreduce_tpu.storage.mockcache import MockRemoteCache

URLS = [f"https://log{i}.example.com/2026" for i in range(12)]


# -- partitioner --------------------------------------------------------


def test_partition_disjoint_covering_deterministic():
    for w in (1, 2, 3, 5):
        owners = fleet.partition_map(URLS, w)
        assert owners == fleet.partition_map(URLS, w)  # pure function
        assert set(owners.values()) <= set(range(w))
        parts = [fleet.partition_logs(URLS, i, w) for i in range(w)]
        flat = [u for p in parts for u in p]
        assert sorted(flat) == sorted(URLS)  # covering
        assert len(flat) == len(set(flat))  # disjoint


def test_partition_takeover_moves_only_dead_owners_logs():
    owners = fleet.partition_map(URLS, 4)
    dead = 0
    alive = [w for w in range(4) if w != dead]
    reassigned = fleet.partition_map(URLS, 4, alive=alive)
    for url in URLS:
        if owners[url] == dead:
            assert reassigned[url] in alive  # re-homed to a live worker
        else:
            assert reassigned[url] == owners[url]  # never moved


def test_partition_range_stripes_cover_tree():
    for tree in (0, 1, 7, 1003):
        for w in (1, 2, 5):
            stripes = [fleet.partition_range(tree, i, w) for i in range(w)]
            assert stripes[0][0] == 0
            pos = 0
            for off, lim in stripes:
                assert off == pos  # contiguous, disjoint
                pos += lim
            assert pos == tree  # covering


def test_worker_state_path():
    assert fleet.worker_state_path("/s/agg.npz", 2, 4) == "/s/agg.w2.npz"
    assert fleet.worker_state_path("/s/agg.npz", 0, 1) == "/s/agg.npz"
    assert fleet.worker_state_path("", 2, 4) == ""
    assert fleet.worker_state_path("/s/state", 1, 2) == "/s/state.w1"


def test_resolve_fleet_env_layering(monkeypatch):
    for k in ("CTMR_NUM_WORKERS", "CTMR_WORKER_ID",
              "CTMR_CHECKPOINT_PERIOD", "CTMR_COORDINATOR"):
        monkeypatch.delenv(k, raising=False)
    assert fleet.resolve_fleet() == (1, 0, "", "")
    # Explicit beats env.
    monkeypatch.setenv("CTMR_NUM_WORKERS", "8")
    monkeypatch.setenv("CTMR_WORKER_ID", "3")
    monkeypatch.setenv("CTMR_CHECKPOINT_PERIOD", "30s")
    monkeypatch.setenv("CTMR_COORDINATOR", "jax")
    assert fleet.resolve_fleet(4, 1, "10s", "redis") == (4, 1, "10s", "redis")
    # Explicit workerId = 0 is a real id (every fleet needs exactly one
    # worker 0), NOT the unset sentinel — it must beat the env too.
    assert fleet.resolve_fleet(4, 0, "10s", "redis")[1] == 0
    # Env fills the gaps.
    assert fleet.resolve_fleet() == (8, 3, "30s", "jax")
    # Unparseable env ints are ignored.
    monkeypatch.setenv("CTMR_NUM_WORKERS", "banana")
    assert fleet.resolve_fleet()[0] == 1


# -- coordinators -------------------------------------------------------


def _elect_pair(cache, **kw):
    c0 = fleet.CacheFleetCoordinator(cache, "t", 0, 2, **kw)
    c1 = fleet.CacheFleetCoordinator(cache, "t", 1, 2, **kw)
    results = {}

    def go(c, i):
        results[i] = c.start()
        c.barrier(timeout_s=10)

    ts = [threading.Thread(target=go, args=(c, i))
          for i, c in enumerate((c0, c1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(15)
    assert not any(t.is_alive() for t in ts), "barrier did not release"
    assert sorted(results.values()) == [False, True], results
    return c0, c1, results


def test_cache_coordinator_election_barrier_epoch_shutdown():
    cache = MockRemoteCache()
    c0, c1, results = _elect_pair(cache)
    leader = c0 if results[0] else c1
    follower = c1 if results[0] else c0
    assert sorted(leader.alive_workers()) == [0, 1]
    leader.publish_epoch(1)
    leader.publish_epoch(2)  # last-writer-wins value slot
    assert follower.current_epoch() == 2
    assert follower.shutdown_requested() is None
    leader.request_shutdown("drain")
    assert follower.shutdown_requested() == "drain"
    c0.close()
    c1.close()


def test_stale_shutdown_broadcast_not_replayed():
    """A stop broadcast left in a PERSISTENT cache by a previous
    (signal-stopped) run must not self-terminate the next run: the
    key is TTL'd at publish and cleared when a coordinator starts."""
    from datetime import timedelta

    cache = MockRemoteCache()
    old = fleet.CacheFleetCoordinator(cache, "t", 0, 1)
    assert old.start() is True
    old.request_shutdown("leader signal 15")
    assert old.shutdown_requested() == "leader signal 15"
    old.close()
    # The broadcast key carries a TTL (a persistent Redis must not
    # keep it forever even if no successor run ever starts).
    assert cache._expirations.get(fleet.STOP_KEY_PREFIX + "t") is not None
    # Simulate the restart: the stale lease is still live (fresh cache
    # state, no time passed), but start() absorbs the stale broadcast.
    fresh = fleet.CacheFleetCoordinator(cache, "t", 0, 1)
    fresh.start()
    assert fresh.shutdown_requested() is None
    svc = fleet.FleetService(fresh, heartbeat_period_s=0.05,
                             on_shutdown=lambda r: pytest.fail(
                                 f"stale broadcast replayed: {r}"))
    svc.start(timeout_s=5, await_barrier=False)
    time.sleep(0.3)  # several observation rounds
    svc.stop()
    # A FRESH broadcast still works after the clear.
    fresh.request_shutdown("real stop")
    assert fresh.shutdown_requested() == "real stop"


def test_claim_log_exclusive_lease():
    """The per-log fetch lease: one holder at a time, re-affirmable by
    the holder (and by its same-id restart), transferable only after
    release or TTL expiry — the guard against takeover/warm-restart
    double-fetch."""
    cache = MockRemoteCache()
    c0 = fleet.CacheFleetCoordinator(cache, "t", 0, 2)
    c1 = fleet.CacheFleetCoordinator(cache, "t", 1, 2)
    url = URLS[0]
    assert c0.claim_log(url) is True
    assert c1.claim_log(url) is False  # held
    assert c0.claim_log(url) is True  # holder re-affirms (TTL refresh)
    c1.release_log(url)  # non-holder release is a no-op
    assert c1.claim_log(url) is False
    c0.release_log(url)
    assert c1.claim_log(url) is True  # transferred after release
    # A restart with the holder's id re-affirms the old incarnation's
    # lease instead of deadlocking against itself.
    c1b = fleet.CacheFleetCoordinator(cache, "t", 1, 2)
    assert c1b.claim_log(url) is True
    for c in (c0, c1, c1b):
        c.close()


def test_fleet_service_claims_filter_and_release():
    cache = MockRemoteCache()
    svc = fleet.FleetService(
        fleet.CacheFleetCoordinator(cache, "cl", 0, 2))
    peer = fleet.CacheFleetCoordinator(cache, "cl", 1, 2)
    taken = URLS[0]
    assert peer.claim_log(taken)
    assert svc.claim(taken) is False
    free = URLS[1]
    assert svc.claim(free) is True
    assert svc.stats()["claims"] == [free]
    svc.release_claims()
    assert svc.stats()["claims"] == []
    assert peer.claim_log(free) is True  # released → claimable
    peer.close()
    svc.coordinator.close()


def test_fleet_assignments_sth_failure_contained(monkeypatch):
    """Stripe mode resolves the tree size with one STH fetch inside
    the main run loop; a transient failure there must land in the
    engine's per-round error list (empty round, retried next poll),
    not propagate and kill the worker process."""
    from ct_mapreduce_tpu.cmd import ct_fetch
    from ct_mapreduce_tpu.ingest import ctclient

    class BoomClient:
        def __init__(self, url):
            pass

        def get_sth(self):
            raise OSError("connection refused")

    monkeypatch.setattr(ctclient, "CTLogClient", BoomClient)
    svc = fleet.FleetService(
        fleet.CacheFleetCoordinator(MockRemoteCache(), "sth", 0, 2))
    errors = []
    out = ct_fetch.fleet_assignments(
        svc, ["https://log.example/a"], errors=errors)
    assert out == []
    assert len(errors) == 1 and "STH fetch" in errors[0]
    svc.coordinator.close()


def test_fleet_assignments_skips_leased_logs():
    """A log whose fetch lease another worker still holds (takeover
    survivor vs. the owner's restart) is excluded from this round's
    assignments and picked up again once the lease is released."""
    from ct_mapreduce_tpu.cmd import ct_fetch

    cache = MockRemoteCache()
    svc = fleet.FleetService(
        fleet.CacheFleetCoordinator(cache, "as", 0, 2))
    peer = fleet.CacheFleetCoordinator(cache, "as", 1, 2)
    mine = fleet.partition_logs(URLS, 0, 2)
    assert len(mine) >= 2
    held = mine[0]
    assert peer.claim_log(held)  # survivor mid-fetch of our log
    urls = [u for (u, _, _, _) in ct_fetch.fleet_assignments(svc, URLS)]
    assert held not in urls
    assert urls == [u for u in mine if u != held]
    svc.release_claims()
    peer.release_log(held)
    urls = [u for (u, _, _, _) in ct_fetch.fleet_assignments(svc, URLS)]
    assert urls == mine  # re-contended and won next round
    svc.release_claims()
    peer.close()
    svc.coordinator.close()


def test_rejoin_skips_barrier_and_republishes_start():
    """A restarted worker rejoining a running fleet must not block on
    the start barrier: a follower behind the incumbent's published
    start key detects the rejoin itself; a worker that inherited an
    expired lease (or one asserting local checkpoint evidence via
    ``rejoin=True``) re-publishes the start key instead of waiting for
    membership that may never re-form."""
    cache = MockRemoteCache()
    c0, c1, results = _elect_pair(cache)  # running fleet, barrier done
    # Case 1: respawn as a FOLLOWER behind the still-live lease — the
    # incumbent's started key marks the fleet as already running.
    re0 = fleet.CacheFleetCoordinator(cache, "t", 0, 2)
    svc = fleet.FleetService(re0)
    t0 = time.monotonic()
    svc.start(timeout_s=30)  # must not wait anywhere near timeout
    assert time.monotonic() - t0 < 5.0
    assert svc.rejoined is True
    assert re0.fleet_started() is True
    assert svc.stats()["rejoined"] is True
    svc.stop()
    # Case 2: caller-asserted rejoin on a LEADER (fresh cache simulates
    # the expired-lease takeover; peers finished, membership will never
    # re-form): start() returns immediately and the start key is
    # re-published so any polling follower is released.
    from ct_mapreduce_tpu.coordinator.coordinator import STARTED_KEY_PREFIX

    cache2 = MockRemoteCache()
    lead = fleet.CacheFleetCoordinator(cache2, "t", 0, 2)
    svc2 = fleet.FleetService(lead)
    t0 = time.monotonic()
    assert svc2.start(timeout_s=30, rejoin=True) is True
    assert time.monotonic() - t0 < 5.0, "rejoining leader blocked"
    assert cache2.exists(STARTED_KEY_PREFIX + lead._coord.identifier)
    svc2.stop()
    c0.close()
    c1.close()


def test_cache_coordinator_liveness_and_promotion():
    from datetime import timedelta

    cache = MockRemoteCache()
    c0, c1, results = _elect_pair(
        cache, liveness_timeout_s=0.2,
        key_life_initial=timedelta(seconds=0.2),
        key_life_renewal=timedelta(seconds=0.2))
    leader = c0 if results[0] else c1
    follower = c1 if results[0] else c0
    # Leader dies: its heartbeat AND election lease expire; the
    # follower's next heartbeat round promotes it (elastic failover,
    # the reference's lease-expiry semantics).
    leader._coord._stop_renewal.set()  # simulate process death
    deadline = time.monotonic() + 5.0
    promoted = False
    while time.monotonic() < deadline and not promoted:
        time.sleep(0.1)
        follower.heartbeat()
        assert leader.worker_id not in follower.alive_workers() or True
        promoted = follower.maybe_promote()
    assert promoted, "follower never inherited the expired lease"
    assert follower.is_leader
    # The dead leader's heartbeat is gone from the liveness view.
    assert leader.worker_id not in follower.alive_workers()
    c0.close()
    c1.close()


def test_cache_coordinator_over_live_miniredis():
    """The same protocol over the real socket client + miniredis —
    pins RemoteCache.put/get on the RESP path."""
    from ct_mapreduce_tpu.storage.rediscache import RedisCache
    from ct_mapreduce_tpu.utils.miniredis import MiniRedis

    server = MiniRedis().start()
    try:
        cache = RedisCache(server.address)
        c = fleet.CacheFleetCoordinator(cache, "mr", 0, 1,
                                        liveness_timeout_s=5.0)
        assert c.start() is True  # sole contender wins
        c.barrier(timeout_s=5)
        assert sorted(c.alive_workers()) == [0]
        c.publish_epoch(7)
        assert c.current_epoch() == 7
        cache.put("fleet-ttl-probe", "x")
        assert cache.get("fleet-ttl-probe") == "x"
        c.request_shutdown("bye")
        assert c.shutdown_requested() == "bye"
        c.close()
        cache.close()
    finally:
        server.stop()


def test_jax_coordinator_single_process_fallback():
    """Single-process jax: leadership is process 0, the barrier a
    no-op, epoch/shutdown degrade to local values (no distributed
    client to carry them)."""
    c = fleet.JaxFleetCoordinator("t")
    assert c.num_workers == 1 and c.worker_id == 0
    assert c.start() is True
    c.barrier(timeout_s=1)
    c.publish_epoch(3)
    assert c.current_epoch() == 3
    c.request_shutdown("x")
    assert c.shutdown_requested() == "x"
    c.close()


def test_build_coordinator_selection():
    cache = MockRemoteCache()
    assert isinstance(fleet.build_coordinator("", None, "t", 0, 1),
                      fleet.SoloFleetCoordinator)
    assert isinstance(fleet.build_coordinator("", cache, "t", 0, 2),
                      fleet.CacheFleetCoordinator)
    assert isinstance(fleet.build_coordinator("redis", cache, "t", 0, 1),
                      fleet.CacheFleetCoordinator)
    with pytest.raises(ValueError):
        fleet.build_coordinator("zookeeper", cache, "t", 0, 2)
    with pytest.raises(ValueError):
        fleet.build_coordinator("redis", None, "t", 0, 2)


# -- the service loop ---------------------------------------------------


def test_fleet_service_checkpoint_cadence_and_stats():
    hits = []
    svc = fleet.FleetService(
        fleet.SoloFleetCoordinator("s"), heartbeat_period_s=0.05,
        checkpoint_period_s=0.1, on_checkpoint=hits.append)
    assert svc.start(timeout_s=5) is True
    deadline = time.monotonic() + 5.0
    while len(hits) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    svc.stop()
    assert len(hits) >= 3, hits
    assert hits == sorted(hits)  # epochs advance monotonically
    st = svc.stats()
    assert st["role"] == "leader"
    assert st["workers_alive"] == [0]
    assert st["checkpoints_run"] == len(hits)
    assert st["checkpoint_epoch"] >= hits[-1]


def test_fleet_service_shutdown_broadcast_and_partition():
    cache = MockRemoteCache()
    coord = fleet.CacheFleetCoordinator(cache, "b", 0, 2,
                                        liveness_timeout_s=5.0)
    seen = []
    svc = fleet.FleetService(coord, heartbeat_period_s=0.05,
                             on_shutdown=seen.append)
    # Peer heartbeat so the (leader) barrier releases.
    peer = fleet.CacheFleetCoordinator(cache, "b", 1, 2,
                                       liveness_timeout_s=5.0)
    peer.heartbeat()
    svc.start(timeout_s=5)
    mine = svc.partition(URLS)
    assert mine == fleet.partition_logs(URLS, 0, 2)
    assert svc.stats()["partition"] == fleet.partition_map(URLS, 2)
    peer.request_shutdown("peer says stop")
    deadline = time.monotonic() + 5.0
    while not seen and time.monotonic() < deadline:
        time.sleep(0.02)
    svc.stop()
    peer.close()
    assert seen == ["peer says stop"]


def test_engine_checkpoint_now_fans_out():
    """checkpoint_now: live downloaders get a save request; with none
    in flight the aggregate hook runs directly (idle workers persist
    at the fleet cadence too)."""
    from ct_mapreduce_tpu.ingest.sync import LogSyncEngine

    hook_runs = []
    engine = LogSyncEngine(sink=None, database=None,
                           checkpoint_hook=lambda: hook_runs.append(1))
    engine.checkpoint_now()
    assert hook_runs == [1]  # idle → direct hook

    class FakeWorker:
        def __init__(self):
            self.saves = 0

        def request_save(self):
            self.saves += 1

    w = FakeWorker()
    engine._active_workers.append(w)
    engine.checkpoint_now()
    assert w.saves == 1
    assert hook_runs == [1]  # the downloader's save runs the hook


# -- merge --------------------------------------------------------------


def test_merge_snapshots_sums_and_unions():
    from ct_mapreduce_tpu.agg.aggregator import AggregateSnapshot
    from ct_mapreduce_tpu.agg.merge import merge_snapshots

    a = AggregateSnapshot(
        counts={("i1", "d1"): 3, ("i1", "d2"): 1},
        crls={"i1": {"u1"}}, dns={"i1": {"CN=A"}},
        total=4, verified={"i1": 2}, failed={})
    b = AggregateSnapshot(
        counts={("i1", "d1"): 2, ("i2", "d1"): 5},
        crls={"i1": {"u2"}, "i2": {"u3"}}, dns={"i2": {"CN=B"}},
        total=7, verified={"i1": 1}, failed={"i2": 4})
    m = merge_snapshots([a, b])
    assert m.counts == {("i1", "d1"): 5, ("i1", "d2"): 1, ("i2", "d1"): 5}
    assert m.total == 11
    assert m.crls == {"i1": {"u1", "u2"}, "i2": {"u3"}}
    assert m.dns == {"i1": {"CN=A"}, "i2": {"CN=B"}}
    assert m.verified == {"i1": 3} and m.failed == {"i2": 4}


def test_expand_state_paths(tmp_path):
    from ct_mapreduce_tpu.agg.merge import expand_state_paths

    for w in range(3):
        (tmp_path / f"agg.w{w}.npz").write_bytes(b"x")
    spec = f"{tmp_path}/agg.w*.npz"
    assert expand_state_paths(spec) == [
        str(tmp_path / f"agg.w{w}.npz") for w in range(3)]
    assert expand_state_paths("a.npz, b.npz") == ["a.npz", "b.npz"]
    assert expand_state_paths("") == []


def test_merged_checkpoints_match_single_aggregator(tmp_path):
    """Two workers' device checkpoints fold into the same view one
    aggregator ingesting everything produces — the reduce-side union
    contract (disjoint serial ranges across the workers, one issuer
    shared between them so the registry remap is exercised)."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.merge import load_checkpoints
    from ct_mapreduce_tpu.utils import minicert
    from tools.fleet import snapshot_jsonable

    shared = minicert.make_cert(serial=2, issuer_cn="Shared CA", is_ca=True)
    own = [minicert.make_cert(serial=3 + w, issuer_cn=f"CA {w}", is_ca=True)
           for w in range(2)]
    batches = []
    for w in range(2):
        entries = []
        for e in range(12):
            leaf = minicert.make_cert(
                serial=10_000 * (w + 1) + e,
                issuer_cn="Shared CA" if e % 3 == 0 else f"CA {w}",
                subject_cn=f"m{w}-{e}.example",
                crl_dps=(f"http://crl.example/{w}.crl",))
            entries.append((leaf, shared if e % 3 == 0 else own[w]))
        entries.append(entries[0])  # intra-worker duplicate
        batches.append(entries)

    paths = []
    for w, entries in enumerate(batches):
        # batch_size 64: the walker shape test_cmd already compiled in
        # this process — a fresh width here costs its own ~5 s compile.
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
        agg.ingest(entries)
        path = str(tmp_path / f"agg.w{w}.npz")
        agg.save_checkpoint(path)
        paths.append(path)

    ref = TpuAggregator(capacity=1 << 10, batch_size=64)
    ref.ingest(batches[0] + batches[1])

    merged = load_checkpoints(paths)
    assert snapshot_jsonable(merged.drain()) == snapshot_jsonable(ref.drain())
    # The merged registry unified the shared issuer across workers.
    ids = {merged.registry.issuer_at(i).id()
           for i in range(len(merged.registry))}
    assert len(ids) == 3

    # storage-statistics over a multi-path aggStatePath (glob) reports
    # the fleet as ONE view, equal to the single-aggregator report —
    # text and JSON alike.
    import io

    from ct_mapreduce_tpu.cmd import storage_statistics
    from ct_mapreduce_tpu.config import CTConfig

    ref_path = str(tmp_path / "ref.npz")
    ref.save_checkpoint(ref_path)

    def config_for(state_spec):
        cfg = CTConfig.load(argv=[], env={})
        cfg.backend = "tpu"
        cfg.agg_state_path = state_spec
        return cfg

    def report_text(state_spec):
        out = io.StringIO()
        rc = storage_statistics.report_from_tpu_snapshot(
            config_for(state_spec), out, 1)
        assert rc == 0
        return out.getvalue()

    merged_text = report_text(f"{tmp_path}/agg.w*.npz")
    assert merged_text == report_text(ref_path)
    assert "overall totals" in merged_text
    # JSON mode parity too (the shared collector path).
    assert (storage_statistics.collect_tpu_report(
                config_for(f"{tmp_path}/agg.w*.npz"))
            == storage_statistics.collect_tpu_report(config_for(ref_path)))
