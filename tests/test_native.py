"""Native batch decoder: build, parity vs the Python leaf codec, and
throughput sanity. The native .so is a throughput optimization only —
`_decode_python` must produce byte-identical results, and the tests
run BOTH paths against the same wire data."""

import base64
import datetime

import numpy as np
import pytest

from ct_mapreduce_tpu.ingest import leaf as leaflib
from ct_mapreduce_tpu.native import available, leafpack

from tests import certgen

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)


def _wire_batch():
    issuer = certgen.make_cert(serial=1, issuer_cn="Native CA", is_ca=True,
                               not_after=FUTURE)
    lis, eds, expect = [], [], []
    for s in (10, 11, 12):
        leaf = certgen.make_cert(serial=s, issuer_cn="Native CA",
                                 is_ca=False, not_after=FUTURE)
        li = leaflib.encode_leaf_input(leaf, timestamp_ms=1700000000000 + s)
        ed = leaflib.encode_extra_data([issuer])
        lis.append(base64.b64encode(li).decode())
        eds.append(base64.b64encode(ed).decode())
        expect.append(leaf)
    # precert entry
    pre = certgen.make_cert(serial=99, issuer_cn="Native CA", is_ca=False,
                            not_after=FUTURE)
    li = leaflib.encode_leaf_input(b"\x00" * 12, timestamp_ms=5,
                                   entry_type=leaflib.PRECERT_ENTRY)
    ed = leaflib.encode_extra_data([issuer], entry_type=leaflib.PRECERT_ENTRY,
                                   pre_certificate=pre)
    lis.append(base64.b64encode(li).decode())
    eds.append(base64.b64encode(ed).decode())
    expect.append(pre)
    # garbage base64 + garbage leaf + no chain
    lis.append("!!!notb64!!!")
    eds.append("")
    expect.append(None)
    lis.append(base64.b64encode(b"\xff\xff\x00").decode())
    eds.append("")
    expect.append(None)
    leaf_nochain = certgen.make_cert(serial=13, issuer_cn="Native CA",
                                     is_ca=False, not_after=FUTURE)
    lis.append(base64.b64encode(
        leaflib.encode_leaf_input(leaf_nochain, timestamp_ms=7)).decode())
    eds.append("")
    expect.append(leaf_nochain)
    return lis, eds, expect, issuer


def _check(batch, expect, issuer):
    assert batch.status[0] == leafpack.OK
    for i, exp in enumerate(expect):
        if exp is None:
            assert batch.status[i] in (leafpack.BAD_B64, leafpack.BAD_LEAF,
                                       leafpack.UNSUPPORTED)
            assert batch.length[i] == 0
        else:
            got = batch.data[i, : batch.length[i]].tobytes()
            assert got == exp, f"lane {i} cert mismatch"
    # first three lanes: x509 with issuer
    for i in range(3):
        assert batch.entry_type[i] == leaflib.X509_ENTRY
        assert batch.issuers[i] == issuer
        assert batch.timestamp_ms[i] == 1700000000000 + (10 + i)
    # precert lane
    assert batch.entry_type[3] == leaflib.PRECERT_ENTRY
    assert batch.issuers[3] == issuer
    # no-chain lane: cert packed, NO_CHAIN status
    assert batch.status[6] == leafpack.NO_CHAIN
    assert batch.length[6] > 0
    assert batch.issuers[6] is None


def test_python_fallback_decode():
    lis, eds, expect, issuer = _wire_batch()
    batch = leafpack._decode_python(lis, eds, pad_len=2048)
    _check(batch, expect, issuer)


@pytest.mark.skipif(not available(), reason="no C++ compiler")
def test_native_decode_matches_python():
    lis, eds, expect, issuer = _wire_batch()
    nat = leafpack.decode_raw_batch(lis, eds, pad_len=2048)
    _check(nat, expect, issuer)
    py = leafpack._decode_python(lis, eds, pad_len=2048)
    np.testing.assert_array_equal(nat.data, py.data)
    np.testing.assert_array_equal(nat.length, py.length)
    np.testing.assert_array_equal(nat.timestamp_ms, py.timestamp_ms)
    np.testing.assert_array_equal(nat.entry_type, py.entry_type)
    np.testing.assert_array_equal(nat.status, py.status)
    assert nat.issuers == py.issuers


@pytest.mark.skipif(not available(), reason="no C++ compiler")
def test_native_python_agree_on_malformed_wire():
    """The tricky disagreement cases: over-padded base64, truncated
    extensions frame, truncated chain frame, truncated SECOND chain
    cert — native and Python must return identical statuses."""
    issuer = certgen.make_cert(serial=1, issuer_cn="Mal CA", is_ca=True,
                               not_after=FUTURE)
    leaf = certgen.make_cert(serial=5, issuer_cn="Mal CA", is_ca=False,
                             not_after=FUTURE)
    ok_ed = base64.b64encode(leaflib.encode_extra_data([issuer])).decode()

    li_full = leaflib.encode_leaf_input(leaf, timestamp_ms=1)
    cases = []
    # over-padded base64
    cases.append(("QUJD====", ok_ed))
    # extensions<2> frame missing entirely
    cases.append((base64.b64encode(li_full[:-2]).decode(), ok_ed))
    # extensions length pointing past the buffer
    trunc = li_full[:-2] + b"\x00\x10"
    cases.append((base64.b64encode(trunc).decode(), ok_ed))
    # chain frame length exceeding extra_data
    bad_frame = (len(issuer) + 100).to_bytes(3, "big") + issuer
    cases.append((base64.b64encode(li_full).decode(),
                  base64.b64encode(bad_frame).decode()))
    # second chain cert truncated
    inner = (len(issuer).to_bytes(3, "big") + issuer
             + (500).to_bytes(3, "big") + b"\x01\x02")
    bad2 = len(inner).to_bytes(3, "big") + inner
    cases.append((base64.b64encode(li_full).decode(),
                  base64.b64encode(bad2).decode()))
    # zero-length chain[0]
    empty0 = (3).to_bytes(3, "big") + (0).to_bytes(3, "big")
    cases.append((base64.b64encode(li_full).decode(),
                  base64.b64encode(empty0).decode()))

    lis = [c[0] for c in cases]
    eds = [c[1] for c in cases]
    nat = leafpack.decode_raw_batch(lis, eds, pad_len=2048)
    py = leafpack._decode_python(lis, eds, pad_len=2048)
    np.testing.assert_array_equal(nat.status, py.status)
    np.testing.assert_array_equal(nat.data, py.data)
    assert nat.issuers == py.issuers
    # and none of these were silently accepted as fully OK
    assert (nat.status != leafpack.OK).all()


@pytest.mark.skipif(not available(), reason="no C++ compiler")
def test_native_too_long_flagged():
    lis, eds, expect, issuer = _wire_batch()
    nat = leafpack.decode_raw_batch(lis[:1], eds[:1], pad_len=64)
    assert nat.status[0] == leafpack.TOO_LONG
    assert nat.length[0] == 0


def _timed(fn):
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


@pytest.mark.skipif(not available(), reason="no C++ compiler")
def test_native_throughput_sanity():
    """The native path must beat per-entry Python decode comfortably."""
    lis, eds, _, _ = _wire_batch()
    lis, eds = lis[:3] * 700, eds[:3] * 700  # 2100 entries

    # Best-of-3 each: on this one-core host a single bad scheduling
    # slice under a loaded suite can flip a single-shot comparison.
    # Results are stashed by the timed runs — no extra decode passes.
    results = {}

    def run(name, fn):
        results[name] = fn()

    t_native = min(
        _timed(lambda: run(
            "nat", lambda: leafpack.decode_raw_batch(lis, eds, pad_len=2048)))
        for _ in range(3)
    )
    t_py = min(
        _timed(lambda: run(
            "py", lambda: leafpack._decode_python(lis, eds, pad_len=2048)))
        for _ in range(3)
    )
    np.testing.assert_array_equal(results["nat"].data, results["py"].data)
    assert t_native < t_py, (t_native, t_py)
    print(f"native {2100/t_native:,.0f}/s vs python {2100/t_py:,.0f}/s")


def test_decode_threaded_matches_single():
    """The thread-pool split (multi-core host path) must stitch results
    identical to the single-shot decode, including entry order, issuer
    bytes and status codes (mixed valid/garbage/no-chain wire)."""
    lis, eds, _expect, _issuer = _wire_batch()

    single = leafpack.decode_raw_batch(lis, eds, 2048, workers=1)
    multi = leafpack.decode_raw_batch(lis, eds, 2048, workers=3)
    np.testing.assert_array_equal(single.data, multi.data)
    np.testing.assert_array_equal(single.length, multi.length)
    np.testing.assert_array_equal(single.timestamp_ms, multi.timestamp_ms)
    np.testing.assert_array_equal(single.entry_type, multi.entry_type)
    np.testing.assert_array_equal(single.status, multi.status)
    assert single.issuers == multi.issuers


def test_wire_mutation_fuzz_native_python_agreement():
    """Seeded mutation fuzz over RFC 6962 wire bytes: the C++ decoder
    and the pure-Python codec must agree on status, packed cert bytes,
    timestamps and issuer DER for every mutant (the decode path feeds
    the device pipeline, so silent divergence corrupts identities)."""
    if not available():
        pytest.skip("native library unavailable")

    rng = np.random.default_rng(20260730)
    base_lis, base_eds, _expect, _issuer = _wire_batch()
    lis, eds = [], []
    for _ in range(250):
        j = int(rng.integers(len(base_lis)))
        li = base_lis[j]
        ed = base_eds[j]
        # Mutate the BASE64 TEXT half the time (exercises b64
        # validation parity) and the underlying bytes otherwise.
        if rng.random() < 0.5 and li:
            pos = int(rng.integers(len(li)))
            li = li[:pos] + chr(33 + int(rng.integers(90))) + li[pos + 1:]
        elif ed:
            raw = bytearray(base64.b64decode(ed))
            if raw:
                pos = int(rng.integers(len(raw)))
                raw[pos] ^= int(rng.integers(1, 256))
                ed = base64.b64encode(bytes(raw)).decode()
        lis.append(li)
        eds.append(ed)

    nat = leafpack.decode_raw_batch(lis, eds, 2048, workers=1)
    py = leafpack._decode_python(lis, eds, 2048)
    np.testing.assert_array_equal(nat.status, py.status)
    np.testing.assert_array_equal(nat.length, py.length)
    np.testing.assert_array_equal(nat.data, py.data)
    np.testing.assert_array_equal(nat.timestamp_ms, py.timestamp_ms)
    np.testing.assert_array_equal(nat.entry_type, py.entry_type)
    assert nat.issuers == py.issuers
