"""Query plane (serve/): dynamic batching, snapshot isolation, and the
HTTP membership API.

The load-bearing test is the threaded ingest+query stress
(``test_concurrent_ingest_query_consistency``): queries issued WHILE
the table is growing and batches are folding must return
snapshot-consistent answers — every serial acked longer than the
staleness bound before the query reads as known, and a serial never
fed can never read known (ISSUE 5 acceptance)."""

import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core.types import ExpDate, Issuer
from ct_mapreduce_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from ct_mapreduce_tpu.serve.cache import HotSerialCache
from ct_mapreduce_tpu.serve.server import (
    MembershipOracle,
    QueryServer,
    resolve_serve,
)
from ct_mapreduce_tpu.serve.snapshot import (
    ReplicaPool,
    SnapshotManager,
    capture_view,
)
from ct_mapreduce_tpu.utils import syncerts


@pytest.fixture(scope="module")
def template():
    return syncerts.make_template(issuer_cn="Serve Test CA")


def _serial_bytes(tpl, j: int) -> bytes:
    der = syncerts.stamp_serial(tpl, j)
    return der[tpl.serial_off : tpl.serial_off + tpl.serial_len]


def _identity(tpl):
    """(issuer_id, exp_hour) shared by every restamp of a template."""
    eh = hostder.parse_cert(tpl.leaf_der).not_after_unix_hour
    issuer_id = Issuer.from_spki(
        hostder.parse_cert(tpl.issuer_der).spki).id()
    return issuer_id, eh


# -- MicroBatcher ---------------------------------------------------------


def test_batcher_coalesces_concurrent_requests():
    """Concurrent single-item submits form batches > 1 (the whole
    point of the micro-batcher): a slow oracle keeps the worker busy
    while followers queue, so the next batch carries them all."""
    sizes = []

    def oracle(items):
        sizes.append(len(items))
        time.sleep(0.02)
        return [it * 2 for it in items]

    b = MicroBatcher(oracle, max_batch=64, max_delay_s=0.005)
    try:
        results = {}

        def client(k):
            results[k] = b.submit([k])[0]

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {k: 2 * k for k in range(24)}
        assert sum(sizes) == 24
        assert max(sizes) > 1, f"no coalescing happened: {sizes}"
    finally:
        b.close()


def test_batcher_respects_max_batch():
    sizes = []

    def oracle(items):
        sizes.append(len(items))
        return items

    b = MicroBatcher(oracle, max_batch=4, max_delay_s=0.05)
    try:
        # One request never splits; several small ones pack up to the cap.
        outs = []
        threads = [threading.Thread(
            target=lambda k=k: outs.append(tuple(b.submit([k, k])))
        ) for k in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(outs) == sorted((k, k) for k in range(6))
        assert max(sizes) <= 4
    finally:
        b.close()


def test_batcher_sheds_on_full_queue_with_explicit_rejection():
    release = threading.Event()

    def oracle(items):
        release.wait(timeout=5)
        return items

    b = MicroBatcher(oracle, max_batch=8, max_delay_s=0.001,
                     max_queue_lanes=4)
    try:
        accepted, shed = [], []

        def client(k):
            try:
                accepted.append(b.submit([k])[0])
            except Overloaded:
                shed.append(k)

        # First submit occupies the worker; the queue then fills to its
        # 4-lane cap and the rest must be REJECTED, not queued.
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(12)]
        for t in threads:
            t.start()
            time.sleep(0.002)  # deterministic arrival order
        release.set()
        for t in threads:
            t.join()
        assert shed, "no request was shed despite a 4-lane cap"
        assert accepted, "every request was shed"
        assert len(accepted) + len(shed) == 12
        assert sorted(accepted + shed) == list(range(12))
    finally:
        release.set()
        b.close()


def test_batcher_deadline_expires_queued_request():
    release = threading.Event()

    def oracle(items):
        release.wait(timeout=5)
        return items

    b = MicroBatcher(oracle, max_batch=8, max_delay_s=0.001)
    try:
        first = threading.Thread(target=lambda: b.submit([0]))
        first.start()
        time.sleep(0.02)  # worker is now blocked inside the oracle
        # Unblock the oracle AFTER the second request's 10 ms deadline
        # has passed — by the time its batch forms, it is stale.
        threading.Timer(0.1, release.set).start()
        with pytest.raises(DeadlineExceeded):
            b.submit([1], timeout_s=0.01)
        first.join(timeout=5)
    finally:
        release.set()
        b.close()


def test_batcher_close_fails_pending_loudly():
    hold = threading.Event()

    def oracle(items):
        hold.wait(timeout=5)
        return items

    b = MicroBatcher(oracle, max_batch=2, max_delay_s=0.001)
    errs = []

    def client():
        try:
            b.submit([1])
        except RuntimeError as e:
            errs.append(e)

    t = threading.Thread(target=client)
    t.start()
    time.sleep(0.02)
    hold.set()
    b.close()
    t.join(timeout=5)
    with pytest.raises(RuntimeError):
        b.submit([2])


# -- snapshot views -------------------------------------------------------


def test_view_membership_and_staleness(template):
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    entries = [(syncerts.stamp_serial(template, j), template.issuer_der)
               for j in range(40)]
    agg.ingest(entries)
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)

    view = capture_view(agg, epoch=1)
    present = [(idx, eh, _serial_bytes(template, j)) for j in range(40)]
    absent = [(idx, eh, _serial_bytes(template, j))
              for j in range(1000, 1010)]
    got = view.lookup(present + absent)
    assert got[:40].all()
    assert not got[40:].any()
    assert view.age_s() >= 0
    # Unknown issuer / out-of-range lanes answer False, never crash.
    odd = [(-1, eh, b"\x01"), (idx, 0, b"\x01"),
           (idx, eh, b"\x01" * 64)]
    assert not view.lookup(odd).any()
    # The view is PINNED: later ingest must not leak in.
    agg.ingest([(syncerts.stamp_serial(template, 500),
                 template.issuer_der)])
    assert not view.lookup([(idx, eh, _serial_bytes(template, 500))])[0]
    assert capture_view(agg, epoch=2).lookup(
        [(idx, eh, _serial_bytes(template, 500))])[0]


def test_view_covers_host_lane_serials(template):
    """Serials that took the exact host lane (oversized DER) are part
    of membership too — the view freezes the host sets."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    issuer_idx = agg.registry.get_or_assign(template.issuer_der)
    entries = [(syncerts.stamp_serial(template, j), template.issuer_der)
               for j in range(4)]
    agg.ingest(entries)
    # Land one serial in the exact host lane through the same dedup
    # call every flagged lane takes.
    fields = hostder.parse_cert(syncerts.stamp_serial(template, 99))
    agg._host_dedup(fields, issuer_idx, fields.not_after_unix_hour)
    view = capture_view(agg, epoch=1)
    _, eh = _identity(template)
    assert view.lookup(
        [(issuer_idx, eh, _serial_bytes(template, 99))])[0]


def test_view_device_mode_parity(template):
    """device=True runs the jitted contains kernels on a pinned device
    copy with pow2 padding — answers must match the host path."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(33)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    items = [(idx, eh, _serial_bytes(template, j)) for j in range(50)]
    host = capture_view(agg, epoch=1, device=False).lookup(items)
    dev = capture_view(agg, epoch=1, device=True).lookup(items)
    assert np.array_equal(host, dev)
    assert host[:33].all() and not host[33:].any()


def test_view_sharded_aggregator(template):
    """The sharded read view routes fingerprints to their home shard's
    row block — parity against the device-side global contains."""
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    mesh = Mesh(np.array(jax.devices()), ("shard",))
    agg = ShardedAggregator(mesh, capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(64)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    items = [(idx, eh, _serial_bytes(template, j)) for j in range(80)]
    view = capture_view(agg, epoch=1)
    assert view.n_shards == mesh.devices.size
    got = view.lookup(items)
    assert got[:64].all() and not got[64:].any()
    # Cross-check the routed host probe against the device global
    # contains on the same fingerprints.
    from ct_mapreduce_tpu.core import packing

    fps = np.array(
        [packing.fingerprint_host(idx, eh, _serial_bytes(template, j))
         for j in range(80)], np.uint32)
    assert np.array_equal(view.contains_fps(fps),
                          np.asarray(agg._device_contains(fps)))


def test_snapshot_manager_staleness_refresh(template):
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    mgr = SnapshotManager(agg, max_staleness_s=1000.0)
    v1 = mgr.view()
    assert mgr.view() is v1  # fresh enough → same epoch
    v2 = mgr.refresh()
    assert v2.epoch == v1.epoch + 1
    mgr.max_staleness_s = 0.0
    assert mgr.view().epoch > v2.epoch  # stale → refreshed


# -- the concurrency acceptance test --------------------------------------


def test_concurrent_ingest_query_consistency(template):
    """Ingest and query race for real: a writer thread feeds batches
    through a growing table (capacity starts at 1<<10 so grow-and-
    rehash fires mid-run) while reader threads query through a
    MembershipOracle with a tight staleness bound. Contract (round 12,
    epoch-honest): every answer surfaces its view's age, and a serial
    acked before that view's capture MUST read known — the replica
    pool's staggered refresh means serving never blocks on a capture
    (a mid-grow capture can take seconds while it waits on the fold
    lock), so staleness is surfaced rather than wall-clock-capped. A
    serial never fed must NEVER read known, at any epoch, and
    refreshes must actually keep landing (some answers fresh within
    the bound)."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64,
                        max_capacity=1 << 14, grow_at=0.55)
    issuer_idx = agg.registry.get_or_assign(template.issuer_der)
    _, eh = _identity(template)
    stale = 0.05
    oracle = MembershipOracle(agg, max_batch=256, max_delay_s=0.002,
                              max_staleness_s=stale)
    fresh_ages: list[float] = []
    epoch_walls: dict[int, float] = {}  # epoch -> capture-start wall
    acked: dict[int, float] = {}
    acked_lock = threading.Lock()
    stop = threading.Event()
    errors: list[str] = []
    n_batches, batch = 14, 64  # 896 lanes > 0.55 x 1024 ⇒ grow fires

    def writer():
        try:
            for b in range(n_batches):
                entries = [
                    (syncerts.stamp_serial(template, b * batch + j),
                     template.issuer_der)
                    for j in range(batch)
                ]
                agg.ingest(entries)  # returns ⇒ acked
                now = time.time()
                with acked_lock:
                    for j in range(batch):
                        acked[b * batch + j] = now
        except Exception as err:  # pragma: no cover - fails the test
            errors.append(f"writer: {err!r}")
        finally:
            stop.set()

    def reader(seed):
        r = np.random.default_rng(seed)
        while not stop.is_set() or r.integers(2) == 0:
            with acked_lock:
                known_now = dict(acked)
            if not known_now:
                time.sleep(0.001)
                continue
            js = list(known_now)
            pick = [js[int(r.integers(len(js)))] for _ in range(4)]
            ghosts = [int(r.integers(10**6, 2 * 10**6)) for _ in range(2)]
            items = [(issuer_idx, eh, _serial_bytes(template, j))
                     for j in pick + ghosts]
            try:
                res = oracle.query_raw(items)
            except Overloaded:
                continue
            # The authoritative capture instants: created_wall is
            # anchored at capture START (before the fold-lock wait),
            # so "acked before it" under-approximates "acked before
            # the lock was held" — the direction that keeps the check
            # sound under multi-second mid-grow captures.
            for rep in list(oracle.snapshots._replicas):
                epoch_walls[rep.epoch] = rep.created_wall
            for (known, epoch, age), j in zip(res, pick + ghosts):
                if j in known_now:
                    wall = epoch_walls.get(epoch)
                    if not known and wall is not None \
                            and known_now[j] < wall - 0.05:
                        errors.append(
                            f"acked serial {j} invisible in epoch "
                            f"{epoch}, captured "
                            f"{wall - known_now[j]:.3f}s after its ack")
                elif known:
                    errors.append(f"false positive: ghost serial {j}")
                if not stop.is_set():
                    fresh_ages.append(age)  # GIL-atomic append
            if stop.is_set():
                break

    w = threading.Thread(target=writer)
    readers = [threading.Thread(target=reader, args=(s,)) for s in (1, 2)]
    w.start()
    for t in readers:
        t.start()
    w.join(timeout=120)
    for t in readers:
        t.join(timeout=30)
    # Liveness: staggered refresh keeps landing — the pool advances
    # through multiple epochs instead of serving one ancient view
    # forever. How many land DURING the fixed-length load window is
    # box-speed-dependent (compile-inflated captures on a loaded
    # 1-core CI run can swallow most of it — observed round 17), so
    # the check is a bounded WAIT for the third epoch, not a snapshot
    # of whatever the window happened to reach: queries keep flowing
    # until the refresh machinery proves it is still advancing.
    deadline = time.time() + 60
    while (oracle.snapshots.stats()["snapshot_epoch"] < 3
           and time.time() < deadline):
        with contextlib.suppress(Overloaded):
            oracle.query_raw(
                [(issuer_idx, eh, _serial_bytes(template, 0))])
        time.sleep(0.05)
    pool_stats = oracle.snapshots.stats()
    oracle.close()
    assert not errors, errors[:10]
    assert agg.metrics.get("overflow", 0) >= 0  # table survived
    # The run really exercised growth (the mid-grow torn-read hazard).
    assert agg.capacity > 1 << 10, "table never grew; raise n_batches"
    assert fresh_ages, "no answers recorded"
    assert pool_stats["snapshot_epoch"] >= 3, pool_stats
    assert pool_stats["replicas"] >= 2, pool_stats
    # And the final state is complete: every fed serial present.
    final = capture_view(agg, epoch=99)
    items = [(issuer_idx, eh, _serial_bytes(template, j))
             for j in range(n_batches * batch)]
    assert final.lookup(items).all()


# -- HTTP server ----------------------------------------------------------


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_query_server_http_api(template):
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(20)])
    issuer_id, eh = _identity(template)
    exp_id = ExpDate.from_unix_hour(eh).id()
    srv = QueryServer(agg, 0, host="127.0.0.1",
                      max_delay_s=0.001).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # Bulk query: present + absent, epoch and staleness surfaced.
        queries = [{"issuer": issuer_id, "expDate": exp_id,
                    "serial": _serial_bytes(template, j).hex()}
                   for j in (0, 5, 19, 777)]
        code, body = _post(f"{base}/query", {"queries": queries})
        assert code == 200
        assert [r["known"] for r in body["results"]] == [
            True, True, True, False]
        assert body["epoch"] >= 1 and body["staleness_s"] >= 0
        # Single-query shorthand.
        code, body = _post(f"{base}/query", {
            "issuer": issuer_id, "expDate": exp_id,
            "serial": _serial_bytes(template, 5).hex()})
        assert code == 200 and body["known"] is True
        # Unknown issuer: honest False.
        code, body = _post(f"{base}/query", {
            "issuer": "nosuchissuer=", "expDate": exp_id,
            "serial": "4d00"})
        assert code == 200 and body["known"] is False
        # Malformed: 400, not a 500.
        for bad in ({"queries": []},
                    {"issuer": issuer_id, "expDate": exp_id,
                     "serial": "zz"},
                    {"issuer": issuer_id, "expDate": "June 15",
                     "serial": "4d00"}):
            req = urllib.request.Request(
                f"{base}/query", data=json.dumps(bad).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400
        # Issuer metadata.
        from urllib.parse import quote

        with urllib.request.urlopen(
                f"{base}/issuer/{quote(issuer_id, safe='')}",
                timeout=10) as resp:
            meta = json.loads(resp.read())
        assert meta["unknown_total"] == 20
        assert meta["dns"] == 1 and meta["crls"] == 1
        assert "staleness_s" in meta
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/issuer/doesnotexist",
                                   timeout=10)
        assert ei.value.code == 404
        # Health: queue + snapshot numbers.
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            h = json.loads(resp.read())
        assert h["healthy"] and h["queue_cap"] > 0
        assert h["snapshot_epoch"] >= 1
    finally:
        srv.stop()


def test_query_server_sheds_with_429(template):
    """Overload answers 429 overloaded — never an unbounded queue."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, 0), template.issuer_der)])
    issuer_id, eh = _identity(template)
    exp_id = ExpDate.from_unix_hour(eh).id()
    srv = QueryServer(agg, 0, host="127.0.0.1", max_queue_lanes=2,
                      max_delay_s=0.001).start()
    try:
        # A 3-lane request cannot be admitted into a 2-lane queue.
        q = {"issuer": issuer_id, "expDate": exp_id,
             "serial": _serial_bytes(template, 0).hex()}
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/query",
            data=json.dumps({"queries": [q, q, q]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert json.loads(ei.value.read())["error"] == "overloaded"
        # The plane still answers admissible requests afterwards.
        code, body = _post(f"http://127.0.0.1:{srv.port}/query", q)
        assert code == 200 and body["known"] is True
    finally:
        srv.stop()


def test_query_server_getcert_proxy():
    """/getcert proxies one log entry as PEM (ct-getcert's routed
    path), using the server's transport override."""
    from tests.fakelog import FakeLog
    from tests import certgen
    import datetime

    log = FakeLog()
    future = datetime.datetime(2031, 6, 15, tzinfo=datetime.timezone.utc)
    issuer_der = certgen.make_cert(serial=1, issuer_cn="Proxy CA",
                                   is_ca=True, not_after=future)
    leaf = certgen.make_cert(serial=1000, issuer_cn="Proxy CA",
                             subject_cn="proxy.example.com", is_ca=False,
                             not_after=future)
    log.add_cert(leaf, issuer_der, timestamp_ms=1700000000000)
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    srv = QueryServer(agg, 0, host="127.0.0.1",
                      transport=log.transport).start()
    try:
        from urllib.parse import urlencode

        qs = urlencode({"log": log.url, "index": 0})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/getcert?{qs}",
                timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["pem"].startswith("-----BEGIN CERTIFICATE-----")
        with pytest.raises(urllib.error.HTTPError) as ei:
            qs = urlencode({"log": log.url, "index": 99})
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/getcert?{qs}", timeout=10)
        assert ei.value.code in (404, 502)
    finally:
        srv.stop()


def test_serve_batch_spans_recorded(template):
    """serve.batch spans carry lane counts — what the bench serve leg
    derives its batching-effectiveness gate from."""
    from ct_mapreduce_tpu.telemetry import trace

    tracer = trace.enable()
    t0 = tracer.now_us()
    try:
        agg = TpuAggregator(capacity=1 << 12, batch_size=64)
        agg.ingest([(syncerts.stamp_serial(template, j),
                     template.issuer_der) for j in range(8)])
        issuer_id, eh = _identity(template)
        idx = agg.registry.index_of_issuer_id(issuer_id)
        oracle = MembershipOracle(agg, max_batch=64, max_delay_s=0.01)
        threads = [threading.Thread(
            target=lambda j=j: oracle.query_raw(
                [(idx, eh, _serial_bytes(template, j))])
        ) for j in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        oracle.close()
        spans = [e for e in tracer.events()
                 if e.get("ph") == "X" and e["name"] == "serve.batch"
                 and e["ts"] >= t0]
        assert spans, "no serve.batch spans recorded"
        lanes = sum(e["args"]["lanes"] for e in spans)
        assert lanes == 8
        waits = [e for e in tracer.events()
                 if e.get("ph") == "X" and e["name"] == "serve.wait"
                 and e["ts"] >= t0]
        assert len(waits) == 8
    finally:
        trace.disable()


# -- replica pool (round 12) ----------------------------------------------


def test_replica_pool_mixed_epoch_parity_fuzz(template):
    """N replicas at MIXED epochs through table growth must agree with
    the serial truth set: on every replica, every serial acked before
    that replica's capture reads known, and ghosts read absent at
    every epoch (the ISSUE 7 parity-fuzz acceptance)."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64,
                        max_capacity=1 << 14, grow_at=0.55)
    issuer_idx = agg.registry.get_or_assign(template.issuer_der)
    _, eh = _identity(template)
    pool = ReplicaPool(agg, n_replicas=3, max_staleness_s=1e9,
                       device=True)
    rng = np.random.default_rng(7)
    acked = 0
    truth_at_capture: dict[int, int] = {}
    for _stage in range(6):  # 576 lanes through a 1<<10 table ⇒ grows
        agg.ingest([
            (syncerts.stamp_serial(template, acked + i),
             template.issuer_der)
            for i in range(96)
        ])
        acked += 96
        v = pool.refresh()  # staggered: swaps exactly ONE replica
        truth_at_capture[v.epoch] = acked
    assert agg.capacity > 1 << 10, "table never grew"
    reps = list(pool._replicas)
    assert len(reps) == 3
    assert len({r.epoch for r in reps}) == 3, "epochs not mixed"
    for r in reps:
        n_known = truth_at_capture[r.epoch]
        pick = [int(j) for j in rng.integers(0, acked, size=48)]
        ghosts = [int(j) for j in rng.integers(10**6, 2 * 10**6, size=16)]
        items = [(issuer_idx, eh, _serial_bytes(template, j))
                 for j in pick + ghosts]
        got = r.lookup(items)
        for k, j in enumerate(pick):
            if j < n_known:
                assert got[k], (
                    f"epoch {r.epoch}: serial {j} acked before capture "
                    f"(truth {n_known}) reads absent")
        assert not got[len(pick):].any(), f"ghost hit at epoch {r.epoch}"
    # Round-robin serving rotates through every live replica.
    served = {pool.view().epoch for _ in range(9)}
    assert served == {r.epoch for r in reps}
    # floor_epoch is the oldest live epoch (the cache validity horizon).
    assert pool.floor_epoch() == min(r.epoch for r in reps)


def test_replica_pool_shard_routed_block_pinning(template):
    """On a multi-device mesh the pool pins per-shard row blocks, each
    on its shard's own device — never the full global rows on one chip
    — and the shard-routed probe answers with exact parity."""
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    mesh = Mesh(np.array(jax.devices()), ("shard",))
    agg = ShardedAggregator(mesh, capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(64)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    pool = ReplicaPool(agg, n_replicas=2, max_staleness_s=1e9,
                       device=True).warm()
    devs = jax.devices()
    for v in pool._replicas:
        assert v.n_shards == mesh.devices.size
        assert v._dev_blocks is not None, "replica pinned no blocks"
        assert v._dev_rows is None, "replica pinned the full global rows"
        block = v.rows.shape[0] // v.n_shards
        for s, state in enumerate(v._dev_blocks):
            assert state.rows.shape[0] == block
            assert list(state.rows.devices()) == [devs[s % len(devs)]]
    items = [(idx, eh, _serial_bytes(template, j)) for j in range(80)]
    for v in pool._replicas:
        got = v.lookup(items)
        assert got[:64].all() and not got[64:].any()
        # Device parity against the pure-host routed mirror.
        host = capture_view(agg, epoch=99).lookup(items)
        assert np.array_equal(got, host)


def test_view_device_fallback_to_host(template, monkeypatch):
    """A view that cannot pin a device copy degrades to the host
    mirror (serve.device_fallback) instead of failing the batch."""
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(10)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        view = capture_view(agg, epoch=1, device=True)
        import jax.numpy as jnp
        monkeypatch.setattr(jnp, "asarray", lambda *a, **k: (
            (_ for _ in ()).throw(RuntimeError("no device"))))
        items = [(idx, eh, _serial_bytes(template, j)) for j in range(12)]
        got = view.lookup(items)
        assert got[:10].all() and not got[10:].any()
        assert view._device is False  # latched to the host path
        counters = sink.snapshot()["counters"]
        assert counters.get("serve.device_fallback", 0) >= 1
        # Subsequent lookups answer from the host mirror directly.
        assert view.lookup(items[:3]).all()
    finally:
        tmetrics.set_sink(prev)


# -- hot-serial cache ------------------------------------------------------


def test_hot_serial_cache_unit():
    """Epoch-floor validity + LRU bound + no answer downgrades."""
    c = HotSerialCache(capacity=2)
    c.put(("a",), known=False, epoch=1, created_wall=0.0)
    assert c.get(("a",), floor_epoch=1).known is False  # hit after miss
    # Floor bump (every replica refreshed past epoch 1) ⇒ the entry is
    # unreachable and evicted on probe — no ghost answers across epochs.
    assert c.get(("a",), floor_epoch=2) is None
    assert c.get(("a",), floor_epoch=1) is None
    c.put(("a",), True, 3, 0.0)
    c.put(("b",), True, 3, 0.0)
    c.put(("c",), True, 3, 0.0)
    assert len(c) == 2  # LRU bound holds
    assert c.get(("a",), 3) is None  # oldest evicted
    c.put(("b",), False, 2, 0.0)  # older epoch must not downgrade
    assert c.get(("b",), 2).known is True
    disabled = HotSerialCache(capacity=0)
    disabled.put(("x",), True, 1, 0.0)
    assert disabled.get(("x",), 1) is None and len(disabled) == 0


def test_oracle_cache_hit_and_epoch_invalidation(template):
    """Through the oracle: a miss fills the cache, the repeat hits it
    (same answer, no new batch), and once every replica refreshes past
    the cached epoch a formerly-absent serial reads known — the stale
    False cannot ghost across epochs."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(20)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    oracle = MembershipOracle(agg, max_batch=64, max_delay_s=0.001,
                              max_staleness_s=1e9, replicas=2,
                              cache_size=128)
    try:
        present = (idx, eh, _serial_bytes(template, 3))
        ghost = (idx, eh, _serial_bytes(template, 999))
        r1 = oracle.query_raw([present, ghost])
        assert r1[0][0] is True and r1[1][0] is False
        assert len(oracle.cache) == 2
        batches_before = oracle.snapshots.stats()  # noqa: F841
        hits0, misses0 = oracle.cache.hits, oracle.cache.misses
        r2 = oracle.query_raw([present, ghost])
        assert oracle.cache.hits == hits0 + 2  # pure cache round
        assert oracle.cache.misses == misses0
        assert r2[0][0] is True and r2[1][0] is False
        assert r2[0][1] <= r1[0][1] + 1  # epoch surfaced, not invented
        # Ingest the ghost, then refresh EVERY replica past the cached
        # epoch: the stale False must be invalidated by construction.
        agg.ingest([(syncerts.stamp_serial(template, 999),
                     template.issuer_der)])
        for _ in range(oracle.snapshots.n_replicas):
            oracle.snapshots.refresh()
        r3 = oracle.query_raw([ghost])
        assert r3[0][0] is True, "stale cached False ghosted across epochs"
    finally:
        oracle.close()


# -- oversized-bulk split --------------------------------------------------


def test_bulk_split_oversized_submit_under_ingest(template):
    """A bulk larger than max_batch splits into max_batch-sized
    sub-requests (serve.split_requests), reassembled in order with
    exact parity — while ingest keeps feeding the table."""
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics
    from ct_mapreduce_tpu.telemetry import trace

    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(40)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    tracer = trace.enable()
    t0 = tracer.now_us()
    oracle = MembershipOracle(agg, max_batch=16, max_delay_s=0.001,
                              max_staleness_s=0.05, cache_size=-1)
    stop = threading.Event()

    def bg_ingest():
        j0 = 2000
        while not stop.is_set() and j0 < 2600:
            agg.ingest([(syncerts.stamp_serial(template, j),
                         template.issuer_der)
                        for j in range(j0, j0 + 64)])
            j0 += 64

    bg = threading.Thread(target=bg_ingest)
    bg.start()
    try:
        # 40 present + 20 absent = 60 lanes through a 16-lane cap.
        items = [(idx, eh, _serial_bytes(template, j)) for j in range(40)]
        items += [(idx, eh, _serial_bytes(template, j))
                  for j in range(5000, 5020)]
        for _ in range(3):
            res = oracle.query_raw(items)
            assert [r[0] for r in res] == [True] * 40 + [False] * 20
    finally:
        stop.set()
        bg.join()
        oracle.close()
        trace.disable()
        tmetrics.set_sink(prev)
    counters = sink.snapshot()["counters"]
    assert counters.get("serve.split_requests", 0) >= 3
    spans = [e for e in tracer.events()
             if e.get("ph") == "X" and e["name"] == "serve.batch"
             and e["ts"] >= t0]
    assert spans and all(e["args"]["lanes"] <= 16 for e in spans), \
        "an executed batch exceeded max_batch"


# -- staleness observability (refresh_in_flight / snapshot_age_s) ---------


def test_refresh_in_flight_and_age_surfaced(template, monkeypatch):
    from ct_mapreduce_tpu.serve import snapshot as snapmod
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics

    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        mgr = SnapshotManager(agg, max_staleness_s=1000.0)
        assert mgr.refresh_in_flight is False
        seen = {}
        orig = snapmod.capture_view

        def spying_capture(a, epoch, device=False, devices=None):
            seen["in_flight"] = mgr.refresh_in_flight
            return orig(a, epoch, device=device, devices=devices)

        monkeypatch.setattr(snapmod, "capture_view", spying_capture)
        mgr.refresh()
        monkeypatch.setattr(snapmod, "capture_view", orig)
        assert seen["in_flight"] is True  # flag held across the capture
        assert mgr.refresh_in_flight is False
        st = mgr.stats()
        assert st["refresh_in_flight"] is False
        assert st["snapshot_epoch"] == 1 and st["snapshot_age_s"] >= 0
        mgr.view()
        gauges = sink.snapshot()["gauges"]
        assert "serve.snapshot_age_s" in gauges
        # The pool surfaces the same observability per replica set.
        pool = ReplicaPool(agg, n_replicas=2, max_staleness_s=1e9,
                           device=False).warm()
        pst = pool.stats()
        assert pst["refresh_in_flight"] is False
        assert pst["replicas"] == 2 and len(pst["replica_epochs"]) == 2
        assert pst["snapshot_age_s"] is not None
    finally:
        tmetrics.set_sink(prev)


def test_resolve_serve_layering(monkeypatch):
    """explicit > CTMR_SERVE_* env > defaults, unparseable ignored."""
    for k in ("CTMR_SERVE_REPLICAS", "CTMR_SERVE_DEVICE",
              "CTMR_SERVE_CACHE_SIZE"):
        monkeypatch.delenv(k, raising=False)
    assert resolve_serve() == (2, True, 4096)
    assert resolve_serve(replicas=5, device=False, cache_size=64) == \
        (5, False, 64)
    assert resolve_serve(cache_size=-1)[2] == 0  # -1 disables
    monkeypatch.setenv("CTMR_SERVE_REPLICAS", "7")
    monkeypatch.setenv("CTMR_SERVE_DEVICE", "0")
    monkeypatch.setenv("CTMR_SERVE_CACHE_SIZE", "99")
    assert resolve_serve() == (7, False, 99)
    assert resolve_serve(replicas=3, device=True, cache_size=16) == \
        (3, True, 16)  # explicit beats env
    monkeypatch.setenv("CTMR_SERVE_REPLICAS", "banana")
    assert resolve_serve()[0] == 2  # unparseable env ignored
