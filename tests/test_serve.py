"""Query plane (serve/): dynamic batching, snapshot isolation, and the
HTTP membership API.

The load-bearing test is the threaded ingest+query stress
(``test_concurrent_ingest_query_consistency``): queries issued WHILE
the table is growing and batches are folding must return
snapshot-consistent answers — every serial acked longer than the
staleness bound before the query reads as known, and a serial never
fed can never read known (ISSUE 5 acceptance)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.core.types import ExpDate, Issuer
from ct_mapreduce_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from ct_mapreduce_tpu.serve.server import MembershipOracle, QueryServer
from ct_mapreduce_tpu.serve.snapshot import SnapshotManager, capture_view
from ct_mapreduce_tpu.utils import syncerts


@pytest.fixture(scope="module")
def template():
    return syncerts.make_template(issuer_cn="Serve Test CA")


def _serial_bytes(tpl, j: int) -> bytes:
    der = syncerts.stamp_serial(tpl, j)
    return der[tpl.serial_off : tpl.serial_off + tpl.serial_len]


def _identity(tpl):
    """(issuer_id, exp_hour) shared by every restamp of a template."""
    eh = hostder.parse_cert(tpl.leaf_der).not_after_unix_hour
    issuer_id = Issuer.from_spki(
        hostder.parse_cert(tpl.issuer_der).spki).id()
    return issuer_id, eh


# -- MicroBatcher ---------------------------------------------------------


def test_batcher_coalesces_concurrent_requests():
    """Concurrent single-item submits form batches > 1 (the whole
    point of the micro-batcher): a slow oracle keeps the worker busy
    while followers queue, so the next batch carries them all."""
    sizes = []

    def oracle(items):
        sizes.append(len(items))
        time.sleep(0.02)
        return [it * 2 for it in items]

    b = MicroBatcher(oracle, max_batch=64, max_delay_s=0.005)
    try:
        results = {}

        def client(k):
            results[k] = b.submit([k])[0]

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {k: 2 * k for k in range(24)}
        assert sum(sizes) == 24
        assert max(sizes) > 1, f"no coalescing happened: {sizes}"
    finally:
        b.close()


def test_batcher_respects_max_batch():
    sizes = []

    def oracle(items):
        sizes.append(len(items))
        return items

    b = MicroBatcher(oracle, max_batch=4, max_delay_s=0.05)
    try:
        # One request never splits; several small ones pack up to the cap.
        outs = []
        threads = [threading.Thread(
            target=lambda k=k: outs.append(tuple(b.submit([k, k])))
        ) for k in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(outs) == sorted((k, k) for k in range(6))
        assert max(sizes) <= 4
    finally:
        b.close()


def test_batcher_sheds_on_full_queue_with_explicit_rejection():
    release = threading.Event()

    def oracle(items):
        release.wait(timeout=5)
        return items

    b = MicroBatcher(oracle, max_batch=8, max_delay_s=0.001,
                     max_queue_lanes=4)
    try:
        accepted, shed = [], []

        def client(k):
            try:
                accepted.append(b.submit([k])[0])
            except Overloaded:
                shed.append(k)

        # First submit occupies the worker; the queue then fills to its
        # 4-lane cap and the rest must be REJECTED, not queued.
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(12)]
        for t in threads:
            t.start()
            time.sleep(0.002)  # deterministic arrival order
        release.set()
        for t in threads:
            t.join()
        assert shed, "no request was shed despite a 4-lane cap"
        assert accepted, "every request was shed"
        assert len(accepted) + len(shed) == 12
        assert sorted(accepted + shed) == list(range(12))
    finally:
        release.set()
        b.close()


def test_batcher_deadline_expires_queued_request():
    release = threading.Event()

    def oracle(items):
        release.wait(timeout=5)
        return items

    b = MicroBatcher(oracle, max_batch=8, max_delay_s=0.001)
    try:
        first = threading.Thread(target=lambda: b.submit([0]))
        first.start()
        time.sleep(0.02)  # worker is now blocked inside the oracle
        # Unblock the oracle AFTER the second request's 10 ms deadline
        # has passed — by the time its batch forms, it is stale.
        threading.Timer(0.1, release.set).start()
        with pytest.raises(DeadlineExceeded):
            b.submit([1], timeout_s=0.01)
        first.join(timeout=5)
    finally:
        release.set()
        b.close()


def test_batcher_close_fails_pending_loudly():
    hold = threading.Event()

    def oracle(items):
        hold.wait(timeout=5)
        return items

    b = MicroBatcher(oracle, max_batch=2, max_delay_s=0.001)
    errs = []

    def client():
        try:
            b.submit([1])
        except RuntimeError as e:
            errs.append(e)

    t = threading.Thread(target=client)
    t.start()
    time.sleep(0.02)
    hold.set()
    b.close()
    t.join(timeout=5)
    with pytest.raises(RuntimeError):
        b.submit([2])


# -- snapshot views -------------------------------------------------------


def test_view_membership_and_staleness(template):
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    entries = [(syncerts.stamp_serial(template, j), template.issuer_der)
               for j in range(40)]
    agg.ingest(entries)
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)

    view = capture_view(agg, epoch=1)
    present = [(idx, eh, _serial_bytes(template, j)) for j in range(40)]
    absent = [(idx, eh, _serial_bytes(template, j))
              for j in range(1000, 1010)]
    got = view.lookup(present + absent)
    assert got[:40].all()
    assert not got[40:].any()
    assert view.age_s() >= 0
    # Unknown issuer / out-of-range lanes answer False, never crash.
    odd = [(-1, eh, b"\x01"), (idx, 0, b"\x01"),
           (idx, eh, b"\x01" * 64)]
    assert not view.lookup(odd).any()
    # The view is PINNED: later ingest must not leak in.
    agg.ingest([(syncerts.stamp_serial(template, 500),
                 template.issuer_der)])
    assert not view.lookup([(idx, eh, _serial_bytes(template, 500))])[0]
    assert capture_view(agg, epoch=2).lookup(
        [(idx, eh, _serial_bytes(template, 500))])[0]


def test_view_covers_host_lane_serials(template):
    """Serials that took the exact host lane (oversized DER) are part
    of membership too — the view freezes the host sets."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    issuer_idx = agg.registry.get_or_assign(template.issuer_der)
    entries = [(syncerts.stamp_serial(template, j), template.issuer_der)
               for j in range(4)]
    agg.ingest(entries)
    # Land one serial in the exact host lane through the same dedup
    # call every flagged lane takes.
    fields = hostder.parse_cert(syncerts.stamp_serial(template, 99))
    agg._host_dedup(fields, issuer_idx, fields.not_after_unix_hour)
    view = capture_view(agg, epoch=1)
    _, eh = _identity(template)
    assert view.lookup(
        [(issuer_idx, eh, _serial_bytes(template, 99))])[0]


def test_view_device_mode_parity(template):
    """device=True runs the jitted contains kernels on a pinned device
    copy with pow2 padding — answers must match the host path."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(33)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    items = [(idx, eh, _serial_bytes(template, j)) for j in range(50)]
    host = capture_view(agg, epoch=1, device=False).lookup(items)
    dev = capture_view(agg, epoch=1, device=True).lookup(items)
    assert np.array_equal(host, dev)
    assert host[:33].all() and not host[33:].any()


def test_view_sharded_aggregator(template):
    """The sharded read view routes fingerprints to their home shard's
    row block — parity against the device-side global contains."""
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    mesh = Mesh(np.array(jax.devices()), ("shard",))
    agg = ShardedAggregator(mesh, capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(64)])
    issuer_id, eh = _identity(template)
    idx = agg.registry.index_of_issuer_id(issuer_id)
    items = [(idx, eh, _serial_bytes(template, j)) for j in range(80)]
    view = capture_view(agg, epoch=1)
    assert view.n_shards == mesh.devices.size
    got = view.lookup(items)
    assert got[:64].all() and not got[64:].any()
    # Cross-check the routed host probe against the device global
    # contains on the same fingerprints.
    from ct_mapreduce_tpu.core import packing

    fps = np.array(
        [packing.fingerprint_host(idx, eh, _serial_bytes(template, j))
         for j in range(80)], np.uint32)
    assert np.array_equal(view.contains_fps(fps),
                          np.asarray(agg._device_contains(fps)))


def test_snapshot_manager_staleness_refresh(template):
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    mgr = SnapshotManager(agg, max_staleness_s=1000.0)
    v1 = mgr.view()
    assert mgr.view() is v1  # fresh enough → same epoch
    v2 = mgr.refresh()
    assert v2.epoch == v1.epoch + 1
    mgr.max_staleness_s = 0.0
    assert mgr.view().epoch > v2.epoch  # stale → refreshed


# -- the concurrency acceptance test --------------------------------------


def test_concurrent_ingest_query_consistency(template):
    """Ingest and query race for real: a writer thread feeds batches
    through a growing table (capacity starts at 1<<10 so grow-and-
    rehash fires mid-run) while reader threads query through a
    MembershipOracle with a tight staleness bound. Contract: a serial
    acked more than (staleness bound + capture slack) before the query
    was submitted MUST read known; a serial never fed must NEVER read
    known."""
    agg = TpuAggregator(capacity=1 << 10, batch_size=64,
                        max_capacity=1 << 14, grow_at=0.55)
    issuer_idx = agg.registry.get_or_assign(template.issuer_der)
    _, eh = _identity(template)
    stale = 0.05
    oracle = MembershipOracle(agg, max_batch=256, max_delay_s=0.002,
                              max_staleness_s=stale)
    acked: dict[int, float] = {}
    acked_lock = threading.Lock()
    stop = threading.Event()
    errors: list[str] = []
    n_batches, batch = 14, 64  # 896 lanes > 0.55 x 1024 ⇒ grow fires

    def writer():
        try:
            for b in range(n_batches):
                entries = [
                    (syncerts.stamp_serial(template, b * batch + j),
                     template.issuer_der)
                    for j in range(batch)
                ]
                agg.ingest(entries)  # returns ⇒ acked
                now = time.time()
                with acked_lock:
                    for j in range(batch):
                        acked[b * batch + j] = now
        except Exception as err:  # pragma: no cover - fails the test
            errors.append(f"writer: {err!r}")
        finally:
            stop.set()

    def reader(seed):
        r = np.random.default_rng(seed)
        while not stop.is_set() or r.integers(2) == 0:
            with acked_lock:
                known_now = dict(acked)
            if not known_now:
                time.sleep(0.001)
                continue
            js = list(known_now)
            pick = [js[int(r.integers(len(js)))] for _ in range(4)]
            ghosts = [int(r.integers(10**6, 2 * 10**6)) for _ in range(2)]
            t_q = time.time()
            items = [(issuer_idx, eh, _serial_bytes(template, j))
                     for j in pick + ghosts]
            try:
                res = oracle.query_raw(items)
            except Overloaded:
                continue
            for (known, _epoch, _age), j in zip(res, pick + ghosts):
                if j in known_now:
                    # Acked long before the query ⇒ must be visible.
                    if not known and known_now[j] < t_q - stale - 0.25:
                        errors.append(
                            f"acked serial {j} invisible "
                            f"({t_q - known_now[j]:.3f}s after ack)")
                elif known:
                    errors.append(f"false positive: ghost serial {j}")
            if stop.is_set():
                break

    w = threading.Thread(target=writer)
    readers = [threading.Thread(target=reader, args=(s,)) for s in (1, 2)]
    w.start()
    for t in readers:
        t.start()
    w.join(timeout=120)
    for t in readers:
        t.join(timeout=30)
    oracle.close()
    assert not errors, errors[:10]
    assert agg.metrics.get("overflow", 0) >= 0  # table survived
    # The run really exercised growth (the mid-grow torn-read hazard).
    assert agg.capacity > 1 << 10, "table never grew; raise n_batches"
    # And the final state is complete: every fed serial present.
    final = capture_view(agg, epoch=99)
    items = [(issuer_idx, eh, _serial_bytes(template, j))
             for j in range(n_batches * batch)]
    assert final.lookup(items).all()


# -- HTTP server ----------------------------------------------------------


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_query_server_http_api(template):
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, j), template.issuer_der)
                for j in range(20)])
    issuer_id, eh = _identity(template)
    exp_id = ExpDate.from_unix_hour(eh).id()
    srv = QueryServer(agg, 0, host="127.0.0.1",
                      max_delay_s=0.001).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # Bulk query: present + absent, epoch and staleness surfaced.
        queries = [{"issuer": issuer_id, "expDate": exp_id,
                    "serial": _serial_bytes(template, j).hex()}
                   for j in (0, 5, 19, 777)]
        code, body = _post(f"{base}/query", {"queries": queries})
        assert code == 200
        assert [r["known"] for r in body["results"]] == [
            True, True, True, False]
        assert body["epoch"] >= 1 and body["staleness_s"] >= 0
        # Single-query shorthand.
        code, body = _post(f"{base}/query", {
            "issuer": issuer_id, "expDate": exp_id,
            "serial": _serial_bytes(template, 5).hex()})
        assert code == 200 and body["known"] is True
        # Unknown issuer: honest False.
        code, body = _post(f"{base}/query", {
            "issuer": "nosuchissuer=", "expDate": exp_id,
            "serial": "4d00"})
        assert code == 200 and body["known"] is False
        # Malformed: 400, not a 500.
        for bad in ({"queries": []},
                    {"issuer": issuer_id, "expDate": exp_id,
                     "serial": "zz"},
                    {"issuer": issuer_id, "expDate": "June 15",
                     "serial": "4d00"}):
            req = urllib.request.Request(
                f"{base}/query", data=json.dumps(bad).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400
        # Issuer metadata.
        from urllib.parse import quote

        with urllib.request.urlopen(
                f"{base}/issuer/{quote(issuer_id, safe='')}",
                timeout=10) as resp:
            meta = json.loads(resp.read())
        assert meta["unknown_total"] == 20
        assert meta["dns"] == 1 and meta["crls"] == 1
        assert "staleness_s" in meta
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/issuer/doesnotexist",
                                   timeout=10)
        assert ei.value.code == 404
        # Health: queue + snapshot numbers.
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            h = json.loads(resp.read())
        assert h["healthy"] and h["queue_cap"] > 0
        assert h["snapshot_epoch"] >= 1
    finally:
        srv.stop()


def test_query_server_sheds_with_429(template):
    """Overload answers 429 overloaded — never an unbounded queue."""
    agg = TpuAggregator(capacity=1 << 12, batch_size=64)
    agg.ingest([(syncerts.stamp_serial(template, 0), template.issuer_der)])
    issuer_id, eh = _identity(template)
    exp_id = ExpDate.from_unix_hour(eh).id()
    srv = QueryServer(agg, 0, host="127.0.0.1", max_queue_lanes=2,
                      max_delay_s=0.001).start()
    try:
        # A 3-lane request cannot be admitted into a 2-lane queue.
        q = {"issuer": issuer_id, "expDate": exp_id,
             "serial": _serial_bytes(template, 0).hex()}
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/query",
            data=json.dumps({"queries": [q, q, q]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert json.loads(ei.value.read())["error"] == "overloaded"
        # The plane still answers admissible requests afterwards.
        code, body = _post(f"http://127.0.0.1:{srv.port}/query", q)
        assert code == 200 and body["known"] is True
    finally:
        srv.stop()


def test_query_server_getcert_proxy():
    """/getcert proxies one log entry as PEM (ct-getcert's routed
    path), using the server's transport override."""
    from tests.fakelog import FakeLog
    from tests import certgen
    import datetime

    log = FakeLog()
    future = datetime.datetime(2031, 6, 15, tzinfo=datetime.timezone.utc)
    issuer_der = certgen.make_cert(serial=1, issuer_cn="Proxy CA",
                                   is_ca=True, not_after=future)
    leaf = certgen.make_cert(serial=1000, issuer_cn="Proxy CA",
                             subject_cn="proxy.example.com", is_ca=False,
                             not_after=future)
    log.add_cert(leaf, issuer_der, timestamp_ms=1700000000000)
    agg = TpuAggregator(capacity=1 << 10, batch_size=64)
    srv = QueryServer(agg, 0, host="127.0.0.1",
                      transport=log.transport).start()
    try:
        from urllib.parse import urlencode

        qs = urlencode({"log": log.url, "index": 0})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/getcert?{qs}",
                timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["pem"].startswith("-----BEGIN CERTIFICATE-----")
        with pytest.raises(urllib.error.HTTPError) as ei:
            qs = urlencode({"log": log.url, "index": 99})
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/getcert?{qs}", timeout=10)
        assert ei.value.code in (404, 502)
    finally:
        srv.stop()


def test_serve_batch_spans_recorded(template):
    """serve.batch spans carry lane counts — what the bench serve leg
    derives its batching-effectiveness gate from."""
    from ct_mapreduce_tpu.telemetry import trace

    tracer = trace.enable()
    t0 = tracer.now_us()
    try:
        agg = TpuAggregator(capacity=1 << 12, batch_size=64)
        agg.ingest([(syncerts.stamp_serial(template, j),
                     template.issuer_der) for j in range(8)])
        issuer_id, eh = _identity(template)
        idx = agg.registry.index_of_issuer_id(issuer_id)
        oracle = MembershipOracle(agg, max_batch=64, max_delay_s=0.01)
        threads = [threading.Thread(
            target=lambda j=j: oracle.query_raw(
                [(idx, eh, _serial_bytes(template, j))])
        ) for j in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        oracle.close()
        spans = [e for e in tracer.events()
                 if e.get("ph") == "X" and e["name"] == "serve.batch"
                 and e["ts"] >= t0]
        assert spans, "no serve.batch spans recorded"
        lanes = sum(e["args"]["lanes"] for e in spans)
        assert lanes == 8
        waits = [e for e in tracer.events()
                 if e.get("ph") == "X" and e["name"] == "serve.wait"
                 and e["ts"] >= t0]
        assert len(waits) == 8
    finally:
        trace.disable()
