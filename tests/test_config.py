"""Config layering tests (reference: config/config_test.go:8-86 and
config.go:183-214 precedence: defaults < ini < env < CLI flags)."""

from ct_mapreduce_tpu.config import CTConfig


def test_defaults(tmp_path):
    cfg = CTConfig.load(argv=[], env={}, default_ini=str(tmp_path / "missing.ini"))
    assert cfg.num_threads == 1
    assert cfg.save_period == "15m"
    assert cfg.polling_delay_mean == "10m"
    assert cfg.polling_delay_std_dev == 10
    assert cfg.output_refresh_period == "125ms"
    assert cfg.health_addr == ":8080"
    assert cfg.redis_timeout == "5s"
    assert not cfg.run_forever and not cfg.log_expired_entries


def test_ini_file_overrides_defaults(tmp_path):
    ini = tmp_path / "ct.ini"
    ini.write_text(
        "numThreads = 7\nlogList = https://a.example/log, https://b.example/log\n"
        "runForever = true\nissuerCNFilter = Let's Encrypt\n"
    )
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.num_threads == 7
    assert cfg.run_forever is True
    assert cfg.log_urls() == ["https://a.example/log", "https://b.example/log"]
    assert cfg.issuer_cn_filters() == ["Let's Encrypt"]


def test_env_beats_ini(tmp_path):
    ini = tmp_path / "ct.ini"
    ini.write_text("numThreads = 7\ncertPath = /from/ini\n")
    cfg = CTConfig.load(
        argv=["--config", str(ini)],
        env={"numThreads": "3", "certPath": "/from/env"},
    )
    assert cfg.num_threads == 3
    assert cfg.cert_path == "/from/env"


def test_cli_flags_beat_everything(tmp_path):
    ini = tmp_path / "ct.ini"
    ini.write_text("offset = 5\nlimit = 10\n")
    cfg = CTConfig.load(
        argv=["--config", str(ini), "--offset", "100", "--limit", "200"],
        env={"offset": "50"},
    )
    assert cfg.offset == 100
    assert cfg.limit == 200


def test_unparseable_values_keep_defaults(tmp_path):
    ini = tmp_path / "ct.ini"
    ini.write_text("numThreads = banana\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.num_threads == 1


def test_tpu_directives(tmp_path):
    ini = tmp_path / "ct.ini"
    ini.write_text("backend = tpu\nbatchSize = 131072\ntableBits = 24\n"
                   "tableGrowAt = 0.8\ntableMaxBits = 26\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.backend == "tpu"
    assert cfg.batch_size == 131072
    assert cfg.table_bits == 24
    assert cfg.table_grow_at == 0.8
    assert cfg.table_max_bits == 26
    cfg2 = CTConfig.load(argv=["--config", str(ini), "--backend", "redis"], env={})
    assert cfg2.backend == "redis"
    # Env beats file; unparseable env falls back (config.go:41-123 quirk).
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"tableGrowAt": "0.5"})
    assert cfg3.table_grow_at == 0.5
    cfg4 = CTConfig.load(argv=["--config", str(ini)],
                         env={"tableGrowAt": "banana"})
    assert cfg4.table_grow_at == 0.8
    # Growth disabled and ceilings flow into the aggregator factory.
    from ct_mapreduce_tpu.models.ingest_model import build_aggregator

    ini2 = tmp_path / "ct2.ini"
    ini2.write_text("backend = tpu\ntableBits = 10\nmeshShape = shard:1\n"
                    "tableGrowAt = 0\ntableMaxBits = 20\nbatchSize = 64\n")
    agg = build_aggregator(CTConfig.load(argv=["--config", str(ini2)], env={}))
    assert agg.grow_at == 0
    # The configured 2^20 ceiling is floored to the largest capacity
    # the active layout can actually build (bucket: 24·2^k), so the
    # at-ceiling growth guard can fire (ADVICE r05 grow-livelock fix).
    assert agg.max_capacity == agg._layout_capacity_floor(1 << 20)
    assert 0 < agg.max_capacity <= 1 << 20


def test_usage_mentions_every_reference_directive():
    text = CTConfig().usage()
    for directive in (
        "certPath",
        "redisHost",
        "issuerCNFilter",
        "runForever",
        "pollingDelayMean",
        "pollingDelayStdDev",
        "logExpiredEntries",
        "numThreads",
        "savePeriod",
        "logList",
        "outputRefreshPeriod",
        "statsRefreshPeriod",
        "statsdHost",
        "statsdPort",
        "redisTimeout",
        "healthAddr",
    ):
        assert directive in text, f"usage() missing {directive}"


def test_observability_directives(tmp_path):
    """tracePath / metricsPort (PR 4): ini + env layering, ints parse,
    and usage() documents both."""
    ini = tmp_path / "ct.ini"
    ini.write_text("tracePath = /tmp/run-trace.json\nmetricsPort = 9464\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.trace_path == "/tmp/run-trace.json"
    assert cfg.metrics_port == 9464
    # Env beats file; unparseable env falls back to the file value.
    cfg2 = CTConfig.load(argv=["--config", str(ini)],
                         env={"metricsPort": "9000"})
    assert cfg2.metrics_port == 9000
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"metricsPort": "banana"})
    assert cfg3.metrics_port == 9464
    # Defaults: both off.
    off = CTConfig.load(argv=[], env={})
    assert off.trace_path == "" and off.metrics_port == 0
    usage = CTConfig().usage()
    assert "tracePath" in usage and "metricsPort" in usage


def test_staged_queue_directives(tmp_path, monkeypatch):
    """stagingDepth / chunksPerDispatch (round 11): ini + env
    layering, int parse, defaults-off, usage() — and the sink-side
    CTMR_* env fallback behind the config value."""
    ini = tmp_path / "ct.ini"
    ini.write_text("chunksPerDispatch = 8\nstagingDepth = 3\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.chunks_per_dispatch == 8
    assert cfg.staging_depth == 3
    # Env beats file; unparseable env falls back to the file value.
    cfg2 = CTConfig.load(argv=["--config", str(ini)],
                         env={"chunksPerDispatch": "16",
                              "stagingDepth": "4"})
    assert cfg2.chunks_per_dispatch == 16 and cfg2.staging_depth == 4
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"chunksPerDispatch": "banana"})
    assert cfg3.chunks_per_dispatch == 8
    # Defaults: 0 = resolve via CTMR_* env, then legacy (K=1, depth 2).
    off = CTConfig.load(argv=[], env={})
    assert off.chunks_per_dispatch == 0 and off.staging_depth == 0
    from ct_mapreduce_tpu.ingest.sync import resolve_staging

    monkeypatch.delenv("CTMR_CHUNKS_PER_DISPATCH", raising=False)
    monkeypatch.delenv("CTMR_STAGING_DEPTH", raising=False)
    assert resolve_staging(off.chunks_per_dispatch,
                           off.staging_depth) == (1, 2)
    usage = CTConfig().usage()
    assert "chunksPerDispatch" in usage and "stagingDepth" in usage


def test_query_port_directive(tmp_path):
    """queryPort (ISSUE 5): ini + env layering, int parse, usage()."""
    ini = tmp_path / "ct.ini"
    ini.write_text("queryPort = 9090\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.query_port == 9090
    cfg2 = CTConfig.load(argv=["--config", str(ini)],
                         env={"queryPort": "9999"})
    assert cfg2.query_port == 9999
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"queryPort": "banana"})
    assert cfg3.query_port == 9090
    assert CTConfig.load(argv=[], env={}).query_port == 0  # default off
    assert "queryPort" in CTConfig().usage()


def test_serve_tier_directives(tmp_path):
    """serveReplicas / serveDevice / serveCacheSize (ISSUE 7): ini +
    env layering, bool/int parse, defaults, usage()."""
    ini = tmp_path / "ct.ini"
    ini.write_text(
        "serveReplicas = 4\nserveDevice = false\nserveCacheSize = 512\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.serve_replicas == 4
    assert cfg.serve_device is False
    assert cfg.serve_cache_size == 512
    cfg2 = CTConfig.load(
        argv=["--config", str(ini)],
        env={"serveReplicas": "8", "serveDevice": "true",
             "serveCacheSize": "-1"})
    assert cfg2.serve_replicas == 8
    assert cfg2.serve_device is True
    assert cfg2.serve_cache_size == -1
    # Unparseable env falls back to the file value.
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"serveReplicas": "many"})
    assert cfg3.serve_replicas == 4
    # Defaults: pool/cache auto-sized downstream (resolve_serve),
    # device serving on.
    dflt = CTConfig.load(argv=[], env={})
    assert dflt.serve_replicas == 0
    assert dflt.serve_device is True
    assert dflt.serve_cache_size == 0
    usage = CTConfig().usage()
    for d in ("serveReplicas", "serveDevice", "serveCacheSize"):
        assert d in usage


def test_verify_directives(tmp_path):
    """verifySignatures / verifyLogKeys (ISSUE 8): ini + env layering,
    bool parse, defaults, usage(). The CTMR_VERIFY env equivalent
    layers downstream (verify.lane.resolve_verify, covered by
    tests/test_verify_lane.py)."""
    ini = tmp_path / "ct.ini"
    ini.write_text(
        "verifySignatures = true\nverifyLogKeys = /etc/ct/keys.json\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.verify_signatures is True
    assert cfg.verify_log_keys == "/etc/ct/keys.json"
    cfg2 = CTConfig.load(
        argv=["--config", str(ini)],
        env={"verifySignatures": "false",
             "verifyLogKeys": "/run/keys.json"})
    assert cfg2.verify_signatures is False
    assert cfg2.verify_log_keys == "/run/keys.json"
    # Unparseable env bool falls back to the file value.
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"verifySignatures": "maybe"})
    assert cfg3.verify_signatures is True
    dflt = CTConfig.load(argv=[], env={})
    assert dflt.verify_signatures is False
    assert dflt.verify_log_keys == ""
    usage = CTConfig().usage()
    for d in ("verifySignatures", "verifyLogKeys"):
        assert d in usage


def test_verify_precomp_directives(tmp_path):
    """verifyPrecompWindow / verifyQTableSize (ISSUE 12): ini + env
    layering, int parse, sentinel defaults (-1 window = unset, so an
    explicit 0 — the legacy ladder — survives a stray env), usage().
    The CTMR_VERIFY_PRECOMP_WINDOW / CTMR_VERIFY_QTABLE_SIZE env
    equivalents layer downstream (verify.lane.resolve_verify, covered
    by tests/test_verify_lane.py)."""
    ini = tmp_path / "ct.ini"
    ini.write_text("verifyPrecompWindow = 0\nverifyQTableSize = 48\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.verify_precomp_window == 0
    assert cfg.verify_qtable_size == 48
    cfg2 = CTConfig.load(
        argv=["--config", str(ini)],
        env={"verifyPrecompWindow": "4", "verifyQTableSize": "junk"})
    assert cfg2.verify_precomp_window == 4
    assert cfg2.verify_qtable_size == 48  # unparseable env ignored
    dflt = CTConfig.load(argv=[], env={})
    assert dflt.verify_precomp_window == -1  # unset sentinel
    assert dflt.verify_qtable_size == 0
    usage = CTConfig().usage()
    for d in ("verifyPrecompWindow", "verifyQTableSize"):
        assert d in usage


def test_fleet_directives(tmp_path, monkeypatch):
    """numWorkers / workerId / checkpointPeriod / coordinatorBackend
    (ISSUE 9): ini + env layering, int parse, defaults, usage() — and
    the CTMR_* env fallback behind the config values
    (fleet.resolve_fleet)."""
    ini = tmp_path / "ct.ini"
    ini.write_text(
        "numWorkers = 4\nworkerId = 2\ncheckpointPeriod = 30s\n"
        "coordinatorBackend = redis\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.num_workers == 4
    assert cfg.worker_id == 2
    assert cfg.checkpoint_period == "30s"
    assert cfg.coordinator_backend == "redis"
    # Env beats file; unparseable env int falls back to the file value.
    cfg2 = CTConfig.load(
        argv=["--config", str(ini)],
        env={"numWorkers": "8", "workerId": "5",
             "checkpointPeriod": "1m", "coordinatorBackend": "jax"})
    assert cfg2.num_workers == 8 and cfg2.worker_id == 5
    assert cfg2.checkpoint_period == "1m"
    assert cfg2.coordinator_backend == "jax"
    cfg3 = CTConfig.load(argv=["--config", str(ini)],
                         env={"numWorkers": "banana"})
    assert cfg3.num_workers == 4
    # Defaults: single worker, resolution deferred to resolve_fleet
    # (workerId's unset sentinel is -1 — 0 is a real, pinnable id).
    dflt = CTConfig.load(argv=[], env={})
    assert dflt.num_workers == 0 and dflt.worker_id == -1
    assert dflt.checkpoint_period == "" and dflt.coordinator_backend == ""
    from ct_mapreduce_tpu.ingest.fleet import resolve_fleet

    for k in ("CTMR_NUM_WORKERS", "CTMR_WORKER_ID",
              "CTMR_CHECKPOINT_PERIOD", "CTMR_COORDINATOR"):
        monkeypatch.delenv(k, raising=False)
    assert resolve_fleet(dflt.num_workers, dflt.worker_id,
                         dflt.checkpoint_period,
                         dflt.coordinator_backend) == (1, 0, "", "")
    monkeypatch.setenv("CTMR_NUM_WORKERS", "6")
    monkeypatch.setenv("CTMR_CHECKPOINT_PERIOD", "45s")
    assert resolve_fleet(dflt.num_workers, dflt.worker_id,
                         dflt.checkpoint_period,
                         dflt.coordinator_backend) == (6, 0, "45s", "")
    # An ini that explicitly pins workerId = 0 beats a stray env id.
    monkeypatch.setenv("CTMR_WORKER_ID", "4")
    pinned = CTConfig.load(
        argv=["--config", str(ini)], env={"workerId": "0"})
    assert pinned.worker_id == 0
    assert resolve_fleet(pinned.num_workers, pinned.worker_id,
                         "", "")[1] == 0
    # ...while an UNSET workerId still takes the env value.
    assert resolve_fleet(dflt.num_workers, dflt.worker_id, "", "")[1] == 4
    usage = CTConfig().usage()
    for d in ("numWorkers", "workerId", "checkpointPeriod",
              "coordinatorBackend"):
        assert d in usage


def test_platform_profile_feeds_every_resolver(tmp_path, monkeypatch):
    """platformProfile (ISSUE 13, ROADMAP item 1's unlocking
    refactor): ONE data file supplies tuned knobs to every subsystem's
    resolve_*, with the shared ladder explicit > env > profile >
    default — a tuned device profile needs no code change."""
    import json

    for k in ("CTMR_PLATFORM_PROFILE", "CTMR_CHUNKS_PER_DISPATCH",
              "CTMR_STAGING_DEPTH", "CTMR_SERVE_REPLICAS",
              "CTMR_SERVE_DEVICE", "CTMR_SERVE_CACHE_SIZE",
              "CTMR_VERIFY", "CTMR_VERIFY_BATCH",
              "CTMR_VERIFY_PRECOMP_WINDOW", "CTMR_NUM_WORKERS",
              "CTMR_EMIT_FILTER", "CTMR_FILTER_FP_RATE"):
        monkeypatch.delenv(k, raising=False)
    from ct_mapreduce_tpu.filter import resolve_filter
    from ct_mapreduce_tpu.ingest.fleet import resolve_fleet
    from ct_mapreduce_tpu.ingest.sync import resolve_staging
    from ct_mapreduce_tpu.serve.server import resolve_serve
    from ct_mapreduce_tpu.verify.lane import resolve_verify

    prof = tmp_path / "tuned.json"
    prof.write_text(json.dumps({
        "version": 1, "platform": "test-box",
        "knobs": {
            "staging": {"chunksPerDispatch": 8, "stagingDepth": 3},
            "serve": {"serveReplicas": 5, "serveDevice": False,
                      "serveCacheSize": 512},
            "verify": {"verifyBatch": 4096, "verifyPrecompWindow": 4},
            "fleet": {"numWorkers": 4},
            "filter": {"filterFpRate": 0.005},
        }}))
    monkeypatch.setenv("CTMR_PLATFORM_PROFILE", str(prof))
    # Profile supplies the defaults...
    assert resolve_staging() == (8, 3)
    assert resolve_serve() == (5, False, 512)
    assert resolve_verify()[2] == 4096
    assert resolve_verify()[3] == 4
    assert resolve_fleet()[0] == 4
    assert resolve_filter()[2] == 0.005
    # ...env beats profile...
    monkeypatch.setenv("CTMR_STAGING_DEPTH", "5")
    monkeypatch.setenv("CTMR_SERVE_REPLICAS", "9")
    monkeypatch.setenv("CTMR_VERIFY_PRECOMP_WINDOW", "8")
    assert resolve_staging() == (8, 5)
    assert resolve_serve()[0] == 9
    assert resolve_verify()[3] == 8
    # ...and an explicit directive/kwarg beats both (incl. the
    # 0-is-real sentinel knobs).
    assert resolve_staging(chunks_per_dispatch=2) == (2, 5)
    assert resolve_verify(window=0)[3] == 0
    # An unreadable profile resolves as if absent (no crash).
    monkeypatch.setenv("CTMR_PLATFORM_PROFILE", str(tmp_path / "nope"))
    monkeypatch.delenv("CTMR_STAGING_DEPTH")
    assert resolve_staging() == (1, 2)
    # The directive parses and is documented.
    ini = tmp_path / "p.ini"
    ini.write_text(f"platformProfile = {prof}\ndistribHistory = 6\n"
                   "maxDeltaChain = 3\n")
    cfg = CTConfig.load(argv=["--config", str(ini)], env={})
    assert cfg.platform_profile == str(prof)
    assert cfg.distrib_history == 6 and cfg.max_delta_chain == 3
    usage = CTConfig().usage()
    for d in ("platformProfile", "distribHistory", "maxDeltaChain"):
        assert d in usage
