"""DER/X.509 reference-lane tests: validate the pure-Python extractor
against the `cryptography` package on generated fixtures (the same
fields the device kernel must later reproduce)."""

from datetime import datetime, timedelta, timezone

import pytest

try:
    from cryptography import x509 as cx509
except ImportError:
    cx509 = None

from ct_mapreduce_tpu.core import der as derlib

from certgen import make_cert, requires_cryptography, spki_of


def test_parse_cert_basic_fields():
    not_after = datetime(2031, 5, 6, 7, 0, 0, tzinfo=timezone.utc)
    der = make_cert(
        serial=0x1122334455,
        issuer_cn="Acme Root CA",
        org="Acme Corp",
        not_after=not_after,
        crl_dps=("http://crl.acme.example/root.crl",),
    )
    fields = derlib.parse_cert(der)
    assert fields.serial == bytes.fromhex("1122334455")
    assert fields.not_after == not_after
    assert fields.issuer_cn == "Acme Root CA"
    assert fields.is_ca and fields.basic_constraints_valid
    assert fields.crl_distribution_points == ["http://crl.acme.example/root.crl"]
    assert fields.spki == spki_of(der)
    assert fields.not_after_unix_hour == int(not_after.timestamp()) // 3600


@requires_cryptography
def test_parse_cert_matches_cryptography():
    der = make_cert(serial=0x00ABCDEF7788)
    ours = derlib.parse_cert(der)
    ref = cx509.load_der_x509_certificate(der)
    assert ours.serial == ref.serial_number.to_bytes(
        (ref.serial_number.bit_length() + 8) // 8 or 1, "big"
    )
    assert ours.not_before == ref.not_valid_before_utc
    assert ours.not_after == ref.not_valid_after_utc
    assert ours.issuer_dn == ref.issuer.rfc4514_string()


def test_leading_zero_serial_raw_bytes():
    der = make_cert(serial=0xF0000001)  # high bit → DER pads with 0x00
    assert derlib.raw_serial_bytes(der) == bytes([0x00, 0xF0, 0x00, 0x00, 0x01])


def test_non_ca_cert():
    der = make_cert(is_ca=False, subject_cn="leaf.example.com")
    fields = derlib.parse_cert(der)
    assert fields.basic_constraints_valid and not fields.is_ca
    assert "leaf.example.com" in fields.subject_dn


def test_no_basic_constraints():
    der = make_cert(add_basic_constraints=False)
    fields = derlib.parse_cert(der)
    assert not fields.basic_constraints_valid and not fields.is_ca


def test_multiple_crl_dps():
    urls = ("http://a.example/c.crl", "https://b.example/d.crl")
    fields = derlib.parse_cert(make_cert(crl_dps=urls))
    assert fields.crl_distribution_points == list(urls)


def test_utctime_vs_generalizedtime():
    # Pre-2050 → UTCTime, post-2050 → GeneralizedTime per RFC 5280
    early = make_cert(not_after=datetime(2049, 1, 1, tzinfo=timezone.utc))
    late = make_cert(not_after=datetime(2051, 1, 1, tzinfo=timezone.utc))
    assert derlib.parse_cert(early).not_after.year == 2049
    assert derlib.parse_cert(late).not_after.year == 2051


def test_structural_offsets_are_consistent():
    der = make_cert(serial=0x77)
    f = derlib.parse_cert(der)
    assert der[f.serial_off : f.serial_off + f.serial_len] == f.serial
    assert der[f.spki_off : f.spki_off + f.spki_len] == f.spki
    tag = der[f.not_after_tag_off]
    assert tag in (derlib.TAG_UTC_TIME, derlib.TAG_GENERALIZED_TIME)


def test_pem_roundtrip():
    der = make_cert()
    pem = derlib.der_to_pem(der)
    assert derlib.pem_to_der(pem) == der
    assert derlib.pem_to_der(der) == der  # DER passthrough


def test_truncated_der_raises():
    der = make_cert()
    with pytest.raises(derlib.DerError):
        derlib.parse_cert(der[: len(der) // 2])


@requires_cryptography
def test_multivalued_rdn_rendering():
    # Go pkix.Name.String() joins intra-RDN attributes with '+'
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import NameOID
    from certgen import _key
    from datetime import datetime, timezone

    name = x509.Name(
        [
            x509.RelativeDistinguishedName(
                [
                    x509.NameAttribute(NameOID.ORGANIZATION_NAME, "MultiOrg"),
                    x509.NameAttribute(NameOID.COMMON_NAME, "MultiCN"),
                ]
            ),
            x509.RelativeDistinguishedName(
                [x509.NameAttribute(NameOID.COUNTRY_NAME, "US")]
            ),
        ]
    )
    key = _key(0)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(7)
        .not_valid_before(datetime(2024, 1, 1, tzinfo=timezone.utc))
        .not_valid_after(datetime(2030, 1, 1, tzinfo=timezone.utc))
        .sign(key, hashes.SHA256())
    )
    der = cert.public_bytes(serialization.Encoding.DER)
    ours = derlib.parse_cert(der)
    # Go pkix.Name.String() canonicalizes: regroups by type in fixed
    # order (issuermetadata.go:94 stores this form as the cache value)
    assert ours.issuer_dn == "CN=MultiCN,O=MultiOrg,C=US"
    # The structure-preserving renderer matches cryptography instead
    rdns, _ = derlib.parse_name(der, ours.issuer_off)
    assert (
        derlib.render_dn_rfc4514(rdns)
        == cx509.load_der_x509_certificate(der).issuer.rfc4514_string()
    )
    assert ours.issuer_cn == "MultiCN"


def test_dn_value_escaping():
    der = make_cert(issuer_cn='Weird, CA "quoted"')
    f = derlib.parse_cert(der)
    assert '\\,' in f.issuer_dn and '\\"' in f.issuer_dn
    if cx509 is not None:
        assert f.issuer_dn == (
            cx509.load_der_x509_certificate(der).issuer.rfc4514_string())
