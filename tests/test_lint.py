"""Tier-1 lint gate (round 16): the full ``ctmrlint`` rule set over
the real package must be clean — zero non-baselined violations, a
tight justified baseline, and a strict time/dependency budget (AST
only, no jax import, <10s) so the gate is cheap enough to never skip.

Also pins the CLI scripting contract: exit 0 clean / 1 violations /
2 error, ``--json`` output shape."""

import json
import pathlib
import subprocess
import sys
import time

from ct_mapreduce_tpu.analysis.engine import load_baseline, run_analysis

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "ct_mapreduce_tpu"
BASELINE = REPO / "ctmrlint.baseline"
MAX_BASELINE_ENTRIES = 10


def test_package_lints_clean_within_budget():
    t0 = time.monotonic()
    live, suppressed, unused = run_analysis(PKG, baseline_path=BASELINE)
    wall = time.monotonic() - t0
    assert not live, (
        "ctmrlint violations (fix them or add a JUSTIFIED baseline "
        "entry to ctmrlint.baseline):\n"
        + "\n".join(f.render() for f in live))
    assert not unused, f"stale baseline entries (delete them): {unused}"
    assert wall < 10.0, f"lint gate took {wall:.1f}s (budget: <10s)"


def test_baseline_is_tight_and_justified():
    entries = load_baseline(BASELINE)  # raises on missing justification
    assert len(entries) <= MAX_BASELINE_ENTRIES, (
        f"baseline has {len(entries)} entries (cap "
        f"{MAX_BASELINE_ENTRIES}) — fix findings instead of "
        f"baselining them")
    for key, why in entries.items():
        assert len(why) >= 15, f"{key}: justification too thin: {why!r}"


def test_cli_clean_run_exit_0_json_and_no_jax():
    """One real subprocess run: exit code 0, --json shape, and the
    jax-free budget (the lint lane must not pay XLA startup)."""
    code = (
        "import sys, json\n"
        "from ct_mapreduce_tpu.analysis.cli import main\n"
        "rc = main(['ct_mapreduce_tpu', '--json'])\n"
        "assert 'jax' not in sys.modules, 'lint lane imported jax'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, capture_output=True,
        text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["counts"]["findings"] == 0
    assert doc["counts"]["unused_baseline"] == 0
    assert doc["counts"]["suppressed"] == len(load_baseline(BASELINE))
    for f in doc["suppressed"]:
        assert {"rule", "path", "line", "symbol", "message",
                "key"} <= set(f)


def test_cli_exit_codes_violations_and_error(tmp_path):
    """Exit 1 on findings, exit 2 on bad invocation — in-process (the
    CLI main is a plain function) to keep the gate fast."""
    from ct_mapreduce_tpu.analysis.cli import main

    bad_pkg = tmp_path / "pkgx"
    bad_pkg.mkdir()
    (bad_pkg / "bad.py").write_text(
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._oops = threading.Lock()\n")
    assert main([str(bad_pkg), "--baseline", "none",
                 "--rules", "lock-order"]) == 1
    assert main([str(tmp_path / "does-not-exist")]) == 2
    assert main([str(bad_pkg), "--rules", "no-such-rule"]) == 2
    assert main([str(bad_pkg), "--baseline",
                 str(tmp_path / "missing.baseline")]) == 2


def test_config_parity_tune_registry_diff(tmp_path):
    """Round 21: the config-parity rule diffs _*_KNOBS declarations
    against tune/registry.py — every violation class fires on a
    synthetic package, and the real package stays clean (the gate
    above already proves zero new baseline entries)."""
    from ct_mapreduce_tpu.analysis.config_parity import (
        ConfigParityChecker)

    pkg = tmp_path / "ct_mapreduce_tpu"
    (pkg / "tune").mkdir(parents=True)
    (pkg / "sub.py").write_text(
        "_FOO_KNOBS = (\n"
        "    Knob('alpha', 'CTMR_ALPHA', 1),\n"
        "    Knob('beta', 'CTMR_BETA', 2),\n"
        "    Knob('gamma', 'CTMR_GAMMA', 3),\n"
        "    Knob('delta', 'CTMR_DELTA', 4),\n"
        ")\n"
        "def resolve_foo():\n"
        "    return resolve_section('foo', _FOO_KNOBS, {})\n")
    (pkg / "tune" / "registry.py").write_text(
        "SWEEPABLE = {\n"
        "    'foo': {'alpha': [1, 2], 'beta': [], 'ghostk': [1]},\n"
        "    'stale': {},\n"
        "}\n"
        "EXCLUDED = {\n"
        "    'foo': {'alpha': 'a justification well past fifteen',\n"
        "            'gamma': 'short'},\n"
        "}\n")
    live, _, _ = run_analysis(pkg, checkers=[ConfigParityChecker()])
    symbols = {f.symbol for f in live}
    assert {"tune-both:foo.alpha",        # in both tables
            "tune-ladder:foo.beta",       # empty sweep ladder
            "tune-justification:foo.gamma",  # < 15 chars
            "tune-unregistered:foo.delta",   # in neither table
            "tune-ghost:foo.ghostk",      # registry names no Knob
            "tune-section:stale",         # section never resolved
            } <= symbols
    # Declared-and-registered cleanly (alpha minus the dup) raises
    # nothing else tune-flavored beyond the planted six.
    assert len([s for s in symbols if s.startswith("tune-")]) == 6

    # A package with no tune/registry.py (pre-round-21 layout) gets
    # exactly the missing-registry finding, not a crash.
    pkg2 = tmp_path / "p2" / "ct_mapreduce_tpu"
    pkg2.mkdir(parents=True)
    (pkg2 / "m.py").write_text("x = 1\n")
    live2, _, _ = run_analysis(pkg2, checkers=[ConfigParityChecker()])
    assert "tune-registry-missing" in {f.symbol for f in live2}


def test_cli_rule_selection_and_listing(capsys):
    from ct_mapreduce_tpu.analysis.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out.split()
    assert {"lock-order", "donation-safety", "determinism",
            "jit-purity", "metric-registry", "span-registry",
            "config-parity"} == set(out)
    # Single-rule run over the real package stays clean too.
    assert main([str(PKG), "--rules", "lock-order",
                 "--baseline", "none"]) == 0
