"""Live-Redis integration tier, gated on the RedisHost env var.

Mirror of the reference's real-Redis tier
(/root/reference/storage/rediscache_test.go:16-28,46-440 and
/root/reference/coordinator/coordinator_test.go:61-220): every test
skips unless ``RedisHost=<ip:port>`` is set, then drives the
hand-rolled RESP2 client (storage/rediscache.py) against the real
server — set/TTL/queue/SETNX semantics, SSCAN behavior, reconnect
after a dropped connection, and leader election under contention.

Recipe (README parity): ``docker run -p 6379:6379 redis`` then
``RedisHost=127.0.0.1:6379 python -m pytest tests/test_redis_live.py``.
"""

import os
import threading
import time
import uuid
from datetime import datetime, timedelta, timezone

import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("RedisHost"),
    reason="set RedisHost=<ip:port> to run live-Redis tests "
    "(/root/reference/storage/rediscache_test.go:16-28)",
)


@pytest.fixture()
def cache():
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    c = RedisCache(os.environ["RedisHost"])
    created: list[str] = []
    c._test_keys = created  # noqa: SLF001 — cleanup bookkeeping

    def track(key: str) -> str:
        created.append(key)
        return key

    c.track = track
    yield c
    for key in created:
        try:
            c.client.execute("DEL", key)
        except Exception:
            pass
    c.close()


def _key(prefix: str) -> str:
    return f"test::{prefix}::{uuid.uuid4().hex}"


def test_memory_policy_advisory(cache):
    # The reference warns unless maxmemory-policy=noeviction
    # (rediscache.go:44-55); the check must at least run cleanly.
    assert cache.memory_policy_correct() in (True, False)


def test_set_semantics(cache):
    key = cache.track(_key("set"))
    assert cache.set_insert(key, "Alpha") is True
    assert cache.set_insert(key, "Alpha") is False  # SADD idempotent
    assert cache.set_insert(key, "Beta") is True
    assert cache.set_contains(key, "Alpha")
    assert not cache.set_contains(key, "Gamma")
    assert sorted(cache.set_list(key)) == ["Alpha", "Beta"]
    assert cache.set_cardinality(key) == 2
    assert cache.set_remove(key, "Alpha") is True
    assert cache.set_cardinality(key) == 1


def test_set_scan_returns_all_members_dedup_client_side(cache):
    # Redis SSCAN may return duplicates (knowncertificates.go:66-68);
    # the client contract is "every member appears at least once".
    key = cache.track(_key("scan"))
    members = {f"m{i:04d}" for i in range(500)}
    for m in members:
        cache.set_insert(key, m)
    scanned = list(cache.set_to_iter(key))
    assert set(scanned) == members
    assert len(scanned) >= len(members)


def test_ttl_expiry(cache):
    key = cache.track(_key("ttl"))
    cache.set_insert(key, "x")
    cache.expire_in(key, timedelta(milliseconds=300))
    assert cache.exists(key)
    time.sleep(0.6)
    assert not cache.exists(key)


def test_expire_at(cache):
    key = cache.track(_key("expat"))
    cache.set_insert(key, "x")
    cache.expire_at(key, datetime.now(timezone.utc) + timedelta(seconds=1))
    assert cache.exists(key)
    time.sleep(1.5)
    assert not cache.exists(key)


def test_try_set_first_writer_wins(cache):
    key = cache.track(_key("setnx"))
    assert cache.try_set(key, "first", timedelta(minutes=1)) == "first"
    assert cache.try_set(key, "second", timedelta(minutes=1)) == "first"


def test_queue_semantics(cache):
    key = cache.track(_key("queue"))
    dest = cache.track(_key("queue-dest"))
    assert cache.queue(key, "one") == 1
    assert cache.queue(key, "two") == 2
    assert cache.queue_length(key) == 2
    got = cache.blocking_pop_copy(key, dest, timedelta(seconds=2))
    assert got == "one"
    assert cache.queue_length(dest) == 1
    cache.list_remove(dest, "one")
    assert cache.queue_length(dest) == 0
    assert cache.pop(key) == "two"


def test_log_state_roundtrip(cache):
    from ct_mapreduce_tpu.core.types import CertificateLog

    short = f"log.example/{uuid.uuid4().hex}"
    cache.track(f"log::{short}")
    log = CertificateLog(
        short_url=short, max_entry=12345,
        last_entry_time=datetime(2024, 6, 1, tzinfo=timezone.utc),
    )
    cache.store_log_state(log)
    back = cache.load_log_state(short)
    assert back is not None
    assert back.max_entry == 12345
    assert back.short_url == short


def test_reconnect_after_connection_drop(cache):
    key = cache.track(_key("reconn"))
    cache.set_insert(key, "pre")
    # Sever the TCP connection underneath the client; the next command
    # must retry/reconnect (rediscache.go:22-28 retry contract).
    cache.client.close()
    assert cache.set_contains(key, "pre")


def test_election_forty_contenders(cache):
    from ct_mapreduce_tpu.coordinator.coordinator import Coordinator

    name = f"elect-{uuid.uuid4().hex}"
    cache.track(f"leader-{name}")
    winners: list[int] = []
    coords: list[Coordinator] = []
    lock = threading.Lock()

    def contend(i: int) -> None:
        from ct_mapreduce_tpu.storage.rediscache import RedisCache

        c = RedisCache(os.environ["RedisHost"])
        coord = Coordinator(c, name)
        if coord.await_leader():
            with lock:
                winners.append(i)
        with lock:
            coords.append(coord)

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(40)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(coords) == 40
    assert len(winners) == 1  # exactly one leader (coordinator_test.go:61-104)
    for coord in coords:
        coord.close()


def test_start_barrier_sixteen_followers(cache):
    from ct_mapreduce_tpu.coordinator.coordinator import Coordinator
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    name = f"barrier-{uuid.uuid4().hex}"
    cache.track(f"leader-{name}")
    leader = Coordinator(cache, name)
    assert leader.await_leader()
    cache.track(f"started-{leader.identifier}")

    released: list[int] = []
    lock = threading.Lock()

    def follow(i: int) -> None:
        c = RedisCache(os.environ["RedisHost"])
        coord = Coordinator(c, name, await_sleep_period_s=0.05)
        assert not coord.await_leader()
        coord.await_start(timeout_s=20)
        with lock:
            released.append(i)
        coord.close()
        c.close()

    threads = [threading.Thread(target=follow, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    assert not released  # nobody released before the leader starts
    leader.send_start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(released) == list(range(16))
    leader.close()


def test_lease_expiry_fails_over(cache):
    from ct_mapreduce_tpu.coordinator.coordinator import Coordinator
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    name = f"lease-{uuid.uuid4().hex}"
    cache.track(f"leader-{name}")
    first = Coordinator(
        cache, name,
        key_life_initial=timedelta(seconds=1),
        key_life_renewal=timedelta(seconds=1),
        renewal_period_s=0.4,
    )
    assert first.await_leader()
    # A live leader keeps the lease alive across several lifetimes.
    time.sleep(2.0)
    second_cache = RedisCache(os.environ["RedisHost"])
    second = Coordinator(second_cache, name, key_life_initial=timedelta(seconds=1))
    assert not second.await_leader()
    # Leader dies (renewal stops) → lease lapses → a new contender wins.
    first.close()
    time.sleep(2.0)
    third = Coordinator(second_cache, name, key_life_initial=timedelta(seconds=1))
    assert third.await_leader()
    cache.track(f"leader-{name}")
    third.close()
    second.close()
    second_cache.close()
