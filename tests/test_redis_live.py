"""Live-Redis integration tier: socket-level tests of the RESP2 client.

Mirror of the reference's real-Redis tier
(/root/reference/storage/rediscache_test.go:16-28,46-440 and
/root/reference/coordinator/coordinator_test.go:61-220): set/TTL/queue/
SETNX semantics, SSCAN behavior, reconnect after a dropped connection,
and leader election under contention, all through the hand-rolled RESP2
client (storage/rediscache.py) over a real TCP socket.

The reference skips this tier unless a server is reachable; here it
runs BY DEFAULT against :mod:`ct_mapreduce_tpu.utils.miniredis` (an in-process RESP2
server with real Redis semantics), because this image cannot run
redis-server. Set ``RedisHost=<ip:port>`` to point the same tests at a
genuine server instead (``docker run -p 6379:6379 redis`` →
``RedisHost=127.0.0.1:6379 python -m pytest tests/test_redis_live.py``).
Tests that need miniredis-only fault knobs (OOM injection, restart,
scan duplication) always use a private miniredis instance.
"""

import os
import threading
import time
import uuid
from datetime import datetime, timedelta, timezone

import pytest

from ct_mapreduce_tpu.utils.miniredis import MiniRedis


@pytest.fixture(scope="module")
def redis_addr():
    """Address of the server under test: $RedisHost, else a shared
    in-process miniredis."""
    env = os.environ.get("RedisHost")
    if env:
        yield env
        return
    server = MiniRedis().start()
    yield server.address
    server.stop()


@pytest.fixture()
def cache(redis_addr):
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    c = RedisCache(redis_addr)
    created: list[str] = []

    def track(key: str) -> str:
        created.append(key)
        return key

    c.track = track
    yield c
    for key in created:
        try:
            c.client.execute("DEL", key)
        except Exception:
            pass
    c.close()


def _key(prefix: str) -> str:
    return f"test::{prefix}::{uuid.uuid4().hex}"


def test_memory_policy_advisory(cache):
    # The reference warns unless maxmemory-policy=noeviction
    # (rediscache.go:44-55); the check must at least run cleanly.
    assert cache.memory_policy_correct() in (True, False)


def test_set_semantics(cache):
    key = cache.track(_key("set"))
    assert cache.set_insert(key, "Alpha") is True
    assert cache.set_insert(key, "Alpha") is False  # SADD idempotent
    assert cache.set_insert(key, "Beta") is True
    assert cache.set_contains(key, "Alpha")
    assert not cache.set_contains(key, "Gamma")
    assert sorted(cache.set_list(key)) == ["Alpha", "Beta"]
    assert cache.set_cardinality(key) == 2
    assert cache.set_remove(key, "Alpha") is True
    assert cache.set_cardinality(key) == 1


def test_set_scan_returns_all_members_dedup_client_side(cache):
    # Redis SSCAN may return duplicates (knowncertificates.go:66-68);
    # the client contract is "every member appears at least once".
    key = cache.track(_key("scan"))
    members = {f"m{i:04d}" for i in range(500)}
    for m in members:
        cache.set_insert(key, m)
    scanned = list(cache.set_to_iter(key))
    assert set(scanned) == members
    assert len(scanned) >= len(members)


def test_ttl_expiry(cache):
    key = cache.track(_key("ttl"))
    cache.set_insert(key, "x")
    cache.expire_in(key, timedelta(milliseconds=300))
    assert cache.exists(key)
    time.sleep(1.2)  # expire_in clamps sub-second durations up to 1s
    assert not cache.exists(key)


def test_expire_at(cache):
    key = cache.track(_key("expat"))
    cache.set_insert(key, "x")
    cache.expire_at(key, datetime.now(timezone.utc) + timedelta(seconds=1))
    assert cache.exists(key)
    time.sleep(1.5)
    assert not cache.exists(key)


def test_try_set_first_writer_wins(cache):
    key = cache.track(_key("setnx"))
    assert cache.try_set(key, "first", timedelta(minutes=1)) == "first"
    assert cache.try_set(key, "second", timedelta(minutes=1)) == "first"


def test_queue_semantics(cache):
    key = cache.track(_key("queue"))
    dest = cache.track(_key("queue-dest"))
    assert cache.queue(key, "one") == 1
    assert cache.queue(key, "two") == 2
    assert cache.queue_length(key) == 2
    # Real Redis semantics: BRPOPLPUSH moves the source TAIL to the
    # destination HEAD (the earlier expectation of FIFO order here was
    # wrong and never caught, because the tier never ran).
    got = cache.blocking_pop_copy(key, dest, timedelta(seconds=2))
    assert got == "two"
    assert cache.queue_length(dest) == 1
    cache.list_remove(dest, "two")
    assert cache.queue_length(dest) == 0
    assert cache.pop(key) == "one"


def test_blocking_pop_times_out(cache):
    key = cache.track(_key("queue-empty"))
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        cache.blocking_pop_copy(key, key + "-dest", timedelta(seconds=1))
    assert time.monotonic() - t0 >= 0.9


def test_blocking_pop_wakes_on_push(redis_addr, cache):
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    key = cache.track(_key("queue-wake"))
    dest = cache.track(_key("queue-wake-dest"))
    got: list[str] = []

    def consumer() -> None:
        c = RedisCache(redis_addr)
        got.append(c.blocking_pop_copy(key, dest, timedelta(seconds=4)))
        c.close()

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.3)
    cache.queue(key, "payload")
    t.join(timeout=8)
    assert got == ["payload"]


def test_log_state_roundtrip(cache):
    from ct_mapreduce_tpu.core.types import CertificateLog

    short = f"log.example/{uuid.uuid4().hex}"
    cache.track(f"log::{short}")
    log = CertificateLog(
        short_url=short, max_entry=12345,
        last_entry_time=datetime(2024, 6, 1, tzinfo=timezone.utc),
    )
    cache.store_log_state(log)
    back = cache.load_log_state(short)
    assert back is not None
    assert back.max_entry == 12345
    assert back.short_url == short


def test_reconnect_after_connection_drop(cache):
    key = cache.track(_key("reconn"))
    cache.set_insert(key, "pre")
    # Sever the TCP connection underneath the client; the next command
    # must retry/reconnect (rediscache.go:22-28 retry contract).
    cache.client.close()
    assert cache.set_contains(key, "pre")


def test_election_forty_contenders(redis_addr, cache):
    from ct_mapreduce_tpu.coordinator.coordinator import Coordinator
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    name = f"elect-{uuid.uuid4().hex}"
    cache.track(f"leader-{name}")
    winners: list[int] = []
    coords: list[Coordinator] = []
    lock = threading.Lock()

    def contend(i: int) -> None:
        c = RedisCache(redis_addr)
        coord = Coordinator(c, name)
        if coord.await_leader():
            with lock:
                winners.append(i)
        with lock:
            coords.append(coord)

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(40)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(coords) == 40
    assert len(winners) == 1  # exactly one leader (coordinator_test.go:61-104)
    for coord in coords:
        coord.close()


def test_start_barrier_sixteen_followers(redis_addr, cache):
    from ct_mapreduce_tpu.coordinator.coordinator import Coordinator
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    name = f"barrier-{uuid.uuid4().hex}"
    cache.track(f"leader-{name}")
    leader = Coordinator(cache, name)
    assert leader.await_leader()
    cache.track(f"started-{leader.identifier}")

    released: list[int] = []
    lock = threading.Lock()

    def follow(i: int) -> None:
        c = RedisCache(redis_addr)
        coord = Coordinator(c, name, await_sleep_period_s=0.05)
        assert not coord.await_leader()
        coord.await_start(timeout_s=20)
        with lock:
            released.append(i)
        coord.close()
        c.close()

    threads = [threading.Thread(target=follow, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    assert not released  # nobody released before the leader starts
    leader.send_start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(released) == list(range(16))
    leader.close()


def test_lease_expiry_fails_over(redis_addr, cache):
    from ct_mapreduce_tpu.coordinator.coordinator import Coordinator
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    name = f"lease-{uuid.uuid4().hex}"
    cache.track(f"leader-{name}")
    first = Coordinator(
        cache, name,
        key_life_initial=timedelta(seconds=1),
        key_life_renewal=timedelta(seconds=1),
        renewal_period_s=0.4,
    )
    assert first.await_leader()
    # A live leader keeps the lease alive across several lifetimes.
    time.sleep(2.0)
    second_cache = RedisCache(redis_addr)
    second = Coordinator(second_cache, name, key_life_initial=timedelta(seconds=1))
    assert not second.await_leader()
    # Leader dies (renewal stops) → lease lapses → a new contender wins.
    first.close()
    time.sleep(2.0)
    third = Coordinator(second_cache, name, key_life_initial=timedelta(seconds=1))
    assert third.await_leader()
    cache.track(f"leader-{name}")
    third.close()
    second.close()
    second_cache.close()


# -- miniredis-only fault injection (knobs a real server can't offer) --


def test_oom_is_fatal():
    """Redis OOM must raise RedisFatalError, not be retried — the
    reference fatals the process (rediscache.go:57-65)."""
    from ct_mapreduce_tpu.storage.rediscache import (
        RedisCache, RedisFatalError,
    )

    server = MiniRedis().start()
    try:
        c = RedisCache(server.address)
        assert c.set_insert("k", "v") is True
        server.set_oom(True)
        t0 = time.monotonic()
        with pytest.raises(RedisFatalError):
            c.set_insert("k", "v2")
        assert time.monotonic() - t0 < 1.0  # no retry backoff on OOM
        server.set_oom(False)
        assert c.set_insert("k", "v2") is True
        c.close()
    finally:
        server.stop()


def test_sscan_duplicates_are_dedupable():
    """With the server replaying duplicates per SSCAN page (Redis's
    documented contract), set_to_iter surfaces every member at least
    once and consumers re-dedup — knowncertificates.go:65-96 parity."""
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    server = MiniRedis(scan_duplicate=True).start()
    try:
        c = RedisCache(server.address)
        members = {f"d{i:03d}" for i in range(40)}
        for m in members:
            c.set_insert("dupset", m)
        scanned = list(c.set_to_iter("dupset"))
        assert len(scanned) > len(members)  # duplicates really occurred
        assert set(scanned) == members
        c.close()
    finally:
        server.stop()


def test_sscan_deletion_between_pages_skips_nothing():
    """Redis guarantees members present from scan start to scan end are
    returned at least once. A deletion between pages must not shift
    later members past the cursor (the index-cursor bug the advisor
    flagged: removing an already-returned member used to skip the next
    unreturned one)."""
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    server = MiniRedis().start()
    try:
        c = RedisCache(server.address)
        members = [f"s{i:03d}" for i in range(40)]
        for m in members:
            c.set_insert("delscan", m)
        seen: list[str] = []
        cursor = "0"
        pages = 0
        while True:
            cursor, page = c.client.execute(
                "SSCAN", "delscan", cursor, "COUNT", "10")
            seen.extend(page)
            pages += 1
            if pages == 1:
                # Remove an already-returned member mid-scan: with a
                # numeric index cursor this shifted every later member
                # down one slot, silently skipping one.
                assert c.set_remove("delscan", page[0]) is True
            if cursor == "0":
                break
        assert pages > 1  # multi-page scan actually happened
        survivors = set(members) - {seen[0]}
        assert survivors <= set(seen)  # no survivor skipped
        c.close()
    finally:
        server.stop()


def test_reconnect_after_server_restart():
    """Kill the server mid-session, restart it on the same port: the
    client's retry loop must transparently reconnect."""
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    server = MiniRedis().start()
    port = server.port
    c = RedisCache(server.address)
    assert c.set_insert("restart", "a") is True
    server.stop()
    time.sleep(0.1)
    server2 = MiniRedis(port=port).start()
    try:
        # Data is fresh (restart), but the command must succeed via
        # reconnect rather than raising.
        assert c.set_insert("restart", "a") is True
        c.close()
    finally:
        server2.stop()


def test_eviction_policy_warning_path(capsys):
    """A server with maxmemory_policy != noeviction triggers the
    advisory warning (rediscache.go:44-55)."""
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    server = MiniRedis(maxmemory_policy="allkeys-lru").start()
    try:
        c = RedisCache(server.address)
        assert c.memory_policy_correct() is False
        assert "noeviction" in capsys.readouterr().err
        c.close()
    finally:
        server.stop()


def test_reads_never_materialize_phantom_keys(cache):
    """Real Redis creates no key on read paths and drops containers
    that become empty; exists()/keys_matching() must agree between
    miniredis and a genuine server."""
    key = cache.track(_key("phantom"))
    cache.queue_length(key)          # LLEN on a missing key
    cache.set_contains(key, "x")     # SISMEMBER on a missing key
    assert not cache.exists(key)
    with pytest.raises(TimeoutError):
        cache.blocking_pop_copy(key, key + "-dest",
                                timedelta(milliseconds=1100))
    assert not cache.exists(key)
    assert not cache.exists(key + "-dest")
    # A drained container disappears entirely.
    cache.queue(key, "only")
    assert cache.exists(key)
    assert cache.pop(key) == "only"
    assert not cache.exists(key)
    cache.set_insert(key, "m")
    cache.set_remove(key, "m")
    assert not cache.exists(key)


def test_shared_client_thread_safety(cache):
    """One RedisCache shared by many store workers (the production
    shape: FilesystemDatabase holds a single client) must serialize
    its socket correctly under contention — every insert lands, no
    interleaved frames."""
    key = cache.track(_key("hammer"))
    n_threads, per = 8, 50
    errs: list = []

    def worker(t: int) -> None:
        try:
            for j in range(per):
                cache.set_insert(key, f"t{t}-{j}")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs
    assert cache.set_cardinality(key) == n_threads * per


def test_statistics_v2_rededups_scan_duplicates(tmp_path, capsys):
    """storage-statistics -v2 drains serial sets via SSCAN; Redis may
    replay members (knowncertificates.go:65-96), so the report must
    re-dedup client-side. Driven end to end against a duplicating
    server through the real CLI."""
    from tests import certgen

    from ct_mapreduce_tpu.cmd import storage_statistics
    from ct_mapreduce_tpu.ingest.sync import DatabaseSink
    from ct_mapreduce_tpu.ingest.leaf import DecodedEntry
    from ct_mapreduce_tpu.storage.certdb import FilesystemDatabase
    from ct_mapreduce_tpu.storage.noop import NoopBackend
    from ct_mapreduce_tpu.storage.rediscache import RedisCache

    server = MiniRedis(scan_duplicate=True).start()
    try:
        future = datetime(2031, 6, 15, tzinfo=timezone.utc)
        issuer = certgen.make_cert(serial=1, issuer_cn="Dup CA",
                                   is_ca=True, not_after=future)
        cache = RedisCache(server.address)
        db = FilesystemDatabase(NoopBackend(), cache)
        sink = DatabaseSink(db)
        n = 40
        for s in range(n):
            leaf = certgen.make_cert(serial=1000 + s, issuer_cn="Dup CA",
                                     is_ca=False, not_after=future)
            sink.store(DecodedEntry(index=s, cert_der=leaf,
                                    issuer_der=issuer, timestamp_ms=0,
                                    entry_type=0), "dup-log")

        ini = tmp_path / "ct.ini"
        ini.write_text(f"redisHost = {server.address}\n")
        rc = storage_statistics.main(["-config", str(ini), "-v", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert f"{n} serials" in out
        # The -v2 serial list carries each serial exactly once despite
        # the server replaying SSCAN members.
        serial_lines = [ln for ln in out.splitlines()
                        if ln.strip().startswith("Serials: ")]
        assert serial_lines, out
        import ast

        listed = ast.literal_eval(serial_lines[0].split(":", 1)[1].strip())
        assert len(listed) == n
        assert len(set(listed)) == n
        cache.close()
    finally:
        server.stop()
