"""CTMRCK02 incremental checkpoints (round 22, ISSUE 18).

The contract under test: a versioned chain — one full **base**
snapshot plus append-only **delta segments** carrying only each epoch
tick's churn — restores STATE-IDENTICAL to the ck01 full-save oracle
(tune.harness.ckpt_state_digest), stays bounded by ``ckptMaxChain``
via compaction anchors, survives tampering/truncation with loud
``CkptError``s (a listed-but-broken chain must never half-load), heals
the one legal stale artifact (a manifest older than its base), and a
SIGKILL at any write boundary leaves a validating, resumable chain
(the fleet-level version of that last clause lives in
tests/test_multiprocess.py; the pre-rename boundaries are covered here
with a self-killing child process).
"""

import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

if os.environ.get("CT_TPU_TESTS", "") == "":
    jax.config.update("jax_platforms", "cpu")

from ct_mapreduce_tpu.agg import ckpt
from ct_mapreduce_tpu.agg.aggregator import HostSnapshotAggregator
from ct_mapreduce_tpu.tune import harness

ENTRIES = 400
BITS = 12


def _mk(tmp_path, mode="ck02", max_chain=0, entries=ENTRIES):
    agg, eh = harness.build_aggregator(entries, BITS)
    agg.configure_checkpointing(mode=mode, max_chain=max_chain)
    path = str(tmp_path / "agg.npz")
    return agg, eh, path


def _reader(path, capacity=1 << BITS):
    r = HostSnapshotAggregator(capacity=capacity)
    r.load_checkpoint(path)
    return r


# -- segment codec -------------------------------------------------------


def test_segment_codec_roundtrip():
    dev = [(0, 401000, b"\x00" * 8 + b"\x01" * 8), (2, 401007, b"ab")]
    host = [(1, 401001, b"longserial" * 4)]
    blob = {"baseHour": 400000, "countAfter": 7}
    data, header = ckpt.encode_segment(3, "f" * 64, dev, host, blob)
    assert header["seq"] == 3
    assert header["targetSha256"] == ckpt.chain_token(
        "f" * 64, header["payloadSha256"])
    h2, d2, hs2, b2 = ckpt.decode_segment(data)
    assert h2 == header
    assert d2 == dev
    assert hs2 == host
    assert b2 == blob


def test_segment_codec_rejects_corruption():
    data, _ = ckpt.encode_segment(
        1, "0" * 64, [(0, 401000, b"serialserial")], [], {"x": 1})
    # Any flipped payload byte breaks payloadSha256.
    bad = bytearray(data)
    bad[-1] ^= 0xFF
    with pytest.raises(ckpt.CkptError):
        ckpt.decode_segment(bytes(bad))
    # Truncation anywhere breaks the self-delimiting size check.
    for cut in (4, len(data) // 2, len(data) - 1):
        with pytest.raises(ckpt.CkptError):
            ckpt.decode_segment(data[:cut])
    with pytest.raises(ckpt.CkptError):
        ckpt.decode_segment(b"NOTCK02!" + data[8:])


# -- chain round trip vs the ck01 oracle ---------------------------------


@pytest.mark.slow
def test_chain_restore_matches_ck01_oracle(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)          # base
    harness.ckpt_churn(agg, eh, 37, ENTRIES)
    agg.save_checkpoint(path)          # segment 1
    harness.ckpt_churn(agg, eh, 23, ENTRIES + 1000)
    agg.save_checkpoint(path)          # segment 2
    want = harness.ckpt_state_digest(agg)

    chain = ckpt.resolve_chain(path)
    assert len(chain.segments) == 2
    assert chain.segments[0][0]["devRows"] == 37
    assert chain.segments[1][0]["devRows"] == 23

    # The ck01 oracle: a full save of the same state.
    oracle = str(tmp_path / "oracle.npz")
    agg.configure_checkpointing(mode="ck01")
    agg.save_checkpoint(oracle)
    assert not os.path.exists(ckpt.manifest_path(oracle))

    for src in (path, oracle):
        assert harness.ckpt_state_digest(_reader(src)) == want


@pytest.mark.slow
def test_restored_writer_extends_chain(tmp_path):
    """A restored aggregator continues the chain: its next save
    extends rather than re-anchoring — including after a restart from
    a plain base with no manifest (the synthesized-manifest path)."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator

    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    harness.ckpt_churn(agg, eh, 11, ENTRIES)
    agg.save_checkpoint(path)

    r = TpuAggregator(capacity=1 << BITS, grow_at=0.0)
    r.load_checkpoint(path)
    r.configure_checkpointing(mode="ck02")
    harness.ckpt_churn(r, eh, 13, ENTRIES + 2000)
    r.save_checkpoint(path)
    chain = ckpt.resolve_chain(path)
    assert [s[0]["seq"] for s in chain.segments] == [1, 2]
    assert harness.ckpt_state_digest(_reader(path)) == \
        harness.ckpt_state_digest(r)


def test_empty_tick_writes_no_segment(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    agg.save_checkpoint(path)          # nothing folded since the base
    assert len(ckpt.resolve_chain(path).segments) == 0
    assert not os.path.exists(ckpt.segment_path(path, 1))


# -- compaction / bounded chains -----------------------------------------


def test_compaction_bounds_chain(tmp_path):
    agg, eh, path = _mk(tmp_path, max_chain=2)
    agg.save_checkpoint(path)
    lengths = []
    for t in range(5):
        harness.ckpt_churn(agg, eh, 5, ENTRIES + 100 * t)
        agg.save_checkpoint(path)
        n = len(ckpt.resolve_chain(path).segments)
        lengths.append(n)
        assert n <= 2
    # Ticks 1,2 extend; tick 3 anchors (chain at maxChain); 4,5 extend.
    assert lengths == [1, 2, 0, 1, 2]
    # The anchor really cleaned the superseded segments up (seq 1-2 of
    # the OLD chain are gone; the new chain reuses those seq numbers).
    assert harness.ckpt_state_digest(_reader(path)) == \
        harness.ckpt_state_digest(agg)


# -- tampering / healing -------------------------------------------------


@pytest.mark.slow
def test_stale_manifest_heals_to_base_alone(tmp_path):
    """Crash ordering rule: a compaction renames its fresh base BEFORE
    its fresh manifest, so a manifest whose baseSha256 doesn't match
    the on-disk base is by construction OLDER than the base — the base
    alone is the newest durable full state and must win."""
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    want_base = harness.ckpt_state_digest(agg)
    harness.ckpt_churn(agg, eh, 9, ENTRIES)
    agg.save_checkpoint(path)

    # Simulate the mid-compaction crash: the base changes under the
    # manifest (zip archives tolerate trailing bytes, so the npz still
    # loads — but its sha no longer matches the manifest). The healed
    # restore is the BASE's state: in a real crash the fresh anchor is
    # itself a complete snapshot, so dropping the stale chain is
    # exactly right — never replay old segments onto a newer base.
    with open(path, "ab") as fh:
        fh.write(b"\x00")
    chain = ckpt.resolve_chain(path)
    assert len(chain.segments) == 0
    assert harness.ckpt_state_digest(_reader(path)) == want_base


def test_broken_listed_chain_raises(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    harness.ckpt_churn(agg, eh, 9, ENTRIES)
    agg.save_checkpoint(path)

    seg = ckpt.segment_path(path, 1)
    raw = open(seg, "rb").read()
    # Corrupt one payload byte: the LISTED segment no longer verifies.
    bad = bytearray(raw)
    bad[-1] ^= 0xFF
    open(seg, "wb").write(bytes(bad))
    with pytest.raises(ckpt.CkptError):
        ckpt.resolve_chain(path)
    # A listed-but-missing segment is just as fatal: never half-load.
    os.unlink(seg)
    with pytest.raises(ckpt.CkptError):
        ckpt.resolve_chain(path)
    open(seg, "wb").write(raw)
    assert len(ckpt.resolve_chain(path).segments) == 1


@pytest.mark.slow
def test_disk_tip_mismatch_forces_anchor(tmp_path):
    """If the on-disk manifest no longer matches the writer's in-memory
    tip (another process extended it, an operator rolled files back),
    extending would fork the chain — the writer must anchor instead."""
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    harness.ckpt_churn(agg, eh, 9, ENTRIES)
    agg.save_checkpoint(path)

    man = ckpt.read_manifest(path)
    man["chain"] = []                  # roll the manifest back
    ckpt.write_manifest(path, man)
    harness.ckpt_churn(agg, eh, 9, ENTRIES + 500)
    agg.save_checkpoint(path)          # must anchor, not extend
    chain = ckpt.resolve_chain(path)
    assert len(chain.segments) == 0
    assert harness.ckpt_state_digest(_reader(path)) == \
        harness.ckpt_state_digest(agg)


# -- poisons: the dirty log drops, the next save anchors ------------------


@pytest.mark.slow
def test_serialless_fold_poisons_log(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    harness.ckpt_churn(agg, eh, 9, ENTRIES)
    agg.want_serials = False           # count-only fold: rows untracked
    harness.ckpt_churn(agg, eh, 9, ENTRIES + 500)
    agg.want_serials = True
    assert agg._ckpt_dirty_lost
    agg.save_checkpoint(path)
    assert len(ckpt.resolve_chain(path).segments) == 0  # anchored
    assert harness.ckpt_state_digest(_reader(path)) == \
        harness.ckpt_state_digest(agg)


def test_segment_budget_poisons_log(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    # Park the accounting just under the budget; the next recorded row
    # must tip it over and poison (no need to fold 256 MB of churn).
    budget = agg._ckpt_resolved().segment_budget_mb << 20
    agg._ckpt_row_bytes = budget - 1
    harness.ckpt_churn(agg, eh, 9, ENTRIES)
    assert agg._ckpt_dirty_lost
    agg.save_checkpoint(path)
    assert len(ckpt.resolve_chain(path).segments) == 0
    assert harness.ckpt_state_digest(_reader(path)) == \
        harness.ckpt_state_digest(agg)


@pytest.mark.slow
def test_grow_poisons_log(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.save_checkpoint(path)
    harness.ckpt_churn(agg, eh, 9, ENTRIES)
    agg.grow(1 << (BITS + 1))          # rebuilt table: row log is moot
    assert agg._ckpt_dirty_lost
    agg.save_checkpoint(path)
    assert len(ckpt.resolve_chain(path).segments) == 0
    assert harness.ckpt_state_digest(_reader(path)) == \
        harness.ckpt_state_digest(agg)


# -- filter capture rides the chain --------------------------------------


@pytest.mark.slow
def test_capture_tokens_survive_chain_restore(tmp_path):
    agg, eh, path = _mk(tmp_path)
    agg.enable_filter_capture()
    harness.ckpt_churn(agg, eh, 17, ENTRIES)
    agg.save_checkpoint(path)          # base (capture reconfig anchors)
    harness.ckpt_churn(agg, eh, 19, ENTRIES + 1000)
    agg.save_checkpoint(path)
    assert len(ckpt.resolve_chain(path).segments) == 1

    r = HostSnapshotAggregator(capacity=1 << BITS)
    r.enable_filter_capture()
    r.load_checkpoint(path)
    assert r.capture_content_hashes() == agg.capture_content_hashes()
    assert harness.ckpt_state_digest(r) == harness.ckpt_state_digest(agg)


# -- merge plane over chains ---------------------------------------------


@pytest.mark.slow
def test_merge_loads_chains(tmp_path):
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.core.types import Issuer

    agg, eh, path = _mk(tmp_path)
    # drain() maps issuer idx 0 through the registry (the synthetic
    # harness corpus folds everything under one issuer).
    agg.registry.assign_issuer(Issuer.from_string("CN=Test CA"))
    harness.ckpt_churn(agg, eh, 15, ENTRIES)
    agg.save_checkpoint(path)
    harness.ckpt_churn(agg, eh, 15, ENTRIES + 1000)
    agg.save_checkpoint(path)
    assert len(ckpt.resolve_chain(path).segments) >= 1
    snap = merge.load_checkpoints([path]).drain()
    assert snap.total == int(agg._table_fill)


# -- pre-rename kill boundaries (self-killing child) ----------------------

_KILL_CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("CT_TPU_TESTS", None)
    sys.path.insert(0, sys.argv[1])
    path, point = sys.argv[2], sys.argv[3]

    from ct_mapreduce_tpu.tune import harness

    agg, eh = harness.build_aggregator(400, 12)
    agg.configure_checkpointing(mode="ck02")
    agg.save_checkpoint(path)                       # durable base
    print("DIGEST " + harness.ckpt_state_digest(agg), flush=True)
    harness.ckpt_churn(agg, eh, 21, 400)
    os.environ["CTMR_CKPT_KILL"] = point
    agg.save_checkpoint(path)                       # dies inside
    raise SystemExit(3)                             # must not be reached
""")


@pytest.mark.slow
@pytest.mark.parametrize("point", ["seg-pre-rename", "manifest-pre-rename"])
@pytest.mark.timeout(180)
def test_kill_before_rename_keeps_last_tick(tmp_path, point):
    """Dying BEFORE a rename publishes nothing: the durable chain is
    exactly the previous tick's (here: the base), and it restores
    byte-for-byte to the digest the child printed at that tick.
    (The post-rename boundaries run under the full fleet worker in
    tests/test_multiprocess.py::test_fleet_kill_points_ck02.)"""
    repo = str(Path(__file__).resolve().parent.parent)
    path = str(tmp_path / "agg.npz")
    child = tmp_path / "kill_child.py"
    child.write_text(_KILL_CHILD)
    env = dict(os.environ)
    env.pop("CTMR_CKPT_KILL", None)
    proc = subprocess.run(
        [sys.executable, str(child), repo, path, point],
        capture_output=True, text=True, timeout=150, env=env)
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    digest = next(line.split(" ", 1)[1]
                  for line in proc.stdout.splitlines()
                  if line.startswith("DIGEST "))

    chain = ckpt.resolve_chain(path)
    assert len(chain.segments) == 0
    if point == "manifest-pre-rename":
        # The segment's rename already happened; it's just unlisted.
        assert os.path.exists(ckpt.segment_path(path, 1))
    assert harness.ckpt_state_digest(_reader(path)) == digest
