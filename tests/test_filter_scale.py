"""Scaled filter build (round 19): streamed canonical keys, fused
multi-group layer dispatch, and the memory-bounded capture spill ring.

The headline contract is BYTE IDENTITY: streamed, fused, in-memory,
fleet-merged, and spill-ring builds of the same logical state must
produce identical ``CTMRFL01`` artifacts (the round-15 determinism
contract survives the round-19 rework). Pinned here by a randomized
property test (oversized host-lane serials and a mid-capture growth
event included), plus spill-ring crash-restart resume and the new
resolve_filter knobs.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.agg.aggregator import (  # noqa: E402
    HostSnapshotAggregator,
    TpuAggregator,
)
from ct_mapreduce_tpu.filter import (  # noqa: E402
    ListGroupSource,
    PackedGroupSource,
    SpillCaptureRing,
    build_artifact,
    build_artifact_from_sources,
    build_from_aggregator,
    resolve_filter,
)
from ct_mapreduce_tpu.filter import fused as fused_mod  # noqa: E402
from ct_mapreduce_tpu.filter import stream  # noqa: E402
from ct_mapreduce_tpu.filter.cascade import FilterCascade  # noqa: E402
from ct_mapreduce_tpu.utils import minicert  # noqa: E402


def random_state(rng, n_groups=None, oversized=True):
    """Randomized {(issuerID, expHour): [serial bytes]} corpora —
    duplicate serials, shared issuers across expiry buckets, and
    oversized host-lane serials included."""
    n_groups = n_groups or int(rng.integers(1, 8))
    state = {}
    for g in range(n_groups):
        iss = f"scale-issuer-{g % max(1, n_groups // 2)}"
        n = int(rng.integers(1, 300))
        serials = [rng.integers(0, 256, int(rng.integers(3, 20)),
                                dtype=np.uint8).tobytes()
                   for _ in range(n)]
        if oversized and g % 2 == 0:
            serials.append(bytes([g]) * 61)  # > MAX_SERIAL_BYTES
        state[(iss, 500_000 + 24 * g)] = serials + serials[: n // 4]
    return state


# -- byte identity across every build path --------------------------------


def test_build_paths_byte_identity_property():
    """The round-19 acceptance property: for randomized corpora, the
    fused/streamed builder at several (chunk, lane) shapes equals the
    round-15 per-group reference path byte for byte."""
    rng = np.random.default_rng(20260805)
    for trial in range(4):
        state = random_state(rng)
        ref = build_artifact(state, fp_rate=0.02, use_device=False,
                             fused=False).to_bytes()
        for kwargs in (dict(), dict(stream_chunk=17),
                       dict(fused_lanes=64),
                       dict(stream_chunk=5, fused_lanes=29)):
            blob = build_artifact(state, fp_rate=0.02,
                                  use_device=False,
                                  **kwargs).to_bytes()
            assert blob == ref, (trial, kwargs)


def test_fused_device_lane_byte_identity():
    """One device leg (small pow2 shapes): the jitted fused scatter
    and the NumPy lane build the same artifact."""
    rng = np.random.default_rng(7)
    state = random_state(rng, n_groups=4)
    host = build_artifact(state, fp_rate=0.02,
                          use_device=False).to_bytes()
    dev = build_artifact(state, fp_rate=0.02, use_device=True,
                         fused_lanes=128).to_bytes()
    assert dev == host


def test_packed_source_matches_list_source():
    """A PackedGroupSource feeding pre-packed numpy chunks (the
    10⁸-scale entry point — no per-serial Python objects) builds the
    same bytes as the list path, oversized host-lane serials
    included."""
    rng = np.random.default_rng(11)
    state = random_state(rng, n_groups=3)
    ref = build_artifact(state, fp_rate=0.01,
                         use_device=False).to_bytes()

    sources = []
    for (iss, eh), serials in sorted(state.items()):
        uniq = sorted(set(serials))
        fit = [s for s in uniq if len(s) <= 46]
        big = [s for s in uniq if len(s) > 46]

        def provider(chunk_size, fit=fit, big=big):
            for s0 in range(0, len(fit), chunk_size):
                block = fit[s0: s0 + chunk_size]
                lens, mat = stream.pack_serials(block)
                yield lens, mat, []
            if big:
                yield (np.zeros((0,), np.int64),
                       np.zeros((0, 46), np.uint8), list(big))

        sources.append(PackedGroupSource(iss, eh, len(uniq), provider))
    blob = build_artifact_from_sources(
        sources, fp_rate=0.01, use_device=False,
        stream_chunk=13).to_bytes()
    assert blob == ref


def test_fused_contains_matches_layer_contains():
    """The fused mixed-group probe equals per-group layer_contains on
    the same arena (the chase's bit-parity contract)."""
    from ct_mapreduce_tpu.filter.cascade import (
        _pack_words,
        build_layer,
        layer_contains,
    )

    rng = np.random.default_rng(3)
    ms = np.array([1024, 2048, 512], np.int64)
    ks = np.array([5, 7, 2], np.int64)
    offs_words = np.concatenate(([0], np.cumsum(ms // 32)[:-1]))
    keysets = [rng.integers(0, 2**32, size=(n, 4), dtype=np.uint32)
               for n in (40, 70, 9)]
    words = [build_layer(keysets[g], int(ms[g]), int(ks[g]), 2,
                         use_device=False) for g in range(3)]
    words_all = np.concatenate(words)
    probes = [rng.integers(0, 2**32, size=(25, 4), dtype=np.uint32)
              for _ in range(3)]
    S = np.concatenate(keysets + probes)
    chunks = []
    pos = 0
    sizes = [k.shape[0] for k in keysets] + [25, 25, 25]
    spans = []
    for n in sizes:
        spans.append(np.arange(pos, pos + n, dtype=np.int64))
        pos += n
    chunks = [(0, spans[0]), (1, spans[1]), (2, spans[2]),
              (0, spans[3]), (1, spans[4]), (2, spans[5])]
    got = fused_mod.fused_contains(words_all, chunks, S, 2,
                                   offs_words, ms, ks)
    for (g, idx), hit in zip(chunks, got):
        want = layer_contains(words[g], int(ms[g]), int(ks[g]), 2,
                              S[idx])
        assert np.array_equal(hit, want), g
    # Sanity: the layer really contains its own keys.
    assert got[0].all() and got[1].all() and got[2].all()
    assert _pack_words is not None


def test_fused_dispatch_collapse():
    """The lever itself: a many-group build issues far fewer scatter
    dispatches than the per-(group, layer) count, and the
    groups-per-dispatch stat reflects real packing."""
    rng = np.random.default_rng(5)
    groups = [rng.integers(0, 2**32, size=(int(rng.integers(20, 120)), 4),
                           dtype=np.uint32) for _ in range(12)]
    cascades, stats = fused_mod.build_cascades_fused(
        groups, 0.02, use_device=False)
    assert stats.layers >= 12  # one per group at least
    assert stats.dispatches < stats.layers
    assert stats.mean_groups_per_dispatch() > 2.0
    # And the per-group reference agrees (spot check one group).
    allk = np.concatenate(groups)
    bounds = np.cumsum([0] + [g.shape[0] for g in groups])
    mask = np.zeros(allk.shape[0], bool)
    mask[bounds[3]: bounds[4]] = True
    ref = FilterCascade.build(groups[3], allk[~mask], 0.02,
                              use_device=False)
    got = cascades[3]
    assert len(ref.layers) == len(got.layers)
    for a, b in zip(ref.layers, got.layers):
        assert (a.m, a.k) == (b.m, b.k)
        assert np.array_equal(a.words, b.words)


# -- spill ring -----------------------------------------------------------


def make_ring_state(ring_or_dict, rng, n=300):
    for j in range(n):
        key = (int(rng.integers(0, 4)), 500_000 + int(rng.integers(0, 3)))
        sb = rng.integers(0, 256, int(rng.integers(3, 18)),
                          dtype=np.uint8).tobytes()
        if isinstance(ring_or_dict, SpillCaptureRing):
            ring_or_dict.add(key, sb)
        else:
            ring_or_dict.setdefault(key, set()).add(sb)


def test_spill_ring_matches_dict_capture(tmp_path):
    rng1 = np.random.default_rng(17)
    rng2 = np.random.default_rng(17)
    ring = SpillCaptureRing(str(tmp_path / "spill"), mem_bytes=2048)
    plain: dict = {}
    make_ring_state(ring, rng1)
    make_ring_state(plain, rng2)
    assert ring.spilled_bytes > 0  # the tiny budget really spilled
    assert ring.stats()["segments"] >= 1
    assert ring.items() == sorted(
        (k, set(v)) for k, v in plain.items())
    # Idempotent read; dedup across memory + segments held.
    assert ring.items() == ring.items()


def test_spill_ring_crash_restart_resume(tmp_path):
    """Durably-flushed segments survive a crash (object dropped
    without close); a new ring over the same directory resumes with
    them and keeps appending — no segment number reuse."""
    spill = str(tmp_path / "spill")
    ring = SpillCaptureRing(spill, mem_bytes=64)  # spills ~every add
    for j in range(40):
        ring.add((1, 500_000), bytes([j]) * 8)
    flushed = ring.spilled_bytes
    segs = ring.stats()["segments"]
    assert segs >= 2
    pre_crash = {sb for _, s in ring.items() for sb in s}
    del ring  # crash: in-memory tier lost, segments durable

    back = SpillCaptureRing(spill, mem_bytes=1 << 20)
    assert back.spilled_bytes == flushed
    resumed = {sb for _, s in back.items() for sb in s}
    # Everything durably flushed is back (the unflushed memory tier
    # re-captures via the resume-at-cursor re-fold in production).
    assert resumed == pre_crash  # mem was empty at 'crash' (tiny budget)
    back.add((2, 500_001), b"\xaa" * 9)
    back.flush()
    assert back.stats()["segments"] == segs + 1
    assert ((2, 500_001), {b"\xaa" * 9}) in back.items()


def test_aggregator_spill_capture_byte_identity(tmp_path, monkeypatch):
    """Ingest through a GROWING table with the spill ring on: emitted
    artifact AND checkpoint filter arrays byte-identical to the
    in-memory capture of the same corpus."""
    monkeypatch.setenv("CTMR_TABLE", "bucket")
    issuer = minicert.make_cert(serial=1, issuer_cn="Spill CA",
                                is_ca=True)

    def corpus(n, base):
        ents = [(minicert.make_cert(serial=base + s,
                                    issuer_cn="Spill CA",
                                    subject_cn=f"s{s}.example"), issuer)
                for s in range(n)]
        return ents + ents[: n // 5]

    aggs = []
    for spill in (False, True):
        agg = TpuAggregator(capacity=1 << 8, batch_size=64,
                            grow_at=0.5, max_capacity=1 << 14)
        if spill:
            agg.enable_filter_capture(
                spill_dir=str(tmp_path / "ring"), spill_mem_bytes=512)
        else:
            agg.enable_filter_capture()
        agg.ingest(corpus(150, 1000))  # growth fires mid-corpus
        assert agg.capacity > (1 << 8)
        aggs.append(agg)
    plain, spilled = aggs
    assert isinstance(spilled.filter_capture, SpillCaptureRing)
    assert spilled.filter_capture.spilled_bytes > 0
    a = build_from_aggregator(plain, fp_rate=0.01).to_bytes()
    b = build_from_aggregator(spilled, fp_rate=0.01).to_bytes()
    assert a == b
    p1, p2 = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    plain.save_checkpoint(p1)
    spilled.save_checkpoint(p2)
    z1 = np.load(p1, allow_pickle=True)
    z2 = np.load(p2, allow_pickle=True)
    assert np.array_equal(z1["filter_keys"], z2["filter_keys"])
    assert list(z1["filter_vals"]) == list(z2["filter_vals"])
    # The npz round-trips into a dict capture (contract unchanged),
    # and a restored run can re-arm the ring seeded from it.
    back = HostSnapshotAggregator(capacity=1 << 10)
    back.load_checkpoint(p2)
    assert isinstance(back.filter_capture, dict)
    back.enable_filter_capture(spill_dir=str(tmp_path / "ring2"),
                               spill_mem_bytes=256)
    assert isinstance(back.filter_capture, SpillCaptureRing)
    assert build_from_aggregator(back, fp_rate=0.01).to_bytes() == a


def test_fleet_merged_matches_streamed_spilled(tmp_path):
    """The four-way acceptance identity: in-memory, streamed, spilled,
    and fleet-merged builds of the same logical corpus agree."""
    from ct_mapreduce_tpu.agg import merge
    from ct_mapreduce_tpu.filter import build_from_merged

    issuer_a = minicert.make_cert(serial=1, issuer_cn="FM CA",
                                  is_ca=True)
    issuer_b = minicert.make_cert(serial=2, issuer_cn="FM CA B",
                                  is_ca=True)

    def corpus(n, cn, issuer, base):
        return [(minicert.make_cert(serial=base + s, issuer_cn=cn,
                                    subject_cn=f"m{s}.example"), issuer)
                for s in range(n)]

    half_a = corpus(45, "FM CA", issuer_a, 1000)
    half_b = corpus(45, "FM CA B", issuer_b, 600_000)
    paths = []
    for w, ents in enumerate((half_b, half_a)):
        agg = TpuAggregator(capacity=1 << 10, batch_size=64)
        agg.enable_filter_capture(
            spill_dir=str(tmp_path / f"ring{w}"), spill_mem_bytes=256)
        agg.ingest(ents)
        p = str(tmp_path / f"agg.w{w}.npz")
        agg.save_checkpoint(p)
        paths.append(p)
    serial = TpuAggregator(capacity=1 << 10, batch_size=64)
    serial.enable_filter_capture()
    serial.ingest(half_a + half_b)

    merged_blob = build_from_merged(
        merge.load_checkpoints(paths), fp_rate=0.01).to_bytes()
    in_mem = build_from_aggregator(serial, fp_rate=0.01).to_bytes()
    streamed = build_from_aggregator(serial, fp_rate=0.01)  # warm path
    assert merged_blob == in_mem
    assert streamed.to_bytes() == in_mem


# -- config surface -------------------------------------------------------


def test_resolve_filter_scale_knobs(monkeypatch, tmp_path):
    for env in ("CTMR_FILTER_SPILL_DIR", "CTMR_FILTER_SPILL_MB",
                "CTMR_FILTER_STREAM_CHUNK", "CTMR_FILTER_FUSED_LANES",
                "CTMR_PLATFORM_PROFILE"):
        monkeypatch.delenv(env, raising=False)
    r = resolve_filter()
    assert (r.spill_dir, r.spill_mb, r.stream_chunk, r.fused_lanes) \
        == ("", 256, 0, 0)
    monkeypatch.setenv("CTMR_FILTER_SPILL_DIR", "/x/ring")
    monkeypatch.setenv("CTMR_FILTER_SPILL_MB", "64")
    monkeypatch.setenv("CTMR_FILTER_STREAM_CHUNK", "4096")
    r = resolve_filter()
    assert (r.spill_dir, r.spill_mb, r.stream_chunk) \
        == ("/x/ring", 64, 4096)
    # Explicit beats env.
    r = resolve_filter(spill_dir="/y", spill_mb=32, stream_chunk=512,
                       fused_lanes=2048)
    assert (r.spill_dir, r.spill_mb, r.stream_chunk, r.fused_lanes) \
        == ("/y", 32, 512, 2048)
    # Profile sits under env, above defaults.
    prof = tmp_path / "prof.json"
    prof.write_text(json.dumps({
        "version": 1, "platform": "test",
        "knobs": {"filter": {"filterCaptureSpillMB": 128,
                             "filterFusedLanes": 8192}}}))
    monkeypatch.setenv("CTMR_PLATFORM_PROFILE", str(prof))
    monkeypatch.delenv("CTMR_FILTER_SPILL_MB", raising=False)
    r = resolve_filter()
    assert (r.spill_mb, r.fused_lanes) == (128, 8192)


def test_config_scale_directives(tmp_path):
    from ct_mapreduce_tpu.config import CTConfig

    ini = tmp_path / "f.ini"
    ini.write_text("filterCaptureSpillDir = /tmp/ring\n"
                   "filterCaptureSpillMB = 96\n"
                   "filterStreamChunk = 65536\n"
                   "filterFusedLanes = 131072\n")
    cfg = CTConfig.load(["-config", str(ini)], env={})
    assert cfg.filter_capture_spill_dir == "/tmp/ring"
    assert cfg.filter_capture_spill_mb == 96
    assert cfg.filter_stream_chunk == 65536
    assert cfg.filter_fused_lanes == 131072
    for d in ("filterCaptureSpillDir", "filterCaptureSpillMB",
              "filterStreamChunk", "filterFusedLanes"):
        assert d in cfg.usage()


def test_list_source_semantics():
    src = ListGroupSource("iss", 500_000,
                          [b"\x02", b"\x01", b"\x02", b"\x61" * 60])
    assert src.n == 3  # dedup incl. the oversized serial
    blocks = list(stream.key_blocks(src, 0, 2, use_device=False))
    total = sum(b.shape[0] for b in blocks)
    assert total == 3
    keys = np.concatenate(blocks)
    assert len({k.tobytes() for k in keys}) == 3
