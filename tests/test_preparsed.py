"""Pre-parsed ingest lane: sidecar extraction + walker-free device step.

Three contracts pinned here (ISSUE 7):

1. **Sidecar == device walker, on EVERY input.** The native extractor
   (ctmr_extract_sidecars) is a scalar port of ops/der_kernel.py's
   parse_certs — bit-exact ok bits and fields across the mutation
   fuzz, walker-rejected mutants included. This is what lets the
   pre-parsed lane substitute host extraction for the on-device walk
   without re-routing a single lane (the ParsEval divergence class,
   arXiv:2405.18993, as a hard test instead of a hope).
2. **Sidecar fields == exact host lane** on certs both accept (the
   same hard contract the walker itself carries in
   test_der_kernel.py's fuzz — serial window, expiry bucket, CA flag,
   CN bytes, CRLDP URLs).
3. **Undecidable lanes fall back to the device walker** through the
   sink, with aggregate results AND host-lane spill counts identical
   to the pure walker lane.
"""

import base64
import datetime
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ct_mapreduce_tpu.core import der as hostder
from ct_mapreduce_tpu.native import available, leafpack
from ct_mapreduce_tpu.ops import der_kernel

from tests import certgen
from tests.test_der_kernel import fixture_certs, pack

UTC = datetime.timezone.utc
FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)

pytestmark = pytest.mark.skipif(
    not available(), reason="native library unavailable (no C++ compiler)")

# (sidecar field, ParsedCerts field) — everything the walker extracts.
FIELD_PAIRS = [
    ("serial_off", "serial_off"), ("serial_len", "serial_len"),
    ("not_after_hour", "not_after_hour"), ("is_ca", "is_ca"),
    ("has_crldp", "has_crldp"),
    ("cn_off", "issuer_cn_off"), ("cn_len", "issuer_cn_len"),
    ("issuer_off", "issuer_off"), ("issuer_len", "issuer_len"),
    ("spki_off", "spki_off"), ("spki_len", "spki_len"),
    ("crldp_off", "crldp_off"), ("crldp_len", "crldp_len"),
]


def _assert_sidecar_equals_walker(ders, pad_to=1024):
    data, length = pack(ders, pad_to=pad_to)
    sc = leafpack.extract_sidecars(data, length)
    out = der_kernel.parse_certs(data, length)
    ok_dev = np.asarray(out.ok)
    assert np.array_equal(sc.ok.astype(bool), ok_dev), (
        "ok-bit divergence at lanes "
        f"{np.nonzero(sc.ok.astype(bool) != ok_dev)[0][:10]}")
    for i in np.nonzero(ok_dev)[0]:
        for sf, df in FIELD_PAIRS:
            got = int(getattr(sc, sf)[i])
            want = int(np.asarray(getattr(out, df))[i])
            assert got == want, (
                f"lane {i} field {sf}: sidecar={got} walker={want} "
                f"der={ders[i].hex()}")
    return sc, out


def test_sidecar_matches_walker_on_fixtures():
    certs = fixture_certs() + [
        certgen.make_cert(serial=7, crl_dps=("ldap://drop.me/x",)),
        certgen.make_cert(serial=8, is_ca=True),
        certgen.make_cert(serial=9, extra_extensions=5),
    ]
    sc, _ = _assert_sidecar_equals_walker(certs)
    assert sc.ok.all()


@pytest.mark.slow
def test_sidecar_matches_walker_on_mutation_fuzz():
    """The strong pin: ok bits AND fields bit-equal on 400 mutants —
    including the walker-REJECTED ones (equality of the reject set is
    what guarantees identical host-lane spill counts).

    @slow since round 15 (tier-1 budget banking, ISSUE 10): the ok-bit
    agreement is now ALSO pinned tier-1 by the divergence-classified
    walker fuzz (test_der_kernel.py: sidecar_undecidable == 0 over 300
    mutants) and the field equality by
    test_sidecar_fields_match_exact_host_lane_fuzz below; this 400-
    mutant sweep re-walks the same contract and runs in the full
    (unmarked) suite."""
    rng = np.random.default_rng(20260804)
    bases = fixture_certs()
    mutants = []
    for _ in range(400):
        b = bytearray(bases[int(rng.integers(len(bases)))])
        for _k in range(int(rng.integers(1, 4))):
            b[int(rng.integers(len(b)))] ^= int(rng.integers(1, 256))
        mutants.append(bytes(b))
    sc, out = _assert_sidecar_equals_walker(mutants)
    accepted = int(np.asarray(out.ok).sum())
    rejected = len(mutants) - accepted
    # The fuzz must exercise both sides of the ok bit.
    assert accepted > 50 and rejected > 10, (accepted, rejected)


def test_sidecar_fields_match_exact_host_lane_fuzz():
    """Satellite contract: on every fuzzed DER that BOTH the sidecar
    extractor and the strict host parser accept, the identity-surface
    fields agree byte-for-byte (serial window, expiry bucket, isCA,
    CN bytes, CRLDP URLs). Walker-style bounded leniency (sidecar
    accepts, host rejects) is tolerated and bounded, exactly like the
    device walker's own fuzz contract."""
    rng = np.random.default_rng(20260805)
    bases = fixture_certs()
    mutants = []
    for _ in range(300):
        b = bytearray(bases[int(rng.integers(len(bases)))])
        b[int(rng.integers(len(b)))] ^= int(rng.integers(1, 256))
        mutants.append(bytes(b))
    data, length = pack(mutants, pad_to=1024)
    sc = leafpack.extract_sidecars(data, length)
    accepted = mismatches = host_rejects = 0
    for i, der in enumerate(mutants):
        if not sc.ok[i]:
            continue
        accepted += 1
        try:
            ref = hostder.parse_cert(der)
        except Exception:
            host_rejects += 1
            continue
        serial_window = der[int(sc.serial_off[i]):
                            int(sc.serial_off[i]) + int(sc.serial_len[i])]
        cn_bytes = der[int(sc.cn_off[i]):int(sc.cn_off[i]) + int(sc.cn_len[i])]
        try:
            cn_str = cn_bytes.decode("utf-8")
        except UnicodeDecodeError:
            cn_str = cn_bytes.decode("latin-1")
        if bool(sc.has_crldp[i]):
            try:
                urls = hostder._parse_crldp(der, int(sc.crldp_off[i]))
            except Exception:
                urls = ["<unparseable>"]
        else:
            urls = []
        if (serial_window != ref.serial
                or int(sc.not_after_hour[i]) != ref.not_after_unix_hour
                or bool(sc.is_ca[i]) != ref.is_ca
                or cn_str != ref.issuer_cn
                or int(sc.spki_off[i]) != ref.spki_off
                or int(sc.spki_len[i]) != ref.spki_len
                or sorted(urls) != sorted(ref.crl_distribution_points)):
            mismatches += 1
            print(f"MISMATCH lane {i} der={der.hex()}")
    assert accepted > 50, accepted
    assert mismatches == 0, f"{mismatches}/{accepted}"
    assert host_rejects < 0.25 * accepted, (host_rejects, accepted)


def _wire(pairs):
    """[(leaf_der, issuer_der)] → base64 wire lists."""
    from ct_mapreduce_tpu.ingest import leaf as leaflib

    lis, eds = [], []
    for j, (leaf, issuer) in enumerate(pairs):
        lis.append(base64.b64encode(
            leaflib.encode_leaf_input(leaf, timestamp_ms=1700000000000 + j)
        ).decode())
        eds.append(base64.b64encode(
            leaflib.encode_extra_data([issuer])).decode())
    return lis, eds


def _replay_sink(lis, eds, preparsed, cn_prefixes=(), chunk=None,
                 now=None):
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch

    chunk = chunk or len(lis)
    agg = TpuAggregator(capacity=1 << 12, batch_size=chunk,
                        cn_prefixes=cn_prefixes, now=now)
    sink = AggregatorSink(agg, flush_size=chunk, device_queue_depth=0,
                          preparsed=preparsed)
    sink.store_raw_batch(RawBatch(list(lis), list(eds), 0, "pre-log"))
    sink.flush()
    return agg, agg.drain()


def test_undecidable_lanes_fall_back_to_device_walker():
    """Certs the walker cannot decide (here: past the MAX_EXTS scan
    budget, which the strict host parser handles fine) must flow
    through the sink's walker-fallback replay and land EXACTLY where
    the pure walker lane puts them — same drains, same metrics, same
    host-lane spill counts."""
    issuer = certgen.make_cert(serial=1, issuer_cn="Fallback CA",
                               is_ca=True, not_after=FUTURE)
    pairs = []
    for s in range(6):
        pairs.append((certgen.make_cert(
            serial=100 + s, issuer_cn="Fallback CA", is_ca=False,
            not_after=FUTURE), issuer))
    # Over-budget extension lists: walker (and sidecar) reject, exact
    # host lane accepts.
    heavy = [certgen.make_cert(serial=200 + s, issuer_cn="Fallback CA",
                               is_ca=False, not_after=FUTURE,
                               extra_extensions=der_kernel.MAX_EXTS + 4)
             for s in range(3)]
    pairs += [(h, issuer) for h in heavy]
    # And one structurally-broken cert (serial tag corrupted): both
    # lanes must hand it to the exact host lane, which rejects it.
    broken = bytearray(pairs[0][0])
    ref = hostder.parse_cert(bytes(broken))
    broken[ref.serial_off - 2] = 0x05
    pairs.append((bytes(broken), issuer))

    data, length = pack([p[0] for p in pairs], pad_to=2048)
    sc = leafpack.extract_sidecars(data, length)
    assert not sc.ok[6:].any(), "heavy/broken lanes must be undecidable"
    assert sc.ok[:6].all()

    lis, eds = _wire(pairs)
    agg_w, snap_w = _replay_sink(lis, eds, preparsed=False)
    agg_p, snap_p = _replay_sink(lis, eds, preparsed=True)
    assert snap_w.counts == snap_p.counts
    assert snap_w.crls == snap_p.crls and snap_w.dns == snap_p.dns
    assert agg_w.metrics == agg_p.metrics, (agg_w.metrics, agg_p.metrics)
    assert snap_p.total == 9  # 6 clean + 3 heavy; broken rejected
    assert agg_p.metrics["host_lane"] == 4  # 3 heavy + 1 broken
    assert agg_p.metrics["parse_errors"] == 1


def test_filter_routing_parity_with_walker_lane():
    """CA / expired / CN-filter / boundary-hour routing: the host-side
    predicate mirror must land every lane exactly where the walker
    lane lands it (metrics AND drained counts)."""
    now = datetime.datetime(2026, 1, 1, tzinfo=UTC)
    issuer = certgen.make_cert(serial=1, issuer_cn="Route CA", is_ca=True,
                               not_after=FUTURE)
    boundary = now.replace(minute=30)  # expires within the current hour
    pairs = [
        (certgen.make_cert(serial=10, issuer_cn="Route CA", is_ca=False,
                           not_after=FUTURE), issuer),
        (certgen.make_cert(serial=11, issuer_cn="Route CA", is_ca=True,
                           not_after=FUTURE), issuer),  # filtered: CA
        (certgen.make_cert(serial=12, issuer_cn="Route CA", is_ca=False,
                           not_after=datetime.datetime(
                               2020, 1, 1, tzinfo=UTC)), issuer),  # expired
        (certgen.make_cert(serial=13, issuer_cn="Route CA", is_ca=False,
                           not_after=boundary), issuer),  # boundary → host
        (certgen.make_cert(serial=14, issuer_cn="Other CA", is_ca=False,
                           not_after=FUTURE), issuer),  # CN filter miss
    ]
    lis, eds = _wire(pairs)
    results = []
    for pre in (False, True):
        agg, snap = _replay_sink(lis, eds, preparsed=pre,
                                 cn_prefixes=("Route CA",), now=now)
        results.append((agg.metrics, dict(snap.counts), snap.total))
    assert results[0] == results[1], results
    metrics, _counts, total = results[1]
    assert total == 2  # serial 10 (device) + serial 13 (boundary, host)
    assert metrics["filtered_ca"] == 1
    assert metrics["filtered_expired"] == 1
    assert metrics["filtered_cn"] == 1
    assert metrics["host_lane"] == 1


def test_preparsed_dedup_and_replay():
    """Dedup across the pre-parsed lane: a replayed stream inserts
    nothing, and the was-unknown bitmask decodes to the right lanes."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from __graft_entry__ import _packed_batch, _NOW

    data, length, issuer_idx, valid, templates = _packed_batch(96, 1024)
    agg = TpuAggregator(capacity=1 << 12, batch_size=32, now=_NOW)
    for t in templates:
        agg.registry.get_or_assign(t.issuer_der)
    sc = leafpack.extract_sidecars(data, length)
    assert sc.ok.all()
    res1 = agg.ingest_preparsed(sc, issuer_idx, valid, data, length)
    assert res1.was_unknown.all()
    res2 = agg.ingest_preparsed(sc, issuer_idx, valid, data, length)
    assert not res2.was_unknown.any()
    assert agg.metrics["inserted"] == 96 and agg.metrics["known"] == 96
    assert agg.drain().total == 96


def test_preparsed_overflow_spills_to_host_lane_exactly():
    """Probe-overflow lanes surface through the compacted-flag
    readback (including the spill fallback past flag_cap) and resolve
    through the exact host lane — totals stay exact and match the
    walker lane at identical table settings."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.telemetry import metrics as tmetrics
    from __graft_entry__ import _packed_batch, _NOW

    n = 512
    data, length, issuer_idx, valid, templates = _packed_batch(n, 1024)
    sc = leafpack.extract_sidecars(data, length)

    def run(pre):
        # Tiny table, growth off, single probe: most lanes overflow.
        agg = TpuAggregator(capacity=32, batch_size=n, now=_NOW,
                            max_probes=1, grow_at=0, max_capacity=32)
        for t in templates:
            agg.registry.get_or_assign(t.issuer_der)
        if pre:
            res = agg.ingest_preparsed(sc, issuer_idx, valid, data, length)
        else:
            res = agg.ingest_packed(data, length, issuer_idx, valid)
        return agg, res

    sink = tmetrics.InMemSink()
    prev = tmetrics.get_sink()
    tmetrics.set_sink(sink)
    try:
        agg_p, res_p = run(True)
    finally:
        tmetrics.set_sink(prev)
    agg_w, res_w = run(False)
    assert agg_p.metrics["overflow"] > 64  # past flag_cap ⇒ spill path
    assert agg_p.metrics == agg_w.metrics
    assert np.array_equal(res_p.was_unknown, res_w.was_unknown)
    assert agg_p.drain().counts == agg_w.drain().counts
    counters = sink.snapshot()["counters"]
    assert counters.get("ingest.flag_cap_spill", 0) >= 1
    # The spill fetched the full overflow bitmask on top of the
    # compact block — still far below a per-lane int32 status row.
    assert counters["ingest.d2h_flag_bytes"] < 4 * n


def test_sidecar_unavailable_falls_back_to_walker_lane(monkeypatch):
    """CTMR_NATIVE=0 (or a missing library) must leave the sink on the
    walker lane — preparsed is an optimization, never a dependency."""
    monkeypatch.setenv("CTMR_NATIVE", "0")
    issuer = certgen.make_cert(serial=1, issuer_cn="NoNative CA",
                               is_ca=True, not_after=FUTURE)
    pairs = [(certgen.make_cert(serial=30 + s, issuer_cn="NoNative CA",
                                is_ca=False, not_after=FUTURE), issuer)
             for s in range(4)]
    lis, eds = _wire(pairs)
    agg, snap = _replay_sink(lis, eds, preparsed=True)
    assert snap.total == 4
    assert agg.metrics["inserted"] == 4
