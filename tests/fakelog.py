"""An in-process CT log served through the injectable transport.

The reference tests against real logs + a real Redis; this
zero-egress environment instead synthesizes a wire-faithful log: real
signed templates (tests/certgen or utils/syncerts), RFC 6962 leaf
encoding, and a transport callable that answers get-sth / get-entries
/ get-entry-and-proof exactly like a log server would.
"""

from __future__ import annotations

import base64
import json
import re
from urllib.parse import parse_qs, urlparse

from ct_mapreduce_tpu.ingest import leaf as leaflib


class FakeLog:
    def __init__(self, url: str = "https://ct.example.com/fake"):
        self.url = url
        self.entries: list[dict] = []  # {"leaf_input": b64, "extra_data": b64}
        self.max_batch = 1000
        self.rate_limit_hits = 0  # serve this many 429s before succeeding
        self.server_error_hits = 0  # serve this many 5xx before succeeding
        self.server_error_status = 503
        self.retry_after: str | None = None
        self.requests: list[str] = []

    def add_cert(self, cert_der: bytes, issuer_der: bytes, timestamp_ms: int = 0):
        li = leaflib.encode_leaf_input(cert_der, timestamp_ms)
        ed = leaflib.encode_extra_data([issuer_der])
        self.entries.append(
            {
                "leaf_input": base64.b64encode(li).decode(),
                "extra_data": base64.b64encode(ed).decode(),
            }
        )

    def add_precert(
        self, precert_der: bytes, issuer_der: bytes, timestamp_ms: int = 0
    ):
        li = leaflib.encode_leaf_input(
            b"\x00" * 10,  # TBS stand-in; the store path uses extra_data
            timestamp_ms,
            entry_type=leaflib.PRECERT_ENTRY,
        )
        ed = leaflib.encode_extra_data(
            [issuer_der],
            entry_type=leaflib.PRECERT_ENTRY,
            pre_certificate=precert_der,
        )
        self.entries.append(
            {
                "leaf_input": base64.b64encode(li).decode(),
                "extra_data": base64.b64encode(ed).decode(),
            }
        )

    def add_garbage(self):
        self.entries.append(
            {
                "leaf_input": base64.b64encode(b"\xff\xff").decode(),
                "extra_data": "",
            }
        )

    # -- transport -------------------------------------------------------
    def transport(self, url: str) -> tuple[int, dict, bytes]:
        self.requests.append(url)
        if self.rate_limit_hits > 0:
            self.rate_limit_hits -= 1
            headers = {}
            if self.retry_after is not None:
                headers["Retry-After"] = self.retry_after
            return 429, headers, b"slow down"
        if self.server_error_hits > 0:
            self.server_error_hits -= 1
            headers = {}
            if self.retry_after is not None:
                headers["Retry-After"] = self.retry_after
            return self.server_error_status, headers, b"upstream sad"
        parsed = urlparse(url)
        if parsed.path.endswith("/ct/v1/get-sth"):
            return 200, {}, json.dumps(
                {"tree_size": len(self.entries), "timestamp": 1700000000000}
            ).encode()
        if parsed.path.endswith("/ct/v1/get-entries"):
            q = parse_qs(parsed.query)
            start = int(q["start"][0])
            end = min(
                int(q["end"][0]), start + self.max_batch - 1, len(self.entries) - 1
            )
            if start >= len(self.entries):
                return 400, {}, b"range beyond tree size"
            return 200, {}, json.dumps(
                {"entries": self.entries[start : end + 1]}
            ).encode()
        m = re.search(r"/ct/v1/get-entry-and-proof$", parsed.path)
        if m:
            q = parse_qs(parsed.query)
            idx = int(q["leaf_index"][0])
            e = self.entries[idx]
            return 200, {}, json.dumps(
                {"leaf_input": e["leaf_input"], "extra_data": e["extra_data"],
                 "audit_path": []}
            ).encode()
        return 404, {}, b"not found"
