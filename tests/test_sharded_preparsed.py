"""Pre-parsed ingest lane over the mesh: host-routed sharded step.

``ShardedAggregator._device_step_preparsed`` routes every sidecar lane
to its fingerprint's home shard ON THE HOST (numpy SHA-256 mirror +
the `_shard_of` hash), partitions lanes per shard before H2D, and runs
a shard-local fingerprint+insert step — no ``all_to_all``. Contracts
pinned here:

1. The numpy fingerprint mirror equals the scalar host reference (and
   therefore the device SHA) word for word.
2. mesh=1 sharded-preparsed is parity-EXACT with single-chip preparsed:
   was-unknown lanes, metrics, drains — including probe-overflow spill
   counts through the compacted-flag readback and its bitmask
   fallback.
3. A multi-shard mesh keeps the same aggregate parity, dedups across
   replays, and psum's per-issuer counts correctly.
4. The old loud rejection is gone: preparsedIngest + meshShape is a
   supported combination end to end through the sink.
"""

import datetime
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
from jax.sharding import Mesh

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.native import available, leafpack

pytestmark = pytest.mark.skipif(
    not available(), reason="native library unavailable (no C++ compiler)")

UTC = datetime.timezone.utc


def _mesh(n):
    devs = np.array(jax.devices()[:n])
    assert devs.size == n, "conftest must provide 8 virtual devices"
    return Mesh(devs, ("shard",))


def _fixtures(n, pad=1024):
    from __graft_entry__ import _NOW, _packed_batch

    data, length, issuer_idx, valid, templates = _packed_batch(n, pad)
    sc = leafpack.extract_sidecars(data, length)
    return data, length, issuer_idx, valid, templates, sc, _NOW


def test_fingerprints_np_matches_host_reference():
    rng = np.random.default_rng(3)
    n = 128
    ii = rng.integers(0, packing.MAX_ISSUERS, n).astype(np.int32)
    eh = rng.integers(400_000, 650_000, n).astype(np.int32)
    slen = rng.integers(1, packing.MAX_SERIAL_BYTES + 1, n).astype(np.int32)
    ser = np.zeros((n, packing.MAX_SERIAL_BYTES), np.uint8)
    for i in range(n):
        ser[i, : slen[i]] = rng.integers(0, 256, slen[i])
    fps = packing.fingerprints_np(ii, eh, ser, slen)
    for i in range(n):
        want = packing.fingerprint_host(
            int(ii[i]), int(eh[i]), bytes(ser[i, : slen[i]]))
        assert tuple(int(x) for x in fps[i]) == want, i


def _run_preparsed(agg, fixtures, repeats=1):
    data, length, issuer_idx, valid, templates, sc, _now = fixtures
    for t in templates:
        agg.registry.get_or_assign(t.issuer_der)
    results = [agg.ingest_preparsed(sc, issuer_idx, valid, data, length)
               for _ in range(repeats)]
    return results, agg


def test_mesh1_parity_exact_with_single_chip():
    """counts, spill counts, flagged-lane ids: mesh=1 must be
    indistinguishable from the single-chip pre-parsed lane (same table
    structure at matched capacity, same lane processing order)."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    fx = _fixtures(96)
    now = fx[6]
    (r1a, r2a), a = _run_preparsed(
        TpuAggregator(capacity=1 << 12, batch_size=32, now=now),
        fx, repeats=2)
    (r1b, r2b), b = _run_preparsed(
        ShardedAggregator(_mesh(1), capacity=1 << 12, batch_size=32,
                          now=now),
        fx, repeats=2)
    np.testing.assert_array_equal(r1a.was_unknown, r1b.was_unknown)
    np.testing.assert_array_equal(r2a.was_unknown, r2b.was_unknown)
    np.testing.assert_array_equal(r1a.filtered, r1b.filtered)
    assert r1a.serials == r1b.serials
    assert a.metrics == b.metrics, (a.metrics, b.metrics)
    assert a.drain().counts == b.drain().counts


def test_mesh1_overflow_spill_parity_exact():
    """Probe-overflow spills (tiny table, single probe) must surface
    through the per-shard compacted-flag readback — including the
    full-bitmask fallback past flag_cap — at EXACTLY the lanes the
    single-chip lane flags. Capacity 48 rounds identically under both
    table constructions (bucket layout)."""
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    fx = _fixtures(512)
    now = fx[6]
    (r1,), a = _run_preparsed(
        TpuAggregator(capacity=48, batch_size=512, now=now, max_probes=1,
                      grow_at=0, max_capacity=48), fx)
    (r2,), b = _run_preparsed(
        ShardedAggregator(_mesh(1), capacity=48, batch_size=512, now=now,
                          max_probes=1, grow_at=0, max_capacity=48), fx)
    assert a.capacity == b.capacity == 48
    assert a.metrics["overflow"] > 64  # past flag_cap ⇒ spill fallback
    assert a.metrics == b.metrics, (a.metrics, b.metrics)
    np.testing.assert_array_equal(r1.was_unknown, r2.was_unknown)
    assert a.drain().counts == b.drain().counts


def test_mesh8_parity_dedup_and_issuer_counts():
    from ct_mapreduce_tpu.agg.aggregator import TpuAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    fx = _fixtures(96)
    now = fx[6]
    (r1a, r2a), a = _run_preparsed(
        TpuAggregator(capacity=1 << 12, batch_size=32, now=now),
        fx, repeats=2)
    (r1b, r2b), b = _run_preparsed(
        ShardedAggregator(_mesh(8), capacity=1 << 12, batch_size=32,
                          now=now),
        fx, repeats=2)
    # First pass inserts everything, replay inserts nothing — and the
    # psum'd per-issuer totals match the single-chip fold exactly.
    assert r1b.was_unknown.all() and not r2b.was_unknown.any()
    np.testing.assert_array_equal(r1a.was_unknown, r1b.was_unknown)
    assert a.metrics == b.metrics, (a.metrics, b.metrics)
    np.testing.assert_array_equal(a.issuer_totals, b.issuer_totals)
    assert a.drain().counts == b.drain().counts
    assert b._table_fill_exact() == 96


def test_routing_is_fingerprint_home_shard():
    """The host route must place every lane on the shard the device
    hash would pick (shard_of_np == _shard_of on the same words)."""
    import jax.numpy as jnp

    from ct_mapreduce_tpu.agg import sharded

    rng = np.random.default_rng(11)
    fps = rng.integers(0, 2**32, size=(257, 4), dtype=np.uint64).astype(
        np.uint32)
    for n_shards in (2, 8):
        host = sharded.shard_of_np(fps, n_shards)
        dev = np.asarray(sharded._shard_of(jnp.asarray(fps), n_shards))
        np.testing.assert_array_equal(host, dev)


def test_sink_accepts_preparsed_with_mesh():
    """End to end through AggregatorSink: preparsedIngest + mesh is a
    supported combination (the round-7 rejection is gone), undecidable
    lanes still replay through the walker path on the mesh."""
    import base64 as b64mod

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator
    from ct_mapreduce_tpu.ingest import leaf as leaflib
    from ct_mapreduce_tpu.ingest.sync import AggregatorSink, RawBatch
    from ct_mapreduce_tpu.ops import der_kernel
    from tests import certgen

    FUTURE = datetime.datetime(2031, 6, 15, tzinfo=UTC)
    issuer = certgen.make_cert(serial=1, issuer_cn="Mesh CA", is_ca=True,
                               not_after=FUTURE)
    pairs = [(certgen.make_cert(serial=100 + s, issuer_cn="Mesh CA",
                                is_ca=False, not_after=FUTURE), issuer)
             for s in range(8)]
    # One walker-undecidable cert (over the extension scan budget):
    # must replay through the sharded walker path, not get lost.
    pairs.append((certgen.make_cert(
        serial=200, issuer_cn="Mesh CA", is_ca=False, not_after=FUTURE,
        extra_extensions=der_kernel.MAX_EXTS + 4), issuer))
    lis, eds = [], []
    for j, (leaf, iss) in enumerate(pairs):
        lis.append(b64mod.b64encode(leaflib.encode_leaf_input(
            leaf, timestamp_ms=1700000000000 + j)).decode())
        eds.append(b64mod.b64encode(
            leaflib.encode_extra_data([iss])).decode())

    agg = ShardedAggregator(_mesh(8), capacity=1 << 12, batch_size=16)
    sink = AggregatorSink(agg, flush_size=16, device_queue_depth=0,
                          preparsed=True)
    sink.store_raw_batch(RawBatch(lis, eds, 0, "mesh-log"))
    sink.flush()
    snap = agg.drain()
    assert snap.total == len(pairs)
    assert agg.metrics["inserted"] == len(pairs)
    # The undecidable lane took the exact host lane via walker replay.
    assert agg.metrics["host_lane"] == 1
