"""CT_COMPILE_CACHE: persistent XLA compilation cache wiring.

The bench's three legs repaid ~580 s of repeated compile in
BENCH_r05.json; on locally-compiling stacks the persistent cache
removes that tax across processes. This tier-1 test pins the contract:
with the cache enabled, a SECOND trace of the same step shape is a
cache HIT (observed through jax's own monitoring events), not a
recompile.

The probe runs in a SUBPROCESS (round-17 budget audit): its
``jax.clear_caches()`` — required to prove the persistent hit — used
to wipe every in-memory executable of the whole tier-1 process
mid-suite, so everything compiled by the (alphabetically earlier)
bench-smoke legs was silently recompiled by every later test file.
Isolating it repaid ~1 subprocess jax startup to save several
kernel-family recompiles per suite run.
"""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = r"""
import os, sys
import numpy as np
import jax

if os.environ.get("CT_TPU_TESTS", "") == "":
    jax.config.update("jax_platforms", "cpu")
import bench

cache_dir = os.environ["CT_COMPILE_CACHE"]
assert bench.maybe_enable_compile_cache() == cache_dir

from jax._src import monitoring

events = []
monitoring.register_event_listener(lambda name, **kw: events.append(name))

from ct_mapreduce_tpu.core import packing
from ct_mapreduce_tpu.ops import pipeline

# A real (small) pre-parsed step shape — the same jit'd program the
# aggregator dispatches.
s = packing.MAX_SERIAL_BYTES

def step(table):
    return pipeline.ingest_step_preparsed(
        table, np.zeros((1, 64, s), np.uint8),
        np.zeros((1, 64), np.int32),
        np.full((1, 64), packing.DEFAULT_BASE_HOUR + 1, np.int32),
        np.zeros((1, 64), np.int32), np.ones((1, 64), bool),
        np.int32(packing.DEFAULT_BASE_HOUR),
        max_probes=4, flag_cap=64,
    )

table, out = step(pipeline.make_table(1 << 10))
np.asarray(out.packed)
assert any(os.scandir(cache_dir)), "no cache entry written"
first_hits = sum(1 for e in events if "cache_hit" in e)

# Drop every in-memory executable; the SAME shape must come back from
# the persistent cache, not a recompile.
jax.clear_caches()
table, out = step(pipeline.make_table(1 << 10))
np.asarray(out.packed)
second_hits = sum(1 for e in events if "cache_hit" in e)
assert second_hits > first_hits, (
    "no persistent-cache hit on the second trace "
    f"(events: {sorted(set(events))})")
print("CACHE-HIT-OK")
"""


@pytest.mark.timeout(120)
def test_second_trace_of_same_step_shape_is_cache_hit(tmp_path):
    env = dict(os.environ)
    env["CT_COMPILE_CACHE"] = str(tmp_path)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.environ.get("PYTHONPATH", ""), REPO) if p)
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=110,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CACHE-HIT-OK" in proc.stdout, (proc.stdout,
                                           proc.stderr[-500:])
