"""CT_COMPILE_CACHE: persistent XLA compilation cache wiring.

The bench's three legs repaid ~580 s of repeated compile in
BENCH_r05.json; on locally-compiling stacks the persistent cache
removes that tax across processes. This tier-1 test pins the contract:
with the cache enabled, a SECOND trace of the same step shape is a
cache HIT (observed through jax's own monitoring events), not a
recompile.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(120)
def test_second_trace_of_same_step_shape_is_cache_hit(tmp_path, monkeypatch):
    import jax

    if os.environ.get("CT_TPU_TESTS", "") == "":
        jax.config.update("jax_platforms", "cpu")
    monkeypatch.setenv("CT_COMPILE_CACHE", str(tmp_path))
    import bench

    assert bench.maybe_enable_compile_cache() == str(tmp_path)
    # Earlier compiles in this process may have latched the "no cache
    # configured" decision; drop it so the new dir takes effect (a
    # fresh production process never needs this).
    from jax._src import compilation_cache

    compilation_cache.reset_cache()

    from jax._src import monitoring

    events: list[str] = []
    listener = lambda name, **kw: events.append(name)  # noqa: E731
    monitoring.register_event_listener(listener)
    try:
        from ct_mapreduce_tpu.core import packing
        from ct_mapreduce_tpu.ops import pipeline

        # A real (small) pre-parsed step shape — the same jit'd program
        # the aggregator dispatches.
        s = packing.MAX_SERIAL_BYTES

        def step(table):
            return pipeline.ingest_step_preparsed(
                table, np.zeros((1, 64, s), np.uint8),
                np.zeros((1, 64), np.int32),
                np.full((1, 64), packing.DEFAULT_BASE_HOUR + 1, np.int32),
                np.zeros((1, 64), np.int32), np.ones((1, 64), bool),
                np.int32(packing.DEFAULT_BASE_HOUR),
                max_probes=4, flag_cap=64,
            )

        table, out = step(pipeline.make_table(1 << 10))
        np.asarray(out.packed)
        assert any(os.scandir(tmp_path)), "no cache entry written"
        first_hits = sum(1 for e in events if "cache_hit" in e)

        # Drop every in-memory executable; the SAME shape must come
        # back from the persistent cache, not a recompile.
        jax.clear_caches()
        table, out = step(pipeline.make_table(1 << 10))
        np.asarray(out.packed)
        second_hits = sum(1 for e in events if "cache_hit" in e)
        assert second_hits > first_hits, (
            f"no persistent-cache hit on the second trace "
            f"(events: {sorted(set(events))})")
    finally:
        monitoring._unregister_event_listener_by_callback(listener)
        # Leave no cache dir configured for later tests in-process.
        jax.config.update("jax_compilation_cache_dir", None)
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
