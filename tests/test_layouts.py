"""Cross-layout checkpoint compatibility: the snapshot's layout wins.

A checkpoint written under either dedup-table layout must restore and
keep exact counts regardless of the CTMR_TABLE value at load time —
slot positions are only meaningful in the structure that wrote them,
so load_checkpoint rebuilds the WRITER's layout and every downstream
op dispatches on the state type (pipeline.table_insert).
"""

import datetime
import os
import tempfile

import numpy as np
import pytest

from ct_mapreduce_tpu.agg import TpuAggregator
from ct_mapreduce_tpu.ops import buckettable, hashtable

from certgen import make_cert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2024, 6, 1, tzinfo=UTC)


def entries(n, issuer_cn, base=5000):
    # NOTE: certgen reuses one keypair, so every "issuer" here shares
    # one SPKI digest — i.e. ONE identity (the reference keys issuers
    # by SHA-256(SPKI), /root/reference/storage/types.go:104-141, not
    # by DN). Distinct serial bases are what make entries distinct.
    ca = make_cert(issuer_cn=issuer_cn)
    return [
        (make_cert(serial=base + i, issuer_cn=issuer_cn, is_ca=False,
                   subject_cn=f"x{i}.example.com"), ca)
        for i in range(n)
    ]


@pytest.mark.parametrize("writer,reader", [
    ("open", "bucket"), ("bucket", "open"),
])
def test_checkpoint_layout_survives_env_change(monkeypatch, writer, reader):
    monkeypatch.setenv("CTMR_TABLE", writer)
    a = TpuAggregator(capacity=1 << 10, batch_size=64, now=NOW)
    ents = entries(150, f"Layout CA {writer}")
    res = a.ingest(ents)
    assert res.was_unknown.all()
    fd, path = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    try:
        a.save_checkpoint(path)

        monkeypatch.setenv("CTMR_TABLE", reader)
        b = TpuAggregator(capacity=1 << 10, batch_size=64, now=NOW)
        b.load_checkpoint(path)
        # The restored table keeps the WRITER's structure.
        want_cls = (buckettable.BucketTable if writer == "bucket"
                    else hashtable.TableState)
        assert isinstance(b.table, want_cls)
        # Everything from before the restart is known...
        res2 = b.ingest(ents)
        assert not res2.was_unknown.any()
        # ...new entries insert through the dispatched path...
        more = entries(60, f"Layout CA {writer} 2", base=9000)
        res3 = b.ingest(more)
        assert res3.was_unknown.all()
        # ...and the drained totals stay exact.
        assert b.drain().total == 210
    finally:
        os.unlink(path)
