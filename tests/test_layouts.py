"""Cross-layout checkpoint compatibility: the snapshot's layout wins.

A checkpoint written under either dedup-table layout must restore and
keep exact counts regardless of the CTMR_TABLE value at load time —
slot positions are only meaningful in the structure that wrote them,
so load_checkpoint rebuilds the WRITER's layout and every downstream
op dispatches on the state type (pipeline.table_insert).
"""

import datetime
import os
import tempfile

import numpy as np
import pytest

from ct_mapreduce_tpu.agg import TpuAggregator
from ct_mapreduce_tpu.ops import buckettable, hashtable

from certgen import make_cert

UTC = datetime.timezone.utc
NOW = datetime.datetime(2024, 6, 1, tzinfo=UTC)


def entries(n, issuer_cn, base=5000):
    # NOTE: certgen reuses one keypair, so every "issuer" here shares
    # one SPKI digest — i.e. ONE identity (the reference keys issuers
    # by SHA-256(SPKI), /root/reference/storage/types.go:104-141, not
    # by DN). Distinct serial bases are what make entries distinct.
    ca = make_cert(issuer_cn=issuer_cn)
    return [
        (make_cert(serial=base + i, issuer_cn=issuer_cn, is_ca=False,
                   subject_cn=f"x{i}.example.com"), ca)
        for i in range(n)
    ]


@pytest.mark.parametrize("writer,reader", [
    ("open", "bucket"), ("bucket", "open"),
])
def test_checkpoint_layout_survives_env_change(monkeypatch, writer, reader):
    monkeypatch.setenv("CTMR_TABLE", writer)
    a = TpuAggregator(capacity=1 << 10, batch_size=64, now=NOW)
    ents = entries(150, f"Layout CA {writer}")
    res = a.ingest(ents)
    assert res.was_unknown.all()
    fd, path = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    try:
        a.save_checkpoint(path)

        monkeypatch.setenv("CTMR_TABLE", reader)
        b = TpuAggregator(capacity=1 << 10, batch_size=64, now=NOW)
        b.load_checkpoint(path)
        # The restored table keeps the WRITER's structure.
        want_cls = (buckettable.BucketTable if writer == "bucket"
                    else hashtable.TableState)
        assert isinstance(b.table, want_cls)
        # Everything from before the restart is known...
        res2 = b.ingest(ents)
        assert not res2.was_unknown.any()
        # ...new entries insert through the dispatched path...
        more = entries(60, f"Layout CA {writer} 2", base=9000)
        res3 = b.ingest(more)
        assert res3.was_unknown.all()
        # ...and the drained totals stay exact.
        assert b.drain().total == 210
    finally:
        os.unlink(path)


@pytest.mark.parametrize(
    "layout",
    ["bucket",
     # @slow since round 17 (tier-1 budget banking, ISSUE 12): the
     # re-hash-on-topology-mismatch contract is layout-independent
     # code; tier-1 keeps the default bucket layout, and open-layout
     # checkpoint/parity coverage stays tier-1 via test_layouts'
     # other legs + test_staged_open_layout_parity. The open param
     # re-runs the same contract at ~16 s in the full suite.
     pytest.param("open", marks=pytest.mark.slow)])
def test_checkpoint_topology_mismatch_rehashes(monkeypatch, layout):
    # A multi-shard writer records positions in shard-local addressing
    # (dest * nb_local + local hash); a single-chip reader must re-hash
    # every row instead of trusting positions, or its own hashes can't
    # reach them and dedup double-counts.
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    monkeypatch.setenv("CTMR_TABLE", layout)
    mesh = Mesh(np.array(jax.devices()[:8]), ("shard",))
    a = ShardedAggregator(mesh, capacity=1 << 12, batch_size=64, now=NOW)
    ents = entries(150, f"Topo CA {layout}")
    res = a.ingest(ents)
    assert res.was_unknown.all()
    fd, path = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    try:
        a.save_checkpoint(path)
        z = np.load(path, allow_pickle=True)
        assert int(z["n_shards"]) == 8

        b = TpuAggregator(capacity=1 << 10, batch_size=64, now=NOW)
        b.load_checkpoint(path)
        res2 = b.ingest(ents)  # everything already known — no recount
        assert not res2.was_unknown.any()
        more = entries(60, f"Topo CA {layout} 2", base=9000)
        assert b.ingest(more).was_unknown.all()
        assert b.drain().total == 210

        # And back: the sharded reader re-hashes any snapshot.
        c = ShardedAggregator(mesh, capacity=1 << 12, batch_size=64, now=NOW)
        c.load_checkpoint(path)
        assert not c.ingest(ents).was_unknown.any()
        assert c.drain().total == 150
    finally:
        os.unlink(path)


def test_host_snapshot_reads_sharded_bucket_checkpoint(monkeypatch):
    # storage-statistics --backend=tpu must be able to report on a
    # snapshot written by a mesh writer WITHOUT claiming the device:
    # the host reader re-hashes through the NumPy bulk insert.
    import jax
    from jax.sharding import Mesh

    from ct_mapreduce_tpu.agg.aggregator import HostSnapshotAggregator
    from ct_mapreduce_tpu.agg.sharded_agg import ShardedAggregator

    monkeypatch.setenv("CTMR_TABLE", "bucket")
    mesh = Mesh(np.array(jax.devices()[:8]), ("shard",))
    a = ShardedAggregator(mesh, capacity=1 << 12, batch_size=64, now=NOW)
    ents = entries(120, "HostSnap CA")
    assert a.ingest(ents).was_unknown.all()
    fd, path = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    try:
        a.save_checkpoint(path)
        h = HostSnapshotAggregator(capacity=1 << 10, batch_size=64, now=NOW)
        h.load_checkpoint(path)
        assert isinstance(h.table.rows, np.ndarray)
        assert h.drain().total == 120
    finally:
        os.unlink(path)
