"""Test configuration: force an 8-device virtual CPU platform so
multi-chip sharding paths are exercised without TPU hardware (mirrors
the reference's pattern of gating real-Redis tests behind env vars,
/root/reference/storage/rediscache_test.go:16-28 — here the real-TPU
tests are the gated tier and the virtual mesh is the default)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The ambient environment may have already imported jax (sitecustomize
# registering a TPU plugin), so setting JAX_PLATFORMS here is too late;
# jax.config wins either way. The real-TPU tier opts back in via
# CT_TPU_TESTS=1.
if os.environ.get("CT_TPU_TESTS", "") == "":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Lock-order witness across the WHOLE suite (round 16): every
# concurrency test doubles as a race-order probe against the declared
# hierarchy (analysis/lockspec.py). Installed before any package
# module creates a lock; CTMR_LOCK_WITNESS=0 opts a run out.
# pytest_sessionfinish below fails the run on any order violation or
# cycle the witness observed.
os.environ.setdefault("CTMR_LOCK_WITNESS", "1")
from ct_mapreduce_tpu.analysis import witness as _lock_witness  # noqa: E402

_lock_witness.install()


def on_tpu() -> bool:
    import jax

    return any(d.platform == "tpu" for d in jax.devices())


requires_tpu = pytest.mark.skipif(
    os.environ.get("CT_TPU_TESTS", "") == "", reason="set CT_TPU_TESTS=1 to run"
)


def pytest_configure(config):
    # pytest-timeout isn't in this image; register the mark so suites
    # that do install it get real timeouts and bare runs stay clean.
    config.addinivalue_line(
        "markers",
        "timeout(seconds): per-test timeout (enforced only when "
        "pytest-timeout is installed)",
    )
    config.addinivalue_line(
        "markers",
        "slow: outside the tier-1 budget (tier-1 runs -m 'not slow'); "
        "e.g. per-batch-width ECDSA kernel compiles",
    )


def pytest_sessionfinish(session, exitstatus):
    """The suite-wide lock-witness gate: zero order violations or
    cycles across everything the tier-1 run exercised."""
    w = _lock_witness.active()
    if w is None:
        return
    findings = w.findings()
    if not findings:
        return
    lines = ["", "=" * 70,
             "LOCK WITNESS: order violations / cycles observed:"]
    for v in findings:
        if v.get("kind") == "order":
            lines.append(
                f"  order: {v['held']} (rank {v['held_rank']}) held "
                f"while acquiring {v['acquiring']} "
                f"(rank {v['acquiring_rank']}) [{v['thread']}] at "
                f"{v['where']}")
        else:
            lines.append(
                f"  cycle: {' -> '.join(v.get('cycle', []))} "
                f"[{v['thread']}] at {v['where']}")
    lines.append("(hierarchy: ct_mapreduce_tpu/analysis/lockspec.py; "
                 "docs/ANALYSIS.md)")
    lines.append("=" * 70)
    print("\n".join(lines))
    session.exitstatus = 1
