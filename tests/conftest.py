"""Test configuration: force an 8-device virtual CPU platform so
multi-chip sharding paths are exercised without TPU hardware (mirrors
the reference's pattern of gating real-Redis tests behind env vars,
/root/reference/storage/rediscache_test.go:16-28 — here the real-TPU
tests are the gated tier and the virtual mesh is the default)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The ambient environment may have already imported jax (sitecustomize
# registering a TPU plugin), so setting JAX_PLATFORMS here is too late;
# jax.config wins either way. The real-TPU tier opts back in via
# CT_TPU_TESTS=1.
if os.environ.get("CT_TPU_TESTS", "") == "":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def on_tpu() -> bool:
    import jax

    return any(d.platform == "tpu" for d in jax.devices())


requires_tpu = pytest.mark.skipif(
    os.environ.get("CT_TPU_TESTS", "") == "", reason="set CT_TPU_TESTS=1 to run"
)


def pytest_configure(config):
    # pytest-timeout isn't in this image; register the mark so suites
    # that do install it get real timeouts and bare runs stay clean.
    config.addinivalue_line(
        "markers",
        "timeout(seconds): per-test timeout (enforced only when "
        "pytest-timeout is installed)",
    )
    config.addinivalue_line(
        "markers",
        "slow: outside the tier-1 budget (tier-1 runs -m 'not slow'); "
        "e.g. per-batch-width ECDSA kernel compiles",
    )
